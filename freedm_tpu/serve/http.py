"""Zero-dependency JSON front end for the query service + QSTS jobs.

Same machinery as the metrics exposition endpoint
(:class:`freedm_tpu.core.metrics.MetricsServer`): stdlib
``ThreadingHTTPServer`` on a daemon thread, loopback bind by default,
ephemeral port when asked for 0.  One OS thread per in-flight request
is exactly what the micro-batcher wants — concurrent waiters are what
it coalesces.

Routes:

- ``POST /v1/pf`` / ``POST /v1/n1`` / ``POST /v1/vvc`` /
  ``POST /v1/topo`` — a JSON body matching the workload's request
  record (:mod:`freedm_tpu.serve.service`); 200 with the typed
  response dict on success.
- ``POST /v1/qsts`` — submit a QSTS study to the async jobs layer
  (:mod:`freedm_tpu.scenarios.jobs`); 202 with ``{"job_id": ...}``.
- ``POST /v1/topo/sweep`` — submit an async topology sweep to the same
  jobs layer (chunked + checkpointed; docs/topology.md); 202 with
  ``{"job_id": ...}``.
- ``GET /v1/jobs/<id>`` — poll a job (progress, then the summary);
  ``POST /v1/jobs/<id>/cancel`` — stop it at the next chunk boundary.
- ``POST /v1/snapshot`` — this replica's contribution to a router-
  initiated consistent cut (:mod:`freedm_tpu.core.snapshot`): the
  request-conservation ledger, cache byte accounting, and job table,
  each read atomically.  Body: ``{"snapshot_id": ..., "node": ...}``.
- ``GET /healthz`` — liveness + the workload/case table.
- ``GET /metrics`` — this replica's Prometheus registry rendering, the
  per-replica half of the router's fleet federation scrape
  (``RouterServer`` ``GET /metrics`` sums these under a ``replica``
  label; docs/observability.md).
- ``GET /stats`` — queue depth, the batcher's shape-bucket table, the
  per-shape recompile attribution (``recompiles_by_bucket``:
  ``"workload/case:bucket" -> first dispatches``, so a recompile storm
  names its tenant without reading traces), the incremental tier's
  ``cache`` block (hits per tier, misses, evictions, byte occupancy,
  single-flight joins — docs/serving.md "Incremental tier"), and the
  serve metric snapshot.

Errors are *typed*, never free-text-only: the body is always
``{"error": {"type": <ServeError.code>, "detail": ...}}`` with the
matching HTTP status (400 invalid_request, 404 not_found, 429
overloaded, 503 shutting_down, 504 deadline_exceeded, 500 internal).
Clients switch on ``error.type``; 429/503 mean back off and retry —
and carry a ``Retry-After`` header sized by the error class — while
400/404/504 mean don't.

Fleet discipline (docs/robustness.md): a request arriving with an
``X-Deadline-Budget-S`` header (the router's propagated deadline
budget) has its ``timeout_s`` clamped to that budget, so a retried
request can never outlive the deadline its client is still waiting
on.  ``/healthz`` reports ``draining: true`` once shutdown has begun
(:meth:`ServeServer.begin_drain`) — the router stops sending new work
while in-flight requests finish.

Keep-alive discipline: handlers speak HTTP/1.1 persistent connections,
so every error path must leave the socket **positionally clean** — the
declared request body is read (drained) before any routing or
validation can fail, and a body the server refuses to read (oversized,
bogus ``Content-Length``) answers with ``Connection: close`` so the
unread bytes can never be parsed as the next pipelined request.
``tests/test_serve.py`` pins this with two requests on one socket.
"""

from __future__ import annotations

import json
import os
import time
from http.server import BaseHTTPRequestHandler
from urllib.parse import urlparse

from freedm_tpu.core.faults import FAULTS
from freedm_tpu.core.metrics import BackgroundHttpServer
from freedm_tpu.serve.queue import InvalidRequest, NotFound, ServeError
from freedm_tpu.serve.service import BUS_CASES, FEEDER_CASES, WORKLOADS, Service

#: Request bodies past this are refused unread (a 256-outage N-1
#: request is ~2 KB; nothing legitimate approaches a megabyte).
MAX_BODY_BYTES = 4_000_000


def retry_after_header(seconds) -> str:
    """The one ``Retry-After`` formatting rule of both HTTP front ends
    (this server and the replica router): whole seconds, floor 1."""
    return str(int(max(1, round(float(seconds)))))


def read_request_body(handler, max_bytes: int = MAX_BODY_BYTES) -> bytes:
    """The shared keep-alive body discipline of BOTH HTTP front ends
    (this server and the replica router): read the declared request
    body, or refuse it with the connection marked for close — either
    way the socket is left positionally clean for (or closed against)
    the next pipelined request."""
    raw = handler.headers.get("Content-Length") or "0"
    try:
        length = int(raw)
    except ValueError:
        length = -1
    if length < 0 or length > max_bytes:
        handler.close_connection = True
        raise InvalidRequest(
            f"request body over {max_bytes} bytes or "
            f"Content-Length unparseable ({raw!r})"
        )
    return handler.rfile.read(length) if length else b""


def trace_parent_ctx(headers):
    """Adopt the router's propagated ``X-Trace-Id``/``X-Span-Id``
    headers as a wire span context (``tracing.Tracer.start``'s
    ``parent_ctx``), so a replica's ``serve.request`` span parents
    under the router's ``serve.route`` span in ONE cross-process tree
    — the fleet-valid trace_id provenance receipts carry.  ``None``
    when the request arrived untraced."""
    trace_id = headers.get("X-Trace-Id")
    if not trace_id:
        return None
    ctx = {"trace_id": str(trace_id)}
    span_id = headers.get("X-Span-Id")
    if span_id:
        ctx["span_id"] = str(span_id)
    return ctx


def apply_deadline_budget(payload, header_value) -> None:
    """Clamp a workload payload's ``timeout_s`` to the router's
    propagated ``X-Deadline-Budget-S`` budget (in place).  A request
    must not out-wait the client that is still holding the deadline
    upstream; an unparseable or non-positive budget is ignored."""
    if not header_value or not isinstance(payload, dict):
        return
    try:
        budget = float(header_value)
    except (TypeError, ValueError):
        return
    if budget <= 0:
        return
    t = payload.get("timeout_s")
    payload["timeout_s"] = (
        min(float(t), budget)
        if isinstance(t, (int, float)) and not isinstance(t, bool) and t > 0
        else budget
    )


class ServeServer(BackgroundHttpServer):
    """``--serve-port``: the JSON query endpoint (+ QSTS jobs when a
    :class:`~freedm_tpu.scenarios.jobs.JobManager` is attached)."""

    def __init__(self, service: Service, port: int = 0,
                 host: str = "127.0.0.1", jobs=None):
        # Loopback by default, like the metrics server: the service has
        # no auth; widening the bind is an explicit caller decision.
        svc = service
        jm = jobs
        # Closed over by the handler; begin_drain()/stop() flip it so
        # /healthz advertises the drain to the router's prober.
        flags = {"draining": False}
        self._flags = flags

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # load generators must not spam stderr
                pass

            def _reply(self, code: int, obj,
                       retry_after_s=None) -> None:
                data = (json.dumps(obj) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if retry_after_s is not None:
                    # Typed backpressure (429/503) always tells the
                    # client WHEN to come back, not just to go away.
                    self.send_header("Retry-After",
                                     retry_after_header(retry_after_s))
                if self.close_connection:
                    # An unread body is still on the socket: tell the
                    # client this connection is done.
                    self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(data)

            def _error(self, err: ServeError) -> None:
                self._reply(err.http_status,
                            {"error": {"type": err.code, "detail": str(err)}},
                            retry_after_s=getattr(err, "retry_after_s", None))

            def _reply_text(self, code: int, text: str,
                            content_type: str) -> None:
                data = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                if self.close_connection:
                    self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(data)

            def _jobs(self):
                if jm is None:
                    raise NotFound(
                        "QSTS jobs are not enabled on this server"
                    )
                return jm

            def do_GET(self):
                path = urlparse(self.path).path
                try:
                    # GETs can legally carry a body (some proxies do):
                    # drain it like POST does, or the leftover bytes
                    # corrupt the next pipelined request.
                    self._read_body()
                    if path == "/healthz":
                        self._reply(200, {
                            "ok": True,
                            "draining": flags["draining"],
                            "workloads": list(WORKLOADS),
                            "bus_cases": list(BUS_CASES),
                            "feeder_cases": list(FEEDER_CASES),
                            "qsts": jm is not None,
                        })
                    elif path == "/stats":
                        stats = svc.stats()
                        if jm is not None:
                            stats["qsts"] = jm.stats()
                        self._reply(200, stats)
                    elif path == "/metrics":
                        # The per-replica federation scrape target: the
                        # process registry in the text exposition
                        # format, exactly what MetricsServer serves —
                        # but on the serve port, so the router can sum
                        # the fleet without a second port per replica.
                        from freedm_tpu.core.metrics import REGISTRY

                        self._reply_text(
                            200, REGISTRY.render_prometheus(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/provenance":
                        # The drift observatory document (receipts by
                        # tier, shadow outcomes, drift windows) on the
                        # serve port, so the router/soak can scrape it
                        # per replica without a second port.
                        from freedm_tpu.core.provenance import PROVENANCE

                        self._reply(200, PROVENANCE.report())
                    elif path.startswith("/v1/jobs/"):
                        job_id = path[len("/v1/jobs/"):]
                        self._reply(200, self._jobs().get(job_id))
                    elif path == "/":
                        self._reply(200, {
                            "service": "freedm_tpu serve",
                            "post": [f"/v1/{w}" for w in WORKLOADS]
                            + ["/v1/qsts", "/v1/topo/sweep",
                               "/v1/jobs/<id>/cancel", "/v1/snapshot"],
                            "get": ["/healthz", "/stats", "/metrics",
                                    "/provenance", "/v1/jobs/<id>"],
                        })
                    else:
                        self._reply(404, {"error": {"type": "not_found",
                                                    "detail": path}})
                except ServeError as e:
                    self._error(e)
                except Exception as e:  # noqa: BLE001 — always answer typed
                    self._reply(500, {"error": {"type": "internal",
                                                "detail": repr(e)}})

            def _read_body(self) -> bytes:
                return read_request_body(self)

            def do_POST(self):
                path = urlparse(self.path).path
                try:
                    if FAULTS.enabled:
                        # Replica-level faults (docs/robustness.md):
                        # kill is an abrupt process death (what the
                        # router's passive failure marking + retries
                        # must absorb); stall models a wedged replica
                        # (what the router's per-try timeout bounds).
                        if FAULTS.should("serve.replica.kill"):
                            os._exit(86)
                        FAULTS.sleep_point("serve.replica.stall", 0.2)
                    # Drain FIRST: everything after this point can fail
                    # without corrupting the persistent connection.
                    body = self._read_body()
                    if path.startswith("/v1/jobs/") and path.endswith("/cancel"):
                        job_id = path[len("/v1/jobs/"):-len("/cancel")]
                        self._reply(200, self._jobs().cancel(job_id))
                        return
                    if not path.startswith("/v1/"):
                        self._reply(404, {"error": {"type": "not_found",
                                                    "detail": path}})
                        return
                    if not body:
                        raise InvalidRequest("missing JSON request body")
                    try:
                        payload = json.loads(body)
                    except ValueError as e:
                        raise InvalidRequest(f"malformed JSON: {e}") from None
                    if path == "/v1/qsts":
                        self._reply(202, self._jobs().submit(payload))
                        return
                    if path == "/v1/topo/sweep":
                        # Async topology sweep beside QSTS: chunked +
                        # checkpointed, polled via GET /v1/jobs/<id>.
                        self._reply(202, self._jobs().submit_topo(payload))
                        return
                    if path == "/v1/snapshot":
                        # This replica's contribution to a router-
                        # initiated consistent cut (core/snapshot.py):
                        # ledger + cache + job table, each read
                        # atomically under its own leaf lock.  The
                        # router supplies snapshot_id and the node name
                        # it knows this replica by.
                        if not isinstance(payload, dict):
                            raise InvalidRequest(
                                "snapshot body must be a JSON object"
                            )
                        doc = {
                            "snapshot_id": payload.get("snapshot_id"),
                            "node": payload.get("node")
                            or f"replica:{self.server.server_port}",
                            "status": "complete",
                            "captured_at": time.time(),
                            "serve": {
                                "ledger": svc.ledger.snapshot_state()
                            },
                        }
                        if svc.cache is not None:
                            doc["cache"] = svc.cache.snapshot_state()
                        if jm is not None:
                            doc["jobs"] = jm.snapshot_state()
                        self._reply(200, doc)
                        return
                    workload = path[len("/v1/"):]
                    apply_deadline_budget(
                        payload, self.headers.get("X-Deadline-Budget-S")
                    )
                    response = svc.request(
                        workload, payload,
                        parent_ctx=trace_parent_ctx(self.headers),
                    )
                    self._reply(200, response.to_dict())
                except ServeError as e:
                    self._error(e)
                except Exception as e:  # noqa: BLE001 — always answer typed
                    self._reply(500, {"error": {"type": "internal",
                                                "detail": repr(e)}})

        super().__init__(Handler, port=port, host=host)

    def begin_drain(self) -> None:
        """Advertise the drain on ``/healthz`` (``draining: true``) so
        the router stops routing NEW work here; in-flight requests
        keep their handler threads and finish normally."""
        self._flags["draining"] = True

    def stop(self) -> None:
        self.begin_drain()
        super().stop()
