"""Zero-dependency JSON front end for the query service.

Same machinery as the metrics exposition endpoint
(:class:`freedm_tpu.core.metrics.MetricsServer`): stdlib
``ThreadingHTTPServer`` on a daemon thread, loopback bind by default,
ephemeral port when asked for 0.  One OS thread per in-flight request
is exactly what the micro-batcher wants — concurrent waiters are what
it coalesces.

Routes:

- ``POST /v1/pf`` / ``POST /v1/n1`` / ``POST /v1/vvc`` — a JSON body
  matching the workload's request record
  (:mod:`freedm_tpu.serve.service`); 200 with the typed response dict
  on success.
- ``GET /healthz`` — liveness + the workload/case table.
- ``GET /stats`` — queue depth, bucket table, serve metric snapshot.

Errors are *typed*, never free-text-only: the body is always
``{"error": {"type": <ServeError.code>, "detail": ...}}`` with the
matching HTTP status (400 invalid_request, 429 overloaded, 503
shutting_down, 504 deadline_exceeded, 500 internal).  Clients switch on
``error.type``; 429/503 mean back off and retry, 400/504 mean don't.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler
from urllib.parse import urlparse

from freedm_tpu.core.metrics import BackgroundHttpServer
from freedm_tpu.serve.queue import InvalidRequest, ServeError
from freedm_tpu.serve.service import BUS_CASES, FEEDER_CASES, WORKLOADS, Service

#: Request bodies past this are rejected before parsing (a 256-outage
#: N-1 request is ~2 KB; nothing legitimate approaches a megabyte).
MAX_BODY_BYTES = 4_000_000


class ServeServer(BackgroundHttpServer):
    """``--serve-port``: the JSON query endpoint."""

    def __init__(self, service: Service, port: int = 0,
                 host: str = "127.0.0.1"):
        # Loopback by default, like the metrics server: the service has
        # no auth; widening the bind is an explicit caller decision.
        svc = service

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # load generators must not spam stderr
                pass

            def _reply(self, code: int, obj) -> None:
                data = (json.dumps(obj) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, err: ServeError) -> None:
                self._reply(err.http_status,
                            {"error": {"type": err.code, "detail": str(err)}})

            def do_GET(self):
                path = urlparse(self.path).path
                if path == "/healthz":
                    self._reply(200, {
                        "ok": True,
                        "workloads": list(WORKLOADS),
                        "bus_cases": list(BUS_CASES),
                        "feeder_cases": list(FEEDER_CASES),
                    })
                elif path == "/stats":
                    self._reply(200, svc.stats())
                elif path == "/":
                    self._reply(200, {
                        "service": "freedm_tpu serve",
                        "post": [f"/v1/{w}" for w in WORKLOADS],
                        "get": ["/healthz", "/stats"],
                    })
                else:
                    self._reply(404, {"error": {"type": "not_found",
                                                "detail": path}})

            def do_POST(self):
                path = urlparse(self.path).path
                if not path.startswith("/v1/"):
                    self._reply(404, {"error": {"type": "not_found",
                                                "detail": path}})
                    return
                workload = path[len("/v1/"):]
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    if length <= 0:
                        raise InvalidRequest("missing JSON request body")
                    if length > MAX_BODY_BYTES:
                        raise InvalidRequest(
                            f"request body over {MAX_BODY_BYTES} bytes"
                        )
                    try:
                        payload = json.loads(self.rfile.read(length))
                    except ValueError as e:
                        raise InvalidRequest(f"malformed JSON: {e}") from None
                    response = svc.request(workload, payload)
                    self._reply(200, response.to_dict())
                except ServeError as e:
                    self._error(e)
                except Exception as e:  # noqa: BLE001 — always answer typed
                    self._reply(500, {"error": {"type": "internal",
                                                "detail": repr(e)}})

        super().__init__(Handler, port=port, host=host)
