"""Incremental serving tier: base-case factorization/solution reuse.

At million-user traffic most what-if queries are small deltas against a
shared base case, yet a cold serve path runs every ``POST /v1/pf`` as a
full Newton solve from flat start.  This module is the
amortize-one-factorization-over-many-queries layer (the SABLE /
accelerated-DC-loadflow pattern from PAPERS.md, applied to serving): a
bounded per-(case, topology, pf_backend) cache holding each base case's
converged solutions **plus the reusable solve artifacts** —

- the FDLF B′/B″ LU pair (:func:`freedm_tpu.pf.krylov.build_fdlf_precond`
  with ``kind="lu"``), factorized ONCE per (case, topology) and reused
  by every delta answer;
- the BCSR symbolic Jacobian pattern
  (:func:`freedm_tpu.pf.sparse.jacobian_pattern`) for sparse-backend
  cases (the handle pins the process-wide pattern cache entry alive for
  the case's lifetime);
- a lazily-built DC screen (:func:`freedm_tpu.pf.dc.make_dc_solver`)
  sharing the SAME B′ factorization via its ``lu=`` argument — zero
  extra O(n³) work to attach DC screening to a cached case.

Three answer tiers, cheapest first (:class:`ServeCache` classifies,
:class:`~freedm_tpu.serve.service.Service` acts):

1. **exact** — the request's injection vector is byte-identical to a
   cached solution: answered from host memory, sub-millisecond, no
   device touch at all.
2. **delta** — the injections differ from a cached solution at ≤
   ``delta_max_rank`` buses (and ≤ ``delta_max_pu`` per-bus magnitude):
   answered by warm-started fast-decoupled sweeps whose inner solve is
   :func:`freedm_tpu.pf.n1.smw_delta_solve` over the cached LU pair —
   the rank-0 (matrix-unchanged) case of the same correction solve the
   N-1 screen uses at rank 2.  O(n²) triangular solves per sweep
   instead of the full path's per-iteration O(n³) re-factorization.
   Every delta answer is **verified** by a host float64 residual check
   (:func:`freedm_tpu.pf.krylov.host_injections` — the same oracle the
   solver tests trust); a residual above the engine tolerance falls
   through to tier 3, so the cache can serve a wrong-enough answer to
   exactly nobody.
3. **warm** — too big a delta to correct: the full solve proceeds, but
   seeded with the nearest cached solution through the ``v0``/``theta0``
   warm-start path (PR 4 measured 37% fewer Newton iterations).

Plus the operational machinery a shared cache needs: **invalidation**
keyed on a topology digest (a mutated case hashes to a different entry
— a stale solution is unreachable, never served), **LRU + TTL
eviction** byte-accounted against the ``--serve-cache-mb`` budget
(artifacts included), and **single-flight population** — concurrent
identical cold requests elect one leader ticket; followers ride its
solve and are answered at scatter time, so a thundering herd on a cold
case compiles and factorizes once.

Threading: one cache lock guards the maps/accounting (lookups are pure
host work — dict probes and O(n) numpy compares); artifact builds run
under a per-entry build lock so a cold case cannot stall other cases'
lookups.  The cache lock never nests inside (or around) the admission
queue's condition — pinned by the GL006 static lock graph and a
DebugLock test.  The delta solve's single device sync is the designed
pull at the verify boundary (GL002 registry entry).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from freedm_tpu.core import faults
from freedm_tpu.core import metrics as obs
from freedm_tpu.core import profiling

#: Recent solutions scanned per lookup for the nearest delta/warm base.
DELTA_SCAN = 8

#: Fast-decoupled correction sweeps the delta tier may spend before the
#: residual check decides (the program exits early on convergence).
DELTA_MAX_SWEEPS = 30

#: Per-bus injection deltas above this (pu) are not worth attempting a
#: linear-regime correction on — straight to the warm tier.
DELTA_MAX_PU = 0.5

#: Minimum seconds between full TTL sweeps of one entry's solution
#: list: a sweep is O(solutions) under the global lock, so it must not
#: run on every lookup (freshness is still enforced per served
#: candidate — an expired solution is never answered, sweep or no
#: sweep; the sweep just reclaims the bytes).
_TTL_SWEEP_S = 1.0

_TIERS = ("exact", "delta", "warm", "miss")


def injection_digest(p: np.ndarray, q: np.ndarray) -> str:
    """Content key of one injection pair (exact-hit identity)."""
    return hashlib.sha1(p.tobytes() + q.tobytes()).hexdigest()


def topology_digest(sys) -> str:
    """Digest of everything that shapes the network matrices — bus
    types/shunts/setpoints and the full branch table.  Injections are
    deliberately EXCLUDED (they are the delta dimension); any other
    mutation (an outage baked into ``x``, a retap, an added branch)
    changes the digest, so a stale entry is unreachable rather than
    invalid — the "stale entry never served" contract."""
    h = hashlib.sha1()
    for arr in (sys.bus_type, sys.v_set, sys.g_shunt, sys.b_shunt,
                sys.from_bus, sys.to_bus, sys.r, sys.x, sys.b_chg,
                sys.tap, sys.shift):
        h.update(np.ascontiguousarray(arr).tobytes())
    h.update(repr((sys.n_bus, sys.n_branch, float(sys.base_mva))).encode())
    return h.hexdigest()[:16]


def _nbytes(x) -> int:
    """Recursive byte size of numpy/jax arrays (tuples/lists walked)."""
    if x is None:
        return 0
    if isinstance(x, (tuple, list)):
        return sum(_nbytes(e) for e in x)
    size = getattr(x, "size", None)
    itemsize = getattr(getattr(x, "dtype", None), "itemsize", None)
    if size is not None and itemsize is not None:
        return int(size) * int(itemsize)
    return 0


class CachedSolution:
    """One converged operating point of a cached case: the injections
    it answers exactly, the solution state, and the response stamps."""

    __slots__ = ("digest", "p_inj", "q_inj", "v", "theta", "p", "q",
                 "iterations", "mismatch", "converged", "stamp", "nbytes")

    def __init__(self, digest: str, p_inj, q_inj, v, theta, p, q,
                 iterations: int, mismatch: float, converged: bool):
        self.digest = digest
        # np.array (copy) — scatter hands batch-row VIEWS; storing them
        # would pin the whole padded [bucket, n] batch in memory and
        # falsify the byte accounting.
        self.p_inj = np.array(p_inj, np.float64)
        self.q_inj = np.array(q_inj, np.float64)
        self.v = np.array(v, np.float64)
        self.theta = np.array(theta, np.float64)
        self.p = np.array(p, np.float64)
        self.q = np.array(q, np.float64)
        self.iterations = int(iterations)
        self.mismatch = float(mismatch)
        self.converged = bool(converged)
        self.stamp = time.monotonic()
        self.nbytes = sum(
            a.nbytes for a in (self.p_inj, self.q_inj, self.v, self.theta,
                               self.p, self.q)
        ) + 128  # key/slot overhead, order-of-magnitude honest


class CaseEntry:
    """One (case, topology, pf_backend)'s artifacts + solution store.

    ``precond`` is the ``kind="lu"`` FDLF pair; ``pattern`` the BCSR
    symbolic handle (sparse-backend cases); ``delta_fn`` the jitted
    warm-started fast-decoupled correction program; ``dc_solver()``
    lazily attaches a DC screen sharing the B′ factorization.
    ``solutions`` is digest → :class:`CachedSolution`, LRU-ordered,
    manipulated only under the owning cache's lock."""

    __slots__ = ("key", "case", "sys", "backend", "tol", "rdtype",
                 "build_lock", "precond", "pattern", "delta_fn", "_dc",
                 "solutions", "artifact_bytes", "accounted", "alive",
                 "last_used", "ttl_sweep", "_th_free", "_v_free",
                 "precision")

    def __init__(self, case: str, sys, backend: str, topo: str,
                 precision: str = "f64"):
        self.key = (case, topo, backend)
        self.case = case
        self.sys = sys
        self.backend = backend
        self.precision = precision
        self.build_lock = threading.Lock()
        self.precond = None
        self.pattern = None
        self.delta_fn = None
        self._dc = None
        self.solutions: "OrderedDict[str, CachedSolution]" = OrderedDict()
        self.artifact_bytes = 0
        # artifact_bytes has been added to the owning cache's byte
        # account (guarded by the cache lock on BOTH the add and every
        # subtract, so a racing invalidate can never drive the account
        # negative).
        self.accounted = False
        self.alive = True
        self.last_used = time.monotonic()
        self.ttl_sweep = 0.0  # last full TTL sweep (time-gated)
        from freedm_tpu.grid.bus import PQ, SLACK

        self._th_free = np.asarray(sys.bus_type) != SLACK
        self._v_free = np.asarray(sys.bus_type) == PQ
        from freedm_tpu.utils import cplx

        self.rdtype = cplx.default_rdtype(None)
        import jax.numpy as jnp

        self.tol = 1e-8 if self.rdtype == jnp.float64 else 3e-5

    # -- artifacts (built once, under build_lock) -----------------------------
    def build_artifacts(self) -> None:
        """Factorize the FDLF pair (and grab the BCSR pattern handle on
        sparse-backend cases) — the one-time per-(case, topology) cost
        every tier amortizes.  Idempotent; callers serialize on
        ``build_lock`` (single-flight: a herd factorizes once)."""
        if self.precond is not None:
            return
        from freedm_tpu.pf.krylov import build_fdlf_precond
        from freedm_tpu.pf.sparse import jacobian_pattern, resolve_backend

        t0 = time.monotonic()
        precond = build_fdlf_precond(self.sys, dtype=self.rdtype, kind="lu")
        pattern = None
        if resolve_backend(self.backend, self.sys.n_bus) == "sparse":
            pattern = jacobian_pattern(self.sys)
        self.artifact_bytes = _nbytes(precond.bp) + _nbytes(precond.bq)
        if pattern is not None:
            # The BCSR pattern's index arrays are held alive by this
            # entry — budget them like every other artifact.  (Jitted
            # executables are not byte-accounted, same as the serve
            # engines' programs.)
            self.artifact_bytes += (
                _nbytes(pattern.f) + _nbytes(pattern.t)
                + _nbytes(pattern.rows)
            )
        self.pattern = pattern
        self.precond = precond
        if profiling.PROFILER.enabled:
            profiling.PROFILER.record_host(
                "serve.cache.build", time.monotonic() - t0
            )

    def ensure_delta_fn(self):
        """The jitted correction program (built lazily, compiled by XLA
        on its first call — or at :meth:`ServeCache.prewarm_entry`)."""
        with self.build_lock:
            self.build_artifacts()
            if self.delta_fn is None:
                self.delta_fn = _build_delta_program(
                    self.sys, self.precond, self.tol, DELTA_MAX_SWEEPS,
                    self.rdtype, precision=self.precision,
                )
        return self.delta_fn

    def dc_solver(self):
        """DC screen over this case, sharing the entry's B′ LU (no
        second factorization — ``make_dc_solver(lu=...)``)."""
        with self.build_lock:
            self.build_artifacts()
            if self._dc is None:
                from freedm_tpu.pf.dc import make_dc_solver

                self._dc = make_dc_solver(
                    self.sys, dtype=self.rdtype, lu=self.precond.bp
                )
        return self._dc

    def verify(self, theta: np.ndarray, v: np.ndarray, p_req: np.ndarray,
               q_req: np.ndarray) -> float:
        """Host float64 residual of a candidate solution against the
        REQUEST's injections — the delta tier's accept/fall-through
        gate, sharing :func:`~freedm_tpu.pf.krylov.host_injections`
        with the solver oracles."""
        from freedm_tpu.pf.krylov import host_injections

        p_calc, q_calc = host_injections(self.sys, theta, v)
        fp = np.where(self._th_free, p_calc - p_req, 0.0)
        fq = np.where(self._v_free, q_calc - q_req, 0.0)
        # np.float64 (a float subclass — callers unchanged) so the
        # gridprobe F64_SURFACES evaluation check has dtype evidence
        # that the gate computed in double precision.
        return np.float64(max(np.max(np.abs(fp)), np.max(np.abs(fq))))


def _build_delta_program(sys, precond, tol, max_sweeps, rdtype,
                         precision: str = "f64"):
    """Compile the delta tier's correction: warm-started fast-decoupled
    sweeps whose inner solve is ``smw_delta_solve`` (rank-0: the cached
    LU pair IS the matrix — an injection delta moves only the RHS),
    iterated until the mismatch clears ``tol`` or ``max_sweeps``.  One
    jitted program per (case, topology); every delta answer reuses it.

    ``precision="mixed"`` (the ``--pf-precision`` key, resolved by the
    owning :class:`ServeCache`) runs the INNER triangular solves in
    float32 — an f32 copy of the cached LU pair — as mixed-precision
    iterative refinement: the iterates, the mismatch, and the exit test
    stay in the working dtype, so the f32 solve only *proposes* each
    sweep's correction direction while the f64 residual drives
    convergence (B′/B″ are approximate sweep operators already — a few
    ulps of f32 solve error just costs sweeps, not accuracy).  The
    acceptance contract is unchanged: :meth:`CaseEntry.verify`'s host
    float64 residual check is still the only gate between a delta
    answer and the client, and a residual miss falls through to the
    warm tier exactly as before — mixed can only ever make the tier
    slower-but-correct, never wrong (the same oracle discipline as the
    solvers' mixed inner GMRES, docs/solvers.md "Mixed precision").
    """
    import jax
    import jax.numpy as jnp

    from freedm_tpu.pf.fdlf import decoupled_parts
    from freedm_tpu.pf.mfree import make_injection_fn
    from freedm_tpu.pf.n1 import smw_delta_solve

    mixed = precision == "mixed"
    parts = decoupled_parts(sys, rdtype)
    th_free, v_free = parts.th_free, parts.v_free
    inject = make_injection_fn(sys, rdtype)
    if mixed:
        lu_p = (jnp.asarray(precond.bp[0], jnp.float32), precond.bp[1])
        lu_q = (jnp.asarray(precond.bq[0], jnp.float32), precond.bq[1])

        def _solve_p(dp):
            return smw_delta_solve(
                lu_p, None, None, dp.astype(jnp.float32)
            ).astype(rdtype)

        def _solve_q(dq):
            return smw_delta_solve(
                lu_q, None, None, dq.astype(jnp.float32)
            ).astype(rdtype)
    else:
        lu_p, lu_q = precond.bp, precond.bq

        def _solve_p(dp):
            return smw_delta_solve(lu_p, None, None, dp)

        def _solve_q(dq):
            return smw_delta_solve(lu_q, None, None, dq)

    @jax.jit
    def correct(theta0, v0, p_sched, q_sched):
        with jax.default_matmul_precision("highest"):
            p_s = jnp.asarray(p_sched, rdtype)
            q_s = jnp.asarray(q_sched, rdtype)

            def mismatch(theta, v):
                p_calc, q_calc = inject(theta, v)
                dp = (p_s - p_calc) / v * th_free
                dq = (q_s - q_calc) / v * v_free
                return dp, dq

            def err_from(dp, dq, v):
                return jnp.maximum(
                    jnp.max(jnp.abs(dp * v)), jnp.max(jnp.abs(dq * v))
                ).astype(rdtype)

            theta = jnp.asarray(theta0, rdtype)
            v = jnp.asarray(v0, rdtype)
            dp, dq = mismatch(theta, v)

            def cond(c):
                theta_c, v_c, dp_c, dq_c, it = c
                return jnp.logical_and(
                    it < max_sweeps, err_from(dp_c, dq_c, v_c) >= tol
                )

            def body(c):
                theta, v, dp, dq, it = c
                theta = theta + _solve_p(dp) * th_free
                _, dq2 = mismatch(theta, v)
                v = v + _solve_q(dq2) * v_free
                dp3, dq3 = mismatch(theta, v)
                return (theta, v, dp3, dq3, it + 1)

            theta, v, dp, dq, it = jax.lax.while_loop(
                cond, body, (theta, v, dp, dq, jnp.int32(0))
            )
            p_calc, q_calc = inject(theta, v)
            return theta, v, p_calc, q_calc, err_from(dp, dq, v), it

    return correct


class _Flight:
    """One in-progress cold solve and the followers riding it."""

    __slots__ = ("entry", "digest", "followers")

    def __init__(self, entry: CaseEntry, digest: str):
        self.entry = entry
        self.digest = digest
        self.followers: List[object] = []  # Ticket-shaped records


class ServeCache:
    """The bounded incremental-tier store (see the module docstring).

    ``max_bytes`` budgets solutions **plus artifacts**; a case whose
    artifacts alone would overrun it is never cached (``entry`` returns
    ``None`` and the serve path stays cold — correct, just uncached).
    ``verify_tol`` overrides the engine-tolerance accept bar of the
    delta tier (tests use it to force fall-through).
    """

    def __init__(self, max_bytes: int, ttl_s: float = 600.0,
                 delta_max_rank: int = 16, delta_max_pu: float = DELTA_MAX_PU,
                 verify_tol: Optional[float] = None,
                 precision: str = "f64"):
        from freedm_tpu.pf.krylov import resolve_precision

        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self.delta_max_rank = int(delta_max_rank)
        self.delta_max_pu = float(delta_max_pu)
        self.verify_tol = verify_tol
        # Inner precision of the delta tier's correction program (the
        # --pf-precision key): "mixed" = f32 SMW sweeps under the
        # unchanged float64 verify oracle; resolved once here so every
        # entry compiles the same program kind.
        self.precision = resolve_precision(precision)
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str, str], CaseEntry] = {}
        self._lru: "OrderedDict[Tuple[Tuple[str, str, str], str], CaseEntry]" \
            = OrderedDict()
        self._flights: Dict[Tuple[Tuple[str, str, str], str], _Flight] = {}
        self.bytes = 0
        self._counts = {t: 0 for t in _TIERS}
        self._joins = 0
        self._evictions = {"lru": 0, "ttl": 0, "invalidate": 0}

    # -- entries --------------------------------------------------------------
    def entry(self, case: str, sys, backend: str,
              topo: Optional[str] = None) -> Optional[CaseEntry]:
        """The live entry for (case, topology, backend) — created (and
        its artifacts factorized, single-flight) on first touch, or
        ``None`` when the case cannot fit the byte budget.  Callers
        re-fetch per request: an evicted/invalidated entry is dead and
        its key resolves to a fresh rebuild."""
        if topo is None:
            topo = topology_digest(sys)
        key = (case, topo, backend)
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                ent.last_used = time.monotonic()
                return ent
            n = sys.n_bus
            # Two [n, n] LU factors (+ pivots) in the working dtype.
            est = 2 * (n * n + n) * 8
            if est > self.max_bytes:
                return None
            ent = CaseEntry(case, sys, backend, topo,
                            precision=self.precision)
            self._entries[key] = ent
        with ent.build_lock:
            ent.build_artifacts()
        with self._lock:
            # `accounted` pairs the one add with the (at most one)
            # subtract in invalidate/_evict_locked — a racing
            # invalidation between build and this block must not drive
            # the byte account negative.
            if ent.alive and not ent.accounted:
                ent.accounted = True
                self.bytes += ent.artifact_bytes
                self._evict_locked()
            self._set_gauges_locked()
        return ent

    def peek_entry(self, case: str, topo: Optional[str],
                   backend: str) -> Optional[CaseEntry]:
        """The live entry for the key, or ``None`` — never builds.  The
        scatter-side publish path uses this so an invalidated/evicted
        entry's in-flight inserts genuinely land nowhere (re-creating
        the entry there would also put an O(n³) artifact factorization
        on the device-executor lane)."""
        with self._lock:
            ent = self._entries.get((case, topo, backend))
            if ent is not None:
                ent.last_used = time.monotonic()
            return ent

    # -- lookup (host-only: GL002 zero-sync hot path) -------------------------
    def lookup(self, entry: CaseEntry, digest: str, p: np.ndarray,
               q: np.ndarray):
        """Classify one pf request against the entry's solutions.

        Returns ``(tier, payload)``: ``("exact", solution)`` /
        ``("delta", nearest)`` / ``("warm", nearest)`` /
        ``("miss", None)``.  Pure host work — dict probes plus O(n)
        numpy compares over at most :data:`DELTA_SCAN` recent solutions
        — so the submit path never blocks on the device here.
        """
        now = time.monotonic()
        ttl = self.ttl_s
        with self._lock:
            entry.last_used = now
            # Full TTL sweeps are time-gated (at most one per
            # _TTL_SWEEP_S per entry): O(solutions) work must not sit
            # on every lookup's critical section, or the exact-hit
            # sub-millisecond contract dies at exactly the repeat
            # volume the tier exists for.
            if ttl > 0 and now - entry.ttl_sweep >= _TTL_SWEEP_S:
                entry.ttl_sweep = now
                self._prune_expired_locked(entry, now)
            sol = entry.solutions.get(digest)
            if sol is not None and ttl > 0 and now - sol.stamp > ttl:
                # Freshness is enforced on the candidate itself, not
                # just by the gated sweep: an expired solution is never
                # served.
                self._drop_expired_locked(entry, sol)
                sol = None
            if sol is not None and np.array_equal(sol.p_inj, p) \
                    and np.array_equal(sol.q_inj, q):
                self._touch_locked(entry, sol, now)
                return "exact", sol
            best_delta = None
            best_delta_rank = None
            best_warm = None
            best_warm_l1 = None
            scanned = 0
            for s in reversed(entry.solutions.values()):
                if scanned >= DELTA_SCAN:
                    break
                scanned += 1
                if ttl > 0 and now - s.stamp > ttl:
                    continue  # expired: never served (sweep reaps it)
                dp = p - s.p_inj
                dq = q - s.q_inj
                changed = (np.abs(dp) > 1e-12) | (np.abs(dq) > 1e-12)
                rank = int(np.count_nonzero(changed))
                mag = float(max(np.max(np.abs(dp)), np.max(np.abs(dq))))
                l1 = float(np.sum(np.abs(dp)) + np.sum(np.abs(dq)))
                if rank <= self.delta_max_rank and mag <= self.delta_max_pu:
                    if best_delta_rank is None or rank < best_delta_rank:
                        best_delta, best_delta_rank = s, rank
                if best_warm_l1 is None or l1 < best_warm_l1:
                    best_warm, best_warm_l1 = s, l1
            if best_delta is not None:
                self._touch_locked(entry, best_delta, now)
                return "delta", best_delta
            if best_warm is not None:
                self._touch_locked(entry, best_warm, now)
                return "warm", best_warm
            return "miss", None

    # -- delta tier (device correction + the ONE designed verify sync) --------
    def delta_answer(self, entry: CaseEntry, near: CachedSolution,
                     p: np.ndarray, q: np.ndarray) -> Optional[dict]:
        """Correct ``near`` to the requested injections off the cached
        factorization; verify on host; ``None`` on a residual miss (the
        caller falls through to the warm tier).  The ``np.asarray``
        pulls below are the delta-verify boundary — the one designed
        sync of the cache path (GL002)."""
        if entry.delta_fn is None:
            entry.ensure_delta_fn()
        t0 = time.monotonic()
        res = entry.delta_fn(near.theta, near.v, p, q)
        theta = np.asarray(res[0], np.float64)
        v = np.asarray(res[1], np.float64)
        p_calc = np.asarray(res[2], np.float64)
        q_calc = np.asarray(res[3], np.float64)
        sweeps = np.asarray(res[5])
        if profiling.PROFILER.enabled:
            profiling.PROFILER.record_host(
                "serve.cache.delta_solve", time.monotonic() - t0
            )
        if faults.FAULTS.enabled and faults.FAULTS.should(
            "serve.cache.corrupt"
        ):
            # Injected artifact corruption (docs/robustness.md): the
            # candidate is perturbed BEFORE the verify, on the already-
            # pulled host arrays.  The float64 residual check below is
            # the only thing standing between this and a wrong answer —
            # it must catch the corruption and fall through.
            v = v + faults.FAULTS.arg("serve.cache.corrupt", 0.05)
        if not (np.all(np.isfinite(theta)) and np.all(np.isfinite(v))):
            return None
        err = entry.verify(theta, v, p, q)
        tol = self.verify_tol if self.verify_tol is not None else entry.tol
        if err > tol:
            return None  # fall through to the warm tier — never served
        if self.verify_tol is not None and err > entry.tol:
            # The OVERRIDDEN bar accepted what the engine bar would have
            # rejected (the chaos negative-proof configuration): journal
            # it, so a loosened verify is never silent — the shadow
            # verifier (core/provenance.py) is now the only gate left.
            obs.EVENTS.emit(
                "serve.cache.loose_accept",
                case=entry.case, residual_pu=float(err),
                engine_tol=float(entry.tol), verify_tol=float(tol),
            )
        return {
            "theta": theta, "v": v, "p": p_calc, "q": q_calc,
            "iterations": int(sweeps), "mismatch": err, "converged": True,
        }

    # -- insertion (host-only: GL002 zero-sync hot path) ----------------------
    def insert(self, entry: CaseEntry, digest: str, p: np.ndarray,
               q: np.ndarray, v, theta, p_calc, q_calc, iterations: int,
               mismatch: float, converged: bool) -> Optional[CachedSolution]:
        """Store one converged operating point (full-solve scatter or a
        verified delta answer); evicts LRU/TTL victims past the byte
        budget.  Dead entries (evicted/invalidated while the solve was
        in flight) are skipped."""
        if not converged:
            return None
        sol = CachedSolution(digest, p, q, v, theta, p_calc, q_calc,
                             iterations, mismatch, converged)
        with self._lock:
            if not entry.alive:
                return None
            old = entry.solutions.pop(digest, None)
            if old is not None:
                self._lru.pop((entry.key, digest), None)
                self.bytes -= old.nbytes
            entry.solutions[digest] = sol
            self._lru[(entry.key, digest)] = entry
            self.bytes += sol.nbytes
            entry.last_used = sol.stamp
            self._evict_locked()
            self._set_gauges_locked()
        return sol

    # -- single flight --------------------------------------------------------
    def flight_claim(self, entry: CaseEntry, digest: str, follower):
        """Atomically: late exact-hit, join an in-progress solve, or
        lead a new one.  Returns ``("exact", solution)``,
        ``("joined", None)`` (the follower is parked on the flight), or
        ``("lead", None)`` (the caller enqueues the real solve and
        settles/aborts the flight when it completes)."""
        key = (entry.key, digest)
        with self._lock:
            sol = entry.solutions.get(digest)
            if sol is not None:
                self._touch_locked(entry, sol, time.monotonic())
                return "exact", sol
            fl = self._flights.get(key)
            if fl is not None:
                fl.followers.append(follower)
                self._joins += 1
                return "joined", None
            self._flights[key] = _Flight(entry, digest)
            return "lead", None

    def settle_flight(self, key) -> Tuple[Optional[CaseEntry], List[object]]:
        """Pop one flight at leader completion: ``(entry, followers)``
        (entry ``None`` if the flight vanished with an invalidation)."""
        with self._lock:
            fl = self._flights.pop(key, None)
            if fl is None:
                return None, []
            return fl.entry, fl.followers

    def abort_flight(self, key) -> List[object]:
        """Pop a flight whose leader failed/expired: its followers (the
        caller fails them with the leader's error)."""
        with self._lock:
            fl = self._flights.pop(key, None)
            return [] if fl is None else fl.followers

    # -- invalidation / eviction ----------------------------------------------
    def invalidate(self, case: Optional[str] = None) -> int:
        """Drop every entry (artifacts + solutions) for ``case`` (or
        all cases) — the explicit topology/status-change hook.  Returns
        dropped solution count.  In-flight solves against a dropped
        entry still answer their waiters; their insert lands nowhere."""
        dropped = 0
        with self._lock:
            for key in [k for k in self._entries
                        if case is None or k[0] == case]:
                ent = self._entries.pop(key)
                ent.alive = False
                for dig in list(ent.solutions):
                    sol = ent.solutions.pop(dig)
                    self._lru.pop((key, dig), None)
                    self.bytes -= sol.nbytes
                    dropped += 1
                if ent.accounted:
                    ent.accounted = False
                    self.bytes -= ent.artifact_bytes
                self._evictions["invalidate"] += 1
                obs.SERVE_CACHE_EVICTIONS.labels("invalidate").inc()
            self._set_gauges_locked()
        return dropped

    def _drop_expired_locked(self, entry: CaseEntry,
                             sol: CachedSolution) -> None:
        entry.solutions.pop(sol.digest, None)
        self._lru.pop((entry.key, sol.digest), None)
        self.bytes -= sol.nbytes
        self._evictions["ttl"] += 1
        obs.SERVE_CACHE_EVICTIONS.labels("ttl").inc()

    def _prune_expired_locked(self, entry: CaseEntry, now: float) -> None:
        if self.ttl_s <= 0:
            return
        for sol in [s for s in entry.solutions.values()
                    if now - s.stamp > self.ttl_s]:
            self._drop_expired_locked(entry, sol)

    def _touch_locked(self, entry: CaseEntry, sol: CachedSolution,
                      now: float) -> None:
        # A touch refreshes LRU order only; TTL ages from insert time.
        entry.solutions.move_to_end(sol.digest)
        self._lru.move_to_end((entry.key, sol.digest), last=True)

    def _evict_locked(self) -> None:
        """LRU victims until the budget holds: solutions first (oldest
        touch anywhere), then whole idle entries' artifacts."""
        while self.bytes > self.max_bytes and self._lru:
            (ekey, dig), ent = self._lru.popitem(last=False)
            sol = ent.solutions.pop(dig, None)
            if sol is not None:
                self.bytes -= sol.nbytes
                self._evictions["lru"] += 1
                obs.SERVE_CACHE_EVICTIONS.labels("lru").inc()
        if self.bytes > self.max_bytes and len(self._entries) > 1:
            for key in sorted(self._entries,
                              key=lambda k: self._entries[k].last_used):
                if self.bytes <= self.max_bytes:
                    break
                ent = self._entries.pop(key)
                ent.alive = False
                if ent.accounted:
                    ent.accounted = False
                    self.bytes -= ent.artifact_bytes
                self._evictions["lru"] += 1
                obs.SERVE_CACHE_EVICTIONS.labels("lru").inc()

    # -- accounting -----------------------------------------------------------
    def record(self, tier: str) -> None:
        """Count one resolved lookup (tier ∈ exact/delta/warm/miss) and
        refresh the hit-ratio gauge."""
        with self._lock:
            self._counts[tier] += 1
            lookups = sum(self._counts.values())
            served = self._counts["exact"] + self._counts["delta"]
            ratio = served / lookups if lookups else 0.0
        if tier == "miss":
            obs.SERVE_CACHE_MISSES.inc()
        else:
            obs.SERVE_CACHE_HITS.labels(tier).inc()
        obs.SERVE_CACHE_HIT_RATIO.set(ratio)

    def _set_gauges_locked(self) -> None:
        obs.SERVE_CACHE_BYTES.set(self.bytes)

    def prewarm_entry(self, entry: CaseEntry) -> None:
        """Compile the delta program at startup (``--serve-prewarm``):
        the first delta request pays a solve, not an XLA compile."""
        fn = entry.ensure_delta_fn()
        sys = entry.sys
        v0 = np.where(entry._v_free, 1.0, np.asarray(sys.v_set, np.float64))
        out = fn(np.zeros(sys.n_bus), v0,
                 np.asarray(sys.p_inj, np.float64),
                 np.asarray(sys.q_inj, np.float64))
        np.asarray(out[0])  # block: the compile is done when we return

    def stats(self) -> dict:
        """The ``/stats`` cache block."""
        with self._lock:
            return {
                "bytes": self.bytes,
                "budget_bytes": self.max_bytes,
                "ttl_s": self.ttl_s,
                "delta_max_rank": self.delta_max_rank,
                "entries": len(self._entries),
                "solutions": sum(len(e.solutions)
                                 for e in self._entries.values()),
                "hits": {t: self._counts[t] for t in ("exact", "delta",
                                                      "warm")},
                "misses": self._counts["miss"],
                "flight_joins": self._joins,
                "inflight": len(self._flights),
                "evictions": dict(self._evictions),
                "hit_ratio": round(
                    (self._counts["exact"] + self._counts["delta"])
                    / max(sum(self._counts.values()), 1), 4
                ),
            }

    def snapshot_state(self) -> dict:
        """Byte-accounting cut for the snapshot auditor
        (:mod:`freedm_tpu.core.snapshot`): the running ``bytes`` counter
        versus a from-scratch walk of the same structures under the same
        lock hold — any difference is an accounting leak (a solution or
        artifact added/removed without its byte delta)."""
        with self._lock:
            accounted = sum(
                sol.nbytes
                for ent in self._entries.values()
                for sol in ent.solutions.values()
            ) + sum(
                ent.artifact_bytes
                for ent in self._entries.values() if ent.accounted
            )
            return {
                "bytes": self.bytes,
                "accounted_bytes": accounted,
                "budget_bytes": self.max_bytes,
                "entries": len(self._entries),
                "solutions": sum(len(e.solutions)
                                 for e in self._entries.values()),
                "inflight_leads": len(self._flights),
            }
