"""Typed query workloads + the multi-tenant serving facade.

Three what-if workloads, each a thin typed shell over a solver the tree
already ships (the serving layer adds *no* numerics of its own):

- **pf** — snapshot AC power flow: a named case plus per-bus injection
  overrides (or a uniform stress ``scale``), solved by the batched
  Newton-Raphson path (:mod:`freedm_tpu.pf.newton`).  One request = one
  ``vmap`` lane.
- **n1** — N-1 contingency screen over a *subset* of branches, through
  the Sherman-Morrison-Woodbury fast-decoupled screen
  (:mod:`freedm_tpu.pf.n1`).  One request = ``len(outages)`` lanes;
  islanding (bridge) outages are rejected at validation, because their
  lanes are mathematically garbage (singular B′).
- **vvc** — Volt-VAR what-if: a proposed Q-setpoint vector for a feeder,
  answered with the loss/voltage-band report the proposal would produce
  (:mod:`freedm_tpu.pf.ladder`).  One request = one scenario lane.

Every response is stamped with the solver's own convergence evidence
(``residual_pu``/``converged``) plus a conservation check (power-flow:
Σ realized P = network losses, which must be small and non-negative;
VVC: substation minus load power), so a client never has to trust a
200 status alone.

:class:`Service` ties the pieces together: per-request validation
(synchronous, so an invalid request never occupies queue depth),
admission (:mod:`freedm_tpu.serve.queue`), micro-batched dispatch
(:mod:`freedm_tpu.serve.batcher`), and engine caching — one compiled
engine per (workload, case), shape-bucketed so the jit recompile count
is bounded by the bucket table and *counted*
(``serve_recompiles_total``).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time as _time
from concurrent.futures import TimeoutError as _FuturesTimeout
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from freedm_tpu.core import metrics as obs
from freedm_tpu.core import profiling
from freedm_tpu.core import provenance as _prov
from freedm_tpu.core import tracing
from freedm_tpu.serve.queue import (
    AdmissionQueue,
    DeadlineExceeded,
    InvalidRequest,
    Overloaded,
    ServeError,
    ShuttingDown,
    Ticket,
)

WORKLOADS = ("pf", "n1", "vvc", "topo")

#: Voltage band for the VVC report, pu (ANSI C84.1 service band).
V_BAND = (0.95, 1.05)


# ---------------------------------------------------------------------------
# Request / response records
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PowerFlowRequest:
    """Snapshot power flow: ``case`` + injection overrides.

    ``p_inj``/``q_inj`` are full per-bus vectors in system pu (length
    ``n_bus``); omitted, the case's stored injections scaled by
    ``scale`` are used.  ``v0``/``theta0`` optionally warm-start the
    Newton iteration from a previous solution (same ``[n]`` validation)
    — a repeated what-if client gets the same iteration savings the
    QSTS engine's step-to-step carry does; omitted, the flat start is
    used.
    """

    case: str
    p_inj: Optional[Sequence[float]] = None
    q_inj: Optional[Sequence[float]] = None
    v0: Optional[Sequence[float]] = None
    theta0: Optional[Sequence[float]] = None
    scale: float = 1.0
    # Full [n] voltage/angle vectors in the response.  Off by default:
    # summary stats answer most what-ifs, and building per-bus lists is
    # measurable per-request work on the scatter path.
    return_state: bool = False
    timeout_s: float = 30.0


@dataclass(frozen=True)
class N1Request:
    """Contingency screen over a branch subset (indices into the case's
    branch table; each must be non-islanding)."""

    case: str
    outages: Sequence[int] = ()
    timeout_s: float = 30.0


@dataclass(frozen=True)
class VVCRequest:
    """Volt-VAR what-if: a proposed ``[nb, 3]`` Q-setpoint table (kvar,
    0 where not controlled) for a feeder case."""

    case: str
    q_ctrl_kvar: Sequence[Sequence[float]] = ()
    timeout_s: float = 30.0


@dataclass(frozen=True)
class TopoRequest:
    """Switching screen: enumerate (or neighborhood-sample) open-sets of
    up to ``max_rank`` candidate switches, DC-screen every variant
    through the rank-r SMW lanes over the case's cached B′ LU, rank by
    ``objective`` (lower is better), and AC-verify the ``top_k``
    shortlist on the sparse backend before answering
    (:mod:`freedm_tpu.pf.topo`; docs/topology.md).

    ``switches`` is the candidate branch list (``None`` = every
    branch); ``mode="radial"`` additionally requires each surviving
    variant's closed set to be a spanning tree.  Caps: ``max_rank`` ≤
    ``--topo-max-rank``, variant count ≤ ``--topo-max-variants``,
    ``top_k`` ≤ ``--topo-top-k``.
    """

    case: str
    switches: Optional[Sequence[int]] = None
    max_rank: int = 2
    mode: str = "mesh"
    objective: str = "loss"
    flow_limit: float = 1.0
    top_k: int = 4
    search: str = "exhaustive"
    samples: int = 0
    seed: int = 0
    timeout_s: float = 30.0


@dataclass
class BatchInfo:
    """How this request was served — the micro-batching receipt.

    ``tier`` names the incremental-tier path that answered it:
    ``"full"`` = a dispatched device solve (warm-started or not),
    ``"exact"`` = the cached solution verbatim (this covers single-
    flight followers too — they ride the leader's solve and are
    answered from its just-inserted solution), ``"delta"`` = the
    residual-verified SMW/FDLF correction off the cached factorization
    (``bucket`` 0: no batch was dispatched for the cache tiers).
    """

    lanes: int  # real lanes in the dispatched batch (all requests)
    bucket: int  # padded static shape the batch ran at
    queue_ms: float  # admission -> dispatch
    solve_ms: float  # batched solve wall time (shared by the batch)
    tier: str = "full"  # incremental tier: full | exact | delta

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass
class PowerFlowResponse:
    workload: str
    case: str
    scale: float
    converged: bool
    iterations: int
    residual_pu: float
    p_balance_pu: float  # Σ realized P = network losses (small, >= ~0)
    q_balance_pu: float
    v_min_pu: float
    v_max_pu: float
    batch: BatchInfo
    v: Optional[List[float]] = None  # per-bus |V| (return_state=True)
    theta: Optional[List[float]] = None  # per-bus angle, rad
    # Provenance receipt (core/provenance.py) — attached only when the
    # observatory is enabled, so disabled-mode responses are
    # byte-identical to before.
    provenance: Optional[dict] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["batch"] = self.batch.to_dict()
        if self.provenance is None:
            d.pop("provenance")
        return d


@dataclass
class N1Response:
    workload: str
    case: str
    outages: List[int]
    converged: List[bool]
    residual_pu: List[float]
    v_min_pu: List[float]
    v_max_pu: List[float]
    worst_residual_pu: float
    all_converged: bool
    batch: BatchInfo
    provenance: Optional[dict] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["batch"] = self.batch.to_dict()
        if self.provenance is None:
            d.pop("provenance")
        return d


@dataclass
class VVCResponse:
    workload: str
    case: str
    converged: bool
    residual: float
    loss_kw: float
    loss_base_kw: float  # losses at the zero-injection baseline
    loss_delta_kw: float  # loss_kw - loss_base_kw (negative = improvement)
    v_min_pu: float
    v_max_pu: float
    band_violations: int  # live node-phases outside V_BAND
    batch: BatchInfo
    provenance: Optional[dict] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["batch"] = self.batch.to_dict()
        if self.provenance is None:
            d.pop("provenance")
        return d


@dataclass
class TopoResponse:
    """One switching screen's verdict: exclusion accounting (structural
    + SMW-backstop), the AC-verified shortlist, and the screen rate."""

    workload: str
    case: str
    mode: str
    objective: str
    max_rank: int
    n_variants: int
    # The exclusion accounting partitions the variant space exactly:
    # n_feasible + n_disconnected + n_nonradial + n_islanded
    # == n_variants.
    n_feasible: int
    n_islanded: int  # SMW singular-capacitance backstop fired ALONE
    n_disconnected: int  # structural connectivity check fires
    n_nonradial: int  # connected but not a spanning tree (mode=radial)
    shortlist: List[dict]  # open_branches/objective/ac stamps per entry
    all_verified: bool  # every shortlist entry's AC lane converged
    batch: BatchInfo
    provenance: Optional[dict] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["batch"] = self.batch.to_dict()
        if self.provenance is None:
            d.pop("provenance")
        return d


# ---------------------------------------------------------------------------
# Case registry
# ---------------------------------------------------------------------------

#: Bus-system cases servable by pf/n1 (MATPOWER builtins).
BUS_CASES = ("case14", "case_ieee30")
#: Feeder cases servable by vvc.
FEEDER_CASES = ("vvc_9bus",)


#: Cap on the client-named synthetic meshN size: the dense Newton path
#: is O(n^2) memory, and the case name is attacker-controlled input.
MAX_MESH_BUSES = 2000


def _resolve_bus_case(name: str):
    if name in BUS_CASES:
        from freedm_tpu.grid.matpower import load_builtin

        return load_builtin(name)
    if name.startswith("mesh") and name[4:].isdigit():
        # meshN: the synthetic transmission generator at N buses —
        # the scale-test tenant (bench.py uses mesh118).
        n = int(name[4:])
        if not 2 <= n <= MAX_MESH_BUSES:
            raise InvalidRequest(
                f"meshN size must be in [2, {MAX_MESH_BUSES}], got {n}"
            )
        from freedm_tpu.grid.cases import synthetic_mesh

        return synthetic_mesh(n, seed=1, load_mw=10.0, chord_frac=1.0)
    raise InvalidRequest(
        f"unknown bus case {name!r} (have: {', '.join(BUS_CASES)}, meshN)"
    )


def _resolve_feeder_case(name: str):
    if name in FEEDER_CASES:
        from freedm_tpu.grid import cases

        return getattr(cases, name)()
    raise InvalidRequest(
        f"unknown feeder case {name!r} (have: {', '.join(FEEDER_CASES)})"
    )


def _pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Pad a stacked batch up to its bucket by repeating the last row —
    a real, convergent lane, so padding can never poison batch numerics."""
    pad = bucket - arr.shape[0]
    if pad <= 0:
        return arr
    return np.concatenate([arr, np.repeat(arr[-1:], pad, axis=0)])


def _as_vector(val, n: int, what: str) -> np.ndarray:
    arr = np.asarray(val, np.float64)
    if arr.shape != (n,):
        raise InvalidRequest(f"{what} must be a length-{n} vector, got shape {arr.shape}")
    if not np.all(np.isfinite(arr)):
        raise InvalidRequest(f"{what} contains non-finite values")
    return arr


# ---------------------------------------------------------------------------
# Engines: one compiled solver front per (workload, case)
# ---------------------------------------------------------------------------


class _Engine:
    """Common engine shape the batcher drives.

    ``validate`` runs on the submitter's thread (before admission);
    ``assemble`` runs on the batch-assembly lane; ``solve``/``scatter``
    run on the workload's executor lane (or inline on the dispatch
    thread at ``--serve-pipeline-depth 0``).  ``solve`` must only
    *dispatch* — it returns the async device result and never blocks;
    the batcher performs the one deferred ``jax.block_until_ready`` at
    its measurement boundary, which is what keeps device time honest
    and lets batch N+1 assemble while batch N solves.
    """

    workload = ""

    def __init__(self, case: str):
        self.case = case
        self.key = (self.workload, case)
        self.compiled_buckets: set = set()
        # Resolved solver identity for provenance receipts: the pf/n1
        # engines overwrite these with the dense/sparse + f64/mixed
        # resolution their compiled programs actually run; workloads
        # with no Jacobian/Krylov inner (vvc, topo) keep None.
        self.pf_backend: Optional[str] = None
        self.pf_precision: Optional[str] = None

    def validate(self, req):  # -> prepared payload (host arrays)
        raise NotImplementedError

    def example_request(self):
        """A minimal valid request for this engine — what
        :meth:`Service.prewarm` pushes through every bucket at startup."""
        raise NotImplementedError

    def lanes(self, prepared) -> int:
        return 1

    def assemble(self, group: List[Ticket], bucket: int):
        raise NotImplementedError

    def solve(self, batch):
        raise NotImplementedError

    def scatter(self, group: List[Ticket], results, info: BatchInfo) -> None:
        raise NotImplementedError


def _mesh_lanes(mesh) -> int:
    """Lane-shard count of an engine's mesh (0 = no mesh): the batch
    sizes the mesh program accepts are this count's multiples."""
    if mesh is None:
        return 0
    from freedm_tpu.parallel.mesh import lane_shards

    return lane_shards(mesh)


class PowerFlowEngine(_Engine):
    workload = "pf"

    def __init__(self, case: str, max_iter: int = 12, mesh=None,
                 backend: str = "auto", precision: str = "auto"):
        super().__init__(case)
        import jax

        from freedm_tpu.grid.bus import PQ
        from freedm_tpu.pf.krylov import resolve_precision
        from freedm_tpu.pf.newton import make_newton_solver
        from freedm_tpu.pf.sparse import resolve_backend

        sys_ = _resolve_bus_case(case)
        self._sys = sys_  # the serving cache keys its entry off this
        self.pf_backend = resolve_backend(backend, sys_.n_bus)
        self.pf_precision = (
            resolve_precision(precision)
            if self.pf_backend == "sparse" else "f64"
        )
        # Incremental-tier attach points, set by Service.engine() when a
        # cache is configured: `publish` is the Service-bound callback
        # scatter feeds converged solutions (and flight settles) into;
        # `cache_topo` is the topology digest computed ONCE (BusSystem
        # is frozen — in-place mutation cannot stale it), so per-request
        # entry resolution is a dict probe, not an O(n+m) hash.
        self.cache_backend: Optional[str] = None
        self.cache_topo: Optional[str] = None
        self.publish = None
        self.n_bus = sys_.n_bus
        self._p0 = np.asarray(sys_.p_inj, np.float64)
        self._q0 = np.asarray(sys_.q_inj, np.float64)
        # Flat start (the solver's own default): PQ magnitudes at 1.0,
        # pinned buses at their setpoint, zero angles — what a request
        # without v0/theta0 runs from.
        bt = np.asarray(sys_.bus_type)
        self._v0_flat = np.where(
            bt == PQ, 1.0, np.asarray(sys_.v_set, np.float64)
        )
        self._theta0_flat = np.zeros(self.n_bus)
        # The while-loop solve (not the fixed-iteration scan): per-lane
        # iteration counts are real under vmap (converged lanes stop
        # updating), so the response's `iterations` and the pf metrics
        # actually show what a warm start saves.
        solve, _ = make_newton_solver(sys_, max_iter=max_iter,
                                      backend=backend, precision=precision)
        # The dispatch buffers are DONATED: the assembled batch arrays
        # (p, q, v0, th0) are freshly padded per dispatch and alias the
        # result's (p, q, v, theta) buffers exactly, so every batch
        # re-uses its own HBM instead of allocating four fresh
        # [bucket, n] results (gridprobe GP004 audits the declaration).
        self._batched = jax.jit(
            jax.vmap(lambda p, q, v0, th0: solve(
                p_inj=p, q_inj=q, v0=v0, theta0=th0
            )),
            donate_argnums=(0, 1, 2, 3),
        )
        # Mesh form of the same while-loop solve: used for buckets the
        # device count divides; other buckets take the vmap program.
        self._mesh_lanes = _mesh_lanes(mesh)
        if self._mesh_lanes:
            self._batched_mesh, _ = make_newton_solver(
                sys_, max_iter=max_iter, mesh=mesh, backend=backend,
                precision=precision,
            )

    def solve(self, batch):
        # Dispatch only — the batcher blocks at its own measurement
        # boundary, so assembly of the next batch overlaps this one.
        p = batch[0]
        if self._mesh_lanes and p.shape[0] % self._mesh_lanes == 0:
            return self._batched_mesh(
                p_inj=p, q_inj=batch[1], v0=batch[2], theta0=batch[3]
            )
        return self._batched(*batch)

    def example_request(self):
        return PowerFlowRequest(case=self.case)

    def validate(self, req: PowerFlowRequest):
        if not (math.isfinite(req.scale) and 0.0 < req.scale <= 10.0):
            raise InvalidRequest(f"scale must be in (0, 10], got {req.scale!r}")
        p = (
            _as_vector(req.p_inj, self.n_bus, "p_inj")
            if req.p_inj is not None
            else self._p0 * req.scale
        )
        q = (
            _as_vector(req.q_inj, self.n_bus, "q_inj")
            if req.q_inj is not None
            else self._q0 * req.scale
        )
        if req.v0 is not None:
            v0 = _as_vector(req.v0, self.n_bus, "v0")
            if np.any(v0 < 0.1) or np.any(v0 > 2.0):
                raise InvalidRequest(
                    "v0 magnitudes must be in [0.1, 2.0] pu"
                )
        else:
            v0 = self._v0_flat
        if req.theta0 is not None:
            th0 = _as_vector(req.theta0, self.n_bus, "theta0")
            if np.any(np.abs(th0) > 2.0 * np.pi):
                raise InvalidRequest("theta0 angles must be within ±2π rad")
        else:
            th0 = self._theta0_flat
        if req.v0 is not None or req.theta0 is not None:
            obs.SERVE_WARM_START.inc()
        return {"p": p, "q": q, "v0": v0, "th0": th0}

    def assemble(self, group: List[Ticket], bucket: int):
        p = _pad_rows(np.stack([t.prepared["p"] for t in group]), bucket)
        q = _pad_rows(np.stack([t.prepared["q"] for t in group]), bucket)
        v0 = _pad_rows(np.stack([t.prepared["v0"] for t in group]), bucket)
        th0 = _pad_rows(np.stack([t.prepared["th0"] for t in group]), bucket)
        return p, q, v0, th0

    def scatter(self, group: List[Ticket], r, info: BatchInfo) -> None:
        v = np.asarray(r.v)
        theta = np.asarray(r.theta)
        p = np.asarray(r.p)
        q = np.asarray(r.q)
        its = np.asarray(r.iterations)
        conv = np.asarray(r.converged)
        mism = np.asarray(r.mismatch)
        # The result is host-side here anyway — record the served lanes'
        # iteration counts on the existing pf metrics, so a scrape shows
        # the iteration savings warm-started clients are getting.
        obs.PF_ITERATIONS.labels("newton").observe(its[: len(group)])
        obs.PF_RESIDUAL.labels("newton").set(float(mism[: len(group)].max()))
        p_bal = p.sum(axis=1)
        q_bal = q.sum(axis=1)
        v_min = v.min(axis=1)
        v_max = v.max(axis=1)
        fb = getattr(r, "fallbacks", None)
        if fb is not None:
            fb = np.asarray(fb)
        for i, t in enumerate(group):
            want_state = bool(t.request.return_state)
            resp = PowerFlowResponse(
                workload="pf",
                case=self.case,
                scale=float(t.request.scale),
                converged=bool(conv[i]),
                iterations=int(its[i]),
                residual_pu=float(mism[i]),
                p_balance_pu=float(p_bal[i]),
                q_balance_pu=float(q_bal[i]),
                v_min_pu=float(v_min[i]),
                v_max_pu=float(v_max[i]),
                v=np.round(v[i], 9).tolist() if want_state else None,
                theta=np.round(theta[i], 9).tolist() if want_state else None,
                batch=info,
            )
            if _prov.PROVENANCE.enabled:
                warm_src = t.prepared.get("warm_src")
                _prov.PROVENANCE.stamp(
                    resp, workload="pf", case=self.case,
                    tier="warm" if warm_src else info.tier,
                    span=t.span, backend=self.pf_backend,
                    precision=self.pf_precision,
                    fallbacks=None if fb is None else int(fb[i]),
                    iterations=int(its[i]),
                    residual=float(mism[i]),
                    warm_source=warm_src,
                    info=info,
                    solution=(self._sys, t.prepared["p"],
                              t.prepared["q"], v[i], theta[i]),
                )
            t.future.set_result(resp)
        if self.publish is not None:
            # Incremental tier: insert converged lanes into the serving
            # cache and settle any single-flight followers parked on
            # these tickets' digests (host arrays only — already pulled).
            self.publish(self, group, v, theta, p, q, its, conv, mism, info)


class N1Engine(_Engine):
    workload = "n1"

    #: Validation cap on outages per request (also the largest bucket).
    MAX_OUTAGES = 256

    def __init__(self, case: str, max_iter: int = 24, mesh=None,
                 backend: str = "auto", precision: str = "auto"):
        super().__init__(case)
        from freedm_tpu.pf.krylov import resolve_precision
        from freedm_tpu.pf.n1 import make_n1_screen, secure_outages
        from freedm_tpu.pf.sparse import resolve_backend

        sys_ = _resolve_bus_case(case)
        self.pf_backend = resolve_backend(backend, sys_.n_bus)
        self.pf_precision = (
            resolve_precision(precision)
            if self.pf_backend == "sparse" else "f64"
        )
        self.n_branch = sys_.n_branch
        self._secure = sorted(secure_outages(sys_))
        self._secure_set = frozenset(self._secure)
        # The mesh screen pads ragged lane counts internally, so it
        # serves every bucket; no fallback program needed.
        self._screen = make_n1_screen(sys_, max_iter=max_iter, mesh=mesh,
                                      backend=backend, precision=precision)

    def validate(self, req: N1Request):
        ks = list(req.outages)
        if not ks:
            raise InvalidRequest("outages must be a non-empty list of branch indices")
        if len(ks) > self.MAX_OUTAGES:
            raise InvalidRequest(
                f"at most {self.MAX_OUTAGES} outages per request, got {len(ks)}"
            )
        bad = [
            k for k in ks
            if not (isinstance(k, (int, np.integer)) and 0 <= k < self.n_branch)
        ]
        if bad:
            raise InvalidRequest(
                f"outage indices must be ints in [0, {self.n_branch}), got {bad}"
            )
        islanding = [k for k in ks if k not in self._secure_set]
        if islanding:
            raise InvalidRequest(
                f"outages {islanding} island the network (bridge branches); "
                f"their screen lanes would be singular"
            )
        return {"ks": np.asarray(ks, np.int64)}

    def lanes(self, prepared) -> int:
        return int(prepared["ks"].shape[0])

    def assemble(self, group: List[Ticket], bucket: int):
        ks = np.concatenate([t.prepared["ks"] for t in group])
        if ks.shape[0] < bucket:
            # Pad with replicas of the first requested outage — a real
            # non-islanding lane the screen solves anyway.
            ks = np.concatenate(
                [ks, np.full(bucket - ks.shape[0], ks[0], np.int64)]
            )
        return ks

    def solve(self, batch):
        return self._screen(batch)  # dispatch only; the batcher syncs

    def example_request(self):
        return N1Request(case=self.case, outages=[self._secure[0]])

    def scatter(self, group: List[Ticket], r, info: BatchInfo) -> None:
        v = np.asarray(r.v)
        conv = np.asarray(r.converged)
        mism = np.asarray(r.mismatch)
        off = 0
        for t in group:
            k = int(t.prepared["ks"].shape[0])
            sl = slice(off, off + k)
            off += k
            res = mism[sl].astype(np.float64).tolist()
            resp = N1Response(
                workload="n1",
                case=self.case,
                outages=t.prepared["ks"].tolist(),
                converged=conv[sl].tolist(),
                residual_pu=res,
                v_min_pu=v[sl].min(axis=1).astype(np.float64).tolist(),
                v_max_pu=v[sl].max(axis=1).astype(np.float64).tolist(),
                worst_residual_pu=max(res),
                all_converged=bool(conv[sl].all()),
                batch=info,
            )
            if _prov.PROVENANCE.enabled:
                _prov.PROVENANCE.stamp(
                    resp, workload="n1", case=self.case, tier=info.tier,
                    span=t.span, backend=self.pf_backend,
                    precision=self.pf_precision,
                    residual=max(res), info=info,
                )
            t.future.set_result(resp)


class VVCEngine(_Engine):
    workload = "vvc"

    def __init__(self, case: str, pf_iters: int = 20, mesh=None,
                 backend: str = "auto", precision: str = "auto"):
        # ``backend``/``precision`` are accepted for engine-construction
        # uniformity; the ladder sweep has no Jacobian and no Krylov
        # inner, so both are no-ops here.
        super().__init__(case)
        import jax
        import jax.numpy as jnp

        from freedm_tpu.pf import ladder
        from freedm_tpu.utils import cplx
        from freedm_tpu.utils.cplx import C

        feeder = _resolve_feeder_case(case)
        self.nb = feeder.n_branches
        mask = np.asarray(feeder.phase_mask, np.float64)
        self._mask = mask
        # Live node-phases incl. the always-3-phase substation row —
        # the denominator of the voltage-band report.
        self._live = np.concatenate([np.ones((1, 3)), mask]) > 0

        _, solve_fixed = ladder.make_ladder_solver(feeder, max_iter=pf_iters)
        s = cplx.as_c(feeder.s_load)
        s_re, s_im = jnp.asarray(s.re), jnp.asarray(s.im)
        mask_j = jnp.asarray(mask, s_re.dtype)

        def one(q_kvar):
            # Injecting Q reduces the load's Q draw (modules/vvc.py).
            res = solve_fixed(C(s_re, s_im - q_kvar * mask_j))
            loss = ladder.total_loss_kw(feeder, res)
            return loss, res.v_node.abs(), res.converged, res.residual

        self._batched = jax.jit(jax.vmap(one))
        self._mesh_lanes = _mesh_lanes(mesh)
        if self._mesh_lanes:
            from freedm_tpu.parallel import mesh as pmesh

            s1 = pmesh.lane_spec(mesh, 1)
            s3 = pmesh.lane_spec(mesh, 3)
            self._batched_mesh = pmesh.shard_batched(
                lambda qb: jax.vmap(one)(qb), mesh,
                in_specs=(s3,), out_specs=(s1, s3, s1, s1),
            )
        base = solve_fixed(s)
        self.loss_base_kw = float(ladder.total_loss_kw(feeder, base))

    def validate(self, req: VVCRequest):
        q = np.asarray(req.q_ctrl_kvar, np.float64)
        if q.shape != (self.nb, 3):
            raise InvalidRequest(
                f"q_ctrl_kvar must be [{self.nb}, 3] (kvar per node-phase), "
                f"got shape {q.shape}"
            )
        if not np.all(np.isfinite(q)):
            raise InvalidRequest("q_ctrl_kvar contains non-finite values")
        dead = (self._mask == 0) & (q != 0)
        if dead.any():
            raise InvalidRequest(
                f"q_ctrl_kvar proposes injection on {int(dead.sum())} dead "
                f"node-phase(s) (phase does not exist there)"
            )
        return {"q": q}

    def assemble(self, group: List[Ticket], bucket: int):
        return _pad_rows(np.stack([t.prepared["q"] for t in group]), bucket)

    def solve(self, batch):
        # Dispatch only; the batcher syncs at its measurement boundary.
        if self._mesh_lanes and batch.shape[0] % self._mesh_lanes == 0:
            import jax

            return self._batched_mesh(jax.numpy.asarray(batch))
        return self._batched(batch)

    def example_request(self):
        return VVCRequest(case=self.case,
                          q_ctrl_kvar=np.zeros((self.nb, 3)))

    def scatter(self, group: List[Ticket], out, info: BatchInfo) -> None:
        loss, vmag, conv, residual = out
        loss = np.asarray(loss)
        vmag = np.asarray(vmag)
        conv = np.asarray(conv)
        residual = np.asarray(residual)
        # Vectorize the band report over the batch (the per-lane Python
        # loop below must stay cheap — it runs on the dispatch thread).
        vm_live = vmag[:, self._live]  # [b, n_live]
        v_min = vm_live.min(axis=1)
        v_max = vm_live.max(axis=1)
        viols = np.sum(
            (vm_live < V_BAND[0]) | (vm_live > V_BAND[1]), axis=1
        )
        for i, t in enumerate(group):
            resp = VVCResponse(
                workload="vvc",
                case=self.case,
                converged=bool(conv[i]),
                residual=float(residual[i]),
                loss_kw=float(loss[i]),
                loss_base_kw=self.loss_base_kw,
                loss_delta_kw=float(loss[i]) - self.loss_base_kw,
                v_min_pu=float(v_min[i]),
                v_max_pu=float(v_max[i]),
                band_violations=int(viols[i]),
                batch=info,
            )
            if _prov.PROVENANCE.enabled:
                _prov.PROVENANCE.stamp(
                    resp, workload="vvc", case=self.case, tier=info.tier,
                    span=t.span, residual=float(residual[i]), info=info,
                )
            t.future.set_result(resp)


class TopoEngine(_Engine):
    """The switching-screen workload: one request = one full variant
    sweep (enumerate → radiality check → SMW screen → on-device top-k →
    AC verify), dispatched as a single lane through the micro-batcher.

    The heavy artifacts ride the serving cache when one is configured:
    ``attach_cache_lu`` (called by :meth:`Service.engine`) hands this
    engine the case's already-factorized B′ LU pair, so attaching the
    topology workload to a served case pays zero extra O(n³) work.
    Variant counts are shape-bucketed (powers of two) so the compile
    count stays bounded like every other engine's.
    """

    workload = "topo"

    def __init__(self, case: str, mesh=None, backend: str = "auto",
                 precision: str = "auto", max_rank: int = 2,
                 max_variants: int = 20000, top_k: int = 8):
        super().__init__(case)
        from freedm_tpu.pf.topo import MAX_TOPO_RANK

        sys_ = _resolve_bus_case(case)
        self._sys = sys_
        self.n_branch = sys_.n_branch
        self.max_rank = min(int(max_rank), MAX_TOPO_RANK)
        self.max_variants = int(max_variants)
        self.top_k = max(int(top_k), 1)
        self._mesh = mesh
        self._precision = precision
        self._lu = None  # serving-cache B′ LU pair (attach_cache_lu)
        self._built = False
        self._build_lock = threading.Lock()
        # Variant-lane shape buckets: powers of two up to the variant
        # cap — one compiled screen program per bucket, not per count.
        b, buckets = 1, []
        while b < self.max_variants:
            buckets.append(b)
            b *= 2
        buckets.append(self.max_variants)
        self._vbuckets = tuple(sorted(set(buckets)))

    def attach_cache_lu(self, lu) -> None:
        """Adopt a cached B′ ``lu_factor`` pair (must be called before
        the first solve; the lazily-built screen factorizes its own
        otherwise)."""
        if not self._built:
            self._lu = lu

    def _ensure_built(self) -> None:
        """Build the screen/radiality/verify programs once (submitter
        thread, like other engines' __init__ compiles — under a lock so
        a first-touch herd builds one set)."""
        if self._built:
            return
        with self._build_lock:
            if self._built:
                return
            from freedm_tpu.pf import topo as tp

            self._screen = tp.make_topo_screen(
                self._sys, r_max=self.max_rank, lu=self._lu,
                mesh=self._mesh,
            )
            self._rad = tp.make_radiality_check(self._sys, self.max_rank)
            self._verify = tp.make_ac_verifier(
                self._sys, k=self.top_k, precision=self._precision,
            )
            self._built = True

    def example_request(self):
        return TopoRequest(case=self.case, switches=[0], max_rank=1,
                           top_k=1)

    def validate(self, req: TopoRequest):
        from freedm_tpu.pf import topo as tp

        # Build the compiled programs NOW, on the submitter's thread —
        # before the ticket deadline starts — so a first-touch request
        # pays the compile wall like every other engine's first touch
        # (engine construction), not against its own timeout on the
        # executor lane.
        self._ensure_built()
        # Field/vocabulary validation is ONE implementation shared with
        # the async path (pf/topo.validate_sweep_spec, the same checker
        # jobs.parse_topo_job_request uses) — the sync endpoint and the
        # sweep job cannot drift on what a legal spec is.  The engine
        # then layers its own serving caps (--topo-* config) on top.
        int_fields = {"max_rank": req.max_rank, "top_k": req.top_k,
                      "samples": req.samples, "seed": req.seed}
        for name, v in int_fields.items():
            if isinstance(v, bool) or not isinstance(v, (int, np.integer)):
                raise InvalidRequest(f"{name!r} must be an integer")
        if isinstance(req.flow_limit, bool) or not isinstance(
            req.flow_limit, (int, float)
        ) or not math.isfinite(req.flow_limit):
            raise InvalidRequest("'flow_limit' must be a finite number")
        if req.switches is not None and (
            not isinstance(req.switches, (list, tuple))
            or not req.switches
            or any(isinstance(k, bool)
                   or not isinstance(k, (int, np.integer))
                   for k in req.switches)
        ):
            # Same strictness as the async parser: a JSON bool/string
            # in the list is a typo, never a branch index.
            raise InvalidRequest(
                "'switches' must be a non-empty list of integer branch "
                "indices (or omitted for the full branch set)"
            )
        try:
            spec = tp.TopoSweepSpec(
                case=self.case,
                switches=(None if req.switches is None
                          else tuple(int(k) for k in req.switches)),
                max_rank=int(req.max_rank), mode=req.mode,
                objective=req.objective,
                flow_limit=float(req.flow_limit), top_k=int(req.top_k),
                search=req.search, samples=int(req.samples),
                seed=int(req.seed),
            )
            tp.validate_sweep_spec(spec, self.n_branch)
        except (TypeError, ValueError) as e:
            raise InvalidRequest(str(e)) from None
        if req.max_rank > self.max_rank:
            raise InvalidRequest(
                f"max_rank must be <= {self.max_rank} "
                f"(--topo-max-rank), got {req.max_rank}"
            )
        if req.top_k > self.top_k:
            raise InvalidRequest(
                f"top_k must be <= {self.top_k} (--topo-top-k), "
                f"got {req.top_k}"
            )
        if spec.search == "neighborhood" and spec.samples > self.max_variants:
            raise InvalidRequest(
                f"neighborhood search needs samples in "
                f"[1, {self.max_variants}], got {spec.samples}"
            )
        if spec.search == "exhaustive":
            n_switch = (self.n_branch if spec.switches is None
                        else len(spec.switches))
            count = tp.count_exhaustive(n_switch, spec.max_rank)
            if count > self.max_variants:
                raise InvalidRequest(
                    f"exhaustive enumeration is {count} variants, over "
                    f"the {self.max_variants} cap (--topo-max-variants); "
                    f"lower max_rank, shrink switches, or use "
                    f"search='neighborhood'"
                )
        variants = tp.sweep_variants(spec, self.n_branch)
        if variants.shape[0] == 0:
            raise InvalidRequest("the request produces zero variants")
        # Pad the rank axis to the engine's static r_max: one compiled
        # program serves every requested rank.
        if variants.shape[1] < self.max_rank:
            variants = np.concatenate([
                variants,
                np.full((variants.shape[0],
                         self.max_rank - variants.shape[1]), -1, np.int32),
            ], axis=1)
        return {
            "variants": variants,
            "mode": req.mode,
            "objective": req.objective,
            "flow_limit": float(req.flow_limit),
            "top_k": int(req.top_k),
        }

    def assemble(self, group: List[Ticket], bucket: int):
        # One request = one sweep; the group shares a dispatch slot but
        # each sweep is its own compiled-program chain (no padding).
        return [t.prepared for t in group]

    def solve(self, batch):
        # Dispatch-only chain per request (screen → top-k select → AC
        # verify, all device-resident): the batcher performs the one
        # deferred block_until_ready at its measurement boundary.
        self._ensure_built()
        return [self._solve_one(prep) for prep in batch]

    def _solve_one(self, prep):
        import jax
        import jax.numpy as jnp

        from freedm_tpu.pf import topo as tp

        variants = prep["variants"]
        v_real = int(variants.shape[0])
        bucket = next(b for b in self._vbuckets if b >= v_real)
        if v_real < bucket:
            variants = np.concatenate([
                variants,
                np.repeat(variants[-1:], bucket - v_real, axis=0),
            ])
        slj = jnp.asarray(variants)
        valid = jnp.asarray(np.arange(bucket) < v_real)
        # The shared per-chunk ladder (pf/topo.screen_chunk): the sync
        # endpoint, the async sweep, and the bench all compose masking/
        # objective/exclusion accounting through this one helper.
        verdict = tp.screen_chunk(
            self._screen, self._rad, slj, valid, prep["mode"],
            prep["objective"], prep["flow_limit"],
        )
        obj = verdict.objective
        # top_k cannot exceed the lane count (a 2-variant request under
        # an 8-deep shortlist cap is legal); the shortlist arrays pad
        # back to the verifier's static K with infeasible rows.
        k_eff = min(self.top_k, int(obj.shape[0]))
        neg, idx = jax.lax.top_k(-obj, k_eff)
        short_obj = -neg
        short_feas = jnp.isfinite(short_obj)
        # Infeasible shortlist slots collapse to the base topology —
        # an islanding/disconnected variant can never reach an AC lane.
        short_slots = jnp.where(short_feas[:, None], slj[idx], -1)
        short_worst = verdict.screen.worst_flow[idx]
        if k_eff < self.top_k:
            pad = self.top_k - k_eff
            short_obj = jnp.concatenate(
                [short_obj, jnp.full(pad, jnp.inf, short_obj.dtype)]
            )
            short_feas = jnp.concatenate(
                [short_feas, jnp.zeros(pad, bool)]
            )
            short_slots = jnp.concatenate([
                short_slots,
                jnp.full((pad, short_slots.shape[1]), -1,
                         short_slots.dtype),
            ])
            short_worst = jnp.concatenate(
                [short_worst, jnp.zeros(pad, short_worst.dtype)]
            )
        ac = self._verify(tp.status_from_slots(short_slots, self.n_branch))
        return {
            "n_variants": v_real,
            "short_obj": short_obj,
            "short_slots": short_slots,
            "short_feas": short_feas,
            "short_worst": short_worst,
            "ac_converged": ac.converged,
            "ac_mismatch": ac.mismatch,
            "ac_v": ac.v,
            # The exclusion accounting partitions the variant space
            # exactly: feasible + disconnected + nonradial + islanded
            # (the SMW backstop firing ALONE) == n_variants.
            "n_feasible": verdict.feasible,
            "n_islanded": verdict.islanded,
            "n_disconnected": verdict.disconnected,
            "n_nonradial": verdict.nonradial,
        }

    def scatter(self, group: List[Ticket], results,
                info: BatchInfo) -> None:
        for j, t in enumerate(group):
            r = results[j]
            # The one designed device->host pull per result field;
            # everything below is host numpy.
            obj = np.asarray(r["short_obj"])
            slots = np.asarray(r["short_slots"])
            feas = np.asarray(r["short_feas"])
            worst = np.asarray(r["short_worst"])
            conv = np.asarray(r["ac_converged"])
            mism = np.asarray(r["ac_mismatch"])
            v = np.asarray(r["ac_v"])
            nv = np.asarray(r["n_variants"])
            nf = np.asarray(r["n_feasible"])
            ni = np.asarray(r["n_islanded"])
            nd = np.asarray(r["n_disconnected"])
            nr = np.asarray(r["n_nonradial"])
            want = int(t.prepared["top_k"])
            shortlist = []
            for i in range(min(want, obj.shape[0])):
                if not feas[i]:
                    break  # trailing slots past the feasible count
                shortlist.append({
                    "open_branches": sorted(
                        int(s) for s in slots[i] if s >= 0
                    ),
                    "objective": float(obj[i]),
                    "worst_flow_pu": float(worst[i]),
                    "ac_converged": bool(conv[i]),
                    "ac_residual_pu": float(mism[i]),
                    "v_min_pu": float(v[i].min()),
                    "v_max_pu": float(v[i].max()),
                })
            n_variants = int(nv)
            obs.TOPO_VARIANTS.inc(n_variants)
            resp = TopoResponse(
                workload="topo",
                case=self.case,
                mode=t.prepared["mode"],
                objective=t.prepared["objective"],
                max_rank=int(t.request.max_rank),
                n_variants=n_variants,
                n_feasible=int(nf),
                n_islanded=int(ni),
                n_disconnected=int(nd),
                n_nonradial=int(nr),
                shortlist=shortlist,
                all_verified=bool(
                    all(e["ac_converged"] for e in shortlist)
                ) if shortlist else False,
                batch=info,
            )
            if _prov.PROVENANCE.enabled:
                worst_ac = max(
                    (e["ac_residual_pu"] for e in shortlist), default=None
                )
                _prov.PROVENANCE.stamp(
                    resp, workload="topo", case=self.case, tier=info.tier,
                    span=t.span, residual=worst_ac, info=info,
                )
            t.future.set_result(resp)


_ENGINE_TYPES = {
    "pf": PowerFlowEngine,
    "n1": N1Engine,
    "vvc": VVCEngine,
    "topo": TopoEngine,
}

_REQUEST_TYPES = {
    "pf": PowerFlowRequest,
    "n1": N1Request,
    "vvc": VVCRequest,
    "topo": TopoRequest,
}


def _response_from_solution(eng, request: PowerFlowRequest, sol,
                            info: BatchInfo) -> PowerFlowResponse:
    """Build a pf response from a cached/corrected solution record
    (``CachedSolution``-shaped: host numpy state + stamps) — the same
    fields the scatter path computes, honoring ``return_state``."""
    want_state = bool(request.return_state)
    return PowerFlowResponse(
        workload="pf",
        case=eng.case,
        scale=float(request.scale),
        converged=bool(sol.converged),
        iterations=int(sol.iterations),
        residual_pu=float(sol.mismatch),
        p_balance_pu=float(np.sum(sol.p)),
        q_balance_pu=float(np.sum(sol.q)),
        v_min_pu=float(np.min(sol.v)),
        v_max_pu=float(np.max(sol.v)),
        v=np.round(sol.v, 9).tolist() if want_state else None,
        theta=np.round(sol.theta, 9).tolist() if want_state else None,
        batch=info,
    )


def parse_request(workload: str, payload: dict):
    """Build the typed request record from a JSON payload, rejecting
    unknown workloads and unknown fields with typed errors."""
    cls = _REQUEST_TYPES.get(workload)
    if cls is None:
        raise InvalidRequest(
            f"unknown workload {workload!r} (have: {', '.join(WORKLOADS)})"
        )
    if not isinstance(payload, dict):
        raise InvalidRequest("request body must be a JSON object")
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(payload) - names
    if unknown:
        raise InvalidRequest(
            f"unknown field(s) {sorted(unknown)} for workload {workload!r}"
        )
    if "case" not in payload:
        raise InvalidRequest("missing required field 'case'")
    try:
        return cls(**payload)
    except TypeError as e:
        raise InvalidRequest(str(e)) from None


# ---------------------------------------------------------------------------
# Service facade
# ---------------------------------------------------------------------------


class SnapshotLedger:
    """Request-conservation ledger for consistent-cut snapshots
    (:mod:`freedm_tpu.core.snapshot`).

    Every transition happens under one leaf lock, and each submission
    is classified atomically with its ``offered`` bump, so a
    ``snapshot_state()`` read taken at ANY instant satisfies the
    invariants the cut auditor checks:

        offered  == admitted + shed + rejected
        admitted == ok + error + inflight   (inflight derived, >= 0)

    A torn scrape — two reads at different times stitched into one
    "state" — breaks the first equation as soon as any request was
    offered between the reads, which is exactly the negative proof
    ``torn_serve_doc`` builds.  Settlement is idempotent per ticket
    (``Ticket.ledger_state``), so the expire/error/abort paths may race
    without double-counting.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.offered = 0
        self.admitted = 0
        self.shed = 0
        self.rejected = 0
        self.ok = 0
        self.error = 0

    def admit(self, ticket: Ticket) -> None:
        with self._lock:
            if ticket.ledger_state is not None:
                return  # a racing settle already implied admission
            ticket.ledger_state = "inflight"
            self.offered += 1
            self.admitted += 1

    def shed_one(self) -> None:
        with self._lock:
            self.offered += 1
            self.shed += 1

    def reject(self) -> None:
        with self._lock:
            self.offered += 1
            self.rejected += 1

    def settle(self, ticket: Ticket, ok: bool) -> None:
        with self._lock:
            st = ticket.ledger_state
            if st in ("ok", "error"):
                return  # already settled (e.g. expire racing an error)
            if st is None:
                # Settled before submit() reached its admit() call (a
                # cache-tier hit completes inline): imply the admission
                # so the equations never see a settled-but-unadmitted
                # ticket.
                self.offered += 1
                self.admitted += 1
            if ok:
                ticket.ledger_state = "ok"
                self.ok += 1
            else:
                ticket.ledger_state = "error"
                self.error += 1

    def snapshot_state(self) -> dict:
        with self._lock:
            return {
                "offered": self.offered,
                "admitted": self.admitted,
                "shed": self.shed,
                "rejected": self.rejected,
                "ok": self.ok,
                "error": self.error,
                "inflight": self.admitted - self.ok - self.error,
            }


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two plus their 1.5x intermediates up to (and
    including) ``max_batch`` — the static shape set jit programs are
    compiled for.

    The intermediates (3, 6, 12, 24, 48, ...) halve the worst-case
    padding waste of the pure power-of-two table (from ~50% of a
    dispatch's lanes to ~33%); the extra compiles they cost are a
    startup concern only — ``--serve-prewarm`` pushes every bucket
    through XLA before traffic arrives (docs/serving.md).
    :func:`padding_waste_pct` reports the table's worst case and
    ``/stats`` carries both it and the measured padding fraction.
    """
    out = set()
    b = 1
    while b < max_batch:
        out.add(b)
        mid = b + b // 2  # the 1.5x intermediate (integer for b >= 2)
        if b >= 2 and mid < max_batch:
            out.add(mid)
        b *= 2
    out.add(int(max_batch))
    return tuple(sorted(out))


def padding_waste_pct(buckets: Tuple[int, ...]) -> float:
    """Worst-case padded-lane share of a bucket table: the maximum,
    over every real lane count up to the largest bucket, of
    ``(bucket - lanes) / bucket`` for the bucket that lane count lands
    in.  Pure powers of two sit just under 50% (lanes = 2^k + 1); the
    default table with 1.5x intermediates stays under 34%."""
    table = tuple(sorted(set(int(b) for b in buckets)))
    worst = 0.0
    for lanes in range(1, table[-1] + 1):
        bucket = next(b for b in table if b >= lanes)
        worst = max(worst, (bucket - lanes) / bucket)
    return round(100.0 * worst, 2)


class ServeConfig(NamedTuple):
    """Serving knobs (CLI: ``--serve-port`` and friends).

    ``max_batch`` bounds lanes per dispatch; ``max_wait_ms`` is the
    coalescing window the batcher holds the first request of a batch
    open for (adaptive: a lone request with an empty queue behind it
    skips the window); ``queue_depth`` is the admission bound in lanes
    (beyond it, requests shed with ``overloaded``); ``buckets``
    defaults to the powers of two up to ``max_batch``.
    """

    max_batch: int = 64
    max_wait_ms: float = 2.0
    queue_depth: int = 512
    default_timeout_s: float = 30.0
    pf_max_iter: int = 12
    n1_max_iter: int = 24
    vvc_pf_iters: int = 20
    buckets: Optional[Tuple[int, ...]] = None
    # Solver-lane mesh (CLI: --mesh-devices / --mesh-batch-axis): shard
    # each engine's batched lane axis over this many local devices via
    # shard_map (-1 = all, 0 = unsharded).  Buckets that do not divide
    # the device count dispatch on the single-device program instead —
    # responses are byte-identical either way (docs/scaling.md).
    mesh_devices: int = 0
    mesh_batch_axis: str = "batch"
    # Jacobian backend for the pf/N-1 engines (CLI: --pf-backend):
    # dense [2n,2n] LU, BCSR sparse (pf/sparse.py), or auto — sparse
    # at/above the documented bus-count crossover, which keeps the
    # small recognized cases on the measured-faster dense path while
    # client-named meshN scale tenants get the sparse one.
    pf_backend: str = "auto"
    # Inner-solve precision for the Krylov-based pf/N-1 backends (CLI:
    # --pf-precision): "f64" = full-precision inner GMRES, "mixed" =
    # f32 inner under the working-dtype acceptance oracle with
    # per-lane fallback (docs/solvers.md "Mixed precision"), "auto" =
    # mixed on tpu/gpu, f64 on cpu.  Dense-backend engines validate
    # and ignore it (no reduced-precision inner exists there).
    pf_precision: str = "auto"
    # Pipelined dispatch (CLI: --serve-pipeline-depth): assembled
    # batches buffered per workload's device-executor lane, so batch
    # N+1 coalesces/pads while batch N solves and pf/n1/vvc no longer
    # serialize behind each other.  0 = legacy single-thread dispatch
    # (the equivalence oracle; docs/serving.md).  1 = classic double
    # buffering (one batch executing + one buffered), the default.
    pipeline_depth: int = 1
    # Engines to compile at startup (CLI: --serve-prewarm, repeatable):
    # "workload/case" entries; every bucket of each named engine is
    # compiled before the first request, tagged in /stats
    # recompiles_by_bucket and excluded from serve_recompiles_total.
    prewarm: Tuple[str, ...] = ()
    # Incremental serving tier (serve/cache.py; CLI: --serve-cache-mb /
    # --serve-cache-ttl-s / --serve-delta-max-rank): byte budget for the
    # per-(case, topology, backend) base-case cache — solutions PLUS the
    # reusable artifacts (FDLF LU pair, BCSR pattern) — 0 disables the
    # tier entirely; solution TTL; and the largest changed-bus count the
    # SMW delta tier will attempt before falling to warm-start seeding.
    cache_mb: float = 64.0
    cache_ttl_s: float = 600.0
    delta_max_rank: int = 16
    # Delta-tier inline verify override (None = the engine tolerance).
    # Exists for the chaos negative proof (tools/chaos.py
    # --shadow-negative): LOOSENING it deliberately bypasses the inline
    # residual gate so the shadow verifier (core/provenance.py) must be
    # the layer that catches a corrupted answer.  Never loosen it in
    # production service of real queries.
    cache_verify_tol: Optional[float] = None
    # Topology sweeps (serve workload "topo" + the async sweep jobs;
    # CLI: --topo-max-rank / --topo-max-variants / --topo-top-k):
    # simultaneous-flip cap per variant, per-request variant ceiling
    # (the sync endpoint's admission bound — async sweeps chunk past
    # it), and the AC-verified shortlist size cap (also the verifier's
    # compiled lane count).
    topo_max_rank: int = 2
    topo_max_variants: int = 20000
    topo_top_k: int = 8

    def bucket_table(self) -> Tuple[int, ...]:
        bs = self.buckets if self.buckets else default_buckets(self.max_batch)
        bs = tuple(sorted(set(int(b) for b in bs)))
        if bs[-1] < self.max_batch:
            bs = bs + (int(self.max_batch),)
        return bs


class Service:
    """The multi-tenant query service: validate → admit → micro-batch →
    solve → scatter.

    ``submit`` returns a :class:`concurrent.futures.Future` resolving to
    a typed response (or raising a :class:`ServeError`); ``request`` is
    the blocking convenience.  Engines are built lazily per
    (workload, case) and cached for the service's lifetime.
    """

    #: Distinct (workload, case) engines one service will build; each is
    #: a permanent cache entry with its own compiled programs.
    MAX_ENGINES = 32

    def __init__(self, config: ServeConfig = ServeConfig(), start: bool = True):
        from freedm_tpu.pf.krylov import PF_PRECISIONS
        from freedm_tpu.pf.sparse import BACKENDS
        from freedm_tpu.serve.batcher import MicroBatcher

        if config.pf_backend not in BACKENDS:
            raise ValueError(
                f"unknown pf_backend {config.pf_backend!r} "
                f"(have: {', '.join(BACKENDS)})"
            )
        if config.pf_precision not in PF_PRECISIONS:
            raise ValueError(
                f"unknown pf_precision {config.pf_precision!r} "
                f"(have: {', '.join(PF_PRECISIONS)})"
            )
        if config.pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be >= 0 (0 = serialized dispatch), "
                f"got {config.pipeline_depth}"
            )
        self.config = config
        # The solver-lane mesh every engine shards over (None =
        # unsharded); built once so all engines share one device set.
        self.mesh = None
        if config.mesh_devices not in (0, 1):
            from freedm_tpu.parallel.mesh import solver_mesh

            self.mesh = solver_mesh(
                config.mesh_devices, config.mesh_batch_axis
            )
        # The incremental serving tier (exact/delta/warm answers off
        # cached base-case solutions + factorizations); None = disabled.
        self.cache = None
        if config.cache_mb and config.cache_mb > 0:
            from freedm_tpu.serve.cache import ServeCache

            self.cache = ServeCache(
                max_bytes=int(config.cache_mb * 1024 * 1024),
                ttl_s=config.cache_ttl_s,
                delta_max_rank=config.delta_max_rank,
                precision=config.pf_precision,
                verify_tol=config.cache_verify_tol,
            )
        # Request-conservation ledger the snapshot auditor checks; all
        # completion paths funnel through _complete_ok/_complete_error/
        # _expire, so settle() there covers batched, cached, follower,
        # expired, and drained tickets alike.
        self.ledger = SnapshotLedger()
        self._engines: Dict[Tuple[str, str], _Engine] = {}
        # Global lock guards the maps only; SLOW engine construction
        # (XLA compiles in VVCEngine/N1Engine __init__) happens under a
        # per-key build lock so a first-touch tenant cannot stall the
        # batcher's engine lookups for everyone else.
        self._engines_lock = threading.Lock()
        self._build_locks: Dict[Tuple[str, str], threading.Lock] = {}
        # Pre-resolved "ok" counter children: the per-request completion
        # path skips the labels() lookup.
        self._ok_counters = {
            w: obs.SERVE_REQUESTS.labels(w, "ok") for w in WORKLOADS
        }
        self.queue = AdmissionQueue(
            max_depth=config.queue_depth,
            depth_gauge=obs.SERVE_QUEUE_DEPTH,
            on_expired=self._expire,
        )
        self.batcher = MicroBatcher(self, config)
        if start:
            self.batcher.start()
        if config.prewarm:
            # Synchronous by design: startup pays the compile storm so
            # the first request's p99 is a solve, not an XLA compile.
            try:
                self.prewarm(config.prewarm)
            except BaseException:
                # The constructor won't return, so nobody could call
                # stop() — don't leak the assembly/executor threads.
                self.batcher.stop()
                raise

    # -- engine cache --------------------------------------------------------
    def engine(self, workload: str, case: str) -> _Engine:
        if workload not in _ENGINE_TYPES:
            raise InvalidRequest(
                f"unknown workload {workload!r} (have: {', '.join(WORKLOADS)})"
            )
        if not isinstance(case, str) or not case:
            raise InvalidRequest("'case' must be a non-empty string")
        key = (workload, case)
        with self._engines_lock:
            eng = self._engines.get(key)
            if eng is not None:
                return eng
            if len(self._engines) >= self.MAX_ENGINES:
                # Engines (and their jit programs) are never evicted: a
                # client cycling case names must not grow the cache
                # without bound.
                raise InvalidRequest(
                    f"engine cache full ({self.MAX_ENGINES} cases); "
                    f"reuse an already-served case"
                )
            build_lock = self._build_locks.get(key)
            if build_lock is None:
                build_lock = self._build_locks[key] = threading.Lock()
        with build_lock:
            with self._engines_lock:
                eng = self._engines.get(key)
            if eng is not None:  # another submitter built it meanwhile
                return eng
            cfg = self.config
            kwargs = {
                "pf": {"max_iter": cfg.pf_max_iter},
                "n1": {"max_iter": cfg.n1_max_iter},
                "vvc": {"pf_iters": cfg.vvc_pf_iters},
                "topo": {"max_rank": cfg.topo_max_rank,
                         "max_variants": cfg.topo_max_variants,
                         "top_k": cfg.topo_top_k},
            }[workload]
            eng = _ENGINE_TYPES[workload](
                case, mesh=self.mesh, backend=cfg.pf_backend,
                precision=cfg.pf_precision, **kwargs
            )
            if workload == "topo" and self.cache is not None:
                # The topology screen rides the serving cache's B′ LU:
                # a case already served by pf answers switching sweeps
                # with ZERO additional O(n³) factorization work (the
                # make_topo_screen(lu=...) seam, same as the DC screen).
                # Deliberate trade-off: entry() BUILDS the full artifact
                # set (B″ + pattern included) even though the screen
                # only uses bp — a topo-first tenant pre-pays the entry
                # a later pf engine for the same case reuses; a case
                # whose artifacts exceed the byte budget returns None
                # and the engine self-factorizes bp below.
                from freedm_tpu.pf.sparse import resolve_backend
                from freedm_tpu.serve.cache import topology_digest

                entry = self.cache.entry(
                    case, eng._sys,
                    resolve_backend(cfg.pf_backend, eng._sys.n_bus),
                    topo=topology_digest(eng._sys),
                )
                if entry is not None:
                    eng.attach_cache_lu(entry.precond.bp)
            if workload == "pf" and self.cache is not None:
                from freedm_tpu.pf.sparse import resolve_backend

                # Resolve the backend ONCE (it is part of the cache
                # key: dense and sparse solutions agree only to solver
                # tolerance) and factorize the entry's artifacts here,
                # inside the engine build lock — first-touch cost, off
                # the steady-state submit path.
                from freedm_tpu.serve.cache import topology_digest

                eng.cache_backend = resolve_backend(
                    cfg.pf_backend, eng.n_bus
                )
                eng.cache_topo = topology_digest(eng._sys)
                eng.publish = self._publish_pf
                self.cache.entry(case, eng._sys, eng.cache_backend,
                                 topo=eng.cache_topo)
            with self._engines_lock:
                self._engines[key] = eng
            return eng

    # -- prewarm (startup compile of configured engines) ---------------------
    def prewarm(self, specs: Sequence[str]) -> List[str]:
        """Compile every bucket of each ``"workload/case"`` engine named
        in ``specs`` before traffic arrives (CLI: ``--serve-prewarm``).

        Each compiled shape is recorded via the batcher's prewarm table
        (tagged in ``/stats`` ``recompiles_by_bucket`` with count 0) and
        never counts on ``serve_recompiles_total`` — the recompile
        counter stays a *steady-state surprise* signal.  Returns the
        ``"workload/case:bucket"`` keys compiled."""
        import jax

        done: List[str] = []
        for spec in specs:
            workload, sep, case = str(spec).partition("/")
            if not sep or not case:
                raise InvalidRequest(
                    f"prewarm spec must be 'workload/case', got {spec!r}"
                )
            eng = self.engine(workload, case)
            req = eng.example_request()
            prepared = eng.validate(req)
            lanes = eng.lanes(prepared)
            for bucket in self.config.bucket_table():
                if bucket in eng.compiled_buckets or lanes > bucket:
                    continue
                t = Ticket(eng.key, req, prepared, lanes, None)
                out = eng.solve(eng.assemble([t], bucket))
                jax.block_until_ready(out)
                self.batcher.note_prewarmed(eng, bucket)
                done.append(f"{workload}/{case}:{bucket}")
            if workload == "pf" and self.cache is not None \
                    and eng.cache_backend is not None:
                # Compile the incremental tier's delta-correction
                # program too, so the first delta hit pays a solve,
                # not an XLA compile.
                entry = self.cache.entry(case, eng._sys, eng.cache_backend,
                                         topo=eng.cache_topo)
                if entry is not None:
                    self.cache.prewarm_entry(entry)
        return done

    # -- submission ----------------------------------------------------------
    def submit(self, workload: str, request, parent_ctx=None):
        """Validate and admit one request; returns its Future.

        ``request`` may be a typed record or a JSON-shaped dict.  Raises
        :class:`InvalidRequest` / :class:`Overloaded` synchronously —
        an unservable request never occupies queue depth.
        ``parent_ctx`` is an optional wire-propagated span context
        (``{"trace_id", "span_id"}`` — what serve/http.py builds from
        the router's ``X-Trace-Id``/``X-Span-Id`` headers), so the
        replica's ``serve.request`` span parents under the router's
        ``serve.route`` span in one cross-process tree.
        """
        # Clamp the metric label to the known vocabulary: a typo'd or
        # hostile workload string must not mint unbounded label series.
        wl = workload if workload in WORKLOADS else "unknown"
        try:
            if isinstance(request, dict):
                request = parse_request(workload, request)
            eng = self.engine(workload, request.case)
            prepared = eng.validate(request)
            lanes = eng.lanes(prepared)
            if lanes > self.config.max_batch:
                raise InvalidRequest(
                    f"request needs {lanes} lanes but max_batch is "
                    f"{self.config.max_batch}; split it"
                )
            timeout = float(getattr(request, "timeout_s", 0) or 0)
        except InvalidRequest:
            obs.SERVE_REQUESTS.labels(wl, "invalid").inc()
            self.ledger.reject()
            raise
        except (TypeError, ValueError) as e:
            # Wrong-typed field VALUES (e.g. scale="1.1", outages=5) come
            # out of numpy/float coercion as raw TypeError/ValueError —
            # still the client's fault, still a typed 400.
            obs.SERVE_REQUESTS.labels(wl, "invalid").inc()
            self.ledger.reject()
            raise InvalidRequest(f"malformed request field: {e}") from None
        if timeout <= 0:
            timeout = self.config.default_timeout_s
        span = tracing.TRACER.start(
            "serve.request", kind="serve", parent_ctx=parent_ctx,
            tags={"workload": workload, "case": request.case, "lanes": lanes},
        )
        ticket = Ticket(
            key=eng.key, request=request, prepared=prepared, lanes=lanes,
            deadline=_time.monotonic() + timeout, span=span,
        )
        # Incremental tier (pf + cache enabled): exact/delta hits return
        # a completed future without occupying queue depth or device
        # time; single-flight followers return a pending future parked
        # on the leader's solve; warm hits seed the prepared arrays and
        # fall through to admission like any other full solve.  A
        # request carrying its OWN v0/theta0 bypasses the cache in both
        # directions — the client is steering the solver (possibly
        # toward a different solution branch), so neither may the cache
        # answer for it nor may its steered solution be served to
        # flat-start clients later.
        if self.cache is not None and workload == "pf" \
                and eng.cache_backend is not None \
                and request.v0 is None and request.theta0 is None:
            try:
                fut = self._cache_tier(eng, ticket)
            except Exception as e:  # noqa: BLE001 — the tier is an
                # optimization: a failing delta compile/dispatch (or any
                # cache-side surprise) must never turn an answerable
                # request into an error — fall through to the full path.
                ticket.span.tag(cache_error=repr(e))
                fut = None
            if fut is not None:
                # Cache-tier answer or joined flight: the ticket was
                # (or will be) settled through _complete_ok/_error —
                # admit() is a no-op if the settle already implied it.
                self.ledger.admit(ticket)
                return fut
        try:
            self.queue.put(ticket)
        except Overloaded as e:
            obs.SERVE_SHED.inc()
            obs.SERVE_REQUESTS.labels(workload, "overloaded").inc()
            self.ledger.shed_one()
            span.tag(outcome="overloaded")
            span.end()
            self._abort_flight(ticket, e)
            raise
        except ShuttingDown as e:
            obs.SERVE_REQUESTS.labels(workload, "shutdown").inc()
            self.ledger.reject()
            span.tag(outcome="shutdown")
            span.end()
            self._abort_flight(ticket, e)
            raise
        self.ledger.admit(ticket)
        return ticket.future

    def request(self, workload: str, request,
                timeout_s: Optional[float] = None, parent_ctx=None):
        """Blocking submit: the typed response, or a raised ServeError.

        The wait honors the REQUEST's own ``timeout_s`` (plus a margin
        for the in-flight solve, which is never cancelled), so a client
        asking for 300 s to cover a first-bucket compile actually gets
        it; an explicit ``timeout_s`` argument REPLACES the record's
        value (so the ticket's queue deadline moves with it too); a wait
        that still runs out surfaces as the typed
        :class:`DeadlineExceeded`, not a raw future timeout.
        """
        if isinstance(request, dict):
            try:
                request = parse_request(workload, request)
            except InvalidRequest:
                wl = workload if workload in WORKLOADS else "unknown"
                obs.SERVE_REQUESTS.labels(wl, "invalid").inc()
                self.ledger.reject()
                raise
        if timeout_s is not None and hasattr(request, "timeout_s"):
            request = dataclasses.replace(request, timeout_s=float(timeout_s))
        fut = self.submit(workload, request, parent_ctx=parent_ctx)
        t = float(getattr(request, "timeout_s", 0) or 0)
        if t <= 0:
            t = self.config.default_timeout_s
        wait = t + 10.0
        try:
            return fut.result(timeout=wait)
        except _FuturesTimeout:
            raise DeadlineExceeded(
                f"no result within {wait:.0f}s (the batch may still "
                f"be solving; its result is discarded)"
            ) from None

    # -- incremental serving tier (serve/cache.py) ---------------------------
    def _cache_tier(self, eng, ticket: Ticket):
        """Run one validated pf ticket through the tier ladder.

        Returns the ticket's future when the cache answered (exact or
        verified delta) or parked it on an in-flight leader (single
        flight); returns ``None`` when the ticket must take the full
        path — possibly warm-seeded, and marked as its digest's flight
        leader so an identical herd coalesces onto this one solve.
        """
        from freedm_tpu.serve.cache import injection_digest

        cache = self.cache
        entry = cache.entry(eng.case, eng._sys, eng.cache_backend,
                            topo=eng.cache_topo)
        if entry is None:  # case over the byte budget: stays uncached
            return None
        t0 = _time.monotonic()
        prepared = ticket.prepared
        p, q = prepared["p"], prepared["q"]
        digest = injection_digest(p, q)
        tier, near = cache.lookup(entry, digest, p, q)
        if profiling.PROFILER.enabled:
            profiling.PROFILER.record_host(
                "serve.cache.lookup", _time.monotonic() - t0
            )
        if tier == "exact":
            cache.record("exact")
            return self._respond_cached(eng, ticket, near, "exact", 0.0)
        if tier == "delta":
            t1 = _time.monotonic()
            ans = cache.delta_answer(entry, near, p, q)
            if ans is not None:
                sol = cache.insert(
                    entry, digest, p, q, ans["v"], ans["theta"], ans["p"],
                    ans["q"], ans["iterations"], ans["mismatch"], True,
                )
                if sol is None:  # entry died mid-answer: serve transient
                    from freedm_tpu.serve.cache import CachedSolution

                    sol = CachedSolution(
                        digest, p, q, ans["v"], ans["theta"], ans["p"],
                        ans["q"], ans["iterations"], ans["mismatch"], True,
                    )
                cache.record("delta")
                return self._respond_cached(
                    eng, ticket, sol, "delta",
                    round((_time.monotonic() - t1) * 1e3, 3),
                )
            tier = "warm"  # residual fall-through: never served unverified
        # Full-solve path: claim the digest's flight (or join one).
        outcome, late = cache.flight_claim(entry, digest, ticket)
        if outcome == "exact":  # a leader finished while we classified
            cache.record("exact")
            return self._respond_cached(eng, ticket, late, "exact", 0.0)
        if outcome == "joined":
            cache.record("miss")
            ticket.span.tag(cache_tier="flight")
            return ticket.future
        ticket.cache_flight = (entry.key, digest)
        if tier == "warm" and near is not None:
            # Seed the full solve from the nearest cached solution (the
            # v0/theta0 path PR 4 measured at 37% fewer iterations).
            # Client-supplied seeds never reach here — submit bypasses
            # the cache for steered requests.
            prepared["v0"] = near.v
            prepared["th0"] = near.theta
            # Receipt seam: the scatter path reads this to classify the
            # dispatched solve as warm-tier and name its seed solution.
            prepared["warm_src"] = near.digest
            cache.record("warm")
            ticket.span.tag(cache_tier="warm")
        else:
            cache.record("miss")
            ticket.span.tag(cache_tier="miss")
        return None

    def _respond_cached(self, eng, ticket: Ticket, sol, tier: str,
                        solve_ms: float):
        """Complete one ticket from a cached/corrected solution — no
        admission, no batch, no device (exact) or one correction solve
        (delta)."""
        info = BatchInfo(lanes=1, bucket=0, queue_ms=0.0,
                         solve_ms=solve_ms, tier=tier)
        resp = _response_from_solution(eng, ticket.request, sol, info)
        if _prov.PROVENANCE.enabled:
            _prov.PROVENANCE.stamp(
                resp, workload="pf", case=eng.case, tier=tier,
                span=ticket.span, backend=eng.pf_backend,
                precision=eng.pf_precision,
                iterations=int(sol.iterations),
                residual=float(sol.mismatch),
                cache_age_s=_time.monotonic() - sol.stamp,
                info=info,
                solution=(eng._sys, sol.p_inj, sol.q_inj, sol.v,
                          sol.theta),
            )
        ticket.span.tag(cache_tier=tier)
        ticket.future.set_result(resp)
        self._complete_ok(ticket, info)
        return ticket.future

    def _publish_pf(self, eng, group: List[Ticket], v, theta, p, q, its,
                    conv, mism, info: BatchInfo) -> None:
        """Scatter-side cache population + single-flight settlement.

        Runs on the executor lane with HOST arrays only (the scatter
        already pulled them): converged lanes are inserted as cached
        solutions; followers parked on a lane's flight are answered
        from that lane's numbers with an ``exact``-tier receipt.
        """
        cache = self.cache
        if cache is None:
            return
        from freedm_tpu.serve.cache import CachedSolution, injection_digest

        # peek, never build: an invalidated/LRU-evicted entry means the
        # in-flight inserts land nowhere (the documented contract), and
        # an O(n³) artifact re-factorization must never run on the
        # executor lane.
        entry = cache.peek_entry(eng.case, eng.cache_topo,
                                 eng.cache_backend)
        for i, t in enumerate(group):
            fl = t.cache_flight
            if fl is None and (t.request.v0 is not None
                               or t.request.theta0 is not None):
                # Client-steered solve: its solution may sit on a
                # different branch than a flat start would find — never
                # publish it under an injections-only digest.
                continue
            digest = fl[1] if fl is not None else None
            sol = None
            if entry is not None and bool(conv[i]):
                if digest is None:
                    digest = injection_digest(t.prepared["p"],
                                              t.prepared["q"])
                sol = cache.insert(
                    entry, digest, t.prepared["p"], t.prepared["q"],
                    v[i], theta[i], p[i], q[i], int(its[i]),
                    float(mism[i]), True,
                )
            if fl is None:
                continue
            # Settle BEFORE clearing the ticket's flight mark: an
            # exception anywhere above leaves the mark in place, so the
            # batcher's error path still aborts the flight and no
            # follower can hang on a leaked _Flight.
            _fentry, followers = cache.settle_flight(fl)
            t.cache_flight = None
            if not followers:
                continue
            if sol is None:  # dead entry / non-converged: transient
                sol = CachedSolution(
                    fl[1], t.prepared["p"], t.prepared["q"], v[i],
                    theta[i], p[i], q[i], int(its[i]), float(mism[i]),
                    bool(conv[i]),
                )
            # Followers are answered from the just-populated solution —
            # semantically an exact hit, so the receipt matches one
            # (bucket 0: no batch of *theirs* existed).
            finfo = BatchInfo(lanes=1, bucket=0, queue_ms=0.0,
                              solve_ms=0.0, tier="exact")
            for f in followers:
                try:
                    fresp = _response_from_solution(eng, f.request, sol,
                                                    finfo)
                    if _prov.PROVENANCE.enabled:
                        _prov.PROVENANCE.stamp(
                            fresp, workload="pf", case=eng.case,
                            tier="exact", span=f.span,
                            backend=eng.pf_backend,
                            precision=eng.pf_precision,
                            iterations=int(sol.iterations),
                            residual=float(sol.mismatch),
                            cache_age_s=_time.monotonic() - sol.stamp,
                            info=finfo,
                            solution=(eng._sys, sol.p_inj, sol.q_inj,
                                      sol.v, sol.theta),
                        )
                    f.future.set_result(fresp)
                    self._complete_ok(f, finfo)
                except Exception as e:  # noqa: BLE001 — never hang the rest
                    self._complete_error(f, e)

    def _abort_flight(self, ticket: Ticket, err: BaseException) -> None:
        """A flight leader failed/expired/shed before populating the
        cache: fail its followers with the same typed error."""
        fl = getattr(ticket, "cache_flight", None)
        if fl is None or self.cache is None:
            return
        ticket.cache_flight = None
        for f in self.cache.abort_flight(fl):
            self._complete_error(f, err)

    # -- completion accounting (called by the batcher / queue) ---------------
    def _expire(self, ticket: Ticket) -> None:
        self.ledger.settle(ticket, ok=False)
        obs.SERVE_REQUESTS.labels(ticket.key[0], "deadline").inc()
        obs.SERVE_REQUEST_LATENCY.observe(
            max(_time.monotonic() - ticket.enqueued_at, 0.0)
        )
        ticket.span.tag(outcome="deadline")
        ticket.span.end()
        err = DeadlineExceeded("deadline passed while queued")
        ticket.future.set_exception(err)
        self._abort_flight(ticket, err)

    def _complete_ok(self, ticket: Ticket, info: BatchInfo) -> None:
        self.ledger.settle(ticket, ok=True)
        self._ok_counters[ticket.key[0]].inc()
        # The exemplar links a latency bucket straight to its trace
        # (NOOP.trace_id is None = no exemplar recorded).
        obs.SERVE_REQUEST_LATENCY.observe(
            max(_time.monotonic() - ticket.enqueued_at, 0.0),
            exemplar=ticket.span.trace_id,
        )
        span = ticket.span
        if span is not tracing.NOOP:
            span.tag(outcome="ok", bucket=info.bucket,
                     batch_lanes=info.lanes)
            span.end()

    def _complete_error(self, ticket: Ticket, err: BaseException) -> None:
        self.ledger.settle(ticket, ok=False)
        outcome = err.code if isinstance(err, ServeError) else "error"
        obs.SERVE_REQUESTS.labels(ticket.key[0], outcome).inc()
        obs.SERVE_REQUEST_LATENCY.observe(
            max(_time.monotonic() - ticket.enqueued_at, 0.0)
        )
        ticket.span.tag(outcome=outcome)
        ticket.span.end()
        if not ticket.future.done():
            ticket.future.set_exception(err)
        self._abort_flight(ticket, err)

    # -- introspection / lifecycle -------------------------------------------
    def stats(self) -> dict:
        snap = obs.REGISTRY.snapshot()

        def metric(name):
            return snap.get(name, {}).get("values", {})

        return {
            "queue_depth_lanes": self.queue.depth_lanes,
            "engines": sorted(
                f"{w}/{c}" for (w, c) in self._engines
            ),
            "buckets": list(self.config.bucket_table()),
            # Padding honesty: the table's analytic worst-case padded-
            # lane share plus the measured share of pad lanes actually
            # dispatched (the 1.5x intermediate buckets exist to push
            # both down — docs/serving.md).
            "padding": {
                "worst_case_pad_pct": padding_waste_pct(
                    self.config.bucket_table()
                ),
                "dispatched_lanes": self.batcher.dispatched_lanes,
                "padded_lanes": self.batcher.padded_lanes,
                "observed_pad_pct": round(
                    100.0 * self.batcher.padded_lanes
                    / max(self.batcher.dispatched_lanes
                          + self.batcher.padded_lanes, 1), 2
                ),
            },
            "max_batch": self.config.max_batch,
            "max_wait_ms": self.config.max_wait_ms,
            "mesh_devices": _mesh_lanes(self.mesh) or 1,
            "pf_backend": self.config.pf_backend,
            "pf_precision": self.config.pf_precision,
            # Pipeline shape: buffered batches per executor lane (0 =
            # the serialized single-thread path) + live lane state.
            "pipeline_depth": self.config.pipeline_depth,
            "executor_lanes": {
                w: {"queued": lane.queued(), "busy": lane.busy()}
                for w, lane in sorted(self.batcher.lanes.items())
            },
            # Shapes compiled at startup (--serve-prewarm): present in
            # the recompiles table at count 0, excluded from the
            # serve_recompiles_total counter.
            "prewarmed": sorted(self.batcher.prewarmed),
            "requests": metric("serve_requests_total"),
            "shed": metric("serve_shed_total"),
            "recompiles": metric("serve_recompiles_total"),
            # Per-shape compile attribution ("workload/case:bucket" ->
            # first dispatches of that shape): the aggregate counter
            # above says a storm happened, this table says WHO.  The
            # snapshot is taken under the batcher's shapes lock, so a
            # /stats read mid-recompile-storm sees a consistent table.
            "recompiles_by_bucket": dict(
                sorted(self.batcher.shape_table().items())
            ),
            # Incremental-tier state: hit/miss/eviction counts, byte
            # budget occupancy, flight joins (docs/serving.md).
            "cache": (
                {"enabled": True, **self.cache.stats()}
                if self.cache is not None else {"enabled": False}
            ),
            # Numerical-honesty observatory: receipt counts by tier +
            # shadow-verify outcomes (core/provenance.py; full document
            # at GET /provenance).
            "provenance": _prov.PROVENANCE.stats_block(),
            "batch_lanes": metric("serve_batch_lanes"),
            "queue_wait_seconds": metric("serve_queue_wait_seconds"),
            "solve_seconds": metric("serve_solve_seconds"),
            "request_seconds": metric("serve_request_seconds"),
            # Request-conservation ledger (the snapshot auditor's
            # ticket-accounting input; docs/snapshots.md).
            "ledger": self.ledger.snapshot_state(),
        }

    def snapshot_state(self) -> dict:
        """This replica's serve-side contribution to a consistent cut:
        the conservation ledger plus the cache's byte accounting, each
        read atomically under its own leaf lock."""
        doc = {"ledger": self.ledger.snapshot_state()}
        if self.cache is not None:
            doc["cache"] = self.cache.snapshot_state()
        return doc

    def start(self) -> "Service":
        self.batcher.start()
        return self

    def stop(self, drain_s: float = 5.0) -> None:
        """Graceful shutdown: seal admission (new submissions raise the
        typed ``shutting_down``), let the batcher FINISH every already-
        admitted ticket for up to ``drain_s`` seconds, then fail
        whatever is still queued and stop the pipeline.  Admitted work
        completing instead of being dropped is the drain contract the
        router's rolling-restart path depends on; ``drain_s=0`` is the
        old drop-everything behavior."""
        self.queue.seal()
        deadline = _time.monotonic() + max(drain_s, 0.0)
        while _time.monotonic() < deadline:
            if self.queue.depth_lanes == 0 and not self.batcher.busy():
                break
            _time.sleep(0.02)
        for t in self.queue.close():
            self._complete_error(t, ShuttingDown("service stopped"))
        self.batcher.stop()
