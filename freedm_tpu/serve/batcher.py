"""Micro-batching dispatch: coalesce, bucket, solve once, scatter.

The serving economics this module exists for: a jitted ``vmap``-ed
solve's wall time is dominated by dispatch/launch overhead at snapshot
sizes, so 32 coalesced power-flow lanes cost barely more than one.
SABLE's batched power flow and Podracer's centralized-batched compute
(PAPERS.md) both hinge on exactly this; the batcher is the host-side
machinery that converts concurrent independent requests into that one
batched device program:

- **Coalescing window** — the first admitted ticket opens a batch; the
  batcher then drains *compatible* tickets (same (workload, case) key)
  for up to ``max_wait_ms`` or until ``max_batch`` lanes, whichever
  first.  A lone request therefore pays the full window (2 ms default)
  waiting for peers that never come — that flat cost IS the price of
  coalescing at low load, which is why ``max_wait_ms`` must stay well
  under a single solve time; a full batch dispatches the moment it
  fills.
- **Shape buckets** — the real lane count is padded up to the smallest
  bucket (default: powers of two ≤ ``max_batch``), so XLA compiles at
  most ``len(buckets)`` programs per engine, ever.  The first dispatch
  of each (engine, bucket) is counted on ``serve_recompiles_total`` —
  the compile storm is bounded *and observable*.
- **Scatter** — per-request responses (with each request's own lanes
  sliced back out) resolve the waiters' futures; a solver exception
  fails the whole batch's tickets with a typed ``internal`` error
  rather than hanging them.

One dispatch thread per service is deliberate: the solvers share one
device, so a second dispatcher would only interleave compiles and
ruin the latency accounting.  Spans: each dispatch records
``serve.batch`` (parented to the oldest request's ``serve.request``
span) with a child ``pf.solve`` span around the device work, so
``/trace`` and ``tools/trace_report.py`` explain serving tails with
the same machinery that explains broker rounds.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from freedm_tpu.core import metrics as obs
from freedm_tpu.core import profiling
from freedm_tpu.core import tracing
from freedm_tpu.serve.queue import ServeError, Ticket


class _InternalError(ServeError):
    code = "internal"
    http_status = 500


class MicroBatcher:
    """The dispatch loop (one daemon thread per :class:`~freedm_tpu.serve.service.Service`)."""

    def __init__(self, service, config):
        self.service = service
        self.config = config
        self.buckets = config.bucket_table()
        # Per-shape compile attribution: "workload/case:bucket" -> first
        # dispatches of that shape (each one synchronous XLA compile).
        # /stats exposes this table so a recompile storm is attributable
        # without reading traces.
        self.recompiles_by_bucket: dict = {}
        # Watchdog surface (core.slo): the loop beats this every
        # iteration; a dispatch stuck in a compile/solve stops beating
        # while `busy()` stays true.
        self.last_beat = time.monotonic()
        self._dispatching = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # -- bucketing -----------------------------------------------------------
    def bucket_for(self, lanes: int) -> int:
        for b in self.buckets:
            if lanes <= b:
                return b
        return self.buckets[-1]

    # -- watchdog surface (core.slo) -----------------------------------------
    def progress_age(self) -> float:
        """Seconds since the dispatch loop last completed an iteration."""
        return time.monotonic() - self.last_beat

    def busy(self) -> bool:
        """True while the loop owes progress: a dispatch is executing,
        or admitted lanes are waiting for one."""
        return self._dispatching or self.service.queue.depth_lanes > 0

    # -- main loop -----------------------------------------------------------
    def _run(self) -> None:
        q = self.service.queue
        window_s = max(self.config.max_wait_ms, 0.0) / 1000.0
        while not self._stop.is_set():
            self.last_beat = time.monotonic()
            head = q.pop(timeout=0.2)
            if head is None:
                continue
            group = [head]
            lanes = head.lanes
            window_end = time.monotonic() + window_s
            while lanes < self.config.max_batch:
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                t = q.pop_compatible(
                    head.key, self.config.max_batch - lanes, remaining
                )
                if t is None:
                    break
                group.append(t)
                lanes += t.lanes
            self._dispatch(group, lanes)

    # -- dispatch ------------------------------------------------------------
    def _dispatch(self, group: List[Ticket], lanes: int) -> None:
        self._dispatching = True
        try:
            self._dispatch_inner(group, lanes)
        finally:
            self._dispatching = False

    def _dispatch_inner(self, group: List[Ticket], lanes: int) -> None:
        workload, case = group[0].key
        engine = self.service.engine(workload, case)
        bucket = self.bucket_for(lanes)
        now = time.monotonic()
        # One array observe for the whole batch (histogram observe is
        # vectorized; per-ticket calls were measurable on the hot path).
        obs.SERVE_QUEUE_WAIT.observe(
            [max(now - t.enqueued_at, 0.0) for t in group]
        )
        obs.SERVE_BATCH_LANES.labels(workload).observe(lanes)

        new_shape = bucket not in engine.compiled_buckets
        if new_shape:
            obs.SERVE_RECOMPILES.labels(workload).inc()
            key = f"{workload}/{case}:{bucket}"
            self.recompiles_by_bucket[key] = (
                self.recompiles_by_bucket.get(key, 0) + 1
            )

        span = tracing.TRACER.start(
            "serve.batch", kind="serve",
            parent_ctx=group[0].span.context(),
            tags={"workload": workload, "case": case, "requests": len(group),
                  "lanes": lanes, "bucket": bucket},
        )
        try:
            with span.activate():
                batch = engine.assemble(group, bucket)
                t0 = time.monotonic()
                with tracing.TRACER.start(
                    f"pf.solve:{workload}", kind="solve",
                    tags={"solver": workload, "bucket": bucket,
                          "jit_compile": new_shape},
                ):
                    results = engine.solve(batch)
                solve_s = time.monotonic() - t0
                engine.compiled_buckets.add(bucket)
                obs.SERVE_SOLVE_LATENCY.labels(workload).observe(solve_s)

                from freedm_tpu.serve.service import BatchInfo

                info = BatchInfo(
                    lanes=lanes,
                    bucket=bucket,
                    queue_ms=round((now - group[0].enqueued_at) * 1e3, 3),
                    solve_ms=round(solve_s * 1e3, 3),
                )
                engine.scatter(group, results, info)
            span.tag(solve_ms=round(solve_s * 1e3, 3))
            span.end()
            if profiling.PROFILER.enabled:  # one attribute check when off
                if new_shape:
                    # First dispatch of this (engine, bucket): solve_s IS
                    # the synchronous XLA compile (plus one warm solve).
                    profiling.PROFILER.record_compile(
                        workload, bucket, solve_s
                    )
                profiling.PROFILER.record_host(
                    "serve.dispatch",
                    max(time.monotonic() - now - solve_s, 0.0),
                )
                profiling.PROFILER.sample_memory("serve")
            for t in group:
                self.service._complete_ok(t, info)
        except Exception as e:  # noqa: BLE001 — waiters must never hang
            span.tag(error=repr(e))
            span.end()
            err = _InternalError(f"batch dispatch failed: {e!r}")
            for t in group:
                self.service._complete_error(t, err)
