"""Pipelined micro-batching dispatch: assemble on one lane, execute on
per-engine lanes, scatter at the deferred sync point.

The serving economics this module exists for: a jitted ``vmap``-ed
solve's wall time is dominated by dispatch/launch overhead at snapshot
sizes, so 32 coalesced power-flow lanes cost barely more than one.
SABLE's batched power flow and Podracer's centralized-batched compute
(PAPERS.md) both hinge on exactly this; the batcher is the host-side
machinery that converts concurrent independent requests into batched
device programs.  Since ISSUE 9 it is a **two-stage pipeline** in the
sebulba shape (Podracer's split of host actors from device learners):

- **Batch-assembly lane** (one thread) — pops the admission queue
  *fairly across (workload, case) keys* (:meth:`AdmissionQueue.pop_fair`:
  a hot tenant cannot starve the others), coalesces compatible tickets,
  buckets/pads them (``engine.assemble``, host numpy), and hands the
  assembled batch to its workload's executor lane over a **bounded
  handoff queue** (``pipeline_depth`` batches deep).  Assembly for
  batch N+1 therefore overlaps device execution of batch N — the
  double-buffering that takes host assembly out of the critical path.
- **Device-executor lanes** (one thread per workload: pf / n1 / vvc) —
  dispatch ``engine.solve`` (async), perform the ONE deferred
  ``jax.block_until_ready`` at the measurement boundary (so
  ``serve_solve_seconds`` is honest device wall, not dispatch time),
  and scatter results to the waiters.  Per-engine lanes mean a slow
  VVC batch no longer head-of-line-blocks a cheap pf snapshot.

``pipeline_depth=0`` (``--serve-pipeline-depth 0``) keeps the legacy
single-thread path — the same ``_assemble``/``_execute`` code run
inline on the dispatch thread — as a fallback and as the equivalence
oracle the pipeline tests compare against byte-for-byte.

Batching semantics carried over from the single-loop design:

- **Coalescing window** — the first admitted ticket opens a batch; the
  assembly lane drains *compatible* tickets (same (workload, case)
  key) for up to ``max_wait_ms`` or ``max_batch`` lanes.  **Adaptive**:
  a lone ticket whose device lane would otherwise starve (empty queue
  behind it, lane idle) dispatches immediately instead of sleeping out
  the window — the flat low-load latency tax the old loop paid is
  gone.  While the lane is *busy*, the batch keeps coalescing to the
  window instead: it could not start any sooner anyway, so waiting
  costs no latency and buys batch fill (self-clocking batch sizing).
- **Shape buckets** — real lanes pad up to the smallest bucket, so XLA
  compiles at most ``len(buckets)`` programs per engine, ever.  The
  first dispatch of each (engine, bucket) is counted on
  ``serve_recompiles_total`` and attributed in ``recompiles_by_bucket``;
  shape claims happen under ``_shapes_lock`` so concurrent lanes and
  ``/stats`` readers agree.
- **Failure containment** — a solver exception on an executor lane
  fails only *that batch's* tickets with a typed ``internal`` error;
  the lane thread and the assembly lane keep running.  A failed batch
  also aborts any single-flight followers parked on its tickets'
  cache digests (``Service._complete_error`` → ``abort_flight``).
- **Incremental tier upstream** — with ``--serve-cache-mb`` > 0, exact
  and verified-delta pf answers are completed at *submit* time
  (:mod:`freedm_tpu.serve.cache`) and never occupy queue depth, a
  coalescing window, or a device dispatch here; the batches this loop
  does dispatch populate the cache at scatter time (the pf engine's
  ``publish`` hook), which is where single-flight followers are
  answered from their leader's lane.

Watchdog surface (core.slo): the assembly loop and every executor lane
beat independently and expose ``busy()``, so a stall is attributable
to the stage that wedged.  Spans: ``serve.request`` →  ``serve.batch``
(opened at assembly, carried across the thread handoff) → ``pf.solve``
(opened on the executor lane inside the batch span's activation), so
``/trace`` shows assembly overlapping device execution.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Dict, List, Optional

from freedm_tpu.core import metrics as obs
from freedm_tpu.core import profiling
from freedm_tpu.core import roofline
from freedm_tpu.core import tracing
from freedm_tpu.core.faults import FAULTS
from freedm_tpu.serve.queue import ServeError, ShuttingDown, Ticket


class _InternalError(ServeError):
    code = "internal"
    http_status = 500


class _KeyState:
    """Per-(workload, case) accumulation state on the assembly lane.

    ``open_*`` is the batch currently coalescing (its ``deadline`` is
    the coalescing-window expiry); ``ready`` is an overflow batch that
    filled while its executor lane had no room and waits for a slot.
    Batches accumulate exactly while the device is busy — the
    self-clocking dynamic-batching effect the pipeline exists for."""

    __slots__ = ("open_group", "open_lanes", "deadline", "ready")

    def __init__(self):
        self.open_group: List[Ticket] = []
        self.open_lanes = 0
        self.deadline = 0.0
        self.ready = None  # Optional[(group, lanes)]


class _Assembled:
    """One assembled batch in flight between the stages."""

    __slots__ = ("group", "lanes", "workload", "case", "engine", "bucket",
                 "batch", "span", "new_shape", "inline")

    def __init__(self, group, lanes, workload, case, engine, bucket, batch,
                 span, new_shape, inline):
        self.group = group
        self.lanes = lanes
        self.workload = workload
        self.case = case
        self.engine = engine
        self.bucket = bucket
        self.batch = batch
        self.span = span
        self.new_shape = new_shape
        self.inline = inline


class ExecutorLane:
    """One bounded device-executor lane (one daemon thread) per workload.

    The assembly lane feeds it assembled batches over a
    ``pipeline_depth``-deep queue; the lane dispatches the solve,
    blocks at the deferred sync point, and scatters.  A crashed batch
    fails only its own tickets; the lane keeps consuming."""

    def __init__(self, batcher: "MicroBatcher", workload: str, depth: int):
        self.batcher = batcher
        self.workload = workload
        self.depth = max(int(depth), 1)
        self._q: _queue.Queue = _queue.Queue(maxsize=self.depth)
        # Watchdog surface: beats at every loop iteration; stops
        # beating while a dispatch is stuck in a compile/solve with
        # busy() true.
        self.last_beat = time.monotonic()
        self._executing = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ExecutorLane":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"serve-exec-{self.workload}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._drain()

    def _drain(self) -> None:
        while True:
            try:
                work = self._q.get_nowait()
            except _queue.Empty:
                break
            self._fail(work, ShuttingDown("service stopped"))
        self._set_inflight()

    def _fail(self, work: _Assembled, err: BaseException) -> None:
        work.span.tag(error=repr(err))
        work.span.end()
        for t in work.group:
            self.batcher.service._complete_error(t, err)

    # -- handoff (assembly lane side) ----------------------------------------
    def has_room(self) -> bool:
        """True while the handoff queue can take another batch — the
        assembly lane's ``pop_fair`` predicate, so a full lane's key is
        skipped instead of blocking assembly for everyone."""
        return self._q.qsize() < self.depth

    def submit(self, work: _Assembled) -> bool:
        """Enqueue one assembled batch (bounded; the pop_fair gate
        makes blocking here a rare race, not the steady state)."""
        while not self._stop.is_set():
            try:
                self._q.put(work, timeout=0.1)
            except _queue.Full:
                continue
            self._set_inflight()
            return True
        self._fail(work, ShuttingDown("service stopped"))
        return False

    # -- watchdog surface (core.slo) -----------------------------------------
    def busy(self) -> bool:
        return self._executing or not self._q.empty()

    def progress_age(self) -> float:
        return time.monotonic() - self.last_beat

    def queued(self) -> int:
        return self._q.qsize()

    def _set_inflight(self) -> None:
        obs.SERVE_INFLIGHT.labels(self.workload).set(
            self._q.qsize() + (1 if self._executing else 0)
        )

    # -- executor loop -------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            self.last_beat = time.monotonic()
            try:
                work = self._q.get(timeout=0.2)
            except _queue.Empty:
                continue
            self._executing = True
            try:
                self._set_inflight()
                self.batcher._execute(work)
            finally:
                self._executing = False
                self.last_beat = time.monotonic()
                self._set_inflight()
        self._drain()


class MicroBatcher:
    """The two-stage dispatch pipeline of a
    :class:`~freedm_tpu.serve.service.Service` (assembly thread +
    per-workload :class:`ExecutorLane` threads; one inline thread when
    ``pipeline_depth=0``)."""

    def __init__(self, service, config):
        self.service = service
        self.config = config
        self.buckets = config.bucket_table()
        self.pipeline_depth = max(
            int(getattr(config, "pipeline_depth", 0)), 0
        )
        #: Executor lanes by workload; empty on the legacy
        #: (``pipeline_depth=0``) path.  Built at :meth:`start`.
        self.lanes: Dict[str, ExecutorLane] = {}
        # Per-shape compile attribution: "workload/case:bucket" -> first
        # dispatches of that shape (each one synchronous XLA compile).
        # /stats exposes this table so a recompile storm is attributable
        # without reading traces.  Guarded by _shapes_lock together with
        # every engine's compiled_buckets set: the assembly lane claims
        # shapes while executor lanes run and /stats readers iterate.
        self.recompiles_by_bucket: dict = {}
        #: Shapes compiled at startup by :meth:`Service.prewarm` — shown
        #: in /stats, excluded from ``serve_recompiles_total``.
        self.prewarmed: set = set()
        # Measured padding accounting (ints mutated under the GIL, read
        # by /stats): real lanes dispatched vs pad lanes the bucket
        # table added on top.  /stats derives the observed padding
        # fraction from these — the live counterpart of the table's
        # analytic worst case (service.padding_waste_pct).
        self.dispatched_lanes = 0
        self.padded_lanes = 0
        self._shapes_lock = threading.Lock()
        # Watchdog surface (core.slo): the assembly loop beats this
        # every iteration; a stage stuck in assemble/submit stops
        # beating while `busy()` stays true.
        self.last_beat = time.monotonic()
        self._dispatching = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self.pipeline_depth > 0 and not self.lanes:
            from freedm_tpu.serve.service import WORKLOADS

            for w in WORKLOADS:
                self.lanes[w] = ExecutorLane(
                    self, w, self.pipeline_depth
                ).start()
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        for lane in self.lanes.values():
            lane.stop(timeout=timeout)

    # -- bucketing -----------------------------------------------------------
    def bucket_for(self, lanes: int) -> int:
        for b in self.buckets:
            if lanes <= b:
                return b
        return self.buckets[-1]

    # -- shape claims (assembly lane + prewarm + /stats) ---------------------
    def _claim_shape(self, engine, workload: str, case: str,
                     bucket: int) -> bool:
        """Atomically claim the first dispatch of (engine, bucket);
        True exactly once per shape.  Prewarmed shapes were claimed at
        startup and never count on ``serve_recompiles_total``."""
        with self._shapes_lock:
            if bucket in engine.compiled_buckets:
                return False
            engine.compiled_buckets.add(bucket)
            key = f"{workload}/{case}:{bucket}"
            self.recompiles_by_bucket[key] = (
                self.recompiles_by_bucket.get(key, 0) + 1
            )
        obs.SERVE_RECOMPILES.labels(workload).inc()
        return True

    def _unclaim_shape(self, engine, bucket: int) -> None:
        """A claimed first dispatch failed before its solve completed:
        un-mark the bucket so the retry re-claims it and the actual
        XLA compile is attributed (jit_compile tag + compile account).
        The recompile counter/table keep their increment — the retry
        counts again, same as the pre-pipeline retry semantics."""
        with self._shapes_lock:
            engine.compiled_buckets.discard(bucket)

    def note_prewarmed(self, engine, bucket: int) -> None:
        """Record a startup-compiled (engine, bucket): tagged in the
        /stats table (count 0 = no request-driven first dispatch) and
        excluded from ``serve_recompiles_total``."""
        workload, case = engine.key
        key = f"{workload}/{case}:{bucket}"
        with self._shapes_lock:
            engine.compiled_buckets.add(bucket)
            self.recompiles_by_bucket.setdefault(key, 0)
            self.prewarmed.add(key)

    def shape_table(self) -> dict:
        """Locked snapshot of ``recompiles_by_bucket`` for /stats."""
        with self._shapes_lock:
            return dict(self.recompiles_by_bucket)

    # -- watchdog surface (core.slo) -----------------------------------------
    def progress_age(self) -> float:
        """Seconds since the assembly loop last completed an iteration."""
        return time.monotonic() - self.last_beat

    def busy(self) -> bool:
        """True while the pipeline owes progress: a batch is being
        assembled/executed, or admitted lanes are waiting for one."""
        return (
            self._dispatching
            or self.service.queue.depth_lanes > 0
            or any(lane.busy() for lane in self.lanes.values())
        )

    # -- assembly loop -------------------------------------------------------
    def _run(self) -> None:
        if self.lanes:
            self._run_pipelined()
        else:
            self._run_serial()

    def _run_serial(self) -> None:
        """The legacy single-thread path (``--serve-pipeline-depth 0``):
        coalesce, assemble, solve, block, and scatter inline — the
        equivalence oracle the pipeline is tested against."""
        q = self.service.queue
        window_s = max(self.config.max_wait_ms, 0.0) / 1000.0
        while not self._stop.is_set():
            self.last_beat = time.monotonic()
            head = q.pop(timeout=0.2)
            if head is None:
                continue
            group = [head]
            lanes = head.lanes
            window_end = time.monotonic() + window_s
            while lanes < self.config.max_batch:
                if q.depth_lanes == 0:
                    # Adaptive coalescing: nothing queued behind this
                    # batch — dispatch now instead of sleeping out the
                    # window (the old flat low-load latency tax).
                    break
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                t = q.pop_compatible(
                    head.key, self.config.max_batch - lanes, remaining
                )
                if t is None:
                    break
                group.append(t)
                lanes += t.lanes
            self._dispatch(group, lanes)

    def _run_pipelined(self) -> None:
        """The pipelined assembly loop: shared coalescing windows.

        Unlike the serial path, the assembly thread never sits inside
        one key's window while other keys' work waits — it pops tickets
        fairly into per-key *open batches* and flushes each batch when
        it fills, its window expires, or nothing else is queued
        (adaptive).  A batch whose executor lane is full keeps
        accumulating instead of blocking — batch size self-clocks to
        device speed, which is what keeps dispatch overhead off the
        critical path."""
        q = self.service.queue
        window_s = max(self.config.max_wait_ms, 0.0) / 1000.0
        states: dict = {}  # key -> _KeyState
        max_batch = self.config.max_batch

        def lane_room(key) -> bool:
            lane = self.lanes.get(key[0])
            return lane.has_room() if lane is not None else True

        def lane_idle(key) -> bool:
            lane = self.lanes.get(key[0])
            return not lane.busy() if lane is not None else True

        def flush_open(key, st) -> None:
            group, lanes = st.open_group, st.open_lanes
            st.open_group, st.open_lanes = [], 0
            self._dispatch(group, lanes)

        def key_can_take(key) -> bool:
            st = states.get(key)
            if st is None:
                return True
            # Stop popping a key only when both its buffers are spoken
            # for: an overflow batch parked AND a full open batch.
            return not (st.ready is not None
                        and st.open_lanes >= max_batch)

        while not self._stop.is_set():
            self.last_beat = time.monotonic()
            now = time.monotonic()
            pending = False  # anything coalescing or parked
            for key, st in states.items():
                room = lane_room(key)
                if st.ready is not None and room:
                    group, lanes = st.ready
                    st.ready = None
                    self._dispatch(group, lanes)
                    room = lane_room(key)
                if st.open_lanes:
                    # Flush when full, when the window expired, or when
                    # there is no coalescing upside left (nothing
                    # admitted and the lane is starving).
                    due = (st.open_lanes >= max_batch
                           or now >= st.deadline
                           or (q.depth_lanes == 0 and lane_idle(key)))
                    if due and st.ready is None and room:
                        flush_open(key, st)
                    else:
                        pending = True
                elif st.ready is not None:
                    pending = True
            timeout = 0.2
            if pending:
                # Window expiry and lane drains do not signal the
                # admission queue's condition — poll on a short bound
                # while anything is coalescing or parked.
                timeout = 0.002
            t = q.pop_fair(timeout=timeout, key_ok=key_can_take)
            if t is None:
                if q.depth_lanes == 0:
                    # Adaptive: nothing admitted and the lane would
                    # starve — flush its open batch now.  A BUSY lane's
                    # batch keeps coalescing instead (it could not
                    # start any sooner anyway, so waiting costs no
                    # latency and buys batch fill).
                    for key, st in states.items():
                        if st.open_lanes and st.ready is None \
                                and lane_idle(key):
                            flush_open(key, st)
                continue
            st = states.get(t.key)
            if st is None:
                st = states[t.key] = _KeyState()
            if st.open_lanes and st.open_lanes + t.lanes > max_batch:
                # The ticket straddles the batch boundary: park the
                # full open batch.  If an older batch is already
                # parked, force IT out first (blocking submit, bounded
                # by the lane's execution time) — the older tickets
                # must dispatch before the newer ones so same-key FIFO
                # completion order holds.
                if st.ready is not None:
                    group, lanes = st.ready
                    st.ready = None
                    self._dispatch(group, lanes)
                st.ready = (st.open_group, st.open_lanes)
                st.open_group, st.open_lanes = [], 0
            if not st.open_lanes:
                st.deadline = time.monotonic() + window_s
            st.open_group.append(t)
            st.open_lanes += t.lanes
            if q.depth_lanes == 0 and st.ready is None \
                    and lane_idle(t.key):
                # Adaptive coalescing: nothing queued behind this
                # ticket and its lane is starving — dispatch now
                # instead of waiting the window.
                flush_open(t.key, st)
        # Stop: fail whatever was still coalescing (the admission queue
        # was already drained by Service.stop with the same error).
        err = ShuttingDown("service stopped")
        for st in states.values():
            groups = []
            if st.ready is not None:
                groups.append(st.ready[0])
            if st.open_lanes:
                groups.append(st.open_group)
            for group in groups:
                for t in group:
                    self.service._complete_error(t, err)

    # -- dispatch ------------------------------------------------------------
    def _dispatch(self, group: List[Ticket], lanes: int) -> None:
        self._dispatching = True
        try:
            work = self._assemble(group, lanes)
            if work is None:
                return  # assembly failed; its tickets were completed
            lane = self.lanes.get(work.workload)
            if lane is not None:
                lane.submit(work)
            else:
                self._execute(work)
        finally:
            self._dispatching = False

    # -- stage 1: host-side assembly -----------------------------------------
    def _assemble(self, group: List[Ticket],
                  lanes: int) -> Optional[_Assembled]:
        workload, case = group[0].key
        now = time.monotonic()
        span = tracing.NOOP
        try:
            engine = self.service.engine(workload, case)
            bucket = self.bucket_for(lanes)
            obs.SERVE_BATCH_LANES.labels(workload).observe(lanes)
            self.dispatched_lanes += lanes
            self.padded_lanes += max(bucket - lanes, 0)
            span = tracing.TRACER.start(
                "serve.batch", kind="serve",
                parent_ctx=group[0].span.context(),
                tags={"workload": workload, "case": case,
                      "requests": len(group), "lanes": lanes,
                      "bucket": bucket},
            )
            with span.activate():
                batch = engine.assemble(group, bucket)
            # Claim the shape only once assembly succeeded: a failed
            # batch must not mark its bucket compiled, or the retry
            # that actually pays the XLA compile would be mis-tagged
            # jit_compile=false and dropped from the compile account.
            new_shape = self._claim_shape(engine, workload, case, bucket)
            if profiling.PROFILER.enabled:  # one attribute check when off
                profiling.PROFILER.record_host(
                    "serve.assemble", max(time.monotonic() - now, 0.0)
                )
            return _Assembled(
                group=group, lanes=lanes, workload=workload, case=case,
                engine=engine, bucket=bucket, batch=batch, span=span,
                new_shape=new_shape, inline=not self.lanes,
            )
        except Exception as e:  # noqa: BLE001 — waiters must never hang
            span.tag(error=repr(e))
            span.end()
            err = _InternalError(f"batch assembly failed: {e!r}")
            for t in group:
                self.service._complete_error(t, err)
            return None

    # -- stage 2: device execution + scatter (executor lane / inline) --------
    def _execute(self, work: _Assembled) -> None:
        import jax

        group = work.group
        engine = work.engine
        workload = work.workload
        t_host0 = time.monotonic()
        # Queue wait is admission -> start of device dispatch, so the
        # handoff-queue time on the executor lane is included (the
        # receipt and serve_queue_wait_seconds must explain the full
        # pre-solve wait, not just the assembly lane's share).  One
        # array observe for the whole batch (vectorized; per-ticket
        # calls were measurable here).
        obs.SERVE_QUEUE_WAIT.observe(
            [max(t_host0 - t.enqueued_at, 0.0) for t in group]
        )
        solve_s = 0.0
        try:
            if FAULTS.enabled:
                # Injected executor faults (docs/robustness.md): a
                # delay models a compile storm / slow device; a crash
                # must fail ONLY this batch's tickets with the typed
                # `internal` error while the lane itself survives —
                # the crash-containment contract the router's retry
                # depends on.
                FAULTS.sleep_point("serve.exec.delay")
                if FAULTS.should("serve.exec.crash"):
                    raise RuntimeError("fault injected: serve.exec.crash")
            with work.span.activate():
                t0 = time.monotonic()
                with tracing.TRACER.start(
                    f"pf.solve:{workload}", kind="solve",
                    tags={"solver": workload, "bucket": work.bucket,
                          "jit_compile": work.new_shape},
                ):
                    results = engine.solve(work.batch)
                    # The ONE designed deferred sync: solve() above
                    # returned an async dispatch; blocking here, at the
                    # measurement boundary, makes solve_s honest device
                    # wall on both the pipelined and legacy paths.
                    jax.block_until_ready(results)
                solve_s = time.monotonic() - t0
                # Exemplared with the batch span's trace: a p99 solve
                # bucket on /metrics links straight to its trace tree
                # (None while tracing is off = no exemplar recorded).
                obs.SERVE_SOLVE_LATENCY.labels(workload).observe(
                    solve_s, exemplar=work.span.trace_id
                )

                from freedm_tpu.serve.service import BatchInfo

                info = BatchInfo(
                    lanes=work.lanes,
                    bucket=work.bucket,
                    # Admission -> device dispatch (incl. the executor
                    # handoff), measured from the head-of-batch ticket.
                    queue_ms=round(
                        (t_host0 - group[0].enqueued_at) * 1e3, 3
                    ),
                    solve_ms=round(solve_s * 1e3, 3),
                    # Every dispatched batch is the full-solve tier;
                    # exact/delta cache answers never reach this loop
                    # (serve/cache.py completes them at submit time).
                    tier="full",
                )
                engine.scatter(group, results, info)
            work.span.tag(solve_ms=round(solve_s * 1e3, 3))
            work.span.end()
            if profiling.PROFILER.enabled:  # one attribute check when off
                if work.new_shape:
                    # First dispatch of this (engine, bucket): solve_s
                    # IS the synchronous XLA compile (plus one warm
                    # solve).
                    profiling.PROFILER.record_compile(
                        workload, work.bucket, solve_s
                    )
                profiling.PROFILER.record_host(
                    "serve.dispatch" if work.inline else "serve.execute",
                    max(time.monotonic() - t_host0 - solve_s, 0.0),
                )
                profiling.PROFILER.sample_memory("serve")
            if roofline.ROOFLINE.enabled:  # one attribute check when off
                # solve_s is block_until_ready-bounded above — the
                # honest device wall the roofline join needs.  The
                # registry traced these programs at fixed lane counts
                # (pf bucket 4, vvc bucket 2), so the model cost scales
                # linearly with the dispatched bucket; a compile-tainted
                # first dispatch is counted but not credited wall.
                _rl_prog, _rl_base = {
                    "pf": ("serve/pf/bucket4", 4.0),
                    "vvc": ("serve/vvc/bucket2", 2.0),
                    "n1": ("pf/n1/smw", None),
                }.get(workload, (None, None))
                if _rl_prog is not None:
                    roofline.ROOFLINE.record_dispatch(
                        _rl_prog,
                        device_s=None if work.new_shape else solve_s,
                        scale=1.0 if _rl_base is None
                        else work.bucket / _rl_base,
                    )
            for t in group:
                self.service._complete_ok(t, info)
        except Exception as e:  # noqa: BLE001 — waiters must never hang
            if work.new_shape:
                self._unclaim_shape(engine, work.bucket)
            work.span.tag(error=repr(e))
            work.span.end()
            err = _InternalError(f"batch dispatch failed: {e!r}")
            for t in group:
                self.service._complete_error(t, err)
