"""Admission control for the query-serving subsystem.

The serving path must never "queue forever": a grid operator's what-if
console and a planning screen's 500-outage sweep share one device, and
the only honest behaviors under overload are (a) a bounded wait and
(b) an explicit, *typed* rejection the client can back off on.  This
module is that boundary:

- the :class:`ServeError` hierarchy — every way a request can fail
  without an answer, each with a stable wire ``code`` and an HTTP status
  the front end (:mod:`freedm_tpu.serve.http`) maps directly;
- :class:`Ticket` — one admitted request: its validated payload, its
  lane weight (an N-1 screen of 40 outages costs 40 lanes, a power-flow
  snapshot costs 1), its monotonic deadline, and the future its waiter
  blocks on;
- :class:`AdmissionQueue` — a bounded FIFO measured in *lanes*, not
  requests, so a single huge screen cannot sneak past a depth limit
  sized for snapshots.  ``put`` raises :class:`Overloaded` instead of
  blocking (shed-on-overload; the client retries with backoff, the
  server's latency distribution stays bounded); expired tickets are
  completed with :class:`DeadlineExceeded` at pop time so a stale
  request never wastes a solve.

Depth accounting feeds the ``serve_queue_depth`` gauge
(:mod:`freedm_tpu.core.metrics`) on every transition, so a scrape sees
backpressure building before the shed counter moves.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple


class ServeError(Exception):
    """Base of the typed serving errors.

    ``code`` is the stable wire identifier (the JSON ``error.type``
    field); ``http_status`` is the front-end mapping.  Clients switch on
    ``code``, never on the message text.
    """

    code = "internal"
    http_status = 500
    #: Back-off hint (seconds) carried as a ``Retry-After`` header by
    #: the HTTP front end on retryable statuses (429/503); ``None`` on
    #: errors a client must not retry.
    retry_after_s: Optional[float] = None


class Overloaded(ServeError):
    """Admission rejected: the queue is at depth.  Shed-on-overload is
    deliberate — rejecting now with a typed error beats an unbounded
    queue whose p99 grows with depth."""

    code = "overloaded"
    http_status = 429
    retry_after_s = 1.0


class DeadlineExceeded(ServeError):
    """The request's deadline passed before a batch picked it up."""

    code = "deadline_exceeded"
    http_status = 504


class InvalidRequest(ServeError):
    """The request failed validation (unknown case, wrong vector length,
    islanding outage, non-finite values, ...)."""

    code = "invalid_request"
    http_status = 400


class ShuttingDown(ServeError):
    """The service is stopping.  Since the graceful-drain change this
    is only raised for work that was never admitted (submission after
    :meth:`AdmissionQueue.seal`, or tickets still queued when the drain
    budget ran out) — already-admitted tickets finish normally."""

    code = "shutting_down"
    http_status = 503
    retry_after_s = 2.0


class Unavailable(ServeError):
    """No replica can take the request right now: the router's entire
    replica table is down, draining, or breaker-open.  Always carries a
    ``Retry-After`` — the fleet is expected to recover."""

    code = "unavailable"
    http_status = 503
    retry_after_s = 1.0


class NotFound(ServeError):
    """The named resource (a job id, a disabled subsystem) does not
    exist on this server."""

    code = "not_found"
    http_status = 404


class Ticket:
    """One admitted request, queued for a batch slot."""

    __slots__ = (
        "key", "request", "prepared", "lanes", "enqueued_at",
        "deadline", "future", "span", "taken", "cache_flight",
        "ledger_state",
    )

    def __init__(self, key: Tuple[str, str], request, prepared, lanes: int,
                 deadline: Optional[float], span=None):
        self.key = key  # (workload, case) — only same-key tickets batch
        self.request = request
        self.prepared = prepared  # engine-validated arrays
        self.lanes = int(lanes)
        self.enqueued_at = time.monotonic()
        self.deadline = deadline  # monotonic, or None
        self.future: Future = Future()
        self.span = span  # serve.request span (or tracing NOOP)
        self.taken = False  # popped from one index; lazily dropped from the other
        # Single-flight leadership (serve/cache.py): the (entry key,
        # injection digest) this ticket's solve populates, or None.
        self.cache_flight = None
        # Conservation-ledger phase (serve/service.py SnapshotLedger):
        # None → "inflight" → "ok"/"error"; guarded by the ledger's own
        # lock so a ticket settles exactly once in a consistent cut.
        self.ledger_state = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) > self.deadline


class AdmissionQueue:
    """Bounded FIFO of :class:`Ticket`\\ s, measured in lanes.

    Two indexes over the same tickets, both O(1) per operation at
    serving rates: a global arrival-order deque (head-of-line fairness
    across keys) and a per-key deque (the batcher's compatible-ticket
    drain).  A ticket popped through one index is flagged ``taken`` and
    lazily discarded when it surfaces at the other's head — no linear
    scans on the hot path.

    ``max_depth`` bounds the *sum of lane weights* waiting — the
    quantity that actually determines how much solve work is promised
    but not delivered.  Expired tickets are completed with
    :class:`DeadlineExceeded` when they surface at a head, so a stale
    request never wastes a solve.
    """

    def __init__(self, max_depth: int = 512, depth_gauge=None,
                 on_expired=None):
        self.max_depth = int(max_depth)
        self._cond = threading.Condition()
        self._fifo: deque = deque()
        self._by_key: Dict[Tuple[str, str], deque] = {}
        # Round-robin rotation over keys for pop_fair: every key ever
        # admitted, oldest-served first.  Bounded by the engine cache
        # (MAX_ENGINES × workloads), so empty keys just get skipped.
        self._rr: deque = deque()
        self._lanes = 0
        self._closed = False
        self._depth_gauge = depth_gauge
        self._on_expired = on_expired  # callback(ticket) for accounting

    # -- accounting ----------------------------------------------------------
    def _set_gauge_locked(self) -> None:
        if self._depth_gauge is not None:
            self._depth_gauge.set(self._lanes)

    @property
    def depth_lanes(self) -> int:
        with self._cond:
            return self._lanes

    def __len__(self) -> int:
        with self._cond:
            return sum(1 for t in self._fifo if not t.taken)

    # -- producer side -------------------------------------------------------
    def put(self, ticket: Ticket) -> None:
        """Admit or shed.  Raises :class:`Overloaded` when the ticket's
        lanes would push the queue past ``max_depth`` (the caller
        completes the future with the error and counts the shed), and
        :class:`ShuttingDown` after :meth:`close`."""
        with self._cond:
            if self._closed:
                raise ShuttingDown("service is stopping")
            if self._lanes + ticket.lanes > self.max_depth:
                raise Overloaded(
                    f"queue at depth ({self._lanes}/{self.max_depth} lanes); "
                    f"retry with backoff"
                )
            self._fifo.append(ticket)
            kq = self._by_key.get(ticket.key)
            if kq is None:
                kq = self._by_key[ticket.key] = deque()
                self._rr.append(ticket.key)
            kq.append(ticket)
            self._lanes += ticket.lanes
            self._set_gauge_locked()
            self._cond.notify_all()

    # -- consumer side (batcher thread) --------------------------------------
    def _take_locked(self, ticket: Ticket) -> None:
        ticket.taken = True
        self._lanes -= ticket.lanes
        self._set_gauge_locked()

    def pop(self, timeout: Optional[float] = None) -> Optional[Ticket]:
        """Oldest live ticket, blocking up to ``timeout`` seconds.
        Expired tickets encountered on the way are completed with
        :class:`DeadlineExceeded` and skipped."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            dead: List[Ticket] = []
            took = None
            with self._cond:
                now = time.monotonic()
                while self._fifo:
                    t = self._fifo[0]
                    if t.taken:
                        self._fifo.popleft()
                        continue
                    if t.expired(now):
                        self._fifo.popleft()
                        self._take_locked(t)
                        dead.append(t)
                        continue
                    self._fifo.popleft()
                    self._take_locked(t)
                    took = t
                    break
                if took is None and not dead:
                    remaining = None if deadline is None else deadline - now
                    if remaining is not None and remaining <= 0:
                        return None
                    if self._closed:
                        # Sealed and empty: nothing can arrive (put
                        # raises), but honoring the timeout keeps the
                        # draining batcher from spinning hot.
                        self._cond.wait(remaining if remaining is not None
                                        else 0.2)
                        return None
                    self._cond.wait(remaining)
                    continue
            self._fail_expired(dead)
            if took is not None:
                return took

    def pop_fair(self, timeout: Optional[float] = None,
                 key_ok=None) -> Optional[Ticket]:
        """Oldest live ticket of the least-recently-served (workload,
        case) key — round-robin across keys, so one hot tenant cannot
        starve the others' batch assembly.  ``key_ok(key)`` (optional)
        gates keys for this pass: the pipelined batcher passes its
        executor-lane capacity check, so a key whose lane is full is
        skipped instead of blocking assembly for everyone.  Expired
        tickets encountered on the way are completed with
        :class:`DeadlineExceeded` and skipped."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            dead: List[Ticket] = []
            took = None
            skipped = False  # a live key was gated off by key_ok
            with self._cond:
                now = time.monotonic()
                # Amortized cleanup of the global index: pop_fair never
                # reads _fifo, so lazily drop the taken heads here to
                # keep it from growing without bound.
                while self._fifo and self._fifo[0].taken:
                    self._fifo.popleft()
                for _ in range(len(self._rr)):
                    key = self._rr[0]
                    self._rr.rotate(-1)
                    kq = self._by_key.get(key)
                    while kq:
                        t = kq[0]
                        if t.taken:
                            kq.popleft()
                            continue
                        if t.expired(now):
                            kq.popleft()
                            self._take_locked(t)
                            dead.append(t)
                            continue
                        break
                    if not kq:
                        continue
                    if key_ok is not None and not key_ok(key):
                        skipped = True
                        continue
                    t = kq.popleft()
                    self._take_locked(t)
                    took = t
                    break
                if took is None and not dead:
                    remaining = None if deadline is None else deadline - now
                    if remaining is not None and remaining <= 0:
                        return None
                    if self._closed:
                        # Sealed and empty (see pop()): wait out the
                        # timeout instead of hot-spinning the caller —
                        # but keep the short re-check bound while a
                        # gated key still holds drainable tickets.
                        w = remaining if remaining is not None else 0.2
                        self._cond.wait(min(w, 0.05) if skipped else w)
                        return None
                    # A gated key's lane drains without notifying this
                    # condition — wake on a short bound to re-check.
                    if skipped:
                        remaining = 0.05 if remaining is None \
                            else min(remaining, 0.05)
                    self._cond.wait(remaining)
                    continue
            self._fail_expired(dead)
            if took is not None:
                return took

    def pop_compatible(self, key: Tuple[str, str], max_lanes: int,
                       timeout: float) -> Optional[Ticket]:
        """Oldest queued ticket with this ``key`` whose lanes fit in
        ``max_lanes``, blocking up to ``timeout`` for one to arrive.
        A head ticket too big for the remaining batch space stays put
        (it opens the next batch); other keys' tickets are untouched."""
        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            dead: List[Ticket] = []
            took = None
            blocked = False  # head fits the key but not the batch space
            with self._cond:
                now = time.monotonic()
                kq = self._by_key.get(key)
                while kq:
                    t = kq[0]
                    if t.taken:
                        kq.popleft()
                        continue
                    if t.expired(now):
                        kq.popleft()
                        self._take_locked(t)
                        dead.append(t)
                        continue
                    if t.lanes <= max_lanes:
                        kq.popleft()
                        self._take_locked(t)
                        took = t
                    else:
                        blocked = True
                    break
                if took is None and not dead:
                    if blocked or self._closed:
                        return None
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    self._cond.wait(remaining)
                    continue
            self._fail_expired(dead)
            if took is not None:
                return took
            if time.monotonic() >= deadline:
                return None

    def _fail_expired(self, dead: List[Ticket]) -> None:
        for t in dead:
            if self._on_expired is not None:
                self._on_expired(t)
            else:
                t.future.set_exception(
                    DeadlineExceeded("deadline passed while queued")
                )

    # -- shutdown ------------------------------------------------------------
    def seal(self) -> None:
        """Refuse NEW work (``put`` raises :class:`ShuttingDown`) while
        keeping every already-admitted ticket poppable — the graceful-
        drain half of shutdown: the batcher keeps dispatching what was
        promised, only not-yet-admitted work sees the typed error."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def close(self) -> List[Ticket]:
        """Refuse new work and return the still-queued tickets (the
        service drains them with :class:`ShuttingDown`)."""
        with self._cond:
            self._closed = True
            drained = [t for t in self._fifo if not t.taken]
            for t in drained:
                self._take_locked(t)
            self._fifo.clear()
            self._by_key.clear()
            self._cond.notify_all()
        return drained
