"""Cache-affinity failover router for a replicated serving fleet.

One serve process is one failure domain: a wedged executor lane or a
killed host takes the whole front door down.  This module is the thin
horizontal layer ROADMAP's multi-host item calls for — N independent
replica processes (each a full :mod:`freedm_tpu.serve` stack with its
own PR-10 incremental cache) behind a zero-dependency HTTP router that:

- **consistent-hashes the request's ``case`` onto the replica ring**
  (``vnodes`` virtual points per replica, blake2-hashed), so repeat
  traffic for a (case, topology) lands on the same replica and its
  incremental cache stays hot.  The case name *is* the topology
  identity at the front door — replicas key their caches by the full
  (case, topology-digest, backend) triple internally, so a stale
  router can never cause a wrong answer, only a cold one;
- keeps a **health-checked replica table**: a background prober GETs
  every replica's ``/healthz`` (which also reports ``draining``), and
  proxy failures mark replicas passively — a kill is noticed by the
  very request that hit it, not a probe later;
- runs a **per-replica circuit breaker** (closed → open after
  ``breaker_failures`` consecutive transport failures → half-open
  after ``breaker_cooldown_s`` → closed on a successful trial), so a
  dead replica costs one connect timeout per cooldown, not per
  request;
- retries with **jittered exponential backoff under the request's own
  deadline budget**: the budget (the request's ``timeout_s``) is
  propagated to replicas via the ``X-Deadline-Budget-S`` header
  (replicas clamp their queue deadline to it), every retry re-checks
  the remaining budget, and a request is never retried past its own
  deadline — it answers a typed 504 instead;
- **fails over along the ring**: an unavailable owner's keys walk to
  the next replica clockwise (counted on ``router_failovers_total``),
  so one replica's death moves only its own hash range;
- honors **graceful drain**: a replica whose ``/healthz`` reports
  ``draining: true`` (SIGTERM, rolling restart) stops receiving new
  requests while its in-flight work finishes; its range rebalances to
  the ring successors;
- sheds with a **typed 503 + ``Retry-After``** only when every replica
  is open/down/draining (``router_shed_total``).

Everything is surfaced on the existing registry/tracer: ``router_*``
metrics, per-replica breaker-state gauges, and one ``serve.route``
span per routed request (tags: case, owner, served-by replica,
attempts, outcome).  The router itself exposes ``/healthz`` (its own
liveness + the replica table), ``/stats``, and ``GET /metrics`` — the
fleet federation scrape: every replica's registry with a ``replica``
label injected plus the router's own series, so one scrape target
covers the whole fleet (``router_federation_up`` marks replicas that
missed the scrape).  ``POST /v1/snapshot`` initiates a fleet-wide
consistent cut (every replica's conservation ledger, cache accounting,
and job table captured and audited — :mod:`freedm_tpu.core.snapshot`;
docs/snapshots.md); ``GET /v1/snapshot/<id>`` serves the retained,
audited cut document.

Scope: the router fronts the synchronous what-if workloads
(``POST /v1/pf|n1|vvc``).  QSTS jobs are replica-local state (a job id
only means something to the process that runs it) — route those to a
replica directly.  See ``docs/robustness.md``.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import random
import socket
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler
from typing import Dict, List, NamedTuple, Optional, Tuple
from urllib.parse import urlparse

from freedm_tpu.core import metrics as obs
from freedm_tpu.core import tracing
from freedm_tpu.serve.queue import (
    DeadlineExceeded,
    InvalidRequest,
    NotFound,
    ServeError,
    Unavailable,
)

#: Workloads the router fronts (same vocabulary as serve.service).
ROUTED_WORKLOADS = ("pf", "n1", "vvc", "topo")

#: Breaker states, also the ``router_breaker_state`` gauge encoding.
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

def _hash64(key: str) -> int:
    """Stable 64-bit ring position (blake2b — no PYTHONHASHSEED)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring with virtual nodes.

    Affinity stability is the contract the tests pin: adding or
    removing one member only remaps keys that hashed into that
    member's arcs — every other key keeps its owner."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []  # sorted (hash, member)

    def add(self, member: str) -> None:
        pts = [(_hash64(f"{member}#{i}"), member)
               for i in range(self.vnodes)]
        self._points = sorted(self._points + pts)

    def remove(self, member: str) -> None:
        self._points = [(h, m) for h, m in self._points if m != member]

    def members(self) -> List[str]:
        return sorted({m for _, m in self._points})

    def preference(self, key: str) -> List[str]:
        """All members, clockwise from ``key``'s ring position,
        deduplicated: ``[owner, first failover, ...]``."""
        if not self._points:
            return []
        h = _hash64(key)
        # binary search for the first point >= h (wraps to 0)
        lo, hi = 0, len(self._points)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._points[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        out: List[str] = []
        n = len(self._points)
        for i in range(n):
            m = self._points[(lo + i) % n][1]
            if m not in out:
                out.append(m)
        return out

    def owner(self, key: str) -> Optional[str]:
        pref = self.preference(key)
        return pref[0] if pref else None


def _relabel_exposition(text: str, replica: str,
                        seen_meta: set) -> List[str]:
    """Inject ``replica="<id>"`` into every sample line of a Prometheus
    text exposition, keeping the first ``# HELP``/``# TYPE`` per metric
    fleet-wide (``seen_meta`` carries the dedupe state across calls).
    A sample that already carries a ``replica`` label — the router's
    own breaker/federation gauges — is passed through untouched: a
    duplicate label name is illegal exposition."""
    label = 'replica="{}"'.format(
        replica.replace("\\", "\\\\").replace('"', '\\"')
    )
    out: List[str] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                key = (parts[1], parts[2])
                if key in seen_meta:
                    continue
                seen_meta.add(key)
            out.append(line)
            continue
        # Split an OpenMetrics exemplar suffix (` # {trace_id="..."} v`,
        # core/metrics.py) off FIRST: its braces must not be mistaken
        # for the sample's label set by the rfind below.
        exemplar = ""
        cut = line.find(" # {")
        if cut != -1:
            line, exemplar = line[:cut], line[cut:]
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            close = line.rfind("}")
            inner = line[brace + 1:close]
            if 'replica="' in inner:
                out.append(line + exemplar)
                continue
            inner = "{},{}".format(inner, label) if inner else label
            out.append(line[:brace + 1] + inner + "}" + line[close + 1:]
                       + exemplar)
        elif space != -1:
            out.append("{}{{{}}}{}{}".format(line[:space], label,
                                             line[space:], exemplar))
        else:
            out.append(line + exemplar)
    return out


class ReplicaState:
    """One replica's routing state (mutated under the router lock)."""

    __slots__ = ("id", "host", "port", "state", "failures", "opened_at",
                 "healthy", "draining", "admin_drained", "trial_inflight",
                 "last_probe")

    def __init__(self, rid: str, host: str, port: int):
        self.id = rid
        self.host = host
        self.port = port
        self.state = CLOSED
        self.failures = 0  # consecutive transport failures
        self.opened_at = 0.0
        self.healthy = True  # optimistic until a probe/proxy says otherwise
        # Two drain verdicts with different owners: ``draining`` is the
        # REPLICA's own /healthz (or shutting_down) signal, refreshed by
        # every probe; ``admin_drained`` is the router-side
        # :meth:`Router.drain` decision, which a probe must never undo.
        self.draining = False
        self.admin_drained = False
        self.trial_inflight = False  # half-open: one trial at a time
        self.last_probe = 0.0

    @property
    def is_draining(self) -> bool:
        return self.draining or self.admin_drained

    def to_dict(self) -> dict:
        return {
            "id": self.id, "breaker": self.state,
            "healthy": self.healthy, "draining": self.is_draining,
            "admin_drained": self.admin_drained,
            "consecutive_failures": self.failures,
        }


class RouterConfig(NamedTuple):
    """Routing knobs (CLI: ``--router-port`` and friends)."""

    #: Active /healthz probe cadence per replica.
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 1.0
    #: Consecutive transport failures that open a replica's breaker.
    breaker_failures: int = 3
    #: Open → half-open cooldown.
    breaker_cooldown_s: float = 2.0
    #: Jittered exponential backoff between retries: base * 2^attempt,
    #: uniformly jittered in [0.5x, 1.5x], capped.
    retry_base_s: float = 0.025
    retry_cap_s: float = 0.5
    #: Deadline budget for requests that carry no timeout_s of their own.
    default_timeout_s: float = 30.0
    #: Per-attempt ceiling (None = the remaining budget): bounds how
    #: long one stalled replica can eat before the router fails over.
    try_timeout_s: Optional[float] = None
    connect_timeout_s: float = 2.0
    #: Virtual ring points per replica.
    vnodes: int = 64
    #: Backoff-jitter seed (deterministic retries for tests/replays).
    seed: int = 0
    #: Consistent-cut snapshot bound (``--snapshot-timeout-s``): the
    #: fan-out to replicas never blocks the initiator past this — a
    #: dead/stalled replica yields a typed incomplete cut, not a hang.
    snapshot_timeout_s: float = 10.0
    #: Per-node cut document cap (``--snapshot-max-bytes``).
    snapshot_max_bytes: int = 4_000_000


class _ProxyReply(NamedTuple):
    status: int
    body: bytes
    retry_after: Optional[str]
    #: Which replica produced the answer (the ``X-Served-By`` response
    #: header) — None on router-originated errors.
    served_by: Optional[str] = None


class Router:
    """The replica table + routing core.  :class:`RouterServer` is the
    HTTP shell around it; tests drive this class directly."""

    def __init__(self, replicas: List[str],
                 config: RouterConfig = RouterConfig()):
        self.config = config
        self._lock = threading.Lock()
        self._rng = random.Random(f"router:{config.seed}")
        self.ring = HashRing(vnodes=config.vnodes)
        self.replicas: Dict[str, ReplicaState] = {}
        for r in replicas:
            self.add_replica(r)
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        # Consistent-cut snapshot state (core/snapshot.py): one cut at
        # a time (concurrent initiations answer a typed 409), bounded
        # result retention.
        self._snapshot_lock = threading.Lock()
        self._snapshot_active = False
        self._snapshot_counter = 0
        self._snapshots: "OrderedDict[str, dict]" = OrderedDict()

    # -- membership ----------------------------------------------------------
    def add_replica(self, addr: str) -> None:
        host, _, port = str(addr).rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"replica must be host:port, got {addr!r}")
        with self._lock:
            if addr in self.replicas:
                return
            self.replicas[addr] = ReplicaState(addr, host, int(port))
            self.ring.add(addr)
        obs.ROUTER_BREAKER_STATE.labels(addr).set(_STATE_CODE[CLOSED])
        self._set_available_gauge()

    def remove_replica(self, addr: str) -> None:
        with self._lock:
            self.replicas.pop(addr, None)
            self.ring.remove(addr)
        self._set_available_gauge()

    def drain(self, addr: str) -> None:
        """Administratively stop routing NEW work to a replica (its
        in-flight requests finish on their own connections)."""
        with self._lock:
            st = self.replicas.get(addr)
            if st is not None:
                st.admin_drained = True
        obs.EVENTS.emit("router.drain", replica=addr)
        self._set_available_gauge()

    # -- availability / breaker ---------------------------------------------
    def _admittable_locked(self, st: ReplicaState, now: float) -> bool:
        if st.is_draining or not st.healthy:
            return False
        if st.state == CLOSED:
            return True
        if st.state == OPEN:
            if now - st.opened_at >= self.config.breaker_cooldown_s:
                self._transition_locked(st, HALF_OPEN)
            else:
                return False
        # half-open: one trial request at a time
        if st.trial_inflight:
            return False
        st.trial_inflight = True
        return True

    def _transition_locked(self, st: ReplicaState, state: str) -> None:
        if st.state == state:
            return
        st.state = state
        if state == OPEN:
            st.opened_at = time.monotonic()
        if state != HALF_OPEN:
            st.trial_inflight = False
        obs.ROUTER_BREAKER_STATE.labels(st.id).set(_STATE_CODE[state])
        obs.ROUTER_BREAKER_TRANSITIONS.labels(st.id, state).inc()

    def _record_failure(self, rid: str) -> None:
        opened = []
        with self._lock:
            st = self.replicas.get(rid)
            if st is None:
                return
            st.trial_inflight = False
            st.failures += 1
            if st.state == HALF_OPEN or (
                st.state == CLOSED
                and st.failures >= self.config.breaker_failures
            ):
                self._transition_locked(st, OPEN)
                opened.append((st.id, st.failures))
        for rid_, fails in opened:
            obs.EVENTS.emit("router.breaker_open", replica=rid_,
                            consecutive_failures=fails)
        self._set_available_gauge()

    def _record_success(self, rid: str) -> None:
        events = []
        with self._lock:
            st = self.replicas.get(rid)
            if st is None:
                return
            st.trial_inflight = False
            st.failures = 0
            st.healthy = True
            if st.state != CLOSED:
                self._transition_locked(st, CLOSED)
                events.append(st.id)
        for rid_ in events:
            obs.EVENTS.emit("router.breaker_close", replica=rid_)
        self._set_available_gauge()

    def _set_available_gauge(self) -> None:
        now = time.monotonic()
        with self._lock:
            n = sum(
                1 for st in self.replicas.values()
                if not st.is_draining and st.healthy
                and (st.state != OPEN
                     or now - st.opened_at >= self.config.breaker_cooldown_s)
            )
        obs.ROUTER_REPLICAS_AVAILABLE.set(n)

    # -- health prober -------------------------------------------------------
    def start_probes(self) -> "Router":
        if self._prober is None or not self._prober.is_alive():
            self._stop.clear()
            self._prober = threading.Thread(
                target=self._probe_loop, name="router-prober", daemon=True
            )
            self._prober.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=2.0)

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the prober must outlive
                # any single bad probe/emit; a dead prober would freeze
                # the health table for the router's whole lifetime.
                pass
            self._stop.wait(self.config.probe_interval_s)

    def probe_once(self) -> None:
        """One active /healthz pass over the table (also callable
        synchronously from tests)."""
        with self._lock:
            targets = list(self.replicas.values())
        for st in targets:
            healthy, draining = self._probe(st)
            with self._lock:
                cur = self.replicas.get(st.id)
                if cur is None:
                    continue
                changed = healthy != cur.healthy
                cur.healthy = healthy
                cur.draining = draining if healthy else cur.draining
                cur.last_probe = time.monotonic()
            if changed and healthy:
                obs.EVENTS.emit("router.replica_up", replica=st.id)
            elif changed:
                obs.EVENTS.emit("router.replica_down", replica=st.id)
        self._set_available_gauge()

    def _probe(self, st: ReplicaState) -> Tuple[bool, bool]:
        try:
            conn = http.client.HTTPConnection(
                st.host, st.port, timeout=self.config.probe_timeout_s
            )
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    return False, False
                d = json.loads(body)
                return True, bool(d.get("draining", False))
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            # HTTPException covers IncompleteRead/BadStatusLine from a
            # replica dying mid-response — a probe failure, never a
            # prober-thread death.
            return False, False

    # -- fleet federation (GET /metrics) -------------------------------------
    def federate_metrics(self) -> str:
        """One Prometheus scrape target for the whole fleet: every
        replica's ``GET /metrics`` rendering with a ``replica=<id>``
        label injected on each sample line (``# HELP``/``# TYPE``
        deduplicated fleet-wide), followed by the router's own registry
        labeled ``replica="router"``.  Fleet totals are a query-side
        ``sum without(replica)(...)`` — the label keeps per-replica
        attribution, which a pre-summed exposition would destroy.  A
        replica that fails the scrape contributes nothing but its
        ``router_federation_up{replica=...} 0`` marker."""
        with self._lock:
            targets = list(self.replicas.values())
        seen_meta: set = set()
        out: List[str] = []
        for st in targets:
            text = self._scrape_metrics(st)
            obs.ROUTER_FEDERATION_UP.labels(st.id).set(
                0.0 if text is None else 1.0
            )
            if text is None:
                continue
            out.extend(_relabel_exposition(text, st.id, seen_meta))
        # Router-local series last, so its just-updated federation_up
        # gauges describe THIS scrape.
        out.extend(_relabel_exposition(
            obs.REGISTRY.render_prometheus(), "router", seen_meta
        ))
        return "\n".join(out) + "\n"

    def _scrape_metrics(self, st: ReplicaState) -> Optional[str]:
        try:
            conn = http.client.HTTPConnection(
                st.host, st.port, timeout=self.config.probe_timeout_s
            )
            try:
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    return None
                return body.decode("utf-8", "replace")
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            return None

    # -- routing core --------------------------------------------------------
    def route(self, path: str, body: bytes) -> _ProxyReply:
        """Route one ``POST /v1/<workload>`` body; always returns a
        typed HTTP reply (never raises to the shell)."""
        workload = path[len("/v1/"):]
        try:
            case, timeout_s = self._parse(workload, body)
        except ServeError as e:
            obs.ROUTER_REQUESTS.labels(e.code).inc()
            return _error_reply(e)
        deadline = time.monotonic() + timeout_s
        span = tracing.TRACER.start(
            "serve.route", kind="route",
            tags={"workload": workload, "case": case},
        )
        try:
            reply, served_by, attempts, outcome = self._route_attempts(
                case, path, body, deadline, span
            )
        except Exception as e:  # noqa: BLE001 — the shell answers typed
            obs.ROUTER_REQUESTS.labels("error").inc()
            span.tag(outcome="error", error=repr(e))
            span.end()
            return _error_reply(_RouterInternal(repr(e)))
        span.tag(outcome=outcome, attempts=attempts,
                 served_by=served_by or "")
        span.end()
        obs.ROUTER_REQUESTS.labels(outcome).inc()
        return reply._replace(served_by=served_by)

    def _parse(self, workload: str, body: bytes) -> Tuple[str, float]:
        if workload not in ROUTED_WORKLOADS:
            raise InvalidRequest(
                f"router fronts {'/'.join(ROUTED_WORKLOADS)}; "
                f"route {workload!r} to a replica directly"
            )
        try:
            payload = json.loads(body or b"null")
        except ValueError as e:
            raise InvalidRequest(f"malformed JSON: {e}") from None
        if not isinstance(payload, dict) or not isinstance(
            payload.get("case"), str
        ) or not payload["case"]:
            raise InvalidRequest(
                "request body must be a JSON object with a 'case' string "
                "(the router's affinity key)"
            )
        timeout_s = payload.get("timeout_s", 0)
        # bool is an int subclass: {"timeout_s": true} must not become
        # a 1-second budget (mirrors http.apply_deadline_budget).
        if isinstance(timeout_s, bool) or \
                not isinstance(timeout_s, (int, float)) or timeout_s <= 0:
            timeout_s = self.config.default_timeout_s
        return payload["case"], float(timeout_s)

    def _pick(self, preference: List[str], now: float,
              avoid=frozenset()) -> Tuple[Optional[ReplicaState], bool]:
        """First admittable replica in ring order; second value is
        whether the pick is a failover off the affinity owner.
        ``avoid`` holds replicas that already answered THIS request
        with per-replica backpressure (429) — another replica may have
        room, so they are skipped for the request's remaining attempts."""
        with self._lock:
            for i, rid in enumerate(preference):
                if rid in avoid:
                    continue
                st = self.replicas.get(rid)
                if st is None:
                    continue
                if self._admittable_locked(st, now):
                    return st, i > 0
        return None, False

    def _release_pick(self, st: ReplicaState) -> None:
        """Undo a pick that will NOT be forwarded to (probe-only
        re-picks): a claimed half-open trial slot must be returned or
        the breaker's single trial leaks."""
        with self._lock:
            st.trial_inflight = False

    def _route_attempts(self, case: str, path: str, body: bytes,
                        deadline: float, span):
        cfg = self.config
        preference = self.ring.preference(case)
        attempt = 0
        last_err: Optional[ServeError] = None
        # Replicas that answered THIS request with per-replica 429:
        # skipped for the request's remaining attempts (failover, not
        # hammering) — cleared only by running out of alternatives.
        overloaded: set = set()
        while True:
            now = time.monotonic()
            remaining = deadline - now
            if remaining <= 0:
                err = last_err or DeadlineExceeded(
                    "deadline budget exhausted before any replica answered"
                )
                if not isinstance(err, DeadlineExceeded):
                    err = DeadlineExceeded(
                        f"deadline budget exhausted retrying "
                        f"(last: {err.code})"
                    )
                return _error_reply(err), None, attempt, "deadline"
            st, failover = self._pick(preference, now, avoid=overloaded)
            if st is None and overloaded:
                # No non-shedding replica left.  Distinguish "every
                # admittable replica shed THIS request" (propagate the
                # typed 429 promptly) from "the shedders are also the
                # only ones alive and now something else changed": a
                # re-pick WITHOUT the avoid set tells which — and its
                # half-open trial claim is released, since no request
                # is actually sent.
                st2, _ = self._pick(preference, now)
                if st2 is not None:
                    self._release_pick(st2)
                    err = _Overloaded(
                        "every available replica is shedding (fleet at "
                        "admission depth); back off and retry"
                    )
                    return _error_reply(err), None, attempt, "overloaded"
            if st is None:
                # Nothing admittable at all (down, draining, or
                # breaker-open): typed shed with a Retry-After sized to
                # the breaker cooldown (by then an open breaker is
                # half-open and will trial a request).
                obs.ROUTER_SHED.inc()
                err = Unavailable(
                    "no replica available (all down, draining, or "
                    "breaker-open); retry after the cooldown"
                )
                err.retry_after_s = max(cfg.breaker_cooldown_s, 1.0)
                return _error_reply(err), None, attempt, "unavailable"
            attempt += 1
            if attempt > 1:
                obs.ROUTER_RETRIES.inc()
            if failover:
                obs.ROUTER_FAILOVERS.inc()
            ok, reply = self._forward_once(st, path, body, remaining,
                                           trace_ctx=span.context())
            if ok:
                return reply, st.id, attempt, _outcome_of(reply)
            last_err = reply  # a ServeError on the failure path
            if isinstance(reply, _Overloaded):
                # Per-replica backpressure: fail over to the next ring
                # replica immediately — no backoff, another replica may
                # have room right now.
                overloaded.add(st.id)
                continue
            # Failure-shaped errors (transport, internal, draining):
            # jittered exponential backoff, never past the deadline.
            back = min(
                cfg.retry_base_s * (2 ** (attempt - 1)), cfg.retry_cap_s
            ) * (0.5 + self._rng.random())
            back = min(back, max(deadline - time.monotonic(), 0.0))
            if back > 0:
                span.annotate("backoff", attempt=attempt,
                              sleep_ms=round(back * 1e3, 3))
                time.sleep(back)

    def _forward_once(self, st: ReplicaState, path: str, body: bytes,
                      remaining: float, trace_ctx=None):
        """One proxy attempt.  Returns ``(True, _ProxyReply)`` on an
        answer the client should see, or ``(False, ServeError)`` on a
        failure the retry loop handles (``_Overloaded`` = fail over
        now, anything else = backoff then retry)."""
        cfg = self.config
        per_try = remaining if cfg.try_timeout_s is None \
            else min(remaining, cfg.try_timeout_s)
        headers = {
            "Content-Type": "application/json",
            # The deadline budget rides the wire: the replica clamps
            # its own queue deadline to it, so a retried request
            # cannot straddle budgets.
            "X-Deadline-Budget-S": f"{remaining:.3f}",
        }
        if trace_ctx:
            # Trace context rides the wire too (serve/http.py adopts
            # it): the replica's serve.request span parents under this
            # route span in one cross-process tree, and the replica's
            # provenance receipt carries the router-valid trace_id.
            headers["X-Trace-Id"] = trace_ctx["trace_id"]
            headers["X-Span-Id"] = trace_ctx["span_id"]
        t0 = time.monotonic()
        try:
            conn = http.client.HTTPConnection(
                st.host, st.port,
                timeout=max(min(per_try, 1e6), 0.001),
            )
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
                status = resp.status
                retry_after = resp.getheader("Retry-After")
            finally:
                conn.close()
        except (OSError, http.client.HTTPException) as e:
            obs.ROUTER_PROXY_LATENCY.observe(time.monotonic() - t0)
            self._record_failure(st.id)
            kind = "timeout" if isinstance(e, socket.timeout) else "connect"
            return False, _Transport(f"{kind} error on {st.id}: {e!r}")
        obs.ROUTER_PROXY_LATENCY.observe(time.monotonic() - t0)
        if status >= 500 and _error_code(payload) in (None, "internal",
                                                      "error"):
            # Replica-internal failure: breaker-relevant, retryable
            # (the solve is pure — a crashed batch re-runs cleanly).
            self._record_failure(st.id)
            return False, _Transport(
                f"replica {st.id} answered {status} internal"
            )
        # Any other answer is an ANSWER: typed client errors (400/404),
        # typed backpressure (429/503), and 200s all pass through.
        self._record_success(st.id)
        code = _error_code(payload)
        if code == "shutting_down":
            # The replica is draining: remember it so new work stops
            # landing there before the next probe, and retry elsewhere.
            with self._lock:
                cur = self.replicas.get(st.id)
                if cur is not None:
                    cur.draining = True
            self._set_available_gauge()
            return False, _Transport(f"replica {st.id} draining")
        if code == "overloaded":
            # Per-replica backpressure: another replica may have room.
            return False, _Overloaded(f"replica {st.id} overloaded")
        return True, _ProxyReply(status, payload, retry_after)

    # -- consistent-cut snapshots (core/snapshot.py) -------------------------
    def snapshot(self, snapshot_id: Optional[str] = None) -> dict:
        """Initiate one fleet-wide consistent cut: fan out
        ``POST /v1/snapshot`` to EVERY replica (dead ones stub in as
        ``incomplete`` — the cut must cover the fleet, not the healthy
        subset), assemble, audit, and retain the cut.  Bounded by
        ``snapshot_timeout_s`` — a stalled replica can never wedge the
        initiator — and serialized: a second initiation while one runs
        answers the typed 409."""
        from freedm_tpu.core import snapshot as snapmod

        with self._snapshot_lock:
            if self._snapshot_active:
                obs.SNAPSHOT_CUTS.labels("rejected").inc()
                raise _SnapshotBusy(
                    "a fleet snapshot is already in progress; "
                    "poll GET /v1/snapshot/<id> and retry"
                )
            self._snapshot_active = True
            self._snapshot_counter += 1
            sid = snapshot_id or (
                f"cut-{self._snapshot_counter}-{int(time.time() * 1e3)}"
            )
        try:
            return self._snapshot_run(snapmod, sid)
        finally:
            with self._snapshot_lock:
                self._snapshot_active = False

    def _snapshot_run(self, snapmod, sid: str) -> dict:
        cfg = self.config
        span = tracing.TRACER.start(
            "snapshot.fleet", kind="snapshot", tags={"snapshot_id": sid}
        )
        t0 = time.monotonic()
        with self._lock:
            targets = list(self.replicas.values())
        obs.EVENTS.emit(
            "snapshot.start", snapshot_id=sid, node="router",
            origin="router", peers=[st.id for st in targets],
        )
        docs: List[Optional[dict]] = [None] * len(targets)

        def grab(i: int, st: ReplicaState) -> None:
            docs[i] = self._snapshot_replica(st, sid,
                                             cfg.snapshot_timeout_s)

        threads = [
            threading.Thread(target=grab, args=(i, st), daemon=True,
                             name=f"snapshot-{st.id}")
            for i, st in enumerate(targets)
        ]
        for th in threads:
            th.start()
        deadline = t0 + cfg.snapshot_timeout_s
        for th in threads:
            th.join(timeout=max(deadline - time.monotonic(), 0.0))
        pending = []
        node_docs: List[dict] = []
        for st, doc in zip(targets, docs):
            if doc is None:
                pending.append(st.id)
                node_docs.append({"snapshot_id": sid, "node": st.id,
                                  "status": "incomplete"})
            else:
                doc.setdefault("node", st.id)
                node_docs.append(
                    snapmod.bound_doc(doc, cfg.snapshot_max_bytes)
                )
        cut = snapmod.assemble_cut(sid, node_docs)
        violations = snapmod.audit_cut(cut)
        snapmod.record_violations(sid, violations)
        capture_ms = round((time.monotonic() - t0) * 1e3, 3)
        cut["origin"] = "router"
        cut["captured_at"] = time.time()
        cut["capture_ms"] = capture_ms
        cut["violations"] = [v.as_dict() for v in violations]
        with self._snapshot_lock:
            self._snapshots[sid] = cut
            while len(self._snapshots) > snapmod.KEEP_CUTS:
                self._snapshots.popitem(last=False)
        obs.SNAPSHOT_CUTS.labels(cut["status"]).inc()
        obs.SNAPSHOT_CAPTURE.observe(capture_ms / 1e3)
        if cut["status"] == "complete":
            obs.EVENTS.emit("snapshot.complete", snapshot_id=sid,
                            node="router", capture_ms=capture_ms,
                            violations=len(violations))
        else:
            obs.EVENTS.emit("snapshot.incomplete", snapshot_id=sid,
                            node="router", capture_ms=capture_ms,
                            pending=pending,
                            timeout_s=cfg.snapshot_timeout_s)
        span.tag(outcome=cut["status"], capture_ms=capture_ms)
        span.end()
        return cut

    def _snapshot_replica(self, st: ReplicaState, sid: str,
                          timeout_s: float) -> Optional[dict]:
        body = json.dumps({"snapshot_id": sid, "node": st.id}).encode()
        try:
            conn = http.client.HTTPConnection(
                st.host, st.port, timeout=max(timeout_s, 0.001)
            )
            try:
                conn.request(
                    "POST", "/v1/snapshot", body=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                payload = resp.read()
                if resp.status != 200:
                    return None
                doc = json.loads(payload)
                return doc if isinstance(doc, dict) else None
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            return None

    def snapshot_result(self, snapshot_id: str) -> Optional[dict]:
        with self._snapshot_lock:
            return self._snapshots.get(snapshot_id)

    # -- introspection -------------------------------------------------------
    def states(self) -> Dict[str, dict]:
        with self._lock:
            return {rid: st.to_dict()
                    for rid, st in sorted(self.replicas.items())}

    def stats(self) -> dict:
        snap = obs.REGISTRY.snapshot()

        def metric(name):
            return snap.get(name, {}).get("values", {})

        return {
            "replicas": self.states(),
            "ring_members": self.ring.members(),
            "vnodes": self.config.vnodes,
            "requests": metric("router_requests_total"),
            "retries": metric("router_retries_total"),
            "failovers": metric("router_failovers_total"),
            "shed": metric("router_shed_total"),
            "breaker_state": metric("router_breaker_state"),
            "proxy_seconds": metric("router_proxy_seconds"),
        }


class _Transport(ServeError):
    code = "transport"
    http_status = 502


class _Overloaded(ServeError):
    code = "overloaded"
    http_status = 429
    retry_after_s = 1.0


class _RouterInternal(ServeError):
    code = "internal"
    http_status = 500


class _SnapshotBusy(ServeError):
    """One consistent cut at a time: a concurrent initiation is a
    client-visible, typed conflict — never a second marker wave."""

    code = "snapshot_in_progress"
    http_status = 409
    retry_after_s = 1.0


def _error_code(payload: bytes) -> Optional[str]:
    try:
        d = json.loads(payload)
        return d["error"]["type"]
    except (ValueError, KeyError, TypeError):
        return None


def _outcome_of(reply: _ProxyReply) -> str:
    if reply.status == 200:
        return "ok"
    return _error_code(reply.body) or f"http_{reply.status}"


def _error_reply(err: ServeError) -> _ProxyReply:
    from freedm_tpu.serve.http import retry_after_header

    body = (json.dumps(
        {"error": {"type": err.code, "detail": str(err)}}
    ) + "\n").encode()
    ra = getattr(err, "retry_after_s", None)
    return _ProxyReply(
        err.http_status, body,
        retry_after_header(ra) if ra else None,
    )


class RouterServer:
    """The HTTP shell: ``POST /v1/*`` routed, ``GET /healthz``/
    ``/stats`` served locally.  Same zero-dependency scaffold as the
    serve front end."""

    def __init__(self, router: Router, port: int = 0,
                 host: str = "127.0.0.1"):
        from freedm_tpu.core.metrics import BackgroundHttpServer

        rt = router
        self.router = router

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, status: int, data: bytes,
                       retry_after: Optional[str] = None,
                       served_by: Optional[str] = None) -> None:
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if retry_after:
                    self.send_header("Retry-After", retry_after)
                if served_by:
                    self.send_header("X-Served-By", served_by)
                if self.close_connection:
                    self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(data)

            def _read_body(self) -> bytes:
                from freedm_tpu.serve.http import read_request_body

                return read_request_body(self)

            def do_GET(self):
                path = urlparse(self.path).path
                try:
                    self._read_body()
                    if path == "/healthz":
                        states = rt.states()
                        body = (json.dumps({
                            "ok": True,
                            "role": "router",
                            "replicas": states,
                        }) + "\n").encode()
                        self._reply(200, body)
                    elif path == "/stats":
                        self._reply(
                            200, (json.dumps(rt.stats()) + "\n").encode()
                        )
                    elif path.startswith("/v1/snapshot/"):
                        sid = path[len("/v1/snapshot/"):]
                        cut = rt.snapshot_result(sid)
                        if cut is None:
                            r = _error_reply(NotFound(
                                f"unknown snapshot_id {sid!r} (cuts are "
                                f"retained bounded; re-initiate with "
                                f"POST /v1/snapshot)"
                            ))
                            self._reply(404, r.body)
                        else:
                            self._reply(
                                200,
                                (json.dumps(cut, default=str)
                                 + "\n").encode(),
                            )
                    elif path == "/metrics":
                        # Fleet federation: replica registries summed
                        # under a replica label + the router's own
                        # series (text exposition, not JSON).
                        data = rt.federate_metrics().encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                        self.send_header("Content-Length", str(len(data)))
                        if self.close_connection:
                            self.send_header("Connection", "close")
                        self.end_headers()
                        self.wfile.write(data)
                    else:
                        self._reply(404, _error_reply(
                            NotFound(f"no route {path!r}")
                        ).body)
                except ServeError as e:
                    r = _error_reply(e)
                    self._reply(r.status, r.body, r.retry_after)

            def do_POST(self):
                path = urlparse(self.path).path
                try:
                    body = self._read_body()
                    if path == "/v1/snapshot":
                        # Initiate one fleet-wide consistent cut; the
                        # full audited document is at
                        # GET /v1/snapshot/<id>.
                        cut = rt.snapshot()
                        self._reply(200, (json.dumps({
                            "snapshot_id": cut["snapshot_id"],
                            "status": cut["status"],
                            "nodes": sorted(cut["nodes"]),
                            "capture_ms": cut["capture_ms"],
                            "violations": cut["violations"],
                        }) + "\n").encode())
                        return
                    if not path.startswith("/v1/"):
                        r = _error_reply(NotFound(f"no route {path!r}"))
                        self._reply(404, r.body)
                        return
                    reply = rt.route(path, body)
                    self._reply(reply.status, reply.body,
                                reply.retry_after, reply.served_by)
                except ServeError as e:
                    r = _error_reply(e)
                    self._reply(r.status, r.body, r.retry_after)
                except Exception as e:  # noqa: BLE001 — always typed
                    r = _error_reply(_RouterInternal(repr(e)))
                    self._reply(r.status, r.body, r.retry_after)

        self._server = BackgroundHttpServer(Handler, port=port, host=host)
        self.port = self._server.port

    def start(self) -> "RouterServer":
        self._server.start()
        self.router.start_probes()
        return self

    def stop(self) -> None:
        self.router.stop()
        self._server.stop()
