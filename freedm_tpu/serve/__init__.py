"""Micro-batching query serving for power-flow, N-1, and VVC what-ifs.

See ``docs/serving.md``.  Pieces: admission + typed errors
(:mod:`freedm_tpu.serve.queue`), the coalescing/bucketing dispatcher
(:mod:`freedm_tpu.serve.batcher`), the typed workloads and the
:class:`Service` facade (:mod:`freedm_tpu.serve.service`), and the JSON
front end (:mod:`freedm_tpu.serve.http`, CLI ``--serve-port``).
"""

from freedm_tpu.serve.queue import (  # noqa: F401
    AdmissionQueue,
    DeadlineExceeded,
    InvalidRequest,
    NotFound,
    Overloaded,
    ServeError,
    ShuttingDown,
    Unavailable,
)
from freedm_tpu.serve.service import (  # noqa: F401
    N1Request,
    N1Response,
    PowerFlowRequest,
    PowerFlowResponse,
    ServeConfig,
    Service,
    TopoRequest,
    TopoResponse,
    VVCRequest,
    VVCResponse,
    default_buckets,
    parse_request,
)
from freedm_tpu.serve.cache import (  # noqa: F401
    CachedSolution,
    CaseEntry,
    ServeCache,
    injection_digest,
    topology_digest,
)
from freedm_tpu.serve.http import ServeServer  # noqa: F401
from freedm_tpu.serve.router import (  # noqa: F401
    HashRing,
    Router,
    RouterConfig,
    RouterServer,
)
