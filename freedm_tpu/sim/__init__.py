"""Co-simulation rigs: run the framework without real hardware.

The reference ships ``pscad-interface`` — a standalone table server
that emulates the simulator side of the RTDS protocol so N DGI
processes can be tested against one simulated grid (SURVEY.md §2.4).
This package is its TPU-native replacement: the "simulator" is the
physics-bearing pure-JAX plant (:class:`freedm_tpu.devices.adapters
.plant.PlantAdapter`), served over the same lock-step buffer protocol
the RTDS adapter speaks.
"""

from freedm_tpu.sim.plantserver import PlantServer, load_rig

__all__ = ["PlantServer", "load_rig"]
