"""Standalone plant server speaking the RTDS lock-step protocol.

Reference: ``pscad-interface-master`` — one process, one TCP server per
``<adapter>`` element, shared state/command device tables with
reader/writer locks (``src/PosixMain.cpp:46-80``,
``include/CTableManager.hpp:43-88``).  N DGI processes connect their
RTDS adapters and exchange whole float buffers against the tables.

Here the tables *are* a live plant: a
:class:`~freedm_tpu.devices.adapters.plant.PlantAdapter` (radial feeder
+ ladder power flow + frequency droop) advanced by a physics clock.
Each served port performs the simulator half of the lock-step exchange
— read the client's command buffer, apply it, reply with the state
buffer — so a fleet process (or several) runs against real closed-loop
physics with no hardware, which is strictly more than the reference's
static tables.

Run standalone:  ``python -m freedm_tpu.sim.plantserver rig.xml``
(see :func:`load_rig` for the XML schema), or embed in-process for
tests via :class:`PlantServer`.
"""

from __future__ import annotations

import socket
import threading
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from freedm_tpu.core import logging as dgilog
from freedm_tpu.core.config import NULL_COMMAND
from freedm_tpu.devices.adapters.plant import PlantAdapter
from freedm_tpu.devices.adapters.rtds import WIRE_DTYPE, read_exactly
from freedm_tpu.utils.textio import read_source

Binding = Tuple[str, str]  # (device, signal)

logger = dgilog.get_logger(__name__)


@dataclass
class _Port:
    """One served adapter port: its socket + buffer⇄table bindings.

    ``protocol``: "rtds" = the byte-oriented lock-step float exchange;
    "pscad" = the header-based simulation protocol
    (``pscad-interface-master/src/CSimulationAdapter.cpp``).
    """

    states: List[Binding]  # index order = buffer order
    commands: List[Binding]
    server: socket.socket = None  # type: ignore[assignment]
    threads: List[threading.Thread] = field(default_factory=list)
    protocol: str = "rtds"


#: PSCAD simulation protocol framing (CSimulationAdapter.hpp:65 and
#: DeviceTable.hpp:42: 5-byte header, 8-byte double signal values —
#: native byte order in the reference, which deployed little-endian).
SIM_HEADER_SIZE = 5
SIM_DTYPE = np.dtype("<f8")


class PlantServer:
    """Serve a PlantAdapter's signals over RTDS lock-step TCP ports."""

    def __init__(self, plant: PlantAdapter, period_s: float = 0.050):
        self.plant = plant
        self.period_s = period_s
        self._plant_lock = threading.RLock()
        self._ports: List[_Port] = []
        self._stop = threading.Event()
        self._physics: Optional[threading.Thread] = None
        self.exchanges = 0

    # -- configuration -------------------------------------------------------
    def add_port(
        self,
        states: Sequence[Binding],
        commands: Sequence[Binding],
        bind: Tuple[str, int] = ("127.0.0.1", 0),
        protocol: str = "rtds",
    ) -> Tuple[str, int]:
        """Declare a served port; returns its bound (host, port).

        ``protocol="rtds"``: the DGI-side lock-step float exchange.
        ``protocol="pscad"``: the line-oriented simulation protocol a
        PSCAD co-simulation drives (RST/SET push states into the plant,
        GET reads back what the DGI commanded).
        """
        if protocol not in ("rtds", "pscad"):
            raise ValueError(f"unknown port protocol {protocol!r}")
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(bind)
        srv.listen(4)
        self._ports.append(
            _Port(list(states), list(commands), server=srv, protocol=protocol)
        )
        return srv.getsockname()

    def port_address(self, i: int) -> Tuple[str, int]:
        return self._ports[i].server.getsockname()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "PlantServer":
        with self._plant_lock:
            self.plant.step()  # prime voltages/omega before first client
        self._physics = threading.Thread(target=self._physics_loop, daemon=True)
        self._physics.start()
        for p in self._ports:
            t = threading.Thread(target=self._accept_loop, args=(p,), daemon=True)
            t.start()
            p.threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for p in self._ports:
            try:
                p.server.close()
            except OSError:
                pass
        if self._physics is not None:
            self._physics.join(timeout=2.0)

    def _physics_loop(self) -> None:
        while not self._stop.wait(self.period_s):
            with self._plant_lock:
                self.plant.step()

    # -- the simulator half of the lock-step exchange ------------------------
    def _accept_loop(self, p: _Port) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = p.server.accept()
            except OSError:
                return
            target = (
                self._serve_sim_conn if p.protocol == "pscad" else self._serve_conn
            )
            t = threading.Thread(target=target, args=(p, conn), daemon=True)
            t.start()
            p.threads.append(t)

    def _serve_conn(self, p: _Port, conn: socket.socket) -> None:
        """Receive commands, apply, reply with states — the reverse
        order of the DGI side (CRtdsAdapter.cpp:141-145)."""
        conn.settimeout(None)  # the client's command write paces us
        try:
            while not self._stop.is_set():
                if not p.commands:
                    # Nothing to block on: pace state pushes ourselves.
                    if self._stop.wait(self.period_s):
                        break
                if p.commands:
                    raw = read_exactly(conn, len(p.commands) * 4)
                    cmds = np.frombuffer(raw, WIRE_DTYPE).astype(np.float64)
                    with self._plant_lock:
                        for (device, signal), v in zip(p.commands, cmds):
                            if abs(v - NULL_COMMAND) > 0.5:
                                self.plant.set_command(device, signal, float(v))
                if p.states:
                    with self._plant_lock:
                        vals = [
                            self.plant.get_state(device, signal)
                            for device, signal in p.states
                        ]
                    conn.sendall(np.asarray(vals, WIRE_DTYPE).tobytes())
                self.exchanges += 1
        except (ConnectionError, OSError):
            pass  # client went away; the acceptor keeps serving
        finally:
            conn.close()

    # -- the PSCAD simulation protocol ---------------------------------------
    def _apply_external(self, device: str, signal: str, value: float) -> None:
        """Install an externally simulated state into the plant: Load
        drain, Drer generation, and Desd storage have dedicated inputs;
        everything else flows through the command path (Fid state,
        Pload pload, …).  Un-installable signals (e.g. Omega frequency,
        which only physics produces) are skipped with a warning — one
        bad binding must not kill the connection or the rest of the
        message."""
        try:
            tname = self.plant.placements[device][0]
            if (tname, signal) == ("Load", "drain"):
                self.plant.set_load(device, value)
            elif (tname, signal) == ("Drer", "generation"):
                self.plant.set_generation(device, value)
            elif (tname, signal) == ("Desd", "storage"):
                self.plant.set_storage(device, value)
            else:
                self.plant.set_command(device, signal, value)
        except KeyError:
            logger.warn(
                f"simulation pushed un-installable state "
                f"{device}.{signal}; skipped"
            )

    def _serve_sim_conn(self, p: _Port, conn: socket.socket) -> None:
        """Header-based exchange (CSimulationAdapter::HandleConnection):
        5-byte header, then SET/RST push ``len(states)`` doubles into
        the plant (RST also seeds commands from the same values — the
        reference's COMMAND_TABLE ← STATE_TABLE copy) and GET replies
        with ``len(commands)`` doubles of the DGI-commanded values."""
        conn.settimeout(None)
        try:
            while not self._stop.is_set():
                header = read_exactly(conn, SIM_HEADER_SIZE)
                kind = header.rstrip(b"\x00 ").decode(errors="replace")
                if kind in ("RST", "SET"):
                    raw = read_exactly(conn, len(p.states) * SIM_DTYPE.itemsize)
                    vals = np.frombuffer(raw, SIM_DTYPE)
                    with self._plant_lock:
                        for (device, signal), v in zip(p.states, vals):
                            self._apply_external(device, signal, float(v))
                        if kind == "RST":
                            for (device, signal), v in zip(p.states, vals):
                                try:
                                    self.plant.set_command(device, signal, float(v))
                                except KeyError:
                                    pass  # state without a command path
                elif kind == "GET":
                    # The COMMAND table view: what the DGI commanded,
                    # not the plant state (they differ for e.g. Desd
                    # charge rate vs storage level).
                    with self._plant_lock:
                        vals = [
                            self.plant.last_command(device, signal)
                            for device, signal in p.commands
                        ]
                    conn.sendall(np.asarray(vals, SIM_DTYPE).tobytes())
                else:
                    # An unknown verb's payload length is unknowable, so
                    # the stream cannot resync — close the connection
                    # (the client reconnects) instead of misparsing the
                    # payload as headers forever.
                    logger.warn(
                        f"unrecognized simulation header {header!r}; "
                        "closing connection"
                    )
                    return
                self.exchanges += 1
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()


# ---------------------------------------------------------------------------
# rig.xml
# ---------------------------------------------------------------------------


def load_rig(source: Union[str, "os.PathLike[str]"]) -> PlantServer:
    """Build a PlantServer from a rig XML (pscad-interface's
    ``rscad.xml`` role, ``pscad-interface-master/src/PosixMain.cpp:46-80``):

    .. code-block:: xml

        <rig case="vvc_9bus" period="0.05">
          <device name="SST1" type="Sst" node="2"/>
          <device name="DRER_A" type="Drer" node="1" value="30"/>
          <device name="LOAD_A" type="Load" node="0" value="10"/>
          <adapter port="5501">
            <state device="SST1" signal="gateway" index="0"/>
            <command device="SST1" signal="gateway" index="0"/>
          </adapter>
        </rig>

    ``case`` names a constructor in :mod:`freedm_tpu.grid.cases`;
    ``value`` seeds Drer generation / Load drain.  ``port="0"`` binds an
    ephemeral port (query it via :meth:`PlantServer.port_address`).
    """
    root = ET.fromstring(read_source(source, "<"))
    from freedm_tpu.grid import cases

    case_name = root.get("case", "vvc_9bus")
    try:
        feeder = getattr(cases, case_name)()
    except AttributeError as e:
        raise ValueError(f"unknown feeder case {case_name!r}") from e

    placements: Dict[str, Tuple[str, int]] = {}
    seeds: List[Tuple[str, str, float]] = []
    for d in root.findall("device"):
        name, tname = d.get("name"), d.get("type")
        if not name or not tname or d.get("node") is None:
            raise ValueError("device needs name, type, node attributes")
        placements[name] = (tname, int(d.get("node")))
        if d.get("value") is not None:
            seeds.append((name, tname, float(d.get("value"))))

    plant = PlantAdapter(
        feeder,
        placements,
        droop=float(root.get("droop", 0.05)),
        # base="feeder" grounds physics in the feeder's spot loads (the
        # closed-loop VVC rig mode).
        feeder_base_load=root.get("base") == "feeder",
    )
    for name, tname, value in seeds:
        if tname == "Drer":
            plant.set_generation(name, value)
        elif tname == "Load":
            plant.set_load(name, value)
        else:
            raise ValueError(f"value= only seeds Drer/Load, not {tname}")
    plant.reveal_devices()

    server = PlantServer(plant, period_s=float(root.get("period", 0.05)))
    for a in root.findall("adapter"):
        port = int(a.get("port", "0"))

        def table(kind: str) -> List[Binding]:
            entries = sorted(
                a.findall(kind), key=lambda e: int(e.get("index", "0"))
            )
            idx = [int(e.get("index", "0")) for e in entries]
            if idx != list(range(len(idx))):
                raise ValueError(f"{kind} entry indices are not dense 0..n-1")
            return [(e.get("device"), e.get("signal")) for e in entries]

        server.add_port(
            table("state"),
            table("command"),
            bind=("127.0.0.1", port),
            protocol=a.get("protocol", "rtds"),
        )
    return server


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import time

    ap = argparse.ArgumentParser(
        description="FREEDM-TPU plant server (pscad-interface replacement)"
    )
    ap.add_argument("config", help="rig.xml path")
    args = ap.parse_args(argv)
    server = load_rig(args.config)
    server.start()
    import json

    addrs = [list(server.port_address(i)) for i in range(len(server._ports))]
    print(json.dumps({"plantserver": addrs}), flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
