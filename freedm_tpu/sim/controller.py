"""Sample plug-and-play device controller (client half of the PnP
session protocol).

Reference: the FREEDM ``device-controller`` companion repository
(``docs/devices/pnp_adapter.rst`` "Sample Device Controller"): a
scriptable process that Hello-joins a DGI's factory port with a set of
devices, then exchanges DeviceStates/DeviceCommands until disconnected.
The script commands there (enable/change/work/dieHorribly) map to plain
method calls here; tests drive them directly.
"""

from __future__ import annotations

import socket
from typing import Dict, List, Optional, Tuple

from freedm_tpu.core.config import NULL_COMMAND

CRLF = "\r\n"


class PnpClient:
    """One device controller: owns devices, speaks the session protocol."""

    def __init__(self, identifier: str, address: Tuple[str, int], timeout_s: float = 5.0):
        self.identifier = identifier
        self.address = address
        self.timeout_s = timeout_s
        # name -> (type, {state signal: value})
        self.devices: Dict[str, Tuple[str, Dict[str, float]]] = {}
        self.last_commands: Dict[Tuple[str, str], float] = {}
        self._sock: Optional[socket.socket] = None

    # -- script commands -----------------------------------------------------
    def enable(self, type_name: str, name: str, **states: float) -> None:
        """Add a device (the ``enable`` script command); reconnect to
        refresh the Hello if already connected."""
        self.devices[name] = (type_name, dict(states))
        if self._sock is not None:
            self.disconnect()

    def change(self, name: str, signal: str, value: float) -> None:
        self.devices[name][1][signal] = value

    # -- protocol ------------------------------------------------------------
    def connect(self) -> str:
        """Hello → first reply header ('Start' on success)."""
        self._sock = socket.create_connection(self.address, timeout=self.timeout_s)
        lines = ["Hello", self.identifier]
        lines += [f"{t} {n}" for n, (t, _) in self.devices.items()]
        self._send(*lines)
        reply = self._recv()
        if not reply or reply[0] != "Start":
            self.close()
            return reply[0] if reply else ""
        return "Start"

    def exchange(self) -> Dict[Tuple[str, str], float]:
        """One DeviceStates → DeviceCommands round; returns the non-NULL
        commands as {(device, signal): value} (also kept in
        ``last_commands``)."""
        lines = ["DeviceStates"]
        for name, (_, states) in self.devices.items():
            for sig, val in states.items():
                lines.append(f"{name} {sig} {val}")
        self._send(*lines)
        reply = self._recv()
        if not reply or reply[0] != "DeviceCommands":
            raise ConnectionError(f"expected DeviceCommands, got {reply[:1]}")
        out: Dict[Tuple[str, str], float] = {}
        for line in reply[1:]:
            if not line.strip():
                continue
            name, sig, raw = line.split()
            value = float(raw)
            if abs(value - NULL_COMMAND) > 0.5:
                out[(name, sig)] = value
        self.last_commands = out
        return out

    def disconnect(self) -> None:
        """PoliteDisconnect (graceful; the server frees the slots)."""
        if self._sock is None:
            return
        try:
            self._send("PoliteDisconnect")
            self._recv()  # PoliteDisconnect / Accepted
        except (OSError, ConnectionError):
            pass
        self.close()

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- wire ----------------------------------------------------------------
    def _send(self, *lines: str) -> None:
        assert self._sock is not None, "not connected"
        self._sock.sendall((CRLF.join(lines) + CRLF + CRLF).encode("ascii"))

    def _recv(self) -> List[str]:
        assert self._sock is not None, "not connected"
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionError("server closed")
            buf += chunk
        return buf.split(b"\r\n\r\n", 1)[0].decode("ascii").split(CRLF)
