"""Small text-input helpers shared by the config parsers."""

from __future__ import annotations

import os
from pathlib import Path
from typing import Union


def read_source(source: Union[str, Path], marker: str) -> str:
    """Accept a filesystem path or raw config text; return the text.

    Disambiguation order: a :class:`~pathlib.Path` is always a path; a
    string naming an existing file is a path; anything else is raw text
    of the target format.  ``marker`` (a substring characteristic of the
    format, e.g. ``"<"`` for XML) is only a fallback check: a marker-free
    non-existent string that also looks like a pathname (single token, no
    newline) raises ``FileNotFoundError`` rather than being misparsed as
    config text.
    """
    if isinstance(source, Path):
        return source.read_text()
    text = str(source)
    if os.path.exists(text):
        return Path(text).read_text()
    # Nonexistent but path-shaped — a single line without the format
    # marker that is one token or contains a path separator — is a
    # typo'd path, not config text.
    pathlike = (
        "\n" not in text
        and marker not in text
        and (" " not in text or "/" in text or "\\" in text)
    )
    if pathlike:
        raise FileNotFoundError(f"config source not found: {text!r}")
    return text
