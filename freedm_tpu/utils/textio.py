"""Small text-input helpers shared by the config parsers."""

from __future__ import annotations

from pathlib import Path
from typing import Union


def read_source(source: Union[str, Path], marker: str) -> str:
    """Accept a filesystem path or raw config text; return the text.

    ``marker`` is a substring that only appears in raw text of the given
    format (e.g. ``"<"`` for XML, ``"\\n"`` for line-oriented DSLs) —
    if absent, ``source`` is treated as a path.
    """
    text = str(source)
    if marker not in text:
        return Path(source).read_text()
    return text
