"""Complex arithmetic over real arrays.

TPU MXU/VPU hardware has no native complex dtype — and the TPU backend in
this environment rejects ``complex64`` outright — so every phasor quantity
in freedm_tpu is carried as an explicit (re, im) pair of real arrays.  This
is the idiomatic TPU design, not a workaround: a complex matmul lowered by
XLA costs 4 real matmuls + adds anyway, and keeping the parts separate lets
us fuse, shard, and Pallas-kernel them like any other real tensor.

:class:`C` is a NamedTuple (hence a pytree): it flows through ``jit``,
``vmap``, ``scan``, ``while_loop`` and ``shard_map`` transparently, and
supports operator arithmetic so solver code reads like the math.

Replaces the reference's ``arma::cx_mat`` usage throughout
``Broker/src/vvc/`` (e.g. ``DPF_return7.cpp``, ``form_Yabc.cpp``).
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np

ArrayLike = Union[jax.Array, np.ndarray, float]


def default_rdtype(dtype=None):
    """The framework's default real dtype: float64 when x64 is enabled
    (CPU reference/tests), float32 otherwise (TPU). Pass ``dtype`` to
    override."""
    if dtype is not None:
        return dtype
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


class C(NamedTuple):
    """A complex tensor as a (re, im) pair of equal-shape real arrays."""

    re: jax.Array
    im: jax.Array

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, o):
        o = as_c(o)
        return C(self.re + o.re, self.im + o.im)

    def __radd__(self, o):
        return self.__add__(o)

    def __sub__(self, o):
        o = as_c(o)
        return C(self.re - o.re, self.im - o.im)

    def __rsub__(self, o):
        o = as_c(o)
        return C(o.re - self.re, o.im - self.im)

    def __mul__(self, o):
        if isinstance(o, C):
            return C(self.re * o.re - self.im * o.im, self.re * o.im + self.im * o.re)
        return C(self.re * o, self.im * o)

    def __rmul__(self, o):
        return self.__mul__(o)

    def __truediv__(self, o):
        if isinstance(o, C):
            d = o.re * o.re + o.im * o.im
            return C(
                (self.re * o.re + self.im * o.im) / d,
                (self.im * o.re - self.re * o.im) / d,
            )
        return C(self.re / o, self.im / o)

    def __neg__(self):
        return C(-self.re, -self.im)

    # -- structure ----------------------------------------------------------
    def conj(self) -> "C":
        return C(self.re, -self.im)

    def abs2(self) -> jax.Array:
        return self.re * self.re + self.im * self.im

    def abs(self) -> jax.Array:
        return jnp.sqrt(self.abs2())

    def angle(self) -> jax.Array:
        return jnp.arctan2(self.im, self.re)

    @property
    def shape(self):
        return jnp.shape(self.re)

    @property
    def dtype(self):
        return jnp.result_type(self.re)

    def __getitem__(self, idx):
        return C(self.re[idx], self.im[idx])

    def astype(self, dtype) -> "C":
        return C(jnp.asarray(self.re, dtype), jnp.asarray(self.im, dtype))

    def sum(self, axis=None) -> "C":
        return C(jnp.sum(self.re, axis=axis), jnp.sum(self.im, axis=axis))

    def where(self, cond, other=0.0) -> "C":
        o = as_c(other)
        return C(jnp.where(cond, self.re, o.re), jnp.where(cond, self.im, o.im))

    def to_numpy(self) -> np.ndarray:
        """Assemble a host numpy complex array (never runs on device)."""
        return np.asarray(self.re) + 1j * np.asarray(self.im)


def as_c(x, dtype=None) -> C:
    """Coerce a complex/real array-like (or C) into a :class:`C` pair."""
    if isinstance(x, C):
        return x.astype(dtype) if dtype is not None else x
    if isinstance(x, (jax.Array, jnp.ndarray)):
        if jnp.iscomplexobj(x):  # only off-TPU; TPU has no complex dtype
            re, im = jnp.real(x), jnp.imag(x)
        else:
            re, im = x, jnp.zeros_like(x)
    else:
        a = np.asarray(x)
        re, im = np.ascontiguousarray(a.real), np.ascontiguousarray(a.imag)
    if dtype is not None:
        return C(jnp.asarray(re, dtype), jnp.asarray(im, dtype))
    return C(jnp.asarray(re), jnp.asarray(im))


def zeros(shape, dtype=None) -> C:
    z = jnp.zeros(shape, dtype=dtype)
    return C(z, z)


def exp(c: C) -> C:
    """exp(re + j·im) = e^re (cos im + j sin im)."""
    m = jnp.exp(c.re)
    return C(m * jnp.cos(c.im), m * jnp.sin(c.im))


def expj(theta: ArrayLike) -> C:
    """Unit phasor e^{jθ}."""
    theta = jnp.asarray(theta)
    return C(jnp.cos(theta), jnp.sin(theta))


def polar(mag: ArrayLike, theta: ArrayLike) -> C:
    mag = jnp.asarray(mag)
    return C(mag * jnp.cos(theta), mag * jnp.sin(theta))


def matmul(m: ArrayLike, x: C) -> C:
    """Real matrix @ complex operand — two real matmuls (MXU-shaped)."""
    m = jnp.asarray(m)
    return C(m @ x.re, m @ x.im)


def einsum(spec: str, a: C, b: C) -> C:
    """Complex einsum from four real einsums."""
    rr = jnp.einsum(spec, a.re, b.re)
    ii = jnp.einsum(spec, a.im, b.im)
    ri = jnp.einsum(spec, a.re, b.im)
    ir = jnp.einsum(spec, a.im, b.re)
    return C(rr - ii, ri + ir)
