"""Per-request audit trails: receipts x traces x events, joined on trace_id.

The provenance observatory (:mod:`freedm_tpu.core.provenance`) leaves
three JSONL streams behind a serving run:

- **receipts** (``--provenance-log``): one ``provenance.receipt`` record
  per served answer — tier, backend/precision, iterations, residual,
  warm-start source, cache age;
- **traces** (``--trace-log``, per process): the span records, including
  the router's ``serve.route`` span and the replica's ``serve.request``
  span stitched by the wire-propagated context;
- **events** (``--events-log``): the discrete journal —
  ``shadow.mismatch`` records (each carrying the full receipt of the
  answer it indicts), ``serve.cache.loose_accept``, breaker flips, SLO
  breaches.

Each stream answers a different question; none alone answers *"what
exactly happened to request X?"*.  This tool joins all three on
``trace_id`` into one audit trail per request: the receipt that was
served, the cross-process span tree that produced it, and every journal
event that mentions it — so a ``shadow.mismatch`` alert resolves to
the offending request's full story in one command::

    python -m freedm_tpu.tools.audit_report \\
        --receipts receipts.jsonl --trace trace_*.jsonl \\
        --events events.jsonl
    python -m freedm_tpu.tools.audit_report --receipts r.jsonl \\
        --trace t.jsonl --only-flagged --json audit.json

Streams are optional: with only receipts, the report is a tier/latency
roll-up; adding traces attaches span trees; adding events attaches
mismatches.  Unjoinable records (a receipt stamped while tracing was
off has ``trace_id: null``) are counted, never dropped silently.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence

#: Journal events that indict a request (the audit flags these).
_FLAG_EVENTS = ("shadow.mismatch", "serve.cache.loose_accept")


def _read_jsonl(path: str) -> List[dict]:
    """Tolerant JSONL reader: a killed process can truncate its last
    line mid-write, so unparseable lines are skipped, not fatal."""
    out: List[dict] = []
    for line in Path(path).read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def load_receipts(paths: Sequence[str]) -> List[dict]:
    """Receipt records from provenance logs.  Accepts both the journal
    form (``event: provenance.receipt``) and bare receipt lines (the
    ``receipt_log_json`` canonical form used by tests)."""
    out: List[dict] = []
    for path in paths:
        for rec in _read_jsonl(path):
            if rec.get("event") == "provenance.receipt":
                out.append(rec)
            elif "event" not in rec and "tier" in rec and "workload" in rec:
                out.append(rec)
    return out


def load_events(paths: Sequence[str]) -> List[dict]:
    """Journal events, excluding the receipt records themselves (those
    are the left side of the join, not annotations on it)."""
    out: List[dict] = []
    for path in paths:
        for rec in _read_jsonl(path):
            if rec.get("event") and rec["event"] != "provenance.receipt":
                out.append(rec)
    return out


def _event_trace_id(event: dict) -> Optional[str]:
    """An event mentions a request either directly (``trace_id``) or
    through the receipt it carries (``shadow.mismatch``)."""
    tid = event.get("trace_id")
    if tid:
        return str(tid)
    receipt = event.get("receipt")
    if isinstance(receipt, dict) and receipt.get("trace_id"):
        return str(receipt["trace_id"])
    return None


def _span_summary(trace: dict) -> dict:
    """Condense one merged trace (trace_report's build_traces shape)
    into the audit row: tree depth, node list, the root chain."""
    spans = trace["spans"]
    return {
        "spans": len(spans),
        "nodes": sorted({s.get("node", "") for s in spans}),
        "roots": [s["name"] for s in trace["roots"]],
        "duration_ms": round((trace["t1"] - trace["t0"]) * 1e3, 3),
        "tree": [
            {
                "name": s["name"],
                "kind": s.get("kind", ""),
                "node": s.get("node", ""),
                "dur_ms": round((s["t1"] - s["t0"]) * 1e3, 3),
                "parent_id": s.get("parent_id"),
            }
            for s in spans
        ],
    }


def build_audit(
    receipt_paths: Sequence[str],
    trace_paths: Sequence[str] = (),
    event_paths: Sequence[str] = (),
) -> dict:
    """The join: one trail per receipt-bearing trace_id."""
    receipts = load_receipts(receipt_paths)
    events = load_events(event_paths)

    traces: Dict[str, dict] = {}
    if trace_paths:
        from freedm_tpu.tools import trace_report

        spans, clocks = trace_report.load_records(trace_paths)
        trace_report.correct_timestamps(spans, clocks)
        traces = trace_report.build_traces(spans)

    events_by_tid: Dict[str, List[dict]] = {}
    for e in events:
        tid = _event_trace_id(e)
        if tid is not None:
            events_by_tid.setdefault(tid, []).append(e)

    trails: Dict[str, dict] = {}
    untraced = 0
    for r in receipts:
        tid = r.get("trace_id")
        if not tid:
            untraced += 1
            continue
        trail = trails.setdefault(
            str(tid), {"receipts": [], "trace": None, "events": [],
                       "flagged": False},
        )
        trail["receipts"].append(r)
    for tid, trail in trails.items():
        if tid in traces:
            trail["trace"] = _span_summary(traces[tid])
        trail["events"] = events_by_tid.get(tid, [])
        trail["flagged"] = any(
            e.get("event") in _FLAG_EVENTS for e in trail["events"]
        )

    tiers: Dict[str, int] = {}
    for r in receipts:
        tiers[r.get("tier", "?")] = tiers.get(r.get("tier", "?"), 0) + 1
    return {
        "receipts": len(receipts),
        "receipts_by_tier": dict(sorted(tiers.items())),
        "receipts_without_trace_id": untraced,
        "trails": trails,
        "flagged": sorted(
            tid for tid, t in trails.items() if t["flagged"]
        ),
        "events_unjoined": sum(
            1 for e in events if _event_trace_id(e) is None
        ),
    }


def render_text(audit: dict, only_flagged: bool = False) -> str:
    out: List[str] = []
    out.append(
        f"audit: {audit['receipts']} receipts "
        f"({audit['receipts_by_tier']}), "
        f"{len(audit['trails'])} joinable trails, "
        f"{len(audit['flagged'])} flagged"
    )
    if audit["receipts_without_trace_id"]:
        out.append(
            f"  {audit['receipts_without_trace_id']} receipts carry no "
            "trace_id (tracing was off when they were stamped)"
        )
    for tid, trail in sorted(audit["trails"].items()):
        if only_flagged and not trail["flagged"]:
            continue
        r = trail["receipts"][-1]
        flag = "  ** FLAGGED **" if trail["flagged"] else ""
        out.append(
            f"\ntrace {tid}{flag}\n"
            f"  receipt: tier={r.get('tier')} case={r.get('case')} "
            f"backend={r.get('pf_backend')}/{r.get('pf_precision')} "
            f"iters={r.get('iterations')} residual={r.get('residual_pu')} "
            f"solve={r.get('solve_ms')}ms"
        )
        if r.get("warm_source"):
            out.append(f"  warm-start source: {r['warm_source']}")
        tr = trail["trace"]
        if tr is not None:
            out.append(
                f"  trace: {tr['spans']} spans over "
                f"{','.join(tr['nodes'])} roots={tr['roots']} "
                f"({tr['duration_ms']}ms)"
            )
        for e in trail["events"]:
            detail = ""
            if e.get("event") == "shadow.mismatch":
                detail = (f" max_dv_pu={e.get('max_dv_pu')} "
                          f"tol={e.get('tol')}")
            out.append(f"  event: {e.get('event')}{detail}")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Join receipts + traces + journal events into "
        "per-request audit trails"
    )
    ap.add_argument("--receipts", nargs="+", required=True, metavar="PATH",
                    help="provenance receipt JSONL file(s)")
    ap.add_argument("--trace", nargs="*", default=[], metavar="PATH",
                    help="trace JSONL file(s) — router + replicas")
    ap.add_argument("--events", nargs="*", default=[], metavar="PATH",
                    help="event journal JSONL file(s)")
    ap.add_argument("--only-flagged", action="store_true",
                    help="render only trails with an indicting event")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the full JSON artifact here")
    args = ap.parse_args(argv)
    audit = build_audit(args.receipts, args.trace, args.events)
    print(render_text(audit, only_flagged=args.only_flagged))
    if args.json:
        Path(args.json).write_text(json.dumps(audit, indent=2))
    # Exit 1 when any trail is flagged: the tool doubles as a gate.
    return 1 if audit["flagged"] else 0


if __name__ == "__main__":
    sys.exit(main())
