"""Skew-corrected causal round-timeline reconstructor.

Input: one or more per-node trace JSONL files (``--trace-log``, the
``/trace`` route, or ``tools/soak.py``'s per-slice collection), each a
stream of span records and ``clock`` records as written by
:mod:`freedm_tpu.core.tracing`.

What it does, in order:

1. **Merge** every file's spans by ``trace_id`` — a cross-node trace has
   its round/phase spans on the originating node and its recv/handler
   spans on the peers, stitched by the wire-propagated context.  The
   same merge stitches the serve tier's cross-process request trees:
   the router's ``serve.route`` span and the replica's ``serve.request``
   span (parented via the forwarded ``X-Trace-Id``/``X-Span-Id``
   headers) land in different files but the same tree.
2. **Correct timestamps** with each node's clock-sync offset table: the
   ``clock`` records journal the synchronizer's measured offset
   (``virtual_now = clock() + offset``), so adding each node's offset
   (nearest record at or before the span; the earliest one for spans
   recorded before the first measurement) puts all spans on the fleet's
   shared virtual clock.  Without this, a ±seconds host-clock skew makes
   node B's handler appear to run *before* node A sent the message.
3. **Reconstruct** the causal timeline per trace: the span tree in
   corrected time, the **critical path** (the parent chain that ends at
   the trace's latest-ending span — the chain an operator must shorten
   to shorten the round), and **phase-overrun attribution** (which
   node/phase blew its ``timings.cfg`` budget, how often, by how much).
4. **Summarize** phase durations and DCN ack RTTs as p50/p95/p99 via
   the fixed-bucket estimator (:func:`freedm_tpu.core.metrics
   .estimate_quantiles`) — no external tooling needed.

Usage::

    python -m freedm_tpu.tools.trace_report trace_*.jsonl
    python -m freedm_tpu.tools.trace_report trace_*.jsonl --json report.json
    python -m freedm_tpu.tools.trace_report trace_*.jsonl --trace 1a2b3c...
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from freedm_tpu.core.metrics import estimate_quantiles

#: Fixed buckets (seconds) for the p50/p95/p99 estimates.
_SUMMARY_BUCKETS = (
    0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


# ---------------------------------------------------------------------------
# load + clock correction
# ---------------------------------------------------------------------------


def load_records(paths: Sequence[str]) -> Tuple[List[dict], Dict[str, List[Tuple[float, float]]]]:
    """Read trace files into (spans, clock tables).

    The clock table maps node → [(ts, offset_s), ...] sorted by ts;
    unparseable lines are skipped (a killed process can truncate its
    last line mid-write).
    """
    spans: List[dict] = []
    clocks: Dict[str, List[Tuple[float, float]]] = {}
    for path in paths:
        for line in Path(path).read_text(encoding="utf-8").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("rec") == "clock":
                clocks.setdefault(rec.get("node", ""), []).append(
                    (float(rec.get("ts", 0.0)), float(rec.get("offset_s", 0.0)))
                )
            elif "span_id" in rec:
                spans.append(rec)
    for tbl in clocks.values():
        tbl.sort()
    return spans, clocks


def _offset_at(tbl: Optional[List[Tuple[float, float]]], t: float) -> float:
    """The node's offset in force at raw time ``t``: the newest record
    at or before ``t``, or the earliest record for spans predating the
    first measurement (better than assuming zero skew)."""
    if not tbl:
        return 0.0
    off = tbl[0][1]
    for ts, o in tbl:
        if ts <= t:
            off = o
        else:
            break
    return off


def correct_timestamps(
    spans: List[dict],
    clocks: Dict[str, List[Tuple[float, float]]],
    overrides: Optional[Dict[str, float]] = None,
) -> List[dict]:
    """Shift every span onto the shared virtual clock (in place).

    ``overrides`` (``--offsets``) pins a node's offset regardless of its
    journaled table.  The applied correction is kept on the span as
    ``clock_offset_s``.
    """
    for s in spans:
        node = s.get("node", "")
        if overrides is not None and node in overrides:
            off = float(overrides[node])
        else:
            off = _offset_at(clocks.get(node), float(s["t0"]))
        s["t0"] = float(s["t0"]) + off
        s["t1"] = float(s["t1"]) + off
        s["clock_offset_s"] = round(off, 9)
    return spans


# ---------------------------------------------------------------------------
# reconstruction
# ---------------------------------------------------------------------------


def build_traces(spans: List[dict]) -> Dict[str, dict]:
    """Group spans into traces: ``{trace_id: {"spans", "by_id",
    "children", "roots", "t0", "t1"}}``.  Roots are spans whose parent
    is absent from the trace (the round span, or an orphaned subtree
    whose originating node's file was not supplied)."""
    traces: Dict[str, dict] = {}
    for s in spans:
        tr = traces.setdefault(
            s["trace_id"],
            {"spans": [], "by_id": {}, "children": {}, "roots": []},
        )
        if s["span_id"] in tr["by_id"]:
            continue  # overlapping exports (file + /trace scrape) dedup
        tr["spans"].append(s)
        tr["by_id"][s["span_id"]] = s
    for tr in traces.values():
        tr["spans"].sort(key=lambda s: (s["t0"], s["t1"]))
        for s in tr["spans"]:
            pid = s.get("parent_id")
            if pid is not None and pid in tr["by_id"]:
                tr["children"].setdefault(pid, []).append(s)
            else:
                tr["roots"].append(s)
        tr["t0"] = min(s["t0"] for s in tr["spans"])
        tr["t1"] = max(s["t1"] for s in tr["spans"])
    return traces


def critical_path(trace: dict) -> List[dict]:
    """The parent chain ending at the trace's latest-ending span — the
    sequence of causally-linked operations that determined when the
    trace finished (shorten any link, the trace ends earlier)."""
    if not trace["spans"]:
        return []
    cur = max(trace["spans"], key=lambda s: s["t1"])
    chain = [cur]
    by_id = trace["by_id"]
    while True:
        pid = chain[-1].get("parent_id")
        if pid is None or pid not in by_id:
            break
        chain.append(by_id[pid])
    chain.reverse()
    return chain


def cross_node_links(trace: dict) -> int:
    """Parent-child edges whose endpoints live on different nodes — the
    wire-propagated causality the trace context exists to preserve."""
    n = 0
    for s in trace["spans"]:
        pid = s.get("parent_id")
        if pid is not None:
            parent = trace["by_id"].get(pid)
            if parent is not None and parent.get("node") != s.get("node"):
                n += 1
    return n


def overrun_attribution(spans: List[dict]) -> Dict[str, dict]:
    """Aggregate phase-overrun tags per (node, phase): how often each
    phase blew its budget and by how much."""
    out: Dict[str, dict] = {}
    for s in spans:
        if s.get("kind") != "phase":
            continue
        tags = s.get("tags") or {}
        if not tags.get("overrun"):
            continue
        key = f"{s.get('node', '')}/{s['name']}"
        agg = out.setdefault(
            key, {"count": 0, "total_ms": 0.0, "max_ms": 0.0, "rounds": []}
        )
        over = float(tags.get("overrun_ms", 0.0))
        agg["count"] += 1
        agg["total_ms"] = round(agg["total_ms"] + over, 3)
        agg["max_ms"] = round(max(agg["max_ms"], over), 3)
        rnd = tags.get("round")
        if rnd is not None and len(agg["rounds"]) < 50:
            agg["rounds"].append(rnd)
    return out


def _quantile_summary(durations_by_key: Dict[str, List[float]]) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    bounds = np.asarray(_SUMMARY_BUCKETS, np.float64)
    for key, vals in sorted(durations_by_key.items()):
        arr = np.asarray(vals, np.float64)
        idx = np.searchsorted(bounds, arr, side="left")
        counts = np.bincount(idx, minlength=len(bounds) + 1)
        qs = estimate_quantiles(bounds, counts)
        if qs is None:
            continue
        out[key] = {
            "count": int(arr.size),
            "p50_ms": round(qs[0] * 1e3, 3),
            "p95_ms": round(qs[1] * 1e3, 3),
            "p99_ms": round(qs[2] * 1e3, 3),
        }
    return out


def summaries(spans: List[dict]) -> Dict[str, dict]:
    """Fleet-wide p50/p95/p99 of phase durations (per phase name) and
    DCN ack RTTs (per node), from the fixed-bucket estimator."""
    phases: Dict[str, List[float]] = {}
    rtts: Dict[str, List[float]] = {}
    for s in spans:
        if s.get("kind") == "phase":
            phases.setdefault(s["name"], []).append(s["t1"] - s["t0"])
        elif s.get("kind") == "send":
            rtt = (s.get("tags") or {}).get("rtt_s")
            if rtt is not None:
                rtts.setdefault(s.get("node", ""), []).append(float(rtt))
    return {
        "phase_ms": _quantile_summary(phases),
        "ack_rtt_ms": _quantile_summary(rtts),
    }


# ---------------------------------------------------------------------------
# report assembly + rendering
# ---------------------------------------------------------------------------


def report(
    paths: Sequence[str],
    offsets: Optional[Dict[str, float]] = None,
    correct: bool = True,
) -> dict:
    """The full JSON artifact: corrected, merged, reconstructed."""
    spans, clocks = load_records(paths)
    if correct:
        correct_timestamps(spans, clocks, overrides=offsets)
    traces = build_traces(spans)
    trace_out: Dict[str, dict] = {}
    for tid, tr in traces.items():
        cp = critical_path(tr)
        trace_out[tid] = {
            "spans": len(tr["spans"]),
            "nodes": sorted({s.get("node", "") for s in tr["spans"]}),
            "roots": [s["name"] for s in tr["roots"]],
            "duration_ms": round((tr["t1"] - tr["t0"]) * 1e3, 3),
            "cross_node_links": cross_node_links(tr),
            "critical_path": [
                {
                    "name": s["name"],
                    "kind": s.get("kind", ""),
                    "node": s.get("node", ""),
                    "start_ms": round((s["t0"] - tr["t0"]) * 1e3, 3),
                    "dur_ms": round((s["t1"] - s["t0"]) * 1e3, 3),
                }
                for s in cp
            ],
            "tree": tr,  # stripped before JSON dump (internal use)
        }
    return {
        "files": [str(p) for p in paths],
        "nodes": sorted(
            {s.get("node", "") for s in spans} | set(clocks.keys())
        ),
        "clock_offsets_s": {
            n: round(tbl[-1][1], 6) for n, tbl in clocks.items() if tbl
        },
        "spans": len(spans),
        "traces": trace_out,
        "overruns": overrun_attribution(spans),
        "summaries": summaries(spans),
    }


def _render_tree(tr: dict, out: List[str]) -> None:
    t0 = tr["t0"]

    def walk(span: dict, depth: int) -> None:
        tags = span.get("tags") or {}
        extra = []
        if span.get("kind") == "send":
            if "rtt_s" in tags:
                extra.append(f"rtt={tags['rtt_s'] * 1e3:.1f}ms")
            if tags.get("expired"):
                extra.append("EXPIRED")
            retr = sum(
                1 for e in span.get("events", ()) if e.get("name") == "retransmit"
            )
            if retr:
                extra.append(f"retransmits={retr}")
        if tags.get("overrun"):
            extra.append(f"OVERRUN +{tags['overrun_ms']:.1f}ms")
        timers = sum(
            1 for e in span.get("events", ()) if e.get("name") == "timer_fired"
        )
        if timers:
            extra.append(f"timers={timers}")
        out.append(
            "  {:>9.3f}ms {:>9.3f}ms  {}{:<28s} {}{}".format(
                (span["t0"] - t0) * 1e3,
                (span["t1"] - span["t0"]) * 1e3,
                "  " * depth,
                span["name"],
                span.get("node", ""),
                ("  [" + " ".join(extra) + "]") if extra else "",
            )
        )
        for child in sorted(
            tr["children"].get(span["span_id"], ()), key=lambda s: s["t0"]
        ):
            walk(child, depth + 1)

    for root in sorted(tr["roots"], key=lambda s: s["t0"]):
        walk(root, 0)


def render_text(rep: dict, top: int = 3, trace_id: Optional[str] = None) -> str:
    """Human-readable report: summaries, overruns, and the span tree of
    the ``top`` longest traces that have a round root (or one specific
    trace via ``trace_id``)."""
    out: List[str] = []
    out.append(
        f"trace report: {rep['spans']} spans, {len(rep['traces'])} traces, "
        f"nodes: {', '.join(rep['nodes'])}"
    )
    if rep["clock_offsets_s"]:
        out.append(
            "clock offsets (s): "
            + ", ".join(f"{n}={o:+.6f}" for n, o in rep["clock_offsets_s"].items())
        )
    for section, unit in (("phase_ms", "phase"), ("ack_rtt_ms", "ack rtt")):
        rows = rep["summaries"].get(section) or {}
        for key, q in rows.items():
            out.append(
                f"{unit:>8s} {key:<28s} n={q['count']:<6d} "
                f"p50={q['p50_ms']}ms p95={q['p95_ms']}ms p99={q['p99_ms']}ms"
            )
    if rep["overruns"]:
        out.append("phase overruns:")
        for key, agg in sorted(rep["overruns"].items()):
            out.append(
                f"  {key:<36s} count={agg['count']} "
                f"total=+{agg['total_ms']}ms max=+{agg['max_ms']}ms"
            )
    if trace_id is not None:
        chosen = [tid for tid in rep["traces"] if tid.startswith(trace_id)]
    else:
        # Round- or route-rooted traces first, the causally richest
        # (cross-node links) before the merely long: that is where the
        # latency story of a fleet round — or of a routed serve request
        # whose serve.route (router process) and serve.request (replica
        # process) spans merged into one tree — lives.
        rounds_first = sorted(
            rep["traces"],
            key=lambda tid: (
                "round" not in rep["traces"][tid]["roots"]
                and "serve.route" not in rep["traces"][tid]["roots"],
                -rep["traces"][tid]["cross_node_links"],
                -rep["traces"][tid]["duration_ms"],
            ),
        )
        chosen = rounds_first[:top]
    for tid in chosen:
        tr_rep = rep["traces"][tid]
        out.append(
            f"\ntrace {tid}  {tr_rep['duration_ms']}ms  "
            f"spans={tr_rep['spans']}  nodes={','.join(tr_rep['nodes'])}  "
            f"cross-node links={tr_rep['cross_node_links']}"
        )
        _render_tree(tr_rep["tree"], out)
        if len(tr_rep["critical_path"]) > 1:
            out.append("  critical path:")
            for s in tr_rep["critical_path"]:
                out.append(
                    f"    {s['start_ms']:>9.3f}ms +{s['dur_ms']:<9.3f}ms "
                    f"{s['name']} [{s['node']}]"
                )
    return "\n".join(out)


def _strip_internal(rep: dict) -> dict:
    """Drop the in-memory tree objects before JSON serialization."""
    out = dict(rep)
    out["traces"] = {
        tid: {k: v for k, v in tr.items() if k != "tree"}
        for tid, tr in rep["traces"].items()
    }
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge per-node trace files into a skew-corrected "
        "causal round timeline"
    )
    ap.add_argument("files", nargs="+", help="trace JSONL files (one per node)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the full JSON artifact here")
    ap.add_argument("--trace", default=None, metavar="ID",
                    help="render only the trace(s) whose id starts with ID")
    ap.add_argument("--top", type=int, default=3,
                    help="how many round timelines to render (default 3)")
    ap.add_argument("--offsets", default=None, metavar="PATH",
                    help="JSON file {node: offset_s} overriding the "
                         "journaled clock tables")
    ap.add_argument("--no-correct", action="store_true",
                    help="skip the clock-offset correction (raw host clocks)")
    args = ap.parse_args(argv)
    overrides = None
    if args.offsets:
        overrides = {
            str(k): float(v)
            for k, v in json.loads(Path(args.offsets).read_text()).items()
        }
    rep = report(args.files, offsets=overrides, correct=not args.no_correct)
    print(render_text(rep, top=args.top, trace_id=args.trace))
    if args.json:
        Path(args.json).write_text(json.dumps(_strip_internal(rep), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
