"""gridprobe: jaxpr/HLO-level program auditor for freedm_tpu.

gridlint (PR 8) enforces invariants on the *source text*; the contracts
gating the next perf work — which dtypes actually flow through each
traced program, what each program captures as constants, which buffers
could be donated, and how many distinct programs XLA compiles — live in
the *compiler IR*.  gridprobe traces every entrypoint declared in
:data:`freedm_tpu.tools.ir_rules.registry.PROGRAM_REGISTRY` to jaxpr
(and lowered HLO for cost analysis) on the CPU backend with x64
enabled, runs the IR rules (GP001 dtype-flow, GP002 host-transfer,
GP003 constant-capture, GP004 donation-readiness) over each, checks the
host-side float64 oracle surfaces by evaluation, and diffs a **program
inventory** — per-program arg/result dtypes+shapes, primitive counts,
and XLA cost-analysis FLOP/byte estimates — against the checked-in
``freedm_tpu/tools/ir_inventory.json`` (GP006), so a silent
program-count or FLOP blowup fails the build with a readable delta.
A registry entry that no longer builds is itself a finding (GP005).

Usage::

    python -m freedm_tpu.tools.gridprobe                  # audit + diff
    python -m freedm_tpu.tools.gridprobe --write-inventory
    python -m freedm_tpu.tools.gridprobe --format=json
    python -m freedm_tpu.tools.gridprobe --list-programs

Exit codes: 0 clean, 1 findings, 2 bad invocation/internal error —
the same contract as gridlint.  Suppression is declaration, not
comments: a program opts into a mixed-precision boundary
(``allow_dtypes`` + ``boundary_reason``) or out of a rule
(``suppress``) in the registry, where review sees it.  Policy:
docs/static_analysis.md ("IR auditing").
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

# The probe is CPU-only by design (deterministic inventory, no device
# needed); pin the platform before anything imports jax.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from freedm_tpu.tools.ir_rules import all_ir_rules
from freedm_tpu.tools.ir_rules.base import (
    F64Surface,
    Finding,
    ProgramSpec,
    TracedProgram,
    aval_str,
)

INVENTORY_VERSION = 1


def repo_root() -> Path:
    """The repo root the default inventory path resolves against (the
    parent of the installed ``freedm_tpu`` package)."""
    import freedm_tpu

    return Path(freedm_tpu.__file__).resolve().parent.parent


def config_defaults(config_path: Optional[str] = None
                    ) -> Tuple[str, float, float]:
    """(inventory path, const_mb, flops_tol) from GlobalConfig — the
    ``probe-*`` config keys, so embedders and the CLI agree.  Pass a
    ``freedm.cfg`` path (gridprobe's ``--config``) to honor an
    operator's configured values; otherwise the dataclass defaults."""
    from freedm_tpu.core.config import GlobalConfig

    cfg = (GlobalConfig.from_file(config_path) if config_path
           else GlobalConfig())
    return cfg.probe_inventory, cfg.probe_const_mb, cfg.probe_flops_tol


class ProbeResult:
    """Findings plus the traced programs and the freshly built
    inventory (the ``artifacts`` analogue of gridlint's LintResult)."""

    def __init__(self, findings: List[Finding],
                 programs: List[TracedProgram],
                 inventory: dict):
        self.findings = findings
        self.programs = programs
        self.inventory = inventory

    @property
    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "findings": [f.to_dict() for f in self.findings],
            "stats": {
                "programs": len(self.programs),
                "findings_total": len(self.findings),
                "findings_by_rule": self.by_rule,
                "inventory": self.inventory,
            },
        }


# -- registry loading --------------------------------------------------------

def load_registry(module: Optional[str] = None,
                  registry_file: Optional[str] = None):
    """(PROGRAM_REGISTRY, F64_SURFACES) from the default module, a
    dotted module name, or a plain python file (fixture tests)."""
    if registry_file:
        spec = importlib.util.spec_from_file_location(
            "_gridprobe_registry", registry_file
        )
        if spec is None or spec.loader is None:
            raise RuntimeError(f"cannot load registry file {registry_file!r}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(
            module or "freedm_tpu.tools.ir_rules.registry"
        )
    programs = list(getattr(mod, "PROGRAM_REGISTRY", ()))
    surfaces = list(getattr(mod, "F64_SURFACES", ()))
    return programs, surfaces


# -- tracing -----------------------------------------------------------------

def trace_spec(spec: ProgramSpec) -> TracedProgram:
    """Build and trace one registry entry (jaxpr + lowered cost).

    One trace serves both views: ``jit(fn).trace()`` yields the closed
    jaxpr for the rules/inventory AND the lowering for cost analysis —
    tracing is the dominant probe cost, so paying it once per program
    roughly halves every ``make check``.  Falls back to the two-pass
    ``make_jaxpr`` + ``lower`` on jax versions without ``.trace``
    (structurally identical output, verified for jit-of-jit too).
    """
    import jax

    fn, args = spec.build()
    traced = None
    try:
        traced = jax.jit(fn).trace(*args)
        closed = traced.jaxpr
    except AttributeError:
        closed = jax.make_jaxpr(fn)(*args)
    lowered = None
    cost: dict = {}
    try:
        lowered = (traced.lower() if traced is not None
                   else jax.jit(fn).lower(*args))
        raw = lowered.cost_analysis()
        if isinstance(raw, (list, tuple)):  # older jax: one per computation
            raw = raw[0] if raw else {}
        if isinstance(raw, dict):
            cost = {
                "flops": float(raw.get("flops", -1.0)),
                "bytes_accessed": float(raw.get("bytes accessed", -1.0)),
            }
    except Exception:
        # Cost analysis is best-effort (backend-dependent); the jaxpr
        # rules and the structural inventory never depend on it.
        cost = {"flops": -1.0, "bytes_accessed": -1.0}
    return TracedProgram(spec, closed, lowered=lowered, cost=cost)


def _float_leaves(value):
    """Floating numpy leaves of a host-oracle output (tuples walked).
    Builtin python floats are deliberately NOT leaves: they carry no
    dtype evidence of the internal computation, so a surface returning
    only builtins is vacuous — the engine flags it (GP005) and the
    oracle must return numpy float64 instead (``np.float64`` is a
    ``float`` subclass, so callers are unaffected)."""
    import numpy as np

    if isinstance(value, (tuple, list)):
        for v in value:
            yield from _float_leaves(v)
    elif isinstance(value, np.ndarray) and np.issubdtype(
            value.dtype, np.floating):
        yield value
    elif isinstance(value, np.floating):
        yield value


def check_surface(surface: F64Surface) -> List[Finding]:
    """Evaluate one host f64 oracle surface: every floating output leaf
    must be float64 (GP001 at the value level — numpy oracles have no
    jaxpr to walk)."""
    import numpy as np

    try:
        fn, args = surface.build()
        out = fn(*args)
    except Exception as e:
        return [Finding(
            "GP005", surface.where, 1, 0,
            f"[{surface.name}] f64 surface failed to build/evaluate: {e!r}",
            "fix or re-register the surface in ir_rules/registry.py",
        )]
    findings = []
    leaves = list(_float_leaves(out))
    if not leaves:
        # A surface whose output carries no dtype evidence cannot be
        # checked — an unfalsifiable check must fail loudly, not pass.
        return [Finding(
            "GP005", surface.where, 1, 0,
            f"[{surface.name}] f64 surface returned no numpy floating "
            f"leaves to check (builtin float is dtype-blind)",
            "return numpy float64 from the oracle (np.float64 is a "
            "float subclass — callers are unaffected)",
        )]
    for leaf in leaves:
        if leaf.dtype != np.float64:
            findings.append(Finding(
                "GP001", surface.where, 1, 0,
                f"[{surface.name}] host float64 oracle surface returns "
                f"{leaf.dtype.name} (silent demotion)",
                "the oracle must compute and return numpy float64 "
                "regardless of input dtypes",
            ))
    return findings


# -- inventory ---------------------------------------------------------------

def _sig6(v: float) -> float:
    """6-significant-digit rounding: keeps the checked-in file stable
    against sub-ulp cost-model noise without hiding real drift."""
    return float(f"{float(v):.6g}")


def build_inventory(programs: List[TracedProgram],
                    surfaces_out: Dict[str, List[str]]) -> dict:
    import jax

    progs = {}
    for tp in programs:
        prims = tp.primitive_counts()
        progs[tp.spec.name] = {
            "where": tp.spec.where,
            "args": [aval_str(a) for a in tp.in_avals],
            "results": [aval_str(a) for a in tp.out_avals],
            "eqns": sum(prims.values()),
            "primitives": dict(sorted(prims.items())),
            "consts_bytes": tp.consts_bytes(),
            "flops": _sig6(tp.cost.get("flops", -1.0)),
            "bytes_accessed": _sig6(tp.cost.get("bytes_accessed", -1.0)),
            "donation_candidates": [
                list(c) for c in tp.donation_candidates()
            ],
            "donated": tp.donated_args(),
        }
    return {
        "version": INVENTORY_VERSION,
        "jax": jax.__version__,  # recorded for humans, never compared
        "x64": bool(jax.config.jax_enable_x64),
        "programs": dict(sorted(progs.items())),
        "f64_surfaces": dict(sorted(surfaces_out.items())),
    }


#: Absolute slack per scalar column, applied BEFORE the relative
#: tolerance: a zero-baseline column (e.g. a program with no consts)
#: must not turn an 8-byte lowering change into infinite drift — the
#: jax-version noise the relative tolerance is documented to absorb.
_ABS_SLACK = {
    "eqns": 16.0,
    "consts_bytes": 4096.0,
    "flops": 4096.0,
    "bytes_accessed": 4096.0,
}


def _rel_drift(cur: float, rec: float, slack: float) -> Optional[float]:
    """Relative drift of two scalar columns; None when not comparable
    (either side missing/negative — cost analysis unavailable) or when
    the absolute change is within the column's slack."""
    if cur is None or rec is None or cur < 0 or rec < 0:
        return None
    if abs(cur - rec) <= slack:
        return None
    if rec == 0:
        return float("inf")
    return abs(cur - rec) / abs(rec)


def diff_inventory(current: dict, recorded: dict, flops_tol: float,
                   inventory_rel: str) -> List[Finding]:
    """GP006: readable findings for every way the traced program set
    drifted from the checked-in inventory."""

    def f(message: str, hint: str = "") -> Finding:
        return Finding("GP006", inventory_rel, 1, 0, message, hint or (
            "if the change is intended, regenerate with "
            "`python -m freedm_tpu.tools.gridprobe --write-inventory` "
            "and commit the diff"
        ))

    findings: List[Finding] = []
    cur_p = current.get("programs", {})
    rec_p = recorded.get("programs", {})
    for name in sorted(set(rec_p) - set(cur_p)):
        findings.append(f(
            f"program `{name}` is in the inventory but no longer traced "
            f"(registry entry removed/renamed?)"
        ))
    for name in sorted(set(cur_p) - set(rec_p)):
        findings.append(f(
            f"program `{name}` is traced but not in the inventory "
            f"(new program / new shape bucket?)"
        ))
    for name in sorted(set(cur_p) & set(rec_p)):
        cur, rec = cur_p[name], rec_p[name]
        # Structural columns compare exactly (donated included: a
        # dropped donate_argnums is a silent HBM regression, not noise).
        for col in ("args", "results", "donated"):
            if cur.get(col) != rec.get(col):
                findings.append(f(
                    f"program `{name}` {col} drifted: "
                    f"{rec.get(col)} -> {cur.get(col)}"
                ))
        for col in ("eqns", "consts_bytes", "flops", "bytes_accessed"):
            drift = _rel_drift(cur.get(col), rec.get(col),
                               _ABS_SLACK.get(col, 0.0))
            if drift is not None and drift > flops_tol:
                findings.append(f(
                    f"program `{name}` {col} drifted "
                    f"{rec.get(col)} -> {cur.get(col)} "
                    f"({drift:+.0%} vs the {flops_tol:.0%} tolerance)"
                ))
    cur_s = current.get("f64_surfaces", {})
    rec_s = recorded.get("f64_surfaces", {})
    for name in sorted(set(rec_s) - set(cur_s)):
        findings.append(f(f"f64 surface `{name}` no longer registered"))
    for name in sorted(set(cur_s) - set(rec_s)):
        findings.append(f(f"f64 surface `{name}` not in the inventory"))
    return findings


# -- the probe ---------------------------------------------------------------

def run_probe(
    registry: Optional[str] = None,
    registry_file: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    const_mb: Optional[float] = None,
    flops_tol: Optional[float] = None,
    inventory_path: Optional[str] = None,
    inventory_mode: str = "check",  # "check" | "write" | "skip"
    config_path: Optional[str] = None,
) -> ProbeResult:
    """Programmatic entry: trace the registry, run the IR rules, and
    (by default) diff the checked-in inventory."""
    import jax

    # Deterministic inventory contract: CPU backend + x64, regardless
    # of how the host process was launched.  The env pin at module
    # import handles fresh processes; environments whose interpreter
    # start-up pre-imports jax with a device platform need the config
    # route (harmless when the backend is already CPU; best-effort when
    # an embedder already initialized a device backend).
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    jax.config.update("jax_enable_x64", True)

    cfg_inv, cfg_const, cfg_tol = config_defaults(config_path)
    const_mb = cfg_const if const_mb is None else const_mb
    flops_tol = cfg_tol if flops_tol is None else flops_tol
    inv_rel = inventory_path or cfg_inv
    inv_path = Path(inv_rel)
    if not inv_path.is_absolute():
        inv_path = repo_root() / inv_path

    specs, surfaces = load_registry(registry, registry_file)
    findings: List[Finding] = []
    programs: List[TracedProgram] = []
    for spec in specs:
        if not (repo_root() / spec.where).exists():
            findings.append(Finding(
                "GP005", spec.where, 1, 0,
                f"[{spec.name}] registry entry points at a module that "
                f"does not exist",
                "fix the spec's `where` path in ir_rules/registry.py",
            ))
        try:
            programs.append(trace_spec(spec))
        except Exception as e:
            findings.append(Finding(
                "GP005", spec.where, 1, 0,
                f"[{spec.name}] registry entry failed to build/trace: "
                f"{type(e).__name__}: {e}",
                "the registered entrypoint was renamed or its build "
                "broke — fix the entry (orphaned entries are findings "
                "by design, like GL002's HOT_PATHS)",
            ))
        if (spec.allow_dtypes and not spec.boundary_reason):
            findings.append(Finding(
                "GP005", spec.where, 1, 0,
                f"[{spec.name}] declares a mixed-precision boundary "
                f"without a boundary_reason",
                "the declaration is the suppression — say why "
                "(docs/static_analysis.md, declared-boundary policy)",
            ))

    selected = all_ir_rules(const_mb=const_mb)
    if rules:
        wanted = set(rules)
        selected = [r for r in selected if r.id in wanted]
    for tp in programs:
        for rule in selected:
            if rule.id in tp.spec.suppress:
                continue
            findings.extend(rule.check(tp))

    surfaces_out: Dict[str, List[str]] = {}
    # Surfaces are evaluated whenever GP001/GP005 run OR the inventory
    # is in play (their registered set is part of the recorded state —
    # a --rules subset must not masquerade as a surface removal).
    if (rules is None or {"GP001", "GP005"} & set(rules)
            or inventory_mode in ("check", "write")):
        for surface in surfaces:
            sfs = check_surface(surface)
            findings.extend(sfs)
            if not any(x.rule == "GP005" for x in sfs):
                surfaces_out[surface.name] = ["checked-f64"]

    inventory = build_inventory(programs, surfaces_out)
    if inventory_mode == "write":
        inv_path.parent.mkdir(parents=True, exist_ok=True)
        inv_path.write_text(
            json.dumps(inventory, indent=1, sort_keys=True) + "\n",
            encoding="utf-8",
        )
    elif inventory_mode == "check":
        try:
            rel = inv_path.relative_to(repo_root()).as_posix()
        except ValueError:
            rel = str(inv_path)
        if not inv_path.exists():
            findings.append(Finding(
                "GP006", rel, 1, 0,
                "inventory file does not exist",
                "generate it with `python -m freedm_tpu.tools.gridprobe "
                "--write-inventory` and commit it",
            ))
        else:
            try:
                recorded = json.loads(inv_path.read_text(encoding="utf-8"))
            except ValueError as e:
                findings.append(Finding(
                    "GP006", rel, 1, 0,
                    f"inventory file is not valid JSON: {e}",
                    "regenerate with --write-inventory",
                ))
            else:
                findings.extend(
                    diff_inventory(inventory, recorded, flops_tol, rel)
                )

    # ``--rules`` scopes EVERY finding — per-program rules, surface
    # checks, and the engine-level GP005/GP006 — so an iterating
    # developer gets exactly the signal they asked for (default runs
    # pass no subset and see everything).
    if rules:
        wanted_ids = set(rules)
        findings = [f for f in findings if f.rule in wanted_ids]
    findings.sort(key=Finding.sort_key)
    return ProbeResult(findings, programs, inventory)


# -- output / CLI ------------------------------------------------------------

def record_metrics(result: ProbeResult) -> None:
    """``gridprobe_findings_total{rule=...}`` on the process registry,
    mirroring gridlint's contract."""
    try:
        from freedm_tpu.core import metrics as obs
    except Exception:
        return
    for rule_id, count in sorted(result.by_rule.items()):
        obs.GRIDPROBE_FINDINGS.labels(rule_id).inc(count)


def render_text(result: ProbeResult) -> str:
    lines = []
    for f in result.findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
        if f.hint:
            lines.append(f"    hint: {f.hint}")
    by_rule = ", ".join(f"{k}={v}" for k, v in sorted(result.by_rule.items()))
    if result.findings:
        lines.append(
            f"gridprobe: {len(result.findings)} finding(s) over "
            f"{len(result.programs)} program(s) [{by_rule}]"
        )
    else:
        lines.append(
            f"gridprobe: clean ({len(result.programs)} program(s) traced)"
        )
    return "\n".join(lines)


def render_github(result: ProbeResult) -> str:
    lines = []
    for f in result.findings:
        msg = f.message + (f" (hint: {f.hint})" if f.hint else "")
        msg = msg.replace("%", "%25").replace("\n", "%0A")
        lines.append(
            f"::error file={f.path},line={f.line},col={f.col},"
            f"title={f.rule}::{msg}"
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="gridprobe",
        description="jaxpr/HLO-level program auditor (GP001-GP006) "
                    "with a CI-diffed program inventory",
    )
    ap.add_argument("-c", "--config", default=None, metavar="PATH",
                    help="freedm.cfg to read the probe-inventory / "
                         "probe-const-mb / probe-flops-tol keys from "
                         "(flags below override; default: built-in "
                         "defaults)")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text", help="output format (default text)")
    ap.add_argument("--rules", default=None, metavar="IDS",
                    help="comma-separated rule ids to run (default all)")
    ap.add_argument("--registry", default=None, metavar="MODULE",
                    help="dotted registry module (default "
                         "freedm_tpu.tools.ir_rules.registry)")
    ap.add_argument("--registry-file", default=None, metavar="PATH",
                    help="plain python registry file (fixture tests)")
    ap.add_argument("--inventory", default=None, metavar="PATH",
                    help="inventory JSON path (default: the "
                         "probe-inventory config key, relative to the "
                         "repo root)")
    ap.add_argument("--write-inventory", action="store_true",
                    help="regenerate the inventory file instead of "
                         "diffing it (commit the result)")
    ap.add_argument("--no-inventory", action="store_true",
                    help="skip the inventory diff (rules only)")
    ap.add_argument("--const-mb", type=float, default=None, metavar="MB",
                    help="GP003 capture threshold (default: the "
                         "probe-const-mb config key)")
    ap.add_argument("--flops-tol", type=float, default=None, metavar="R",
                    help="relative drift tolerance for the inventory's "
                         "scalar columns (default: the probe-flops-tol "
                         "config key)")
    ap.add_argument("--list-programs", action="store_true",
                    help="print the registered program names and exit")
    args = ap.parse_args(argv)

    if args.list_programs:
        try:
            specs, surfaces = load_registry(args.registry,
                                            args.registry_file)
        except Exception as e:
            print(f"gridprobe: cannot load registry: {e!r}",
                  file=sys.stderr)
            return 2
        for spec in specs:
            tags = []
            if spec.f64:
                tags.append("f64")
            if spec.allow_dtypes:
                tags.append("boundary:" + ",".join(sorted(spec.allow_dtypes)))
            print(f"{spec.name}  ({spec.where})"
                  + (f"  [{' '.join(tags)}]" if tags else ""))
        for surface in surfaces:
            print(f"{surface.name}  ({surface.where})  [f64-surface]")
        return 0

    mode = ("write" if args.write_inventory
            else "skip" if args.no_inventory else "check")
    rules = ([r.strip() for r in args.rules.split(",")]
             if args.rules else None)
    try:
        result = run_probe(
            registry=args.registry,
            registry_file=args.registry_file,
            rules=rules,
            const_mb=args.const_mb,
            flops_tol=args.flops_tol,
            inventory_path=args.inventory,
            inventory_mode=mode,
            config_path=args.config,
        )
    except Exception as e:  # internal error must not masquerade as clean
        print(f"gridprobe: internal error: {e!r}", file=sys.stderr)
        return 2
    record_metrics(result)
    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
    elif args.format == "github":
        out = render_github(result)
        if out:
            print(out)
        print(render_text(result), file=sys.stderr)
    else:
        print(render_text(result))
    return 1 if result.findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `gridprobe ... | head` — not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
