"""CI smoke for the serving stack: boot, query, verify residuals.

Starts a real :class:`~freedm_tpu.serve.ServeServer` on an ephemeral
port, POSTs a small mixed batch of pf / N-1 / VVC queries over HTTP
(several concurrently, so the micro-batcher actually coalesces), and
asserts every answer is 200 with its solver residual below tolerance
and its conservation stamp sane.  One command, exit code 0 iff healthy:

    python -m freedm_tpu.tools.serve_smoke

Used by ``.github/workflows/ci.yml``; also a handy local sanity check
after touching the serve path.
"""

from __future__ import annotations

import json
import sys
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

#: f32-appropriate residual ceiling (CI runs on CPU without x64).
TOL_PU = 1e-3


def _post(port: int, path: str, payload: dict) -> Tuple[int, dict]:
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        with e:
            return e.code, json.loads(e.read())


def main(argv: Optional[List[str]] = None) -> int:
    from freedm_tpu.serve import ServeConfig, ServeServer, Service

    # Pipeline explicitly on (the default, double-buffered shape CI
    # must exercise): an assembly lane feeding per-workload executor
    # lanes.
    svc = Service(ServeConfig(max_batch=8, max_wait_ms=10.0,
                              buckets=(1, 4, 8), pipeline_depth=1))
    srv = ServeServer(svc, port=0).start()
    print(f"[serve-smoke] server on port {srv.port}", flush=True)
    failures: List[str] = []

    def ok(name: str, cond: bool, detail: str = "") -> None:
        print(f"[serve-smoke] {'ok  ' if cond else 'FAIL'} {name}  {detail}",
              flush=True)
        if not cond:
            failures.append(name)

    try:
        queries = [
            ("pf", "/v1/pf", {"case": "case14", "scale": 1.0}),
            ("pf", "/v1/pf", {"case": "case14", "scale": 1.1}),
            ("pf", "/v1/pf", {"case": "case14", "scale": 0.9}),
            ("n1", "/v1/n1", {"case": "case14", "outages": [0, 1]}),
            ("vvc", "/v1/vvc", {"case": "vvc_9bus",
                                "q_ctrl_kvar": [[0.0] * 3] * 8}),
        ]
        # Concurrent POSTs: the three pf queries must coalesce.
        with ThreadPoolExecutor(len(queries)) as pool:
            results = list(pool.map(
                lambda q: (q[0], *_post(srv.port, q[1], q[2])), queries
            ))
        for workload, code, d in results:
            ok(f"{workload}_status_200", code == 200, f"code={code} {d}")
            if code != 200:
                continue
            if workload == "pf":
                ok("pf_residual", d["converged"] and d["residual_pu"] < TOL_PU,
                   f"residual={d['residual_pu']}")
                ok("pf_conservation", 0.0 <= d["p_balance_pu"] < 0.5,
                   f"p_balance={d['p_balance_pu']}")
            elif workload == "n1":
                ok("n1_residuals",
                   d["all_converged"] and d["worst_residual_pu"] < TOL_PU,
                   f"worst={d['worst_residual_pu']}")
            else:
                ok("vvc_residual", d["converged"],
                   f"residual={d['residual']}")
                ok("vvc_baseline", abs(d["loss_delta_kw"]) < 1e-3,
                   f"delta={d['loss_delta_kw']}")
        code, d = _post(srv.port, "/v1/pf", {"case": "bogus"})
        ok("typed_invalid_request",
           code == 400 and d["error"]["type"] == "invalid_request",
           f"code={code}")
        # Incremental tier (ISSUE 10): a repeat of an already-served
        # query must answer from the cache (receipt tier "exact", no
        # batch dispatched — bucket 0).
        code, d = _post(srv.port, "/v1/pf", {"case": "case14", "scale": 1.0})
        ok("cache_exact_repeat",
           code == 200 and d["batch"]["tier"] == "exact"
           and d["batch"]["bucket"] == 0,
           f"batch={d.get('batch')}")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/stats", timeout=10
        ) as r:
            stats = json.loads(r.read())
        ok("stats_served", bool(stats["engines"]),
           f"engines={stats['engines']}")
        ok("stats_pipeline", stats["pipeline_depth"] == 1
           and set(stats["executor_lanes"]) == {"pf", "n1", "vvc", "topo"},
           f"depth={stats['pipeline_depth']} "
           f"lanes={sorted(stats['executor_lanes'])}")
        ok("stats_cache_block",
           stats["cache"]["enabled"] is True
           and stats["cache"]["hits"]["exact"] >= 1,
           f"cache={stats['cache']}")
    finally:
        srv.stop()
        svc.stop()
    print(json.dumps({"serve_smoke_pass": not failures,
                      "failed": failures}), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
