"""gridlint: AST-level invariant checker for the freedm_tpu codebase.

``compileall + pyflakes`` catch syntax and name errors; the invariants
this framework actually runs on — trace purity of jitted solver bodies,
no device syncs in the serving/QSTS hot loops, chunk functions pure in
the timestep index, config keys threaded through CLI + docs, metric /
event / span names matching ``docs/observability.md``, and lock-order
discipline across the threaded modules — are enforced by nothing.
gridlint turns those contracts (pinned in prose in ``docs/solvers.md``,
``docs/scenarios.md``, ``docs/observability.md``) into machine-checked
rules, the correctness-tooling analogue of ``tools/perf_gate.py``.

Zero third-party dependencies (stdlib ``ast``/``tokenize`` only), so it
runs in a bare container before ``pip install`` — the same graceful
posture as the Makefile's pyflakes step.  Each file's tree is walked
once into a shared index (:mod:`freedm_tpu.tools.lint_rules.base`);
rules visit the indexes.

Usage::

    python -m freedm_tpu.tools.gridlint freedm_tpu tests bench.py
    python -m freedm_tpu.tools.gridlint --format=json freedm_tpu
    python -m freedm_tpu.tools.gridlint --list-rules

Exit codes: 0 clean, 1 findings, 2 bad invocation/internal error.

Suppression: ``# gridlint: disable=GL001`` (comma-separated ids, or no
``=RULE`` for all rules) on the flagged line, or on a standalone
comment line directly above it.  Policy: docs/static_analysis.md.

See the rule catalogue in :mod:`freedm_tpu.tools.lint_rules` and
``docs/static_analysis.md`` for the invariant behind each rule ID.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from freedm_tpu.tools.lint_rules import all_rules
from freedm_tpu.tools.lint_rules.base import FileIndex, Finding, ProjectIndex

SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".claude"}


class LintResult:
    """Findings plus rule artifacts (e.g. GL006's lock graph)."""

    def __init__(self, findings: List[Finding], files: List[str],
                 artifacts: Optional[Dict[str, object]] = None):
        self.findings = findings
        self.files = files
        self.artifacts = artifacts or {}

    @property
    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "findings": [f.to_dict() for f in self.findings],
            "stats": {
                "files": len(self.files),
                "findings_total": len(self.findings),
                "findings_by_rule": self.by_rule,
                **{k: v for k, v in self.artifacts.items()},
            },
        }


def iter_py_files(paths: Sequence[str]):
    for p in paths:
        path = Path(p)
        if path.is_file():
            yield path
        elif path.is_dir():
            for sub in sorted(path.rglob("*.py")):
                if not any(part in SKIP_DIRS for part in sub.parts):
                    yield sub


def find_root(paths: Sequence[str], explicit: Optional[str] = None) -> Path:
    """The project root cross-file rules read docs from: ``--root`` if
    given, else the first ancestor of a scanned path containing a
    ``docs`` directory, else the current directory."""
    if explicit:
        return Path(explicit).resolve()
    for p in paths:
        cur = Path(p).resolve()
        if cur.is_file():
            cur = cur.parent
        for cand in (cur, *cur.parents):
            if (cand / "docs").is_dir() and (
                (cand / "freedm_tpu").is_dir() or (cand / "core").is_dir()
                or (cand / "cli.py").is_file()
            ):
                return cand
    return Path.cwd().resolve()


def run_lint(paths: Sequence[str], root: Optional[str] = None,
             rules: Optional[Sequence[str]] = None) -> LintResult:
    """Programmatic entry: lint ``paths``, return a :class:`LintResult`.

    ``rules`` restricts to a subset of rule ids (default: all).
    """
    root_path = find_root(paths, root)
    project = ProjectIndex(root_path)
    findings: List[Finding] = []
    files: List[str] = []
    for path in iter_py_files(paths):
        resolved = path.resolve()
        try:
            rel = resolved.relative_to(root_path).as_posix()
        except ValueError:
            rel = path.as_posix()
        try:
            source = resolved.read_text(encoding="utf-8")
            fi = FileIndex(resolved, rel, source)
        except (OSError, SyntaxError, ValueError) as e:
            findings.append(Finding(
                "GL000", rel, getattr(e, "lineno", 1) or 1, 0,
                f"file could not be parsed: {e!r}",
                "fix the syntax error (compileall would also reject this)",
            ))
            continue
        project.add(fi)
        files.append(rel)

    artifacts: Dict[str, object] = {}
    selected = all_rules()
    if rules:
        wanted = set(rules)
        selected = [r for r in selected if r.id in wanted]
    for rule in selected:
        for f in rule.check(project):
            fi = project.files.get(f.path)
            if fi is not None and fi.suppressed(f.rule, f.line):
                continue
            findings.append(f)
        extra = getattr(rule, "artifacts", None)
        if extra:
            artifacts.update(extra)

    findings.sort(key=Finding.sort_key)
    # Dedupe (a node reachable through two traced roots, say).
    seen = set()
    unique: List[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return LintResult(unique, files, artifacts)


def record_metrics(result: LintResult) -> None:
    """Record finding counts on the process-wide registry
    (``gridlint_findings_total{rule=...}``) when the runtime metrics
    stack is importable — optional, so the linter itself stays
    dependency-free in bare containers."""
    try:
        from freedm_tpu.core import metrics as obs
    except Exception:  # numpy missing in a bare container: stay silent
        return
    for rule_id, count in sorted(result.by_rule.items()):
        obs.GRIDLINT_FINDINGS.labels(rule_id).inc(count)


def render_text(result: LintResult) -> str:
    lines = []
    for f in result.findings:
        loc = f"{f.path}:{f.line}:{f.col}"
        lines.append(f"{loc}: {f.rule} {f.message}")
        if f.hint:
            lines.append(f"    hint: {f.hint}")
    by_rule = ", ".join(f"{k}={v}" for k, v in sorted(result.by_rule.items()))
    if result.findings:
        lines.append(
            f"gridlint: {len(result.findings)} finding(s) in "
            f"{len(result.files)} file(s) [{by_rule}]"
        )
    else:
        lines.append(f"gridlint: clean ({len(result.files)} file(s))")
    return "\n".join(lines)


def render_github(result: LintResult) -> str:
    lines = []
    for f in result.findings:
        msg = f.message + (f" (hint: {f.hint})" if f.hint else "")
        msg = msg.replace("%", "%25").replace("\n", "%0A")
        lines.append(
            f"::error file={f.path},line={f.line},col={f.col},"
            f"title={f.rule}::{msg}"
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="gridlint",
        description="AST-level invariant checker (GL001-GL006) for freedm_tpu",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: freedm_tpu "
                         "tests bench.py, where present)")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text", help="output format (default text)")
    ap.add_argument("--rules", default=None, metavar="IDS",
                    help="comma-separated rule ids to run (default all)")
    ap.add_argument("--root", default=None, metavar="DIR",
                    help="project root for cross-file rules (docs/ lookup; "
                         "default: auto-detected from the scanned paths)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name}")
            if rule.hint:
                print(f"    {rule.hint}")
        return 0

    paths = args.paths
    if not paths:
        paths = [p for p in ("freedm_tpu", "tests", "bench.py")
                 if Path(p).exists()]
        if not paths:
            print("gridlint: no paths given and no default targets found",
                  file=sys.stderr)
            return 2
    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    try:
        result = run_lint(paths, root=args.root, rules=rules)
    except Exception as e:  # internal error must not masquerade as clean
        print(f"gridlint: internal error: {e!r}", file=sys.stderr)
        return 2
    record_metrics(result)
    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
    elif args.format == "github":
        out = render_github(result)
        if out:
            print(out)
        print(render_text(result), file=sys.stderr)
    else:
        print(render_text(result))
    return 1 if result.findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `gridlint ... | head` — not an error
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
