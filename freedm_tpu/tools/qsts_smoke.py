"""CI smoke for the QSTS jobs stack: submit, poll, verify the summary.

Starts a real :class:`~freedm_tpu.serve.ServeServer` with a
:class:`~freedm_tpu.scenarios.jobs.JobManager` on an ephemeral port,
submits a small-S, T=24 study on the 9-bus reference feeder through
``POST /v1/qsts``, polls ``GET /v1/jobs/<id>`` to completion, and
sanity-asserts the summary (violation minutes finite, energy balance
stamped, every lane-step converged).  Typed-error paths (bad spec,
unknown job id) are exercised too.  One command, exit code 0 iff
healthy:

    python -m freedm_tpu.tools.qsts_smoke

Used by ``.github/workflows/ci.yml``; also a handy local sanity check
after touching the scenarios path.
"""

from __future__ import annotations

import json
import math
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

POLL_TIMEOUT_S = 300.0


def _post(port: int, path: str, payload: dict) -> Tuple[int, dict]:
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        with e:
            return e.code, json.loads(e.read())


def _get(port: int, path: str) -> Tuple[int, dict]:
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        with e:
            return e.code, json.loads(e.read())


def main(argv: Optional[List[str]] = None) -> int:
    from freedm_tpu.scenarios.jobs import JobManager
    from freedm_tpu.serve import ServeConfig, ServeServer, Service

    svc = Service(ServeConfig(max_batch=4, buckets=(1, 4)))
    jm = JobManager(workers=1).start()
    srv = ServeServer(svc, port=0, jobs=jm).start()
    print(f"[qsts-smoke] server on port {srv.port}", flush=True)
    failures: List[str] = []

    def ok(name: str, cond: bool, detail: str = "") -> None:
        print(f"[qsts-smoke] {'ok  ' if cond else 'FAIL'} {name}  {detail}",
              flush=True)
        if not cond:
            failures.append(name)

    try:
        code, d = _post(srv.port, "/v1/qsts", {
            "case": "vvc_9bus", "scenarios": 4, "steps": 24,
            "dt_minutes": 60.0, "chunk_steps": 8, "seed": 3,
        })
        ok("submit_202", code == 202 and "job_id" in d, f"code={code} {d}")
        job_id = d.get("job_id", "")
        deadline = time.monotonic() + POLL_TIMEOUT_S
        j = {}
        while time.monotonic() < deadline:
            code, j = _get(srv.port, f"/v1/jobs/{job_id}")
            if code != 200 or j.get("state") in ("completed", "failed",
                                                 "cancelled"):
                break
            time.sleep(0.5)
        ok("job_completed", j.get("state") == "completed",
           f"state={j.get('state')} error={j.get('error')}")
        s = j.get("summary") or {}
        ok("violation_minutes_finite",
           math.isfinite(s.get("violation_bus_minutes_mean", math.nan)),
           f"viol={s.get('violation_bus_minutes_mean')}")
        ok("energy_balance_stamped", s.get("energy_balance_ok") is True,
           f"loss_kwh_mean={s.get('energy_loss_kwh_mean')}")
        ok("all_converged", s.get("lane_steps_not_converged") == 0,
           f"nonconv={s.get('lane_steps_not_converged')}")
        ok("progress_counted",
           j.get("chunks_done") == j.get("chunks_total") == 3,
           f"chunks={j.get('chunks_done')}/{j.get('chunks_total')}")

        code, d = _post(srv.port, "/v1/qsts", {"case": "vvc_9bus",
                                               "scenarios": 10**9})
        ok("typed_invalid_spec",
           code == 400 and d["error"]["type"] == "invalid_request",
           f"code={code}")
        code, d = _get(srv.port, "/v1/jobs/deadbeef")
        ok("typed_job_not_found",
           code == 404 and d["error"]["type"] == "not_found",
           f"code={code}")
        code, d = _get(srv.port, "/stats")
        ok("stats_counts_jobs",
           code == 200 and d.get("qsts", {}).get("jobs", 0) >= 1,
           f"qsts={d.get('qsts')}")
    finally:
        srv.stop()
        jm.stop()
        svc.stop()
    print(json.dumps({"qsts_smoke_pass": not failures,
                      "failed": failures}), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
