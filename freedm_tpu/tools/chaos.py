"""Chaos rig: a replicated serving fleet under a deterministic fault
schedule.

The robustness acceptance test for the router tier (docs/robustness.md):

- spawn N (default 3) **replica** processes — each a full serve stack
  (:mod:`freedm_tpu.serve`) with its own incremental cache, prewarmed,
  on an ephemeral port;
- front them with the cache-affinity failover router
  (:mod:`freedm_tpu.serve.router`) in this process;
- drive a **closed-loop mixed load** through the router while the
  fault schedule runs: one replica carries a ``serve.replica.kill``
  fault (its K-th request hard-exits the process — a deterministic
  mid-load kill), another a low-rate ``serve.exec.crash`` (typed
  batch failures the router must retry);
- assert the contract: **zero non-typed client failures** (every
  response the client sees is a 200 or a typed
  ``{"error": {"type": ...}}`` — never a connection reset), request
  success ratio **>= 0.999** via router retries, the victim's breaker
  opened, and the **cache hit ratio on the victim's hash range
  retained within 10%** after failover (the survivor warms the moved
  range in one pass).

One command, one pass/fail JSON artifact::

    python -m freedm_tpu.tools.chaos --out chaos.json

``--replica`` is the internal entry the rig spawns: a serve-only
process that prints ``{"replica_port": N}`` and drains gracefully on
SIGTERM (stops admitting, finishes in-flight, exits 0).
``tools/soak.py --chaos`` folds this rig's artifact into the soak
artifact.  Exit code 0 iff every check passed.

The rig also proves the **consistent-cut snapshot** machinery
(:mod:`freedm_tpu.core.snapshot`) adversarially: ≥3 marker-coordinated
cuts taken *during* the fault schedule must audit clean (zero
``snapshot_violations_total``), a deliberately uncoordinated torn
scrape of the same fleet must flag ≥1 bogus ticket-accounting
violation, and a cut taken after the kill must come back as a typed
``incomplete`` within the snapshot deadline — never a hung initiator.

Every replica also runs the **shadow verifier**
(:mod:`freedm_tpu.core.provenance`) at rate 1.0 on the cache tiers, and
the rig gates on **zero shadow mismatches**: a chaos run that passes
the robustness checks but serves even one numerically-wrong answer
fails.  ``--shadow-negative`` runs the inverse proof — inject
``serve.cache.corrupt`` with the inline residual verify loosened
(``ServeConfig.cache_verify_tol``), and assert the shadow lane CATCHES
the corrupt answer the inline check no longer can.  A verifier that
cannot fail a corrupted fleet proves nothing about a clean one.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Cases the mixed load spreads over the ring (distinct engines, all
#: cheap at CPU scale).  mesh cases give the ring enough distinct keys
#: that every replica owns some range.
LOAD_CASES = ("case14", "case_ieee30", "mesh20", "mesh24", "mesh28")


# ---------------------------------------------------------------------------
# Replica entry (--replica): serve-only process with graceful drain
# ---------------------------------------------------------------------------


def run_replica(fault_spec: Optional[str] = None,
                prewarm: str = "pf/case14",
                shadow_rate: Optional[str] = None) -> int:
    from freedm_tpu.core.faults import FAULTS
    from freedm_tpu.serve import ServeConfig, ServeServer, Service

    if fault_spec:
        FAULTS.configure(fault_spec)
    if shadow_rate:
        from freedm_tpu.core.provenance import PROVENANCE

        PROVENANCE.configure(enabled=True, rate_spec=shadow_rate,
                             replica=f"chaos-{os.getpid()}")
    svc = Service(ServeConfig(
        max_batch=16, queue_depth=256,
        prewarm=(prewarm,) if prewarm else (),
    ))
    srv = ServeServer(svc, port=0).start()
    done = threading.Event()

    def _drain(signum, frame):
        # Graceful drain: /healthz flips to draining (the router stops
        # sending new work), admitted tickets finish, then exit 0.
        srv.begin_drain()
        done.set()

    signal.signal(signal.SIGTERM, _drain)
    print(json.dumps({"replica_port": srv.port, "pid": os.getpid()}),
          flush=True)
    while not done.wait(0.2):
        pass
    srv.stop()
    svc.stop()
    return 0


# ---------------------------------------------------------------------------
# The rig
# ---------------------------------------------------------------------------


class _Check:
    def __init__(self):
        self.results: List[Dict] = []

    def record(self, name: str, ok: bool, detail: str = "") -> bool:
        self.results.append({"name": name, "ok": bool(ok), "detail": detail})
        print(f"[chaos] {'ok ' if ok else 'FAIL'} {name}  {detail}",
              flush=True)
        return ok

    @property
    def passed(self) -> bool:
        return all(r["ok"] for r in self.results)


class _Replica:
    def __init__(self, index: int, fault_spec: Optional[str], env: dict,
                 shadow_rate: Optional[str] = None):
        self.index = index
        self.fault_spec = fault_spec
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "freedm_tpu.tools.chaos", "--replica"]
            + (["--fault-spec", fault_spec] if fault_spec else [])
            + (["--shadow-rate", shadow_rate] if shadow_rate else []),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True,
        )
        self.port: Optional[int] = None

    def wait_port(self, timeout_s: float) -> Optional[int]:
        deadline = time.monotonic() + timeout_s

        def reader():
            line = self.proc.stdout.readline()
            try:
                self.port = json.loads(line)["replica_port"]
            except (ValueError, KeyError):
                pass

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        while time.monotonic() < deadline and self.port is None:
            if self.proc.poll() is not None:
                return None
            time.sleep(0.2)
        return self.port

    @property
    def id(self) -> Optional[str]:
        return f"127.0.0.1:{self.port}" if self.port is not None else None

    def alive(self) -> bool:
        return self.proc.poll() is None


class _Loader:
    """Closed-loop mixed load through the router.  Every completed
    request is classified: ok (200), typed (a JSON error body with a
    type), or UNTYPED (connection reset / unparseable — the class
    that must be zero)."""

    def __init__(self, router_port: int, n_threads: int = 4,
                 cases=LOAD_CASES):
        self.port = router_port
        self.n_threads = n_threads
        self.cases = tuple(cases)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self.ok = 0
        self.typed: Dict[str, int] = {}
        self.untyped = 0

    def _classify(self, status: int, body: bytes) -> None:
        with self._lock:
            if status == 200:
                self.ok += 1
                return
            try:
                code = json.loads(body)["error"]["type"]
            except (ValueError, KeyError, TypeError):
                self.untyped += 1
                return
            self.typed[code] = self.typed.get(code, 0) + 1

    def _loop(self, seed: int) -> None:
        import random
        import urllib.error
        import urllib.request

        rng = random.Random(seed)
        while not self._stop.is_set():
            case = rng.choice(self.cases)
            body = json.dumps({
                "case": case,
                "scale": round(rng.choice((1.0, 1.0, 0.95, 1.05)), 3),
                "timeout_s": 60,
            }).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{self.port}/v1/pf", data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=90) as r:
                    self._classify(r.status, r.read())
            except urllib.error.HTTPError as e:
                payload = e.read()
                e.close()
                self._classify(e.code, payload)
            except Exception:
                # Transport-level failure surfaced to the CLIENT: the
                # router exists to make this impossible.
                with self._lock:
                    self.untyped += 1

    def start(self) -> "_Loader":
        self._threads = [
            threading.Thread(target=self._loop, args=(i,), daemon=True)
            for i in range(self.n_threads)
        ]
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> Dict[str, object]:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=95)
        total = self.ok + sum(self.typed.values()) + self.untyped
        return {
            "requests": total,
            "ok": self.ok,
            "typed": dict(sorted(self.typed.items())),
            "untyped": self.untyped,
            "success_ratio": round(self.ok / total, 6) if total else 0.0,
        }


def _get_json(port: int, path: str, timeout_s: float = 10.0) -> Dict:
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout_s
        ) as r:
            return json.loads(r.read())
    except Exception:
        return {}


def _cache_counts(replicas: List[_Replica]) -> Dict[str, float]:
    """Summed exact/delta hits + misses over the LIVE replicas' /stats
    cache blocks — the fleet-wide hit-ratio window."""
    out = {"exact": 0.0, "delta": 0.0, "misses": 0.0}
    for rep in replicas:
        if not rep.alive() or rep.port is None:
            continue
        cache = _get_json(rep.port, "/stats").get("cache") or {}
        hits = cache.get("hits") or {}
        out["exact"] += float(hits.get("exact", 0) or 0)
        out["delta"] += float(hits.get("delta", 0) or 0)
        out["misses"] += float(cache.get("misses", 0) or 0)
    return out


def _shadow_counts(replicas: List[_Replica]) -> Dict[str, float]:
    """Summed provenance/shadow counters over the LIVE replicas' /stats
    blocks — the fleet-wide numerical-honesty window.  (The killed
    victim's counters die with it; a mismatch it had flagged before the
    kill is invisible here, which is why the soak ALSO gates per-slice.)
    """
    out = {"receipts": 0.0, "verified": 0.0, "mismatches": 0.0}
    for rep in replicas:
        if not rep.alive() or rep.port is None:
            continue
        prov = _get_json(rep.port, "/stats").get("provenance") or {}
        receipts = prov.get("receipts") or {}  # per-tier dict
        out["receipts"] += sum(float(v) for v in receipts.values())
        out["verified"] += float(prov.get("shadow_verified", 0) or 0)
        out["mismatches"] += float(prov.get("shadow_mismatches", 0) or 0)
    return out


def _post_pf(router_port: int, case: str, timeout_s: float = 90.0) -> bool:
    import urllib.error
    import urllib.request

    body = json.dumps({"case": case, "timeout_s": timeout_s}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{router_port}/v1/pf", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s + 5) as r:
            return r.status == 200
    except urllib.error.HTTPError as e:
        e.close()
        return False
    except Exception:
        return False


def _hit_ratio_probe(router_port: int, cases: List[str],
                     replicas: List[_Replica],
                     repeats: int = 16) -> Optional[float]:
    """(exact+delta hits)/lookups across a repeats x cases window of
    identical queries driven through the router.  16 repeats per key
    keeps one post-failover warming miss per key inside the 10%
    retention budget ((R-1)/R = 0.9375)."""
    before = _cache_counts(replicas)
    for _ in range(repeats):
        for c in cases:
            _post_pf(router_port, c)
    after = _cache_counts(replicas)
    hits = (after["exact"] - before["exact"]) + (
        after["delta"] - before["delta"]
    )
    lookups = hits + (after["misses"] - before["misses"])
    return round(hits / lookups, 4) if lookups > 0 else None


def _torn_scrape_proof(check: _Check, replica: _Replica,
                       primed_case: str) -> int:
    """The negative proof: an UNCOORDINATED scrape of a live replica —
    admission counters from one instant glued to offer counters from a
    later one, with traffic in between — must flag ticket-accounting
    violations the marker-coordinated cut does not.  Returns the bogus
    violation count."""
    from freedm_tpu.core import snapshot as snap

    early = _get_json(replica.port, "/stats").get("ledger") or {}
    # Deterministic traffic between the two scrapes: every request
    # moves `offered`, so the torn document cannot balance.
    for _ in range(4):
        _post_pf_replica(replica.port, primed_case)
    late = _get_json(replica.port, "/stats").get("ledger") or {}
    torn = snap.torn_serve_doc(early, late)
    cut = snap.assemble_cut("torn-proof", [{
        "snapshot_id": "torn-proof", "node": replica.id or "replica",
        "status": "complete", "serve": torn,
    }])
    violations = snap.audit_cut(cut)
    check.record(
        "torn_scrape_flags_violation",
        any(v.check == "ticket_accounting" for v in violations),
        f"violations={[v.check for v in violations]} "
        f"early_offered={early.get('offered')} "
        f"late_offered={late.get('offered')}",
    )
    return len(violations)


def _post_pf_replica(port: int, case: str, timeout_s: float = 90.0) -> bool:
    """One pf request DIRECTLY to a replica (bypassing the router) —
    the torn proof needs traffic that lands on one known ledger."""
    import urllib.error
    import urllib.request

    body = json.dumps({"case": case, "timeout_s": timeout_s}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/pf", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s + 5) as r:
            return r.status == 200
    except urllib.error.HTTPError as e:
        e.close()
        return False
    except Exception:
        return False


def run_chaos(n_replicas: int = 3, load_s: float = 6.0,
              post_kill_s: float = 8.0, clients: int = 4,
              kill_after: int = 80, out: Optional[str] = None,
              workdir: Optional[str] = None) -> Dict:
    """The kill-one-of-N acceptance scenario; returns the artifact."""
    import tempfile

    from freedm_tpu.serve.router import Router, RouterConfig, RouterServer

    t0 = time.monotonic()
    wd = workdir or tempfile.mkdtemp(prefix="freedm_chaos_")
    cache_dir = os.path.join(wd, "jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    env = dict(
        os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR=cache_dir,
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1",
    )
    check = _Check()
    # The DETERMINISTIC fault schedule: replica 0 hard-exits on its
    # kill_after-th request (the mid-load kill); replica 1 carries a
    # low-rate executor crash (typed internal failures the router's
    # retry absorbs).  Replica 2 is clean.
    specs: List[Optional[str]] = [
        f"seed=11;serve.replica.kill:1:after={kill_after}:max=1",
        "seed=12;serve.exec.crash:0.02:max=5",
    ] + [None] * max(n_replicas - 2, 0)
    # Every replica shadow-verifies ALL cache-tier answers (rate 1.0 on
    # exact + delta): under a fault schedule is exactly when a silently
    # wrong cached answer would slip out, so chaos gates on zero
    # mismatches in addition to the robustness checks.
    shadow_rate = "seed=13;0.0,exact=1.0,delta=1.0"
    replicas = [_Replica(i, specs[i] if i < len(specs) else None, env,
                         shadow_rate=shadow_rate)
                for i in range(n_replicas)]
    router_server = None
    loader = None
    summary: Dict[str, object] = {}
    shadow: Dict[str, float] = {}
    cuts: List[Dict] = []
    try:
        ports = [rep.wait_port(300.0) for rep in replicas]
        check.record("replicas_up", all(p is not None for p in ports),
                     f"ports={ports}")
        if not all(p is not None for p in ports):
            raise RuntimeError("replica spawn failed")
        router = Router(
            [rep.id for rep in replicas],
            RouterConfig(
                probe_interval_s=0.5,
                breaker_failures=2,
                breaker_cooldown_s=1.0,
                default_timeout_s=60.0,
                # A dead replica fails the snapshot POST fast; the
                # bound only matters for a STALLED one, and 5 s keeps
                # the post-kill incomplete-cut proof snappy.
                snapshot_timeout_s=5.0,
            ),
        )
        router_server = RouterServer(router, port=0).start()

        # Prime every case once through the router (absorbs each
        # replica's first-touch engine compile outside the windows).
        primed = all(
            _post_pf(router_server.port, c, timeout_s=240.0)
            for c in LOAD_CASES
        )
        check.record("fleet_primed", primed, f"cases={LOAD_CASES}")

        # The victim is replica 0 (the kill fault).  The affected hash
        # range = the load cases it owns.
        victim = replicas[0]
        # At most 2 probe cases: the pre-fault probe's requests DRAW on
        # the victim's kill schedule (every POST counts), and priming +
        # 16 x len(cases) must stay comfortably under kill_after so the
        # kill lands in the LOAD window, not during the probe.
        victim_cases = [
            c for c in LOAD_CASES if router.ring.owner(c) == victim.id
        ][:2]
        if not victim_cases:
            # Every ring is different (ephemeral ports): fall back to
            # probing whichever range the victim does own among a wider
            # candidate set, else the first case (retention still
            # meaningful — the range simply did not move).
            victim_cases = [
                c for c in (f"mesh{n}" for n in range(20, 60, 2))
                if router.ring.owner(c) == victim.id
            ][:2] or [LOAD_CASES[0]]
            for c in victim_cases:
                _post_pf(router_server.port, c, timeout_s=240.0)
        pre_ratio = _hit_ratio_probe(
            router_server.port, victim_cases, replicas
        )
        check.record("pre_fault_hit_ratio_measured", pre_ratio is not None,
                     f"ratio={pre_ratio} cases={victim_cases}")

        # Closed-loop mixed load; the schedule kills replica 0 mid-way.
        # The victim's own hash range is always part of the mix — the
        # ephemeral-port ring may have handed it none of LOAD_CASES,
        # and a victim that sees no traffic can neither be killed by
        # its schedule nor prove failover.
        loader = _Loader(
            router_server.port, n_threads=clients,
            cases=tuple(LOAD_CASES) + tuple(victim_cases),
        ).start()
        # Consistent cuts DURING the fault schedule: marker-coordinated
        # snapshots taken while the mixed load (and replica 1's
        # exec.crash faults) are in flight must audit clean — every
        # per-replica ledger/cache scrape is atomic under its own lock,
        # so the assembled cut balances at any instant.
        clean = 0
        for _ in range(10):
            if not victim.alive():
                break
            try:
                cut = router.snapshot()
            except Exception:
                break
            cuts.append(cut)
            if cut["status"] == "complete" and not cut["violations"]:
                clean += 1
            if clean >= 3:
                break
            time.sleep(0.15)
        check.record(
            "three_consistent_cuts_under_load", clean >= 3,
            f"cuts={len(cuts)} complete_clean={clean} "
            f"violations={sum(len(c['violations']) for c in cuts)}",
        )
        check.record(
            "zero_snapshot_violations",
            all(not c["violations"] for c in cuts),
            f"violations={[v for c in cuts for v in c['violations']]}",
        )
        # The torn-read negative proof on the SAME fleet, mid-load: the
        # clean replica (no fault spec) takes the uncoordinated scrape.
        _torn_scrape_proof(check, replicas[-1], LOAD_CASES[0])
        time.sleep(load_s)
        killed = not victim.alive()
        deadline = time.monotonic() + post_kill_s
        while time.monotonic() < deadline:
            time.sleep(0.5)
            killed = killed or not victim.alive()
        summary = loader.stop()
        loader = None
        check.record(
            "replica_killed_by_schedule", killed,
            f"victim={victim.id} rc={victim.proc.poll()}",
        )
        # Mid-fleet-death snapshot: a cut taken with the victim dead
        # must come back as a TYPED incomplete (the dead replica a
        # status=incomplete stub) within the snapshot deadline — a hung
        # initiator here is exactly the failure mode the bound exists
        # to kill.  The surviving nodes' docs still audit clean.
        snap_t0 = time.monotonic()
        try:
            post_cut = router.snapshot()
        except Exception as e:  # noqa: BLE001
            post_cut = {"status": f"error:{e!r}", "violations": [None]}
        snap_elapsed = time.monotonic() - snap_t0
        check.record(
            "post_kill_cut_typed_incomplete",
            post_cut["status"] == "incomplete"
            and not post_cut["violations"]
            and snap_elapsed < 5.0 + 2.0,
            f"status={post_cut['status']} elapsed_s={snap_elapsed:.2f} "
            f"violations={post_cut['violations']}",
        )
        cuts.append(post_cut)
        check.record(
            "zero_untyped_client_failures", summary["untyped"] == 0,
            f"untyped={summary['untyped']} over {summary['requests']}",
        )
        check.record(
            "success_ratio_over_999",
            summary["requests"] > 0 and summary["success_ratio"] >= 0.999,
            f"ratio={summary['success_ratio']} typed={summary['typed']}",
        )
        states = router.states()
        vstate = states.get(victim.id, {})
        check.record(
            "victim_breaker_opened_or_marked_down",
            vstate.get("breaker") == "open" or not vstate.get("healthy", True),
            f"victim={vstate}",
        )
        # Post-failover: the victim's range now lands on survivors; one
        # warming pass per key, then hits — retention within 10%.
        post_ratio = _hit_ratio_probe(
            router_server.port, victim_cases, replicas
        )
        retained = (
            pre_ratio is not None and post_ratio is not None
            and post_ratio >= pre_ratio * 0.9
        )
        check.record(
            "cache_hit_ratio_retained_after_failover", retained,
            f"pre={pre_ratio} post={post_ratio} range={victim_cases}",
        )
        # Numerical honesty under chaos: the shadow verifier audited
        # the cache tiers at rate 1.0 throughout — any mismatch means a
        # wrong answer was SERVED, and no robustness score excuses that.
        shadow = _shadow_counts(replicas)
        check.record(
            "shadow_zero_mismatches",
            shadow["receipts"] > 0 and shadow["mismatches"] == 0,
            f"receipts={shadow['receipts']:.0f} "
            f"verified={shadow['verified']:.0f} "
            f"mismatches={shadow['mismatches']:.0f}",
        )
        # Graceful drain: SIGTERM a SURVIVOR — it must flip /healthz to
        # draining, finish its in-flight work, and exit 0 (the rolling-
        # restart path), while the remaining replica keeps answering.
        drained = next(rep for rep in replicas[1:] if rep.alive())
        drained.proc.send_signal(signal.SIGTERM)
        drain_deadline = time.monotonic() + 15.0
        while drained.alive() and time.monotonic() < drain_deadline:
            time.sleep(0.2)
        check.record(
            "sigterm_drain_exits_clean", drained.proc.poll() == 0,
            f"replica={drained.id} rc={drained.proc.poll()}",
        )
        router.probe_once()
        still_ok = _post_pf(router_server.port, victim_cases[0],
                            timeout_s=120.0)
        check.record("fleet_serves_after_drain", still_ok,
                     f"case={victim_cases[0]}")
        router_stats = router.stats()
    except Exception as e:  # noqa: BLE001 — the artifact must exist
        check.record("rig_error", False, repr(e))
        router_stats = {}
    finally:
        if loader is not None:
            summary = loader.stop()
        if router_server is not None:
            router_server.stop()
        for rep in replicas:
            if rep.alive():
                rep.proc.terminate()
        deadline = time.monotonic() + 10.0
        for rep in replicas:
            while rep.alive() and time.monotonic() < deadline:
                time.sleep(0.1)
            if rep.alive():
                rep.proc.kill()
    artifact = {
        "pass": check.passed,
        "replicas": n_replicas,
        "duration_s": round(time.monotonic() - t0, 1),
        "checks": check.results,
        "load": summary,
        "router": router_stats,
        "shadow": shadow,
        "snapshots": [
            {"snapshot_id": c.get("snapshot_id"), "status": c.get("status"),
             "capture_ms": c.get("capture_ms"),
             "violations": c.get("violations")}
            for c in cuts
        ],
        "fault_specs": specs[:n_replicas],
        "workdir": wd,
    }
    if out:
        with open(out, "w") as fh:
            json.dump(artifact, fh, indent=2)
    print(json.dumps({"chaos_pass": artifact["pass"],
                      "failed": [c["name"] for c in check.results
                                 if not c["ok"]]}), flush=True)
    return artifact


# ---------------------------------------------------------------------------
# Shadow-verifier negative proof (--shadow-negative)
# ---------------------------------------------------------------------------


def run_shadow_negative(out: Optional[str] = None) -> Dict:
    """Prove the shadow verifier CATCHES a wrong served answer.

    The inverse of the zero-mismatch gate: with the delta tier's inline
    residual verify loosened to uselessness
    (``ServeConfig(cache_verify_tol=1e9)`` — the knob exists only for
    this proof) and ``serve.cache.corrupt`` firing on every delta
    candidate, a small-delta request is SERVED numerically wrong.  The
    checks assert, in order: the corrupt answer really went out on the
    delta tier, the inline bypass journaled ``serve.cache.loose_accept``
    (so the scenario is the one we think it is), and the shadow lane's
    independent f64 re-solve flagged it — ``shadow_mismatch_total``
    incremented and a ``shadow.mismatch`` event carrying the answer's
    full receipt landed in the journal.  In-process, one Service, no
    router: the proof is about the verifier, not the fleet.
    """
    from freedm_tpu.core import metrics as obs
    from freedm_tpu.core.faults import FAULTS
    from freedm_tpu.core.provenance import PROVENANCE
    from freedm_tpu.core.tracing import TRACER
    from freedm_tpu.serve import ServeConfig, Service

    t0 = time.monotonic()
    check = _Check()
    # Tracing on, so the receipt carries a real trace_id and the
    # mismatch-event join below proves the receipt names the request.
    TRACER.configure(enabled=True, node="shadow-negative")
    FAULTS.configure("seed=5;serve.cache.corrupt:1:arg=0.05")
    PROVENANCE.configure(enabled=True, rate_spec="seed=3;0.0,delta=1.0",
                         replica="shadow-negative")
    svc = Service(ServeConfig(max_batch=4, queue_depth=64,
                              cache_verify_tol=1e9))
    n_bus = 14
    base_p = [0.0] * n_bus
    base_q = [0.0] * n_bus
    # One bus nudged 0.05 pu: rank-1, well inside the delta tier's
    # rank/magnitude gates, far outside the 1e-4 pu mismatch tolerance
    # once the corrupt fault adds 0.05 to |V|.
    bumped_p = list(base_p)
    bumped_p[3] = 0.05
    receipt = None
    try:
        prime = svc.request(
            "pf", {"case": "case14", "p_inj": base_p, "q_inj": base_q,
                   "timeout_s": 300.0}, timeout_s=300.0)
        prime_tier = (prime.provenance or {}).get("tier")
        check.record("prime_full_solve", prime_tier == "full",
                     f"tier={prime_tier}")
        served = svc.request(
            "pf", {"case": "case14", "p_inj": bumped_p, "q_inj": base_q,
                   "timeout_s": 300.0}, timeout_s=300.0)
        receipt = served.provenance
        check.record("corrupt_answer_served_on_delta_tier",
                     (receipt or {}).get("tier") == "delta",
                     f"receipt={receipt}")
        loose = [e for e in obs.EVENTS.tail(500)
                 if e.get("event") == "serve.cache.loose_accept"]
        check.record(
            "inline_verify_bypassed", len(loose) > 0,
            f"loose_accept events={len(loose)} "
            + (f"residual={loose[-1].get('residual_pu')}" if loose else ""),
        )
        # The shadow lane re-solves on its own jitted f64 program; the
        # first item pays the compile, so the drain budget is generous.
        drained = PROVENANCE.drain(timeout_s=300.0)
        check.record("shadow_queue_drained", drained, "")
        stats = PROVENANCE.stats_block()
        check.record(
            "shadow_caught_mismatch",
            stats.get("shadow_mismatches", 0) >= 1,
            f"verified={stats.get('shadow_verified')} "
            f"mismatches={stats.get('shadow_mismatches')}",
        )
        mism = [e for e in obs.EVENTS.tail(500)
                if e.get("event") == "shadow.mismatch"]
        ok_evt = bool(mism) and isinstance(mism[-1].get("receipt"), dict) \
            and mism[-1]["receipt"].get("trace_id") is not None \
            and mism[-1]["receipt"].get("trace_id") \
            == (receipt or {}).get("trace_id")
        check.record(
            "mismatch_event_carries_receipt", ok_evt,
            f"events={len(mism)} "
            + (f"max_dv_pu={mism[-1].get('max_dv_pu')}" if mism else ""),
        )
    except Exception as e:  # noqa: BLE001 — the artifact must exist
        check.record("rig_error", False, repr(e))
    finally:
        svc.stop()
        PROVENANCE.reset()
        FAULTS.configure(None)
        TRACER.configure(enabled=False)
    artifact = {
        "pass": check.passed,
        "scenario": "shadow_negative",
        "duration_s": round(time.monotonic() - t0, 1),
        "checks": check.results,
        "receipt": receipt,
    }
    if out:
        with open(out, "w") as fh:
            json.dump(artifact, fh, indent=2)
    print(json.dumps({"shadow_negative_pass": artifact["pass"],
                      "failed": [c["name"] for c in check.results
                                 if not c["ok"]]}), flush=True)
    return artifact


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Replicated-serving chaos rig (router + fault schedule)"
    )
    ap.add_argument("--replica", action="store_true",
                    help="internal: run as one serve replica")
    ap.add_argument("--fault-spec", default=None,
                    help="fault schedule for --replica mode")
    ap.add_argument("--shadow-rate", default=None, metavar="SPEC",
                    help="shadow-verify rate spec for --replica mode")
    ap.add_argument("--shadow-negative", action="store_true",
                    help="run the shadow-verifier negative proof instead "
                         "of the kill-one-of-N scenario")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--load", type=float, default=6.0,
                    help="pre/post-kill load window, seconds")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--kill-after", type=int, default=80,
                    help="victim hard-exits on its Nth request")
    ap.add_argument("--out", default=None, metavar="PATH")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)
    if args.replica:
        return run_replica(fault_spec=args.fault_spec,
                           shadow_rate=args.shadow_rate)
    if args.shadow_negative:
        artifact = run_shadow_negative(out=args.out)
        return 0 if artifact["pass"] else 1
    artifact = run_chaos(
        n_replicas=args.replicas, load_s=args.load,
        post_kill_s=args.load + 2.0, clients=args.clients,
        kill_after=args.kill_after, out=args.out, workdir=args.workdir,
    )
    return 0 if artifact["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
