"""Multi-process federation soak: N slices + plantserver + kills/rejoins.

The reference's scale rig is ``Broker/testing/run_test.sh`` — five DGI
processes (``MultipleDgi3A..E``) wired by ``--add-host`` against one
table server, run 15 s, then killed, with pass/fail judged by a human
reading logs.  This tool is the framework's equivalent, automated
(VERDICT r4 item 8):

- one plantserver process (live feeder physics, RTDS lock-step TCP);
- N federated ``python -m freedm_tpu`` processes over real UDP with
  lossy links (network.xml reliability injection), each owning a
  **different row segment** of the shared feeder's VVC devices (the
  reference's s1→SST2-4 partition shape, ``Broker_s1`` master/slave
  deployment);
- scripted fault schedule: kill a member → regroup, restart → re-merge,
  kill the LEADER → re-election + slave VVC fallback, restart → full
  group again;
- machine-checked assertions on the slices' own JSON round summaries:
  group membership counts, leadership change, power conservation
  (Σ gateway ≈ 0), and VVC liveness through the master's death;
- SLO verdicts, not just counters: every slice runs the in-process SLO
  monitor (``core/slo.py``), and the rig asserts that the member-kill
  phase produced at least one ``slo.breach`` → ``slo.recovered`` pair
  in some slice's journal (a restarted slice's kernel re-warm trips
  the broker-overrun objective, then recovers warm); the artifact also
  carries ``/slo`` + ``/profile`` snapshots;
- one command, one pass/fail JSON artifact:

    python -m freedm_tpu.tools.soak --slices 5 --out soak.json

Exit code 0 iff every check passed.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Feeder rows carrying per-phase Sst_{a,b,c} VVC devices, partitioned
# round-robin across slices (heterogeneous segments: every slice
# actuates a different subset of the one physical feeder).
VVC_ROWS = (2, 3, 4, 5, 6, 7)


def _free_ports(n: int, sock_type: int) -> List[int]:
    socks = [socket.socket(socket.AF_INET, sock_type) for _ in range(n)]
    for s in socks:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def free_udp_ports(n: int) -> List[int]:
    return _free_ports(n, socket.SOCK_DGRAM)


def free_tcp_ports(n: int) -> List[int]:
    return _free_ports(n, socket.SOCK_STREAM)


#: Unlabelled counters lifted from each slice's /metrics scrape into the
#: soak artifact — the transport/solver columns of the SOAK trajectory.
SCRAPE_KEYS = (
    "dcn_sends_total",
    "dcn_retransmits_total",
    "dcn_acks_total",
    "dcn_expired_total",
    "dcn_reconnects_total",
    "dcn_datagrams_in_total",
    "dcn_datagrams_out_total",
    "broker_rounds_total",
    "federation_migrations_total",
    "serve_shed_total",
    "qsts_jobs_submitted_total",
    "qsts_resumes_total",
)


def scrape_slice_metrics(port: int, timeout_s: float = 3.0) -> Dict[str, float]:
    """Pull the SCRAPE_KEYS counters from one slice's metrics endpoint;
    an unreachable slice (killed, still compiling) returns {}."""
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=timeout_s
        ) as r:
            text = r.read().decode()
    except Exception:
        return {}
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, _, value = line.rpartition(" ")
        if name in SCRAPE_KEYS:
            try:
                out[name] = float(value)
            except ValueError:
                pass
    return out


def read_events_jsonl(path: Path) -> List[Dict]:
    """The slice's event journal (append survives its kill/restart);
    [] when missing/torn."""
    if not path.exists():
        return []
    out: List[Dict] = []
    for line in path.read_text().splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # the kill can tear the last line
        if isinstance(rec, dict):
            out.append(rec)
    return out


def slo_breach_recover_pairs(events: List[Dict],
                             after_ts: float = 0.0) -> List[Dict]:
    """Matched (slo.breach, slo.recovered) pairs per objective, breach
    no earlier than ``after_ts`` — the soak's "this slice went out of
    objective and came back" evidence."""
    open_breach: Dict[str, Dict] = {}
    pairs: List[Dict] = []
    for ev in events:
        name = ev.get("event")
        slo = ev.get("slo")
        if name == "slo.breach" and ev.get("ts", 0.0) >= after_ts:
            open_breach[slo] = ev
        elif name == "slo.recovered" and slo in open_breach:
            b = open_breach.pop(slo)
            pairs.append({
                "slo": slo,
                "breach_ts": b.get("ts"),
                "recovered_ts": ev.get("ts"),
                "breach_value": b.get("value"),
                "burn_fast": b.get("burn_fast"),
            })
    return pairs


def scrape_json_route(port: int, route: str, timeout_s: float = 3.0) -> Dict:
    """One JSON GET against a slice's metrics server ({} on failure)."""
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{route}", timeout=timeout_s
        ) as r:
            return json.loads(r.read())
    except Exception:
        return {}


def sum_roofline(snaps: Dict[str, Dict]) -> Dict:
    """Fleet-summed roofline block: per-program dispatch counts and
    blocked device wall added across every slice's ``/roofline``
    snapshot (the fleet's attribution, not one process's)."""
    fleet: Dict[str, Dict] = {}
    enabled = False
    for snap in snaps.values():
        if not snap:
            continue
        enabled = enabled or bool(snap.get("enabled"))
        for name, row in snap.get("programs", {}).items():
            agg = fleet.setdefault(name, {
                "dispatches": 0, "blocked_dispatches": 0, "device_s": 0.0,
            })
            agg["dispatches"] += int(row.get("dispatches") or 0)
            agg["blocked_dispatches"] += int(
                row.get("blocked_dispatches") or 0
            )
            agg["device_s"] = round(
                agg["device_s"] + float(row.get("device_s") or 0.0), 6
            )
    return {"enabled": enabled, "programs": fleet}


_CACHE_DIR: Optional[str] = None


def _env() -> Dict[str, str]:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    if _CACHE_DIR:
        # All slices (and restarted slices) run identical JAX programs:
        # a shared persistent compilation cache turns the N-process
        # startup compile storm into one compile + N-1 cache hits.
        env["JAX_COMPILATION_CACHE_DIR"] = _CACHE_DIR
        env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "1"
    return env


@dataclasses.dataclass
class SliceSpec:
    uuid: str
    port: int
    rows: List[int]
    generation: float
    drain: float
    plant_port: Optional[int] = None
    cfg_path: Optional[Path] = None
    metrics_port: Optional[int] = None  # the slice's /metrics TCP port
    serve_port: Optional[int] = None  # the slice's what-if query TCP port


class Check:
    def __init__(self):
        self.results: List[Dict] = []

    def record(self, name: str, ok: bool, detail: str = "") -> bool:
        self.results.append({"name": name, "ok": bool(ok), "detail": detail})
        status = "ok " if ok else "FAIL"
        print(f"[soak] {status} {name}  {detail}", flush=True)
        return ok

    @property
    def passed(self) -> bool:
        return all(r["ok"] for r in self.results)


class Proc:
    """One federated slice process with a summary-line reader.

    Kill/restart without losing the slice's well-known UDP port (ADVICE
    r5: the rejoin/re-merge checks were flaky because another process
    could grab the port between ``kill()`` and the restart): ``kill()``
    immediately re-binds the port on a SO_REUSEADDR reservation socket,
    which closes the kill→restart window; ``start()`` releases it just
    before spawning.  The remaining gap — child startup until its
    endpoint binds — is covered by the spawn retry: a bind loser exits
    immediately and is relaunched (with the reservation re-taken in
    between).  The port must stay stable across a restart because every
    OTHER slice's config names this slice as ``host:port``.
    """

    def __init__(self, spec: SliceSpec):
        self.spec = spec
        self.lines: List[Dict] = []
        self.proc: Optional[subprocess.Popen] = None
        self._holder: Optional[socket.socket] = None
        self._started_once = False

    def _reserve_port(self) -> None:
        if self._holder is not None:
            return
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind(("127.0.0.1", self.spec.port))
        except OSError:
            s.close()  # transient holder; start() retries the spawn
            return
        self._holder = s

    def _release_port(self) -> None:
        if self._holder is not None:
            self._holder.close()
            self._holder = None

    def _spawn(self) -> None:
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "freedm_tpu", "-c", str(self.spec.cfg_path),
             "--summary-every", "5", "--realtime"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=_env(), text=True,
        )
        threading.Thread(target=self._pump, daemon=True).start()

    def start(self) -> "Proc":
        restart = self._started_once
        self._started_once = True
        attempts = 3 if restart else 1
        for attempt in range(attempts):
            self._release_port()
            self._spawn()
            if not restart:
                return self
            # A bind loser dies within seconds; a healthy slice keeps
            # running (its first summary can take much longer under a
            # cold JIT cache, so only an EXIT counts as failure).
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline:
                if self.proc.poll() is not None:
                    break
                time.sleep(0.1)
            if self.proc.poll() is None:
                return self
            print(f"[soak] restart of {self.spec.uuid} exited rc="
                  f"{self.proc.returncode}, retry {attempt + 1}", flush=True)
            self._reserve_port()
            time.sleep(0.5)
        return self

    def _pump(self):
        proc = self.proc
        for line in proc.stdout:
            if line.startswith("{"):
                try:
                    self.lines.append(json.loads(line))
                except ValueError:
                    pass

    def last(self) -> Dict:
        return self.lines[-1] if self.lines else {}

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self):
        if self.alive():
            self.proc.kill()
            self.proc.wait(timeout=10)
        # Hold the port for the rejoin (released by the next start()).
        self._reserve_port()


class ServeLoader:
    """Closed-loop background query load against one slice's what-if
    endpoint (``freedm_tpu.serve``, ``serve-port``).

    Runs for the whole fault schedule: the point is that serving and the
    broker round loop coexist through kills, rejoins, and re-elections.
    Counts completed queries, typed 429 sheds, and transport errors
    (expected while the target slice is down or still compiling); the
    summary folds into the soak artifact's ``metrics`` object.
    """

    def __init__(self, port: int, case: str = "case14", n_conns: int = 2):
        self.port = int(port)
        self.case = case
        self.n_conns = int(n_conns)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self.ok = 0
        self.shed = 0
        self.errors = 0
        self._t0: Optional[float] = None
        self._elapsed: Optional[float] = None

    def _loop(self, seed: int) -> None:
        import random
        import urllib.error
        import urllib.request

        rng = random.Random(seed)
        url = f"http://127.0.0.1:{self.port}/v1/pf"
        while not self._stop.is_set():
            body = json.dumps(
                {"case": self.case, "scale": round(rng.uniform(0.9, 1.1), 3)}
            ).encode()
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": "application/json"}
            )
            try:
                # Generous timeout: the first query compiles the solver
                # inside the slice process.
                with urllib.request.urlopen(req, timeout=60) as r:
                    json.loads(r.read())
                with self._lock:
                    self.ok += 1
            except urllib.error.HTTPError as e:
                e.close()
                with self._lock:
                    if e.code == 429:
                        self.shed += 1
                    else:
                        self.errors += 1
            except Exception:
                with self._lock:
                    self.errors += 1
                # The slice is down (fault schedule) or not yet serving.
                self._stop.wait(0.5)

    def start(self) -> "ServeLoader":
        self._t0 = time.monotonic()
        self._threads = [
            threading.Thread(target=self._loop, args=(i,), daemon=True)
            for i in range(self.n_conns)
        ]
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> Dict[str, float]:
        if self._elapsed is None and self._t0 is not None:
            self._elapsed = time.monotonic() - self._t0
        self._stop.set()
        for t in self._threads:
            t.join(timeout=65)
        dur = self._elapsed or 0.0
        return {
            "serve_requests_ok": float(self.ok),
            "serve_qps_achieved": round(self.ok / dur, 2) if dur else 0.0,
            "serve_client_shed_429": float(self.shed),
            "serve_client_errors": float(self.errors),
            "serve_window_s": round(dur, 1),
        }


class CacheProbe:
    """Delta-heavy repeat-query phase against one slice's serve port
    (ISSUE 10): a few distinct base injection vectors, each re-queried
    several times (exact-hit traffic) interleaved with rank-1
    perturbations (delta-hit traffic).  The slice's ``/stats`` cache
    block is snapshotted before/after so the asserted hit ratio covers
    exactly this window, and the client-side p50s give the artifact a
    delta-vs-full speedup figure measured through the real HTTP path.
    """

    #: Known bus counts; any other case is learned from a
    #: ``return_state`` response at run time (no hardcoded crash).
    N_BUS = {"case14": 14, "case_ieee30": 30}

    def __init__(self, port: int, case: str = "case14"):
        self.port = int(port)
        self.case = case
        self.n = self.N_BUS.get(case)

    def _learn_n(self) -> Optional[int]:
        import urllib.request

        body = json.dumps({"case": self.case, "return_state": True,
                           "timeout_s": 120}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/v1/pf", data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                return len(json.loads(r.read())["v"])
        except Exception:
            return None

    def _cache_stats(self) -> Dict:
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{self.port}/stats", timeout=30
            ) as r:
                return json.loads(r.read()).get("cache") or {}
        except Exception:
            return {}

    def _query(self, p_inj) -> Optional[float]:
        import urllib.request

        body = json.dumps({
            "case": self.case, "p_inj": list(p_inj),
            "q_inj": [0.0] * self.n, "timeout_s": 120,
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/v1/pf", data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                json.loads(r.read())
            return time.perf_counter() - t0
        except Exception:
            return None

    def run(self, bases: int = 3, repeats: int = 6,
            perturbed: int = 12) -> Optional[Dict[str, float]]:
        import random

        if self.n is None:
            self.n = self._learn_n()
        if self.n is None:
            return None  # case unreachable/unknown: skip, don't crash
        before = self._cache_stats()
        if not before.get("enabled", False):
            return None
        base_vecs = []
        for b in range(bases):
            p = [0.0] * self.n
            p[1 + b % (self.n - 1)] = -0.05 * (b + 1)
            base_vecs.append(p)
        # Prime each base (cold full solves through the serve path).
        prime_lats = [self._query(p) for p in base_vecs]
        # Repeat phase: identical vectors — the exact tier's traffic.
        exact_lats = []
        for _ in range(repeats):
            for p in base_vecs:
                exact_lats.append(self._query(p))
        # Perturbed phase: rank-1 deltas — the SMW delta tier's traffic.
        rng = random.Random(5)
        delta_lats = []
        for j in range(perturbed):
            p = list(base_vecs[j % bases])
            p[2 + j % (self.n - 3)] += rng.uniform(-0.02, 0.02)
            delta_lats.append(self._query(p))
        after = self._cache_stats()

        def count(d, *path):
            cur: object = d
            for k in path:
                cur = (cur or {}).get(k, 0) if isinstance(cur, dict) else 0
            return float(cur or 0)

        hits_e = count(after, "hits", "exact") - count(before, "hits", "exact")
        hits_d = count(after, "hits", "delta") - count(before, "hits", "delta")
        hits_w = count(after, "hits", "warm") - count(before, "hits", "warm")
        misses = count(after, "misses") - count(before, "misses")
        lookups = hits_e + hits_d + hits_w + misses

        def p50(lats):
            ok = sorted(x for x in lats if x is not None)
            return round(ok[len(ok) // 2] * 1e3, 3) if ok else None

        out: Dict[str, float] = {
            "serve_cache_probe_hit_ratio": (
                round((hits_e + hits_d) / lookups, 4) if lookups else 0.0
            ),
            "serve_cache_probe_lookups": lookups,
            "serve_cache_probe_exact_hits": hits_e,
            "serve_cache_probe_delta_hits": hits_d,
            "serve_cache_probe_exact_p50_ms": p50(exact_lats),
            "serve_cache_probe_delta_p50_ms": p50(delta_lats),
            "serve_cache_probe_full_p50_ms": p50(prime_lats),
        }
        full = out["serve_cache_probe_full_p50_ms"]
        delta = out["serve_cache_probe_delta_p50_ms"]
        if full and delta:
            out["serve_cache_probe_delta_speedup"] = round(full / delta, 2)
        return out


class QstsProbe:
    """One QSTS job driven across the kill/restart schedule.

    The study is submitted (with a stable ``job_key``) to the slice the
    schedule is about to kill; after the slice restarts, the SAME spec
    is resubmitted and the server resumes it from its chunk-boundary
    checkpoint (``qsts-checkpoint-dir`` in the slice config).  At the
    end the finished summary is compared against an uninterrupted
    reference run computed in this process — they must match EXACTLY
    (timing keys aside), which is the QSTS resume-determinism contract
    (deterministic profiles + exact chunk-state roundtrip).
    """

    #: Long enough to straddle the kill (16 chunks), small enough for a
    #: CPU slice: 4 scenarios x 4 days of 15-min steps on case14.
    SPEC = {
        "case": "case14", "scenarios": 4, "steps": 384,
        "dt_minutes": 15.0, "chunk_steps": 24, "seed": 11,
        "job_key": "soakprobe",
    }

    #: The jobs-API route the spec submits to.
    SUBMIT_PATH = "/v1/qsts"

    def __init__(self, port: int):
        self.port = int(port)
        self.job_id: Optional[str] = None
        self.submitted = False
        self.resubmitted = False
        self.chunks_before_kill = 0

    def _post(self, path: str, payload: dict, timeout_s: float = 60.0):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read())

    def submit(self, timeout_s: float = 120.0) -> bool:
        """Submit (or resubmit after a restart); tolerant of a slice
        that is still compiling — the caller records the outcome."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                d = self._post(self.SUBMIT_PATH, self.SPEC)
                self.job_id = d["job_id"]
                self.resubmitted = self.submitted
                self.submitted = True
                return True
            except Exception:
                time.sleep(2.0)
        return False

    def _poll(self) -> Dict:
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{self.port}/v1/jobs/{self.job_id}",
            timeout=10,
        ) as r:
            return json.loads(r.read())

    def wait_chunks(self, n: int, timeout_s: float) -> bool:
        """Block until the job has completed >= n chunks (i.e. a chunk
        checkpoint is on disk) — the kill must interrupt a study that
        has real state to resume, or the resume path isn't exercised."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                j = self._poll()
                self.chunks_before_kill = int(j.get("chunks_done", 0))
                if self.chunks_before_kill >= n:
                    return True
                if j.get("state") in ("completed", "failed", "cancelled"):
                    return self.chunks_before_kill >= n
            except Exception:
                pass
            time.sleep(1.0)
        return False

    def wait(self, timeout_s: float) -> Dict:
        """Poll the job to a terminal state; {} if unreachable."""
        deadline = time.monotonic() + timeout_s
        last: Dict = {}
        while time.monotonic() < deadline:
            try:
                last = self._poll()
                if last.get("state") in ("completed", "failed", "cancelled"):
                    return last
            except Exception:
                pass
            time.sleep(2.0)
        return last

    def reference_summary(self) -> Dict:
        """The uninterrupted run, computed in THIS process (same jax
        platform/dtype as the slices: CPU default precision)."""
        from freedm_tpu.scenarios.engine import StudySpec, run_study

        spec = {k: v for k, v in self.SPEC.items() if k != "job_key"}
        return run_study(StudySpec(**spec))

    @staticmethod
    def strip_timing(summary: Dict) -> Dict:
        from freedm_tpu.scenarios.engine import strip_timing

        return strip_timing(summary)


class TopoProbe(QstsProbe):
    """One topology sweep driven across the kill/restart schedule —
    the switching-screen twin of :class:`QstsProbe`: submitted (stable
    ``job_key``) to the slice the schedule kills, resubmitted after the
    restart so the server resumes it from its chunk checkpoint, and the
    finished summary compared EXACTLY (timing keys aside) against an
    uninterrupted reference computed in this process.  Variant
    generation is a pure function of the spec, so the resumed shortlist
    must match bit-for-bit — the topo resume-determinism contract.
    """

    #: Enough chunks to straddle the kill on a busy CPU slice: every
    #: rank-2 variant of a 120-bus mesh at the smallest chunk size.
    #: AC verify off — the resume contract under test is the SCREEN's
    #: (the shortlist + counters), and the sparse verifier's compile
    #: cost would dominate the soak budget.
    SPEC = {
        "case": "mesh120", "max_rank": 2, "chunk_variants": 256,
        "top_k": 8, "seed": 11, "ac_verify": False,
        "job_key": "topoprobe",
    }

    SUBMIT_PATH = "/v1/topo/sweep"

    def reference_summary(self) -> Dict:
        """The uninterrupted sweep, computed in THIS process (same jax
        platform/dtype as the slices)."""
        from freedm_tpu.pf.topo import TopoSweepSpec, run_topo_sweep

        spec = {k: v for k, v in self.SPEC.items() if k != "job_key"}
        return run_topo_sweep(TopoSweepSpec(**spec))

    @staticmethod
    def strip_timing(summary: Dict) -> Dict:
        from freedm_tpu.pf.topo import strip_topo_timing

        return strip_topo_timing(summary)


class AgentsProbe(QstsProbe):
    """One agent-population QSTS job driven across the kill/restart
    schedule — the grid-edge twin of :class:`QstsProbe`: the closed
    loop's per-agent state lanes (EV SoC, thermostat temperature,
    inverter Q, DR engagement) ride the chunk checkpoint, so the
    killed-and-resumed study must STILL match the uninterrupted
    reference exactly (docs/agents.md resume contract)."""

    #: Two days of 15-min steps on case14 with a small mixed
    #: population: long enough to straddle the kill, cheap enough for
    #: a busy CPU slice stepping 180 agent lanes per scenario-step.
    SPEC = {
        "case": "case14", "scenarios": 4, "steps": 192,
        "dt_minutes": 15.0, "chunk_steps": 24, "seed": 13,
        "agents": {"ev": 60, "thermostat": 50, "inverter": 40, "dr": 30},
        "job_key": "agentsprobe",
    }

    def reference_summary(self) -> Dict:
        """The uninterrupted run, computed in THIS process (same jax
        platform/dtype as the slices)."""
        from freedm_tpu.scenarios.agents import AgentSpec
        from freedm_tpu.scenarios.engine import StudySpec, run_study

        spec = {k: v for k, v in self.SPEC.items() if k != "job_key"}
        spec["agents"] = AgentSpec(**spec["agents"])
        return run_study(StudySpec(**spec))


class SnapshotProbe:
    """One marker-coordinated fleet snapshot taken mid-fault-schedule
    (docs/snapshots.md): POST ``/snapshot`` on one slice's metrics
    server initiates the Chandy–Lamport cut over the live federation;
    every slice's per-node cut document is then collected from its own
    ``GET /snapshot?id=``, assembled, and audited in this process.  The
    soak gates on the assembled cut being complete with ZERO invariant
    violations — under 20% UDP loss and after two kill/rejoin cycles is
    exactly when an inconsistent capture would show.
    ``--no-snapshot-probe`` is the escape hatch."""

    def __init__(self, slices: List[tuple]):
        #: (uuid, metrics_port) per live slice; the first one initiates.
        self.slices = list(slices)

    @staticmethod
    def _initiate(port: int, timeout_s: float = 10.0) -> Optional[str]:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/snapshot", data=b"", method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return json.loads(r.read()).get("snapshot_id")
        except Exception:
            return None

    def run(self, timeout_s: float = 60.0) -> Optional[Dict]:
        from freedm_tpu.core import snapshot as snap

        if not self.slices:
            return None
        sid = self._initiate(self.slices[0][1])
        if sid is None:
            return None
        # Each node's coordinator stores its own doc when its cut
        # closes (all markers back); poll every slice until all report
        # or the budget runs out — a missing doc is an incomplete node.
        deadline = time.monotonic() + timeout_s
        docs: Dict[str, Dict] = {}
        while time.monotonic() < deadline and len(docs) < len(self.slices):
            for uuid, port in self.slices:
                if uuid in docs:
                    continue
                doc = scrape_json_route(port, f"/snapshot?id={sid}")
                if doc.get("snapshot_id") == sid:
                    docs[uuid] = doc
            if len(docs) < len(self.slices):
                time.sleep(0.25)
        for uuid, _ in self.slices:
            docs.setdefault(uuid, {
                "snapshot_id": sid, "node": uuid, "status": "incomplete",
            })
        cut = snap.assemble_cut(sid, list(docs.values()))
        violations = snap.audit_cut(cut)
        capture = [
            d.get("capture_ms") for d in docs.values()
            if d.get("capture_ms") is not None
        ]
        return {
            "snapshot_id": sid,
            "status": cut["status"],
            "nodes": len(cut["nodes"]),
            "violations": [v.as_dict() for v in violations],
            "capture_ms_max": max(capture) if capture else None,
            "node_status": {
                u: d.get("status") for u, d in sorted(docs.items())
            },
        }


def wait_for(procs: List[Proc], cond, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.25)
    return cond()


def write_configs(
    workdir: Path, specs: List[SliceSpec], loss_pct: int, vvc: bool = True
) -> None:
    from freedm_tpu.core.config import Timings
    from freedm_tpu.devices.schema import DEFAULT_TYPES

    lines = ["<root>"]
    for t in DEFAULT_TYPES:
        lines.append(f"  <deviceType><id>{t.id}</id>")
        for s in t.states:
            lines.append(f"    <state>{s}</state>")
        for c in t.commands:
            lines.append(f"    <command>{c}</command>")
        lines.append("  </deviceType>")
    lines.append("</root>")
    (workdir / "device.xml").write_text("\n".join(lines))

    # Small realtime budgets (gm 80 + sc 40 + lb 150 + vvc 250 = 520 ms
    # rounds): realtime pacing keeps every slice's protocol timers on
    # the same wall clock — free-running slices round at wildly
    # different rates (one compiles while another spins), and the
    # election's wall-clock timeouts then declare live peers dead
    # forever.  This is the reference's own deployment shape.
    small = {"gm_phase_time": 80, "sc_phase_time": 40,
             "lb_phase_time": 150, "vvc_phase_time": 250}
    tvals = {
        f.name: small.get(f.name, getattr(Timings(), f.name))
        for f in dataclasses.fields(Timings)
    }
    (workdir / "timings.cfg").write_text(
        "\n".join(f"{k.upper()} = {v}" for k, v in tvals.items())
    )

    # Rig: every slice's devices live in ONE plant (shared physics),
    # served over one RTDS port per slice.
    rig = ['<rig case="vvc_9bus" base="feeder" period="0.02">']
    tables: Dict[str, Dict[str, List]] = {}
    for i, spec in enumerate(specs):
        devs = [(f"SST{i}", "Sst", 2 + (i % 6), None)]
        if spec.generation:
            devs.append((f"GEN{i}", "Drer", 1 + (i % 7), spec.generation))
        if spec.drain:
            devs.append((f"LOAD{i}", "Load", 1 + ((i + 3) % 7), spec.drain))
        for row in spec.rows:
            for ph in "abc":
                devs.append((f"Q{row}_{ph}", f"Sst_{ph}", row, None))
        states, commands = [], []
        for name, tname, node, value in devs:
            v = f' value="{value}"' if value is not None else ""
            rig.append(
                f'  <device name="{name}" type="{tname}" node="{node}"{v}/>'
            )
            sig = {"Drer": "generation", "Load": "drain"}.get(tname, "gateway")
            states.append((name, tname, sig))
            if tname.startswith("Sst"):
                commands.append((name, tname, "gateway"))
        tables[spec.uuid] = {"states": states, "commands": commands}
        rig.append('  <adapter port="0">')
        for kind in ("state", "command"):
            for j, (dev, _, sig) in enumerate(tables[spec.uuid][kind + "s"]):
                rig.append(f'    <{kind} device="{dev}" signal="{sig}" index="{j}"/>')
        rig.append("  </adapter>")
    rig.append("</rig>")
    (workdir / "rig.xml").write_text("\n".join(rig))

    # Shared adapter.xml; owner= routes, non-local owners are skipped in
    # federate mode.  Plant ports are patched in later (ephemeral).
    al = ["<root>"]
    for spec in specs:
        al.append(
            f'  <adapter name="sim-{spec.port}" type="rtds" owner="{spec.uuid}">'
        )
        al.append(
            f"    <info><host>127.0.0.1</host><port>@PORT-{spec.uuid}@</port>"
            f"<poll>0.02</poll></info>"
        )
        for kind in ("state", "command"):
            al.append(f"    <{kind}>")
            for j, (dev, tname, sig) in enumerate(tables[spec.uuid][kind + "s"]):
                al.append(
                    f'      <entry index="{j + 1}"><type>{tname}</type>'
                    f"<device>{dev}</device><signal>{sig}</signal></entry>"
                )
            al.append(f"    </{kind}>")
        al.append("  </adapter>")
    al.append("</root>")
    (workdir / "adapter.xml.tmpl").write_text("\n".join(al))

    for spec in specs:
        net = ["<network>", f"  <incoming><reliability>{100 - loss_pct}</reliability></incoming>", "  <outgoing>"]
        for other in specs:
            if other.uuid != spec.uuid:
                net.append(
                    f'    <channel uuid="{other.uuid}">'
                    f"<reliability>{100 - loss_pct}</reliability></channel>"
                )
        net += ["  </outgoing>", "</network>"]
        (workdir / f"network_{spec.port}.xml").write_text("\n".join(net))

        cfg = workdir / f"freedm_{spec.port}.cfg"
        peers = "\n".join(
            f"add-host = {o.uuid}" for o in specs if o.uuid != spec.uuid
        )
        vvc_line = "vvc-case = vvc_9bus\n" if vvc else ""
        metrics_line = (
            f"metrics-port = {spec.metrics_port}\n"
            f"events-log = {workdir}/events_{spec.port}.jsonl\n"
            if spec.metrics_port is not None
            else ""
        )
        # Per-slice trace files (core.tracing): trace_report.py merges
        # them into the skew-corrected causal round timeline.
        trace_line = f"trace-log = {workdir}/trace_{spec.port}.jsonl\n"
        # SLO monitor + profiling registry (core.slo, core.profiling):
        # every slice judges its own objectives and journals
        # slo.breach/slo.recovered — the fault schedule's compile storms
        # (a restarted slice re-warming its kernels inside 150-250 ms
        # phase budgets) must breach the overrun objective and then
        # recover, which run_soak asserts from the victim's journal.
        # Short fast window so recovery lands within the soak; the
        # overrun target is loose (0.25/round) so a loaded CI box's
        # occasional steady-state overrun cannot breach on its own.
        slo_line = (
            "slo-enabled = yes\n"
            "slo-fast-window-s = 20\n"
            "slo-slow-window-s = 120\n"
            "slo-overrun-rate = 0.25\n"
            "profile-metrics = yes\n"
        )
        # What-if query endpoint (freedm_tpu.serve): the soak drives a
        # closed-loop load against one slice to prove serving and the
        # broker round loop coexist through kills/rejoins.
        # Provenance + shadow verification (core/provenance.py): the
        # serving slice audits EVERY cache-tier answer on the f64
        # shadow lane and journals every receipt — run_soak gates on
        # zero mismatches (a soak that "passes" while serving one wrong
        # cached answer did not pass).
        serve_line = (
            f"serve-port = {spec.serve_port}\n"
            f"qsts-checkpoint-dir = {workdir}/qsts_{spec.port}\n"
            f"shadow-verify-rate = seed=17;0.0,exact=1.0,delta=1.0\n"
            f"provenance-log = {workdir}/receipts_{spec.port}.jsonl\n"
            if spec.serve_port is not None
            else ""
        )
        cfg.write_text(
            f"hostname = 127.0.0.1\nport = {spec.port}\nfederate = yes\n"
            f"{peers}\nmigration-step = 1\n{vvc_line}{metrics_line}"
            f"{trace_line}{slo_line}{serve_line}"
            f"device-config = {workdir}/device.xml\n"
            f"adapter-config = {workdir}/adapter.xml\n"
            f"timings-config = {workdir}/timings.cfg\n"
            f"network-config = {workdir}/network_{spec.port}.xml\n"
        )
        spec.cfg_path = cfg


def finalize_adapter_xml(workdir: Path, specs: List[SliceSpec], plant_ports: List[int]):
    text = (workdir / "adapter.xml.tmpl").read_text()
    for spec, port in zip(specs, plant_ports):
        spec.plant_port = port
        text = text.replace(f"@PORT-{spec.uuid}@", str(port))
    (workdir / "adapter.xml").write_text(text)


def run_soak(
    n_slices: int = 5,
    duration_s: float = 60.0,
    loss_pct: int = 20,
    workdir: Optional[str] = None,
    out: Optional[str] = None,
    vvc: bool = True,
    serve_load: bool = True,
    qsts_probe: bool = False,
    topo_probe: bool = False,
    agents_probe: bool = False,
    snapshot_probe: bool = True,
    chaos: bool = False,
) -> Dict:
    import tempfile

    global _CACHE_DIR
    t_start = time.monotonic()
    wd = Path(workdir) if workdir else Path(tempfile.mkdtemp(prefix="freedm_soak_"))
    wd.mkdir(parents=True, exist_ok=True)
    _CACHE_DIR = str(wd / "jax_cache")
    os.makedirs(_CACHE_DIR, exist_ok=True)
    ports = free_udp_ports(n_slices)
    metrics_ports = free_tcp_ports(n_slices)
    serve_ports = free_tcp_ports(n_slices) if serve_load else [None] * n_slices
    specs = []
    for i, port in enumerate(ports):
        rows = [r for j, r in enumerate(VVC_ROWS) if j % n_slices == i]
        # One big producer, the rest consumers: migrations must flow.
        gen = 20.0 * (n_slices - 1) if i == 0 else 0.0
        drain = 0.0 if i == 0 else 15.0
        specs.append(
            SliceSpec(
                uuid=f"127.0.0.1:{port}", port=port, rows=rows,
                generation=gen, drain=drain, metrics_port=metrics_ports[i],
                serve_port=serve_ports[i],
            )
        )
    write_configs(wd, specs, loss_pct, vvc=vvc)

    check = Check()
    slice_metrics: Dict[str, Dict[str, float]] = {}
    loader: Optional[ServeLoader] = None
    serve_summary: Optional[Dict[str, float]] = None
    cache_summary: Optional[Dict[str, float]] = None
    snapshot_summary: Optional[Dict] = None
    slo_pairs: List[Dict] = []
    pre_kill_pairs: List[Dict] = []
    slo_status: Dict = {}
    profile_snap: Dict = {}
    roofline_snaps: Dict[str, Dict] = {}
    provenance_snaps: Dict[str, Dict] = {}
    plant = subprocess.Popen(
        [sys.executable, "-m", "freedm_tpu.sim.plantserver", str(wd / "rig.xml")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=_env(), text=True,
    )
    procs: List[Proc] = []
    try:
        line = plant.stdout.readline()
        plant_ports = [p for _, p in json.loads(line)["plantserver"]]
        check.record("plantserver_up", len(plant_ports) == n_slices,
                     f"ports={plant_ports}")
        finalize_adapter_xml(wd, specs, plant_ports)

        procs = [Proc(s).start() for s in specs]
        # Phase budget: the slices JIT-compile their VVC/LB kernels on
        # first rounds (amortized by the shared compilation cache, but
        # the first process still pays ~30-60 s on CPU) before the
        # first summary line appears.
        form_timeout = max(3.0 * duration_s, 180.0)

        wait_for(procs, lambda: all(p.lines for p in procs), form_timeout)

        def members_everywhere(n):
            return lambda: all(
                p.last().get("fed_members") == n for p in procs if p.alive()
            )

        def one_leader(procs_):
            return len({p.last().get("fed_leader") for p in procs_ if p.alive()}) == 1

        # The FIRST formation absorbs the cold compilation cache: the
        # first slice to reach a kernel compiles it for everyone, but
        # N slices racing on an empty cache still stagger their
        # first useful rounds by minutes.  Later phases (kill/rejoin)
        # run on a warm cache and use the tighter budget.
        ok = wait_for(
            procs,
            lambda: members_everywhere(n_slices)() and one_leader(procs),
            max(2.0 * form_timeout, 360.0),
        )
        check.record(
            f"group_of_{n_slices}_forms", ok,
            f"members={[p.last().get('fed_members') for p in procs]}",
        )

        # Background what-if query load against ONE slice's serve port,
        # running through the whole fault schedule (the target may be a
        # kill victim — the loader tolerates the gap and reconnects).
        if serve_load and specs[-1].serve_port is not None:
            loader = ServeLoader(specs[-1].serve_port).start()
        leaders = {p.last().get("fed_leader") for p in procs}
        check.record("single_leader", len(leaders) == 1, f"leaders={leaders}")
        leader_uuid = next(iter(leaders)) if leaders else None

        # Power migrates and stays conserved under loss.
        def migrated():
            return any(p.last().get("fed_migrations", 0) > 0 for p in procs)

        ok = wait_for(procs, migrated, duration_s)
        check.record("migrations_flow", ok,
                     f"migs={[p.last().get('fed_migrations') for p in procs]}")

        def conservation_ok():
            totals = [p.last().get("gateway_total") for p in procs]
            if any(t is None for t in totals):
                return False
            return abs(sum(totals)) <= 2.0  # ≤ two in-flight quanta

        ok = wait_for(procs, conservation_ok, duration_s / 2)
        totals = [round(p.last().get("gateway_total", float("nan")), 2) for p in procs]
        check.record("power_conserved", ok, f"gateways={totals} sum={round(sum(totals), 2)}")

        # VVC runs somewhere (the master covers the union of segments).
        def vvc_live():
            return any("vvc_loss_kw" in p.last() for p in procs)

        if vvc:
            check.record("vvc_live", wait_for(procs, vvc_live, duration_s),
                         "")

        # -- fault schedule --------------------------------------------------
        member = next(p for p in procs if p.spec.uuid != leader_uuid)
        # QSTS probe: a long-running scenario job on the very slice the
        # schedule is about to kill (resubmitted after its restart; the
        # final summary must match an uninterrupted reference exactly).
        probe: Optional[QstsProbe] = None
        if qsts_probe and member.spec.serve_port is not None:
            probe = QstsProbe(member.spec.serve_port)
            check.record("qsts_probe_submitted", probe.submit(),
                         f"target={member.spec.uuid}")
            if probe.submitted:
                # The kill must land MID-STUDY: wait for >=1 completed
                # chunk so a checkpoint exists and the resubmission
                # actually exercises cross-process resume.
                check.record(
                    "qsts_probe_checkpointed_before_kill",
                    probe.wait_chunks(1, timeout_s=form_timeout),
                    f"chunks_done={probe.chunks_before_kill}",
                )
        # Topology sweep probe: same kill/resume discipline on the
        # switching-screen job class (chunked + checkpointed sweep).
        tprobe: Optional[TopoProbe] = None
        if topo_probe and member.spec.serve_port is not None:
            tprobe = TopoProbe(member.spec.serve_port)
            check.record("topo_probe_submitted", tprobe.submit(),
                         f"target={member.spec.uuid}")
            if tprobe.submitted:
                check.record(
                    "topo_probe_checkpointed_before_kill",
                    tprobe.wait_chunks(1, timeout_s=form_timeout),
                    f"chunks_done={tprobe.chunks_before_kill}",
                )
        # Agent-population probe: the closed-loop study whose per-agent
        # state lanes must survive the kill inside the checkpoint.
        aprobe: Optional[AgentsProbe] = None
        if agents_probe and member.spec.serve_port is not None:
            aprobe = AgentsProbe(member.spec.serve_port)
            check.record("agents_probe_submitted", aprobe.submit(),
                         f"target={member.spec.uuid}")
            if aprobe.submitted:
                check.record(
                    "agents_probe_checkpointed_before_kill",
                    aprobe.wait_chunks(1, timeout_s=form_timeout),
                    f"chunks_done={aprobe.chunks_before_kill}",
                )
        kill_ts = time.time()
        member.kill()
        survivors = [p for p in procs if p.alive()]
        ok = wait_for(survivors, lambda: all(
            p.last().get("fed_members") == n_slices - 1 for p in survivors
        ), form_timeout)
        check.record("member_death_regroups", ok,
                     f"members={[p.last().get('fed_members') for p in survivors]}")

        member.lines.clear()
        member.start()
        ok = wait_for(procs, members_everywhere(n_slices), form_timeout)
        check.record("member_rejoin_remerges", ok,
                     f"members={[p.last().get('fed_members') for p in procs]}")

        if probe is not None and probe.submitted:
            # Resubmit the identical spec to the restarted slice: its
            # jobs layer finds the chunk checkpoint and resumes.
            check.record("qsts_probe_resubmitted",
                         probe.submit(timeout_s=form_timeout),
                         "same job_key after restart")
        if tprobe is not None and tprobe.submitted:
            check.record("topo_probe_resubmitted",
                         tprobe.submit(timeout_s=form_timeout),
                         "same job_key after restart")
        if aprobe is not None and aprobe.submitted:
            check.record("agents_probe_resubmitted",
                         aprobe.submit(timeout_s=form_timeout),
                         "same job_key after restart")

        # Consistent-cut snapshot MID-schedule: the fleet just re-merged
        # after the member kill (every slice live again) and the leader
        # kill is still ahead — a marker-coordinated cut over the lossy
        # federation must assemble complete and audit clean.
        if snapshot_probe:
            live = [
                (p.spec.uuid, p.spec.metrics_port)
                for p in procs
                if p.alive() and p.spec.metrics_port is not None
            ]
            snapshot_summary = SnapshotProbe(live).run(
                timeout_s=max(60.0, form_timeout / 3.0)
            )
            check.record(
                "snapshot_probe_clean",
                snapshot_summary is not None
                and snapshot_summary["status"] == "complete"
                and not snapshot_summary["violations"],
                f"summary={snapshot_summary}",
            )

        # Kill the LEADER: re-election among survivors + slave VVC
        # fallback (members keep volt-var alive without their master).
        leader_proc = next(p for p in procs if p.spec.uuid == leader_uuid)
        leader_proc.kill()
        survivors = [p for p in procs if p.alive()]
        ok = wait_for(survivors, lambda: all(
            p.last().get("fed_members") == n_slices - 1 for p in survivors
        ) and one_leader(survivors), form_timeout)
        new_leaders = {p.last().get("fed_leader") for p in survivors}
        check.record(
            "leader_death_reelects",
            ok and len(new_leaders) == 1 and leader_uuid not in new_leaders,
            f"new_leaders={new_leaders}",
        )

        def survivor_vvc_moves():
            return any(
                "vvc_loss_kw" in p.lines[-1]
                for p in survivors
                if p.lines
            )

        if vvc:
            for p in survivors:
                p.lines.clear()
            check.record(
                "vvc_survives_master_death",
                wait_for(survivors, survivor_vvc_moves, form_timeout),
                "standalone fallback on the members",
            )

        leader_proc.lines.clear()
        leader_proc.start()
        ok = wait_for(procs, members_everywhere(n_slices), form_timeout)
        check.record("leader_rejoin_remerges", ok,
                     f"members={[p.last().get('fed_members') for p in procs]}")

        crashed = [p.spec.uuid for p in procs if not p.alive()]
        check.record("no_unexpected_crashes", not crashed, f"crashed={crashed}")

        # Delta-heavy repeat-query phase (ISSUE 10): stop the random
        # background load FIRST so the /stats counter window measures
        # the probe's repeat/perturbed traffic, not the loader's noise,
        # then assert the incremental tier actually absorbed it.
        if serve_load:
            if loader is not None:
                serve_summary = loader.stop()
                loader = None
            cache_target = next(
                (p for p in sorted(procs,
                                   key=lambda p: p.spec is not specs[-1])
                 if p.alive() and p.spec.serve_port is not None),
                None,
            )
            if cache_target is not None:
                cache_summary = CacheProbe(
                    cache_target.spec.serve_port
                ).run()
                ratio = (cache_summary or {}).get(
                    "serve_cache_probe_hit_ratio"
                )
                check.record(
                    "serve_cache_hit_ratio_over_half",
                    ratio is not None and ratio > 0.5,
                    f"ratio={ratio} "
                    f"speedup={(cache_summary or {}).get('serve_cache_probe_delta_speedup')}",
                )

        if tprobe is not None and tprobe.submitted:
            tjob = tprobe.wait(timeout_s=max(2.0 * form_timeout, 300.0))
            t_completed = tjob.get("state") == "completed"
            check.record(
                "topo_probe_completes", t_completed,
                f"state={tjob.get('state')} err={tjob.get('error')}",
            )
            if t_completed:
                tref = tprobe.reference_summary()
                tgot = TopoProbe.strip_timing(tjob["summary"])
                twant = TopoProbe.strip_timing(tref)
                check.record(
                    "topo_probe_matches_reference", tgot == twant,
                    "killed-and-resumed sweep vs uninterrupted: "
                    + ("exact" if tgot == twant
                       else f"{tgot} != {twant}"),
                )
        if probe is not None and probe.submitted:
            job = probe.wait(timeout_s=max(2.0 * form_timeout, 300.0))
            completed = job.get("state") == "completed"
            resumed_from = (job.get("summary") or {}).get(
                "resumed_from_chunk", 0
            )
            check.record(
                "qsts_probe_completes", completed,
                f"state={job.get('state')} resumed_from={resumed_from}",
            )
            if completed:
                if probe.chunks_before_kill >= 1:
                    # A checkpoint existed pre-kill: the finished job
                    # must have RESUMED, not silently restarted.
                    check.record(
                        "qsts_probe_resumed_mid_study", resumed_from >= 1,
                        f"resumed_from_chunk={resumed_from} after "
                        f"{probe.chunks_before_kill} pre-kill chunks",
                    )
                ref = probe.reference_summary()
                got = QstsProbe.strip_timing(job["summary"])
                want = QstsProbe.strip_timing(ref)
                check.record(
                    "qsts_probe_matches_reference", got == want,
                    f"killed-and-resumed summary vs uninterrupted: "
                    f"{'exact' if got == want else f'{got} != {want}'}",
                )
        if aprobe is not None and aprobe.submitted:
            ajob = aprobe.wait(timeout_s=max(2.0 * form_timeout, 300.0))
            a_completed = ajob.get("state") == "completed"
            check.record(
                "agents_probe_completes", a_completed,
                f"state={ajob.get('state')} err={ajob.get('error')}",
            )
            if a_completed:
                aref = aprobe.reference_summary()
                agot = AgentsProbe.strip_timing(ajob["summary"])
                awant = AgentsProbe.strip_timing(aref)
                check.record(
                    "agents_probe_matches_reference", agot == awant,
                    "killed-and-resumed agent study vs uninterrupted: "
                    + ("exact" if agot == awant
                       else f"{agot} != {awant}"),
                )

        # SLO verdict: the member-kill schedule restarts two slices,
        # and each restart re-warms its jit kernels inside 150-250 ms
        # realtime phase budgets — the broker_overruns objective must
        # BREACH on some slice after the first kill and then RECOVER
        # once the kernels are warm.  Asserted from the slices' own
        # journals (slo.breach/slo.recovered events), which is the
        # whole point of the SLO layer: the rig reads a verdict, not a
        # counter.  Recovery needs a CLEAN fast window (20 s) after the
        # storm, so the rig settles here until the pair appears instead
        # of reading the journals the instant the last rejoin lands —
        # the faults themselves used to take >20 s of kernel re-warm,
        # which hid this; fast warm paths finish the schedule before
        # the monitor can possibly declare recovery.
        def _post_kill_pairs() -> List[Dict]:
            out: List[Dict] = []
            for spec in specs:
                events = read_events_jsonl(wd / f"events_{spec.port}.jsonl")
                for pair in slo_breach_recover_pairs(events, after_ts=kill_ts):
                    pair["slice"] = spec.uuid
                    out.append(pair)
            return out

        slo_pairs[:] = _post_kill_pairs()
        settle_deadline = time.time() + 60.0  # fast window + slack
        while not slo_pairs and time.time() < settle_deadline:
            time.sleep(2.0)
            slo_pairs[:] = _post_kill_pairs()
        for spec in specs:
            events = read_events_jsonl(wd / f"events_{spec.port}.jsonl")
            for pair in slo_breach_recover_pairs(events):
                if pair.get("breach_ts", 0.0) < kill_ts:
                    pair["slice"] = spec.uuid
                    pre_kill_pairs.append(pair)
        check.record(
            "slo_breach_and_recover_after_kill", bool(slo_pairs),
            f"pairs={[(p['slice'], p['slo']) for p in slo_pairs]}",
        )

        # /slo and /profile snapshots, preferring the slice that served
        # queries (its profile account carries the serve compile/host
        # entries): the artifact carries the judgment layer's final
        # verdict and the compile/memory/host accounts alongside the
        # raw counters.
        for p in sorted(
            procs,
            key=lambda p: (p.spec.serve_port is None, p.spec is not specs[-1]),
        ):
            if p.alive() and p.spec.metrics_port is not None:
                slo_status = scrape_json_route(p.spec.metrics_port, "/slo")
                profile_snap = scrape_json_route(
                    p.spec.metrics_port, "/profile"
                )
                if slo_status:
                    break

        # Per-slice transport/solver counters, scraped from each live
        # slice's metrics endpoint before teardown — the SOAK trajectory's
        # retransmit columns.
        slice_metrics.update(
            (p.spec.uuid, scrape_slice_metrics(p.spec.metrics_port))
            for p in procs
            if p.alive() and p.spec.metrics_port is not None
        )
        # Per-slice roofline snapshots, fleet-summed into the artifact
        # below: the dispatch/device-wall attribution of the whole soak
        # run, per program (empty rows while --roofline is off).
        roofline_snaps.update(
            (p.spec.uuid,
             scrape_json_route(p.spec.metrics_port, "/roofline"))
            for p in procs
            if p.alive() and p.spec.metrics_port is not None
        )
        # Per-slice provenance/shadow snapshots: the numerical-honesty
        # verdict.  Every cache-tier answer the serving slice produced
        # was shadow-verified on the independent f64 lane; one mismatch
        # fails the soak regardless of every other check.
        provenance_snaps.update(
            (p.spec.uuid,
             scrape_json_route(p.spec.metrics_port, "/provenance"))
            for p in procs
            if p.alive() and p.spec.metrics_port is not None
        )
        shadow_on = {
            uuid: snap for uuid, snap in provenance_snaps.items()
            if snap.get("enabled")
        }
        mismatches = sum(
            int(st.get("mismatches", 0) or 0)
            for snap in shadow_on.values()
            for st in (snap.get("shadow") or {}).values()
        )
        verified = sum(
            int(st.get("verified", 0) or 0)
            for snap in shadow_on.values()
            for st in (snap.get("shadow") or {}).values()
        )
        if serve_load:
            check.record(
                "shadow_zero_mismatches",
                bool(shadow_on) and mismatches == 0,
                f"slices={len(shadow_on)} verified={verified} "
                f"mismatches={mismatches}",
            )
    finally:
        if loader is not None:
            serve_summary = loader.stop()
        for p in procs:
            p.kill()
            p._release_port()
        plant.kill()
        plant.wait(timeout=10)

    # Fleet totals summed over the scraped slices (the rig parent's own
    # registry sees no traffic — the counters live in the slice
    # processes): the SOAK trajectory's retransmit/round columns, with
    # the per-slice breakdown alongside.
    totals: Dict[str, float] = {}
    for counters in slice_metrics.values():
        for k, v in counters.items():
            totals[k] = totals.get(k, 0.0) + v
    if serve_summary is not None:
        # Loader-side achieved QPS/sheds alongside the server-side
        # serve_shed_total scraped above (absent if the serving slice
        # died before the final scrape).
        totals.update(serve_summary)
        totals.setdefault("serve_shed_total", serve_summary["serve_client_shed_429"])
    if cache_summary is not None:
        # The repeat-query phase's hit ratio + delta speedup, measured
        # through the live slice's HTTP path and its /stats window.
        totals.update(cache_summary)
    # Per-slice trace files + a merged mini-report: the artifact records
    # how causally connected the run was (cross-node links prove the
    # wire trace context survived the lossy transport), with the full
    # timeline reconstructable offline via trace_report.py.
    trace_files = [
        str(wd / f"trace_{s.port}.jsonl")
        for s in specs
        if (wd / f"trace_{s.port}.jsonl").exists()
    ]
    trace_summary: Dict[str, object] = {"files": trace_files}
    if trace_files:
        try:
            from freedm_tpu.tools import trace_report

            rep = trace_report.report(trace_files)
            trace_summary.update(
                spans=rep["spans"],
                traces=len(rep["traces"]),
                cross_node_links=sum(
                    t["cross_node_links"] for t in rep["traces"].values()
                ),
                overruns=rep["overruns"],
                phase_ms=rep["summaries"].get("phase_ms", {}),
            )
        except Exception as e:  # a truncated file must not fail the soak
            trace_summary["error"] = repr(e)
    # Replicated-serving chaos phase (ISSUE 12): the 3-replica router
    # fleet driven through its deterministic fault schedule — a replica
    # hard-killed mid-load must yield zero untyped client failures,
    # >= 99.9% success via router retries, and cache hit-ratio
    # retention on the moved hash range.  Run AFTER the federation
    # schedule (its own processes, its own ports) so the two fault
    # domains cannot mask each other's failures.
    chaos_artifact: Optional[Dict] = None
    if chaos:
        from freedm_tpu.tools import chaos as chaos_mod

        chaos_artifact = chaos_mod.run_chaos(
            workdir=str(wd / "chaos"), out=str(wd / "chaos.json")
        )
        check.record(
            "chaos_replica_fleet", chaos_artifact["pass"],
            f"failed={[c['name'] for c in chaos_artifact['checks'] if not c['ok']]}",
        )

    artifact = {
        "pass": check.passed,
        "slices": n_slices,
        "loss_pct": loss_pct,
        "duration_s": round(time.monotonic() - t_start, 1),
        "checks": check.results,
        "workdir": str(wd),
        "metrics": totals,
        "slice_metrics": slice_metrics,
        "trace": trace_summary,
        "slo": {
            "breach_recover_pairs_after_kill": slo_pairs,
            "breach_recover_pairs_before_kill": pre_kill_pairs,
            "status": slo_status,
        },
        "profile": profile_snap,
        "provenance": {
            uuid: {
                "enabled": bool(snap.get("enabled")),
                "receipts": snap.get("receipts") or {},
                "shadow": snap.get("shadow") or {},
                "drift": snap.get("drift") or {},
            }
            for uuid, snap in provenance_snaps.items() if snap
        },
        "roofline": {
            "fleet": sum_roofline(roofline_snaps),
            "slices": {
                uuid: {
                    "enabled": bool(snap.get("enabled")),
                    "dispatches_total": sum(
                        int(r.get("dispatches") or 0)
                        for r in snap.get("programs", {}).values()
                    ),
                }
                for uuid, snap in roofline_snaps.items() if snap
            },
        },
    }
    if snapshot_summary is not None:
        artifact["snapshot"] = snapshot_summary
    if chaos_artifact is not None:
        artifact["chaos"] = chaos_artifact
    if out:
        Path(out).write_text(json.dumps(artifact, indent=2))
    print(json.dumps({"soak_pass": artifact["pass"],
                      "checks": len(check.results),
                      "failed": [c["name"] for c in check.results if not c["ok"]]}),
          flush=True)
    return artifact


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="Federated multi-process soak rig")
    ap.add_argument("--slices", type=int, default=5)
    ap.add_argument("--duration", type=float, default=60.0,
                    help="per-phase timeout budget, seconds")
    ap.add_argument("--loss", type=int, default=20, metavar="PCT",
                    help="datagram loss percentage on every link")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the JSON artifact here")
    ap.add_argument("--no-vvc", action="store_true",
                    help="run without the VVC module (debug)")
    ap.add_argument("--no-serve-load", action="store_true",
                    help="skip the background what-if query load")
    ap.add_argument("--no-topo-probe", action="store_true",
                    help="skip the topology-sweep kill/resume probe")
    ap.add_argument("--no-qsts-probe", action="store_true",
                    help="skip the QSTS kill/resume determinism probe")
    ap.add_argument("--no-agents-probe", action="store_true",
                    help="skip the agent-population kill/resume probe")
    ap.add_argument("--no-snapshot-probe", action="store_true",
                    help="skip the mid-schedule consistent-cut fleet "
                         "snapshot + invariant audit")
    ap.add_argument("--chaos", action="store_true",
                    help="also run the replicated-serving chaos phase "
                         "(3 replicas + router, deterministic kill "
                         "schedule; tools/chaos.py) and gate on it")
    args = ap.parse_args(argv)
    artifact = run_soak(
        n_slices=args.slices, duration_s=args.duration, loss_pct=args.loss,
        workdir=args.workdir, out=args.out, vvc=not args.no_vvc,
        serve_load=not args.no_serve_load,
        qsts_probe=not args.no_qsts_probe,
        topo_probe=not args.no_topo_probe,
        agents_probe=not args.no_agents_probe,
        snapshot_probe=not args.no_snapshot_probe,
        chaos=args.chaos,
    )
    return 0 if artifact["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
