"""CI smoke for the topology-sweep stack: sync screen + async job.

Starts a real :class:`~freedm_tpu.serve.ServeServer` with a
:class:`~freedm_tpu.scenarios.jobs.JobManager` on an ephemeral port,
then drives the switching-screen workload both ways it ships:

- ``POST /v1/topo`` — a synchronous rank-2 screen over every branch of
  ``case14``; asserts the 200, the exclusion accounting (islanded +
  disconnected + feasible partitions the variant space), that every
  shortlist entry is AC-verified converged with a residual below the
  engine tolerance, and that no shortlist entry opens a bridge branch
  (the islanding-never-verified contract).
- ``POST /v1/topo/sweep`` — the same sweep as an async job with a
  ``job_key``; polls ``GET /v1/jobs/<id>`` to completion and asserts
  the job summary's shortlist MATCHES the sync answer's ranking (one
  implementation, two front ends).

Typed-error paths are exercised too (bad objective → 400
``invalid_request``, unknown job id → 404 ``not_found``).  One
command, exit code 0 iff healthy:

    python -m freedm_tpu.tools.topo_smoke

Used by ``.github/workflows/ci.yml``; also a handy local sanity check
after touching pf/topo.py or the serve/jobs wiring.
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

POLL_TIMEOUT_S = 300.0


def _post(port: int, path: str, payload: dict) -> Tuple[int, dict]:
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        with e:
            return e.code, json.loads(e.read())


def _get(port: int, path: str) -> Tuple[int, dict]:
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        with e:
            return e.code, json.loads(e.read())


def main(argv: Optional[List[str]] = None) -> int:
    from freedm_tpu.grid.matpower import load_builtin
    from freedm_tpu.pf.n1 import secure_outages
    from freedm_tpu.scenarios.jobs import JobManager
    from freedm_tpu.serve import ServeConfig, ServeServer, Service

    svc = Service(ServeConfig(max_batch=4, buckets=(1, 4)))
    jm = JobManager(
        workers=1, checkpoint_dir=tempfile.mkdtemp(prefix="topo_smoke_")
    ).start()
    srv = ServeServer(svc, port=0, jobs=jm).start()
    port = srv.port
    failures: List[str] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        line = f"{'ok' if ok else 'FAIL'}  {name}" + (
            f"  ({detail})" if detail else ""
        )
        print(line)
        if not ok:
            failures.append(name)

    try:
        sys_ = load_builtin("case14")
        bridges = set(range(sys_.n_branch)) - set(secure_outages(sys_))

        # -- sync screen ------------------------------------------------
        st, d = _post(port, "/v1/topo", {
            "case": "case14", "max_rank": 2, "top_k": 4,
            "timeout_s": 300,
        })
        check("sync 200", st == 200, f"status={st}")
        if st == 200:
            parts = (d["n_feasible"] + d["n_disconnected"]
                     + d["n_nonradial"] + d["n_islanded"])
            check("exclusion accounting partitions the space",
                  parts == d["n_variants"],
                  f"{d['n_feasible']}+{d['n_disconnected']}"
                  f"+{d['n_nonradial']}+{d['n_islanded']} "
                  f"vs {d['n_variants']}")
            # n_islanded counts SMW-backstop-ONLY exclusions; on case14
            # the structural check catches every islanding variant, so
            # the backstop has nothing left to catch alone.
            check("structural check leaves no backstop-only islands",
                  d["n_islanded"] == 0 and d["n_disconnected"] > 0,
                  f"islanded={d['n_islanded']} "
                  f"disconnected={d['n_disconnected']}")
            check("shortlist non-empty", bool(d["shortlist"]))
            # 5e-4 covers the f32 engine tolerance (3e-5) with margin;
            # under x64 the residuals are ~1e-14.
            check("shortlist AC-verified",
                  d["all_verified"] and all(
                      e["ac_converged"] and e["ac_residual_pu"] < 5e-4
                      for e in d["shortlist"]
                  ))
            check("no bridge reaches the shortlist", all(
                not (set(e["open_branches"]) & bridges)
                for e in d["shortlist"]
            ), f"bridges={sorted(bridges)}")

        # -- typed errors ----------------------------------------------
        st2, d2 = _post(port, "/v1/topo", {"case": "case14",
                                           "objective": "nope"})
        check("bad objective -> 400 invalid_request",
              st2 == 400 and d2["error"]["type"] == "invalid_request")
        st3, d3 = _get(port, "/v1/jobs/deadbeef")
        check("unknown job -> 404 not_found",
              st3 == 404 and d3["error"]["type"] == "not_found")

        # -- async sweep job -------------------------------------------
        st4, d4 = _post(port, "/v1/topo/sweep", {
            "case": "case14", "max_rank": 2, "top_k": 4,
            "chunk_variants": 64, "job_key": "smoke",
        })
        check("sweep job 202", st4 == 202 and d4["kind"] == "topo",
              f"status={st4}")
        job = {}
        if st4 == 202:
            deadline = time.monotonic() + POLL_TIMEOUT_S
            while time.monotonic() < deadline:
                _, job = _get(port, f"/v1/jobs/{d4['job_id']}")
                if job.get("state") in ("completed", "failed",
                                        "cancelled"):
                    break
                time.sleep(0.5)
            check("sweep job completed", job.get("state") == "completed",
                  f"state={job.get('state')} err={job.get('error')}")
        if job.get("state") == "completed" and st == 200:
            js = job["summary"]["shortlist"]
            check("job shortlist matches sync ranking", [
                e["open_branches"] for e in js
            ] == [
                e["open_branches"] for e in d["shortlist"]
            ], f"job={[e['open_branches'] for e in js]}")
            check("job shortlist AC-verified", all(
                e["ac_converged"] and e["ac_true_mismatch_pu"] < 5e-4
                for e in js
            ))
    finally:
        srv.stop()
        jm.stop()
        svc.stop()

    if failures:
        print(f"topo_smoke: {len(failures)} failure(s): "
              f"{', '.join(failures)}", file=sys.stderr)
        return 1
    print("topo_smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
