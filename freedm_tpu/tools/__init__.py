"""Operator tooling (scenario drivers, soak rigs)."""
