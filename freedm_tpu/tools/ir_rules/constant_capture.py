"""GP003 — constant capture: closure-captured arrays above a size
threshold folded into the program as constants.

A jax array referenced from inside a jitted function but created
outside it becomes a *program constant*: it serializes into the compile
payload, duplicates in device memory per program, and — because a new
(case, topology) builds a new program — multiplies per topology.
``pf/krylov.py`` documents the burn: 400 MB of bf16 preconditioner as a
closure constant at 10k buses, which is why both Krylov paths thread
the pair as runtime ARGUMENTS instead.  This rule pins that discipline
for every registered program: any single captured constant at or above
the threshold (``--probe-const-mb``, config ``probe-const-mb``) is a
finding.

Small captures (masks, index vectors, scheduled injections) are the
normal and correct way to bake per-case structure into a program — the
threshold, not a blanket ban, is the invariant.
"""

from __future__ import annotations

from typing import Iterable

from freedm_tpu.tools.lint_rules.base import Finding
from freedm_tpu.tools.ir_rules.base import IrRule, TracedProgram


class ConstantCapture(IrRule):
    id = "GP003"
    name = "constant-capture"
    hint = ("thread the array as a runtime argument (the pf/krylov.py "
            "preconditioner pattern) or build it inside the program "
            "(iota/eye); raise probe-const-mb only for a documented "
            "per-topology artifact")

    def __init__(self, const_mb: float = 0.25):
        self.const_bytes = int(const_mb * 1024 * 1024)

    def check(self, program: TracedProgram) -> Iterable[Finding]:
        for c in program.consts:
            nbytes = getattr(c, "nbytes", 0) or 0
            if nbytes >= self.const_bytes:
                shape = tuple(getattr(c, "shape", ()))
                dtype = getattr(getattr(c, "dtype", None), "name", "?")
                yield self.finding(
                    program.spec,
                    f"captured constant {dtype}{list(shape)} "
                    f"({nbytes / 1e6:.2f} MB >= "
                    f"{self.const_bytes / 1e6:.2f} MB threshold) is folded "
                    f"into the compiled program (recompile/memory hazard "
                    f"per topology)",
                )
