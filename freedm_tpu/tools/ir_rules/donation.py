"""GP004 — donation readiness: declared donatable buffers must have an
aliasable result.

The MFU roadmap item (bf16/f32 inner GMRES + buffer donation across
Newton iterations and serve dispatches) needs to know, per program,
which argument buffers XLA could alias with a same-dtype-same-shape
result — those are the HBM round trips donation would delete.  The
engine computes the candidate pairs for every program and records them
in the inventory (``donation_candidates``); this rule checks only the
*declarations*: a spec that marks an argument index ``donatable`` when
no result buffer can alias it has drifted from the program it
describes — the same self-checking-registry posture as GL002's
``HOT_PATHS`` orphan findings.
"""

from __future__ import annotations

from typing import Iterable

from freedm_tpu.tools.lint_rules.base import Finding
from freedm_tpu.tools.ir_rules.base import IrRule, TracedProgram, aval_str


class DonationReadiness(IrRule):
    id = "GP004"
    name = "donation-readiness"
    hint = ("align the spec's donatable indices with the program: an "
            "index is donation-ready only when some result has the "
            "same dtype+shape (see the inventory's donation_candidates)")

    def check(self, program: TracedProgram) -> Iterable[Finding]:
        spec = program.spec
        if not spec.donatable:
            return
        n_args = len(program.in_avals)
        for idx in spec.donatable:
            if idx >= n_args:
                yield self.finding(
                    spec,
                    f"donatable index {idx} is out of range (program has "
                    f"{n_args} array arguments)",
                )
                continue
            # Check the DECLARED index directly against the results —
            # the inventory's greedy candidate pairing is arbitrary in
            # arg order, and two same-shaped arguments must not make
            # the later one look non-donatable.
            a = program.in_avals[idx]
            aliasable = any(
                getattr(a, "dtype", None) == getattr(r, "dtype", None)
                and getattr(a, "shape", None) == getattr(r, "shape", None)
                for r in program.out_avals
            )
            if not aliasable:
                yield self.finding(
                    spec,
                    f"argument {idx} ({aval_str(program.in_avals[idx])}) is "
                    f"declared donatable but no result buffer can alias it",
                )
