"""GP004 — donation enforcement: declared donatable buffers must be
aliasable AND actually donated by the compiled program.

The MFU roadmap item shipped: the solver iteration programs and the
serve dispatch buffers now declare ``donate_argnums`` on the buffers
XLA can alias with a same-dtype-same-shape result (the HBM round trips
donation deletes).  This rule keeps the registry's ``donatable``
declarations and the programs in lock-step, in BOTH directions:

- a declared index that is out of range, or that no result buffer can
  alias, has drifted from the program it describes (the original
  readiness check);
- a declared index the traced program does NOT donate is a promise the
  compiled code no longer keeps — the donation was dropped in a
  refactor and the HBM win silently evaporated;
- an argument the program donates WITHOUT declaring it is an invisible
  aliasing hazard — donation destroys the caller's buffer, so it must
  be visible in the registry where review sees it.

The engine still records every aliasable pair in the inventory
(``donation_candidates``) plus the actually-donated indices
(``donated``), so the gap between "could donate" and "does donate"
stays measurable — the same self-checking-registry posture as GL002's
``HOT_PATHS`` orphan findings.
"""

from __future__ import annotations

from typing import Iterable

from freedm_tpu.tools.lint_rules.base import Finding
from freedm_tpu.tools.ir_rules.base import IrRule, TracedProgram, aval_str


class DonationEnforcement(IrRule):
    id = "GP004"
    name = "donation-enforcement"
    hint = ("align the spec's donatable indices with the program: a "
            "declared index must have a same-dtype+shape result buffer "
            "AND be donated via donate_argnums on the jitted program; "
            "a donated index must be declared (see the inventory's "
            "donation_candidates / donated columns)")

    def check(self, program: TracedProgram) -> Iterable[Finding]:
        spec = program.spec
        declared = set(spec.donatable)
        donated = set(program.donated_args())
        for idx in sorted(donated - declared):
            yield self.finding(
                spec,
                f"argument {idx} ({aval_str(program.in_avals[idx])}) is "
                f"donated by the program but not declared donatable in "
                f"the registry",
            )
        if not declared:
            return
        n_args = len(program.in_avals)
        for idx in sorted(declared):
            if idx >= n_args:
                yield self.finding(
                    spec,
                    f"donatable index {idx} is out of range (program has "
                    f"{n_args} array arguments)",
                )
                continue
            # Check the DECLARED index directly against the results —
            # the inventory's greedy candidate pairing is arbitrary in
            # arg order, and two same-shaped arguments must not make
            # the later one look non-donatable.
            a = program.in_avals[idx]
            aliasable = any(
                getattr(a, "dtype", None) == getattr(r, "dtype", None)
                and getattr(a, "shape", None) == getattr(r, "shape", None)
                for r in program.out_avals
            )
            if not aliasable:
                yield self.finding(
                    spec,
                    f"argument {idx} ({aval_str(program.in_avals[idx])}) is "
                    f"declared donatable but no result buffer can alias it",
                )
                continue
            if idx not in donated:
                yield self.finding(
                    spec,
                    f"argument {idx} ({aval_str(program.in_avals[idx])}) is "
                    f"declared donatable but the compiled program does not "
                    f"donate it (donate_argnums dropped?)",
                )
