"""gridprobe IR-rule catalogue.

| ID    | Invariant                                                        |
|-------|------------------------------------------------------------------|
| GP001 | dtype flow: f64 surfaces stay f64; bf16/f16 only inside declared boundaries |
| GP002 | host transfer: no callback-shaped primitives inside traced programs |
| GP003 | constant capture: no closure constant >= the size threshold      |
| GP004 | donation enforcement: declared donatable args are aliasable AND donated; donated args are declared |
| GP005 | registry orphan: every registry entry builds and traces (engine-level) |
| GP006 | inventory drift: traced program set matches tools/ir_inventory.json (engine-level) |

GP005/GP006 are emitted by the engine (:mod:`freedm_tpu.tools.gridprobe`)
itself — they are properties of the registry and the checked-in
inventory, not of any one traced program.  Adding a rule mirrors
gridlint: subclass :class:`~freedm_tpu.tools.ir_rules.base.IrRule`,
implement ``check(program)``, append it here, document it in
docs/static_analysis.md, and burn down what it finds before merging.
"""

from __future__ import annotations

from typing import List

from freedm_tpu.tools.ir_rules.base import IrRule


def all_ir_rules(const_mb: float = 0.25) -> List[IrRule]:
    """Fresh rule instances, in reporting order."""
    from freedm_tpu.tools.ir_rules.constant_capture import ConstantCapture
    from freedm_tpu.tools.ir_rules.donation import DonationEnforcement
    from freedm_tpu.tools.ir_rules.dtype_flow import DtypeFlow
    from freedm_tpu.tools.ir_rules.host_transfer import HostTransfer

    return [
        DtypeFlow(),
        HostTransfer(),
        ConstantCapture(const_mb=const_mb),
        DonationEnforcement(),
    ]
