"""Shared engine types for gridprobe: program specs, traced IR, rules.

gridlint (:mod:`freedm_tpu.tools.lint_rules`) audits the *source text*;
gridprobe audits the *compiler IR* — the jaxpr and lowered HLO of every
registered jitted entrypoint.  The shapes here mirror the lint engine
deliberately (``Finding`` is the same class, rules subclass a base with
``id``/``name``/``hint``/``check``) so the two tools share one UX, but
the unit of analysis is a traced **program**, not a parsed file.

A :class:`ProgramSpec` declares one entrypoint: a name, the source
module the findings point at, a zero-argument ``build`` returning
``(fn, args)`` to trace, and the program's *declared contracts* —
whether it is a float64 surface (GP001), which low-precision dtypes it
is allowed to touch and why (the declared mixed-precision boundary),
and which argument indices it declares donation-ready (GP004).  The
declarations ARE the suppression mechanism: gridprobe has no line-level
disables because IR findings have no source line — a program opts out
of a rule by declaring the boundary, visibly, in the registry.

Everything traces on the CPU backend with x64 enabled, so the audited
dtype flow is the float64 contract flow the solver tests and the serve
cache's residual oracles rely on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Tuple

from freedm_tpu.tools.lint_rules.base import Finding

#: Reduced-precision float dtype names GP001 polices.
LOW_PRECISION_FLOATS = ("bfloat16", "float16")

#: Dtypes a float64 contract surface may be silently demoted to.
DEMOTION_TARGETS = ("float32", "bfloat16", "float16")


@dataclass(frozen=True)
class ProgramSpec:
    """One registered jitted entrypoint and its declared contracts."""

    #: Inventory key, e.g. ``"pf/newton/dense"``.
    name: str
    #: Repo-relative path findings for this program point at.
    where: str
    #: Zero-arg builder returning ``(fn, args)``: a jax-traceable
    #: callable plus the positional example arguments to trace it with.
    #: May raise — a failed build is a GP005 registry-orphan finding.
    build: Callable[[], Tuple[Callable, tuple]]
    #: Declared float64 contract surface: every float that flows through
    #: must stay f64 (GP001 flags demotions and non-f64 float results).
    f64: bool = False
    #: Declared mixed-precision boundary: low-precision dtype names
    #: (``"bfloat16"``...) this program is ALLOWED to touch.  Requires
    #: ``boundary_reason`` — the declaration is the visible suppression.
    allow_dtypes: FrozenSet[str] = frozenset()
    #: Why the boundary exists (e.g. "preconditioner streams bf16").
    boundary_reason: str = ""
    #: Argument indices declared donation-ready: each must have an
    #: aliasable (same dtype+shape) result buffer (GP004).
    donatable: Tuple[int, ...] = ()
    #: Rule ids this program opts out of entirely, mapped to the reason
    #: (the registry-level analogue of a gridlint disable comment).
    suppress: Dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class F64Surface:
    """A host-side float64 oracle surface (numpy, not traceable).

    GP001 cannot walk a jaxpr for these, so it *evaluates* them and
    asserts every floating output leaf is float64 — the same "no silent
    demotion" contract, checked at the value level.
    """

    name: str
    where: str
    build: Callable[[], Tuple[Callable, tuple]]


def _iter_nested_jaxprs(value) -> List[object]:
    """Jaxpr objects reachable from one eqn param value (ClosedJaxpr,
    bare Jaxpr, or tuples/lists of either — cond branches etc.)."""
    out: List[object] = []
    if hasattr(value, "jaxpr") and hasattr(value, "consts"):
        out.append(value)  # ClosedJaxpr
    elif hasattr(value, "eqns") and hasattr(value, "invars"):
        out.append(value)  # bare Jaxpr
    elif isinstance(value, (tuple, list)):
        for v in value:
            out.extend(_iter_nested_jaxprs(v))
    return out


def walk_eqns(closed_jaxpr):
    """Every eqn in a closed jaxpr, recursing through pjit bodies,
    scan/while/cond sub-jaxprs, and custom_jvp wrappers."""
    stack = [closed_jaxpr.jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            yield eqn
            for v in eqn.params.values():
                for sub in _iter_nested_jaxprs(v):
                    stack.append(getattr(sub, "jaxpr", sub))


def collect_consts(closed_jaxpr) -> List[object]:
    """All constants baked into a traced program: the top-level closed
    jaxpr's consts plus those of every nested ClosedJaxpr (a jit-of-jit
    trace hoists differently across jax versions — walk both)."""
    consts: List[object] = []
    seen_ids = set()

    def _add(cs):
        for c in cs:
            if id(c) not in seen_ids:
                seen_ids.add(id(c))
                consts.append(c)

    _add(closed_jaxpr.consts)
    stack = [closed_jaxpr.jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            for v in eqn.params.values():
                for sub in _iter_nested_jaxprs(v):
                    if hasattr(sub, "consts"):
                        _add(sub.consts)
                    stack.append(getattr(sub, "jaxpr", sub))
    return consts


def aval_str(aval) -> str:
    """Deterministic short form of an abstract value: ``f64[30,2]``."""
    try:
        return aval.str_short()
    except Exception:
        return str(aval)


def var_dtype_name(v) -> Optional[str]:
    """Dtype name of a jaxpr var/literal's aval, None for non-arrays."""
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    return getattr(dt, "name", None)


class TracedProgram:
    """One spec's traced IR plus the derived views the rules consume."""

    def __init__(self, spec: ProgramSpec, closed_jaxpr, lowered=None,
                 cost: Optional[dict] = None):
        self.spec = spec
        self.closed_jaxpr = closed_jaxpr
        self.lowered = lowered
        self.cost = cost or {}
        self.in_avals = list(closed_jaxpr.in_avals)
        self.out_avals = list(closed_jaxpr.out_avals)
        self.consts = collect_consts(closed_jaxpr)

    def eqns(self):
        return walk_eqns(self.closed_jaxpr)

    def primitive_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for eqn in self.eqns():
            name = eqn.primitive.name
            out[name] = out.get(name, 0) + 1
        return out

    def consts_bytes(self) -> int:
        total = 0
        for c in self.consts:
            nbytes = getattr(c, "nbytes", None)
            if nbytes is None:
                size = getattr(c, "size", 0)
                itemsize = getattr(getattr(c, "dtype", None), "itemsize", 0)
                nbytes = int(size) * int(itemsize)
            total += int(nbytes)
        return total

    def donated_args(self) -> List[int]:
        """Argument indices the traced program actually DONATES.

        A registered entrypoint that declares ``donate_argnums`` is
        itself a jitted function; tracing it under the probe's outer
        ``jax.jit`` leaves its body as a ``pjit`` eqn whose
        ``donated_invars`` params carry the donation flags.  This maps
        those flags back to the program's flattened argument indices
        (the same index space as ``in_avals`` / ``donation_candidates``
        / the spec's ``donatable`` declaration).  A program with no
        pjit eqns — a plain function the probe wrapped itself — donates
        nothing, which is exactly what an empty list reports.
        """
        invar_index = {
            id(v): i for i, v in enumerate(self.closed_jaxpr.jaxpr.invars)
        }
        out: set = set()
        for eqn in self.closed_jaxpr.jaxpr.eqns:
            donated = eqn.params.get("donated_invars")
            if not donated:
                continue
            for v, flag in zip(eqn.invars, donated):
                if flag and id(v) in invar_index:
                    out.add(invar_index[id(v)])
        return sorted(out)

    def donation_candidates(self) -> List[Tuple[int, int, str]]:
        """Greedy (arg, result) pairs with identical dtype+shape — the
        buffers jit could alias with ``donate_argnums`` (the feed-in
        for cross-iteration buffer reuse).  Scalars are skipped: there
        is nothing worth donating there."""
        out: List[Tuple[int, int, str]] = []
        used = set()
        for i, a in enumerate(self.in_avals):
            if not getattr(a, "shape", ()):  # scalar
                continue
            for j, r in enumerate(self.out_avals):
                if j in used:
                    continue
                if (getattr(a, "dtype", None) == getattr(r, "dtype", None)
                        and getattr(a, "shape", None) == getattr(r, "shape", None)):
                    out.append((i, j, aval_str(a)))
                    used.add(j)
                    break
        return out


class IrRule:
    """Base: one IR invariant with an ID, a one-line hint, and a
    per-program check (the engine iterates programs and filters the
    spec's ``suppress`` declarations)."""

    id = "GP000"
    name = "base"
    hint = ""

    def check(self, program: TracedProgram) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, spec: ProgramSpec, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(self.id, spec.where, 1, 0,
                       f"[{spec.name}] {message}",
                       self.hint if hint is None else hint)
