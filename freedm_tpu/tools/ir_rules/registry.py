"""PROGRAM_REGISTRY: every jitted entrypoint gridprobe audits.

The IR-level analogue of GL002's ``HOT_PATHS``: each entry names one
compiled program the framework actually dispatches — the solver cores,
the DC screen + SMW delta pair the serving cache leans on, the N-1
screen, the QSTS chunk bodies, the serve engines' shape-bucket
programs, and the LB auction round — together with its declared
contracts (f64 surface?, allowed mixed-precision boundary, donation
declarations).  An entry that no longer builds is itself a finding
(GP005), so the registry cannot silently rot.

Case sizes are picked to keep a full probe cheap on the CPU backend
while still being LARGE enough that the capture/dtype hazards the rules
police are real at trace time (e.g. the dense-Newton entry runs at 118
buses, where a captured identity matrix would already trip GP003's
default threshold).  Everything traces with x64 enabled, so the audited
flow is the float64 contract flow.

``F64_SURFACES`` lists the *host-side* float64 oracles the serve cache
and the solver accuracy claims rest on — numpy code gridprobe cannot
trace, so GP001 checks them by evaluation: every floating output leaf
must be float64.

Builders import lazily and construct solvers the same way production
does; where a solver's real program takes its heavy artifacts as
runtime arguments (the krylov/sparse preconditioner pair, the FDLF/DC
factor pairs), the entry traces through the solver's ``probe_target``
seam so the audit sees the actual jit boundary, not an outer closure
that would misreport arguments as captured constants.
"""

from __future__ import annotations

from typing import List, Tuple

from freedm_tpu.tools.ir_rules.base import F64Surface, ProgramSpec

#: Shared boundary reason for the bf16 preconditioner stream
#: (pf/krylov.py module docstring: M⁻¹ only steers convergence; the
#: iterates, residuals and JVPs stay in the working dtype).
_BF16_PRECOND = ("preconditioner streams bf16 by design; Newton "
                 "iterates/residuals stay f64 (pf/krylov.py)")

#: Boundary reason for the mixed-precision inner GMRES
#: (--pf-precision mixed): the f32 Krylov iterates and the bf16
#: preconditioner stream only PROPOSE a Newton update — the masked
#: mismatch acceptance oracle and the convergence test stay in the
#: working dtype, and a stalled lane falls back to the f64 inner
#: (docs/solvers.md "Mixed precision").
_MIXED_INNER = ("mixed-precision inner GMRES: f32 Krylov iterates + "
                "bf16 preconditioner propose updates; the f64 masked-"
                "mismatch acceptance oracle + per-lane fallback keep "
                "the convergence contract (pf/krylov.py)")


def _bus_case(name: str):
    from freedm_tpu.serve.service import _resolve_bus_case

    return _resolve_bus_case(name)


def _probe(solver) -> Tuple:
    target = getattr(solver, "probe_target", None)
    if target is None:
        raise RuntimeError(
            f"solver {solver!r} exposes no probe_target seam "
            f"(registry orphaned by a refactor?)"
        )
    return target()


# -- builders ---------------------------------------------------------------

def _newton_dense():
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver

    solve, _ = make_newton_solver(synthetic_mesh(118), backend="dense")
    return _probe(solve)


def _newton_sparse():
    from freedm_tpu.pf.sparse import make_sparse_newton_solver

    solve, _ = make_sparse_newton_solver(_bus_case("case_ieee30"))
    return _probe(solve)


def _krylov():
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.krylov import make_krylov_solver

    solve, _ = make_krylov_solver(synthetic_mesh(40), inner_iters=8)
    return _probe(solve)


def _krylov_mixed():
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.krylov import make_krylov_solver

    solve, _ = make_krylov_solver(synthetic_mesh(40), inner_iters=8,
                                  precision="mixed")
    return _probe(solve)


def _newton_sparse_mixed():
    from freedm_tpu.pf.sparse import make_sparse_newton_solver

    solve, _ = make_sparse_newton_solver(_bus_case("case_ieee30"),
                                         precision="mixed")
    return _probe(solve)


def _fdlf():
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.fdlf import make_fdlf_solver

    solve, _ = make_fdlf_solver(synthetic_mesh(200))
    return _probe(solve)


def _ladder():
    from freedm_tpu.grid.cases import vvc_9bus
    from freedm_tpu.pf.ladder import make_ladder_solver

    solve, _ = make_ladder_solver(vvc_9bus())
    return _probe(solve)


def _dc_solve():
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.dc import make_dc_solver

    return _probe(make_dc_solver(synthetic_mesh(200)).solve)


def _dc_screen():
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.dc import make_dc_solver

    return _probe(make_dc_solver(synthetic_mesh(200)).screen_outages)


def _n1_smw():
    from freedm_tpu.pf.n1 import make_n1_screen

    screen = make_n1_screen(_bus_case("case_ieee30"), backend="dense")
    return _probe(screen)


def _cache_delta(precision: str = "f64"):
    import jax.numpy as jnp
    import numpy as np

    from freedm_tpu.pf.krylov import build_fdlf_precond
    from freedm_tpu.serve.cache import _build_delta_program
    from freedm_tpu.utils import cplx

    sys_ = _bus_case("case_ieee30")
    rdtype = cplx.default_rdtype(None)
    precond = build_fdlf_precond(sys_, dtype=rdtype, kind="lu")
    correct = _build_delta_program(sys_, precond, tol=1e-8, max_sweeps=8,
                                   rdtype=rdtype, precision=precision)
    n = sys_.n_bus
    theta0 = jnp.zeros(n, rdtype)
    v0 = jnp.ones(n, rdtype)
    p = jnp.asarray(np.asarray(sys_.p_inj), rdtype)
    q = jnp.asarray(np.asarray(sys_.q_inj), rdtype)
    return correct, (theta0, v0, p, q)


def _cache_delta_mixed():
    return _cache_delta(precision="mixed")


def _topo_sys():
    from freedm_tpu.grid.cases import synthetic_mesh

    return synthetic_mesh(60, seed=2, load_mw=5.0, chord_frac=1.0)


def _topo_radiality():
    from freedm_tpu.pf.topo import make_radiality_check

    return _probe(make_radiality_check(_topo_sys(), r_max=2))


def _topo_screen():
    from freedm_tpu.pf.topo import make_topo_screen

    return _probe(make_topo_screen(_topo_sys(), r_max=2).screen)


def _topo_topk():
    from freedm_tpu.pf.topo import make_topk_merge

    return _probe(make_topk_merge(r_max=2, k=4))


def _topo_ac_verify():
    from freedm_tpu.pf.topo import make_ac_verifier

    return _probe(make_ac_verifier(_bus_case("case_ieee30"), k=2))


def _serve_pf_bucket():
    import numpy as np

    from freedm_tpu.serve.service import PowerFlowEngine

    eng = PowerFlowEngine("case14", backend="dense")
    bucket, n = 4, eng.n_bus
    p = np.broadcast_to(eng._p0, (bucket, n)).copy()
    q = np.broadcast_to(eng._q0, (bucket, n)).copy()
    v0 = np.broadcast_to(eng._v0_flat, (bucket, n)).copy()
    th0 = np.zeros((bucket, n))
    return eng._batched, (p, q, v0, th0)


def _serve_vvc_bucket():
    import numpy as np

    from freedm_tpu.serve.service import VVCEngine

    eng = VVCEngine("vvc_9bus")
    return eng._batched, (np.zeros((2, eng.nb, 3)),)


def _qsts_spec(case: str):
    from freedm_tpu.scenarios.engine import QstsEngine, StudySpec

    return QstsEngine(StudySpec(
        case=case, scenarios=2, steps=8, chunk_steps=4, seed=7,
    ))


def _qsts_bus_chunk():
    eng = _qsts_spec("case14")
    fn = eng._build_bus_chunk(4)
    p, q = eng._bus_injections(0, 4)
    return fn, (eng.initial_state(), p, q)


def _qsts_feeder_chunk():
    eng = _qsts_spec("vvc_9bus")
    fn = eng._build_feeder_chunk(4)
    s_re, s_im = eng._feeder_injections(0, 4)
    return fn, (eng.initial_state(), s_re, s_im)


def _qsts_agents_chunk():
    from freedm_tpu.scenarios.agents import AgentSpec
    from freedm_tpu.scenarios.engine import QstsEngine, StudySpec

    eng = QstsEngine(StudySpec(
        case="case14", scenarios=2, steps=8, chunk_steps=4, seed=7,
        agents=AgentSpec(ev=6, thermostat=6, inverter=4, dr=4),
    ))
    fn = eng._build_bus_chunk(4)
    p, q = eng._bus_injections(0, 4)
    sig, hs, pop = eng._agent_arrays(0, 4)
    return fn, (eng.initial_state(), p, q, sig, hs, pop)


def _lb_round():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from freedm_tpu.modules.lb import lb_round

    # The superstep feeds the auction float32 state (parallel/
    # superstep.py) — trace at the production dtype.
    n = 16
    rng = np.random.RandomState(3)
    net = jnp.asarray(rng.uniform(-2, 2, n), jnp.float32)
    gw = jnp.zeros(n, jnp.float32)
    mask = jnp.ones((n, n), jnp.float32)
    step = 1.0  # migration_step is a build-time scalar, not traced
    return jax.jit(lambda a, b, c: lb_round(a, b, c, step)), (net, gw, mask)


#: Every registered jitted entrypoint (see module docstring).
PROGRAM_REGISTRY: List[ProgramSpec] = [
    ProgramSpec("pf/newton/dense", "freedm_tpu/pf/newton.py",
                _newton_dense, f64=True),
    # The iteration programs take (bp, bq, x, ps, qs, status); the
    # scheduled injections ps/qs (flat argument indices 3, 4) are
    # donated into the realized p/q results — GP004 enforces the
    # declaration against the compiled donate_argnums.
    ProgramSpec("pf/newton/sparse", "freedm_tpu/pf/sparse.py",
                _newton_sparse, f64=True,
                allow_dtypes=frozenset({"bfloat16"}),
                boundary_reason=_BF16_PRECOND,
                donatable=(3, 4)),
    ProgramSpec("pf/newton/sparse/mixed", "freedm_tpu/pf/sparse.py",
                _newton_sparse_mixed, f64=True,
                allow_dtypes=frozenset({"bfloat16", "float32"}),
                boundary_reason=_MIXED_INNER,
                donatable=(3, 4)),
    ProgramSpec("pf/krylov", "freedm_tpu/pf/krylov.py",
                _krylov, f64=True,
                allow_dtypes=frozenset({"bfloat16"}),
                boundary_reason=_BF16_PRECOND,
                donatable=(3, 4)),
    ProgramSpec("pf/krylov/mixed", "freedm_tpu/pf/krylov.py",
                _krylov_mixed, f64=True,
                allow_dtypes=frozenset({"bfloat16", "float32"}),
                boundary_reason=_MIXED_INNER,
                donatable=(3, 4)),
    ProgramSpec("pf/fdlf", "freedm_tpu/pf/fdlf.py", _fdlf, f64=True),
    ProgramSpec("pf/ladder", "freedm_tpu/pf/ladder.py", _ladder, f64=True),
    ProgramSpec("pf/dc/solve", "freedm_tpu/pf/dc.py", _dc_solve, f64=True),
    ProgramSpec("pf/dc/screen", "freedm_tpu/pf/dc.py", _dc_screen, f64=True),
    ProgramSpec("pf/n1/smw", "freedm_tpu/pf/n1.py", _n1_smw, f64=True),
    ProgramSpec("serve/cache/delta", "freedm_tpu/serve/cache.py",
                _cache_delta, f64=True),
    ProgramSpec("serve/cache/delta/mixed", "freedm_tpu/serve/cache.py",
                _cache_delta_mixed, f64=True,
                allow_dtypes=frozenset({"float32"}),
                boundary_reason=(
                    "mixed-precision delta refinement: f32 triangular "
                    "solves over an f32 LU copy propose each sweep's "
                    "correction; iterates/mismatch/exit test stay f64 "
                    "and the host float64 residual verify remains the "
                    "acceptance oracle with warm-tier fall-through "
                    "(serve/cache.py)")),
    # Topology sweeps (pf/topo.py, POST /v1/topo): the structural
    # radiality/connectivity lanes (pure int program), the rank-r SMW
    # screen lanes (LU/Z ride as runtime arguments, GP003), the
    # donating top-k shortlist merge (the carried best-(obj, slots,
    # gid) buffers alias their outputs — GP004 enforces the
    # declaration), and the sparse-backend AC verify bucket.
    ProgramSpec("pf/topo/radiality", "freedm_tpu/pf/topo.py",
                _topo_radiality, f64=False),
    ProgramSpec("pf/topo/screen", "freedm_tpu/pf/topo.py",
                _topo_screen, f64=True),
    ProgramSpec("pf/topo/topk", "freedm_tpu/pf/topo.py",
                _topo_topk, f64=True, donatable=(0, 1, 2)),
    ProgramSpec("pf/topo/ac_verify", "freedm_tpu/pf/topo.py",
                _topo_ac_verify, f64=True,
                allow_dtypes=frozenset({"bfloat16"}),
                boundary_reason=_BF16_PRECOND),
    # Serve dispatch buffers: the padded (p, q, v0, th0) batch donates
    # into the result's (p, q, v, theta) — four [bucket, n] HBM round
    # trips deleted per dispatch.
    ProgramSpec("serve/pf/bucket4", "freedm_tpu/serve/service.py",
                _serve_pf_bucket, f64=True,
                donatable=(0, 1, 2, 3)),
    ProgramSpec("serve/vvc/bucket2", "freedm_tpu/serve/service.py",
                _serve_vvc_bucket, f64=True),
    # QSTS chunk carries: the state NamedTuple (flat argument indices
    # 0..9 bus / 0..7 feeder) round-trips through host numpy at chunk
    # boundaries, so its device copy donates into the identically-
    # shaped output state.
    ProgramSpec("qsts/bus_chunk", "freedm_tpu/scenarios/engine.py",
                _qsts_bus_chunk, f64=True,
                donatable=tuple(range(10))),
    ProgramSpec("qsts/feeder_chunk", "freedm_tpu/scenarios/engine.py",
                _qsts_feeder_chunk, f64=True,
                donatable=tuple(range(8))),
    # Agent-population chunk: the fused agent-step + Newton-solve scan
    # body (docs/agents.md).  The carry grows to the 17-leaf
    # AgentBusState (per-agent SoC/temperature/Q/engagement lanes ride
    # the checkpointed state), all donated; the population itself is a
    # runtime argument (GP003) and must NOT donate — it is reused
    # unchanged every chunk.
    ProgramSpec("qsts/agents_chunk", "freedm_tpu/scenarios/engine.py",
                _qsts_agents_chunk, f64=True,
                donatable=tuple(range(17))),
    ProgramSpec("lb/auction_round", "freedm_tpu/modules/lb.py",
                _lb_round, f64=False),
]


# -- host-side float64 oracle surfaces --------------------------------------

def _host_injections_surface():
    import numpy as np

    from freedm_tpu.pf.krylov import host_injections

    sys_ = _bus_case("case_ieee30")
    n = sys_.n_bus
    return host_injections, (sys_, np.zeros(n, np.float32),
                             np.ones(n, np.float32))


def _true_mismatch_surface():
    import numpy as np

    from freedm_tpu.pf.krylov import KrylovResult, true_mismatch

    sys_ = _bus_case("case_ieee30")
    n = sys_.n_bus
    # float32 INPUTS on purpose: the oracle must promote, not inherit.
    res = KrylovResult(
        v=np.ones(n, np.float32), theta=np.zeros(n, np.float32),
        p=np.zeros(n, np.float32), q=np.zeros(n, np.float32),
        iterations=np.int32(0), converged=np.bool_(False),
        mismatch=np.float32(1.0), fallbacks=np.int32(0),
    )
    return true_mismatch, (sys_, res)


def _cache_verify_surface():
    import numpy as np

    from freedm_tpu.serve.cache import CaseEntry

    sys_ = _bus_case("case_ieee30")
    n = sys_.n_bus
    entry = CaseEntry("case_ieee30", sys_, "dense", "probe")
    # float32 INPUTS on purpose: the verify gate must promote to f64.
    return entry.verify, (
        np.zeros(n, np.float32), np.ones(n, np.float32),
        np.asarray(sys_.p_inj, np.float64),
        np.asarray(sys_.q_inj, np.float64),
    )


#: Host float64 oracle surfaces: the krylov accuracy oracle and the
#: serve cache's residual-verify gate (every residual-verify site the
#: delta tier and the solver claims rely on routes through these).
F64_SURFACES: List[F64Surface] = [
    F64Surface("pf/krylov/host_injections", "freedm_tpu/pf/krylov.py",
               _host_injections_surface),
    F64Surface("pf/krylov/true_mismatch", "freedm_tpu/pf/krylov.py",
               _true_mismatch_surface),
    F64Surface("serve/cache/verify", "freedm_tpu/serve/cache.py",
               _cache_verify_surface),
]
