"""GP001 — dtype flow: declared float64 surfaces stay f64; no stray
low-precision floats outside a declared mixed-precision boundary.

The contract this pins (docs/solvers.md, docs/serving.md): accuracy
claims never rest on reduced-precision self-evaluation.  The residual
oracles (``pf/krylov.host_injections``/``true_mismatch``), the serve
cache's delta-verify gate, and the tolerance tests all run in float64 —
and the *traced* programs feeding them must not silently demote on the
way.  Concretely, per program:

- ``spec.f64`` programs: any ``convert_element_type`` from float64 down
  to f32/bf16/f16 is a finding, and any float program *result* that is
  not f64 is a finding — unless the target dtype is in the spec's
  declared ``allow_dtypes`` boundary (e.g. the bf16 preconditioner
  stream in ``pf/krylov.py``, which only steers convergence and is
  explicitly documented as precision-irrelevant).
- every program: any bf16/f16 value appearing anywhere in the IR
  outside a declared boundary is a finding.  This is exactly the fence
  the planned bf16/f32 inner-GMRES work (ROADMAP "attack the 1.95%
  MFU") needs already standing: when mixed-precision inners land, they
  land as *declared* boundaries, and anything XLA sneaks in beyond the
  declaration fails the build.

Findings aggregate per (program, kind, dtype) with occurrence counts —
one demotion pattern repeated through a scan body is one finding.
"""

from __future__ import annotations

from typing import Dict, Iterable, Tuple

from freedm_tpu.tools.lint_rules.base import Finding
from freedm_tpu.tools.ir_rules.base import (
    DEMOTION_TARGETS,
    LOW_PRECISION_FLOATS,
    IrRule,
    TracedProgram,
    aval_str,
    var_dtype_name,
)


class DtypeFlow(IrRule):
    id = "GP001"
    name = "dtype-flow"
    hint = ("keep the f64 contract end-to-end, or declare the boundary: "
            "add the dtype to the spec's allow_dtypes with a "
            "boundary_reason in ir_rules/registry.py "
            "(docs/static_analysis.md, declared-boundary policy)")

    def check(self, program: TracedProgram) -> Iterable[Finding]:
        spec = program.spec
        allow = set(spec.allow_dtypes)
        demotions: Dict[Tuple[str, str], int] = {}
        low_seen: Dict[str, int] = {}

        for eqn in program.eqns():
            if (spec.f64
                    and eqn.primitive.name == "convert_element_type"):
                src = var_dtype_name(eqn.invars[0]) if eqn.invars else None
                dst = getattr(eqn.params.get("new_dtype"), "name", None)
                if (src == "float64" and dst in DEMOTION_TARGETS
                        and dst not in allow):
                    demotions[(src, dst)] = demotions.get((src, dst), 0) + 1
            for out in eqn.outvars:
                dt = var_dtype_name(out)
                if dt in LOW_PRECISION_FLOATS and dt not in allow:
                    low_seen[dt] = low_seen.get(dt, 0) + 1

        # Arguments and captured constants are IR too: a bf16 input or
        # const whose only consumer upcasts it would produce no bf16
        # OUTVAR, yet low-precision data is flowing through the program
        # — the boundary must still be declared.
        for i, aval in enumerate(program.in_avals):
            dt = getattr(getattr(aval, "dtype", None), "name", None)
            if dt in LOW_PRECISION_FLOATS and dt not in allow:
                yield self.finding(
                    spec,
                    f"program argument {i} is {aval_str(aval)} — "
                    f"{dt} outside a declared mixed-precision boundary",
                )
        for c in program.consts:
            dt = getattr(getattr(c, "dtype", None), "name", None)
            if dt in LOW_PRECISION_FLOATS and dt not in allow:
                shape = list(getattr(c, "shape", ()))
                yield self.finding(
                    spec,
                    f"captured constant {dt}{shape} sits outside a "
                    f"declared mixed-precision boundary",
                )

        for (src, dst), count in sorted(demotions.items()):
            yield self.finding(
                spec,
                f"float64 contract surface demotes {src} -> {dst} "
                f"({count} site(s) in the traced IR)",
            )
        for dt, count in sorted(low_seen.items()):
            yield self.finding(
                spec,
                f"{dt} appears at {count} IR site(s) outside a declared "
                f"mixed-precision boundary",
            )

        if spec.f64:
            for i, aval in enumerate(program.out_avals):
                dt = getattr(getattr(aval, "dtype", None), "name", "")
                if dt.startswith("float") and dt != "float64" \
                        and dt not in allow:
                    yield self.finding(
                        spec,
                        f"float64 contract surface returns result {i} as "
                        f"{aval_str(aval)} (silent output demotion)",
                    )
