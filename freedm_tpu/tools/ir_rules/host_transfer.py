"""GP002 — host transfer: no callback-shaped primitives inside traced
programs.

A ``pure_callback``/``io_callback``/``debug_callback`` (or an infeed/
outfeed) inside a jitted solver body forces a device→host→device round
trip *per execution* — exactly the sync class GL002 polices at the
source level for the dispatch loops, enforced here at the IR level
where a helper three layers down can smuggle one in.  Host-side oracles
(``host_injections``, the cache verify gate) are DESIGNED to run on
host — after the program returns, on materialized arrays — never inside
the program.
"""

from __future__ import annotations

from typing import Dict, Iterable

from freedm_tpu.tools.lint_rules.base import Finding
from freedm_tpu.tools.ir_rules.base import IrRule, TracedProgram

#: Primitive names that move data across the host boundary mid-program.
HOST_TRANSFER_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed",
})


class HostTransfer(IrRule):
    id = "GP002"
    name = "host-transfer"
    hint = ("move the host work outside the traced program (call it on "
            "the materialized result, like true_mismatch / the cache "
            "verify gate), or compute it in-graph")

    def check(self, program: TracedProgram) -> Iterable[Finding]:
        seen: Dict[str, int] = {}
        for eqn in program.eqns():
            name = eqn.primitive.name
            if name in HOST_TRANSFER_PRIMITIVES:
                seen[name] = seen.get(name, 0) + 1
        for name, count in sorted(seen.items()):
            yield self.finding(
                program.spec,
                f"host-transfer primitive `{name}` appears {count} "
                f"time(s) inside the traced program",
            )
