"""GL003 — chunk purity: the QSTS resume-correctness bedrock.

Bit-for-bit chunk-checkpoint resume (docs/scenarios.md) rests on two
statically checkable facts:

1. **All randomness in ``scenarios/profiles.py`` and
   ``scenarios/agents.py`` is drawn at construction.**
   ``ProfileSet.chunk(t0, t1)`` and the agent ``step`` functions must
   be pure in the timestep index; an RNG draw anywhere but a declared
   construction seam (:data:`CONSTRUCTION_SEAMS` — ``__init__``, the
   ``population_rng`` derivation seam, ``build_population``) makes the
   trajectory depend on chunking order and silently breaks
   byte-identical resume.
2. **Nothing feeding checkpoint identity reads clocks or RNG.**  The
   functions that serialize specs/state or name checkpoint files
   (``to_dict``/``from_dict``, ``state_to_jsonable``,
   ``placement_free_spec``, ``*checkpoint*``...) — and everything they
   reach through same-package calls — must not call ``time.*``,
   ``random.*``, ``np.random.*``, ``datetime.*``, ``uuid.*`` or
   ``os.urandom``: a timestamp in a spec digest means an identical
   resubmission no longer matches its own checkpoint.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from freedm_tpu.tools.lint_rules.base import (
    FileIndex,
    Finding,
    FuncInfo,
    ProjectIndex,
    Rule,
)

#: Function names that (de)serialize specs/state or name checkpoints —
#: the roots of the checkpoint-identity reachability walk.
SEED_NAMES = {
    "to_dict", "from_dict", "state_to_jsonable", "state_from_jsonable",
    "placement_free_spec", "strip_timing", "profile_spec",
}
SEED_SUBSTRINGS = ("checkpoint", "ckpt", "identity", "digest")

#: Function names allowed to construct/consume RNGs in the policed
#: construction-only files (profiles.py / agents.py): object
#: constructors, the profiles-module stream-derivation seam, and the
#: agent population builder.  Everything else must be pure in the
#: timestep index.
CONSTRUCTION_SEAMS = {"__init__", "population_rng", "build_population"}

#: Files under scenarios/ whose randomness must be construction-only.
CONSTRUCTION_FILES = ("profiles.py", "agents.py")

IMPURE_PREFIX = (
    "time.", "random.", "numpy.random.", "datetime.", "uuid.",
)
IMPURE_EXACT = {"os.urandom"}


def _is_scenarios(rel: str) -> bool:
    return rel.startswith("scenarios/") or "/scenarios/" in rel


class ChunkPurity(Rule):
    id = "GL003"
    name = "chunk-purity"
    hint = ("chunk windows and checkpoint identity must be pure "
            "functions of the spec and timestep index: draw randomness "
            "once in __init__, and keep clocks/RNG out of anything a "
            "spec digest or checkpoint file name reaches")

    def check(self, project: ProjectIndex) -> Iterable[Finding]:
        scen_files = [project.files[r] for r in sorted(project.files)
                      if _is_scenarios(project.files[r].rel)]
        for fi in scen_files:
            if fi.rel.endswith(CONSTRUCTION_FILES):
                yield from self._check_rng_in_profiles(fi)
        yield from self._check_checkpoint_identity(scen_files)

    # -- rule 1: construction-only RNG in profiles.py / agents.py -----------
    def _check_rng_in_profiles(self, fi: FileIndex) -> Iterable[Finding]:
        # Names bound from np.random.default_rng(...) — or the profiles
        # module's population_rng(...) seam — anywhere in the file.
        rng_names: Set[str] = set()       # rng = np.random.default_rng(...)
        rng_attrs: Set[str] = set()       # self.rng = np.random.default_rng(...)
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                f = node.value.func
                fname = (f.attr if isinstance(f, ast.Attribute)
                         else f.id if isinstance(f, ast.Name) else None)
                if fname in ("default_rng", "population_rng"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            rng_names.add(t.id)
                        elif isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            rng_attrs.add(t.attr)
        for call in fi.calls:
            in_seam = (call.func is not None
                       and call.func.name in CONSTRUCTION_SEAMS)
            if in_seam:
                continue
            is_draw = False
            if call.dotted is not None and call.dotted.startswith("numpy.random."):
                is_draw = True
            elif call.chain and call.chain[0] in rng_names and len(call.chain) > 1:
                is_draw = True
            elif call.chain and len(call.chain) == 3 and \
                    call.chain[0] == "self" and call.chain[1] in rng_attrs:
                is_draw = True
            if is_draw:
                where = call.func.qualname if call.func else "module level"
                yield self.finding(
                    fi.rel, call.lineno, call.col,
                    f"RNG draw `{'.'.join(call.chain or ('np.random',))}` "
                    f"outside __init__ or a declared construction seam "
                    f"(in `{where}`): profile chunks and agent steps must "
                    f"be pure in the timestep index — draw once at "
                    f"construction",
                )

    # -- rule 2: checkpoint identity reaches no clock/RNG --------------------
    def _check_checkpoint_identity(
            self, files: List[FileIndex]) -> Iterable[Finding]:
        # Name-based call graph over the scenarios package.
        funcs_by_name: Dict[str, List[FuncInfo]] = {}
        for fi in files:
            for f in fi.funcs:
                funcs_by_name.setdefault(f.name, []).append(f)

        def is_seed(f: FuncInfo) -> bool:
            low = f.qualname.lower()
            return f.name in SEED_NAMES or any(
                s in low for s in SEED_SUBSTRINGS
            )

        seeds = [f for fi in files for f in fi.funcs if is_seed(f)]
        reachable: Set[int] = set()
        labels: Dict[int, str] = {}
        stack = list(seeds)
        for f in seeds:
            labels[id(f)] = f.qualname
        while stack:
            f = stack.pop()
            if id(f) in reachable:
                continue
            reachable.add(id(f))
            for call in f.file.calls:
                if call.func is not f or call.tail is None:
                    continue
                for g in funcs_by_name.get(call.tail, []):
                    if id(g) not in reachable:
                        labels[id(g)] = labels.get(id(f), f.qualname)
                        stack.append(g)

        for fi in files:
            for call in fi.calls:
                f = call.func
                if f is None or id(f) not in reachable:
                    continue
                d = call.dotted
                if d is None:
                    continue
                if d in IMPURE_EXACT or any(
                        d.startswith(p) for p in IMPURE_PREFIX):
                    yield self.finding(
                        fi.rel, call.lineno, call.col,
                        f"`{d}` reachable from checkpoint identity "
                        f"(via `{labels.get(id(f), f.qualname)}` -> "
                        f"`{f.qualname}`): identical respecs must map to "
                        f"identical checkpoints",
                    )
