"""GL004 — config threading: every config key reaches the CLI and docs.

``GlobalConfig`` (core/config.py) is the single source of truth for
process settings; the contract since PR 3 is that every key threads
through **three** surfaces: the dataclass field, a ``--key`` flag in
``cli.py``, and a row in ``docs/configuration.md``.  A key missing from
any surface is a knob operators cannot discover or set — exactly the
drift this rule pins:

- field without a ``--field-dashed`` CLI flag,
- field not mentioned in docs/configuration.md (dashed or underscored),
- CLI long flag that maps to no field (minus the declared runtime-only
  flags: ``--rounds``, ``--realtime``...),
- ``key = value`` row in the docs' ``freedm.cfg`` block that is not a
  field (a doc row for a removed key).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Tuple

from freedm_tpu.tools.lint_rules.base import (
    FileIndex,
    Finding,
    ProjectIndex,
    Rule,
)

#: CLI flags that are deliberately runtime-only (not persisted config):
#: run-shape and introspection switches.
RUNTIME_ONLY_FLAGS = {
    "config", "list_loggers", "uuid", "rounds", "realtime",
    "summary_every", "profile_dir",
}


class ConfigThreading(Rule):
    id = "GL004"
    name = "config-threading"
    hint = ("thread the key through all three surfaces: the GlobalConfig "
            "field, an add_argument('--key') flag + _load_config mapping "
            "in cli.py, and the freedm.cfg block in docs/configuration.md")

    def check(self, project: ProjectIndex) -> Iterable[Finding]:
        cfg = project.by_suffix("core/config.py")
        if cfg is None:
            return
        fields = self._config_fields(cfg)
        if not fields:
            return
        cli = project.by_suffix("cli.py")
        cli_flags = self._cli_flags(cli) if cli is not None else None
        doc_text = project.read_doc("docs/configuration.md")

        for name, lineno in sorted(fields.items()):
            if cli_flags is not None and name not in cli_flags:
                yield self.finding(
                    cfg.rel, lineno, 4,
                    f"config key `{name}` has no `--{name.replace('_', '-')}`"
                    f" flag in cli.py",
                )
            if doc_text is not None and not self._in_doc(name, doc_text):
                yield self.finding(
                    cfg.rel, lineno, 4,
                    f"config key `{name}` is not documented in "
                    f"docs/configuration.md",
                )

        if cli_flags is not None:
            for name, lineno in sorted(cli_flags.items()):
                if name not in fields and name not in RUNTIME_ONLY_FLAGS:
                    yield self.finding(
                        cli.rel, lineno, 4,
                        f"CLI flag `--{name.replace('_', '-')}` corresponds "
                        f"to no GlobalConfig key (add the field or list it "
                        f"in RUNTIME_ONLY_FLAGS)",
                    )

        if doc_text is not None:
            for key, lineno in self._doc_cfg_keys(doc_text):
                if key.replace("-", "_") not in fields:
                    yield self.finding(
                        "docs/configuration.md", lineno, 0,
                        f"documented freedm.cfg key `{key}` is not a "
                        f"GlobalConfig field (stale doc row?)",
                    )

    # -- surface extraction ---------------------------------------------------
    def _config_fields(self, cfg: FileIndex) -> Dict[str, int]:
        ci = cfg.classes.get("GlobalConfig")
        if ci is None:
            return {}
        out: Dict[str, int] = {}
        for stmt in ci.node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                name = stmt.target.id
                if not name.startswith("_"):
                    out[name] = stmt.lineno
        return out

    def _cli_flags(self, cli: FileIndex) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for call in cli.calls:
            if call.tail != "add_argument":
                continue
            for a in call.node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                        and a.value.startswith("--"):
                    out[a.value[2:].replace("-", "_")] = call.lineno
        return out

    def _in_doc(self, field: str, text: str) -> bool:
        dashed = re.escape(field.replace("_", "-"))
        under = re.escape(field)
        return re.search(
            rf"(?<![\w-])(?:{dashed}|{under})(?![\w-])", text
        ) is not None

    # -- docs freedm.cfg block ------------------------------------------------
    def _doc_cfg_keys(self, text: str) -> List[Tuple[str, int]]:
        """``key = value`` rows of the first fenced block following the
        ``## freedm.cfg`` heading."""
        lines = text.splitlines()
        out: List[Tuple[str, int]] = []
        in_section = False
        in_fence = False
        for i, line in enumerate(lines, start=1):
            if line.strip().startswith("## "):
                in_section = line.strip().lower() == "## freedm.cfg"
                continue
            if not in_section:
                continue
            if line.strip().startswith("```"):
                if in_fence:
                    break  # end of the block: done
                in_fence = True
                continue
            if not in_fence:
                continue
            m = re.match(r"^\s*([a-z][a-z0-9-]*)\s*=", line)
            if m:
                out.append((m.group(1), i))
        return out
