"""GL006 — lock-order discipline across the threaded modules.

Sixteen modules now hold ``threading.Lock``/``RLock``/``Condition``
state (serve, jobs, broker, dcn, devices, core/*), with cross-module
calls made while holding them (a queue updates a metrics gauge under
its condition; a job worker bumps counters under its lock).  Nothing
pins an acquisition order — a new call edge closing a cycle would be a
deadlock that only fires under production interleavings.

This rule builds the **static lock-acquisition graph**:

- lock identities: ``<file>:<Class>.<attr>`` for ``self.X =
  threading.Lock()`` (and RLock/Condition) declarations, ``<file>:<name>``
  for module-level locks, ``<file>:<qualname>.<name>`` for locals;
- per-function acquired-lock sets (``with self._lock:`` /
  ``.acquire()``), transitively closed over resolvable calls
  (``self.method``, module-level singletons — including cross-module
  ``obs.EVENTS.emit`` / ``tracing.TRACER.start`` style access and
  ``REGISTRY.counter(...)``-typed metric constants);
- an edge A→B whenever B is acquired (directly or via a resolvable
  callee) while A is held.

Findings: cycles in the graph (potential deadlocks), and
callback-shaped calls (``on_*``, ``*_cb``, ``*callback``, ``sink``)
invoked while holding a lock — the classic re-entrancy trap (snapshot
under the lock, call after releasing).  The full graph is exported as
the ``lock_graph`` artifact (JSON stats / ``run_lint`` API), which the
``DebugLock`` runtime recorder in the concurrency tests cross-checks
against observed acquisition order.  Scope: library code (``tests/``
excluded).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from freedm_tpu.tools.lint_rules.base import (
    FileIndex,
    Finding,
    FuncInfo,
    ProjectIndex,
    Rule,
    attr_chain,
    find_cycles,
)

_CALLBACK_SAFE = {"notify", "notify_all", "wait", "set", "clear"}


def _is_library(rel: str) -> bool:
    parts = rel.split("/")
    return "tests" not in parts and not rel.endswith("bench.py")


def _module_dotted(rel: str) -> str:
    base = rel[:-3] if rel.endswith(".py") else rel
    if base.endswith("/__init__"):
        base = base[: -len("/__init__")]
    return base.replace("/", ".")


def _is_callbackish(tail: str) -> bool:
    bare = tail.lstrip("_")
    return (bare.startswith("on_") or bare.endswith("_cb")
            or bare.endswith("callback") or bare == "sink")


class LockOrder(Rule):
    id = "GL006"
    name = "lock-order"
    hint = ("pick one global acquisition order and keep it: restructure "
            "so the inner call happens after releasing (snapshot under "
            "the lock, act outside it)")

    def __init__(self):
        self.artifacts: Dict[str, object] = {}

    def check(self, project: ProjectIndex) -> Iterable[Finding]:
        files = [project.files[r] for r in sorted(project.files)
                 if _is_library(project.files[r].rel)]
        if not files:
            self.artifacts["lock_graph"] = {
                "locks": [], "modules": [], "edges": [], "cycles": [],
            }
            return []

        # -- lock declarations ------------------------------------------------
        # (file rel, Class, attr) -> lock id; module-level by (rel, name).
        class_locks: Dict[Tuple[str, str], Dict[str, str]] = {}
        lock_sites: Dict[str, Tuple[str, int]] = {}
        for fi in files:
            for cname, ci in fi.classes.items():
                for attr, lineno in ci.lock_attrs.items():
                    lid = f"{fi.rel}:{cname}.{attr}"
                    class_locks.setdefault((fi.rel, cname), {})[attr] = lid
                    lock_sites[lid] = (fi.rel, lineno)
            for name, lineno in fi.module_locks.items():
                lid = f"{fi.rel}:{name}"
                lock_sites[lid] = (fi.rel, lineno)

        # -- singleton typing -------------------------------------------------
        # (file rel, global name) -> (file rel, class name)
        singleton: Dict[Tuple[str, str], Tuple[str, str]] = {}
        by_module: Dict[str, FileIndex] = {
            _module_dotted(fi.rel): fi for fi in files
        }
        metrics_fi = None
        for fi in files:
            if fi.rel.endswith("core/metrics.py"):
                metrics_fi = fi
        for fi in files:
            for name, call in fi.module_assigns.items():
                if call.chain is None:
                    continue
                if len(call.chain) == 1 and call.chain[0] in fi.classes:
                    singleton[(fi.rel, name)] = (fi.rel, call.chain[0])
                elif (metrics_fi is not None and "REGISTRY" in call.chain
                      and call.tail in ("counter", "gauge", "histogram")):
                    singleton[(fi.rel, name)] = (
                        metrics_fi.rel, call.tail.capitalize()
                    )

        def method_of(file_rel: str, cname: str,
                      mname: str) -> Optional[FuncInfo]:
            """Resolve a method, climbing same-file base classes."""
            fi = project.files.get(file_rel)
            if fi is None:
                return None
            seen: Set[str] = set()
            stack = [cname]
            while stack:
                cn = stack.pop()
                if cn in seen:
                    continue
                seen.add(cn)
                ci = fi.classes.get(cn)
                if ci is None:
                    continue
                if mname in ci.methods:
                    return ci.methods[mname]
                for b in ci.node.bases:
                    if isinstance(b, ast.Name):
                        stack.append(b.id)
            return None

        def resolve_callee(fi: FileIndex, owner: Optional[FuncInfo],
                           chain: Tuple[str, ...]) -> Optional[FuncInfo]:
            if not chain:
                return None
            if chain[0] == "self" and owner is not None \
                    and owner.class_name is not None and len(chain) == 2:
                return method_of(fi.rel, owner.class_name, chain[1])
            if len(chain) == 1:  # bare call: same-file class constructor
                ci = fi.classes.get(chain[0])
                if ci is not None:
                    return ci.methods.get("__init__")
                return None
            # GLOBAL.meth where GLOBAL is a typed singleton of this file
            # or of an imported module (obs.EVENTS.emit, TRACER.start).
            if len(chain) == 2:
                target = singleton.get((fi.rel, chain[0]))
                if target is None:
                    dotted = fi.alias.get(chain[0])
                    if dotted is not None and "." in dotted:
                        mod, _, gname = dotted.rpartition(".")
                        mfi = by_module.get(mod)
                        if mfi is not None:
                            target = singleton.get((mfi.rel, gname))
                if target is not None:
                    return method_of(target[0], target[1], chain[1])
                return None
            if len(chain) == 3:
                mod = fi.alias.get(chain[0], chain[0])
                mfi = by_module.get(mod)
                if mfi is not None:
                    target = singleton.get((mfi.rel, chain[1]))
                    if target is not None:
                        return method_of(target[0], target[1], chain[2])
            return None

        # -- per-function walk: direct locks, calls, held-calls ---------------
        direct: Dict[int, Set[str]] = {}
        calls_all: Dict[int, List[FuncInfo]] = {}
        held_calls: List[Tuple[FuncInfo, Tuple[str, ...], FuncInfo]] = []
        edges: Set[Tuple[str, str]] = set()
        findings: List[Finding] = []

        def class_lock_attr(fi: FileIndex, cname: str,
                            attr: str) -> Optional[str]:
            """Resolve a ``self.<attr>`` lock, climbing same-file base
            classes (a subclass method acquiring an inherited lock must
            land on the declaring class's lock id)."""
            seen: Set[str] = set()
            stack = [cname]
            while stack:
                cn = stack.pop()
                if cn in seen:
                    continue
                seen.add(cn)
                lid = class_locks.get((fi.rel, cn), {}).get(attr)
                if lid is not None:
                    return lid
                ci = fi.classes.get(cn)
                if ci is not None:
                    for b in ci.node.bases:
                        if isinstance(b, ast.Name):
                            stack.append(b.id)
            return None

        def lock_of(fi: FileIndex, owner: FuncInfo, expr: ast.expr,
                    locals_: Dict[str, str]) -> Optional[str]:
            ch = attr_chain(expr)
            if ch is None:
                return None
            if len(ch) == 2 and ch[0] == "self" and owner.class_name:
                return class_lock_attr(fi, owner.class_name, ch[1])
            if len(ch) == 1:
                if ch[0] in locals_:
                    return locals_[ch[0]]
                if ch[0] in fi.module_locks:
                    return f"{fi.rel}:{ch[0]}"
            return None

        def walk_func(fi: FileIndex, owner: FuncInfo) -> None:
            locals_: Dict[str, str] = {}
            my_direct: Set[str] = set()
            my_calls: List[FuncInfo] = []

            def note_call(node: ast.Call, held: Tuple[str, ...]) -> None:
                ch = attr_chain(node.func)
                tail = (ch[-1] if ch else
                        getattr(node.func, "attr", None)
                        or getattr(node.func, "id", None))
                callee = resolve_callee(fi, owner, ch) if ch else None
                if callee is not None:
                    my_calls.append(callee)
                    if held:
                        held_calls.append((owner, held, callee))
                if held and tail and tail not in _CALLBACK_SAFE \
                        and _is_callbackish(tail):
                    findings.append(self.finding(
                        fi.rel, node.lineno, node.col_offset,
                        f"callback-shaped call `{tail}` invoked while "
                        f"holding {held[-1]} — re-entrancy/deadlock trap; "
                        f"snapshot under the lock, invoke after release",
                    ))
                # .acquire() counts as taking the lock for the edge set.
                if ch and tail == "acquire":
                    lid = lock_of(fi, owner, node.func.value, locals_)
                    if lid is not None:
                        my_direct.add(lid)
                        for h in held:
                            if h != lid:
                                edges.add((h, lid))

            def scan_expr(node: ast.expr, held: Tuple[str, ...]) -> None:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        note_call(sub, held)

            def walk(stmts, held: Tuple[str, ...]) -> None:
                for stmt in stmts:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        continue  # nested defs walked as their own funcs
                    if isinstance(stmt, ast.Assign) and \
                            isinstance(stmt.value, ast.Call):
                        ch = attr_chain(stmt.value.func)
                        d = fi.resolve(ch) if ch else None
                        if d in ("threading.Lock", "threading.RLock",
                                 "threading.Condition"):
                            for t in stmt.targets:
                                if isinstance(t, ast.Name):
                                    lid = (f"{fi.rel}:{owner.qualname}"
                                           f".{t.id}")
                                    locals_[t.id] = lid
                                    lock_sites[lid] = (fi.rel, stmt.lineno)
                    if isinstance(stmt, ast.With):
                        new_held = held
                        for item in stmt.items:
                            scan_expr(item.context_expr, held)
                            lid = lock_of(fi, owner, item.context_expr,
                                          locals_)
                            if lid is not None:
                                my_direct.add(lid)
                                for h in new_held:
                                    if h != lid:
                                        edges.add((h, lid))
                                new_held = new_held + (lid,)
                        walk(stmt.body, new_held)
                        continue
                    for child in ast.iter_child_nodes(stmt):
                        if isinstance(child, ast.stmt):
                            walk([child], held)
                        elif isinstance(child, ast.expr):
                            scan_expr(child, held)
                        elif isinstance(child, (ast.withitem,
                                                ast.excepthandler,
                                                ast.keyword)):
                            for sub in ast.iter_child_nodes(child):
                                if isinstance(sub, ast.stmt):
                                    walk([sub], held)
                                elif isinstance(sub, ast.expr):
                                    scan_expr(sub, held)

            walk(owner.node.body, ())
            direct[id(owner)] = my_direct
            calls_all[id(owner)] = my_calls

        for fi in files:
            for f in fi.funcs:
                if isinstance(f.node, ast.Lambda):
                    continue
                walk_func(fi, f)

        # -- transitive acquired-lock sets (bounded fixpoint) -----------------
        trans: Dict[int, Set[str]] = {
            k: set(v) for k, v in direct.items()
        }
        for _ in range(12):
            changed = False
            for k, callees in calls_all.items():
                cur = trans[k]
                before = len(cur)
                for c in callees:
                    cur |= trans.get(id(c), set())
                if len(cur) != before:
                    changed = True
            if not changed:
                break

        for owner, held, callee in held_calls:
            for lid in trans.get(id(callee), ()):
                for h in held:
                    if h != lid:
                        edges.add((h, lid))

        # -- cycles -----------------------------------------------------------
        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        cycles = find_cycles(adj)
        for cyc in cycles:
            rel, lineno = lock_sites.get(cyc[0], (files[0].rel, 1))
            findings.append(self.finding(
                rel, lineno, 0,
                "lock-order cycle (potential deadlock): "
                + " -> ".join(cyc + [cyc[0]]),
            ))

        modules = sorted({lock_sites[lid][0] for lid in lock_sites})
        self.artifacts["lock_graph"] = {
            "locks": sorted(lock_sites),
            "modules": modules,
            "edges": sorted([list(e) for e in edges]),
            "cycles": [list(c) for c in cycles],
        }
        return findings
