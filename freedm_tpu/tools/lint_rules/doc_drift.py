"""GL005 — metric/event/span drift against docs/observability.md.

docs/observability.md promises "a scrape exposes every name below";
since PR 1 the metric catalogue, the event schema, and the span-kind
table have been kept in sync by hand.  This rule pins the sync in both
directions:

- every metric registered on the process registry
  (``REGISTRY.counter/gauge/histogram("name", ...)``) must appear in
  docs/observability.md; every row of a ``| Metric |`` table must be a
  registered metric (no orphan rows for deleted metrics);
- every journaled event name (``EVENTS.emit("name", ...)`` /
  ``self.journal.emit``) must be documented; every ``| Event |`` table
  row must be emitted somewhere (f-string event names match by their
  static prefix);
- every span ``kind=`` passed to ``TRACER.start`` must appear in the
  tracing kind table, and vice versa.

Scope: library code only (``tests/`` and ``bench.py`` may register
scratch metrics for assertions; those are not part of the documented
vocabulary).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Set, Tuple

from freedm_tpu.tools.lint_rules.base import (
    FileIndex,
    Finding,
    ProjectIndex,
    Rule,
)

_CODE_SPAN = re.compile(r"`([^`]+)`")

DOC_PATH = "docs/observability.md"


def _is_library(rel: str) -> bool:
    parts = rel.split("/")
    return "tests" not in parts and not rel.endswith("bench.py") \
        and not rel.startswith("tests")


def _doc_tokens(text: str) -> Set[str]:
    """All code-span tokens in the doc, normalized: ``{labels}``
    stripped, split on ``/``, commas and whitespace.  Parsed line by
    line (code spans never wrap) so ``` fences cannot desync the
    backtick pairing."""
    out: Set[str] = set()
    for line in text.splitlines():
        if line.lstrip().startswith("```"):
            continue
        for span in _CODE_SPAN.findall(line):
            for piece in re.split(r"[\s,/]+", span):
                piece = piece.split("{")[0].strip().strip("\\|")
                if piece:
                    out.add(piece)
    return out


def _doc_table_rows(text: str, header_cell: str) -> List[Tuple[str, int]]:
    """(first-cell token, lineno) for every row of tables whose header's
    first cell is ``header_cell`` (e.g. "Metric", "Event", "kind")."""
    rows: List[Tuple[str, int]] = []
    lines = text.splitlines()
    mode = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            mode = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        first = cells[0]
        if first == header_cell:
            mode = True
            continue
        if set(first) <= {"-", " ", ":"}:
            continue  # separator row
        if mode:
            for span in _CODE_SPAN.findall(first):
                for piece in re.split(r"[\s,/]+", span):
                    # Escaped pipes inside label sets: name{a\|b}
                    piece = piece.split("{")[0].strip().strip("\\|")
                    if piece:
                        rows.append((piece, i))
    return rows


class DocDrift(Rule):
    id = "GL005"
    name = "doc-drift"
    hint = ("docs/observability.md is the metric/event/span contract: add "
            "the row when registering a name, delete the row when removing "
            "one — a scrape must expose exactly the documented vocabulary")

    def check(self, project: ProjectIndex) -> Iterable[Finding]:
        lib_files = [project.files[r] for r in sorted(project.files)
                     if _is_library(project.files[r].rel)]
        metrics = self._registered_metrics(lib_files)
        events, event_prefixes = self._emitted_events(lib_files)
        kinds = self._span_kinds(lib_files)
        if not metrics and not events and not kinds:
            return  # nothing instrumented in this scan
        text = project.read_doc(DOC_PATH)
        if text is None:
            return
        documented = _doc_tokens(text)

        for name, (rel, lineno) in sorted(metrics.items()):
            if name not in documented:
                yield self.finding(
                    rel, lineno, 0,
                    f"metric `{name}` is registered but has no row/mention "
                    f"in {DOC_PATH}",
                )
        for name, (rel, lineno) in sorted(events.items()):
            if name not in documented:
                yield self.finding(
                    rel, lineno, 0,
                    f"journal event `{name}` is emitted but undocumented "
                    f"in {DOC_PATH}",
                )
        for kind, (rel, lineno) in sorted(kinds.items()):
            if kind not in documented:
                yield self.finding(
                    rel, lineno, 0,
                    f"span kind `{kind}` is recorded but missing from the "
                    f"tracing kind table in {DOC_PATH}",
                )

        for token, lineno in _doc_table_rows(text, "Metric"):
            if not re.fullmatch(r"[a-z][a-z0-9_]+", token):
                continue
            if token not in metrics:
                yield self.finding(
                    DOC_PATH, lineno, 0,
                    f"orphan doc row: metric `{token}` is documented but "
                    f"registered nowhere",
                )
        for token, lineno in _doc_table_rows(text, "Event"):
            if not re.fullmatch(r"[a-z][a-z0-9_.]+", token):
                continue
            if token in events:
                continue
            if any(token.startswith(p) for p in event_prefixes):
                continue
            yield self.finding(
                DOC_PATH, lineno, 0,
                f"orphan doc row: event `{token}` is documented but "
                f"emitted nowhere",
            )
        for token, lineno in _doc_table_rows(text, "kind"):
            if not re.fullmatch(r"[a-z][a-z0-9_]+", token):
                continue
            if token not in kinds:
                yield self.finding(
                    DOC_PATH, lineno, 0,
                    f"orphan doc row: span kind `{token}` is documented "
                    f"but recorded nowhere",
                )

    # -- code-side indexes ----------------------------------------------------
    def _registered_metrics(
            self, files: List[FileIndex]) -> Dict[str, Tuple[str, int]]:
        out: Dict[str, Tuple[str, int]] = {}
        for fi in files:
            for call in fi.calls:
                if call.tail not in ("counter", "gauge", "histogram"):
                    continue
                if not call.chain or "REGISTRY" not in call.chain:
                    continue
                name = call.arg_str(0)
                if name is not None:
                    out.setdefault(name, (fi.rel, call.lineno))
        return out

    def _emitted_events(
            self, files: List[FileIndex],
    ) -> Tuple[Dict[str, Tuple[str, int]], Set[str]]:
        out: Dict[str, Tuple[str, int]] = {}
        prefixes: Set[str] = set()
        for fi in files:
            for call in fi.calls:
                if call.tail != "emit" or not call.chain:
                    continue
                holder = call.chain[-2] if len(call.chain) >= 2 else ""
                if holder not in ("EVENTS", "journal", "_journal", "events"):
                    continue
                name = call.arg_str(0)
                if name is not None:
                    out.setdefault(name, (fi.rel, call.lineno))
                    continue
                prefix = call.arg_fstring_prefix(0)
                if prefix:
                    prefixes.add(prefix)
        return out, prefixes

    def _span_kinds(
            self, files: List[FileIndex]) -> Dict[str, Tuple[str, int]]:
        out: Dict[str, Tuple[str, int]] = {}
        for fi in files:
            for call in fi.calls:
                if call.tail != "start" or not call.chain:
                    continue
                if "TRACER" not in call.chain:
                    continue
                kind = call.kwarg_str("kind")
                if kind is not None:
                    out.setdefault(kind, (fi.rel, call.lineno))
        return out
