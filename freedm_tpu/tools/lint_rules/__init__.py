"""gridlint rule catalogue.

| ID    | Invariant                                                      |
|-------|----------------------------------------------------------------|
| GL001 | jit purity: no host-impure calls inside traced functions       |
| GL002 | hot-path syncs: no implicit device syncs in dispatch/chunk loops |
| GL003 | chunk purity: RNG/time never feed chunk windows or checkpoint identity |
| GL004 | config threading: every config key in cli.py AND docs/configuration.md |
| GL005 | metric/event/span drift vs docs/observability.md               |
| GL006 | lock order: static acquisition graph acyclic, no callbacks under locks |

Each rule lives in its own module and visits the shared per-file
indexes built by the engine (:mod:`freedm_tpu.tools.gridlint`).
Adding a rule: subclass :class:`~freedm_tpu.tools.lint_rules.base.Rule`,
give it an ``id``/``name``/``hint``, implement ``check(project)``, and
append it to :func:`all_rules` — docs/static_analysis.md walks through
a full example.
"""

from __future__ import annotations

from typing import List

from freedm_tpu.tools.lint_rules.base import Rule


def all_rules() -> List[Rule]:
    """Fresh rule instances, in reporting order (stateful rules like
    GL006 carry per-run artifacts, so instances are not shared)."""
    from freedm_tpu.tools.lint_rules.chunk_purity import ChunkPurity
    from freedm_tpu.tools.lint_rules.config_threading import ConfigThreading
    from freedm_tpu.tools.lint_rules.doc_drift import DocDrift
    from freedm_tpu.tools.lint_rules.hot_path import HotPathSync
    from freedm_tpu.tools.lint_rules.jit_purity import JitPurity
    from freedm_tpu.tools.lint_rules.lock_order import LockOrder

    return [
        JitPurity(),
        HotPathSync(),
        ChunkPurity(),
        ConfigThreading(),
        DocDrift(),
        LockOrder(),
    ]
