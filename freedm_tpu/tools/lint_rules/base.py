"""Shared engine types for gridlint: per-file AST indexes and findings.

The engine (:mod:`freedm_tpu.tools.gridlint`) walks every file's tree
ONCE and records what the rules need into a :class:`FileIndex` — import
aliases, function definitions with qualified names, every call with its
resolved dotted callee, class lock attributes, module-level singleton
assignments, and ``# gridlint: disable=`` suppressions.  Rules then
visit these shared indexes (plus targeted sub-walks of individual
function bodies for flow-sensitive checks) instead of re-walking whole
trees.

Everything here is stdlib-only (``ast`` + ``tokenize``): gridlint must
run in a bare CI container before any dependency is installed.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*gridlint:\s*disable(?:=(?P<rules>[A-Za-z0-9_,\s]+))?"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation, pointing at a repo-relative location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""

    def sort_key(self) -> Tuple[str, int, int, str]:
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
        }


class FuncInfo:
    """One function/method/lambda definition."""

    __slots__ = ("node", "name", "qualname", "class_name", "file", "params")

    def __init__(self, node, name: str, qualname: str,
                 class_name: Optional[str], file: "FileIndex"):
        self.node = node
        self.name = name
        self.qualname = qualname  # dotted: "Class.meth", "outer.inner"
        self.class_name = class_name  # nearest enclosing class, if any
        self.file = file
        params: List[str] = []
        args = getattr(node, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                params.append(a.arg)
            if args.vararg:
                params.append(args.vararg.arg)
            if args.kwarg:
                params.append(args.kwarg.arg)
        self.params = tuple(params)


class CallInfo:
    """One call site with its (best-effort) resolved callee."""

    __slots__ = ("node", "chain", "dotted", "tail", "func", "lineno", "col")

    def __init__(self, node: ast.Call, chain: Optional[Tuple[str, ...]],
                 dotted: Optional[str], func: Optional[FuncInfo]):
        self.node = node
        #: Raw attribute chain, e.g. ("obs", "EVENTS", "emit"); None when
        #: the base is not a plain name (a call result, a subscript...).
        self.chain = chain
        #: Chain with the head import alias resolved, joined with dots
        #: (e.g. "freedm_tpu.core.metrics.EVENTS.emit", "numpy.asarray").
        self.dotted = dotted
        #: Terminal callee name — always available, even when the chain
        #: is unresolvable (e.g. ".item" on a subscript).
        self.tail = (
            chain[-1] if chain
            else getattr(node.func, "attr", None)
            or getattr(node.func, "id", None)
        )
        self.func = func  # innermost enclosing FuncInfo (None at module level)
        self.lineno = node.lineno
        self.col = node.col_offset

    def arg_str(self, i: int = 0) -> Optional[str]:
        """The ``i``-th positional argument if it is a string literal."""
        if len(self.node.args) > i:
            a = self.node.args[i]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a.value
        return None

    def arg_fstring_prefix(self, i: int = 0) -> Optional[str]:
        """Leading constant text of an f-string positional argument."""
        if len(self.node.args) > i:
            a = self.node.args[i]
            if isinstance(a, ast.JoinedStr) and a.values:
                head = a.values[0]
                if isinstance(head, ast.Constant) and isinstance(head.value, str):
                    return head.value
        return None

    def kwarg_str(self, name: str) -> Optional[str]:
        for kw in self.node.keywords:
            if kw.arg == name and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
        return None


class ClassInfo:
    __slots__ = ("node", "name", "methods", "lock_attrs", "file")

    def __init__(self, node: ast.ClassDef, name: str, file: "FileIndex"):
        self.node = node
        self.name = name
        self.file = file
        self.methods: Dict[str, FuncInfo] = {}
        #: attr name -> lineno of a ``self.X = threading.Lock()`` style
        #: assignment anywhere in the class body.
        self.lock_attrs: Dict[str, int] = {}


def attr_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """``a.b.c`` -> ("a", "b", "c"); None when the base is not a Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def names_in(node: ast.AST) -> Set[str]:
    """All Name identifiers appearing in an expression subtree."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def find_cycles(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Cycles in a directed graph (iterative white/grey/black DFS with
    parent-chain reconstruction; one cycle reported per distinct node
    set).  Shared by GL006's static lock graph and the runtime
    ``DebugLock`` recorder (:mod:`freedm_tpu.core.debuglock`), so the
    two verdicts cannot drift."""
    cycles: List[List[str]] = []
    color: Dict[str, int] = {}
    parent: Dict[str, Optional[str]] = {}
    reported: Set[frozenset] = set()

    for root in sorted(adj):
        if color.get(root):
            continue
        stack: List[Tuple[str, List[str]]] = [
            (root, sorted(adj.get(root, ())))
        ]
        color[root] = 1
        parent[root] = None
        while stack:
            node, nxts = stack[-1]
            if nxts:
                nxt = nxts.pop(0)
                if color.get(nxt, 0) == 0:
                    color[nxt] = 1
                    parent[nxt] = node
                    stack.append((nxt, sorted(adj.get(nxt, ()))))
                elif color.get(nxt) == 1:  # back edge: a cycle
                    cyc = [nxt]
                    cur = node
                    while cur is not None and cur != nxt:
                        cyc.append(cur)
                        cur = parent.get(cur)
                    cyc.reverse()
                    key = frozenset(cyc)
                    if key not in reported:
                        reported.add(key)
                        cycles.append(cyc)
            else:
                color[node] = 2
                stack.pop()
    return cycles


class FileIndex:
    """Everything gridlint knows about one parsed source file."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        #: local name -> dotted import target ("np" -> "numpy",
        #: "obs" -> "freedm_tpu.core.metrics", "jit" -> "jax.jit").
        self.alias: Dict[str, str] = {}
        self.funcs: List[FuncInfo] = []
        self.calls: List[CallInfo] = []
        self.classes: Dict[str, ClassInfo] = {}
        #: module-level NAME = <Call> assignments (singleton typing).
        self.module_assigns: Dict[str, CallInfo] = {}
        #: module-level NAME = threading.Lock()/RLock()/Condition().
        self.module_locks: Dict[str, int] = {}
        #: lineno -> set of suppressed rule ids, or {"*"} for all.
        self.suppress: Dict[int, Set[str]] = {}
        self._index_suppressions()
        self._index_tree()

    # -- suppression comments ------------------------------------------------
    def _index_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                raw = m.group("rules")
                rules = (
                    {r.strip() for r in raw.split(",") if r.strip()}
                    if raw else {"*"}
                )
                line = tok.start[0]
                self.suppress.setdefault(line, set()).update(rules)
                # A standalone suppression comment covers the next line
                # too (handy above long expressions).
                text_before = tok.line[: tok.start[1]].strip()
                if not text_before:
                    self.suppress.setdefault(line + 1, set()).update(rules)
        except (tokenize.TokenError, IndentationError):
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppress.get(line)
        return bool(rules) and ("*" in rules or rule in rules)

    # -- the single tree walk ------------------------------------------------
    def resolve(self, chain: Tuple[str, ...]) -> str:
        head = self.alias.get(chain[0], chain[0])
        return ".".join((head,) + chain[1:])

    def _index_tree(self) -> None:
        self._walk(self.tree.body, func=None, cls=None, qual=())

    def _walk(self, stmts: Iterable[ast.stmt], func: Optional[FuncInfo],
              cls: Optional[ClassInfo], qual: Tuple[str, ...]) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, func, cls, qual)

    def _walk_stmt(self, stmt: ast.stmt, func: Optional[FuncInfo],
                   cls: Optional[ClassInfo], qual: Tuple[str, ...]) -> None:
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            self._index_import(stmt)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = qual + (stmt.name,)
            fi = FuncInfo(stmt, stmt.name, ".".join(qn),
                          cls.name if cls else None, self)
            self.funcs.append(fi)
            if cls is not None and len(qual) == 1 and qual[0] == cls.name:
                cls.methods[stmt.name] = fi
            for deco in stmt.decorator_list:
                self._visit_expr(deco, func, cls)
            self._walk(stmt.body, fi, cls, qn)
            return
        if isinstance(stmt, ast.ClassDef):
            ci = ClassInfo(stmt, stmt.name, self)
            # Top-level classes only go in the by-name table; nested
            # classes still get their bodies walked.
            if cls is None and func is None:
                self.classes[stmt.name] = ci
            for deco in stmt.decorator_list:
                self._visit_expr(deco, func, cls)
            self._walk(stmt.body, func, ci, qual + (stmt.name,))
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._index_assign(stmt, func, cls)
        # Generic: visit all child expressions, recurse into child
        # statement lists (if/for/while/with/try bodies).
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._walk_stmt(child, func, cls, qual)
            elif isinstance(child, ast.expr):
                self._visit_expr(child, func, cls)
            elif isinstance(child, (ast.withitem, ast.excepthandler,
                                    ast.keyword)):
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.stmt):
                        self._walk_stmt(sub, func, cls, qual)
                    elif isinstance(sub, ast.expr):
                        self._visit_expr(sub, func, cls)

    def _index_import(self, stmt) -> None:
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                if a.asname:  # import jax.numpy as jnp -> jnp: jax.numpy
                    self.alias[a.asname] = a.name
                else:  # import numpy / import a.b -> first segment binds
                    head = a.name.split(".")[0]
                    self.alias.setdefault(head, head)
        else:  # ImportFrom
            if stmt.module is None or stmt.level:
                return  # relative imports: leave unresolved
            for a in stmt.names:
                if a.name == "*":
                    continue
                local = a.asname or a.name
                self.alias[local] = f"{stmt.module}.{a.name}"

    def _index_assign(self, stmt, func: Optional[FuncInfo],
                      cls: Optional[ClassInfo]) -> None:
        value = getattr(stmt, "value", None)
        if value is None:
            return
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        is_lock_ctor = False
        ctor_dotted = None
        if isinstance(value, ast.Call):
            ch = attr_chain(value.func)
            if ch:
                ctor_dotted = self.resolve(ch)
                is_lock_ctor = ctor_dotted in (
                    "threading.Lock", "threading.RLock", "threading.Condition",
                )
        for t in targets:
            if isinstance(t, ast.Name) and func is None and cls is None:
                if isinstance(value, ast.Call):
                    ch = attr_chain(value.func)
                    self.module_assigns[t.id] = CallInfo(
                        value, ch, self.resolve(ch) if ch else None, None
                    )
                if is_lock_ctor:
                    self.module_locks[t.id] = stmt.lineno
            if (is_lock_ctor and cls is not None
                    and isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                cls.lock_attrs.setdefault(t.attr, stmt.lineno)

    def _visit_expr(self, expr: ast.expr, func: Optional[FuncInfo],
                    cls: Optional[ClassInfo]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                ch = attr_chain(node.func)
                self.calls.append(CallInfo(
                    node, ch, self.resolve(ch) if ch else None, func
                ))
            elif isinstance(node, ast.Lambda):
                qn = ((func.qualname + ".<lambda>") if func else "<lambda>")
                self.funcs.append(FuncInfo(
                    node, "<lambda>", qn, cls.name if cls else None, self
                ))


class ProjectIndex:
    """All indexed files plus the repo root for cross-file rules."""

    def __init__(self, root: Path):
        self.root = root
        self.files: Dict[str, FileIndex] = {}

    def add(self, fi: FileIndex) -> None:
        self.files[fi.rel] = fi

    def by_suffix(self, suffix: str) -> Optional[FileIndex]:
        for rel, fi in sorted(self.files.items()):
            if rel.endswith(suffix):
                return fi
        return None

    def read_doc(self, rel: str) -> Optional[str]:
        p = self.root / rel
        try:
            return p.read_text(encoding="utf-8")
        except OSError:
            return None


class Rule:
    """Base: one invariant with an ID, a one-line hint, and a check."""

    id = "GL000"
    name = "base"
    hint = ""

    def check(self, project: ProjectIndex) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, path: str, line: int, col: int, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(self.id, path, line, col, message,
                       self.hint if hint is None else hint)
