"""GL002 — hot-path syncs: implicit device syncs in the serving and
QSTS dispatch loops.

The serve pipeline's lanes (assembly + per-workload executors), the
QSTS chunk loop, and the broker round loop are the paths where one
stray ``float(result[...])`` or ``.item()`` turns an async device
dispatch into a synchronous round-trip — the latency cliff the
micro-batcher exists to avoid.  These paths are *declared* in
:data:`HOT_PATHS` (the hot-path registry): each entry names a
function, where device values enter it (parameters and/or
``.solve()``-style calls), and which sync primitives it is *allowed*
to use because it IS the designed measurement/pull point (the
executor-side ``MicroBatcher._execute``'s deferred
``block_until_ready`` is how ``serve_solve_seconds`` stays honest;
``scatter``'s one ``np.asarray`` per result field is the designed
single device→host transfer).

Within a registered function the rule walks statements in source
order, tracking which names are device-derived ("tainted"): sources
taint, an *allowed* ``np.asarray`` pull untaints its target, and any
``float()`` / ``int()`` / ``np.asarray`` / ``np.array`` applied to a
tainted expression — or any unallowed ``block_until_ready`` /
``.item()`` — is a finding.

The registry is also self-checking: an entry whose function no longer
exists (a rename) is itself a finding, so the declaration cannot rot.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import FrozenSet, Iterable, List, Optional, Set, Tuple

from freedm_tpu.tools.lint_rules.base import (
    FileIndex,
    Finding,
    FuncInfo,
    ProjectIndex,
    Rule,
    attr_chain,
    names_in,
)


@dataclass(frozen=True)
class HotPath:
    """One declared hot path.

    ``path_suffix``/``qualname`` locate the function (closures defined
    inside it are covered too).  ``sources`` are parameter names that
    carry device arrays; ``source_calls`` are method tails whose return
    value is a device array (``solve``).  ``allow`` lists permitted
    sync primitives at this designed boundary: ``"block_until_ready"``
    and/or ``"asarray"``.
    """

    path_suffix: str
    qualname: str
    sources: Tuple[str, ...] = ()
    source_calls: Tuple[str, ...] = ()
    allow: FrozenSet[str] = frozenset()


HOT_PATHS: Tuple[HotPath, ...] = (
    # serve pipeline, stage 1 — the assembly lane: coalescing loop and
    # host-side assemble.  Pure host work: NO device value may be
    # pulled or synced here, ever (the whole point of the pipeline is
    # that assembly overlaps device execution).
    HotPath("freedm_tpu/serve/batcher.py", "MicroBatcher._run"),
    HotPath("freedm_tpu/serve/batcher.py", "MicroBatcher._run_serial"),
    HotPath("freedm_tpu/serve/batcher.py", "MicroBatcher._run_pipelined"),
    HotPath("freedm_tpu/serve/batcher.py", "MicroBatcher._dispatch"),
    HotPath("freedm_tpu/serve/batcher.py", "MicroBatcher._assemble"),
    # serve pipeline, stage 2 — the device-executor side: device
    # results flow out of engine.solve; the ONE designed deferred
    # jax.block_until_ready lives in MicroBatcher._execute (it is the
    # serve_solve_seconds / compile-account measurement boundary, on
    # both the pipelined and the --serve-pipeline-depth 0 path).
    HotPath("freedm_tpu/serve/batcher.py", "ExecutorLane._run"),
    HotPath("freedm_tpu/serve/batcher.py", "MicroBatcher._execute",
            source_calls=("solve",),
            allow=frozenset({"block_until_ready"})),
    # Engine solve(): dispatch-only since the pipeline split — any
    # block_until_ready inside an engine would serialize the assembly
    # lane's overlap and is a finding.
    HotPath("freedm_tpu/serve/service.py", "PowerFlowEngine.solve"),
    HotPath("freedm_tpu/serve/service.py", "N1Engine.solve"),
    HotPath("freedm_tpu/serve/service.py", "VVCEngine.solve"),
    HotPath("freedm_tpu/serve/service.py", "TopoEngine.solve"),
    HotPath("freedm_tpu/serve/service.py", "TopoEngine._solve_one"),
    # Engine scatter(): the one designed device->host pull per result
    # field; everything after the np.asarray is host numpy.
    HotPath("freedm_tpu/serve/service.py", "PowerFlowEngine.scatter",
            sources=("r", "results"), allow=frozenset({"asarray"})),
    HotPath("freedm_tpu/serve/service.py", "N1Engine.scatter",
            sources=("r", "results"), allow=frozenset({"asarray"})),
    HotPath("freedm_tpu/serve/service.py", "VVCEngine.scatter",
            sources=("out", "results"), allow=frozenset({"asarray"})),
    HotPath("freedm_tpu/serve/service.py", "TopoEngine.scatter",
            sources=("r", "results"), allow=frozenset({"asarray"})),
    # Incremental serving tier (serve/cache.py): lookup and insert are
    # pure host work (dict probes + numpy compares over host arrays) —
    # zero syncs allowed, ever: a device pull on the submit path would
    # re-serialize exactly the latency the cache exists to remove.  The
    # delta tier's correction is the ONE designed sync of the cache
    # path: delta_answer dispatches the jitted program and pulls the
    # candidate at the verify boundary (np.asarray), where the host
    # float64 residual check decides serve-or-fall-through.
    HotPath("freedm_tpu/serve/cache.py", "ServeCache.lookup"),
    HotPath("freedm_tpu/serve/cache.py", "ServeCache.insert"),
    HotPath("freedm_tpu/serve/cache.py", "ServeCache.delta_answer",
            source_calls=("delta_fn",), allow=frozenset({"asarray"})),
    # The scatter-side cache population + single-flight settlement:
    # host arrays only (scatter already performed the designed pull).
    HotPath("freedm_tpu/serve/service.py", "Service._publish_pf"),
    # QSTS chunk loop: run_chunk owns the designed chunk-exit sync +
    # host pull (checkpoint state must be host numpy); the outer study
    # loop and the job workers must not sync at all.
    HotPath("freedm_tpu/scenarios/engine.py", "QstsEngine.run_chunk",
            allow=frozenset({"block_until_ready", "asarray"})),
    HotPath("freedm_tpu/scenarios/engine.py", "run_study"),
    HotPath("freedm_tpu/scenarios/jobs.py", "JobManager._run"),
    HotPath("freedm_tpu/scenarios/jobs.py", "JobManager._execute"),
    # Broker phase handlers: the round loop itself.
    HotPath("freedm_tpu/runtime/broker.py", "Broker.run_round"),
    HotPath("freedm_tpu/runtime/broker.py", "Broker.run"),
    # Replica router (serve/router.py): pure host HTTP proxying — no
    # device value can ever appear on a routing path, so zero syncs are
    # allowed anywhere in the attempt loop or the single-forward step.
    HotPath("freedm_tpu/serve/router.py", "Router.route"),
    HotPath("freedm_tpu/serve/router.py", "Router._route_attempts"),
    HotPath("freedm_tpu/serve/router.py", "Router._forward_once"),
    # Fault injection (core/faults.py): should() runs inside the DCN
    # pump and executor-lane hot paths whenever a schedule is active —
    # host-only bookkeeping, zero syncs.
    HotPath("freedm_tpu/core/faults.py", "FaultRegistry.should"),
)

#: numpy coercions that force a device transfer when fed a jax array.
_NP_COERCIONS = {
    "numpy.asarray", "numpy.array", "numpy.float64", "numpy.float32",
    "numpy.int32", "numpy.int64", "numpy.ravel", "numpy.copy",
}


class HotPathSync(Rule):
    id = "GL002"
    name = "hot-path-sync"
    hint = ("implicit device syncs stall the dispatch pipeline: pull "
            "results once at the engine's designed scatter/asarray "
            "boundary; if this site IS a new designed sync point, "
            "declare it in lint_rules/hot_path.py HOT_PATHS")

    def check(self, project: ProjectIndex) -> Iterable[Finding]:
        for hp in HOT_PATHS:
            fi = self._file_for(project, hp)
            if fi is None:
                continue  # module not in this scan — nothing to check
            owner = self._owner_func(fi, hp)
            if owner is None:
                yield self.finding(
                    fi.rel, 1, 0,
                    f"hot-path registry entry `{hp.qualname}` matches no "
                    f"function in {fi.rel} — update HOT_PATHS in "
                    f"lint_rules/hot_path.py after the rename",
                )
                continue
            yield from self._check_func(fi, owner, hp)

    def _file_for(self, project: ProjectIndex, hp: HotPath) -> Optional[FileIndex]:
        for rel in sorted(project.files):
            if rel.endswith(hp.path_suffix):
                return project.files[rel]
        return None

    def _owner_func(self, fi: FileIndex, hp: HotPath) -> Optional[FuncInfo]:
        for f in fi.funcs:
            if f.qualname == hp.qualname:
                return f
        return None

    # -- order-sensitive taint walk ------------------------------------------
    def _check_func(self, fi: FileIndex, owner: FuncInfo,
                    hp: HotPath) -> Iterable[Finding]:
        tainted: Set[str] = set(hp.sources)
        findings: List[Finding] = []

        def is_source_call(call: ast.Call) -> bool:
            tail = getattr(call.func, "attr", None) or \
                getattr(call.func, "id", None)
            return tail in hp.source_calls

        def dotted(node: ast.expr) -> Optional[str]:
            ch = attr_chain(node)
            return fi.resolve(ch) if ch else None

        def expr_tainted(node: ast.expr) -> bool:
            if tainted and (names_in(node) & tainted):
                return True
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and is_source_call(sub):
                    return True
            return False

        def flag(node: ast.AST, what: str) -> None:
            findings.append(self.finding(
                fi.rel, node.lineno, node.col_offset,
                f"{what} in hot path `{hp.qualname}` "
                f"(declared in the GL002 hot-path registry)",
            ))

        def visit_call(call: ast.Call) -> bool:
            """Check one call; returns True if it is an *allowed pull*
            (np.asarray under an `asarray` allowance)."""
            d = dotted(call.func)
            tail = getattr(call.func, "attr", None) or \
                getattr(call.func, "id", None)
            if tail == "block_until_ready":
                if "block_until_ready" not in hp.allow:
                    flag(call, "unguarded `block_until_ready` device sync")
                return False
            if tail == "item" and isinstance(call.func, ast.Attribute) \
                    and not call.args:
                flag(call, "`.item()` device sync")
                return False
            arg_bad = any(expr_tainted(a) for a in call.args)
            if d in _NP_COERCIONS:
                if "asarray" in hp.allow:
                    return True  # designed pull: untaints its target
                if arg_bad:
                    flag(call, f"`{d}` host coercion of a device result")
                return False
            if d in ("float", "int", "bool") and arg_bad:
                flag(call, f"`{d}()` host coercion of a device result")
            return False

        def scan_expr(node: ast.expr) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    visit_call(sub)

        def handle_assign(targets: List[ast.expr], value: ast.expr) -> None:
            # RHS first: flag syncs inside it, then propagate taint.
            pulled = False
            if isinstance(value, ast.Call):
                pulled = visit_call(value)
                for a in value.args:
                    scan_expr(a)
                for kw in value.keywords:
                    scan_expr(kw.value)
            else:
                scan_expr(value)
            rhs_tainted = (not pulled) and expr_tainted(value)
            names: Set[str] = set()
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
            if rhs_tainted:
                tainted.update(names)
            else:
                tainted.difference_update(names)

        def walk_stmts(stmts: Iterable[ast.stmt]) -> None:
            for stmt in stmts:
                if isinstance(stmt, ast.Assign):
                    handle_assign(stmt.targets, stmt.value)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    handle_assign([stmt.target], stmt.value)
                elif isinstance(stmt, ast.AugAssign):
                    handle_assign([stmt.target], stmt.value)
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walk_stmts(stmt.body)  # closures share the hot path
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    # Iterating a device result taints the loop variable
                    # (`for row in results: float(row)` is a per-lane sync).
                    scan_expr(stmt.iter)
                    names = {n.id for n in ast.walk(stmt.target)
                             if isinstance(n, ast.Name)}
                    if expr_tainted(stmt.iter):
                        tainted.update(names)
                    else:
                        tainted.difference_update(names)
                    walk_stmts(stmt.body)
                    walk_stmts(stmt.orelse)
                else:
                    for child in ast.iter_child_nodes(stmt):
                        if isinstance(child, ast.stmt):
                            walk_stmts([child])
                        elif isinstance(child, ast.expr):
                            scan_expr(child)
                        elif isinstance(child, (ast.withitem,
                                                ast.excepthandler,
                                                ast.keyword)):
                            for sub in ast.iter_child_nodes(child):
                                if isinstance(sub, ast.stmt):
                                    walk_stmts([sub])
                                elif isinstance(sub, ast.expr):
                                    scan_expr(sub)

        walk_stmts(owner.node.body)
        return findings
