"""GL001 — jit purity: host-impure calls inside traced functions.

A function handed to ``jax.jit`` / ``vmap`` / ``shard_map`` or used as
a ``lax.scan`` / ``while_loop`` / ``cond`` body executes its Python
exactly once per trace.  A ``time.time()`` or ``np.random`` draw inside
one silently freezes into the compiled program (the value the first
trace saw, forever), ``print`` runs only at trace time, ``.item()`` /
``np.asarray`` force a device sync mid-trace or fail under vmap — the
exact bug class behind the PR 6 ``stop_gradient`` / vmap-span fixes.

Detection is lexical, matching the contract's wording: any listed
impure call *lexically inside* a traced function (including nested
defs) is flagged.  Trace-time constants computed with numpy on static
arguments are legitimate in rare factory patterns — suppress those
sites explicitly with ``# gridlint: disable=GL001`` so the exception
is visible in review.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from freedm_tpu.tools.lint_rules.base import (
    FileIndex,
    Finding,
    ProjectIndex,
    Rule,
    attr_chain,
)

#: Resolved dotted callables whose function-valued arguments are traced
#: (argument positions that become traced bodies).
TRACING_CALLS: Dict[str, Tuple[int, ...]] = {
    "jax.jit": (0,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    # cond(pred, true_fn, false_fn, *operands) / switch(i, branches, *ops):
    # ONLY the function positions — operands are data, and a Name operand
    # matching a module-level def must not be dragged in as a traced root.
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "jax.experimental.shard_map.shard_map": (0,),
    # The repo's own wrapper: jit(shard_map(fn)) over the lane mesh.
    "freedm_tpu.parallel.mesh.shard_batched": (0,),
}

#: Decorators that make the decorated function a traced body.  Matched
#: on the resolved dotted name of the decorator (or of ``partial``'s
#: first argument).
TRACING_DECOS = {
    "jax.jit", "jax.vmap", "jax.pmap", "jax.checkpoint", "jax.remat",
    "jax.experimental.shard_map.shard_map",
}

#: Impure callees: exact resolved dotted names.
IMPURE_EXACT = {
    "print",
    "numpy.asarray", "numpy.array",
    "os.urandom",
    "uuid.uuid1", "uuid.uuid4",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

#: Impure callees: resolved dotted-name prefixes (module families).
IMPURE_PREFIX = ("time.", "random.", "numpy.random.")


def _dotted_of(fi: FileIndex, node: ast.expr):
    ch = attr_chain(node)
    return fi.resolve(ch) if ch else None


class JitPurity(Rule):
    id = "GL001"
    name = "jit-purity"
    hint = ("traced bodies run their Python once per trace: hoist host "
            "work (clocks, RNG, prints, numpy coercions, .item()) out of "
            "the jit/vmap/scan body; a deliberate trace-time constant "
            "gets an explicit `# gridlint: disable=GL001`")

    def check(self, project: ProjectIndex) -> Iterable[Finding]:
        for rel in sorted(project.files):
            fi = project.files[rel]
            yield from self._check_file(fi)

    # -- traced-root discovery ----------------------------------------------
    def _traced_roots(self, fi: FileIndex) -> List[Tuple[ast.AST, str]]:
        roots: List[Tuple[ast.AST, str]] = []
        seen: Set[int] = set()

        def add(node: ast.AST, label: str) -> None:
            if id(node) not in seen:
                seen.add(id(node))
                roots.append((node, label))

        by_name: Dict[str, List] = {}
        for f in fi.funcs:
            by_name.setdefault(f.name, []).append(f)

        # Decorated definitions.
        for f in fi.funcs:
            deco_list = getattr(f.node, "decorator_list", [])
            for deco in deco_list:
                target = deco.func if isinstance(deco, ast.Call) else deco
                dotted = _dotted_of(fi, target)
                if dotted in TRACING_DECOS:
                    add(f.node, f.qualname)
                elif dotted in ("functools.partial", "partial") and \
                        isinstance(deco, ast.Call) and deco.args:
                    inner = _dotted_of(fi, deco.args[0])
                    if inner in TRACING_DECOS:
                        add(f.node, f.qualname)

        # Call-site arguments of tracing transforms.
        for call in fi.calls:
            if call.dotted is None:
                continue
            positions = TRACING_CALLS.get(call.dotted)
            if positions is None:
                continue
            for pos in positions:
                if pos >= len(call.node.args):
                    continue
                arg = call.node.args[pos]
                # lax.switch takes its branches as a sequence.
                elems = (
                    arg.elts if isinstance(arg, (ast.List, ast.Tuple))
                    else [arg]
                )
                for el in elems:
                    if isinstance(el, ast.Lambda):
                        add(el, f"<lambda>@{call.lineno}")
                    elif isinstance(el, ast.Name):
                        for f in by_name.get(el.id, []):
                            add(f.node, f.qualname)
                    elif isinstance(el, ast.Attribute):
                        for f in by_name.get(el.attr, []):
                            if f.class_name is not None:
                                add(f.node, f.qualname)
        return roots

    # -- the lexical purity walk --------------------------------------------
    def _check_file(self, fi: FileIndex) -> Iterable[Finding]:
        for root, label in self._traced_roots(fi):
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted_of(fi, node.func)
                bad = None
                if dotted is not None:
                    if dotted in IMPURE_EXACT:
                        bad = dotted
                    else:
                        for pre in IMPURE_PREFIX:
                            if dotted.startswith(pre):
                                bad = dotted
                                break
                if bad is None and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    bad = ".item()"
                if bad is not None:
                    yield self.finding(
                        fi.rel, node.lineno, node.col_offset,
                        f"host-impure call `{bad}` inside traced "
                        f"function `{label}` (jit/vmap/scan bodies must "
                        f"be trace-pure)",
                    )
