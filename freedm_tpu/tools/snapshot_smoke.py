"""Snapshot smoke: 3-replica fleet + router, one clean cut, one torn.

The CI acceptance step for the consistent-cut observatory
(docs/snapshots.md): spin up a small real fleet — three serve replica
processes (reusing the chaos rig's ``--replica`` entry) fronted by the
cache-affinity router in this process — then prove both directions:

- **clean cut**: ``POST /v1/snapshot`` through the router assembles a
  complete marker-coordinated cut with ZERO invariant violations, the
  stored cut is served back at ``GET /v1/snapshot/<id>``, and
  ``tools/snapshot_report.py --cut`` exits 0 on it;
- **torn scrape**: two uncoordinated ``/stats`` scrapes of one replica
  with traffic in between, glued by ``snapshot_report.py --torn``, MUST
  exit 1 with a ``ticket_accounting`` finding — the same fleet, the
  same counters, only the coordination missing.

One command, one pass/fail JSON artifact::

    python -m freedm_tpu.tools.snapshot_smoke --out snapshot_smoke.json

Exit code 0 iff every check passed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

from freedm_tpu.tools.chaos import (
    REPO,
    _Check,
    _Replica,
    _get_json,
    _post_pf,
    _post_pf_replica,
)

CASE = "case14"


def run_smoke(n_replicas: int = 3, out: Optional[str] = None,
              workdir: Optional[str] = None) -> Dict:
    import tempfile

    from freedm_tpu.serve.router import Router, RouterConfig, RouterServer
    from freedm_tpu.tools import snapshot_report

    t0 = time.monotonic()
    wd = workdir or tempfile.mkdtemp(prefix="freedm_snapsmoke_")
    cache_dir = os.path.join(wd, "jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    env = dict(
        os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR=cache_dir,
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1",
    )
    check = _Check()
    replicas = [_Replica(i, None, env) for i in range(n_replicas)]
    router_server = None
    cut: Dict = {}
    try:
        ports = [rep.wait_port(300.0) for rep in replicas]
        check.record("replicas_up", all(p is not None for p in ports),
                     f"ports={ports}")
        if not all(p is not None for p in ports):
            raise RuntimeError("replica spawn failed")
        router = Router(
            [rep.id for rep in replicas],
            RouterConfig(probe_interval_s=0.5, default_timeout_s=60.0),
        )
        router_server = RouterServer(router, port=0).start()
        primed = _post_pf(router_server.port, CASE, timeout_s=240.0)
        check.record("fleet_primed", primed, f"case={CASE}")

        # Clean cut: marker-coordinated capture over the whole fleet.
        cut = router.snapshot()
        check.record(
            "clean_cut_complete",
            cut["status"] == "complete"
            and len(cut["nodes"]) == n_replicas,
            f"status={cut['status']} nodes={sorted(cut['nodes'])}",
        )
        check.record(
            "clean_cut_zero_violations", not cut["violations"],
            f"violations={cut['violations']}",
        )
        served = _get_json(router_server.port,
                           f"/v1/snapshot/{cut['snapshot_id']}")
        check.record(
            "cut_served_by_id",
            served.get("snapshot_id") == cut["snapshot_id"],
            f"GET /v1/snapshot/{cut['snapshot_id']}",
        )
        cut_path = os.path.join(wd, "cut.json")
        with open(cut_path, "w") as fh:
            json.dump(cut, fh)
        rc = snapshot_report.main(["--cut", cut_path])
        check.record("report_clean_cut_exit_0", rc == 0, f"rc={rc}")

        # Torn scrape on the SAME fleet: counters from two instants,
        # traffic in between — the report must exit 1.
        victim = replicas[0]
        early = _get_json(victim.port, "/stats")
        for _ in range(4):
            _post_pf_replica(victim.port, CASE)
        late = _get_json(victim.port, "/stats")
        early_path = os.path.join(wd, "early_stats.json")
        late_path = os.path.join(wd, "late_stats.json")
        with open(early_path, "w") as fh:
            json.dump(early, fh)
        with open(late_path, "w") as fh:
            json.dump(late, fh)
        rc = snapshot_report.main(["--torn", early_path, late_path])
        check.record(
            "report_torn_scrape_exit_1", rc == 1,
            f"rc={rc} early_offered={(early.get('ledger') or {}).get('offered')} "
            f"late_offered={(late.get('ledger') or {}).get('offered')}",
        )
    except Exception as e:  # noqa: BLE001 — the artifact must exist
        check.record("rig_error", False, repr(e))
    finally:
        if router_server is not None:
            router_server.stop()
        for rep in replicas:
            if rep.alive():
                rep.proc.terminate()
        deadline = time.monotonic() + 10.0
        for rep in replicas:
            while rep.alive() and time.monotonic() < deadline:
                time.sleep(0.1)
            if rep.alive():
                rep.proc.kill()
    artifact = {
        "pass": check.passed,
        "replicas": n_replicas,
        "duration_s": round(time.monotonic() - t0, 1),
        "checks": check.results,
        "snapshot_id": cut.get("snapshot_id"),
        "capture_ms": cut.get("capture_ms"),
        "workdir": wd,
    }
    if out:
        with open(out, "w") as fh:
            json.dump(artifact, fh, indent=2)
    print(json.dumps({"snapshot_smoke_pass": artifact["pass"],
                      "failed": [c["name"] for c in check.results
                                 if not c["ok"]]}), flush=True)
    return artifact


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Consistent-cut snapshot smoke "
                    "(3-replica fleet + router)"
    )
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--out", default=None, metavar="PATH")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)
    artifact = run_smoke(n_replicas=args.replicas, out=args.out,
                         workdir=args.workdir)
    return 0 if artifact["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
