"""CI smoke for agent-population QSTS jobs: submit, poll, verify.

Starts a real :class:`~freedm_tpu.serve.ServeServer` with a
:class:`~freedm_tpu.scenarios.jobs.JobManager` on an ephemeral port,
submits a small closed-loop agent-population study on case14 through
``POST /v1/qsts`` (the ``agents`` field — docs/agents.md), polls
``GET /v1/jobs/<id>`` to completion, and sanity-asserts the agent
summary rows (population count, agent-step rate, energy/Q aggregates)
plus the ``qsts_agent_steps_per_sec`` / ``qsts_agents_total`` gauges on
``GET /metrics``.  The typed-rejection paths the agents field adds are
exercised too: unknown sub-field, feeder case, population over the
``qsts_agents_max`` ceiling.  One command, exit code 0 iff healthy:

    python -m freedm_tpu.tools.agents_smoke

Used by ``.github/workflows/ci.yml``; also a handy local sanity check
after touching the agents path.
"""

from __future__ import annotations

import json
import math
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

POLL_TIMEOUT_S = 300.0


def _post(port: int, path: str, payload: dict) -> Tuple[int, dict]:
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        with e:
            return e.code, json.loads(e.read())


def _get_raw(port: int, path: str) -> Tuple[int, bytes]:
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        with e:
            return e.code, e.read()


def _get(port: int, path: str) -> Tuple[int, dict]:
    code, body = _get_raw(port, path)
    return code, json.loads(body)


def main(argv: Optional[List[str]] = None) -> int:
    from freedm_tpu.scenarios.jobs import JobManager
    from freedm_tpu.serve import ServeConfig, ServeServer, Service

    svc = Service(ServeConfig(max_batch=4, buckets=(1, 4)))
    jm = JobManager(workers=1).start()
    srv = ServeServer(svc, port=0, jobs=jm).start()
    print(f"[agents-smoke] server on port {srv.port}", flush=True)
    failures: List[str] = []

    def ok(name: str, cond: bool, detail: str = "") -> None:
        print(f"[agents-smoke] {'ok  ' if cond else 'FAIL'} {name}  {detail}",
              flush=True)
        if not cond:
            failures.append(name)

    agents = {"ev": 60, "thermostat": 50, "inverter": 40, "dr": 30}
    try:
        code, d = _post(srv.port, "/v1/qsts", {
            "case": "case14", "scenarios": 4, "steps": 24,
            "dt_minutes": 60.0, "chunk_steps": 8, "seed": 3,
            "agents": agents,
        })
        ok("submit_202", code == 202 and "job_id" in d, f"code={code} {d}")
        job_id = d.get("job_id", "")
        deadline = time.monotonic() + POLL_TIMEOUT_S
        j = {}
        while time.monotonic() < deadline:
            code, j = _get(srv.port, f"/v1/jobs/{job_id}")
            if code != 200 or j.get("state") in ("completed", "failed",
                                                 "cancelled"):
                break
            time.sleep(0.5)
        ok("job_completed", j.get("state") == "completed",
           f"state={j.get('state')} error={j.get('error')}")
        s = j.get("summary") or {}
        ok("agents_total_stamped",
           s.get("agents_total") == sum(agents.values()),
           f"agents_total={s.get('agents_total')}")
        ok("closed_loop_stamped", s.get("agents_closed_loop") is True,
           f"closed={s.get('agents_closed_loop')}")
        ok("agent_rate_stamped",
           (s.get("agent_steps_per_sec") or 0) > 0,
           f"rate={s.get('agent_steps_per_sec')}")
        ok("agent_energy_finite",
           math.isfinite(s.get("agent_energy_puh_mean", math.nan)),
           f"energy={s.get('agent_energy_puh_mean')}")
        ok("all_converged", s.get("lane_steps_not_converged") == 0,
           f"nonconv={s.get('lane_steps_not_converged')}")

        code, body = _get_raw(srv.port, "/metrics")
        text = body.decode()
        rate = total = None
        for line in text.splitlines():
            if line.startswith("qsts_agent_steps_per_sec "):
                rate = float(line.split()[1])
            elif line.startswith("qsts_agents_total "):
                total = float(line.split()[1])
        ok("metric_agent_rate", code == 200 and (rate or 0) > 0,
           f"qsts_agent_steps_per_sec={rate}")
        ok("metric_agents_total", total == sum(agents.values()),
           f"qsts_agents_total={total}")

        code, d = _post(srv.port, "/v1/qsts", {
            "case": "case14", "scenarios": 2, "steps": 8,
            "agents": {"evs": 5},
        })
        ok("typed_unknown_field",
           code == 400 and d["error"]["type"] == "invalid_request",
           f"code={code}")
        code, d = _post(srv.port, "/v1/qsts", {
            "case": "vvc_9bus", "scenarios": 2, "steps": 8,
            "agents": {"ev": 5},
        })
        ok("typed_feeder_rejected",
           code == 400 and d["error"]["type"] == "invalid_request",
           f"code={code}")
        code, d = _post(srv.port, "/v1/qsts", {
            "case": "case14", "scenarios": 2, "steps": 8,
            "agents": {"ev": 2_000_000},
        })
        ok("typed_over_ceiling",
           code == 400 and d["error"]["type"] == "invalid_request",
           f"code={code}")
    finally:
        srv.stop()
        jm.stop()
        svc.stop()
    print(json.dumps({"agents_smoke_pass": not failures,
                      "failed": failures}), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
