"""Audit a consistent-cut fleet snapshot: typed findings, typed exit.

The offline half of the snapshot observatory (docs/snapshots.md): the
capture machinery (:mod:`freedm_tpu.core.snapshot`) assembles cut
documents at runtime; this tool re-runs the invariant auditor over a
cut AFTER the fact — from a stored cut file, from the ``snapshot.node``
events in one or more slice journals, or (the negative proof) from two
uncoordinated ``/stats`` scrapes glued into a torn document.

Modes (exactly one):

``--cut cut.json``
    An assembled cut document — the body of the router's
    ``GET /v1/snapshot/<id>``, a coordinator node doc from the metrics
    server's ``GET /snapshot?id=``, or anything :func:`assemble_cut`
    produced.  A bare node doc (no ``nodes`` map) is wrapped into a
    single-node cut first.

``--events journal.jsonl [more.jsonl ...] [--snapshot-id SID]``
    Assemble the cut from the ``snapshot.node`` records in the given
    event journals (each slice journals its own doc when its cut
    closes).  Without ``--snapshot-id`` the newest snapshot_id seen
    across the journals is audited.

``--torn early_stats.json late_stats.json``
    The negative proof: glue the admission counters of the EARLY
    ``/stats`` scrape to the offer/settle counters of the LATE one
    (:func:`torn_serve_doc`) and audit that — under traffic between the
    two scrapes this MUST flag ticket-accounting violations, which is
    what demonstrates the marker coordination is load-bearing.

Exit codes: **0** the cut audits clean, **1** the auditor returned one
or more typed violations, **2** internal error (unreadable input, no
nodes to audit).  The report itself is one JSON object on stdout.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from freedm_tpu.core.snapshot import (
    Violation,
    assemble_cut,
    audit_cut,
    torn_serve_doc,
)


def _load_json(path: str) -> Dict:
    with open(path) as fh:
        return json.load(fh)


def cut_from_file(path: str) -> Dict:
    """A stored cut document; a bare node doc becomes a one-node cut."""
    doc = _load_json(path)
    if "nodes" in doc:
        return doc
    sid = str(doc.get("snapshot_id", "cut"))
    return assemble_cut(sid, [doc])


def cut_from_journals(paths: List[str],
                      snapshot_id: Optional[str] = None) -> Optional[Dict]:
    """Assemble a cut from ``snapshot.node`` journal records.  Every
    slice journals its own per-node doc; joining the journals joins the
    fleet.  Newest snapshot wins when no id is pinned."""
    node_events: List[Dict] = []
    for path in paths:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line (a live journal)
                if rec.get("event") == "snapshot.node" and "doc" in rec:
                    node_events.append(rec)
    if snapshot_id is None:
        if not node_events:
            return None
        snapshot_id = node_events[-1].get("snapshot_id")
    docs = [rec["doc"] for rec in node_events
            if rec.get("snapshot_id") == snapshot_id]
    if not docs:
        return None
    return assemble_cut(str(snapshot_id), docs)


def torn_cut(early_path: str, late_path: str) -> Dict:
    """The uncoordinated-scrape document, as a one-node cut."""
    early = _load_json(early_path)
    late = _load_json(late_path)
    torn = torn_serve_doc(early.get("ledger", early),
                          late.get("ledger", late))
    return assemble_cut("torn-scrape", [{
        "snapshot_id": "torn-scrape",
        "node": str(early.get("node", "scrape")),
        "status": "complete",
        "serve": torn,
    }])


def report(cut: Dict) -> Dict:
    violations: List[Violation] = audit_cut(cut)
    return {
        "snapshot_id": cut.get("snapshot_id"),
        "status": cut.get("status"),
        "nodes": sorted(cut.get("nodes", {})),
        "violations": [v.as_dict() for v in violations],
        "pass": not violations,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Audit a consistent-cut fleet snapshot "
                    "(exit 0 clean / 1 violations / 2 internal error)"
    )
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--cut", metavar="CUT_JSON",
                      help="stored cut document (or bare node doc)")
    mode.add_argument("--events", nargs="+", metavar="JOURNAL",
                      help="assemble the cut from snapshot.node records "
                           "in these event journals")
    mode.add_argument("--torn", nargs=2,
                      metavar=("EARLY_STATS", "LATE_STATS"),
                      help="negative proof: audit the torn document two "
                           "uncoordinated /stats scrapes produce")
    ap.add_argument("--snapshot-id", default=None, metavar="SID",
                    help="pin the snapshot to audit (--events mode; "
                         "default: the newest one journaled)")
    args = ap.parse_args(argv)
    try:
        if args.cut:
            cut = cut_from_file(args.cut)
        elif args.torn:
            cut = torn_cut(args.torn[0], args.torn[1])
        else:
            cut = cut_from_journals(args.events, args.snapshot_id)
        if cut is None or not cut.get("nodes"):
            print(json.dumps({"error": "no node documents to audit",
                              "snapshot_id": args.snapshot_id}))
            return 2
        rep = report(cut)
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(json.dumps({"error": repr(e)}))
        return 2
    print(json.dumps(rep, indent=2))
    return 0 if rep["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
