"""Noise-aware perf-regression gate over ``bench.py`` snapshots.

The repo's BENCH trajectory (``BENCH_r01..r05.json``) shows the hot
kernels drifting by 4x across PRs when someone was watching — this tool
is the watcher that doesn't sleep: feed it the JSON line ``bench.py``
prints and it (a) flattens the snapshot into a flat metric dict,
(b) appends it to a rolling ``bench_history.jsonl``, and (c) judges the
current run against the history's rolling baseline with per-metric
relative thresholds, exiting nonzero with a per-metric verdict table on
a regression.  Designed to run in CI on a cheap ``--sections`` subset:

    python bench.py --sections quick > snap.json
    python -m freedm_tpu.tools.perf_gate snap.json \
        --history bench_history.jsonl

Noise discipline:

- **Rolling baseline** — the *median* of the last ``--window`` runs
  (default 8) that carried the metric, so one slow CI minute in the
  history cannot poison the baseline the way a mean would.
- **Min-samples rule** — a metric with fewer than ``--min-samples``
  history points (default 3) is ``baseline`` (pass, build history);
  gating starts only once the baseline is real.
- **Direction-aware** — metric names carry their own polarity
  (``*_ms``/``*_seconds``/latency = lower is better; ``*_per_sec``/
  ``qps``/``speedup`` = higher is better); names matching neither rule
  are reported as ``info`` and never gate.
- **Per-metric thresholds** — ``--threshold 0.25`` is the default
  relative tolerance; ``--set-threshold name=0.5`` overrides noisy
  metrics individually.
- **Absolute floors** — ``--floor name=7000`` pins a metric to an
  absolute bar independent of the rolling baseline (below it for a
  higher-is-better metric — above it for lower-is-better — is
  ``REGRESSED`` even while the baseline is still building).  This is
  how a recovered regression stays recovered:
  ``--floor lb_256node_rounds_per_sec=7000``.

Exit codes: 0 = pass (ok/improved/baseline/info only), 1 = at least
one ``REGRESSED`` metric, 2 = unreadable input.  The snapshot is
appended to the history only on a passing run — a regressed run must
not become the next run's baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Dict, List, Optional, Tuple

#: Keys whose subtrees are never flattened into gateable metrics: the
#: registry snapshot is a scrape (huge, already covered by the explicit
#: bench numbers), distributions/buckets are shape tables not scalars.
SKIP_KEYS = {"metrics", "batch_lanes_distribution", "buckets"}

#: Name fragments that mark a metric lower-is-better / higher-is-better.
LOWER_BETTER = ("_ms", "_seconds", "latency", "mismatch", "residual",
                "shed", "errors", "nonconv", "iters_mean", "iters_max",
                "_bytes")
HIGHER_BETTER = ("per_sec", "qps", "speedup", "reduction_pct", "mfu",
                 "vs_baseline", "rounds_per")


def flatten(obj, prefix: str = "") -> Dict[str, float]:
    """Dot-joined numeric leaves of a bench snapshot (bools excluded —
    a flipped assertion is a correctness problem, not a perf drift)."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            if k in SKIP_KEYS:
                continue
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten(v, key))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if prefix:
            out[prefix] = float(obj)
    return out


def direction(name: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 informational.
    Higher-better fragments win ties (``..._per_sec`` contains no
    lower-better fragment, but ``...ms_per_iteration`` style names
    must resolve deterministically).  ``roofline_*`` columns are always
    informational: achieved MFU/intensity on a shared CI host is
    trajectory data for the accelerator-run diff, not a gate — their
    own drift gate is the roofline inventory diff (bench
    ``--sections roofline``), which compares only the deterministic
    model columns."""
    low = name.lower()
    if "roofline_" in low:
        return 0
    if any(f in low for f in HIGHER_BETTER):
        return 1
    if any(f in low for f in LOWER_BETTER):
        return -1
    return 0


def load_history(path: str) -> List[dict]:
    """The history file's entries (oldest first); [] when absent."""
    if not path or not os.path.exists(path):
        return []
    out = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a torn tail write must not kill the gate
            if isinstance(rec, dict) and isinstance(rec.get("metrics"), dict):
                out.append(rec)
    return out


def append_history(path: str, flat: Dict[str, float],
                   label: str = "") -> None:
    rec = {"label": label, "metrics": flat}
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(rec) + "\n")


def gate(
    flat: Dict[str, float],
    history: List[dict],
    threshold: float = 0.25,
    min_samples: int = 3,
    window: int = 8,
    per_metric: Optional[Dict[str, float]] = None,
    floors: Optional[Dict[str, float]] = None,
) -> Tuple[List[dict], bool]:
    """Judge one flattened snapshot against the rolling baseline.

    Returns ``(verdicts, passed)``; each verdict row is
    ``{metric, status, current, baseline, samples, change_pct,
    threshold_pct}`` with status one of ``ok`` / ``improved`` /
    ``REGRESSED`` / ``baseline`` / ``info``.  ``floors`` are absolute
    bars judged on top of (and independent of) the rolling baseline.
    """
    per_metric = per_metric or {}
    floors = floors or {}
    verdicts: List[dict] = []
    matched_floors: set = set()
    passed = True
    for name in sorted(flat):
        cur = flat[name]
        d = direction(name)
        hist_vals = [
            h["metrics"][name] for h in history[-int(window):]
            if isinstance(h["metrics"].get(name), (int, float))
            and not isinstance(h["metrics"].get(name), bool)
        ]
        thr = float(per_metric.get(name, threshold))
        row = {
            "metric": name,
            "current": cur,
            "samples": len(hist_vals),
            "threshold_pct": round(100.0 * thr, 1),
        }
        if d == 0:
            row.update(status="info", baseline=None, change_pct=None)
        elif len(hist_vals) < max(int(min_samples), 1):
            row.update(status="baseline", baseline=None, change_pct=None)
        else:
            base = statistics.median(hist_vals)
            row["baseline"] = base
            if abs(base) < 1e-12:
                # A zero baseline has no relative scale: only gate on a
                # lower-is-better metric growing past the threshold in
                # absolute terms of... nothing to scale by — report it.
                row.update(status="info", change_pct=None)
            else:
                change = (cur - base) / abs(base)
                row["change_pct"] = round(100.0 * change, 2)
                score = d * change  # >0 improved, <0 worse
                if score < -thr:
                    row["status"] = "REGRESSED"
                    passed = False
                elif score > thr:
                    row["status"] = "improved"
                else:
                    row["status"] = "ok"
        floor_key = name if name in floors else next(
            # Flattening prefixes section paths (extra.lb_..., mesh.qsts
            # ...), so a bare metric name matches as a dot-suffix too.
            (k for k in floors if name.endswith("." + k)),
            None,
        )
        floor = floors.get(floor_key) if floor_key is not None else None
        if floor is not None:
            matched_floors.add(floor_key)
            # Absolute bar, judged even while the baseline builds;
            # direction-less names default to higher-is-better.
            row["floor"] = floor
            below = cur > floor if d < 0 else cur < floor
            if below:
                row["status"] = "REGRESSED"
                passed = False
            elif row["status"] in ("info", "baseline"):
                row["status"] = "ok"
        verdicts.append(row)
    # A floor that matched NOTHING is a broken guard, not a pass: the
    # metric it pins was renamed/dropped (or the --floor name is a
    # typo), and silence here would un-guard the exact regression the
    # floor was added against.
    for key in sorted(set(floors) - matched_floors):
        verdicts.append({
            "metric": key, "status": "REGRESSED",
            "current": float("nan"), "baseline": None, "samples": 0,
            "change_pct": None, "threshold_pct": 0.0,
            "floor": floors[key],
            "note": "floor metric absent from snapshot",
        })
        passed = False
    return verdicts, passed


def render_table(verdicts: List[dict], all_rows: bool = False) -> str:
    """Aligned verdict table; by default only gated rows (regressions,
    improvements, fresh baselines) — ``info`` rows on request."""
    rows = [
        v for v in verdicts
        if all_rows or v["status"] in ("REGRESSED", "improved", "baseline",
                                       "ok")
    ]
    if not rows:
        return "(no gateable metrics)"
    head = ("STATUS", "METRIC", "CURRENT", "BASELINE", "CHANGE", "LIMIT")
    table = [head]
    for v in rows:
        table.append((
            v["status"],
            v["metric"],
            f"{v['current']:.6g}",
            "-" if v.get("baseline") is None else f"{v['baseline']:.6g}",
            "-" if v.get("change_pct") is None else f"{v['change_pct']:+.1f}%",
            f"±{v['threshold_pct']:.0f}%",
        ))
    widths = [max(len(r[i]) for r in table) for i in range(len(head))]
    lines = [
        "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        for r in table
    ]
    lines.insert(1, "  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    # Exit-code contract: 0 pass, 1 REGRESSED, 2 gate-side problem.
    # A crash must land on 2, never 1 — CI asserts rc==1 as "the gate
    # caught the regression", and a broken gate must not pass for that.
    try:
        return _main(argv)
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — disambiguate crash from verdict
        print(f"perf_gate: internal error: {e!r}", file=sys.stderr)
        return 2


def _main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Perf-regression gate over bench.py snapshots"
    )
    ap.add_argument("snapshot", help="bench.py JSON output (file path)")
    ap.add_argument("--history", default="bench_history.jsonl",
                    metavar="PATH", help="rolling history file (JSONL)")
    ap.add_argument("--threshold", type=float, default=0.25, metavar="REL",
                    help="default relative regression tolerance "
                         "(default 0.25 = 25%%)")
    ap.add_argument("--min-samples", type=int, default=3, metavar="N",
                    help="history points required before a metric gates "
                         "(default 3; fewer = baseline-building pass)")
    ap.add_argument("--window", type=int, default=8, metavar="N",
                    help="rolling-baseline width: median of the last N "
                         "history points (default 8)")
    ap.add_argument("--set-threshold", action="append", default=[],
                    metavar="NAME=REL",
                    help="per-metric threshold override (repeatable)")
    ap.add_argument("--floor", action="append", default=[],
                    metavar="NAME=VALUE",
                    help="absolute bar for a metric, judged even while "
                         "the baseline builds: below it (higher-is-"
                         "better) or above it (lower-is-better) is "
                         "REGRESSED (repeatable)")
    ap.add_argument("--label", default="", help="label stored with the "
                                                "history entry (e.g. a sha)")
    ap.add_argument("--no-update", action="store_true",
                    help="judge only; never append to the history")
    ap.add_argument("--seed", action="append", default=[], metavar="PATH",
                    help="append these snapshots to the history first "
                         "(ungated) — e.g. the repo's BENCH_r*.json")
    ap.add_argument("--all-rows", action="store_true",
                    help="include info (ungated) metrics in the table")
    args = ap.parse_args(argv)

    per_metric: Dict[str, float] = {}
    for spec in args.set_threshold:
        name, _, val = spec.partition("=")
        if not name or not val:
            print(f"perf_gate: bad --set-threshold {spec!r}", file=sys.stderr)
            return 2
        per_metric[name] = float(val)
    floors: Dict[str, float] = {}
    for spec in args.floor:
        name, _, val = spec.partition("=")
        if not name or not val:
            print(f"perf_gate: bad --floor {spec!r}", file=sys.stderr)
            return 2
        floors[name] = float(val)

    try:
        with open(args.snapshot, "r", encoding="utf-8") as fh:
            snapshot = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"perf_gate: unreadable snapshot: {e}", file=sys.stderr)
        return 2

    # Seeding is idempotent (a seed label already in the history is
    # skipped, so a cron job passing --seed every run cannot pin the
    # rolling baseline to stale values) and honors --no-update.
    seeded_labels = {h.get("label") for h in load_history(args.history)}
    for path in args.seed:
        label = f"seed:{path}"
        if label in seeded_labels:
            continue
        try:
            with open(path, "r", encoding="utf-8") as fh:
                snap = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"perf_gate: unreadable seed {path}: {e}", file=sys.stderr)
            return 2
        if not args.no_update:
            append_history(args.history, flatten(snap), label=label)

    flat = flatten(snapshot)
    if not flat:
        print("perf_gate: snapshot contains no numeric metrics",
              file=sys.stderr)
        return 2
    history = load_history(args.history)
    verdicts, passed = gate(
        flat, history, threshold=args.threshold,
        min_samples=args.min_samples, window=args.window,
        per_metric=per_metric, floors=floors,
    )
    print(render_table(verdicts, all_rows=args.all_rows))
    regressed = [v["metric"] for v in verdicts if v["status"] == "REGRESSED"]
    summary = {
        "perf_gate_pass": passed,
        "metrics": len(flat),
        "gated": sum(
            1 for v in verdicts if v["status"] in ("ok", "improved",
                                                   "REGRESSED")
        ),
        "baseline_building": sum(
            1 for v in verdicts if v["status"] == "baseline"
        ),
        "regressed": regressed,
        "history_runs": len(history),
    }
    print(json.dumps(summary))
    if passed and not args.no_update:
        append_history(args.history, flat, label=args.label)
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
