// tableserver — native co-simulation table server.
//
// C++17 equivalent of the reference's pscad-interface
// (pscad-interface-master/src/{PosixMain,CTableManager,CRtdsAdapter,
// CSimulationAdapter}.cpp): shared state/command device tables behind
// reader/writer locks, served over TCP to
//
//   * DGI processes speaking the RTDS lock-step byte protocol
//     (receive big-endian f32 command buffer, apply non-NULL entries,
//     reply with the big-endian f32 state buffer), and
//   * a PSCAD co-simulation speaking the header protocol
//     (5-byte RST/SET/GET header; SET/RST push little-endian f64
//     states, RST also seeds commands from them, GET reads commands).
//
// The Python plantserver (freedm_tpu/sim/plantserver.py) serves the
// same two protocols backed by LIVE JAX physics; this native server is
// the static-table variant for co-sim hosts that must not carry a
// Python/JAX runtime — exactly the reference's deployment shape, where
// pscad-interface ran beside the simulator as a standalone C++ process.
//
// Config (one line per port, stdin or a file; '#' comments):
//   rtds  <port> states <dev.sig> ... commands <dev.sig> ...
//   pscad <port> states <dev.sig> ... commands <dev.sig> ...
//   seed  <dev.sig> <value>
// After setup, prints one JSON line {"tableserver": [[host, port], ...]}
// to stdout (port 0 binds ephemerally), then serves until SIGTERM.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <iostream>
#include <map>
#include <mutex>
#include <shared_mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

// IAdapter::NULL_COMMAND (Broker/src/device/IAdapter.hpp).
constexpr float kNullCommand = 1.0e8f;
constexpr std::size_t kSimHeaderSize = 5;  // CSimulationAdapter.hpp:65

// ----------------------------------------------------------------------
// CTableManager equivalent: two tables behind one shared_mutex each.
// ----------------------------------------------------------------------
class DeviceTable {
 public:
  void Set(const std::string& key, double value) {
    std::unique_lock lock(mutex_);
    values_[key] = value;
  }
  double Get(const std::string& key) const {
    std::shared_lock lock(mutex_);
    auto it = values_.find(key);
    return it == values_.end() ? 0.0 : it->second;
  }

 private:
  mutable std::shared_mutex mutex_;
  std::map<std::string, double> values_;
};

DeviceTable g_state_table;
DeviceTable g_command_table;
std::atomic<bool> g_stop{false};

// ----------------------------------------------------------------------
// Socket helpers.
// ----------------------------------------------------------------------
bool ReadExactly(int fd, void* buf, std::size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t got = ::read(fd, p, n);
    if (got <= 0) return false;
    p += got;
    n -= static_cast<std::size_t>(got);
  }
  return true;
}

bool WriteAll(int fd, const void* buf, std::size_t n) {
  const auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t put = ::write(fd, p, n);
    if (put <= 0) return false;
    p += put;
    n -= static_cast<std::size_t>(put);
  }
  return true;
}

// Big-endian f32 <-> host (the RTDS wire dtype, CRtdsAdapter's
// EndianSwapIfNeeded).
float BeToFloat(const unsigned char* b) {
  uint32_t v = (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) |
               (uint32_t(b[2]) << 8) | uint32_t(b[3]);
  float f;
  std::memcpy(&f, &v, 4);
  return f;
}

void FloatToBe(float f, unsigned char* b) {
  uint32_t v;
  std::memcpy(&v, &f, 4);
  b[0] = (v >> 24) & 0xff;
  b[1] = (v >> 16) & 0xff;
  b[2] = (v >> 8) & 0xff;
  b[3] = v & 0xff;
}

struct PortSpec {
  std::string protocol;  // "rtds" | "pscad"
  int port = 0;
  std::vector<std::string> states;    // buffer order = index order
  std::vector<std::string> commands;
};

// ----------------------------------------------------------------------
// The DGI half: CRtdsAdapter's peer. Commands first, then states —
// matching the DGI adapter's send-then-read (CRtdsAdapter::Run).
// ----------------------------------------------------------------------
void ServeRtdsConn(const PortSpec& spec, int fd) {
  std::vector<unsigned char> cmd_buf(spec.commands.size() * 4);
  std::vector<unsigned char> state_buf(spec.states.size() * 4);
  while (!g_stop.load()) {
    if (!spec.commands.empty()) {
      if (!ReadExactly(fd, cmd_buf.data(), cmd_buf.size())) break;
      for (std::size_t i = 0; i < spec.commands.size(); ++i) {
        float v = BeToFloat(&cmd_buf[i * 4]);
        // NULL_COMMAND entries leave the table untouched.
        if (std::fabs(v - kNullCommand) > 0.5f) {
          g_command_table.Set(spec.commands[i], v);
        }
      }
    }
    for (std::size_t i = 0; i < spec.states.size(); ++i) {
      FloatToBe(static_cast<float>(g_state_table.Get(spec.states[i])),
                &state_buf[i * 4]);
    }
    if (!spec.states.empty() &&
        !WriteAll(fd, state_buf.data(), state_buf.size())) {
      break;
    }
    if (spec.commands.empty() && spec.states.empty()) break;
  }
  ::close(fd);
}

// ----------------------------------------------------------------------
// The simulation half: CSimulationAdapter's protocol.
// ----------------------------------------------------------------------
void ServeSimConn(const PortSpec& spec, int fd) {
  char header[kSimHeaderSize];
  while (!g_stop.load()) {
    if (!ReadExactly(fd, header, kSimHeaderSize)) break;
    std::string kind(header, strnlen(header, kSimHeaderSize));
    if (kind == "RST" || kind == "SET") {
      std::vector<double> vals(spec.states.size());
      if (!spec.states.empty() &&
          !ReadExactly(fd, vals.data(), vals.size() * sizeof(double))) {
        break;
      }
      for (std::size_t i = 0; i < spec.states.size(); ++i) {
        g_state_table.Set(spec.states[i], vals[i]);
      }
      if (kind == "RST") {
        // CTableManager::UpdateTable(COMMAND_TABLE, STATE_TABLE).
        for (std::size_t i = 0; i < spec.states.size(); ++i) {
          g_command_table.Set(spec.states[i], vals[i]);
        }
      }
    } else if (kind == "GET") {
      std::vector<double> vals(spec.commands.size());
      for (std::size_t i = 0; i < spec.commands.size(); ++i) {
        vals[i] = g_command_table.Get(spec.commands[i]);
      }
      if (!vals.empty() &&
          !WriteAll(fd, vals.data(), vals.size() * sizeof(double))) {
        break;
      }
    } else {
      // Unknown verb: payload length unknowable, the stream cannot
      // resync — drop the connection (the client reconnects).
      std::cerr << "tableserver: unrecognized header, closing\n";
      break;
    }
  }
  ::close(fd);
}

int Listen(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 8) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

int BoundPort(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  return ntohs(addr.sin_port);
}

void AcceptLoop(PortSpec spec, int srv) {
  while (!g_stop.load()) {
    int conn = ::accept(srv, nullptr, nullptr);
    if (conn < 0) break;
    std::thread(spec.protocol == "pscad" ? ServeSimConn : ServeRtdsConn,
                spec, conn)
        .detach();
  }
  ::close(srv);
}

}  // namespace

int main(int argc, char** argv) {
  std::istream* in = &std::cin;
  std::ifstream file;
  if (argc > 1) {
    file.open(argv[1]);
    if (!file) {
      std::cerr << "tableserver: cannot open " << argv[1] << "\n";
      return 1;
    }
    in = &file;
  }

  std::vector<PortSpec> specs;
  std::string line;
  while (std::getline(*in, line)) {
    auto hash = line.find('#');
    if (hash != std::string::npos) line.erase(hash);
    std::istringstream ls(line);
    std::string verb;
    if (!(ls >> verb)) continue;
    if (verb == "seed") {
      std::string key;
      double value;
      if (ls >> key >> value) g_state_table.Set(key, value);
      continue;
    }
    if (verb != "rtds" && verb != "pscad") {
      std::cerr << "tableserver: unknown verb '" << verb << "'\n";
      return 1;
    }
    PortSpec spec;
    spec.protocol = verb;
    ls >> spec.port;
    std::string tok;
    std::vector<std::string>* target = nullptr;
    while (ls >> tok) {
      if (tok == "states") {
        target = &spec.states;
      } else if (tok == "commands") {
        target = &spec.commands;
      } else if (target) {
        target->push_back(tok);
      } else {
        std::cerr << "tableserver: stray token '" << tok << "'\n";
        return 1;
      }
    }
    specs.push_back(std::move(spec));
  }
  if (specs.empty()) {
    std::cerr << "tableserver: no ports configured\n";
    return 1;
  }

  std::vector<std::thread> acceptors;
  std::ostringstream ports_json;
  ports_json << "{\"tableserver\": [";
  for (std::size_t i = 0; i < specs.size(); ++i) {
    int srv = Listen(specs[i].port);
    if (srv < 0) {
      std::cerr << "tableserver: cannot bind port " << specs[i].port << "\n";
      return 1;
    }
    if (i) ports_json << ", ";
    ports_json << "[\"127.0.0.1\", " << BoundPort(srv) << "]";
    acceptors.emplace_back(AcceptLoop, specs[i], srv);
  }
  ports_json << "]}";
  std::cout << ports_json.str() << std::endl;

  for (auto& t : acceptors) t.join();
  return 0;
}
