"""Headline benchmark suite.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Primary metric (BASELINE.json north star): >=10k-bus AC power flow at
<10 ms/iteration on TPU; vs_baseline = 10 ms / achieved ms (>1 beats the
target).  The reference's own envelope is one 9-bus 3-phase ladder solve
per 3000 ms VVC round (``Broker/config/timings.cfg``,
``Broker/src/vvc/DPF_return7.cpp``).

Ladder-iteration history on v5e (the sweep realization is the whole
story at this size — each round moves only 240 KB, so kernel-launch
count dominates): r1-r3 1.32 ms (doubling, separate re/im kernels);
r4 0.749 ms (re‖im packed on the last axis — note the [..,3,2]
trailing-stack variant measured 2.5x SLOWER, minor-dim lane tiling);
r5 0.378 ms (Euler-tour prefix-sum sweeps, ``pf/sweeps.euler_sweeps``:
kernel count independent of tree depth vs ~13 pointer-jumping rounds);
r5 0.311 ms (DFS-preorder branch relabeling inside the solver:
tin = identity cuts the per-iteration data movement to ONE gather +
ONE scatter - dynamic addressing is what remains).

``extra`` carries the remaining BASELINE.md target rows, measured in the
same process:

- ``nr_10000bus_mesh_solve_ms`` — a full 10k-bus **meshed** AC solve
  (matrix-free Newton-GMRES + FDLF-inverse preconditioner,
  ``pf/krylov``; the reference's only solver is a 9-bus radial ladder
  under a 3000 ms budget) — with
  ``nr_10000bus_mesh_true_mismatch_pu``, the solution's residual
  re-evaluated on host in float64 (honest accuracy, not f32 noise);
- ``nr_2000bus_krylov_batch256_lane_solves_per_sec`` — 256 lane-batched
  full-accuracy 2k-bus NR solves (vmap turns the preconditioner into
  MXU matmuls; VERDICT r4 item 5's ">=5x 12.62" target row), with
  ``nr_2000bus_krylov_mfu_pct`` (honest single-digit solver MFU);
- ``n1_2000bus_256way_krylov_screen_ms`` — 256 warm-started outage
  solves at 2000 buses through the status-traced matrix-free path
  (the SMW screen covers the 118/30-bus class; this is the same
  screening workload 17x bigger);
- ``nr_2000bus_mesh_solves_per_sec`` — full Newton-Raphson solves/sec on
  a 2000-bus meshed network (hand-assembled Jacobian, dense LU on MXU);
- ``fdlf_2000bus_mesh_solves_per_sec`` — the fast-decoupled solver on
  the same case (B′/B″ factorized once at build time);
- ``mc_1024lane_118bus_lane_solves_per_sec`` — 1024-scenario Monte-Carlo
  batch (vmap over injections) on a 118-bus mesh, fixed-iteration NR,
  counted in lane-solves/sec;
- ``mc_1024lane_118bus_fdlf_lane_solves_per_sec`` — the same batch
  through FDLF, whose lanes share the build-time factorization
  (~40× the NR batch on v5e);
- ``n1_118way_contingency_batch_ms`` — the full 118-way N-1 screen (vmap
  over branch status) as one batched NR solve that re-factorizes per
  lane, total wall ms — kept as the r4 comparison point;
- ``n1_118way_smw_screen_ms`` — the same screen through the SMW
  fast-decoupled path (``pf/n1.py``): base B′/B″ factorized ONCE,
  per-lane outage = rank-2 Sherman-Morrison-Woodbury correction —
  one O(n³) factor + 118 O(n²) lanes instead of 118 O(n³)
  (VERDICT r4 item 2; ~5.7x the NR batch on v5e);
- ``n1_case30_real_smw_ms`` — the SMW screen over every non-islanding
  outage of the bundled IEEE 30-bus case (``grid/data/case_ieee30.m``)
  — the recognized-case anchor (IEEE 118 has no offline dataset in
  this environment; the 118-bus rows use ``synthetic_mesh(118)`` and
  say so);
- ``lb_256node_rounds_per_sec`` — the LB auction kernel run to
  convergence on a 256-node group (BASELINE.md north-star "LB
  convergence wall-clock vs node count"; the reference paces each LB
  round at 3000 ms, ``LB_ROUND_TIME``).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from freedm_tpu.core.metrics import REGISTRY
from freedm_tpu.grid.cases import synthetic_mesh, synthetic_radial
from freedm_tpu.pf import ladder
from freedm_tpu.pf.fdlf import make_fdlf_solver
from freedm_tpu.pf.krylov import make_krylov_solver, record_result, true_mismatch
from freedm_tpu.pf.newton import make_newton_solver

TARGET_MS_PER_ITER = 10.0
N_BUS = 10_000
MAX_ITER = 20  # the reference's DPF iteration cap (DPF_return7.cpp:15)


def _time(fn, ready, reps):
    jax.block_until_ready(ready(fn()))  # compile + warm
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(ready(out))
    return (time.perf_counter() - t0) / reps


def bench_ladder():
    feeder = synthetic_radial(N_BUS, seed=0, load_kw=1.0)
    _, solve_fixed = ladder.make_ladder_solver(feeder, max_iter=MAX_ITER)
    from freedm_tpu.utils import cplx

    s_load = jax.device_put(cplx.as_c(feeder.s_load, dtype=None))
    dt = _time(lambda: solve_fixed(s_load), lambda r: r.v_node.re, reps=50)
    return dt / MAX_ITER * 1000.0


def bench_ladder_mc_64():
    """The full BASELINE scale matrix at once — 64 Monte-Carlo scenario
    lanes x 10k buses through the vmapped ladder (batching amortizes the
    per-iteration dynamic addressing ~5x beyond the single-lane rate).
    Returns full-feeder solves/sec."""
    feeder = synthetic_radial(N_BUS, seed=0, load_kw=1.0)
    _, solve_fixed = ladder.make_ladder_solver(feeder, max_iter=MAX_ITER)
    from freedm_tpu.utils import cplx

    rng = np.random.default_rng(0)
    scale = rng.uniform(0.7, 1.3, (64, 1, 1))
    s = jax.device_put(cplx.as_c(scale * feeder.s_load[None]))
    batched = jax.jit(jax.vmap(solve_fixed))
    r = batched(s)
    assert bool(jnp.all(r.converged)), "10k MC lanes diverged"
    dt = _time(lambda: batched(s), lambda r: r.v_node.re, reps=10)
    return 64.0 / dt


def bench_nr_2000(maker=make_newton_solver, max_iter=10):
    sys = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    solve, _ = maker(sys, max_iter=max_iter)
    dt = _time(solve, lambda r: r.v, reps=10)
    return 1.0 / dt


def bench_mc_1024(maker=make_newton_solver, max_iter=6):
    sys = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    _, solve_fixed = maker(sys, max_iter=max_iter)
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.7, 1.3, (1024, 1))
    p = jnp.asarray(scale * sys.p_inj[None, :])
    q = jnp.asarray(scale * sys.q_inj[None, :])
    batched = jax.jit(jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi)))
    dt = _time(lambda: batched(p, q), lambda r: r.v, reps=5)
    return 1024.0 / dt


def bench_nr_10k_mesh():
    """The 10k-bus MESHED solve (VERDICT r4 item 1): matrix-free
    Newton-GMRES with the FDLF-inverse preconditioner (``pf/krylov``).
    Returns (ms/solve, f64-oracle mismatch) — the oracle is evaluated on
    host in double precision so the reported accuracy is real, not f32
    evaluation noise."""
    sys_ = synthetic_mesh(10_000, seed=4, load_mw=2.0, chord_frac=0.3)
    # inner=16 measured both faster and slightly more accurate than the
    # default 24 at this size (178 vs 212 ms, 8.7e-6 vs 9.8e-6 true).
    solve, _ = make_krylov_solver(sys_, max_iter=15, inner_iters=16)
    r = solve()
    assert bool(r.converged), f"10k mesh diverged: {float(r.mismatch)}"
    record_result(r)  # already host-side via the assert — no extra sync
    dt = _time(solve, lambda r: r.v, reps=10)
    return dt * 1000.0, true_mismatch(sys_, r)


def bench_nr_2k_krylov_lanes(lanes=256, outer=8, inner=16):
    """Lane-batched full-accuracy NR at 2k buses (VERDICT r4 item 5):
    vmap over per-lane injections turns the preconditioner matvec into
    an MXU matmul and amortizes every kernel launch.  Returns
    (lane_solves/s, MFU %): the FLOP model counts the dominant
    preconditioner matvecs (outer·inner applications of two [n, n]
    matrices per lane) against v5e's 197 TFLOP/s bf16 peak — solver
    workloads are latency/launch-bound, so single-digit MFU is the
    honest number, not a typo."""
    sys_ = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    n = sys_.n_bus
    _, solve_fixed = make_krylov_solver(sys_, max_iter=outer, inner_iters=inner)
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.9, 1.1, (lanes, 1))
    p = jnp.asarray(scale * sys_.p_inj[None, :])
    q = jnp.asarray(scale * sys_.q_inj[None, :])
    batched = jax.jit(
        lambda p, q: jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi))(p, q)
    )
    r = batched(p, q)
    assert bool(jnp.all(r.converged)), "krylov lane batch diverged"
    record_result(r)  # every lane's iterations, worst lane's residual
    dt = _time(lambda: batched(p, q), lambda r: r.v, reps=10)
    lane_rate = lanes / dt
    flops_per_lane = outer * inner * 4.0 * n * n
    mfu = lane_rate * flops_per_lane / 197e12 * 100.0
    return lane_rate, mfu


def bench_n1_2000bus_krylov(k=256):
    """N-1 contingency screening at 2000 buses — far beyond the SMW/FDLF
    screen's 118-bus case: solve the base case once, then vmap the
    status-traced matrix-free solver over ``k`` single-chord outages,
    warm-started from the base solution (3 Newton steps suffice)."""
    sys_ = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    solve, _ = make_krylov_solver(sys_, max_iter=8, inner_iters=16)
    base = solve()
    assert bool(base.converged)
    _, screen_fixed = make_krylov_solver(sys_, max_iter=3, inner_iters=16)
    m = sys_.n_branch
    status = np.ones((k, m), np.float32)
    # Chord outages (indices >= n_bus): never island the ring backbone.
    status[np.arange(k), np.arange(sys_.n_bus, sys_.n_bus + k)] = 0.0
    status = jnp.asarray(status)
    screen = jax.jit(
        lambda s: jax.vmap(
            lambda si: screen_fixed(status=si, v0=base.v, theta0=base.theta)
        )(s)
    )
    r = screen(status)
    assert bool(jnp.all(r.converged)), "2k N-1 screen diverged"
    record_result(r)
    dt = _time(lambda: screen(status), lambda r: r.v, reps=5)
    return dt * 1000.0


def bench_lb_256():
    from freedm_tpu.modules import lb

    n = 256
    rng = np.random.default_rng(0)
    netgen = jnp.asarray(rng.normal(0, 10, n))
    gw0 = jnp.zeros(n)
    mask = jnp.ones((n, n))
    rounds = 64  # enough for this imbalance profile to fully converge
    run = jax.jit(lambda: lb.run_rounds(netgen, gw0, mask, 1.0, rounds))
    gw, migs, _ = run()
    assert int(np.asarray(migs)[-1]) == 0, "did not converge in the budget"
    dt = _time(run, lambda r: r[0], reps=10)
    return rounds / dt


def bench_n1_118():
    sys = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    _, solve_fixed = make_newton_solver(sys, max_iter=6)
    m = sys.n_branch
    # One outage per lane, first 118 branches (the "118-way" screen).
    k = min(118, m)
    status = np.ones((k, m), np.float32)
    status[np.arange(k), np.arange(k)] = 0.0
    status = jnp.asarray(status)
    batched = jax.jit(jax.vmap(lambda s: solve_fixed(status=s)))
    dt = _time(lambda: batched(status), lambda r: r.v, reps=5)
    return dt * 1000.0


def bench_n1_118_smw():
    from freedm_tpu.pf.n1 import make_n1_screen

    sys = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    screen = make_n1_screen(sys, max_iter=24)
    ks = jnp.arange(118)
    r = screen(ks)
    assert bool(np.all(np.asarray(r.converged))), "SMW screen diverged"
    dt = _time(lambda: screen(ks), lambda r: r.v, reps=20)
    return dt * 1000.0


def bench_n1_case30_smw():
    from freedm_tpu.grid.matpower import load_builtin
    from freedm_tpu.pf.n1 import make_n1_screen, secure_outages

    sys = load_builtin("case_ieee30")
    ks = jnp.asarray(secure_outages(sys))
    screen = make_n1_screen(sys, max_iter=24)
    r = screen(ks)
    assert bool(np.all(np.asarray(r.converged))), "case30 screen diverged"
    dt = _time(lambda: screen(ks), lambda r: r.v, reps=20)
    return dt * 1000.0


def main() -> None:
    ms_per_iter = bench_ladder()
    nr10k_ms, nr10k_true = bench_nr_10k_mesh()
    lane_rate, mfu = bench_nr_2k_krylov_lanes()
    extra = {
        "nr_10000bus_mesh_solve_ms": round(nr10k_ms, 1),
        "nr_10000bus_mesh_true_mismatch_pu": float(f"{nr10k_true:.2e}"),
        "nr_2000bus_krylov_batch256_lane_solves_per_sec": round(lane_rate, 1),
        "nr_2000bus_krylov_mfu_pct": round(mfu, 2),
        "n1_2000bus_256way_krylov_screen_ms": round(
            bench_n1_2000bus_krylov(), 1
        ),
        "mc_64lane_10000bus_ladder_solves_per_sec": round(
            bench_ladder_mc_64(), 1
        ),
        "nr_2000bus_mesh_solves_per_sec": round(bench_nr_2000(), 2),
        "fdlf_2000bus_mesh_solves_per_sec": round(
            bench_nr_2000(maker=make_fdlf_solver, max_iter=30), 2
        ),
        "mc_1024lane_118bus_lane_solves_per_sec": round(bench_mc_1024(), 1),
        "mc_1024lane_118bus_fdlf_lane_solves_per_sec": round(
            bench_mc_1024(maker=make_fdlf_solver, max_iter=16), 1
        ),
        "n1_118way_contingency_batch_ms": round(bench_n1_118(), 2),
        "n1_118way_smw_screen_ms": round(bench_n1_118_smw(), 2),
        "n1_case30_real_smw_ms": round(bench_n1_case30_smw(), 2),
        "lb_256node_rounds_per_sec": round(bench_lb_256(), 1),
    }
    print(
        json.dumps(
            {
                "metric": f"pf_ladder_{N_BUS}bus_ms_per_iteration",
                "value": round(ms_per_iter, 3),
                "unit": "ms/iteration",
                "vs_baseline": round(TARGET_MS_PER_ITER / ms_per_iter, 2),
                "extra": extra,
                # Registry snapshot: the BENCH trajectory gains solver-
                # iteration / residual columns without new bench code.
                "metrics": REGISTRY.snapshot(),
            }
        )
    )


if __name__ == "__main__":
    main()
