"""Headline benchmark: AC power-flow solves/sec (BASELINE.md north star).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline envelope (BASELINE.md): the reference runs one 9-bus 3-phase
ladder power flow per 3000 ms VVC round per process
(``Broker/config/timings.cfg``, ``Broker/src/vvc/DPF_return7.cpp``), i.e.
~0.33 solves/sec. North-star target: >=10k-bus at <10 ms/iteration on
TPU. We report batched 9-bus solves/sec (the reference's own workload,
vmapped) so vs_baseline = achieved / 0.33.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

from freedm_tpu.grid.cases import vvc_9bus
from freedm_tpu.pf import ladder
from freedm_tpu.utils import cplx

# Reference cadence: one 9-bus DPF per VVC_ROUND_TIME=3000ms round
# (Broker/config/timings.cfg:14-18) per broker process.
BASELINE_SOLVES_PER_SEC = 1000.0 / 3000.0


def main() -> None:
    feeder = vvc_9bus()
    solve, _ = ladder.make_ladder_solver(feeder)

    batch = 1024
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.7, 1.3, size=(batch, 1, 1))
    s = np.asarray(feeder.s_load)[None] * scale
    s_load = cplx.as_c(s)

    batched = jax.jit(jax.vmap(lambda s: solve(s)))
    # Warm-up / compile.
    jax.block_until_ready(batched(s_load))

    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        out = batched(s_load)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    solves_per_sec = reps * batch / dt
    print(
        json.dumps(
            {
                "metric": "ac_power_flow_solves_per_sec_9bus",
                "value": round(solves_per_sec, 1),
                "unit": "solves/sec",
                "vs_baseline": round(solves_per_sec / BASELINE_SOLVES_PER_SEC, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
