"""Headline benchmark: 10k-bus AC power flow, ms per iteration.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

North-star target (BASELINE.json / BASELINE.md): >=10k-bus AC power flow
at <10 ms/iteration on TPU. vs_baseline = 10 ms / achieved ms (>1 beats
the target). The reference's own envelope is one 9-bus 3-phase ladder
solve per 3000 ms VVC round (``Broker/config/timings.cfg``,
``Broker/src/vvc/DPF_return7.cpp``).
"""

from __future__ import annotations

import json
import time

import jax

from freedm_tpu.grid.cases import synthetic_radial
from freedm_tpu.pf import ladder

TARGET_MS_PER_ITER = 10.0
N_BUS = 10_000
MAX_ITER = 20  # the reference's DPF iteration cap (DPF_return7.cpp:15)


def main() -> None:
    feeder = synthetic_radial(N_BUS, seed=0, load_kw=1.0)
    _, solve_fixed = ladder.make_ladder_solver(feeder, max_iter=MAX_ITER)

    # Hoist the host->device transfer; warm-up / compile.
    from freedm_tpu.utils import cplx

    s_load = jax.device_put(cplx.as_c(feeder.s_load, dtype=None))
    jax.block_until_ready(solve_fixed(s_load).v_node.re)

    reps = 50
    t0 = time.perf_counter()
    for _ in range(reps):
        out = solve_fixed(s_load)
    jax.block_until_ready(out.v_node.re)
    dt = time.perf_counter() - t0

    ms_per_iter = dt / reps / MAX_ITER * 1000.0
    print(
        json.dumps(
            {
                "metric": f"pf_ladder_{N_BUS}bus_ms_per_iteration",
                "value": round(ms_per_iter, 3),
                "unit": "ms/iteration",
                "vs_baseline": round(TARGET_MS_PER_ITER / ms_per_iter, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
