"""Headline benchmark suite.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Primary metric (BASELINE.json north star): >=10k-bus AC power flow at
<10 ms/iteration on TPU; vs_baseline = 10 ms / achieved ms (>1 beats the
target).  The reference's own envelope is one 9-bus 3-phase ladder solve
per 3000 ms VVC round (``Broker/config/timings.cfg``,
``Broker/src/vvc/DPF_return7.cpp``).

Ladder-iteration history on v5e (the sweep realization is the whole
story at this size — each round moves only 240 KB, so kernel-launch
count dominates): r1-r3 1.32 ms (doubling, separate re/im kernels);
r4 0.749 ms (re‖im packed on the last axis — note the [..,3,2]
trailing-stack variant measured 2.5x SLOWER, minor-dim lane tiling);
r5 0.378 ms (Euler-tour prefix-sum sweeps, ``pf/sweeps.euler_sweeps``:
kernel count independent of tree depth vs ~13 pointer-jumping rounds);
r5 0.311 ms (DFS-preorder branch relabeling inside the solver:
tin = identity cuts the per-iteration data movement to ONE gather +
ONE scatter - dynamic addressing is what remains).

``extra`` carries the remaining BASELINE.md target rows, measured in the
same process:

- ``nr_10000bus_mesh_solve_ms`` — a full 10k-bus **meshed** AC solve
  (matrix-free Newton-GMRES + FDLF-inverse preconditioner,
  ``pf/krylov``; the reference's only solver is a 9-bus radial ladder
  under a 3000 ms budget) — with
  ``nr_10000bus_mesh_true_mismatch_pu``, the solution's residual
  re-evaluated on host in float64 (honest accuracy, not f32 noise);
- ``nr_2000bus_krylov_batch256_lane_solves_per_sec`` — 256 lane-batched
  full-accuracy 2k-bus NR solves (vmap turns the preconditioner into
  MXU matmuls; VERDICT r4 item 5's ">=5x 12.62" target row), with
  ``nr_2000bus_krylov_mfu_pct`` (honest single-digit solver MFU);
- ``n1_2000bus_256way_krylov_screen_ms`` — 256 warm-started outage
  solves at 2000 buses through the status-traced matrix-free path
  (the SMW screen covers the 118/30-bus class; this is the same
  screening workload 17x bigger);
- ``nr_2000bus_mesh_solves_per_sec`` — full Newton-Raphson solves/sec on
  a 2000-bus meshed network (hand-assembled Jacobian, dense LU on MXU);
- ``fdlf_2000bus_mesh_solves_per_sec`` — the fast-decoupled solver on
  the same case (B′/B″ factorized once at build time);
- ``mc_1024lane_118bus_lane_solves_per_sec`` — 1024-scenario Monte-Carlo
  batch (vmap over injections) on a 118-bus mesh, fixed-iteration NR,
  counted in lane-solves/sec;
- ``mc_1024lane_118bus_fdlf_lane_solves_per_sec`` — the same batch
  through FDLF, whose lanes share the build-time factorization
  (~40× the NR batch on v5e);
- ``n1_118way_contingency_batch_ms`` — the full 118-way N-1 screen (vmap
  over branch status) as one batched NR solve that re-factorizes per
  lane, total wall ms — kept as the r4 comparison point;
- ``n1_118way_smw_screen_ms`` — the same screen through the SMW
  fast-decoupled path (``pf/n1.py``): base B′/B″ factorized ONCE,
  per-lane outage = rank-2 Sherman-Morrison-Woodbury correction —
  one O(n³) factor + 118 O(n²) lanes instead of 118 O(n³)
  (VERDICT r4 item 2; ~5.7x the NR batch on v5e);
- ``n1_case30_real_smw_ms`` — the SMW screen over every non-islanding
  outage of the bundled IEEE 30-bus case (``grid/data/case_ieee30.m``)
  — the recognized-case anchor (IEEE 118 has no offline dataset in
  this environment; the 118-bus rows use ``synthetic_mesh(118)`` and
  say so);
- ``lb_256node_rounds_per_sec`` — the LB auction kernel run to
  convergence on a 256-node group (BASELINE.md north-star "LB
  convergence wall-clock vs node count"; the reference paces each LB
  round at 3000 ms, ``LB_ROUND_TIME``).
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from freedm_tpu.core.metrics import REGISTRY
from freedm_tpu.grid.cases import synthetic_mesh, synthetic_radial
from freedm_tpu.pf import ladder
from freedm_tpu.pf.fdlf import make_fdlf_solver
from freedm_tpu.pf.krylov import make_krylov_solver, record_result, true_mismatch
from freedm_tpu.pf.newton import make_newton_solver

TARGET_MS_PER_ITER = 10.0
N_BUS = 10_000
MAX_ITER = 20  # the reference's DPF iteration cap (DPF_return7.cpp:15)


def _time(fn, ready, reps):
    jax.block_until_ready(ready(fn()))  # compile + warm
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(ready(out))
    return (time.perf_counter() - t0) / reps


def bench_ladder():
    feeder = synthetic_radial(N_BUS, seed=0, load_kw=1.0)
    _, solve_fixed = ladder.make_ladder_solver(feeder, max_iter=MAX_ITER)
    from freedm_tpu.utils import cplx

    s_load = jax.device_put(cplx.as_c(feeder.s_load, dtype=None))
    dt = _time(lambda: solve_fixed(s_load), lambda r: r.v_node.re, reps=50)
    return dt / MAX_ITER * 1000.0


def bench_ladder_mc_64():
    """The full BASELINE scale matrix at once — 64 Monte-Carlo scenario
    lanes x 10k buses through the vmapped ladder (batching amortizes the
    per-iteration dynamic addressing ~5x beyond the single-lane rate).
    Returns full-feeder solves/sec."""
    feeder = synthetic_radial(N_BUS, seed=0, load_kw=1.0)
    _, solve_fixed = ladder.make_ladder_solver(feeder, max_iter=MAX_ITER)
    from freedm_tpu.utils import cplx

    rng = np.random.default_rng(0)
    scale = rng.uniform(0.7, 1.3, (64, 1, 1))
    s = jax.device_put(cplx.as_c(scale * feeder.s_load[None]))
    batched = jax.jit(jax.vmap(solve_fixed))
    r = batched(s)
    assert bool(jnp.all(r.converged)), "10k MC lanes diverged"
    dt = _time(lambda: batched(s), lambda r: r.v_node.re, reps=10)
    return 64.0 / dt


def bench_nr_2000(maker=make_newton_solver, max_iter=10):
    sys = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    solve, _ = maker(sys, max_iter=max_iter)
    dt = _time(solve, lambda r: r.v, reps=10)
    return 1.0 / dt


def bench_mc_1024(maker=make_newton_solver, max_iter=6):
    sys = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    _, solve_fixed = maker(sys, max_iter=max_iter)
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.7, 1.3, (1024, 1))
    p = jnp.asarray(scale * sys.p_inj[None, :])
    q = jnp.asarray(scale * sys.q_inj[None, :])
    batched = jax.jit(jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi)))
    dt = _time(lambda: batched(p, q), lambda r: r.v, reps=5)
    return 1024.0 / dt


def bench_nr_10k_mesh():
    """The 10k-bus MESHED solve (VERDICT r4 item 1): matrix-free
    Newton-GMRES with the FDLF preconditioner (``pf/krylov``; the
    ``kind="auto"`` pair — LU at this size on every backend, which is
    the fix for the bf16 inverse pair's ~400 MB blowup).  Returns
    (ms/solve, f64-oracle mismatch) — the oracle is evaluated on host
    in double precision so the reported accuracy is real, not f32
    evaluation noise."""
    from freedm_tpu.pf.krylov import build_fdlf_precond

    sys_ = synthetic_mesh(10_000, seed=4, load_mw=2.0, chord_frac=0.3)
    pre = build_fdlf_precond(sys_, kind="auto")
    # inner=16 measured both faster and slightly more accurate than the
    # default 24 at this size (178 vs 212 ms, 8.7e-6 vs 9.8e-6 true).
    solve, _ = make_krylov_solver(sys_, max_iter=15, inner_iters=16,
                                  precond=pre)
    r = solve()
    assert bool(r.converged), f"10k mesh diverged: {float(r.mismatch)}"
    record_result(r)  # already host-side via the assert — no extra sync
    dt = _time(solve, lambda r: r.v, reps=10)
    return dt * 1000.0, true_mismatch(sys_, r)


def bench_nr_2k_krylov_lanes(lanes=256, outer=8, inner=16,
                             precision="auto"):
    """Lane-batched full-accuracy NR at 2k buses (VERDICT r4 item 5):
    vmap over per-lane injections turns the preconditioner matvec into
    an MXU matmul and amortizes every kernel launch.  Returns
    (lane_solves/s, MFU %): the FLOP model counts the dominant
    preconditioner matvecs (outer·inner applications of two [n, n]
    matrices per lane) against v5e's 197 TFLOP/s bf16 peak — solver
    workloads are latency/launch-bound, so single-digit MFU is the
    honest number, not a typo.  ``precision`` threads --pf-precision
    (the mfu section measures "mixed" explicitly)."""
    sys_ = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    n = sys_.n_bus
    _, solve_fixed = make_krylov_solver(sys_, max_iter=outer,
                                        inner_iters=inner,
                                        precision=precision)
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.9, 1.1, (lanes, 1))
    p = jnp.asarray(scale * sys_.p_inj[None, :])
    q = jnp.asarray(scale * sys_.q_inj[None, :])
    batched = jax.jit(
        lambda p, q: jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi))(p, q)
    )
    r = batched(p, q)
    assert bool(jnp.all(r.converged)), "krylov lane batch diverged"
    record_result(r)  # every lane's iterations, worst lane's residual
    dt = _time(lambda: batched(p, q), lambda r: r.v, reps=10)
    lane_rate = lanes / dt
    flops_per_lane = outer * inner * 4.0 * n * n
    mfu = lane_rate * flops_per_lane / 197e12 * 100.0
    return lane_rate, mfu


def bench_n1_2000bus_krylov(k=256):
    """N-1 contingency screening at 2000 buses — far beyond the SMW/FDLF
    screen's 118-bus case: solve the base case once, then vmap the
    status-traced matrix-free solver over ``k`` single-chord outages,
    warm-started from the base solution (3 Newton steps suffice)."""
    sys_ = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    solve, _ = make_krylov_solver(sys_, max_iter=8, inner_iters=16)
    base = solve()
    assert bool(base.converged)
    _, screen_fixed = make_krylov_solver(sys_, max_iter=3, inner_iters=16)
    m = sys_.n_branch
    status = np.ones((k, m), np.float32)
    # Chord outages (indices >= n_bus): never island the ring backbone.
    status[np.arange(k), np.arange(sys_.n_bus, sys_.n_bus + k)] = 0.0
    status = jnp.asarray(status)
    screen = jax.jit(
        lambda s: jax.vmap(
            lambda si: screen_fixed(status=si, v0=base.v, theta0=base.theta)
        )(s)
    )
    r = screen(status)
    assert bool(jnp.all(r.converged)), "2k N-1 screen diverged"
    record_result(r)
    dt = _time(lambda: screen(status), lambda r: r.v, reps=5)
    return dt * 1000.0


#: r05 baseline for the flagship krylov lane throughput — the
#: denominator of the gated ``nr_2000bus_krylov_lane_speedup`` row
#: (ISSUE 14 acceptance: >= 5x, i.e. >= 9380 lane solves/s, or the
#: >= 10% MFU alternative).
KRYLOV_LANE_RATE_R05 = 1876.0


def bench_krylov_donation(outer=8, inner=16):
    """Donation on/off head-to-head: the same 2000-bus matrix-free
    solver (shared preconditioner build, identical math) compiled with
    and without ``donate_argnums`` on its iteration program.  What
    donation deletes is the result-buffer allocation + HBM round trip
    per solve; the ratio is the honest measure of how much that was
    costing on this backend."""
    from freedm_tpu.pf.krylov import build_fdlf_precond

    sys_ = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    pre = build_fdlf_precond(sys_)
    on, _ = make_krylov_solver(sys_, max_iter=outer, inner_iters=inner,
                               precond=pre, donate=True)
    off, _ = make_krylov_solver(sys_, max_iter=outer, inner_iters=inner,
                                precond=pre, donate=False)
    r = on()
    assert bool(r.converged), "donation head-to-head diverged"
    ms_on = _time(on, lambda r: r.v, reps=5) * 1000.0
    ms_off = _time(off, lambda r: r.v, reps=5) * 1000.0
    return {
        "nr_2000bus_krylov_donation_on_ms": round(ms_on, 2),
        "nr_2000bus_krylov_donation_off_ms": round(ms_off, 2),
        "nr_2000bus_krylov_donation_speedup": round(ms_off / ms_on, 3),
    }


def bench_mfu(lanes=256, with_10k=False) -> dict:
    """``--sections mfu``: the solver-core MFU attack rows (ROADMAP
    "Raw speed"; ISSUE 14 acceptance gates).

    - the flagship krylov lane batch at ``--pf-precision mixed`` (the
      production default on tpu/gpu): lane throughput, model MFU, and
      the speedup ratio against the r05 baseline
      (:data:`KRYLOV_LANE_RATE_R05`) that ``perf_gate`` pins with
      ``--floor nr_2000bus_krylov_lane_speedup=5``;
    - the same batch at ``--pf-precision f64`` — the in-process
      mixed-vs-f64 ratio, so the mixed win is measured against the
      same s-step core, not against history alone;
    - mixed-vs-f64 solution agreement + identical convergence flags
      (the tolerance contract, asserted here as well as in tests);
    - the 10k-bus mesh wall (``--mfu-10k``; gated ceiling
      ``--floor nr_10000bus_mesh_solve_ms=60``) with its host-f64
      oracle mismatch;
    - the donation on/off head-to-head.
    """
    out: dict = {}
    rate_mixed, mfu = bench_nr_2k_krylov_lanes(lanes=lanes,
                                               precision="mixed")
    rate_f64, _ = bench_nr_2k_krylov_lanes(lanes=lanes, precision="f64")
    out.update({
        "nr_2000bus_krylov_batch_lanes": lanes,
        "nr_2000bus_krylov_batch256_lane_solves_per_sec": round(
            rate_mixed, 1),
        "nr_2000bus_krylov_mfu_pct": round(mfu, 2),
        "nr_2000bus_krylov_lane_speedup": round(
            rate_mixed / KRYLOV_LANE_RATE_R05, 2),
        "nr_2000bus_krylov_f64_lane_solves_per_sec": round(rate_f64, 1),
        "nr_2000bus_krylov_mixed_vs_f64_speedup": round(
            rate_mixed / rate_f64, 2),
    })

    # Mixed-vs-f64 equivalence at the bench's own scale: identical
    # convergence flags, solutions inside the documented 2e-4 pu bound.
    sys_ = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    sm, _ = make_krylov_solver(sys_, max_iter=15, precision="mixed")
    sf, _ = make_krylov_solver(sys_, max_iter=15, precision="f64")
    rm, rf = sm(), sf()
    assert bool(rm.converged) == bool(rf.converged), \
        "mixed changed the convergence verdict"
    dv = float(jnp.max(jnp.abs(rm.v - rf.v)))
    record_result(rm)  # fallback lanes land on pf_precision_fallbacks
    out.update({
        "mixed_vs_f64_max_dv_pu": float(f"{dv:.2e}"),
        "mixed_within_tolerance": bool(dv < 2e-4),
        "mixed_fallback_iterations": int(np.asarray(rm.fallbacks)),
    })

    if with_10k:
        nr10k_ms, nr10k_true = bench_nr_10k_mesh()
        out.update({
            "nr_10000bus_mesh_solve_ms": round(nr10k_ms, 1),
            "nr_10000bus_mesh_true_mismatch_pu": float(
                f"{nr10k_true:.2e}"),
        })
    out.update(bench_krylov_donation())
    return out


def bench_lb_256():
    from freedm_tpu.modules import lb

    n = 256
    rng = np.random.default_rng(0)
    netgen = jnp.asarray(rng.normal(0, 10, n))
    gw0 = jnp.zeros(n)
    mask = jnp.ones((n, n))
    rounds = 64  # enough for this imbalance profile to fully converge
    run = jax.jit(lambda: lb.run_rounds(netgen, gw0, mask, 1.0, rounds))
    gw, migs, _ = run()
    assert int(np.asarray(migs)[-1]) == 0, "did not converge in the budget"
    dt = _time(run, lambda r: r[0], reps=10)
    return rounds / dt


def bench_n1_118():
    sys = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    _, solve_fixed = make_newton_solver(sys, max_iter=6)
    m = sys.n_branch
    # One outage per lane, first 118 branches (the "118-way" screen).
    k = min(118, m)
    status = np.ones((k, m), np.float32)
    status[np.arange(k), np.arange(k)] = 0.0
    status = jnp.asarray(status)
    batched = jax.jit(jax.vmap(lambda s: solve_fixed(status=s)))
    dt = _time(lambda: batched(status), lambda r: r.v, reps=5)
    return dt * 1000.0


def bench_n1_118_smw():
    from freedm_tpu.pf.n1 import make_n1_screen

    sys = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    screen = make_n1_screen(sys, max_iter=24)
    ks = jnp.arange(118)
    r = screen(ks)
    assert bool(np.all(np.asarray(r.converged))), "SMW screen diverged"
    dt = _time(lambda: screen(ks), lambda r: r.v, reps=20)
    return dt * 1000.0


def bench_n1_case30_smw():
    from freedm_tpu.grid.matpower import load_builtin
    from freedm_tpu.pf.n1 import make_n1_screen, secure_outages

    sys = load_builtin("case_ieee30")
    ks = jnp.asarray(secure_outages(sys))
    screen = make_n1_screen(sys, max_iter=24)
    r = screen(ks)
    assert bool(np.all(np.asarray(r.converged))), "case30 screen diverged"
    dt = _time(lambda: screen(ks), lambda r: r.v, reps=20)
    return dt * 1000.0


# ---------------------------------------------------------------------------
# Serving benchmarks (freedm_tpu.serve): offered-load sweep, micro-batching
# speedup vs batch-size-1 dispatch, and the backpressure/shed envelope.
# ---------------------------------------------------------------------------


def _latency_stats(lats) -> dict:
    if not lats:
        return {"count": 0}
    a = np.sort(np.asarray(lats, np.float64))

    def pct(q):
        return round(float(a[min(len(a) - 1, int(q * len(a)))]) * 1e3, 3)

    return {
        "count": len(a),
        "p50_ms": pct(0.50),
        "p95_ms": pct(0.95),
        "p99_ms": pct(0.99),
        "max_ms": round(float(a[-1]) * 1e3, 3),
    }


def _warm_engine(svc, workload: str, request, buckets) -> None:
    """Pre-compile the given buckets of one engine so measurement windows
    never absorb a synchronous XLA compile."""
    from freedm_tpu.serve.queue import Ticket

    eng = svc.engine(workload, request.case)
    prepared = eng.validate(request)
    for b in buckets:
        t = Ticket(eng.key, request, prepared, eng.lanes(prepared), None)
        out = eng.solve(eng.assemble([t], b))
        jax.block_until_ready(out[0] if isinstance(out, tuple) else out.v)
        eng.compiled_buckets.add(b)


def _mix_pool(svc, case: str, workloads=("pf", "n1", "vvc"), size: int = 96):
    """A round-robin request mix over ``workloads``: snapshot power flows
    with load jitter, single-outage screens over the case's secure
    branches, and random bounded Q what-ifs on the 9-bus feeder.
    Prebuilt typed records, so the measurement loop times the SERVICE,
    not request construction."""
    from freedm_tpu.serve.service import (
        N1Request,
        PowerFlowRequest,
        VVCRequest,
    )

    secure = svc.engine("n1", case)._secure if "n1" in workloads else None
    veng = svc.engine("vvc", "vvc_9bus") if "vvc" in workloads else None
    rng = np.random.default_rng(7)
    pool = []
    for j in range(size):
        kind = workloads[j % len(workloads)]
        if kind == "pf":
            pool.append(("pf", PowerFlowRequest(
                case=case, scale=float(rng.uniform(0.85, 1.15)))))
        elif kind == "n1":
            pool.append(("n1", N1Request(
                case=case, outages=[int(secure[j % len(secure)])])))
        else:
            q = rng.uniform(-30.0, 30.0, (veng.nb, 3)) * veng._mask
            pool.append(("vvc", VVCRequest(case="vvc_9bus", q_ctrl_kvar=q)))
    return pool


def _pipelined_load(svc, pool, n_clients: int, inflight: int,
                    duration_s: float, sample_every: int = 8):
    """Fixed-concurrency load: each client keeps ``inflight`` requests
    outstanding (submit a burst, wait for all, repeat) — the shape real
    front ends offer a batched backend, and what lets the micro-batcher
    actually see concurrency.  Latency is sampled via done-callbacks so
    the measurement itself stays off the dispatch hot path."""
    import concurrent.futures as cf

    from freedm_tpu.serve.queue import ServeError

    lock = threading.Lock()
    completed = [0]
    sheds = [0]
    samples: list = []  # (workload, latency_s, batch_lanes)
    stop_at = time.perf_counter() + duration_s

    def _sampled(workload, t0):
        def cb(fut):
            if fut.exception() is None:
                samples.append((
                    workload, time.perf_counter() - t0,
                    fut.result().batch.lanes,
                ))
        return cb

    def client(ci: int) -> None:
        k = ci * 17  # decorrelate the clients' walk through the pool
        n = len(pool)
        while time.perf_counter() < stop_at:
            futs = []
            for j in range(inflight):
                workload, req = pool[(k + j) % n]
                t0 = time.perf_counter()
                try:
                    f = svc.submit(workload, req)
                except ServeError:
                    with lock:
                        sheds[0] += 1
                    continue
                if (k + j) % sample_every == 0:
                    f.add_done_callback(_sampled(workload, t0))
                futs.append(f)
            k += inflight
            cf.wait(futs)
            ok = sum(1 for f in futs if f.exception() is None)
            with lock:
                completed[0] += ok

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return completed[0], samples, sheds[0]


#: Per-workload batching envelopes: VVC lanes are nearly free under vmap
#: (a 9-bus ladder sweep is pure launch overhead), so its bucket table
#: reaches further.
_WORKLOAD_BUCKETS = {
    "pf": (1, 8, 64),
    "n1": (1, 8, 64),
    "vvc": (1, 16, 128),
}


def _serve_modes(buckets):
    """(config, warm_buckets) for the two serving disciplines compared by
    every row: ``max_wait_ms=0`` disables coalescing — the batcher
    dispatches each request alone, the batch-size-1 baseline the ISSUE's
    >=8x target is against — while the micro-batching config coalesces
    within a 2 ms window, shape-bucketed to bound the compile count."""
    from freedm_tpu.serve import ServeConfig

    # cache_mb=0 throughout the serve section: these rows measure the
    # BATCHING/PIPELINE disciplines, and the request pools deliberately
    # repeat — the incremental tier would answer the repeats before the
    # batcher ever saw them (it has its own section: --sections cache).
    return {
        "batch1": (ServeConfig(max_batch=buckets[-1], max_wait_ms=0.0,
                               queue_depth=4096, buckets=buckets,
                               cache_mb=0.0), (1,)),
        "microbatch": (ServeConfig(max_batch=buckets[-1], max_wait_ms=2.0,
                                   queue_depth=4096, buckets=buckets,
                                   cache_mb=0.0), buckets),
    }


def _run_modes(case: str, workloads, buckets, loads, duration_s,
               reps: int = 3) -> dict:
    """Run the batch1-vs-microbatch comparison for one request mix.

    The two modes' measurement windows are INTERLEAVED (b1, micro, b1,
    micro, ...) and each mode keeps its best window: this container is a
    2-vCPU cgroup whose effective speed drifts, and pairing the windows
    is what makes the ratio a property of the serving discipline rather
    than of which mode drew the slow minute."""
    from freedm_tpu.serve import Service

    modes = _serve_modes(buckets)
    svcs, pools = {}, {}
    try:
        for mode, (cfg, warm_buckets) in modes.items():
            svc = svcs[mode] = Service(cfg)
            pool = pools[mode] = _mix_pool(svc, case, workloads)
            for workload, req in pool[: len(workloads)]:
                _warm_engine(svc, workload, req, warm_buckets)
        entry: dict = {m: {} for m in modes}
        top = None
        for clients, inflight in loads:
            conc = clients * inflight
            top = f"concurrency_{conc}"
            best = {m: 0 for m in modes}
            samples: dict = {m: [] for m in modes}
            for m in modes:  # ramp untimed: start with full pipelines
                _pipelined_load(svcs[m], pools[m], clients, inflight,
                                min(0.4, duration_s))
            for _ in range(reps):
                for m in modes:
                    done, smp, _ = _pipelined_load(
                        svcs[m], pools[m], clients, inflight, duration_s
                    )
                    best[m] = max(best[m], done)
                    samples[m].extend(smp)
            for m in modes:
                stats = _latency_stats([s[1] for s in samples[m]])
                stats["qps"] = round(best[m] / duration_s, 1)
                if conc >= 32 and samples[m]:
                    vals, counts = np.unique(
                        [s[2] for s in samples[m]], return_counts=True
                    )
                    stats["batch_lanes_distribution"] = {
                        str(int(v)): int(c) for v, c in zip(vals, counts)
                    }
                entry[m][top] = stats
    finally:
        for svc in svcs.values():
            svc.stop()
    q1 = entry["batch1"][top]["qps"]
    qm = entry["microbatch"][top]["qps"]
    entry["microbatch_speedup"] = round(qm / q1, 2) if q1 else None
    return entry


def _serve_case(case: str, duration_s: float, per_workload: bool) -> dict:
    """One case's serving envelope: the mixed pf/N-1/VVC sweep, plus
    (for the primary case) per-workload comparisons at each workload's
    own bucket table."""
    entry = {
        "mixed": _run_modes(
            case, ("pf", "n1", "vvc"), (1, 8, 64),
            ((1, 1), (2, 16), (2, 96)), duration_s,
        )
    }
    if per_workload:
        for w in ("pf", "n1", "vvc"):
            entry[w] = _run_modes(
                case, (w,), _WORKLOAD_BUCKETS[w], ((2, 128),), duration_s
            )
    return entry


def _serve_overload(case: str, duration_s: float) -> dict:
    """Open-loop overload: offer ~2x the measured capacity into a small
    admission queue and verify the server sheds with typed errors while
    the p99 of ADMITTED requests stays bounded (the whole point of
    shed-on-overload vs queue-forever)."""
    from freedm_tpu.serve import Overloaded, ServeConfig, Service
    from freedm_tpu.serve.service import PowerFlowRequest

    svc = Service(ServeConfig(max_batch=32, max_wait_ms=2.0,
                              queue_depth=128, buckets=(1, 8, 32),
                              cache_mb=0.0))  # admission is the subject
    req = PowerFlowRequest(case=case, scale=1.0)
    try:
        _warm_engine(svc, "pf", req, (1, 8, 32))
        pool = [("pf", req)]
        done, _, _ = _pipelined_load(svc, pool, 4, 16, duration_s)
        capacity_qps = done / duration_s

        def open_loop(rate_qps: float, window_s: float) -> dict:
            """Paced (open-loop) submission from several generator
            threads — a single pacer cannot hold rate against the
            dispatch thread's GIL share."""
            lock = threading.Lock()
            admitted_lat: list = []
            sheds = [0]
            all_pending: list = []
            n_gen = 4

            def generator(g: int) -> None:
                pending = []
                stop_at = time.perf_counter() + window_s
                tick_s = 0.005
                per_tick_f = rate_qps * tick_s / n_gen
                credit = 0.0  # fractional-rate carry: no int() truncation bias
                while time.perf_counter() < stop_at:
                    tick_end = time.perf_counter() + tick_s
                    credit += per_tick_f
                    n_now = int(credit)
                    credit -= n_now
                    for j in range(n_now):
                        t0 = time.perf_counter()
                        try:
                            fut = svc.submit("pf", req)
                        except Overloaded:
                            with lock:
                                sheds[0] += 1
                            continue
                        if (j % 2) == 0:  # sample latencies off-path
                            fut.add_done_callback(
                                lambda f, t0=t0: admitted_lat.append(
                                    time.perf_counter() - t0
                                ) if f.exception() is None else None
                            )
                        pending.append(fut)
                    rest = tick_end - time.perf_counter()
                    if rest > 0:
                        time.sleep(rest)
                with lock:
                    all_pending.extend(pending)

            threads = [
                threading.Thread(target=generator, args=(g,))
                for g in range(n_gen)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            ok = 0
            for f in all_pending:
                try:
                    f.result(timeout=30)
                    ok += 1
                except Exception:
                    pass
            out = _latency_stats(admitted_lat)
            attempts = sheds[0] + len(all_pending)
            out["offered_qps"] = round(attempts / window_s, 1)
            out["admitted_qps"] = round(ok / window_s, 1)
            out["shed"] = sheds[0]
            out["shed_pct"] = round(100.0 * sheds[0] / max(attempts, 1), 1)
            return out

        return {
            "capacity_qps": round(capacity_qps, 1),
            "at_1x": open_loop(0.9 * capacity_qps, duration_s),
            "at_2x": open_loop(2.0 * capacity_qps, duration_s),
        }
    finally:
        svc.stop()


def _paced_mixed_load(svc, pool, rate_qps: float, window_s: float,
                      n_gen: int = 2) -> dict:
    """Open-loop paced submission of a mixed request pool: offered rate
    is held regardless of completions (the honest p99-vs-QPS shape),
    latencies sampled off-path via done-callbacks."""
    from freedm_tpu.serve.queue import ServeError

    lock = threading.Lock()
    admitted_lat: list = []
    sheds = [0]
    all_pending: list = []

    def generator(g: int) -> None:
        pending = []
        k = g * 29
        n = len(pool)
        stop_at = time.perf_counter() + window_s
        tick_s = 0.005
        per_tick_f = rate_qps * tick_s / n_gen
        credit = 0.0
        while time.perf_counter() < stop_at:
            tick_end = time.perf_counter() + tick_s
            credit += per_tick_f
            n_now = int(credit)
            credit -= n_now
            for j in range(n_now):
                workload, req = pool[(k + j) % n]
                t0 = time.perf_counter()
                try:
                    fut = svc.submit(workload, req)
                except ServeError:
                    with lock:
                        sheds[0] += 1
                    continue
                if (j % 2) == 0:
                    fut.add_done_callback(
                        lambda f, t0=t0: admitted_lat.append(
                            time.perf_counter() - t0
                        ) if f.exception() is None else None
                    )
                pending.append(fut)
            k += n_now
            rest = tick_end - time.perf_counter()
            if rest > 0:
                time.sleep(rest)
        with lock:
            all_pending.extend(pending)

    threads = [threading.Thread(target=generator, args=(g,))
               for g in range(n_gen)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ok = 0
    for f in all_pending:
        try:
            f.result(timeout=60)
            ok += 1
        except Exception:
            pass
    out = _latency_stats(admitted_lat)
    attempts = sheds[0] + len(all_pending)
    out["offered_qps"] = round(attempts / window_s, 1)
    out["admitted_qps"] = round(ok / window_s, 1)
    out["shed"] = sheds[0]
    return out


def _split_stream_load(svc, pools, duration_s: float, inflight: int):
    """One closed-loop client thread PER workload, each keeping
    ``inflight`` requests outstanding — the head-of-line shape ISSUE 9
    names: a continuous vvc stream beside continuous pf/n1 streams.
    On the serialized path every workload's batch convoys behind the
    others on the one dispatch thread; per-engine executor lanes
    overlap them.  Returns (completions, latency samples)."""
    import concurrent.futures as cf

    from freedm_tpu.serve.queue import ServeError

    lock = threading.Lock()
    completed = [0]
    samples: list = []
    stop_at = time.perf_counter() + duration_s

    def client(workload: str) -> None:
        pool = pools[workload]
        k, n, done = 0, len(pools[workload]), 0
        while time.perf_counter() < stop_at:
            futs = []
            for j in range(inflight):
                t0 = time.perf_counter()
                try:
                    f = svc.submit(*pool[(k + j) % n])
                except ServeError:
                    continue
                if (k + j) % 4 == 0:
                    f.add_done_callback(
                        lambda fut, t0=t0, w=workload: samples.append(
                            (w, time.perf_counter() - t0)
                        ) if fut.exception() is None else None
                    )
                futs.append(f)
            k += inflight
            cf.wait(futs)
            done += sum(1 for f in futs if f.exception() is None)
        with lock:
            completed[0] += done

    threads = [threading.Thread(target=client, args=(w,)) for w in pools]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return completed[0], samples


def _serve_pipeline(case: str, duration_s: float) -> dict:
    """ISSUE 9 head-to-head: the two-stage pipeline (per-engine
    executor lanes, ``pipeline_depth=1`` — the default double-buffered
    shape) vs the serialized
    single-thread dispatch (``--serve-pipeline-depth 0``) under
    continuous per-workload pf/n1/vvc streams, plus an offered-load
    sweep (admitted p50/p99 vs offered QPS for both disciplines).

    Methodology matches the other serve rows: the two modes'
    measurement windows are INTERLEAVED and each keeps its best —
    this burstable 2-vCPU box drifts, and pairing windows makes the
    ratio a property of the serving discipline, not of which mode drew
    the slow minute.  Acceptance: ``serve_pipeline_speedup >= 1.3`` at
    flat-or-better admitted p99."""
    from freedm_tpu.serve import ServeConfig, Service

    buckets = (1, 8, 32)
    inflight = 8  # per workload: 24 mixed lanes in flight
    base = dict(max_batch=32, max_wait_ms=2.0, queue_depth=4096,
                buckets=buckets, cache_mb=0.0)  # measure the pipeline,
    # not the cache: the repeating pools would otherwise be answered
    # at submit time and never exercise the executor-lane overlap.
    cfgs = {
        "serialized": ServeConfig(pipeline_depth=0, **base),
        "pipelined": ServeConfig(pipeline_depth=1, **base),
    }
    window_s = max(duration_s / 3.0, 0.4)
    svcs, pools = {}, {}
    entry: dict = {}
    try:
        for mode, cfg in cfgs.items():
            svc = svcs[mode] = Service(cfg)
            mix = _mix_pool(svc, case)
            pools[mode] = {w: [e for e in mix if e[0] == w]
                           for w in ("pf", "n1", "vvc")}
            for workload, req in mix[:3]:
                _warm_engine(svc, workload, req, buckets)
        best = {m: 0 for m in cfgs}
        samples: dict = {m: [] for m in cfgs}
        for m in cfgs:  # ramp untimed: start with full pipelines
            _split_stream_load(svcs[m], pools[m], min(0.3, window_s),
                               inflight)
        for _ in range(6):
            for m in cfgs:
                done, smp = _split_stream_load(
                    svcs[m], pools[m], window_s, inflight
                )
                best[m] = max(best[m], done)
                samples[m].extend(smp)
        for m in cfgs:
            stats = _latency_stats([s[1] for s in samples[m]])
            stats["qps"] = round(best[m] / window_s, 1)
            entry[m] = {"mixed_streams_24": stats}
        q_ser = entry["serialized"]["mixed_streams_24"]["qps"]
        q_pipe = entry["pipelined"]["mixed_streams_24"]["qps"]
        entry["serve_pipeline_speedup"] = (
            round(q_pipe / q_ser, 2) if q_ser else None
        )
        # Overlap evidence (the acceptance's profile_host criterion):
        # over one pipelined window, host assembly time + device solve
        # time exceeding the wall clock PROVES the stages ran
        # concurrently — assembly is no longer additive with solving.
        from freedm_tpu.core import metrics as obs
        from freedm_tpu.core import profiling

        def _solve_sum():
            m = obs.REGISTRY.get("serve_solve_seconds")
            return sum(child.sum for _, child in m.children())

        def _host_sum(path):
            snap = profiling.PROFILER.snapshot()["host"]
            return snap.get(path, {}).get("total_s", 0.0)

        was_enabled = profiling.PROFILER.enabled
        profiling.PROFILER.configure(enabled=True)
        try:
            a0 = _host_sum("serve.assemble")
            x0 = _host_sum("serve.execute")
            s0 = _solve_sum()
            t0 = time.perf_counter()
            # Saturating load: at capacity the stages' summed busy time
            # (assembly lane + three executor lanes' device wall and
            # scatter overhead) can only exceed the elapsed wall if the
            # stages ran concurrently — back-to-back they could not.
            _split_stream_load(svcs["pipelined"], pools["pipelined"],
                               window_s, inflight * 4)
            wall = time.perf_counter() - t0
            assemble_s = _host_sum("serve.assemble") - a0
            execute_s = _host_sum("serve.execute") - x0
            solve_s = _solve_sum() - s0
            entry["overlap"] = {
                "wall_s": round(wall, 3),
                "assemble_s": round(assemble_s, 3),
                "solve_s": round(solve_s, 3),
                "execute_s": round(execute_s, 3),
                "busy_over_wall": round(
                    (assemble_s + solve_s + execute_s) / wall, 2
                ) if wall else None,
                "stages_overlapped": bool(
                    assemble_s + solve_s + execute_s > wall
                ),
            }
        finally:
            profiling.PROFILER.configure(enabled=was_enabled)
        # Offered-load sweep: pace both disciplines at fractions of the
        # pipelined capacity over the flat mixed pool; the pipeline
        # should shift the envelope right (more admitted QPS at
        # flat-or-better p99).
        flat = {m: [e for w in ("pf", "n1", "vvc") for e in pools[m][w]]
                for m in cfgs}
        sweep: dict = {}
        for tag, frac in (("r0_4", 0.4), ("r0_8", 0.8), ("r1_2", 1.2)):
            rate = max(q_pipe * frac, 1.0)
            sweep[tag] = {
                m: _paced_mixed_load(svcs[m], flat[m], rate, window_s)
                for m in cfgs
            }
        entry["offered_load_sweep"] = sweep
    finally:
        for svc in svcs.values():
            svc.stop()
    return entry


# ---------------------------------------------------------------------------
# QSTS benchmarks (freedm_tpu.scenarios): warm-start iteration savings,
# scenario-throughput scaling with bounded recompiles, and kill/resume
# exactness from chunk checkpoints.
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Incremental serving tier (ISSUE 10): exact-hit / delta-hit / warm-start
# ladders against the cache-off full-solve reference, plus the cold-herd
# single-flight proof.  Headline: serve_cache_delta_speedup (CI floor 3x)
# and the exact-hit p50 (< 1 ms floor — no device touch on that path).
# ---------------------------------------------------------------------------


def bench_cache() -> dict:
    """The cache section: request-level latency ladders through a live
    Service (admission + tier ladder included, so the numbers are what
    a client sees), on the 30-bus recognized case — big enough that
    rank 16 deltas exist, small enough for CI."""
    from freedm_tpu.serve import ServeConfig, Service
    from freedm_tpu.serve.service import PowerFlowRequest

    case = "case_ieee30"

    def mk(**kw):
        base = dict(max_batch=8, max_wait_ms=1.0, queue_depth=256,
                    buckets=(1, 8))
        base.update(kw)
        return Service(ServeConfig(**base))

    out: dict = {"case": case}
    rng = np.random.default_rng(17)
    svc_off = mk(cache_mb=0.0)
    svc_on = mk()
    try:
        n = svc_off.engine("pf", case).n_bus
        p0 = np.array(svc_off.engine("pf", case)._p0)
        q0 = np.array(svc_off.engine("pf", case)._q0)

        def delta_req(rank: int):
            p = p0.copy()
            for j in rng.choice(n, size=rank, replace=False):
                p[j] += rng.uniform(-0.03, 0.03)
            return PowerFlowRequest(case=case, p_inj=p.tolist(),
                                    q_inj=q0.tolist(), timeout_s=120)

        def measure(svc, reqs):
            lats, tiers = [], []
            for r in reqs:
                t0 = time.perf_counter()
                resp = svc.request("pf", r)
                lats.append(time.perf_counter() - t0)
                tiers.append(resp.batch.tier)
            return lats, tiers

        # Warm both services (engine compile + the delta program).
        base_req = PowerFlowRequest(case=case, timeout_s=300)
        svc_off.request("pf", base_req)
        svc_on.request("pf", base_req)
        svc_on.request("pf", delta_req(1))  # compiles the delta program

        # (a) exact-hit ladder: identical injections, answered from host
        # memory without touching the device.
        lats, tiers = measure(svc_on, [base_req] * 200)
        assert all(t == "exact" for t in tiers)
        out["exact_hit_p50_ms"] = _latency_stats(lats)["p50_ms"]
        out["exact_hit_served"] = len(lats)

        # (a2) receipt overhead on the hottest path: the same exact-hit
        # ladder with provenance receipts ON (no shadow sampling, no
        # journal) — the difference vs (a) is the price of a stamped
        # answer; the disabled-by-default contract keeps it off every
        # other row.
        from freedm_tpu.core.provenance import PROVENANCE

        PROVENANCE.configure(enabled=True, rate_spec="0.0")
        try:
            lats_r, tiers = measure(svc_on, [base_req] * 200)
            assert all(t == "exact" for t in tiers)
        finally:
            PROVENANCE.reset()
        out["exact_hit_receipts_p50_ms"] = _latency_stats(lats_r)["p50_ms"]
        out["serve_receipt_overhead_us"] = round(max(
            out["exact_hit_receipts_p50_ms"] - out["exact_hit_p50_ms"], 0.0
        ) * 1e3, 1)

        # (b) delta ladder at rank 1/4/16 vs the cache-off full solve
        # over the SAME delta distribution.
        delta = {}
        speedups = []
        for rank in (1, 4, 16):
            reqs = [delta_req(rank) for _ in range(30)]
            full_lats, _ = measure(svc_off, reqs)
            hit_lats, tiers = measure(svc_on, reqs)
            served = sum(1 for t in tiers if t == "delta")
            row = {
                "full_solve_p50_ms": _latency_stats(full_lats)["p50_ms"],
                "delta_hit_p50_ms": _latency_stats(hit_lats)["p50_ms"],
                "delta_served": served,
                "of": len(reqs),
            }
            if served >= len(reqs) // 2:
                s = row["full_solve_p50_ms"] / max(row["delta_hit_p50_ms"],
                                                   1e-6)
                row["speedup"] = round(s, 2)
                speedups.append(s)
            delta[f"rank{rank}"] = row
        out["delta"] = delta
        out["serve_cache_delta_speedup"] = (
            round(min(speedups), 2) if speedups else None
        )

        # (c) warm-start tier: every bus perturbed (rank n > max_rank),
        # so the full solve runs — seeded vs cold iteration counts.
        scales = [float(s) for s in rng.uniform(0.9, 1.1, 24)]
        warm_iters = [
            svc_on.request("pf", PowerFlowRequest(
                case=case, scale=s, timeout_s=120)).iterations
            for s in scales
        ]
        cold_iters = [
            svc_off.request("pf", PowerFlowRequest(
                case=case, scale=s, timeout_s=120)).iterations
            for s in scales
        ]
        red = 1.0 - float(np.mean(warm_iters)) / float(np.mean(cold_iters))
        out["warm_start"] = {
            "warm_iters_mean": round(float(np.mean(warm_iters)), 2),
            "cold_iters_mean": round(float(np.mean(cold_iters)), 2),
            "iters_reduction_pct": round(100.0 * red, 1),
            "meets_25pct_target": bool(red >= 0.25),
        }
        out["serve_cache_warm_iters_reduction_pct"] = round(100.0 * red, 1)
        out["hit_ratio"] = svc_on.stats()["cache"]["hit_ratio"]
    finally:
        svc_off.stop()
        svc_on.stop()

    # (d) cold-herd single-flight proof: N concurrent identical requests
    # on a fresh digest dispatch exactly ONE device solve.  delta tier
    # off so the leader must take the full path (a delta answer would
    # also skip the dispatch, hiding what this row proves).
    svc_h = mk(delta_max_rank=0)
    try:
        svc_h.request("pf", PowerFlowRequest(case=case, timeout_s=300))
        lanes_metric = REGISTRY.get("serve_batch_lanes").labels("pf")
        before = lanes_metric.count
        req = PowerFlowRequest(case=case, scale=0.95, timeout_s=120)
        n_clients = 16
        barrier = threading.Barrier(n_clients)
        ok = [0]
        lock = threading.Lock()

        def client():
            barrier.wait(timeout=60)
            if svc_h.request("pf", req).converged:
                with lock:
                    ok[0] += 1

        threads = [threading.Thread(target=client)
                   for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        st = svc_h.stats()["cache"]
        out["single_flight"] = {
            "herd_clients": n_clients,
            "ok": ok[0],
            "solves_dispatched": lanes_metric.count - before,
            "flight_joins": st["flight_joins"],
        }
    finally:
        svc_h.stop()
    return out


def bench_qsts() -> dict:
    """The QSTS section (ISSUE 4 acceptance): (a) warm starts cut mean
    Newton iterations per timestep by >=30% vs cold starts on a
    24h/15-min profile, (b) scenario throughput scales with S under a
    bounded compile count (one program per chunk shape), (c) a job
    stopped mid-run resumes from its chunk checkpoint and reproduces
    the uninterrupted summary EXACTLY."""
    import tempfile

    from freedm_tpu.scenarios.engine import (
        QstsEngine,
        StudySpec,
        run_study,
        strip_timing,
    )

    base = dict(case="case14", scenarios=16, steps=96, dt_minutes=15.0,
                chunk_steps=24, seed=5)
    out: dict = {}

    # (a) warm vs cold mean Newton iterations per timestep.
    warm = run_study(StudySpec(warm_start=True, **base))
    cold = run_study(StudySpec(warm_start=False, **base))
    reduction = 1.0 - warm["iters_mean"] / cold["iters_mean"]
    out["warm_start"] = {
        "case": base["case"],
        "profile_steps": base["steps"],
        "dt_minutes": base["dt_minutes"],
        "warm_iters_mean": warm["iters_mean"],
        "cold_iters_mean": cold["iters_mean"],
        "iters_reduction_pct": round(100.0 * reduction, 1),
        "meets_30pct_target": bool(reduction >= 0.30),
    }

    # (b) throughput scaling with S, compile excluded: ONE engine per S
    # (its jitted chunk program persists across run_study calls), warmed
    # by a first run, timed on the second — steady-state chunk rate.
    scaling = {}
    for s in (1, 4, 16, 64):
        spec = StudySpec(case=base["case"], scenarios=s, steps=48,
                         dt_minutes=15.0, chunk_steps=24, seed=5)
        eng = QstsEngine(spec)
        first = run_study(spec, engine=eng)  # compile run
        again = run_study(spec, engine=eng)  # warm: no retrace
        scaling[str(s)] = {
            "scenario_steps_per_sec": again["scenario_steps_per_sec"],
            "compiles": first["compiles"],
        }
    out["throughput_scaling"] = scaling
    out["recompiles_bounded"] = bool(
        all(v["compiles"] <= 2 for v in scaling.values())
    )

    # (c) kill mid-run, resume from the chunk checkpoint, compare.
    with tempfile.TemporaryDirectory(prefix="qsts_bench_") as d:
        ck = f"{d}/study.json"
        spec = StudySpec(**base)
        partial = run_study(spec, checkpoint_path=ck, stop_after_chunks=2)
        resumed = run_study(spec, checkpoint_path=ck)
        uninterrupted = run_study(spec)
        exact = strip_timing(resumed) == strip_timing(uninterrupted)
        out["kill_resume"] = {
            "killed_after_chunks": partial["chunks_done"],
            "resumed_from_chunk": resumed["resumed_from_chunk"],
            "summary_exact_match": bool(exact),
        }
    return out


def bench_agents() -> dict:
    """``--sections agents``: the grid-edge agent-population gate set
    (docs/agents.md): (a) a MILLION-agent 24h IEEE30 day-study as one
    row — steady-state agent-steps/s is the CI-floored headline, (b)
    closed-loop vs replayed injections diverge (the Volt-VAR/EV/DR
    feedback through the solved voltages is live, not a replay), (c) a
    chunk-kill resume reproduces the uninterrupted million-agent
    summary EXACTLY (the per-agent state lanes ride the checkpoint)."""
    import tempfile
    from dataclasses import replace

    from freedm_tpu.scenarios.agents import AgentSpec
    from freedm_tpu.scenarios.engine import (
        QstsEngine,
        StudySpec,
        run_study,
        strip_timing,
    )

    agents = AgentSpec(ev=400_000, thermostat=300_000, inverter=150_000,
                       dr=150_000)
    spec = StudySpec(case="case_ieee30", scenarios=1, steps=24,
                     dt_minutes=60.0, chunk_steps=8, seed=11, agents=agents)
    out: dict = {}

    # (a) the million-agent day study: ONE engine, a compile run, then
    # the timed warm run (steady-state rate, like bench_qsts scaling).
    eng = QstsEngine(spec)
    first = run_study(spec, engine=eng)
    warm = run_study(spec, engine=eng)
    out["day_study"] = {
        "case": spec.case,
        "agents_total": warm["agents_total"],
        "steps": spec.steps,
        "dt_minutes": spec.dt_minutes,
        "agent_steps_per_sec": warm["agent_steps_per_sec"],
        "scenario_steps_per_sec": warm["scenario_steps_per_sec"],
        "compiles": first["compiles"],
        "agent_energy_puh_mean": warm["agent_energy_puh_mean"],
    }

    # (b) closed-loop vs replayed: the SAME population observing flat
    # 1.0 pu instead of the solved voltages.  Nonzero physics deltas
    # are the proof the feedback loop actually closes.
    replay = replace(spec, agents=replace(agents, closed_loop=False))
    replayed = run_study(replay)
    out["closed_vs_replayed"] = {
        "loss_mwh_delta": round(abs(warm["energy_loss_mwh_mean"]
                                    - replayed["energy_loss_mwh_mean"]), 6),
        "q_peak_closed_pu": warm["agent_q_peak_pu"],
        "q_peak_replayed_pu": replayed["agent_q_peak_pu"],
        "physics_diverged": bool(
            warm["energy_loss_mwh_mean"] != replayed["energy_loss_mwh_mean"]
            or warm["v_min_pu"] != replayed["v_min_pu"]
        ),
    }

    # (c) kill after one chunk, resume from the checkpoint (million
    # agent-state lanes round-trip through it), compare EXACTLY.
    with tempfile.TemporaryDirectory(prefix="qsts_agents_bench_") as d:
        ck = f"{d}/study.json"
        partial = run_study(spec, engine=eng, checkpoint_path=ck,
                            stop_after_chunks=1)
        resumed = run_study(spec, engine=eng, checkpoint_path=ck)
        out["kill_resume"] = {
            "killed_after_chunks": partial["chunks_done"],
            "resumed_from_chunk": resumed["resumed_from_chunk"],
            "summary_exact_match": bool(
                strip_timing(resumed) == strip_timing(warm)
            ),
        }
    return out


def bench_serve(duration_s: float = 1.5) -> dict:
    """The serving section of the benchmark artifact (ISSUE 3 +
    ISSUE 9): per-case offered-load sweeps over an equal pf/N-1/VVC
    mix, per-workload micro-batching speedups vs batch-size-1
    dispatch, the overload envelope, and the pipelined-vs-serialized
    head-to-head (per-engine executor lanes vs single-thread dispatch,
    with its own offered-load sweep)."""
    out = {
        "case14": _serve_case("case14", duration_s, per_workload=True),
        "case_ieee30": _serve_case("case_ieee30", duration_s,
                                   per_workload=False),
    }
    out["overload_case14"] = _serve_overload("case14", duration_s)
    out["pipeline_case14"] = _serve_pipeline("case14", duration_s)
    return out


def bench_snapshot(duration_s: float = 1.5) -> dict:
    """``--sections snapshot``: the consistent-cut observatory's cost
    envelope (docs/snapshots.md).  Two gated rows over a live
    single-replica fleet (in-process Service behind a real ServeServer,
    fronted by the router's snapshot fan-out):

    - **capture latency**: p50/p95 over repeated marker-coordinated
      cuts of the idle fleet — what one ``POST /v1/snapshot`` costs
      end to end (HTTP fan-out + replica capture + audit);
    - **non-disruption**: serve p99 under a fixed closed-loop pf load,
      measured baseline-vs-with a concurrent snapshot loop.  The
      acceptance bar is the ratio (snapshots must not perturb serving
      p99 by more than 20%), floored in CI as
      ``serve_p99_snapshot_latency_ratio <= 1.2``.
    """
    from freedm_tpu.serve import ServeConfig, Service
    from freedm_tpu.serve.http import ServeServer
    from freedm_tpu.serve.router import Router, RouterConfig
    from freedm_tpu.serve.service import PowerFlowRequest

    # cache_mb=0: snapshots must coexist with the BATCHER, not with the
    # cache tier answering repeats before the queue ever fills.
    svc = Service(ServeConfig(max_batch=32, max_wait_ms=2.0,
                              queue_depth=4096, buckets=(1, 8, 32),
                              cache_mb=0.0))
    req = PowerFlowRequest(case="case14", scale=1.0)
    server = None
    try:
        _warm_engine(svc, "pf", req, (1, 8, 32))
        server = ServeServer(svc, port=0).start()
        router = Router([f"127.0.0.1:{server.port}"],
                        RouterConfig(snapshot_timeout_s=10.0))

        # Capture ladder: cuts of the idle fleet.  Warm the HTTP path
        # first — the first cut pays connection + handler import costs
        # that say nothing about steady-state capture latency.
        for _ in range(3):
            router.snapshot()
        caps, incomplete = [], 0
        for _ in range(24):
            cut = router.snapshot()
            if cut["status"] == "complete" and not cut["violations"]:
                caps.append(cut["capture_ms"] / 1e3)
            else:
                incomplete += 1
        capture = _latency_stats(caps)

        # Non-disruption: identical closed-loop windows, one quiet, one
        # with a background thread initiating cuts every ~50 ms.  The
        # windows are adjacent (same process, same warm engines) so the
        # ratio isolates the snapshot machinery itself.
        pool = [("pf", req)]
        _pipelined_load(svc, pool, 2, 8, min(0.4, duration_s))  # ramp
        _, base_samples, _ = _pipelined_load(svc, pool, 2, 8, duration_s)
        baseline = _latency_stats([s[1] for s in base_samples])

        stop = threading.Event()
        concurrent_cuts = [0]

        def snapper() -> None:
            while not stop.is_set():
                try:
                    c = router.snapshot()
                    if c["status"] == "complete":
                        concurrent_cuts[0] += 1
                except Exception:  # noqa: BLE001 — keep the loop alive
                    pass
                stop.wait(0.05)

        th = threading.Thread(target=snapper, daemon=True,
                              name="bench-snapper")
        th.start()
        try:
            _, snap_samples, _ = _pipelined_load(svc, pool, 2, 8,
                                                 duration_s)
        finally:
            stop.set()
            th.join(timeout=15.0)
        under_snapshot = _latency_stats([s[1] for s in snap_samples])

        ratio = None
        if baseline["p99_ms"] and under_snapshot["p99_ms"]:
            ratio = round(under_snapshot["p99_ms"] / baseline["p99_ms"], 3)
        return {
            "snapshot_capture_p50_ms": capture["p50_ms"],
            "snapshot_capture_p95_ms": capture["p95_ms"],
            "snapshot_capture_count": capture["count"],
            "snapshot_capture_incomplete": incomplete,
            "serve_p99_baseline_ms": baseline["p99_ms"],
            "serve_p99_with_snapshot_ms": under_snapshot["p99_ms"],
            # "latency" in the name makes perf_gate treat this
            # lower-is-better; --floor serve_p99_snapshot_latency_ratio=1.2
            # is the <=20% acceptance ceiling.
            "serve_p99_snapshot_latency_ratio": ratio,
            "concurrent_cuts_completed": concurrent_cuts[0],
        }
    finally:
        if server is not None:
            server.stop()
        svc.stop()


# ---------------------------------------------------------------------------
# Mesh scaling sweep (ISSUE 6): the same batched workload at 1/2/../all
# local devices, lane axes sharded via shard_map (parallel/mesh.py).
# On a 1-device host this degrades to a single-device no-op row; on a
# multi-device host (incl. CPU with
# XLA_FLAGS=--xla_force_host_platform_device_count=N) the sweep is the
# acceptance measurement: >= 1.6x QSTS scenario throughput at D devices
# with byte-identical results.
# ---------------------------------------------------------------------------


def _mesh_device_counts() -> list:
    """1, the powers of two that divide the local device count, and the
    full count — every entry divides D, so one lane count serves all."""
    d_all = jax.local_device_count()
    counts = [1]
    d = 2
    while d < d_all:
        if d_all % d == 0:
            counts.append(d)
        d *= 2
    if d_all > 1:
        counts.append(d_all)
    return counts


def _lane_count(minimum: int, device_counts: list) -> int:
    """Smallest multiple of every device count that is >= minimum."""
    d_all = device_counts[-1]
    return d_all * max(1, -(-minimum // d_all))


def bench_mesh() -> dict:
    """QSTS scenario-axis and Monte-Carlo lane-axis scaling over the
    local device mesh, with sharded-vs-unsharded identity checks."""
    from freedm_tpu.parallel.mesh import make_mesh
    from freedm_tpu.scenarios.engine import (
        QstsEngine,
        StudySpec,
        run_study,
        strip_timing,
    )
    from freedm_tpu.utils import cplx

    d_all = jax.local_device_count()
    counts = _mesh_device_counts()
    out: dict = {"devices_available": d_all}

    # (a) QSTS: vmap-over-scenarios sharded, scan-over-time local.
    s_lanes = _lane_count(16, counts)
    spec_kw = dict(case="mesh118", scenarios=s_lanes, steps=24,
                   chunk_steps=24, seed=5, max_iter=8)
    qsts: dict = {}
    base_rate = None
    base_summary = None
    identical = []
    for d in counts:
        spec = StudySpec(mesh_devices=0 if d == 1 else d, **spec_kw)
        eng = QstsEngine(spec)
        run_study(spec, engine=eng)  # compile + warm
        s = run_study(spec, engine=eng)  # steady-state measurement
        rate = s["scenario_steps_per_sec"]
        row = {
            "scenario_steps_per_sec": rate,
            "qsts_steps_per_sec_per_device": round(rate / d, 1),
        }
        if d == 1:
            base_rate, base_summary = rate, s
        else:
            row["speedup_vs_1dev"] = round(rate / base_rate, 2)
            row["scaling_efficiency"] = round(rate / (base_rate * d), 3)
            same = strip_timing(s) == strip_timing(base_summary)
            identical.append(same)
            row["identical_to_unsharded"] = same
        qsts[str(d)] = row
    out["qsts"] = qsts
    out["qsts_workload"] = {"case": spec_kw["case"],
                            "scenarios": s_lanes, "steps": spec_kw["steps"]}

    # (b) Monte-Carlo ladder lanes through the mesh-batched solver.
    feeder = synthetic_radial(512, seed=0, load_kw=1.0)
    lanes = _lane_count(32, counts)
    rng = np.random.default_rng(0)
    s_load = cplx.as_c(
        rng.uniform(0.7, 1.3, (lanes, 1, 1)) * np.asarray(feeder.s_load)[None]
    )
    mc: dict = {}
    mc_base = None
    mc_ref = None
    mc_identical = []
    for d in counts:
        if d == 1:
            _, sf = ladder.make_ladder_solver(feeder, max_iter=MAX_ITER)
            solver = jax.jit(jax.vmap(sf))
        else:
            _, solver = ladder.make_ladder_solver(
                feeder, max_iter=MAX_ITER,
                mesh=make_mesh(d, axes=("batch",)),
            )
        r = solver(s_load)
        dt = _time(lambda: solver(s_load), lambda r: r.v_node.re, reps=3)
        rate = lanes / dt
        row = {"mc_lane_solves_per_sec": round(rate, 1),
               "mc_lane_solves_per_sec_per_device": round(rate / d, 1)}
        if d == 1:
            mc_base = rate
            mc_ref = np.asarray(r.v_node.re).tobytes()
        else:
            row["speedup_vs_1dev"] = round(rate / mc_base, 2)
            row["scaling_efficiency"] = round(rate / (mc_base * d), 3)
            same = np.asarray(r.v_node.re).tobytes() == mc_ref
            mc_identical.append(same)
            row["identical_to_unsharded"] = same
        mc[str(d)] = row
    out["mc"] = mc
    out["mc_workload"] = {"feeder_buses": 512, "lanes": lanes,
                          "iters": MAX_ITER}

    if d_all == 1:
        out["no_op"] = True  # nothing to shard over; 1-device rows only
    else:
        top = str(counts[-1])
        out["qsts_speedup_at_max_devices"] = qsts[top]["speedup_vs_1dev"]
        out["mc_speedup_at_max_devices"] = mc[top]["speedup_vs_1dev"]
        out["sharded_identical"] = bool(all(identical) and all(mc_identical))
    return out


# ---------------------------------------------------------------------------
# Sparse-vs-dense solver benchmarks (ISSUE 7): the BCSR sparse Newton
# path head-to-head with the dense-LU path at 2000 buses (single and
# batched lanes — acceptance: >=3x with solutions within documented
# tolerance), the 10k-bus solve through the sparse assembly vs the
# jvp-based matrix-free path (same preconditioner, so the delta is the
# assembly strategy), and DC-screen lane throughput.
# ---------------------------------------------------------------------------


def bench_sparse(with_10k: bool = False) -> dict:
    from freedm_tpu.pf.dc import make_dc_solver
    from freedm_tpu.pf.n1 import make_n1_screen
    from freedm_tpu.pf.sparse import (
        jacobian_pattern,
        make_sparse_newton_solver,
    )

    out: dict = {}
    sys2k = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    pat = jacobian_pattern(sys2k)
    slots = (2 * sys2k.n_bus) ** 2
    out["jacobian_2000bus"] = {
        "nnz": pat.nnz,
        "dense_slots": slots,
        "density_pct": round(100.0 * pat.nnz / slots, 4),
    }

    # -- 2000-bus head-to-head: single solve ---------------------------------
    sp, sp_fixed = make_sparse_newton_solver(sys2k, max_iter=12,
                                             inner_iters=16)
    r_s = sp()
    assert bool(r_s.converged), f"sparse 2k diverged: {float(r_s.mismatch)}"
    dn, dn_fixed = make_newton_solver(sys2k, max_iter=10)
    r_d = dn()
    assert bool(r_d.converged), "dense 2k diverged"
    max_dv = float(jnp.max(jnp.abs(r_s.v - r_d.v)))
    sp_rate = 1.0 / _time(sp, lambda r: r.v, reps=5)
    dn_rate = 1.0 / _time(dn, lambda r: r.v, reps=2)

    # -- 2000-bus head-to-head: batched lanes --------------------------------
    lanes = 4  # a dense lane is ~6 s on a 2-vCPU host; keep the row honest
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.9, 1.1, (lanes, 1))
    p = jnp.asarray(scale * sys2k.p_inj[None])
    q = jnp.asarray(scale * sys2k.q_inj[None])
    b_sp = jax.jit(jax.vmap(lambda pi, qi: sp_fixed(p_inj=pi, q_inj=qi)))
    b_dn = jax.jit(jax.vmap(lambda pi, qi: dn_fixed(p_inj=pi, q_inj=qi)))
    rb_s = b_sp(p, q)
    assert bool(jnp.all(rb_s.converged)), "sparse 2k batch diverged"
    sp_lane_rate = lanes / _time(lambda: b_sp(p, q), lambda r: r.v, reps=3)
    rb_d = b_dn(p, q)
    dn_lane_rate = lanes / _time(lambda: b_dn(p, q), lambda r: r.v, reps=1)
    batch_dv = float(jnp.max(jnp.abs(rb_s.v - rb_d.v)))

    single_speedup = sp_rate / dn_rate
    batch_speedup = sp_lane_rate / dn_lane_rate
    out.update({
        "nr_2000bus_dense_solves_per_sec": round(dn_rate, 3),
        "nr_2000bus_sparse_solves_per_sec": round(sp_rate, 3),
        "nr_2000bus_sparse_speedup": round(single_speedup, 2),
        f"nr_2000bus_batch{lanes}_dense_lane_solves_per_sec": round(
            dn_lane_rate, 3
        ),
        f"nr_2000bus_batch{lanes}_sparse_lane_solves_per_sec": round(
            sp_lane_rate, 3
        ),
        "nr_2000bus_batch_sparse_speedup": round(batch_speedup, 2),
        # Documented tolerance (docs/solvers.md): both backends converge
        # the same masked mismatch below the same tol; f32 solutions
        # agree to ~2e-4 pu worst-case (measured ~1e-6 here).
        "sparse_vs_dense_max_dv_pu": float(f"{max(max_dv, batch_dv):.2e}"),
        "sparse_within_tolerance": bool(max(max_dv, batch_dv) < 2e-4),
        "meets_3x_target": bool(
            single_speedup >= 3.0 and batch_speedup >= 3.0
        ),
    })

    # -- DC loadflow screen: lane throughput ---------------------------------
    dc = make_dc_solver(sys2k)
    inj_lanes = 4096
    p_stack = jnp.asarray(
        rng.uniform(0.8, 1.2, (inj_lanes, 1)) * sys2k.p_inj[None]
    )
    r_inj = dc.solve(p_stack)
    assert bool(jnp.all(jnp.isfinite(r_inj.theta))), "DC injection lanes NaN"
    inj_rate = inj_lanes / _time(
        lambda: dc.solve(p_stack), lambda r: r.theta, reps=5
    )
    n_out = 1024  # chord outages: indices >= n_bus never island the ring
    ks = jnp.arange(sys2k.n_bus, sys2k.n_bus + n_out)
    r_out = dc.screen_outages(ks)
    assert not bool(jnp.any(r_out.islanded)), "chord outage flagged islanded"
    out_rate = n_out / _time(
        lambda: dc.screen_outages(ks), lambda r: r.theta, reps=5
    )
    out.update({
        "dc_2000bus_injection_lanes_per_sec": round(inj_rate, 1),
        "dc_2000bus_outage_lanes_per_sec": round(out_rate, 1),
    })

    # -- DC prefilter in front of the AC screen ------------------------------
    # 64 requested outages, AC-verify the 8 DC-worst: the whole point is
    # the DC pass costing a small fraction of the AC lanes it avoids.
    screen_pre = make_n1_screen(sys2k, max_iter=12, backend="sparse",
                                dc_prefilter=8)
    ks64 = np.arange(sys2k.n_bus, sys2k.n_bus + 64)
    pre_res = screen_pre(ks64)
    assert bool(np.all(np.asarray(pre_res.result.converged)))
    pre_ms = _time(
        lambda: screen_pre(ks64), lambda r: r.result.v, reps=2
    ) * 1000.0
    out["n1_2000bus_64to8_dc_prefiltered_screen_ms"] = round(pre_ms, 1)

    # -- 10k-bus: BCSR assembly vs jvp-based matrix-free, shared factors -----
    if with_10k:
        from freedm_tpu.pf.krylov import (
            build_fdlf_precond,
            make_krylov_solver,
            true_mismatch,
        )

        sys10k = synthetic_mesh(10_000, seed=4, load_mw=2.0, chord_frac=0.3)
        # One preconditioner build shared by both paths, so the measured
        # delta is the assembly strategy alone.  kind="auto": streaming
        # inverses on tpu/gpu; LU factors on cpu, where the Newton-
        # Schulz [10k,10k] GEMM iteration is infeasible.
        pre10 = build_fdlf_precond(sys10k, kind="auto")
        s10, _ = make_sparse_newton_solver(
            sys10k, max_iter=15, inner_iters=16, precond=pre10
        )
        r10 = s10()
        assert bool(r10.converged), f"sparse 10k: {float(r10.mismatch)}"
        sp10_ms = _time(s10, lambda r: r.v, reps=2) * 1000.0
        k10, _ = make_krylov_solver(
            sys10k, max_iter=15, inner_iters=16, precond=pre10
        )
        rk10 = k10()
        assert bool(rk10.converged), "krylov 10k diverged"
        ky10_ms = _time(k10, lambda r: r.v, reps=2) * 1000.0
        out.update({
            "nr_10000bus_sparse_solve_ms": round(sp10_ms, 1),
            "nr_10000bus_sparse_true_mismatch_pu": float(
                f"{true_mismatch(sys10k, r10):.2e}"
            ),
            "nr_10000bus_mfree_solve_ms": round(ky10_ms, 1),
            "nr_10000bus_sparse_vs_mfree_drop_pct": round(
                100.0 * (1.0 - sp10_ms / ky10_ms), 1
            ),
            "precond_kind_10k": pre10.kind,
        })
    return out


def bench_topo(chunk: int = 4096, refactor_lanes: int = 32,
               top_k: int = 8) -> dict:
    """``--sections topo``: the switching-screen engine's gate set
    (ISSUE 15 acceptance; ROADMAP "Topology optimization").

    - ``topo_variants_per_sec`` — the headline row ``perf_gate`` pins
      with ``--floor topo_variants_per_sec=10000``: every rank-≤2
      variant of a 118-bus mesh through the full screen ladder
      (vectorized radiality check + rank-r SMW lanes + on-device top-k
      merge), chunked exactly like the sweep job runs it;
    - ``topo_smw_vs_refactor_speedup`` — the same variants solved by
      per-lane B′ re-formation + dense solve (the per-variant
      refactorization the SMW lanes delete), per-variant time ratio;
    - ``topo_ac_verify_topk_ms`` — the shortlist's sparse-backend AC
      verify wall (the "verify" half of screen-then-verify);
    - ``topo_excluded_pct`` — share of variants the screen excludes
      (structural disconnection + the SMW backstop; the agreement
      between the two checks is pinned by tests — the bench just
      reports the rate).
    """
    import jax.numpy as jnp_

    from freedm_tpu.pf import topo as tp

    sys_ = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    m = sys_.n_branch
    ts = tp.make_topo_screen(sys_, r_max=2)
    rad = tp.make_radiality_check(sys_, r_max=2)
    merge = tp.make_topk_merge(2, top_k)
    variants = tp.enumerate_variants(np.arange(m), 2)
    v_total = variants.shape[0]

    def run_all():
        best = merge.init()
        excluded = 0
        for v0 in range(0, v_total, chunk):
            block = variants[v0:v0 + chunk]
            real = block.shape[0]
            if real < chunk:
                block = np.concatenate(
                    [block, np.repeat(block[-1:], chunk - real, axis=0)]
                )
            sl = jnp_.asarray(block)
            valid = jnp_.arange(chunk) < real
            # The shared ladder (pf/topo.screen_chunk): the bench runs
            # the SAME masking/objective/accounting as the serve engine
            # and the sweep job.
            verdict = tp.screen_chunk(ts, rad, sl, valid, "mesh",
                                      "loss", 1.0)
            gid = jnp_.asarray(v0 + np.arange(chunk), jnp_.int32)
            best = merge(*best, verdict.objective, sl, gid)
            excluded += int(np.asarray(
                verdict.disconnected + verdict.islanded
            ))
        jax.block_until_ready(best[0])
        return best, excluded

    (best, excluded) = run_all()  # compile + warm
    t0 = time.perf_counter()
    best, excluded = run_all()
    dt = time.perf_counter() - t0
    rate = v_total / dt

    # Per-variant refactorization head-to-head: re-form B′ with the
    # lane's status and dense-solve it — the O(n³)-per-variant path the
    # SMW lanes replace.  Feasible lanes only (a singular refactorized
    # B′ would be garbage, not slow).
    from freedm_tpu.pf.fdlf import decoupled_parts
    from freedm_tpu.utils import cplx as _cplx

    rdtype = _cplx.default_rdtype(None)
    parts = decoupled_parts(sys_, rdtype)
    th_free = parts.th_free
    p0 = jnp_.asarray(sys_.p_inj, rdtype)
    obj_all = np.asarray(best[0], np.float64)
    sl_all = np.asarray(best[1], np.int64)
    feasible_rows = sl_all[np.isfinite(obj_all)]
    # A MIXED-rank sample: enumeration is rank-ascending, so a naive
    # [:N] slice would measure rank-1 lanes only and never exercise the
    # [r, r] capacitance solve the head-to-head exists to gate.
    pool = np.asarray(tp.enumerate_variants(np.arange(9), 2))
    n1_rows = pool[pool[:, 1] < 0][: refactor_lanes // 4]
    n2_rows = pool[pool[:, 1] >= 0][: refactor_lanes - n1_rows.shape[0]]
    sample = np.concatenate([n1_rows, n2_rows])[:refactor_lanes]

    @jax.jit
    def refactor_screen(slots):
        def lane(sl):
            drop = jnp_.where(sl >= 0, sl, m)
            status = jnp_.ones(m, rdtype).at[drop].set(0.0, mode="drop")
            b = parts.b_prime(status)
            rhs = jnp_.where(th_free > 0, p0, 0.0)
            return jnp_.linalg.solve(b, rhs)

        return jax.vmap(lane)(jnp_.asarray(slots))

    ms_refactor = _time(
        lambda: refactor_screen(sample), lambda r: r, reps=3
    ) * 1000.0 / sample.shape[0]
    smw_detail = ts.detail(np.asarray(sample, np.int32), flow_limit=1.0)
    ms_smw = _time(
        lambda: ts.screen(np.asarray(sample, np.int32), flow_limit=1.0),
        lambda r: r.worst_flow, reps=10,
    ) * 1000.0 / sample.shape[0]
    # Equivalence stamp: the two paths solve the same systems.
    ref_theta = np.asarray(refactor_screen(sample))
    smw_theta = np.asarray(smw_detail.theta)
    ok = ~np.asarray(smw_detail.islanded)
    dtheta = float(np.max(np.abs(ref_theta[ok] - smw_theta[ok])))
    # f64 under x64 (tests/CI); f32 noise floor on accelerator runs.
    tol = 1e-8 if rdtype == jnp_.float64 else 1e-3
    assert dtheta < tol, f"SMW drifted from refactorization: {dtheta}"

    # Shortlist AC verify wall (sparse backend, warm-started lanes).
    # Pad to the verifier's compiled [top_k, m] contract with base-
    # topology rows when fewer shortlist rows are feasible.
    verifier = tp.make_ac_verifier(sys_, k=top_k)
    k_feasible = min(top_k, feasible_rows.shape[0])
    short = np.full((top_k, feasible_rows.shape[1]), -1, np.int32)
    short[:k_feasible] = feasible_rows[:k_feasible]
    status = np.asarray(tp.status_from_slots(short, m))
    r = verifier(status)
    assert bool(np.all(np.asarray(r.converged)[:k_feasible])), \
        "shortlist AC verify diverged"
    ac_ms = _time(lambda: verifier(status), lambda x: x.v, reps=3) * 1000.0

    return {
        "topo_bench_variants": int(v_total),
        "topo_variants_per_sec": round(rate, 1),
        "topo_chunk_variants": int(chunk),
        "topo_smw_per_variant_us": round(ms_smw * 1000.0, 3),
        "topo_refactor_per_variant_us": round(ms_refactor * 1000.0, 3),
        "topo_smw_vs_refactor_speedup": round(ms_refactor / ms_smw, 2),
        "topo_ac_verify_topk_ms": round(ac_ms, 2),
        "topo_excluded_pct": round(100.0 * excluded / v_total, 2),
    }


def bench_quick() -> dict:
    """The cheap subset the CI perf gate runs twice per build
    (``tools/perf_gate.py``): small cases, short compiles, enough reps
    for the rolling-median baseline to be meaningful on a loaded
    2-vCPU runner."""
    return {
        "n1_case30_real_smw_ms": round(bench_n1_case30_smw(), 2),
        "n1_118way_smw_screen_ms": round(bench_n1_118_smw(), 2),
        "lb_256node_rounds_per_sec": round(bench_lb_256(), 1),
    }


def bench_roofline(inventory_path: str, tol: float = 0.5,
                   repeats: int = 3) -> dict:
    """Measured-vs-model roofline pass over the whole program registry
    (``core/roofline.py``): drive every PROGRAM_REGISTRY entry on the
    live backend, join against gridprobe's static flops/bytes, and
    write/diff ``roofline_inventory.json`` — the GP006-style drift gate
    for the model columns (flops, bytes, intensity, bound class).  A
    missing inventory is written (first run / new backend); an existing
    one is diffed and any drift exits 1 with readable findings, exactly
    the gridprobe CI contract.  The returned columns are all
    ``roofline_``-prefixed — direction-neutral in the perf gate, so the
    BENCH trajectory records achieved MFU/intensity without gating on a
    noisy host."""
    import pathlib
    import sys

    from freedm_tpu.core import roofline as rl

    rl.ROOFLINE.configure(enabled=True)
    res = rl.ROOFLINE.measure_registry(repeats=repeats)
    report = rl.ROOFLINE.report()
    inv = rl.build_roofline_inventory(report)
    path = pathlib.Path(inventory_path)
    if not path.is_absolute():
        path = pathlib.Path(__file__).resolve().parent / path
    if path.exists():
        recorded = json.loads(path.read_text(encoding="utf-8"))
        findings = rl.diff_roofline_inventory(inv, recorded, tol)
        if findings:
            for f in findings:
                print(f"ROOFLINE DRIFT: {f}", file=sys.stderr)
            print(
                f"roofline inventory drifted ({len(findings)} finding(s))"
                f" — regenerate {path} deliberately if the change is"
                f" intended", file=sys.stderr,
            )
            raise SystemExit(1)
        written = False
    else:
        path.write_text(
            json.dumps(inv, indent=1, sort_keys=True) + "\n",
            encoding="utf-8",
        )
        written = True
    out = {
        "roofline_programs_total": len(inv["programs"]),
        "roofline_measured_total": len(res["measured"]),
        "roofline_errors_total": len(res["errors"]),
        "roofline_backend": inv["backend"],
        "roofline_inventory_written": written,
    }
    for name, row in sorted(inv["programs"].items()):
        slug = name.replace("/", "_")
        m = row["measured"]
        if m["mfu_pct"] is not None:
            out[f"roofline_{slug}_mfu_pct"] = m["mfu_pct"]
        if row["intensity_flops_per_byte"] is not None:
            out[f"roofline_{slug}_intensity"] = (
                row["intensity_flops_per_byte"]
            )
    return out


def _gridprobe_snapshot() -> dict:
    """Program-inventory stamps for the snapshot: how many distinct
    jitted programs gridprobe audits and their summed XLA cost-analysis
    FLOP estimate (tools/ir_inventory.json — read, not re-traced: the
    checked-in file IS the audited state of this tree).  Rides along in
    every snapshot so the perf trajectory can correlate throughput
    changes with program-set changes (an accidental extra shape bucket
    shows up here before it shows up as a recompile stall)."""
    import pathlib

    inv = (pathlib.Path(__file__).resolve().parent
           / "freedm_tpu" / "tools" / "ir_inventory.json")
    try:
        d = json.loads(inv.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        # Same schema as the normal path — trajectory tooling must be
        # able to index both keys across every snapshot.
        return {"gridprobe_programs_total": 0,
                "gridprobe_inventory_gflops": 0.0}
    progs = d.get("programs", {})
    total = sum(
        p.get("flops", 0.0) for p in progs.values()
        if isinstance(p.get("flops"), (int, float)) and p["flops"] > 0
    )
    return {
        "gridprobe_programs_total": len(progs),
        "gridprobe_inventory_gflops": round(total / 1e9, 6),
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="freedm_tpu headline benchmarks")
    ap.add_argument(
        "--sections", default="solvers,serve,qsts",
        help="comma list of sections to run: solvers, serve, qsts, agents, "
             "quick, mesh, sparse, cache, mfu, topo, roofline, snapshot "
             "(default "
             "solvers,serve,qsts; roofline drives every registered "
             "program through the roofline observatory and writes/diffs "
             "the drift-gated roofline_inventory.json; "
             "topo is the switching-screen gate set — variants/s through "
             "the radiality+SMW+top-k ladder, SMW-vs-refactorization "
             "head-to-head, shortlist AC-verify wall; mfu is "
             "the solver-core MFU gate set — krylov lane throughput at "
             "mixed precision, mixed-vs-f64 head-to-head, donation "
             "on/off, and with --mfu-10k the 10k-bus wall; quick is "
             "the CI perf-gate subset; mesh is the device-scaling sweep — "
             "force virtual CPU devices with "
             "XLA_FLAGS=--xla_force_host_platform_device_count=N; sparse "
             "is the dense-vs-BCSR head-to-head + DC screen throughput; "
             "cache is the incremental serving tier's exact/delta/warm "
             "ladders + the single-flight herd proof; agents is the "
             "grid-edge agent-population gate set — a million-agent 24h "
             "day-study row, closed-vs-replayed divergence, and the "
             "chunk-kill exact-resume proof; snapshot is the "
             "consistent-cut observatory's cost envelope — capture "
             "p50/p95 plus serve p99 with and without a concurrent "
             "snapshot loop, gated as "
             "serve_p99_snapshot_latency_ratio <= 1.2)",
    )
    ap.add_argument("--serve-duration", type=float, default=1.5, metavar="S",
                    help="seconds per serving measurement window")
    ap.add_argument("--sparse-10k", action="store_true",
                    help="include the sparse section's 10k-bus head-to-head "
                         "(two [10k,10k] factorizations + ~minute-long CPU "
                         "solves — ~10 min on a 2-vCPU host, milliseconds "
                         "on a TPU; the 2000-bus acceptance rows always "
                         "run)")
    ap.add_argument("--mfu-lanes", type=int, default=256, metavar="N",
                    help="lane count for the mfu section's krylov batch "
                         "(default 256 — the gated row; shrink it for a "
                         "CPU smoke run)")
    ap.add_argument("--mfu-10k", action="store_true",
                    help="include the mfu section's 10k-bus mesh wall row "
                         "(the <60 ms acceptance ceiling; minutes on a "
                         "small CPU host, like --sparse-10k)")
    ap.add_argument("--roofline-inventory",
                    default="freedm_tpu/tools/roofline_inventory.json",
                    metavar="PATH",
                    help="roofline inventory JSON the roofline section "
                         "writes (when missing) or diffs against "
                         "(repo-root relative)")
    ap.add_argument("--roofline-repeats", type=int, default=3, metavar="N",
                    help="timed dispatches per program in the roofline "
                         "section (default 3; the compile call is always "
                         "excluded)")
    ap.add_argument("--roofline-tol", type=float, default=0.5, metavar="R",
                    help="relative drift tolerance for the roofline "
                         "inventory's gated model columns (default 0.5, "
                         "matching the gridprobe GP006 gate)")
    args = ap.parse_args(argv)
    sections = {s.strip() for s in args.sections.split(",") if s.strip()}
    unknown = sections - {"solvers", "serve", "qsts", "agents", "quick",
                          "mesh", "sparse", "cache", "mfu", "topo",
                          "roofline", "snapshot"}
    if unknown or not sections:
        raise SystemExit(
            f"--sections needs a non-empty subset of solvers,serve,qsts,"
            f"agents,quick,mesh,sparse,cache,mfu,topo,roofline,snapshot; "
            f"got {args.sections!r}"
        )

    obj: dict = {}
    if "serve" in sections:
        obj["serve"] = bench_serve(duration_s=args.serve_duration)
    if "mfu" in sections:
        obj["mfu"] = bench_mfu(lanes=args.mfu_lanes, with_10k=args.mfu_10k)
    if "cache" in sections:
        obj["cache"] = bench_cache()
    if "topo" in sections:
        obj["topo"] = bench_topo()
    if "qsts" in sections:
        obj["qsts"] = bench_qsts()
    if "agents" in sections:
        obj["agents"] = bench_agents()
    if "mesh" in sections:
        obj["mesh"] = bench_mesh()
    if "sparse" in sections:
        obj["sparse"] = bench_sparse(with_10k=args.sparse_10k)
    if "snapshot" in sections:
        obj["snapshot"] = bench_snapshot(duration_s=args.serve_duration)
    if "roofline" in sections:
        obj["roofline"] = bench_roofline(
            args.roofline_inventory, tol=args.roofline_tol,
            repeats=args.roofline_repeats,
        )
    # quick is a strict subset of the solvers section's extra metrics:
    # when solvers also runs, its full-measurement rows supersede quick
    # (same keys, longer reps), so quick only runs standalone.
    if "quick" in sections and "solvers" not in sections:
        quick = bench_quick()
        obj["extra"] = quick
        obj["metric"] = "n1_case30_real_smw_ms"
        obj["value"] = quick["n1_case30_real_smw_ms"]
        obj["unit"] = "ms"
        obj["vs_baseline"] = None
    if "solvers" in sections:
        _solver_sections(obj)
    if "metric" not in obj and "serve" in obj:
        # serve-only invocation: the headline is the best per-workload
        # micro-batching speedup (ISSUE 3 acceptance: >= 8x vs
        # batch-size-1 dispatch).
        case14 = obj["serve"]["case14"]
        speedups = {
            k: v["microbatch_speedup"]
            for k, v in case14.items()
            if isinstance(v, dict) and v.get("microbatch_speedup")
        }
        if speedups:
            w = max(speedups, key=speedups.get)
            obj["metric"] = f"serve_{w}_case14_microbatch_speedup"
            obj["value"] = speedups[w]
            obj["vs_baseline"] = round(speedups[w] / 8.0, 2)
        else:  # batch1 completed nothing anywhere: no ratio to report
            obj["metric"] = "serve_case14_microbatch_speedup"
            obj["value"] = None
            obj["vs_baseline"] = None
        obj["unit"] = "x vs batch-size-1"
    elif "metric" not in obj and "qsts" in obj:
        # qsts-only invocation: the headline is the warm-start saving
        # (ISSUE 4 acceptance: >= 30% fewer Newton iterations/timestep).
        ws = obj["qsts"]["warm_start"]
        obj["metric"] = "qsts_warm_start_iters_reduction_pct"
        obj["value"] = ws["iters_reduction_pct"]
        obj["unit"] = "% vs cold start"
        obj["vs_baseline"] = round(ws["iters_reduction_pct"] / 30.0, 2)
    elif "metric" not in obj and "agents" in obj:
        # agents-only invocation: the headline is the million-agent day
        # study's steady-state agent-step rate (floor-gated in CI at
        # 1e6 agent-steps/s — ~15x below the measured CPU rate).
        a = obj["agents"]["day_study"]
        obj["metric"] = "qsts_agents_day_study_agent_steps_per_sec"
        obj["value"] = a["agent_steps_per_sec"]
        obj["unit"] = "agent-steps/s"
        obj["vs_baseline"] = (
            round(a["agent_steps_per_sec"] / 1_000_000.0, 2)
            if a["agent_steps_per_sec"] else None
        )
    elif "metric" not in obj and "sparse" in obj:
        # sparse-only invocation: the headline is the sparse 2000-bus
        # solve rate (ISSUE 7 acceptance: >= 3x the dense path with
        # solutions inside the documented tolerance).
        sp = obj["sparse"]
        obj["metric"] = "nr_2000bus_sparse_solves_per_sec"
        obj["value"] = sp["nr_2000bus_sparse_solves_per_sec"]
        obj["unit"] = "solves/s"
        obj["vs_baseline"] = round(sp["nr_2000bus_sparse_speedup"] / 3.0, 2)
    elif "metric" not in obj and "cache" in obj:
        # cache-only invocation: the headline is the delta tier's
        # speedup over the full solve (ISSUE 10 acceptance: >= 3x at
        # the same accuracy — residual within the engine tolerance).
        c = obj["cache"]
        obj["metric"] = "serve_cache_delta_speedup"
        obj["value"] = c["serve_cache_delta_speedup"]
        obj["unit"] = "x vs full solve"
        obj["vs_baseline"] = (
            round(c["serve_cache_delta_speedup"] / 3.0, 2)
            if c["serve_cache_delta_speedup"] else None
        )
    elif "metric" not in obj and "topo" in obj:
        # topo-only invocation: the headline is the screen throughput
        # (ISSUE 15 acceptance: >= 10k DC-screened variants/s on one
        # host, floor-gated in CI).
        t = obj["topo"]
        obj["metric"] = "topo_variants_per_sec"
        obj["value"] = t["topo_variants_per_sec"]
        obj["unit"] = "variants/s"
        obj["vs_baseline"] = round(t["topo_variants_per_sec"] / 10000.0, 2)
    elif "metric" not in obj and "mfu" in obj:
        # mfu-only invocation: the headline is the krylov lane speedup
        # over the r05 baseline (ISSUE 14 acceptance: >= 5x, or the
        # >= 10% MFU alternative).
        m = obj["mfu"]
        obj["metric"] = "nr_2000bus_krylov_lane_speedup"
        obj["value"] = m["nr_2000bus_krylov_lane_speedup"]
        obj["unit"] = "x vs r05 f64 inner"
        obj["vs_baseline"] = round(
            m["nr_2000bus_krylov_lane_speedup"] / 5.0, 2
        )
    elif "metric" not in obj and "roofline" in obj:
        # roofline-only invocation (the CI smoke): the headline is the
        # direction-neutral program coverage count — the drift gate
        # itself already exited 1 on any model-column regression.
        r = obj["roofline"]
        obj["metric"] = "roofline_programs_total"
        obj["value"] = r["roofline_programs_total"]
        obj["unit"] = "programs"
        obj["vs_baseline"] = None
    elif "metric" not in obj and "snapshot" in obj:
        # snapshot-only invocation (the CI cost-envelope smoke): the
        # headline is the non-disruption ratio — serve p99 with a
        # concurrent snapshot loop over the quiet baseline (acceptance:
        # <= 1.2, floor-gated in CI).
        s = obj["snapshot"]
        obj["metric"] = "serve_p99_snapshot_latency_ratio"
        obj["value"] = s["serve_p99_snapshot_latency_ratio"]
        obj["unit"] = "x vs no-snapshot p99"
        obj["vs_baseline"] = (
            round(1.2 / s["serve_p99_snapshot_latency_ratio"], 2)
            if s["serve_p99_snapshot_latency_ratio"] else None
        )
    elif "metric" not in obj and "mesh" in obj:
        # mesh-only invocation: the headline is QSTS throughput speedup
        # at all devices (ISSUE 6 acceptance: >= 1.6x at D devices with
        # byte-identical results; 1-device hosts report the no-op row).
        m = obj["mesh"]
        obj["metric"] = "mesh_qsts_speedup_at_max_devices"
        obj["value"] = m.get("qsts_speedup_at_max_devices")
        obj["unit"] = f"x vs 1 device (D={m['devices_available']})"
        obj["vs_baseline"] = (
            round(m["qsts_speedup_at_max_devices"] / 1.6, 2)
            if "qsts_speedup_at_max_devices" in m else None
        )
    # Registry snapshot: the BENCH trajectory gains solver-iteration /
    # residual / serving columns without new bench code.
    obj["metrics"] = REGISTRY.snapshot()
    # IR program-set stamps (gridprobe inventory): both names carry no
    # perf-gate direction fragment, so they record without gating.
    obj["gridprobe"] = _gridprobe_snapshot()
    print(json.dumps(obj))


def _solver_sections(obj: dict) -> None:
    ms_per_iter = bench_ladder()
    nr10k_ms, nr10k_true = bench_nr_10k_mesh()
    lane_rate, mfu = bench_nr_2k_krylov_lanes()
    extra = {
        "nr_10000bus_mesh_solve_ms": round(nr10k_ms, 1),
        "nr_10000bus_mesh_true_mismatch_pu": float(f"{nr10k_true:.2e}"),
        "nr_2000bus_krylov_batch256_lane_solves_per_sec": round(lane_rate, 1),
        "nr_2000bus_krylov_mfu_pct": round(mfu, 2),
        "n1_2000bus_256way_krylov_screen_ms": round(
            bench_n1_2000bus_krylov(), 1
        ),
        "mc_64lane_10000bus_ladder_solves_per_sec": round(
            bench_ladder_mc_64(), 1
        ),
        "nr_2000bus_mesh_solves_per_sec": round(bench_nr_2000(), 2),
        "fdlf_2000bus_mesh_solves_per_sec": round(
            bench_nr_2000(maker=make_fdlf_solver, max_iter=30), 2
        ),
        "mc_1024lane_118bus_lane_solves_per_sec": round(bench_mc_1024(), 1),
        "mc_1024lane_118bus_fdlf_lane_solves_per_sec": round(
            bench_mc_1024(maker=make_fdlf_solver, max_iter=16), 1
        ),
        "n1_118way_contingency_batch_ms": round(bench_n1_118(), 2),
        "n1_118way_smw_screen_ms": round(bench_n1_118_smw(), 2),
        "n1_case30_real_smw_ms": round(bench_n1_case30_smw(), 2),
        "lb_256node_rounds_per_sec": round(bench_lb_256(), 1),
    }
    obj["metric"] = f"pf_ladder_{N_BUS}bus_ms_per_iteration"
    obj["value"] = round(ms_per_iter, 3)
    obj["unit"] = "ms/iteration"
    obj["vs_baseline"] = round(TARGET_MS_PER_ITER / ms_per_iter, 2)
    obj["extra"] = extra


if __name__ == "__main__":
    main()
