"""MQTT adapter tests (VERDICT r3 item 6).

An in-process MQTT 3.1.1 broker stub (CONNECT/SUBSCRIBE/PUBLISH routing
with wildcard matching) exercises the adapter's full join-channel
plug-and-play cycle: join → ACK → JSON self-description → device
registered → AOUT state flow → indexed command publish → leave →
device removed (reference ``CMqttAdapter.cpp``).
"""

import json
import socket
import struct
import threading
import time

import pytest

from freedm_tpu.devices.adapters.mqtt import (
    CONNACK,
    CONNECT,
    PINGREQ,
    PINGRESP,
    PUBLISH,
    SUBACK,
    SUBSCRIBE,
    MqttAdapter,
    MqttClient,
    encode_string,
    packet,
    topic_matches,
)
from freedm_tpu.devices.manager import DeviceManager


class BrokerStub:
    """Minimal MQTT 3.1.1 broker: QoS-0 routing with wildcard filters."""

    def __init__(self):
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._clients = []  # (sock, [filters], wlock)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.messages = []  # every PUBLISH seen, (topic, payload)
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            entry = (sock, [], threading.Lock())
            with self._lock:
                self._clients.append(entry)
            threading.Thread(
                target=self._serve, args=(entry,), daemon=True
            ).start()

    def _read_exactly(self, sock, n):
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _read_packet(self, sock):
        head = self._read_exactly(sock, 1)[0]
        length, shift = 0, 0
        while True:
            b = self._read_exactly(sock, 1)[0]
            length |= (b & 0x7F) << shift
            shift += 7
            if not (b & 0x80):
                break
        return head >> 4, self._read_exactly(sock, length) if length else b""

    def _serve(self, entry):
        sock, filters, wlock = entry
        try:
            while not self._stop.is_set():
                ptype, body = self._read_packet(sock)
                if ptype == CONNECT:
                    with wlock:
                        sock.sendall(packet(CONNACK, 0, b"\x00\x00"))
                elif ptype == SUBSCRIBE:
                    pid = body[:2]
                    i, granted = 2, b""
                    while i < len(body):
                        tlen = struct.unpack(">H", body[i : i + 2])[0]
                        filters.append(body[i + 2 : i + 2 + tlen].decode())
                        i += 2 + tlen + 1  # + requested qos byte
                        granted += b"\x00"
                    with wlock:
                        sock.sendall(packet(SUBACK, 0, pid + granted))
                elif ptype == PUBLISH:
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2 : 2 + tlen].decode()
                    payload = body[2 + tlen :]
                    self.messages.append((topic, payload.decode()))
                    self.route(topic, payload)
                elif ptype == PINGREQ:
                    with wlock:
                        sock.sendall(packet(PINGRESP, 0, b""))
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                if entry in self._clients:
                    self._clients.remove(entry)
            sock.close()

    def route(self, topic, payload: bytes):
        data = packet(PUBLISH, 0, encode_string(topic) + payload)
        with self._lock:
            targets = [
                (s, w)
                for s, filters, w in self._clients
                if any(topic_matches(f, topic) for f in filters)
            ]
        for sock, wlock in targets:
            try:
                with wlock:
                    sock.sendall(data)
            except OSError:
                pass

    def publish(self, topic, payload: str):
        self.messages.append((topic, payload))
        self.route(topic, payload.encode())

    def stop(self):
        self._stop.set()
        self._srv.close()


@pytest.fixture
def broker():
    b = BrokerStub()
    yield b
    b.stop()


def wait_for(cond, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def test_topic_matching():
    assert topic_matches("join/#", "join/dev1/1")
    assert topic_matches("dev1/1/AOUT/#", "dev1/1/AOUT/3")
    assert topic_matches("dev1/+/ACK", "dev1/1/ACK")
    assert not topic_matches("join/#", "leave/dev1")
    assert not topic_matches("dev1/1/AOUT", "dev1/1/AOUT/3")


def test_client_roundtrip(broker):
    got = []
    c = MqttClient("t1", "127.0.0.1", broker.port, lambda t, p: got.append((t, p)))
    c.subscribe(["a/#"])
    time.sleep(0.05)
    c.publish("a/b", "42")
    assert wait_for(lambda: ("a/b", b"42") in got)
    c.close()


SPEC = {"type": "Sst", "AOUT": {"1": "gateway"}, "AIN": {"1": "gateway"}}


@pytest.fixture
def adapter(broker):
    manager = DeviceManager()
    a = MqttAdapter(manager, client_id="DGIClient",
                    address=f"tcp://127.0.0.1:{broker.port}")
    a.start()
    assert a.error is None
    # The adapter's own join announcement follows its SUBSCRIBE on the
    # same socket, so once it shows up the stub has the filters live —
    # publishes from the test thread won't race the subscription.
    assert wait_for(lambda: ("join/DGIClient/1", "Connect") in broker.messages)
    yield a, manager, broker
    a.stop()


def test_join_json_state_command_leave_cycle(adapter):
    a, manager, broker = adapter
    # The adapter announced itself on the join channel at start.
    assert wait_for(lambda: ("join/DGIClient/1", "Connect") in broker.messages)
    # A device joins: the adapter must ACK it.
    broker.publish("join/sst7/1", "join")
    assert wait_for(lambda: ("sst7/1/ACK", "ACK") in broker.messages)
    # The device sends its JSON self-description -> registered + revealed.
    broker.publish("sst7/1/JSON", json.dumps(SPEC))
    assert wait_for(lambda: "sst7" in manager.device_names("Sst"))
    # State flows through the AOUT index topic.
    broker.publish("sst7/1/AOUT/1", "12.5")
    assert wait_for(lambda: manager.get_state("sst7", "gateway") == 12.5)
    # Commands publish on the indexed topic from the AIN reference.
    manager.set_command("sst7", "gateway", -3.0)
    assert wait_for(lambda: ("sst7/1/1", "-3.0") in broker.messages)
    # Leave removes the device from the manager.
    broker.publish("leave/sst7/1", "leave")
    assert wait_for(lambda: "sst7" not in manager.device_names())
    # A later rejoin works (no duplicate-device residue).
    broker.publish("join/sst7/1", "join")
    broker.publish("sst7/1/JSON", json.dumps(SPEC))
    assert wait_for(lambda: "sst7" in manager.device_names("Sst"))


def test_duplicate_join_reacks_without_duplicate_registration(adapter):
    """A re-join (lost ACK / reconnect without leave) gets a fresh ACK —
    dropping it would wedge the device's handshake — but must not
    double-register the device."""
    a, manager, broker = adapter
    broker.publish("join/dev2/1", "join")
    assert wait_for(lambda: ("dev2/1/ACK", "ACK") in broker.messages)
    broker.publish("dev2/1/JSON", json.dumps(SPEC))
    assert wait_for(lambda: "dev2" in manager.device_names("Sst"))
    n_acks = sum(1 for m in broker.messages if m == ("dev2/1/ACK", "ACK"))
    broker.publish("join/dev2/1", "join")
    assert wait_for(
        lambda: sum(1 for m in broker.messages if m == ("dev2/1/ACK", "ACK"))
        == n_acks + 1
    )
    broker.publish("dev2/1/JSON", json.dumps(SPEC))  # re-sent after re-ACK
    time.sleep(0.1)
    assert a.error is None
    assert manager.device_names("Sst").count("dev2") == 1


def test_bad_json_and_unknown_signal_are_not_fatal(adapter):
    a, manager, broker = adapter
    # Protocol order: a device publishes its JSON only after the ACK
    # (which follows the adapter's per-device SUBSCRIBE on the same
    # socket, so the stub's filters are live).
    broker.publish("join/dev3/1", "join")
    assert wait_for(lambda: ("dev3/1/ACK", "ACK") in broker.messages)
    broker.publish("dev3/1/JSON", "{not json")
    broker.publish("dev3/1/AOUT/9", "1.0")  # unknown index
    time.sleep(0.1)
    assert a.error is None
    assert "dev3" not in manager.device_names()
    # The adapter still works for a good device afterwards.
    broker.publish("join/dev4/1", "join")
    assert wait_for(lambda: ("dev4/1/ACK", "ACK") in broker.messages)
    broker.publish("dev4/1/JSON", json.dumps(SPEC))
    assert wait_for(lambda: "dev4" in manager.device_names("Sst"))


def test_unreachable_broker_sets_error_latch():
    manager = DeviceManager()
    a = MqttAdapter(manager, address="tcp://127.0.0.1:1")  # nothing listens
    a.start()
    assert a.error is not None
    assert not a.revealed


def test_factory_builds_mqtt_adapter_from_xml(broker):
    from freedm_tpu.devices.factory import AdapterFactory, parse_adapter_xml

    # Repeated <subscribe> elements (the reference's form) accumulate.
    xml = f"""<root>
      <adapter name="cloud" type="mqtt">
        <info><address>tcp://127.0.0.1:{broker.port}</address>
              <id>NodeA</id><subscribe>sst1</subscribe>
              <subscribe>sst2</subscribe></info>
      </adapter>
    </root>"""
    manager = DeviceManager()
    factory = AdapterFactory(manager)
    specs = parse_adapter_xml(xml)
    a = factory.create_adapter(specs[0])
    assert isinstance(a, MqttAdapter)
    assert a.client_id == "NodeA" and a.subscriptions == ("sst1", "sst2")
    factory.start()
    assert wait_for(lambda: ("join/NodeA/1", "Connect") in broker.messages)
    factory.stop()
