"""Mixed-precision solver contract: the dense/sparse/krylov/mixed
equivalence suite (ISSUE 14).

What these pin, per docs/solvers.md "Mixed precision":

- mixed-precision solutions agree with the f64 inner within the
  documented 2e-4 pu bound (measured far tighter — the ladder's f64
  endgame polishes), with IDENTICAL convergence flags;
- the per-lane f64 fallback path actually runs on a deliberately
  ill-conditioned case, is counted on the result's ``fallbacks``
  field, and never changes the convergence verdict;
- the s-step block GMRES matches the classic one-vector cycle on a
  plain linear system;
- ``kind="auto"`` preconditioner selection obeys the bus-count
  threshold (the 10k-bus bf16 inverse-pair blowup fix);
- donation never destroys a caller's buffer (the wrapper-copy
  contract) and repeated solves stay valid;
- the ``pf_precision_fallbacks_total`` metric receives the fallback
  count from already-materialized results.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid.cases import synthetic_mesh
from freedm_tpu.grid.matpower import load_builtin
from freedm_tpu.pf.krylov import (
    PRECOND_INVERSE_MAX_BUSES,
    _pgmres,
    _pgmres_block,
    _resolve_precond_kind,
    make_krylov_solver,
    resolve_precision,
)
from freedm_tpu.pf.newton import make_newton_solver
from freedm_tpu.pf.sparse import make_sparse_newton_solver


MESH300 = synthetic_mesh(300, seed=4, load_mw=2.0, chord_frac=1.0)


def _ill_conditioned_mesh():
    """A deliberately ill-conditioned case: one chord's reactance
    shrunk 1e7x, blowing the admittance dynamic range far past what
    the f32 inner (or the bf16 preconditioner) can resolve."""
    x = np.asarray(MESH300.x).copy()
    x[MESH300.n_bus + 5] *= 1e-7
    return dataclasses.replace(MESH300, x=x)


# ---------------------------------------------------------------------------
# vocabulary + preconditioner auto selection
# ---------------------------------------------------------------------------


def test_resolve_precision_vocabulary():
    assert resolve_precision("f64") == "f64"
    assert resolve_precision("mixed") == "mixed"
    assert resolve_precision("auto", backend="tpu") == "mixed"
    assert resolve_precision("auto", backend="gpu") == "mixed"
    assert resolve_precision("auto", backend="cpu") == "f64"
    with pytest.raises(ValueError, match="unknown pf precision"):
        resolve_precision("bf16")


def test_unknown_precision_is_typed_everywhere():
    with pytest.raises(ValueError, match="unknown pf precision"):
        make_krylov_solver(MESH300, precision="f16")
    with pytest.raises(ValueError, match="unknown pf precision"):
        make_newton_solver(synthetic_mesh(40), precision="f16")
    from freedm_tpu.scenarios.engine import QstsEngine, StudySpec

    with pytest.raises(ValueError, match="unknown pf_precision"):
        QstsEngine(StudySpec(case="case14", scenarios=2, steps=4,
                             pf_precision="f16"))


def test_default_precond_kind_guards_the_blowup():
    # An UNSPECIFIED build must obey the threshold too (the guard is
    # not opt-in): default construction paths at 10k buses take the LU
    # pair, never the ~400 MB bf16 inverse pair.
    from freedm_tpu.pf.krylov import default_precond_kind

    assert default_precond_kind(PRECOND_INVERSE_MAX_BUSES - 1) == "inverse"
    assert default_precond_kind(PRECOND_INVERSE_MAX_BUSES) == "lu"
    assert default_precond_kind(10_000) == "lu"


def test_precond_auto_kind_obeys_bus_threshold():
    # The 10k-bus blowup fix: on matmul-rich backends the bf16 inverse
    # pair is only built BELOW the threshold (2·2n² bytes — ~400 MB at
    # 10k buses above it); cpu always takes the LU build.
    n_small = PRECOND_INVERSE_MAX_BUSES - 1
    n_large = PRECOND_INVERSE_MAX_BUSES
    assert _resolve_precond_kind("auto", n_small, backend="tpu") == "inverse"
    assert _resolve_precond_kind("auto", n_large, backend="tpu") == "lu"
    assert _resolve_precond_kind("auto", n_small, backend="cpu") == "lu"
    assert _resolve_precond_kind("auto", n_large, backend="cpu") == "lu"
    # Explicit kinds are never overridden.
    assert _resolve_precond_kind("inverse", n_large, backend="tpu") == "inverse"
    assert _resolve_precond_kind("lu", n_small, backend="tpu") == "lu"
    with pytest.raises(ValueError, match="unknown preconditioner kind"):
        _resolve_precond_kind("qr", 10)


# ---------------------------------------------------------------------------
# s-step block GMRES core
# ---------------------------------------------------------------------------


def test_block_gmres_matches_classic_cycle():
    rng = np.random.default_rng(0)
    a = rng.normal(0, 1, (48, 48))
    a = a @ a.T + 48 * np.eye(48)
    aj = jnp.asarray(a)
    b = jnp.asarray(rng.normal(0, 1, 48))
    a_op = lambda u: aj @ u
    m_op = lambda u: u / jnp.diagonal(aj)
    x_classic = _pgmres(a_op, m_op, b, m=16)
    for s in (1, 2, 4, 8):
        x_blk = _pgmres_block(a_op, m_op, b, m=16, s=s)
        r_blk = float(jnp.linalg.norm(aj @ x_blk - b))
        r_classic = float(jnp.linalg.norm(aj @ x_classic - b))
        # Same Krylov space, same minimizer — the block cycle's
        # residual stays within an order of the classic one.
        assert r_blk <= max(10.0 * r_classic, 1e-8), (s, r_blk, r_classic)


def test_block_gmres_survives_breakdown():
    # b already in the preconditioned operator's 1-dim invariant space:
    # the chain dies immediately; the guarded path must return the
    # exact solve, not NaN.
    aj = jnp.eye(8) * 2.0
    b = jnp.zeros(8).at[0].set(1.0)
    x = _pgmres_block(lambda u: aj @ u, lambda u: u, b, m=8, s=4)
    assert bool(jnp.all(jnp.isfinite(x)))
    assert float(jnp.linalg.norm(aj @ x - b)) < 1e-10


# ---------------------------------------------------------------------------
# dense / sparse / krylov / mixed equivalence
# ---------------------------------------------------------------------------

#: The documented mixed-vs-f64 agreement bound (docs/solvers.md); the
#: f64 endgame of the ladder makes the measured agreement far tighter.
MIXED_DV_BOUND = 2e-4


def test_mixed_krylov_matches_dense_and_f64():
    solve_d, _ = make_newton_solver(MESH300, max_iter=12)
    solve_f, _ = make_krylov_solver(MESH300, max_iter=15, precision="f64")
    solve_m, _ = make_krylov_solver(MESH300, max_iter=15, precision="mixed")
    rd, rf, rm = solve_d(), solve_f(), solve_m()
    assert bool(rd.converged) and bool(rf.converged) and bool(rm.converged)
    assert bool(rm.converged) == bool(rf.converged)
    np.testing.assert_allclose(np.asarray(rm.v), np.asarray(rd.v),
                               atol=MIXED_DV_BOUND)
    np.testing.assert_allclose(np.asarray(rm.theta), np.asarray(rd.theta),
                               atol=MIXED_DV_BOUND)
    # Well-conditioned case: the oracle accepts every mixed step.
    assert int(rm.fallbacks) == 0
    assert int(rf.fallbacks) == 0


def test_mixed_sparse_matches_f64_on_real_case():
    sys_ = load_builtin("case_ieee30")
    sf, _ = make_sparse_newton_solver(sys_, precision="f64")
    sm, _ = make_sparse_newton_solver(sys_, precision="mixed")
    rf, rm = sf(), sm()
    assert bool(rf.converged) and bool(rm.converged)
    np.testing.assert_allclose(np.asarray(rm.v), np.asarray(rf.v),
                               atol=MIXED_DV_BOUND)
    assert int(rm.fallbacks) == 0


def test_mixed_fixed_iteration_variant_converges():
    _, fixed_m = make_krylov_solver(MESH300, max_iter=8, precision="mixed")
    r = fixed_m()
    assert bool(r.converged)
    assert r.fallbacks.dtype == jnp.int32


# ---------------------------------------------------------------------------
# the per-lane f64 fallback path
# ---------------------------------------------------------------------------


def test_fallback_runs_on_ill_conditioned_case_and_keeps_contract():
    sys_bad = _ill_conditioned_mesh()
    sm, _ = make_krylov_solver(sys_bad, max_iter=20, precision="mixed")
    sf, _ = make_krylov_solver(sys_bad, max_iter=20, precision="f64")
    rm, rf = sm(), sf()
    # The mixed inner stalls under this conditioning, so the lane MUST
    # have fallen through to full-precision iterations...
    assert int(rm.fallbacks) > 0
    # ...and the convergence CONTRACT is untouched: the verdict is the
    # f64 masked-mismatch test's, identical to the f64 inner's verdict,
    # never a reduced-precision self-evaluation.
    assert bool(rm.converged) == bool(rf.converged)
    assert float(rm.mismatch) <= 2.0 * max(float(rf.mismatch), 1e-12)


def test_fallback_is_per_lane_under_vmap():
    from freedm_tpu.pf.krylov import host_injections

    sys_bad = _ill_conditioned_mesh()
    solve_m, _ = make_krylov_solver(sys_bad, max_iter=20,
                                    precision="mixed")
    n = sys_bad.n_bus
    # Lane 0: the flat start IS the solution (scheduled injections set
    # to the realized flat-start injections -> zero mismatch), so it
    # converges before any inner solve runs; lane 1: the real
    # ill-conditioned operating point, which falls back.  The
    # conditioning is topological, so only a residual-free lane can
    # avoid the stall — which is exactly what makes the per-lane
    # masking visible.
    from freedm_tpu.grid.bus import PQ

    bt = np.asarray(sys_bad.bus_type)
    v_flat = np.where(bt == PQ, 1.0, np.asarray(sys_bad.v_set))
    p0, q0 = host_injections(sys_bad, np.zeros(n), v_flat)
    p = jnp.stack([jnp.asarray(p0), jnp.asarray(sys_bad.p_inj)])
    q = jnp.stack([jnp.asarray(q0), jnp.asarray(sys_bad.q_inj)])
    batched = jax.jit(jax.vmap(
        lambda pi, qi: solve_m(p_inj=pi, q_inj=qi)
    ))
    r = batched(p, q)
    fb = np.asarray(r.fallbacks)
    assert fb.shape == (2,)
    # The easy lane converged without ever paying a full-precision
    # retry; the hard lane did — the batched while_loop masks per lane.
    assert fb[0] == 0
    assert fb[1] > 0
    assert bool(np.asarray(r.converged)[0])


def test_fallbacks_feed_the_metrics_counter():
    from freedm_tpu.core import metrics as obs

    obs.reset_for_tests()
    sys_bad = _ill_conditioned_mesh()
    sm, _ = make_krylov_solver(sys_bad, max_iter=20, precision="mixed")
    r = sm()
    assert int(r.fallbacks) > 0
    from freedm_tpu.pf.krylov import record_result

    record_result(r)
    snap = obs.REGISTRY.snapshot()
    vals = snap["pf_precision_fallbacks_total"]["values"]
    assert vals.get(("krylov",), vals.get("krylov", 0)) >= 1


# ---------------------------------------------------------------------------
# donation safety
# ---------------------------------------------------------------------------


def test_donation_never_destroys_caller_buffers():
    solve, _ = make_krylov_solver(MESH300, max_iter=15)
    p = jnp.asarray(MESH300.p_inj) * 1.05
    r1 = solve(p_inj=p)
    # The impl donates its scheduled-injection args, but the wrapper
    # copies — the caller's array must survive and stay usable.
    r2 = solve(p_inj=p)
    assert bool(r1.converged) and bool(r2.converged)
    np.testing.assert_array_equal(np.asarray(r1.v), np.asarray(r2.v))
    # And the stored base schedule survives default-argument solves.
    r3, r4 = solve(), solve()
    np.testing.assert_array_equal(np.asarray(r3.v), np.asarray(r4.v))


def test_sparse_donation_repeat_solves():
    sys_ = load_builtin("case_ieee30")
    solve, _ = make_sparse_newton_solver(sys_)
    r1, r2 = solve(), solve()
    assert bool(r1.converged) and bool(r2.converged)
    np.testing.assert_array_equal(np.asarray(r1.v), np.asarray(r2.v))
