"""Consistent-cut snapshot tests (``freedm_tpu.core.snapshot``): the
Chandy–Lamport capture protocol over real UDP endpoints and the sans-IO
SR channel, the invariant auditor's typed findings, the torn-read
negative proof, the serve-side state seam, and the offline
``snapshot_report`` tool's exit-code contract.

Reference semantics: the DGI's StateCollection pillar
(``Broker/src/sc/StateCollection.cpp``) — marker-based snapshots whose
per-channel recorded messages + frozen counters form a consistent
global cut (docs/snapshots.md).
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from freedm_tpu.core import metrics as M
from freedm_tpu.core import snapshot as snap
from freedm_tpu.dcn import endpoint as ep_mod
from freedm_tpu.dcn.protocol import SrChannel
from freedm_tpu.runtime.messages import ModuleMessage


def msg(i):
    return ModuleMessage("lb", "draft_request", {"i": i}, source="A:1")


def _pair(provider_a=None, provider_b=None, timeout_s=5.0):
    """Two live UDP endpoints with snapshot coordinators attached."""
    ea = ep_mod.UdpEndpoint("A:1", resend_time_s=0.02).start()
    eb = ep_mod.UdpEndpoint("B:2", resend_time_s=0.02).start()
    ea.connect("B:2", eb.address)
    eb.connect("A:1", ea.address)
    ca = snap.SnapshotCoordinator(ea, state_provider=provider_a,
                                  timeout_s=timeout_s)
    cb = snap.SnapshotCoordinator(eb, state_provider=provider_b,
                                  timeout_s=timeout_s)
    return ea, eb, ca, cb


def _wait(cond, timeout_s=5.0, step=0.02):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(step)
    return cond()


# ---------------------------------------------------------------------------
# capture over live endpoints
# ---------------------------------------------------------------------------


def test_two_node_cut_completes_and_audits_clean():
    got = []
    ea, eb, ca, cb = _pair(
        provider_a=lambda: {"gm": {"coordinators_per_group": [1]}},
        provider_b=lambda: {"gm": {"coordinators_per_group": [1]}},
    )
    eb.sink = got.append
    try:
        for i in range(5):
            ea.send("B:2", msg(i))
        assert _wait(lambda: len(got) == 5)
        sid = ca.initiate()
        assert _wait(lambda: ca.result(sid) is not None
                     and cb.result(sid) is not None)
        doc_a, doc_b = ca.result(sid), cb.result(sid)
        assert doc_a["status"] == doc_b["status"] == "complete"
        # B's inbound channel from A froze at the 5 delivered messages,
        # agreeing with both the marker and A's captured send counter.
        cin = doc_b["channels_in"]["A:1"]
        assert cin["done"] and cin["accepted_at_marker"] == 5
        assert cin["marker"]["sent_at_marker"] == 5
        assert doc_a["channels_out"]["B:2"]["sent_at_capture"] == 5
        cut = snap.assemble_cut(sid, [doc_a, doc_b])
        assert cut["status"] == "complete"
        assert snap.audit_cut(cut) == []
    finally:
        ea.stop(); eb.stop()


def test_concurrent_initiation_raises_typed_in_progress():
    # The peer address points at a dead port: the marker is never ACKed,
    # the cut stays active, and a second initiation is the typed 409.
    ep = ep_mod.UdpEndpoint("A:1", resend_time_s=0.02).start()
    ep.connect("dead:9", ("127.0.0.1", 1))
    coord = snap.SnapshotCoordinator(ep, timeout_s=30.0)
    rejected0 = M.SNAPSHOT_CUTS.labels("rejected").value
    try:
        sid = coord.initiate()
        assert coord.status()["active"] == sid
        with pytest.raises(snap.SnapshotInProgress):
            coord.initiate()
        assert M.SNAPSHOT_CUTS.labels("rejected").value == rejected0 + 1
    finally:
        ep.stop()


def test_dead_peer_times_out_typed_incomplete_never_a_wedge():
    ep = ep_mod.UdpEndpoint("A:1", resend_time_s=0.02).start()
    ep.connect("dead:9", ("127.0.0.1", 1))
    coord = snap.SnapshotCoordinator(ep, timeout_s=0.2)
    try:
        sid = coord.initiate()
        # The endpoint pump ticks the coordinator: the deadline fires
        # without any explicit poke from the initiator.
        assert _wait(lambda: coord.result(sid) is not None, timeout_s=3.0)
        doc = coord.result(sid)
        assert doc["status"] == "incomplete"
        assert doc["pending"] == ["dead:9"]
        incompletes = [e for e in M.EVENTS.tail(100)
                       if e["event"] == "snapshot.incomplete"
                       and e["snapshot_id"] == sid]
        assert incompletes and incompletes[-1]["node"] == "A:1"
        # Not a wedge: the next initiation starts cleanly.
        sid2 = coord.initiate()
        assert sid2 != sid and coord.status()["active"] == sid2
    finally:
        ep.stop()


def test_mid_snapshot_peer_kill_finishes_incomplete():
    ea, eb, ca, _cb = _pair(timeout_s=0.5)
    try:
        eb.stop()  # the peer dies BEFORE the marker can round-trip
        sid = ca.initiate()
        assert _wait(lambda: ca.result(sid) is not None, timeout_s=3.0)
        doc = ca.result(sid)
        assert doc["status"] == "incomplete" and doc["pending"] == ["B:2"]
        # The incomplete node doc still poisons any fleet assembly.
        cut = snap.assemble_cut(sid, [doc])
        assert cut["status"] == "incomplete"
    finally:
        ea.stop()


# ---------------------------------------------------------------------------
# sans-IO: in-flight recording on the SR channel
# ---------------------------------------------------------------------------


def test_in_flight_message_captured_exactly_once():
    a = SrChannel("B:2", src_uuid="A:1", ttl_s=60.0)
    b = SrChannel("A:1", src_uuid="B:2", ttl_s=60.0)
    markers = []
    b.on_marker = lambda peer, payload: markers.append((peer, payload))
    # Settle one message so the pair is SYNced with nonzero counters.
    a.send(msg(0), 0.0)
    b.accept_frames(a.poll(0.0), 0.0)
    a.accept_frames(b.poll(0.0), 0.0)
    # Receiver captures local state FIRST (snap_begin), then a message
    # and the sender's marker are in flight concurrently: the message
    # predates the marker on the FIFO channel, so it is exactly the
    # in-flight state the cut must record.
    base = b.snap_begin()
    assert base["accepted_at_capture"] == 1
    a.send(msg(1), 0.1)
    a.send_marker({"snapshot_id": "s1", "origin": "A:1"}, 0.1)
    frames = a.poll(0.1)
    delivered = b.accept_frames(frames, 0.1)
    # Duplicate datagram: the dup-drop path must not double-record.
    b.accept_frames(frames, 0.1)
    assert [m.payload["i"] for m in delivered] == [1]
    st = b.snap_state()
    assert st["done"] and st["recorded_n"] == 1
    assert st["accepted_at_marker"] - st["accepted_at_capture"] == 1
    assert st["recorded"][0]["type"] == "draft_request"
    assert markers == [("A:1", {"snapshot_id": "s1", "origin": "A:1",
                                "sent_at_marker": 2})]
    # The assembled two-node view audits clean, including the sender's
    # independently captured counter cross-check.
    cut = snap.assemble_cut("s1", [
        {"snapshot_id": "s1", "node": "B:2", "status": "complete",
         "channels_in": {"A:1": st}, "channels_out": {}},
        {"snapshot_id": "s1", "node": "A:1", "status": "complete",
         "channels_in": {},
         "channels_out": {"B:2": {"sent_at_capture": a.sent}}},
    ])
    assert snap.audit_cut(cut) == []


def test_sender_restart_opens_new_channel_epoch_no_bogus_violation():
    # A killed-and-restarted sender (soak/chaos rejoin) re-SYNs with a
    # fresh sync stamp and a sent counter restarted from zero.  The
    # receiver must open a new conservation epoch — a lifetime accept
    # count would exceed the new incarnation's sent_at_marker and read
    # as a bogus channel_conservation violation in the next cut.
    a = SrChannel("B:2", src_uuid="A:1", ttl_s=60.0)
    b = SrChannel("A:1", src_uuid="B:2", ttl_s=60.0)
    b.on_marker = lambda peer, payload: None
    for i in range(5):
        a.send(msg(i), 0.0)
    b.accept_frames(a.poll(0.0), 0.0)
    a.accept_frames(b.poll(0.0), 0.0)
    assert b.accepted == 5
    # The sender process restarts: a brand-new channel, same uuid.
    a2 = SrChannel("B:2", src_uuid="A:1", ttl_s=60.0)
    a2.send(msg(0), 1.0)  # SYN-first with a NEW sync stamp
    delivered = b.accept_frames(a2.poll(1.0), 1.0)
    a2.accept_frames(b.poll(1.0), 1.0)
    assert [m.payload["i"] for m in delivered] == [0]
    assert b.accepted == 1  # epoch reset: counts the new incarnation only
    # A cut taken AFTER the restart audits clean.
    b.snap_begin()
    a2.send_marker({"snapshot_id": "s9", "origin": "A:1"}, 1.1)
    b.accept_frames(a2.poll(1.1), 1.1)
    st = b.snap_state()
    assert st["done"] and not st["resynced"]
    assert st["accepted_at_marker"] == 1
    assert st["marker"]["sent_at_marker"] == 1
    cut = snap.assemble_cut("s9", [
        {"snapshot_id": "s9", "node": "B:2", "status": "complete",
         "channels_in": {"A:1": st}, "channels_out": {}},
        {"snapshot_id": "s9", "node": "A:1", "status": "complete",
         "channels_in": {},
         "channels_out": {"B:2": {"sent_at_capture": a2.sent}}},
    ])
    assert snap.audit_cut(cut) == []


def test_resync_mid_recording_marks_channel_and_auditor_skips():
    # A restart WHILE a cut is recording straddles two channel epochs:
    # the channel is marked resynced and the auditor skips its
    # per-channel equations instead of reporting epoch-torn garbage.
    a = SrChannel("B:2", src_uuid="A:1", ttl_s=60.0)
    b = SrChannel("A:1", src_uuid="B:2", ttl_s=60.0)
    b.on_marker = lambda peer, payload: None
    for i in range(3):
        a.send(msg(i), 0.0)
    b.accept_frames(a.poll(0.0), 0.0)
    a.accept_frames(b.poll(0.0), 0.0)
    b.snap_begin()
    a2 = SrChannel("B:2", src_uuid="A:1", ttl_s=60.0)
    a2.send(msg(0), 1.0)
    b.accept_frames(a2.poll(1.0), 1.0)
    assert b.snap_state()["resynced"]
    # The new incarnation knows nothing of the old cut; if a marker of
    # ITS OWN ever lands here the frozen doc must still be skipped.
    a2.send_marker({"snapshot_id": "other", "origin": "A:1"}, 1.1)
    a2.accept_frames(b.poll(1.0), 1.1)
    b.accept_frames(a2.poll(1.1), 1.1)
    st = b.snap_state()
    assert st["done"] and st["resynced"]
    doc = {"snapshot_id": "s", "node": "B:2", "status": "complete",
           "channels_in": {"A:1": st}, "channels_out": {}}
    cut = snap.assemble_cut("s", [doc])
    assert snap.audit_cut(cut) == []


def test_marker_before_capture_joins_with_empty_recording():
    # Chandy–Lamport join path: a node that first LEARNS of the cut
    # from an inbound marker records the delivering channel empty.
    a = SrChannel("B:2", src_uuid="A:1", ttl_s=60.0)
    b = SrChannel("A:1", src_uuid="B:2", ttl_s=60.0)
    b.on_marker = lambda peer, payload: None
    a.send(msg(0), 0.0)
    b.accept_frames(a.poll(0.0), 0.0)
    a.accept_frames(b.poll(0.0), 0.0)
    a.send_marker({"snapshot_id": "s2", "origin": "A:1"}, 0.1)
    b.accept_frames(a.poll(0.1), 0.1)  # marker with NO prior snap_begin
    st = b.snap_state()
    assert st["done"] and st["recorded_n"] == 0
    assert st["accepted_at_capture"] == st["accepted_at_marker"] == 1


# ---------------------------------------------------------------------------
# auditor: typed findings per invariant
# ---------------------------------------------------------------------------


def _node(name, **extra):
    doc = {"snapshot_id": "s", "node": name, "status": "complete",
           "local": {}, "channels_in": {}, "channels_out": {}}
    doc.update(extra)
    return doc


def test_audit_channel_conservation_and_recording():
    # More accepts than the marker says were ever sent = duplicate
    # delivery; a recording that disagrees with the counter delta means
    # an in-flight message was missed or double-recorded.
    cut = snap.assemble_cut("s", [
        _node("B", channels_in={"A": {
            "done": True, "marker": {"sent_at_marker": 3},
            "accepted_at_marker": 5, "accepted_at_capture": 2,
            "recorded_n": 1,
        }}),
        _node("A"),
    ])
    checks = sorted(v.check for v in snap.audit_cut(cut))
    assert checks == ["channel_conservation", "channel_recording"]
    # Losses are LEGAL on an SR channel (TTL expiry): a deficit is not
    # a conservation violation.
    cut = snap.assemble_cut("s", [
        _node("B", channels_in={"A": {
            "done": True, "marker": {"sent_at_marker": 9},
            "accepted_at_marker": 5, "accepted_at_capture": 2,
            "recorded_n": 3,
        }}),
    ])
    assert snap.audit_cut(cut) == []


def test_audit_counter_mismatch_against_sender_capture():
    cut = snap.assemble_cut("s", [
        _node("B", channels_in={"A": {
            "done": True, "marker": {"sent_at_marker": 4},
            "accepted_at_marker": 4, "accepted_at_capture": 4,
            "recorded_n": 0,
        }}),
        _node("A", channels_out={"B": {"sent_at_capture": 7}}),
    ])
    vs = snap.audit_cut(cut)
    assert [v.check for v in vs] == ["channel_counter_mismatch"]
    assert "sent_at_capture=7" in vs[0].detail


def test_audit_single_leader_in_process_and_federated():
    cut = snap.assemble_cut("s", [
        _node("A", local={
            "gm": {"coordinators_per_group": [1, 2]},
            "fed": {"is_coordinator": True, "members": ["A", "B"]},
        }),
        _node("B", local={
            "fed": {"is_coordinator": True, "members": ["A", "B"]},
        }),
    ])
    vs = snap.audit_cut(cut)
    assert sorted(v.check for v in vs) == ["single_leader", "single_leader"]
    details = " ".join(v.detail for v in vs)
    assert "group 1 has 2 coordinators" in details
    assert "2 nodes claim federation leadership" in details
    # One leader per member set is the legal shape.
    cut = snap.assemble_cut("s", [
        _node("A", local={"fed": {"is_coordinator": True,
                                  "members": ["A", "B"]}}),
        _node("B", local={"fed": {"is_coordinator": False,
                                  "members": ["A", "B"]}}),
    ])
    assert snap.audit_cut(cut) == []


def test_audit_ticket_job_and_cache_accounting():
    ok_ledger = {"offered": 10, "admitted": 8, "shed": 1, "rejected": 1,
                 "ok": 6, "error": 1, "inflight": 1}
    cut = snap.assemble_cut("s", [_node(
        "R",
        serve={"ledger": ok_ledger},
        jobs={"total": 3, "by_state": {"running": 1, "completed": 2}},
        cache={"bytes": 100, "accounted_bytes": 100},
    )])
    assert snap.audit_cut(cut) == []
    cut = snap.assemble_cut("s", [_node(
        "R",
        serve={"ledger": dict(ok_ledger, offered=11, ok=9)},
        jobs={"total": 4, "by_state": {"running": 1, "completed": 2}},
        cache={"bytes": 100, "accounted_bytes": 64},
    )])
    checks = sorted(v.check for v in snap.audit_cut(cut))
    assert checks == ["cache_bytes", "job_accounting",
                      "ticket_accounting", "ticket_accounting"]
    # A malformed ledger is itself a typed violation, not a skip.
    cut = snap.assemble_cut("s", [_node("R", serve={"ledger": {"x": 1}})])
    vs = snap.audit_cut(cut)
    assert [v.check for v in vs] == ["ticket_accounting"]
    assert "malformed" in vs[0].detail


def test_torn_scrape_flags_bogus_violation():
    # Each instant's ledger audits clean on its own; the torn glue of
    # the two MUST fail — the negative proof that the marker
    # coordination is load-bearing.
    early = {"offered": 10, "admitted": 8, "shed": 1, "rejected": 1,
             "ok": 8, "error": 0, "inflight": 0}
    late = {"offered": 14, "admitted": 12, "shed": 1, "rejected": 1,
            "ok": 12, "error": 0, "inflight": 0}
    for ledger in (early, late):
        clean = snap.assemble_cut("s", [_node("R", serve={"ledger": ledger})])
        assert snap.audit_cut(clean) == []
    torn = snap.torn_serve_doc({"ledger": early}, {"ledger": late})
    assert torn["torn"] is True
    cut = snap.assemble_cut("torn", [_node("R", snapshot_id="torn",
                                           serve=torn)])
    vs = snap.audit_cut(cut)
    assert vs and all(v.check == "ticket_accounting" for v in vs)


def test_assemble_cut_drops_foreign_sid_and_propagates_incomplete():
    cut = snap.assemble_cut("s", [
        _node("A"),
        dict(_node("B"), snapshot_id="s0"),  # stale cut: dropped
        dict(_node("C"), status="incomplete"),
    ])
    assert sorted(cut["nodes"]) == ["A", "C"]
    assert cut["status"] == "incomplete"


def test_bound_doc_trims_recordings_then_stubs():
    doc = _node("A", channels_in={"B": {
        "done": True, "recorded_n": 200,
        "recorded": [{"seq": i, "hash": "h" * 40} for i in range(200)],
    }})
    trimmed = snap.bound_doc(dict(doc), 2000)
    assert trimmed["trimmed"] is True
    assert trimmed["channels_in"]["B"]["recorded"] == "trimmed:200"
    assert trimmed["channels_in"]["B"]["recorded_n"] == 200
    stub = snap.bound_doc(dict(doc), 64)
    assert stub["status"] == "oversize" and stub["node"] == "A"
    # Small docs pass through untouched (same object, no copies).
    small = _node("A")
    assert snap.bound_doc(small, 4_000_000) is small


def test_record_violations_bumps_counter_and_journals():
    base = {}
    for v in M.SNAPSHOT_VIOLATIONS.children():
        base[v[0]] = v[1].value
    snap.record_violations("sX", [
        snap.Violation("ticket_accounting", "R", "broken"),
    ])
    assert (M.SNAPSHOT_VIOLATIONS.labels("ticket_accounting").value
            == base.get(("ticket_accounting",), 0) + 1)
    recs = [e for e in M.EVENTS.tail(50)
            if e["event"] == "snapshot.violation"
            and e["snapshot_id"] == "sX"]
    assert recs and recs[-1]["check"] == "ticket_accounting"


# ---------------------------------------------------------------------------
# serve-side state seam
# ---------------------------------------------------------------------------


def test_service_snapshot_state_ledger_balances():
    from freedm_tpu.serve import ServeConfig, Service
    from freedm_tpu.serve.service import PowerFlowRequest

    svc = Service(ServeConfig(max_batch=4, max_wait_ms=1.0,
                              queue_depth=32, buckets=(1, 4)))
    try:
        req = PowerFlowRequest(case="case14", scale=1.0)
        for _ in range(3):
            svc.submit("pf", req).result(timeout=120)
        st = svc.snapshot_state()
        ledger = st["ledger"]
        assert ledger["offered"] >= 3
        assert (ledger["offered"]
                == ledger["admitted"] + ledger["shed"] + ledger["rejected"])
        assert (ledger["admitted"]
                == ledger["ok"] + ledger["error"] + ledger["inflight"])
        # The seam IS the audit input: a one-node cut over it is clean.
        cut = snap.assemble_cut("svc", [_node("R", serve=st)])
        assert snap.audit_cut(cut) == []
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# metrics server routes
# ---------------------------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as r:
        return json.loads(r.read().decode())


def _post(port, path):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=b"", method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, json.loads(r.read().decode())


def test_metrics_server_snapshot_routes():
    srv = M.MetricsServer(port=0).start()
    ep = None
    try:
        # No coordinator installed: GET is typed-disabled, POST is 503.
        assert _get(srv.port, "/snapshot") == {"enabled": False}
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(srv.port, "/snapshot")
        assert err.value.code == 503
        # Installed, peerless: initiation completes instantly.
        ep = ep_mod.UdpEndpoint("A:1", resend_time_s=0.02).start()
        coord = snap.SnapshotCoordinator(ep, timeout_s=2.0)
        snap.install(coord)
        status, body = _post(srv.port, "/snapshot")
        assert status == 200
        sid = body["snapshot_id"]
        doc = _get(srv.port, f"/snapshot?id={sid}")
        assert doc["snapshot_id"] == sid and doc["status"] == "complete"
        assert _get(srv.port, "/snapshot")["node"] == "A:1"
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(srv.port, "/snapshot?id=nope")
        assert err.value.code == 404
    finally:
        snap.install(None)
        if ep is not None:
            ep.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# snapshot_report exit-code contract
# ---------------------------------------------------------------------------


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def test_snapshot_report_clean_cut_exits_0(tmp_path, capsys):
    from freedm_tpu.tools import snapshot_report

    cut = snap.assemble_cut("s", [_node("R", serve={"ledger": {
        "offered": 2, "admitted": 2, "shed": 0, "rejected": 0,
        "ok": 2, "error": 0, "inflight": 0}})])
    rc = snapshot_report.main(["--cut", _write(tmp_path, "cut.json", cut)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["pass"] and rep["nodes"] == ["R"]
    # A bare node doc (no nodes map) is wrapped into a one-node cut.
    rc = snapshot_report.main(
        ["--cut", _write(tmp_path, "node.json", _node("R"))])
    assert rc == 0


def test_snapshot_report_violations_exit_1(tmp_path, capsys):
    from freedm_tpu.tools import snapshot_report

    early = _write(tmp_path, "early.json", {"node": "R", "ledger": {
        "offered": 5, "admitted": 5, "shed": 0, "rejected": 0,
        "ok": 5, "error": 0, "inflight": 0}})
    late = _write(tmp_path, "late.json", {"node": "R", "ledger": {
        "offered": 9, "admitted": 9, "shed": 0, "rejected": 0,
        "ok": 9, "error": 0, "inflight": 0}})
    rc = snapshot_report.main(["--torn", early, late])
    assert rc == 1
    rep = json.loads(capsys.readouterr().out)
    assert not rep["pass"]
    assert rep["violations"][0]["check"] == "ticket_accounting"


def test_snapshot_report_internal_errors_exit_2(tmp_path, capsys):
    from freedm_tpu.tools import snapshot_report

    assert snapshot_report.main(
        ["--cut", str(tmp_path / "missing.json")]) == 2
    # A journal with no snapshot.node records has nothing to audit.
    jp = tmp_path / "events.jsonl"
    jp.write_text(json.dumps({"event": "broker.round", "seq": 1}) + "\n")
    assert snapshot_report.main(["--events", str(jp)]) == 2
    capsys.readouterr()


def test_snapshot_report_assembles_cut_from_journals(tmp_path, capsys):
    from freedm_tpu.tools import snapshot_report

    lines_a = [
        {"event": "snapshot.node", "snapshot_id": "old",
         "doc": dict(_node("A"), snapshot_id="old")},
        {"event": "snapshot.node", "snapshot_id": "new",
         "doc": dict(_node("A"), snapshot_id="new")},
    ]
    lines_b = [
        {"event": "snapshot.node", "snapshot_id": "new",
         "doc": dict(_node("B"), snapshot_id="new")},
    ]
    ja = tmp_path / "a.jsonl"
    ja.write_text("\n".join(json.dumps(r) for r in lines_a) + "\n")
    jb = tmp_path / "b.jsonl"
    jb.write_text("\n".join(json.dumps(r) for r in lines_b) + "\n"
                  + "{torn-tail")  # a live journal's partial last line
    rc = snapshot_report.main(["--events", str(ja), str(jb)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    # Without --snapshot-id the NEWEST journaled cut is audited, joined
    # across both journals.
    assert rep["snapshot_id"] == "new"
    assert rep["nodes"] == ["A", "B"]
    rc = snapshot_report.main(["--events", str(ja), "--snapshot-id", "old"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["nodes"] == ["A"]
