"""Perf-regression gate tests (``freedm_tpu.tools.perf_gate``).

Covers: snapshot flattening (registry snapshot excluded, bools
excluded), direction inference, the min-samples baseline-building rule,
identical runs passing, an injected 50% regression failing (in both
polarities), improvements not failing, the rolling-median baseline's
outlier tolerance, per-metric threshold overrides, and history
append-on-pass/freeze-on-fail via the CLI.
"""

import json

from freedm_tpu.tools import perf_gate as pg


def _hist(*metric_dicts):
    return [{"label": "", "metrics": m} for m in metric_dicts]


# ---------------------------------------------------------------------------
# flatten + direction
# ---------------------------------------------------------------------------


def test_flatten_skips_registry_and_non_scalars():
    flat = pg.flatten({
        "metric": "pf_ladder_ms",
        "value": 0.3,
        "extra": {"nr_2000bus_mesh_solves_per_sec": 12.5,
                  "ok": True},
        "serve": {"case14": {"mixed": {"microbatch_speedup": 8.9}}},
        "metrics": {"huge_registry": {"values": {"": 1e9}}},
        "qsts": {"kill_resume": {"summary_exact_match": True}},
    })
    assert flat == {
        "value": 0.3,
        "extra.nr_2000bus_mesh_solves_per_sec": 12.5,
        "serve.case14.mixed.microbatch_speedup": 8.9,
    }


def test_direction_rules():
    assert pg.direction("extra.n1_case30_real_smw_ms") == -1
    assert pg.direction("serve.overload.at_1x.p99_ms") == -1
    assert pg.direction("extra.lb_256node_rounds_per_sec") == 1
    assert pg.direction("serve.case14.pf.microbatch_speedup") == 1
    assert pg.direction("qsts.warm_start.iters_reduction_pct") == 1
    # ms_per_iteration carries both fragments: higher-better rules win
    # deterministically... it does not contain one, check polarity:
    assert pg.direction("value") == 0  # unknown: informational


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------


def test_min_samples_rule_builds_baseline_before_gating():
    flat = {"a_ms": 10.0}
    verdicts, passed = pg.gate(flat, _hist({"a_ms": 1.0}), min_samples=3)
    assert passed
    assert verdicts[0]["status"] == "baseline"
    # With enough history the same 10x blowup gates.
    verdicts, passed = pg.gate(
        flat, _hist({"a_ms": 1.0}, {"a_ms": 1.1}, {"a_ms": 0.9}),
        min_samples=3,
    )
    assert not passed
    assert verdicts[0]["status"] == "REGRESSED"


def test_identical_runs_pass_and_injected_regression_fails():
    cur = {"a_ms": 10.0, "b_per_sec": 100.0}
    hist = _hist(cur, cur, cur)
    _, passed = pg.gate(cur, hist)
    assert passed
    # 50% slower on a lower-is-better metric: rejected at the default
    # 25% threshold.
    v, passed = pg.gate({"a_ms": 15.0, "b_per_sec": 100.0}, hist)
    assert not passed
    assert [r["status"] for r in v] == ["REGRESSED", "ok"]
    # 50% lower throughput on a higher-is-better metric: also rejected.
    v, passed = pg.gate({"a_ms": 10.0, "b_per_sec": 50.0}, hist)
    assert not passed
    assert [r["status"] for r in v] == ["ok", "REGRESSED"]


def test_improvement_does_not_fail():
    hist = _hist({"a_ms": 10.0}, {"a_ms": 10.0}, {"a_ms": 10.0})
    v, passed = pg.gate({"a_ms": 4.0}, hist)
    assert passed
    assert v[0]["status"] == "improved"


def test_rolling_median_shrugs_off_one_outlier_run():
    # One slow CI minute in the history must not drag the baseline: the
    # median of (10, 10, 30, 10, 10) is 10, so a current 11 is ok.
    hist = _hist(*[{"a_ms": x} for x in (10.0, 10.0, 30.0, 10.0, 10.0)])
    v, passed = pg.gate({"a_ms": 11.0}, hist)
    assert passed and v[0]["status"] == "ok"
    assert v[0]["baseline"] == 10.0


def test_per_metric_threshold_override():
    hist = _hist({"a_ms": 10.0}, {"a_ms": 10.0}, {"a_ms": 10.0})
    _, passed = pg.gate({"a_ms": 14.0}, hist)  # +40% > default 25%
    assert not passed
    _, passed = pg.gate({"a_ms": 14.0}, hist, per_metric={"a_ms": 0.5})
    assert passed


def test_unknown_direction_metrics_never_gate():
    hist = _hist({"mystery": 1.0}, {"mystery": 1.0}, {"mystery": 1.0})
    v, passed = pg.gate({"mystery": 100.0}, hist)
    assert passed
    assert v[0]["status"] == "info"


# ---------------------------------------------------------------------------
# CLI + history lifecycle
# ---------------------------------------------------------------------------


def test_cli_history_appends_on_pass_and_freezes_on_fail(tmp_path, capsys):
    snap = {"extra": {"a_ms": 10.0, "b_per_sec": 100.0}}
    s1 = tmp_path / "s1.json"
    s1.write_text(json.dumps(snap))
    hist = str(tmp_path / "hist.jsonl")

    # Run 1: empty history -> baseline-building pass, appended.
    assert pg.main([str(s1), "--history", hist, "--min-samples", "1"]) == 0
    assert len(pg.load_history(hist)) == 1
    # Run 2: identical -> ok, appended.
    assert pg.main([str(s1), "--history", hist, "--min-samples", "1"]) == 0
    assert len(pg.load_history(hist)) == 2
    # Run 3: injected 50% regression -> exit 1, NOT appended (a
    # regressed run must not become the next run's baseline).
    bad = {"extra": {"a_ms": 15.0, "b_per_sec": 100.0}}
    s3 = tmp_path / "s3.json"
    s3.write_text(json.dumps(bad))
    assert pg.main([str(s3), "--history", hist, "--min-samples", "1"]) == 1
    assert len(pg.load_history(hist)) == 2
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["perf_gate_pass"] is False
    assert summary["regressed"] == ["extra.a_ms"]


def test_cli_unreadable_snapshot_is_usage_error(tmp_path):
    missing = str(tmp_path / "nope.json")
    assert pg.main([missing, "--history",
                    str(tmp_path / "h.jsonl")]) == 2


def test_cli_internal_errors_exit_2_never_1(tmp_path):
    # The exit-code contract CI leans on: rc=1 means REGRESSED and
    # nothing else — a gate-side crash (here: an unparseable threshold
    # value) must land on 2.
    snap = tmp_path / "s.json"
    snap.write_text(json.dumps({"extra": {"a_ms": 1.0}}))
    assert pg.main([str(snap), "--history", str(tmp_path / "h.jsonl"),
                    "--set-threshold", "a_ms=abc"]) == 2


def test_cli_seed_builds_history_from_bench_trajectory(tmp_path):
    # The repo's BENCH_r*.json files can seed the baseline.
    for i, ms in enumerate((10.0, 11.0, 9.5)):
        (tmp_path / f"r{i}.json").write_text(
            json.dumps({"extra": {"a_ms": ms}})
        )
    snap = tmp_path / "cur.json"
    snap.write_text(json.dumps({"extra": {"a_ms": 10.5}}))
    hist = str(tmp_path / "hist.jsonl")
    seed_args = [
        "--seed", str(tmp_path / "r0.json"),
        "--seed", str(tmp_path / "r1.json"),
        "--seed", str(tmp_path / "r2.json"),
    ]
    rc = pg.main([str(snap), "--history", hist] + seed_args)
    assert rc == 0
    # 3 seeds + the passing current run.
    assert len(pg.load_history(hist)) == 4
    # Seeding is idempotent: re-passing the same --seed flags appends
    # nothing new (only the run itself lands), so a cron job cannot pin
    # the rolling baseline to stale seed values.
    rc = pg.main([str(snap), "--history", hist] + seed_args)
    assert rc == 0
    assert len(pg.load_history(hist)) == 5
    labels = [h["label"] for h in pg.load_history(hist)]
    assert labels.count(f"seed:{tmp_path / 'r0.json'}") == 1
    # --no-update freezes the history completely, seeds included.
    rc = pg.main([str(snap), "--history", hist, "--no-update",
                  "--seed", str(tmp_path / "cur.json")])
    assert rc == 0
    assert len(pg.load_history(hist)) == 5


# ---------------------------------------------------------------------------
# absolute floors (--floor): the recovered-regression guard
# ---------------------------------------------------------------------------


def test_floor_trips_below_bar_even_while_baseline_builds():
    flat = {"extra.lb_256node_rounds_per_sec": 5823.0}
    verdicts, passed = pg.gate(
        flat, [], min_samples=3,
        floors={"lb_256node_rounds_per_sec": 7000.0},
    )
    assert not passed
    (row,) = verdicts
    assert row["status"] == "REGRESSED" and row["floor"] == 7000.0


def test_floor_passes_above_bar_and_matches_dot_suffix():
    flat = {"extra.lb_256node_rounds_per_sec": 8100.0}
    verdicts, passed = pg.gate(
        flat, [], min_samples=3,
        floors={"lb_256node_rounds_per_sec": 7000.0},
    )
    assert passed
    (row,) = verdicts
    # Floored metrics gate immediately: "baseline" upgrades to "ok".
    assert row["status"] == "ok" and row["floor"] == 7000.0


def test_floor_on_lower_is_better_trips_above_bar():
    verdicts, passed = pg.gate(
        {"extra.n1_case30_real_smw_ms": 30.0}, [], min_samples=3,
        floors={"n1_case30_real_smw_ms": 20.0},
    )
    assert not passed and verdicts[0]["status"] == "REGRESSED"


def test_floor_matching_no_metric_is_a_broken_guard_not_a_pass():
    # A renamed/dropped metric (or a --floor typo) must fail loudly:
    # silence would un-guard the regression the floor was added against.
    flat = {"extra.other_per_sec": 100.0}
    verdicts, passed = pg.gate(
        flat, [], min_samples=3,
        floors={"lb_256node_rounds_per_sec": 7000.0},
    )
    assert not passed
    broken = [v for v in verdicts if v["metric"] ==
              "lb_256node_rounds_per_sec"]
    assert broken and broken[0]["status"] == "REGRESSED"
    assert broken[0]["note"] == "floor metric absent from snapshot"
    # ...and the table renders it without crashing on the NaN current.
    assert "lb_256node_rounds_per_sec" in pg.render_table(verdicts)
