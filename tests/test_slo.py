"""SLO monitor tests (``freedm_tpu.core.slo``).

Synthetic metric streams drive ``SloMonitor.tick`` with a fake clock:
fast+slow burn-window crossing semantics (fast alone must not breach),
breach → recover event pairing on the journal, the p99 objective over
windowed histogram deltas, watchdog stall detection on a registered
progress source, and the ``/slo`` route.
"""

import json
import urllib.request

import pytest

from freedm_tpu.core import metrics as M
from freedm_tpu.core import slo


@pytest.fixture(autouse=True)
def clean_registry():
    """The monitor reads the process-wide registry: start each test
    from zeroed values (registrations survive)."""
    M.reset_for_tests()
    yield
    M.reset_for_tests()


def make_monitor(**over):
    cfg = dict(fast_window_s=10.0, slow_window_s=40.0, interval_s=1.0,
               burn_trip=2.0, serve_availability=0.9, serve_p99_ms=100.0,
               broker_overrun_rate=0.1, watchdog_s=5.0)
    cfg.update(over)
    journal = M.JsonlEventJournal()
    mon = slo.SloMonitor(slo.SloConfig(**cfg), journal=journal)
    return mon, journal


def slo_events(journal):
    return [(e["event"], e.get("slo")) for e in journal.tail(200)
            if e["event"].startswith("slo.")]


# ---------------------------------------------------------------------------
# burn windows
# ---------------------------------------------------------------------------


def test_availability_breach_and_recover_pairing():
    mon, journal = make_monitor()
    ok = M.SERVE_REQUESTS.labels("pf", "ok")
    # "internal" is what a failed batch dispatch actually emits
    # (ServeError.code via _complete_error) — the SLO must count it.
    bad = M.SERVE_REQUESTS.labels("pf", "internal")
    t = 0.0
    # Healthy traffic long enough to fill both windows.
    for _ in range(50):
        ok.inc(10)
        mon.tick(now=t)
        t += 1.0
    assert slo_events(journal) == []
    # Sustained server faults: burns the 10% budget hard in BOTH
    # windows -> exactly one breach event.
    for _ in range(45):
        ok.inc(5)
        bad.inc(5)
        mon.tick(now=t)
        t += 1.0
    assert slo_events(journal) == [("slo.breach", "serve_availability")]
    assert mon.status()["breached"] == ["serve_availability"]
    # Faults stop: the fast window comes clean -> one recovery, paired.
    for _ in range(20):
        ok.inc(10)
        mon.tick(now=t)
        t += 1.0
    assert slo_events(journal) == [
        ("slo.breach", "serve_availability"),
        ("slo.recovered", "serve_availability"),
    ]
    assert mon.status()["breached"] == []
    assert M.REGISTRY.get("slo_breaches_total").labels(
        "serve_availability"
    ).value == 1


def test_fast_window_spike_without_slow_burn_does_not_breach():
    # The whole point of the two-window discipline: a short fast-window
    # spike on top of a long healthy history must NOT page, because the
    # slow window is not burning.
    mon, journal = make_monitor(fast_window_s=5.0, slow_window_s=200.0)
    ok = M.SERVE_REQUESTS.labels("pf", "ok")
    bad = M.SERVE_REQUESTS.labels("pf", "deadline_exceeded")
    t = 0.0
    for _ in range(200):  # 200 s of clean history
        ok.inc(50)
        mon.tick(now=t)
        t += 1.0
    for _ in range(6):  # a 6 s full-outage blip
        bad.inc(50)
        mon.tick(now=t)
        t += 1.0
    v = mon.tick(now=t)["serve_availability"]
    assert v["burn_fast"] >= 2.0  # the fast window IS on fire...
    assert v["burn_slow"] < 1.0  # ...but the budget is fine
    assert slo_events(journal) == []


def test_overrun_rate_breach_on_compile_storm():
    mon, journal = make_monitor()
    t = 0.0
    # Startup storm: every round overruns (a restarted slice re-warming
    # its kernels inside realtime budgets).
    for _ in range(45):
        M.BROKER_ROUNDS.inc(2)
        M.BROKER_PHASE_OVERRUNS.labels("lb").inc(2)
        mon.tick(now=t)
        t += 1.0
    assert ("slo.breach", "broker_overruns") in slo_events(journal)
    # Warm kernels: clean rounds recover the objective.
    for _ in range(15):
        M.BROKER_ROUNDS.inc(2)
        mon.tick(now=t)
        t += 1.0
    assert slo_events(journal)[-1] == ("slo.recovered", "broker_overruns")


def test_p99_objective_over_windowed_histogram_delta():
    mon, journal = make_monitor(serve_p99_ms=100.0)
    t = 0.0
    for _ in range(50):
        M.SERVE_REQUEST_LATENCY.observe([0.01] * 20)
        mon.tick(now=t)
        t += 1.0
    assert slo_events(journal) == []
    for _ in range(45):
        M.SERVE_REQUEST_LATENCY.observe([2.0] * 20)
        mon.tick(now=t)
        t += 1.0
    assert ("slo.breach", "serve_p99") in slo_events(journal)
    v = mon.status()["objectives"]["serve_p99"]
    assert v["value"] > 100.0
    for _ in range(20):
        M.SERVE_REQUEST_LATENCY.observe([0.01] * 20)
        mon.tick(now=t)
        t += 1.0
    assert slo_events(journal)[-1] == ("slo.recovered", "serve_p99")


def test_qsts_floor_only_judged_while_running():
    mon, journal = make_monitor(qsts_floor_steps_per_sec=1000.0)
    rate = M.REGISTRY.get("qsts_scenario_steps_per_sec")
    running = M.REGISTRY.get("qsts_jobs_running")
    t = 0.0
    # Slow chunks while NO job is running: not judged.
    rate.set(10.0)
    for _ in range(50):
        mon.tick(now=t)
        t += 1.0
    assert slo_events(journal) == []
    # A running job below the floor breaches; back above it recovers.
    running.set(1)
    for _ in range(45):
        mon.tick(now=t)
        t += 1.0
    assert ("slo.breach", "qsts_throughput") in slo_events(journal)
    rate.set(5000.0)
    for _ in range(15):
        mon.tick(now=t)
        t += 1.0
    assert slo_events(journal)[-1] == ("slo.recovered", "qsts_throughput")


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_stall_detection_and_recovery():
    mon, journal = make_monitor(watchdog_s=5.0)
    busy = [True]
    age = [0.0]
    mon.watch("serve.batcher", lambda: busy[0], lambda: age[0])
    mon.tick(now=0.0)
    assert [e for e in journal.tail() if e["event"].startswith("watchdog")] \
        == []
    # Busy with no progress past the limit: exactly one stall event,
    # even across repeated ticks.
    age[0] = 12.0
    mon.tick(now=1.0)
    mon.tick(now=2.0)
    stalls = [e for e in journal.tail() if e["event"] == "watchdog.stall"]
    assert len(stalls) == 1
    assert stalls[0]["target"] == "serve.batcher"
    assert stalls[0]["age_s"] == pytest.approx(12.0)
    assert M.REGISTRY.get("watchdog_stalls_total").labels(
        "serve.batcher"
    ).value == 1
    assert mon.status()["watchdogs"]["serve.batcher"]["stalled"] is True
    # Progress resumes: recovery journaled, stall flag clears.
    age[0] = 0.5
    mon.tick(now=3.0)
    assert journal.tail()[-1]["event"] == "watchdog.recovered"
    assert mon.status()["watchdogs"]["serve.batcher"]["stalled"] is False
    # Idle (not busy) never stalls, whatever the age says.
    busy[0] = False
    age[0] = 99.0
    mon.tick(now=4.0)
    stalls = [e for e in journal.tail() if e["event"] == "watchdog.stall"]
    assert len(stalls) == 1


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------


def test_slo_route_serves_installed_monitor():
    server = M.MetricsServer(port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/slo", timeout=5
        ) as r:
            assert json.loads(r.read()) == {"enabled": False}
        mon, _ = make_monitor()
        slo.install(mon)
        try:
            mon.tick(now=0.0)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/slo", timeout=5
            ) as r:
                body = json.loads(r.read())
        finally:
            slo.install(None)
        assert body["enabled"] is True
        assert body["config"]["serve_p99_ms"] == 100.0
        assert "objectives" in body and "watchdogs" in body
    finally:
        server.stop()
