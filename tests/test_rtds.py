"""RTDS lock-step adapter + plant server rig tests.

Covers the reference's HIL path (CRtdsAdapter.cpp:120-230: 50 ms
send-commands / read-states exchange, big-endian 4-byte floats, reveal
on initialized buffers) and the pscad-interface multi-node rig
(pscad-interface-master/src/PosixMain.cpp:46-80): a fleet driving
devices through real TCP sockets against a separate plant process.
"""

import json
import subprocess
import sys
import time

import numpy as np
import pytest

from freedm_tpu.core.config import NULL_COMMAND
from freedm_tpu.devices.adapters.plant import PlantAdapter
from freedm_tpu.devices.adapters.rtds import RtdsAdapter
from freedm_tpu.devices.factory import AdapterFactory
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.grid import cases
from freedm_tpu.sim.plantserver import PlantServer


def wait_for(cond, timeout=10.0, step=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def make_plant():
    feeder = cases.vvc_9bus()
    placements = {
        "SST1": ("Sst", 2),
        "DRER_A": ("Drer", 1),
        "LOAD_A": ("Load", 0),
        "OMEGA": ("Omega", 0),
    }
    plant = PlantAdapter(feeder, placements, droop=0.05)
    plant.set_generation("DRER_A", 30.0)
    plant.set_load("LOAD_A", 10.0)
    plant.reveal_devices()
    return plant


def test_lockstep_exchange_and_reveal():
    plant = make_plant()
    server = PlantServer(plant, period_s=0.01)
    states = [("SST1", "gateway"), ("DRER_A", "generation"),
              ("LOAD_A", "drain"), ("OMEGA", "frequency")]
    commands = [("SST1", "gateway")]
    host, port = server.add_port(states, commands)
    server.start()

    ad = RtdsAdapter(host, port, poll_s=0.01)
    for i, (d, s) in enumerate(states):
        ad.bind_state(d, s, i)
    ad.bind_command("SST1", "gateway", 0)
    for name in ("SST1", "DRER_A", "LOAD_A", "OMEGA"):
        ad.register_device(name)
    try:
        ad.start()
        # Reveal happens only after a fully-initialized state arrives.
        assert wait_for(lambda: ad.revealed, 5.0), ad.error
        assert ad.get_state("DRER_A", "generation") == pytest.approx(30.0)
        assert ad.get_state("LOAD_A", "drain") == pytest.approx(10.0)
        assert ad.get_state("OMEGA", "frequency") > 300.0
        # Command flows to the plant on the next exchange.
        ad.set_command("SST1", "gateway", 12.5)
        assert wait_for(
            lambda: ad.get_state("SST1", "gateway") == pytest.approx(12.5), 5.0
        ), ad.error
    finally:
        ad.stop()
        server.stop()
    assert ad.error is None
    assert ad.exchanges >= 2


def test_endianness_on_the_wire():
    # The protocol is explicitly big-endian 4-byte floats
    # (CRtdsAdapter::EndianSwapIfNeeded); verify against a raw socket.
    import socket as socket_mod

    plant = make_plant()
    server = PlantServer(plant, period_s=0.05)
    host, port = server.add_port([("DRER_A", "generation")], [("SST1", "gateway")])
    server.start()
    try:
        with socket_mod.create_connection((host, port), timeout=2.0) as s:
            s.sendall(np.asarray([NULL_COMMAND], ">f4").tobytes())
            raw = s.recv(4)
        assert np.frombuffer(raw, ">f4")[0] == pytest.approx(30.0)
        # Same bytes little-endian are NOT the value (catches a
        # byte-order regression).
        assert np.frombuffer(raw, "<f4")[0] != pytest.approx(30.0)
    finally:
        server.stop()


def test_socket_failure_marks_error_not_crash():
    plant = make_plant()
    server = PlantServer(plant, period_s=0.01)
    host, port = server.add_port([("DRER_A", "generation")], [])
    server.start()
    errors = []
    ad = RtdsAdapter(host, port, poll_s=0.01, socket_timeout_s=0.3,
                     on_error=errors.append)
    ad.bind_state("DRER_A", "generation", 0)
    ad.register_device("DRER_A")
    ad.start()
    assert wait_for(lambda: ad.revealed, 5.0)
    server.stop()  # plant dies mid-run
    assert wait_for(lambda: ad.error is not None, 5.0)
    assert errors and isinstance(errors[0], Exception)
    # Last good state still readable (double-buffered staging).
    assert ad.get_state("DRER_A", "generation") == pytest.approx(30.0)
    ad.stop()


# ---------------------------------------------------------------------------
# the full rig: separate plant-server process, fleet over adapter.xml
# ---------------------------------------------------------------------------

RIG_XML = """
<rig case="vvc_9bus" period="0.01" droop="0.05">
  <device name="SST1" type="Sst" node="2"/>
  <device name="DRER_A" type="Drer" node="1" value="30"/>
  <device name="LOAD_A" type="Load" node="0" value="10"/>
  <device name="OMEGA" type="Omega" node="0"/>
  <device name="SST2" type="Sst" node="4"/>
  <device name="LOAD_B" type="Load" node="5" value="30"/>
  <device name="DRER_B" type="Drer" node="6" value="10"/>
  <device name="SST3" type="Sst" node="7"/>
  <device name="LOAD_C" type="Load" node="3" value="20"/>
  <device name="DRER_C" type="Drer" node="3" value="20"/>
  <adapter port="0">
    <state device="SST1" signal="gateway" index="0"/>
    <state device="DRER_A" signal="generation" index="1"/>
    <state device="LOAD_A" signal="drain" index="2"/>
    <state device="OMEGA" signal="frequency" index="3"/>
    <command device="SST1" signal="gateway" index="0"/>
  </adapter>
  <adapter port="0">
    <state device="SST2" signal="gateway" index="0"/>
    <state device="DRER_B" signal="generation" index="1"/>
    <state device="LOAD_B" signal="drain" index="2"/>
    <command device="SST2" signal="gateway" index="0"/>
  </adapter>
  <adapter port="0">
    <state device="SST3" signal="gateway" index="0"/>
    <state device="DRER_C" signal="generation" index="1"/>
    <state device="LOAD_C" signal="drain" index="2"/>
    <command device="SST3" signal="gateway" index="0"/>
  </adapter>
</rig>
"""

NODE_DEVICES = [
    [("SST1", "Sst", "gateway"), ("DRER_A", "Drer", "generation"),
     ("LOAD_A", "Load", "drain"), ("OMEGA", "Omega", "frequency")],
    [("SST2", "Sst", "gateway"), ("DRER_B", "Drer", "generation"),
     ("LOAD_B", "Load", "drain")],
    [("SST3", "Sst", "gateway"), ("DRER_C", "Drer", "generation"),
     ("LOAD_C", "Load", "drain")],
]


def adapter_xml(node: int, port: int) -> str:
    states, commands = [], []
    for i, (dev, typ, sig) in enumerate(NODE_DEVICES[node]):
        states.append(
            f'<entry index="{i + 1}"><type>{typ}</type><device>{dev}</device>'
            f"<signal>{sig}</signal></entry>"
        )
    sst = NODE_DEVICES[node][0][0]
    commands.append(
        f'<entry index="1"><type>Sst</type><device>{sst}</device>'
        f"<signal>gateway</signal></entry>"
    )
    return (
        f'<root><adapter name="rig{node}" type="rtds">'
        f"<info><host>127.0.0.1</host><port>{port}</port><poll>0.01</poll></info>"
        f'<state>{"".join(states)}</state>'
        f'<command>{"".join(commands)}</command>'
        f"</adapter></root>"
    )


@pytest.fixture
def plant_server_process(tmp_path):
    import os

    rig = tmp_path / "rig.xml"
    rig.write_text(RIG_XML)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "freedm_tpu.sim.plantserver", str(rig)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
    )
    line = proc.stdout.readline()
    try:
        ports = [p for _, p in json.loads(line)["plantserver"]]
    except Exception:
        proc.terminate()
        raise RuntimeError(f"plantserver failed to start: {line!r} {proc.stderr.read()[:2000]}")
    yield ports
    proc.terminate()
    proc.wait(timeout=5)


def test_three_node_lb_converges_over_rtds_rig(plant_server_process):
    """BASELINE config #1 through the full HIL stack: fleet ↔ TCP ↔
    plant process, LB converging to the reference outcome [20, -20, 0]."""
    from freedm_tpu.runtime.fleet import Fleet, NodeHandle, build_broker

    ports = plant_server_process
    managers, factories = [], []
    for node, port in enumerate(ports):
        m = DeviceManager(capacity=8)
        f = AdapterFactory(m)
        f.create_from_xml(adapter_xml(node, port))
        f.start()
        managers.append(m)
        factories.append(f)
    try:
        for f in factories:
            for a in f.adapters.values():
                assert wait_for(lambda a=a: a.revealed, 10.0), a.error
        fleet = Fleet(
            [NodeHandle(f"host{i}:5187{i}", m) for i, m in enumerate(managers)],
            migration_step=1.0,
        )
        broker = build_broker(fleet)

        def gateways():
            return np.asarray([m.get_net_value("Sst", "gateway") for m in managers])

        converged = False
        for _ in range(60):
            broker.run(n_rounds=1)
            time.sleep(0.03)  # let two exchanges carry commands/states
            if np.allclose(gateways(), [20.0, -20.0, 0.0], atol=1.01):
                converged = True
                break
        assert converged, f"no convergence; gateways={gateways()}"
        # Everyone settled inside the migration band: no more drafts.
        broker.run(n_rounds=1)
        assert int(broker.shared["lb_round"].n_migrations) <= 1
    finally:
        for f in factories:
            f.stop()
