"""Micro-batching query-serving subsystem tests (``freedm_tpu.serve``):
admission/shed/deadline semantics, typed validation errors, end-to-end
round-trips for all three workloads with conservation stamps, the
concurrent mixed-shape submission contract (every waiter gets its own
result, padding lands in the expected bucket, recompiles stay bounded
by the bucket table), and the JSON front end's typed error mapping.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from freedm_tpu.core import metrics as M
from freedm_tpu.serve import (
    DeadlineExceeded,
    InvalidRequest,
    Overloaded,
    ServeConfig,
    ServeServer,
    Service,
    ShuttingDown,
    default_buckets,
    parse_request,
)
from freedm_tpu.serve.queue import AdmissionQueue, ServeError, Ticket
from freedm_tpu.serve.service import (
    N1Request,
    PowerFlowRequest,
    VVCRequest,
)

#: Shared bucket table for the module's service (small: the jit compile
#: budget of this test file is 3 buckets x 3 engines).
BUCKETS = (1, 2, 4)


@pytest.fixture(scope="module")
def svc():
    # cache_mb=0: this module pins the admission/batching/dispatch path
    # itself — the incremental tier would answer repeat requests before
    # they ever reach it (tests/test_serve_cache.py covers cache-on).
    s = Service(ServeConfig(max_batch=4, max_wait_ms=25.0, queue_depth=64,
                            buckets=BUCKETS, cache_mb=0.0))
    yield s
    s.stop()


# ---------------------------------------------------------------------------
# queue semantics
# ---------------------------------------------------------------------------


def _ticket(lanes=1, deadline=None, key=("pf", "case14")):
    return Ticket(key, None, {}, lanes, deadline)


def test_default_buckets_powers_of_two_plus_intermediates():
    # Powers of two PLUS the 1.5x intermediates (3, 6, 12, ...): the
    # fatter table caps worst-case padding waste at ~33% instead of
    # ~50% (prewarm hides the extra compiles).
    assert default_buckets(64) == (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)
    assert default_buckets(6) == (1, 2, 3, 4, 6)
    assert default_buckets(1) == (1,)


def test_bucket_padding_waste_is_reduced_and_reported(svc):
    from freedm_tpu.serve.service import padding_waste_pct

    pow2 = (1, 2, 4, 8, 16, 32, 64)
    fat = default_buckets(64)
    # The worst case drops from just-under-50% (2^k + 1 lanes) to
    # under 34% — the satellite's pinned reduction.
    assert padding_waste_pct(pow2) > 45.0
    assert padding_waste_pct(fat) <= 34.0
    # /stats carries both the analytic worst case and the measured
    # padding of what was actually dispatched.
    pad = svc.stats()["padding"]
    assert pad["worst_case_pad_pct"] == padding_waste_pct(
        svc.config.bucket_table()
    )
    assert pad["dispatched_lanes"] >= 0
    assert 0.0 <= pad["observed_pad_pct"] <= 100.0


def test_queue_sheds_on_overload_in_lanes():
    q = AdmissionQueue(max_depth=3)
    q.put(_ticket(lanes=2))
    q.put(_ticket(lanes=1))
    with pytest.raises(Overloaded):
        q.put(_ticket(lanes=1))  # 3 + 1 > 3: shed, not block
    assert q.depth_lanes == 3
    # FIFO order out; depth accounting follows.
    t = q.pop(timeout=0.1)
    assert t.lanes == 2
    assert q.depth_lanes == 1


def test_queue_completes_expired_tickets_with_typed_error():
    q = AdmissionQueue(max_depth=8)
    dead = _ticket(deadline=time.monotonic() - 0.01)
    live = _ticket()
    q.put(dead)
    q.put(live)
    got = q.pop(timeout=0.2)
    assert got is live
    assert isinstance(dead.future.exception(timeout=1), DeadlineExceeded)


def test_queue_close_refuses_and_drains():
    q = AdmissionQueue(max_depth=8)
    t = _ticket()
    q.put(t)
    drained = q.close()
    assert drained == [t]
    with pytest.raises(ShuttingDown):
        q.put(_ticket())


def test_pop_compatible_only_matches_key_and_capacity():
    q = AdmissionQueue(max_depth=32)
    a = _ticket(key=("pf", "case14"))
    big = _ticket(lanes=8, key=("n1", "case14"))
    b = _ticket(key=("n1", "case14"))
    for t in (a, big, b):
        q.put(t)
    # Wrong key never surfaces; a head too big for the remaining batch
    # space blocks its key (it opens the next batch) without starvation
    # of the global FIFO.
    assert q.pop_compatible(("vvc", "x"), 4, timeout=0.05) is None
    assert q.pop_compatible(("n1", "case14"), 4, timeout=0.05) is None
    assert q.pop_compatible(("n1", "case14"), 8, timeout=0.05) is big
    assert q.pop(timeout=0.1) is a


# ---------------------------------------------------------------------------
# request validation: typed errors before admission
# ---------------------------------------------------------------------------


def test_parse_request_rejects_unknown_workload_and_fields():
    with pytest.raises(InvalidRequest):
        parse_request("zap", {"case": "case14"})
    with pytest.raises(InvalidRequest):
        parse_request("pf", {"case": "case14", "frobnicate": 1})
    with pytest.raises(InvalidRequest):
        parse_request("pf", {})  # missing case
    req = parse_request("pf", {"case": "case14", "scale": 1.1})
    assert isinstance(req, PowerFlowRequest) and req.scale == 1.1


def test_validation_errors_are_typed(svc):
    with pytest.raises(InvalidRequest):
        svc.request("pf", {"case": "no_such_case"})
    with pytest.raises(InvalidRequest):
        svc.request("pf", {"case": "case14", "scale": -1.0})
    with pytest.raises(InvalidRequest):
        svc.request("pf", {"case": "case14", "p_inj": [1.0, 2.0]})  # wrong len
    with pytest.raises(InvalidRequest):
        svc.request("n1", {"case": "case14", "outages": []})
    with pytest.raises(InvalidRequest):
        svc.request("n1", {"case": "case14", "outages": [10**6]})
    eng = svc.engine("n1", "case14")
    islanding = sorted(set(range(eng.n_branch)) - set(eng._secure))
    assert islanding, "case14 should have bridge branches"
    with pytest.raises(InvalidRequest) as ei:
        svc.request("n1", {"case": "case14", "outages": [islanding[0]]})
    assert "island" in str(ei.value)
    with pytest.raises(InvalidRequest):
        svc.request("vvc", {"case": "vvc_9bus", "q_ctrl_kvar": [[0.0] * 3]})
    nb = svc.engine("vvc", "vvc_9bus").nb
    bad = np.full((nb, 3), np.nan)
    with pytest.raises(InvalidRequest):
        svc.request("vvc", {"case": "vvc_9bus", "q_ctrl_kvar": bad.tolist()})
    # A request wider than the batch ceiling is rejected up front.
    with pytest.raises(InvalidRequest):
        svc.request("n1", {"case": "case14", "outages": list(eng._secure)[:5]})
    # Wrong-typed field VALUES are still typed 400s, not internal errors.
    with pytest.raises(InvalidRequest):
        svc.request("pf", {"case": "case14", "scale": "1.1"})
    with pytest.raises(InvalidRequest):
        svc.request("n1", {"case": "case14", "outages": 5})
    # The client-named synthetic mesh size is capped (O(n^2) memory).
    with pytest.raises(InvalidRequest):
        svc.request("pf", {"case": "mesh100000000"})


# ---------------------------------------------------------------------------
# round-trips: every response carries its convergence/conservation stamp
# ---------------------------------------------------------------------------


def test_pf_roundtrip_stamps_residual_and_conservation(svc):
    r = svc.request("pf", {"case": "case14", "scale": 1.0,
                           "return_state": True})
    assert r.workload == "pf" and r.case == "case14"
    assert r.converged and r.residual_pu < 1e-6
    # Conservation: sum of realized P injections = network losses, a
    # small non-negative number in pu.
    assert 0.0 <= r.p_balance_pu < 0.2
    assert len(r.v) == 14 and len(r.theta) == 14
    assert 0.9 < r.v_min_pu <= r.v_max_pu < 1.15
    assert r.batch.lanes >= 1 and r.batch.bucket in BUCKETS


def test_pf_summary_only_by_default(svc):
    r = svc.request("pf", {"case": "case14"})
    assert r.v is None and r.theta is None
    assert r.converged


def test_n1_roundtrip_screens_requested_subset(svc):
    eng = svc.engine("n1", "case14")
    ks = list(eng._secure)[:3]
    r = svc.request("n1", {"case": "case14", "outages": ks})
    assert r.outages == ks
    assert len(r.converged) == 3 and all(r.converged)
    assert r.all_converged and r.worst_residual_pu < 1e-6
    assert max(r.residual_pu) == r.worst_residual_pu
    assert r.batch.bucket >= 3


def test_vvc_what_if_reports_loss_and_band(svc):
    nb = svc.engine("vvc", "vvc_9bus").nb
    zero = np.zeros((nb, 3))
    r0 = svc.request("vvc", {"case": "vvc_9bus", "q_ctrl_kvar": zero.tolist()})
    assert r0.converged
    # The zero proposal IS the baseline: delta ~ 0.
    assert abs(r0.loss_delta_kw) < 1e-6
    assert r0.band_violations >= 0
    r1 = svc.request("vvc", VVCRequest(case="vvc_9bus",
                                       q_ctrl_kvar=np.full((nb, 3), 100.0)))
    assert r1.converged
    assert abs(r1.loss_kw - r0.loss_kw) > 1e-4  # the what-if moved losses


# ---------------------------------------------------------------------------
# the satellite contract: concurrent mixed-shape submission
# ---------------------------------------------------------------------------


def test_concurrent_mixed_shapes_every_waiter_gets_its_own_result(svc):
    """N threads interleave pf/N-1/VVC submissions: each waiter must get
    its own result, padding must land in the smallest bucket >= the
    batch's real lanes, and the recompile counter must stay <= the
    bucket table size per workload."""
    eng_n1 = svc.engine("n1", "case14")
    nb = svc.engine("vvc", "vvc_9bus").nb
    secure = list(eng_n1._secure)
    scales = [0.9, 1.0, 1.1]
    n1_sets = [secure[:2], secure[2:4]]
    q_props = [np.zeros((nb, 3)), np.full((nb, 3), 150.0),
               np.full((nb, 3), -150.0)]

    rec = M.REGISTRY.get("serve_recompiles_total")
    before = {w: rec.labels(w).value for w in ("pf", "n1", "vvc")}

    jobs = (
        [("pf", PowerFlowRequest(case="case14", scale=s, return_state=True))
         for s in scales]
        + [("n1", N1Request(case="case14", outages=ks)) for ks in n1_sets]
        + [("vvc", VVCRequest(case="vvc_9bus", q_ctrl_kvar=q))
           for q in q_props]
    )
    barrier = threading.Barrier(len(jobs))
    results = [None] * len(jobs)
    errors = []

    def worker(i, workload, req):
        try:
            barrier.wait(timeout=30)
            results[i] = svc.request(workload, req, timeout_s=120)
        except Exception as e:  # noqa: BLE001
            errors.append((i, e))

    threads = [
        threading.Thread(target=worker, args=(i, w, r))
        for i, (w, r) in enumerate(jobs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors, errors
    assert all(r is not None for r in results)

    # Every response is its submitter's own: pf echoes its scale and
    # matches a solo re-solve bit-for-bit-ish; n1 echoes its outage
    # subset; vvc's zero proposal reproduces the baseline.
    pf_rs = results[:3]
    for s, r in zip(scales, pf_rs):
        assert r.scale == s and r.converged
        solo = svc.request("pf", PowerFlowRequest(
            case="case14", scale=s, return_state=True))
        assert np.allclose(r.v, solo.v, atol=1e-9)
    # Heavier load means more losses: the three lanes are distinct and
    # ordered (v_min is pinned at a PV setpoint on this case, so the
    # conservation stamp is the discriminating scalar).
    losses = [r.p_balance_pu for r in pf_rs]
    assert losses[0] < losses[1] < losses[2]

    n1_rs = results[3:5]
    for ks, r in zip(n1_sets, n1_rs):
        assert r.outages == ks
        assert len(r.residual_pu) == len(ks)
        assert r.all_converged and r.worst_residual_pu < 1e-6

    vvc_rs = results[5:]
    assert abs(vvc_rs[0].loss_delta_kw) < 1e-6
    assert abs(vvc_rs[1].loss_kw - vvc_rs[2].loss_kw) > 1e-4

    # Padding landed in the expected bucket: the smallest table entry
    # holding the batch's real lanes.
    for r in (r for rs in (pf_rs, n1_rs, vvc_rs) for r in rs):
        b = r.batch
        assert b.bucket in BUCKETS
        assert b.bucket >= b.lanes
        assert b.bucket == min(x for x in BUCKETS if x >= b.lanes)

    # Bounded compile storm: at most one recompile per bucket per
    # workload, ever (the counter only moves on FIRST use of a shape).
    after = {w: rec.labels(w).value for w in ("pf", "n1", "vvc")}
    for w in ("pf", "n1", "vvc"):
        assert after[w] - before[w] <= len(BUCKETS)


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


def _post(port, path, payload):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        with e:
            return e.code, json.loads(e.read())


def _post_with_headers(port, path, payload):
    """Like :func:`_post` but also returns the response headers (the
    Retry-After satellite asserts on them)."""
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        payload = json.loads(e.read())
        hdrs = dict(e.headers)
        e.close()
        return e.code, payload, hdrs


def test_http_roundtrip_and_typed_errors(svc):
    srv = ServeServer(svc, port=0).start()
    try:
        code, d = _post(srv.port, "/v1/pf", {"case": "case14", "scale": 1.0})
        assert code == 200
        assert d["converged"] and d["residual_pu"] < 1e-6
        assert d["batch"]["bucket"] in BUCKETS

        code, d = _post(srv.port, "/v1/pf", {"case": "bogus"})
        assert code == 400 and d["error"]["type"] == "invalid_request"

        code, d = _post(srv.port, "/v1/zap", {"case": "case14"})
        assert code == 400 and d["error"]["type"] == "invalid_request"

        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=10
        ) as r:
            h = json.loads(r.read())
        assert h["ok"] and "pf" in h["workloads"]

        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/stats", timeout=10
        ) as r:
            stats = json.loads(r.read())
        assert stats["buckets"] == list(BUCKETS)
        assert any(e.startswith("pf/") for e in stats["engines"])
    finally:
        srv.stop()


def test_stats_attributes_recompiles_per_shape_bucket(svc):
    """/stats names WHICH (workload, case, bucket) shapes compiled —
    the aggregate serve_recompiles_total counter says a storm happened,
    the table says who, without reading traces."""
    # Two deterministic shapes on a case this module's other tests
    # don't screen: a 1-outage request (bucket 1) and a 3-outage
    # request (3 lanes -> bucket 4).
    eng = svc.engine("n1", "case_ieee30")
    ks = list(eng._secure)
    svc.request("n1", {"case": "case_ieee30", "outages": ks[:1]})
    svc.request("n1", {"case": "case_ieee30", "outages": ks[:3]})
    # Same shapes again: already-compiled buckets add nothing.
    svc.request("n1", {"case": "case_ieee30", "outages": ks[1:2]})
    table = svc.stats()["recompiles_by_bucket"]
    assert table["n1/case_ieee30:1"] == 1
    assert table["n1/case_ieee30:4"] == 1
    # Every entry is a FIRST dispatch of its shape, and the aggregate
    # counter covers the table's n1 total.
    assert all(v == 1 for v in table.values())
    snap = svc.stats()["recompiles"]
    assert snap.get("n1", 0) >= sum(
        v for k, v in table.items() if k.startswith("n1/")
    )


def _read_http_response(sock) -> bytes:
    """One full HTTP response (headers + Content-Length body) off a
    persistent connection, leaving any pipelined follow-up unread."""
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        assert chunk, f"connection closed early; got {buf!r}"
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":")[1])
    while len(rest) < length:
        chunk = sock.recv(65536)
        assert chunk, "connection closed mid-body"
        rest += chunk
    return head + b"\r\n\r\n" + rest[:length]


def test_http_keepalive_survives_rejected_first_request(svc):
    """Regression (ISSUE 4 satellite): a POST rejected BEFORE its body
    was consumed used to leave the body bytes on the persistent
    connection, so the next pipelined request parsed garbage.  Two
    requests on one socket: the first rejected (404 route, with a
    body), the second a valid pf query — both must answer cleanly."""
    import socket

    srv = ServeServer(svc, port=0).start()
    try:
        def raw(path, payload):
            body = json.dumps(payload).encode()
            return (
                f"POST {path} HTTP/1.1\r\nHost: t\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n"
            ).encode() + body

        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=120) as s:
            # Pipelined: both requests hit the socket before the first
            # response — the drained body is what keeps request #2
            # parseable.
            s.sendall(raw("/v1/zap", {"case": "case14"}))
            s.sendall(raw("/v1/pf", {"case": "case14"}))
            first = _read_http_response(s)
            second = _read_http_response(s)
        assert first.startswith(b"HTTP/1.1 400")
        assert b"invalid_request" in first
        assert second.startswith(b"HTTP/1.1 200")
        assert b'"converged": true' in second

        # A body the server refuses to read cannot be drained: the
        # response must close the connection instead.
        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=30) as s:
            s.sendall(b"POST /v1/pf HTTP/1.1\r\nHost: t\r\n"
                      b"Content-Length: 99999999\r\n\r\n")
            resp = _read_http_response(s)
        assert resp.startswith(b"HTTP/1.1 400")
        assert b"Connection: close" in resp
    finally:
        srv.stop()


def test_pf_warm_start_fields_cut_iterations(svc):
    """ISSUE 4 satellite: v0/theta0 on PowerFlowRequest, validated like
    the other [n] vectors, warm-start the Newton solve — a repeat
    client's second query converges in fewer iterations."""
    cold = svc.request("pf", {"case": "case14", "scale": 1.0,
                              "return_state": True})
    assert cold.converged and cold.iterations >= 1
    warm = svc.request("pf", {"case": "case14", "scale": 1.0,
                              "v0": cold.v, "theta0": cold.theta})
    assert warm.converged and warm.residual_pu < 1e-6
    assert warm.iterations < cold.iterations
    before = M.REGISTRY.get("serve_warm_start_total").value
    svc.request("pf", {"case": "case14", "v0": cold.v})
    assert M.REGISTRY.get("serve_warm_start_total").value == before + 1
    # Validation mirrors p_inj/q_inj: wrong length, non-finite, and
    # out-of-range magnitudes are typed 400s.
    with pytest.raises(InvalidRequest):
        svc.request("pf", {"case": "case14", "v0": [1.0, 1.0]})
    with pytest.raises(InvalidRequest):
        svc.request("pf", {"case": "case14", "v0": [0.0] * 14})
    with pytest.raises(InvalidRequest):
        svc.request("pf", {"case": "case14",
                           "theta0": [float("nan")] * 14})


def test_http_overload_sheds_with_429():
    # A service whose batcher never runs: the queue fills and stays full,
    # so admission control is exercised deterministically.
    # cache_mb=0: the second identical request must hit ADMISSION (the
    # cache's single-flight would park it on the first one instead).
    svc2 = Service(ServeConfig(max_batch=4, queue_depth=1, buckets=(1, 4),
                               cache_mb=0.0),
                   start=False)
    srv = ServeServer(svc2, port=0).start()
    try:
        fut = svc2.submit("pf", {"case": "case14"})  # fills the only slot
        code, d, headers = _post_with_headers(
            srv.port, "/v1/pf", {"case": "case14"}
        )
        assert code == 429 and d["error"]["type"] == "overloaded"
        # Typed backpressure carries the back-off hint (ISSUE 12).
        assert int(headers["Retry-After"]) >= 1
        shed = M.REGISTRY.get("serve_shed_total")
        assert shed.value >= 1
        # drain_s=0: the batcher of this service never runs, so the
        # admitted ticket can only resolve via the shutdown path.
        svc2.stop(drain_s=0)
        assert isinstance(fut.exception(timeout=5), ShuttingDown)
        with pytest.raises(ShuttingDown):
            svc2.submit("pf", {"case": "case14"})
        # Not-yet-admitted work over HTTP: typed 503 + Retry-After.
        code, d, headers = _post_with_headers(
            srv.port, "/v1/pf", {"case": "case14"}
        )
        assert code == 503 and d["error"]["type"] == "shutting_down"
        assert int(headers["Retry-After"]) >= 1
    finally:
        srv.stop()


def test_graceful_stop_drains_admitted_work():
    """The drain satellite: stop() lets already-admitted tickets FINISH
    (typed shutting_down is only for work submitted after the seal)."""
    svc2 = Service(ServeConfig(max_batch=2, buckets=(1, 2), cache_mb=0.0))
    try:
        fut = svc2.submit("pf", {"case": "case14", "timeout_s": 300.0})
        svc2.stop()  # default drain: the admitted solve completes
        resp = fut.result(timeout=30.0)
        assert resp.converged
        with pytest.raises(ShuttingDown):
            svc2.submit("pf", {"case": "case14"})
    finally:
        svc2.stop(drain_s=0)  # idempotent


def test_healthz_reports_draining_after_begin_drain(svc):
    srv = ServeServer(svc, port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=30
        ) as r:
            assert json.loads(r.read())["draining"] is False
        srv.begin_drain()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=30
        ) as r:
            assert json.loads(r.read())["draining"] is True
    finally:
        srv.stop()


def test_deadline_budget_clamps_timeout():
    from freedm_tpu.serve.http import apply_deadline_budget

    p = {"case": "case14", "timeout_s": 30.0}
    apply_deadline_budget(p, "2.5")
    assert p["timeout_s"] == 2.5
    p = {"case": "case14", "timeout_s": 1.0}
    apply_deadline_budget(p, "2.5")  # budget LARGER: timeout kept
    assert p["timeout_s"] == 1.0
    p = {"case": "case14"}
    apply_deadline_budget(p, "2.5")  # no timeout: budget becomes it
    assert p["timeout_s"] == 2.5
    p = {"case": "case14", "timeout_s": 30.0}
    apply_deadline_budget(p, "garbage")  # unparseable: ignored
    apply_deadline_budget(p, "-1")
    apply_deadline_budget(p, None)
    assert p["timeout_s"] == 30.0


# ---------------------------------------------------------------------------
# pipelined serving (ISSUE 9): executor lanes vs the serialized oracle
# ---------------------------------------------------------------------------


def _strip_batch(resp) -> str:
    """Canonical JSON of a response minus the batch receipt (whose
    queue/solve timings and coalescing-dependent lanes/bucket fields
    legitimately differ between runs)."""
    d = resp.to_dict()
    d.pop("batch")
    return json.dumps(d, sort_keys=True)


def _mixed_jobs(svc):
    """A deterministic mixed pf/n1/vvc job set (typed records)."""
    eng = svc.engine("n1", "case14")
    nb = svc.engine("vvc", "vvc_9bus").nb
    sec = list(eng._secure)
    return (
        [("pf", PowerFlowRequest(case="case14", scale=s, return_state=True))
         for s in (0.9, 1.0, 1.1, 1.05)]
        + [("n1", N1Request(case="case14", outages=sec[:2])),
           ("n1", N1Request(case="case14", outages=sec[2:3]))]
        + [("vvc", VVCRequest(case="vvc_9bus",
                              q_ctrl_kvar=np.full((nb, 3), q)))
           for q in (0.0, 100.0, -150.0)]
    )


def _run_concurrent(svc, jobs, timeout_s=300):
    barrier = threading.Barrier(len(jobs))
    results = [None] * len(jobs)
    errors = []

    def worker(i, workload, req):
        try:
            barrier.wait(timeout=60)
            results[i] = svc.request(workload, req, timeout_s=timeout_s)
        except Exception as e:  # noqa: BLE001
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i, w, r))
               for i, (w, r) in enumerate(jobs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s)
    assert not errors, errors
    assert all(r is not None for r in results)
    return results


def test_pipeline_matches_serialized_byte_identical():
    """The ISSUE 9 equivalence contract: concurrent mixed pf/n1/vvc
    served by the pipelined path (per-engine executor lanes, depth 2)
    and by ``--serve-pipeline-depth 0`` (the legacy single-thread
    oracle) produce byte-identical responses, whatever batch
    composition the two schedulers happened to coalesce (the single
    fixed bucket keeps every batch at one compiled shape, so per-lane
    results cannot depend on who shared the batch)."""
    # cache_mb=0 here: this is the BATCHING equivalence oracle (which
    # tier a request lands on depends on thread timing with the cache
    # on); the cache-on equivalence contract has its own oracle in
    # tests/test_serve_cache.py.
    cfg = dict(max_batch=4, max_wait_ms=25.0, queue_depth=64, buckets=(4,),
               cache_mb=0.0)
    svc_pipe = Service(ServeConfig(pipeline_depth=2, **cfg))
    svc_ser = Service(ServeConfig(pipeline_depth=0, **cfg))
    try:
        assert set(svc_pipe.batcher.lanes) == {"pf", "n1", "vvc", "topo"}
        assert svc_ser.batcher.lanes == {}
        jobs = _mixed_jobs(svc_pipe)
        got_pipe = [_strip_batch(r) for r in _run_concurrent(svc_pipe, jobs)]
        got_ser = [_strip_batch(r) for r in _run_concurrent(svc_ser, jobs)]
        assert got_pipe == got_ser
        # And the pipelined service's stats surface names its lanes.
        st = svc_pipe.stats()
        assert st["pipeline_depth"] == 2
        assert set(st["executor_lanes"]) == {"pf", "n1", "vvc", "topo"}
    finally:
        svc_pipe.stop()
        svc_ser.stop()


def test_pipeline_ordered_per_ticket_completion():
    """Same-workload tickets complete in submission order: batches run
    FIFO through the workload's single executor lane, and the scatter
    loop resolves a batch's futures in group (= pop) order."""
    svc2 = Service(ServeConfig(max_batch=2, max_wait_ms=5.0, queue_depth=64,
                               buckets=(1, 2), pipeline_depth=2,
                               cache_mb=0.0))  # identical tickets must QUEUE
    try:
        order = []
        lock = threading.Lock()

        def tag(i):
            def cb(fut):
                if fut.exception() is None:
                    with lock:
                        order.append(i)
            return cb

        futs = []
        for i in range(8):
            f = svc2.submit("pf", {"case": "case14", "timeout_s": 300})
            f.add_done_callback(tag(i))
            futs.append(f)
        for f in futs:
            f.result(timeout=300)
        assert order == sorted(order), order
    finally:
        svc2.stop()


def test_executor_lane_crash_fails_only_its_batch():
    """A solver exception on one executor lane fails only that batch's
    tickets with the typed ``internal`` error; the assembly lane and
    the other lanes keep serving."""
    svc2 = Service(ServeConfig(max_batch=4, max_wait_ms=2.0, queue_depth=64,
                               buckets=(1, 2, 4), pipeline_depth=2))
    try:
        nb = svc2.engine("vvc", "vvc_9bus").nb
        veng = svc2.engine("vvc", "vvc_9bus")
        real_solve = veng.solve
        veng.solve = lambda batch: (_ for _ in ()).throw(
            RuntimeError("injected lane crash")
        )
        with pytest.raises(ServeError) as ei:
            svc2.request("vvc", {"case": "vvc_9bus",
                                 "q_ctrl_kvar": np.zeros((nb, 3)).tolist()})
        assert ei.value.code == "internal"
        # The failed first dispatch must not mark its bucket compiled:
        # the retry below re-claims the shape, so the real compile
        # keeps its jit_compile tag and compile-account entry.
        assert 1 not in veng.compiled_buckets
        # The crash was contained: the vvc lane thread survived and the
        # assembly lane still feeds the other lanes.
        assert svc2.batcher.lanes["vvc"]._thread.is_alive()
        r = svc2.request("pf", {"case": "case14"})
        assert r.converged
        veng.solve = real_solve
        r2 = svc2.request("vvc", {"case": "vvc_9bus",
                                  "q_ctrl_kvar": np.zeros((nb, 3)).tolist()})
        assert r2.converged
    finally:
        svc2.stop()


def test_watchdog_stall_detection_per_lane():
    """Each executor lane is its own watchdog target: a pf solve wedged
    on its lane trips ``watchdog.stall`` for serve.lane.pf (not for the
    assembly thread or the idle lanes), and recovers once it beats."""
    from freedm_tpu.core import metrics as obs
    from freedm_tpu.core.slo import SloConfig, SloMonitor

    journal = obs.JsonlEventJournal()
    mon = SloMonitor(SloConfig(watchdog_s=0.05), journal=journal)
    svc2 = Service(ServeConfig(max_batch=2, max_wait_ms=2.0, queue_depth=64,
                               buckets=(1, 2), pipeline_depth=1,
                               cache_mb=0.0))  # repeats must reach the lane
    try:
        # Warm the engine/bucket first so the stall below is the gate,
        # not an XLA compile.
        svc2.request("pf", {"case": "case14"})
        b = svc2.batcher
        for w, lane in b.lanes.items():
            mon.watch(f"serve.lane.{w}", lane.busy, lane.progress_age)

        eng = svc2.engine("pf", "case14")
        gate = threading.Event()
        real_solve = eng.solve

        def stuck_solve(batch):
            gate.wait(timeout=30)
            return real_solve(batch)

        eng.solve = stuck_solve
        fut = svc2.submit("pf", {"case": "case14", "timeout_s": 300})
        deadline = time.monotonic() + 10
        while not b.lanes["pf"].busy() and time.monotonic() < deadline:
            time.sleep(0.005)
        time.sleep(0.2)  # past the 50 ms watchdog limit
        mon.tick()
        stalls = [e for e in journal.tail()
                  if e["event"] == "watchdog.stall"]
        assert [e["target"] for e in stalls] == ["serve.lane.pf"]
        gate.set()
        fut.result(timeout=300)
        # The future resolves from scatter while the lane is still
        # inside _execute's completion accounting — poll the monitor
        # until the lane's fresh beat lands instead of racing it.
        deadline = time.monotonic() + 10
        rec = []
        while not rec and time.monotonic() < deadline:
            mon.tick()
            rec = [e for e in journal.tail()
                   if e["event"] == "watchdog.recovered"]
            if not rec:
                time.sleep(0.02)
        assert [e["target"] for e in rec] == ["serve.lane.pf"]
    finally:
        svc2.stop()


def test_adaptive_coalescing_skips_empty_window():
    """ISSUE 9 satellite: a lone request with an empty queue behind it
    dispatches immediately instead of sleeping out ``max_wait_ms`` —
    the flat low-load latency tax is gone.  Window set far above any
    solve time so the old behavior would be unmissable."""
    svc2 = Service(ServeConfig(max_batch=4, max_wait_ms=400.0,
                               queue_depth=64, buckets=(1, 2, 4),
                               pipeline_depth=2,
                               cache_mb=0.0))  # the repeat must DISPATCH
    try:
        svc2.request("pf", {"case": "case14"})  # compile the shape
        t0 = time.monotonic()
        r = svc2.request("pf", {"case": "case14"})
        latency = time.monotonic() - t0
        assert r.converged
        # Old loop: >= 0.4 s (the full window).  Adaptive: a warm solve
        # plus scheduling noise, far under half the window.
        assert latency < 0.2, f"lone ticket waited the window: {latency}"
    finally:
        svc2.stop()


def test_prewarm_compiles_buckets_and_excludes_recompile_counter():
    """ISSUE 9 satellite: ``--serve-prewarm`` compiles every bucket of
    the named engine at startup; the shapes show up tagged (count 0) in
    /stats ``recompiles_by_bucket`` + ``prewarmed`` and serving them
    never moves ``serve_recompiles_total``."""
    rec = M.REGISTRY.get("serve_recompiles_total")
    before = rec.labels("pf").value
    svc2 = Service(ServeConfig(max_batch=2, max_wait_ms=2.0, queue_depth=64,
                               buckets=(1, 2), pipeline_depth=2,
                               prewarm=("pf/case14",)))
    try:
        assert rec.labels("pf").value == before  # prewarm never counts
        st = svc2.stats()
        assert st["prewarmed"] == ["pf/case14:1", "pf/case14:2"]
        assert st["recompiles_by_bucket"] == {"pf/case14:1": 0,
                                              "pf/case14:2": 0}
        r = svc2.request("pf", {"case": "case14"})
        assert r.converged
        # Serving a prewarmed shape is a cache hit, not a recompile.
        assert rec.labels("pf").value == before
        assert svc2.stats()["recompiles_by_bucket"]["pf/case14:1"] == 0
        with pytest.raises(InvalidRequest):
            svc2.prewarm(("bogus-spec",))
    finally:
        svc2.stop()
    # A failing prewarm spec at CONSTRUCTION must not leak the already
    # started assembly/executor threads (the constructor never returns,
    # so nobody could stop them).
    before = {t for t in threading.enumerate()}
    with pytest.raises(InvalidRequest):
        Service(ServeConfig(max_batch=2, buckets=(1, 2),
                            prewarm=("pf/no_such_case",)))
    leaked = [t for t in set(threading.enumerate()) - before
              if t.is_alive()
              and t.name.startswith(("serve-batcher", "serve-exec"))]
    assert not leaked, leaked


def test_trace_parentage_survives_thread_handoff():
    """The serve.request → serve.batch → pf.solve span chain keeps its
    parentage across the assembly→executor thread handoff: the batch
    span opens on the assembly lane (parented to the request span's
    wire context) and the solve span opens on the executor lane inside
    the batch span's activation."""
    from freedm_tpu.core import tracing

    tracing.TRACER.configure(enabled=True, node="pipeline-test")
    svc2 = Service(ServeConfig(max_batch=2, max_wait_ms=2.0, queue_depth=64,
                               buckets=(1, 2), pipeline_depth=1))
    try:
        r = svc2.request("pf", {"case": "case14"})
        assert r.converged
        # The request span ends in _complete_ok AFTER the future
        # resolves — poll the flight recorder briefly.
        deadline = time.monotonic() + 10
        req = None
        while req is None and time.monotonic() < deadline:
            recs = tracing.TRACER.tail(200)
            reqs = [x for x in recs if x.get("name") == "serve.request"]
            if reqs:
                req = reqs[-1]
            else:
                time.sleep(0.01)
        assert req is not None
        chain = [x for x in tracing.TRACER.tail(200)
                 if x.get("trace_id") == req["trace_id"]]
        batch = next(x for x in chain if x["name"] == "serve.batch")
        solve = next(x for x in chain if x["name"] == "pf.solve:pf")
        assert batch["parent_id"] == req["span_id"]
        assert solve["parent_id"] == batch["span_id"]
        assert solve["tags"]["jit_compile"] in (True, False)
    finally:
        svc2.stop()
        tracing.TRACER.reset()


def test_debuglock_order_pipeline_shapes_lock():
    """GL006 cross-check for the pipeline's new lock: the batcher's
    ``_shapes_lock`` (shape claims from the assembly lane vs /stats
    readers) composes acyclically with the observed admission-queue
    condition edges and gridlint's static lock graph."""
    import pathlib

    from freedm_tpu.core.debuglock import DebugLock, LockOrderRecorder
    from freedm_tpu.tools.gridlint import run_lint

    rec = LockOrderRecorder()
    cond_name = "freedm_tpu/serve/queue.py:AdmissionQueue._cond"
    shapes_name = "freedm_tpu/serve/batcher.py:MicroBatcher._shapes_lock"
    svc2 = Service(ServeConfig(max_batch=4, max_wait_ms=2.0, queue_depth=64,
                               buckets=(1, 2, 4), pipeline_depth=2),
                   start=False)
    svc2.queue._cond = threading.Condition(
        lock=DebugLock(cond_name, recorder=rec)
    )
    svc2.batcher._shapes_lock = DebugLock(shapes_name, recorder=rec)
    try:
        svc2.start()
        threads = [
            threading.Thread(
                target=lambda: svc2.request("pf", {"case": "case14"})
            )
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        # /stats takes the shapes lock from a reader thread while the
        # pipeline claims shapes — the canonical concurrent access.
        svc2.stats()
    finally:
        svc2.stop()

    observed = rec.snapshot_edges()
    assert rec.acquisitions > 0
    # The shape claim happens OUTSIDE the queue condition and never
    # takes it back: no edge in either direction may exist.
    assert (shapes_name, cond_name) not in observed
    assert (cond_name, shapes_name) not in observed

    root = pathlib.Path(__file__).resolve().parent.parent
    static = run_lint(
        [str(root / "freedm_tpu" / d) for d in ("serve", "scenarios", "core")],
        root=str(root),
    )
    static_edges = {
        tuple(e) for e in static.artifacts["lock_graph"]["edges"]
    }
    union = observed | static_edges
    from freedm_tpu.core.debuglock import LockOrderRecorder as _R
    assert _R.find_cycle(union) is None, (
        "observed pipeline lock order contradicts the GL006 static graph"
    )


# ---------------------------------------------------------------------------
# GL006 confirmation: observed lock order vs the static lock graph
# ---------------------------------------------------------------------------


def test_debuglock_order_confirms_gl006_static_graph():
    # Instrument the admission queue's condition and the depth gauge's
    # metric-family lock with DebugLocks named by GL006's identity
    # scheme, drive real concurrent traffic, and assert the OBSERVED
    # acquisition order composes acyclically with gridlint's STATIC
    # lock graph — the runtime cross-check of the GL006 analysis.
    import pathlib

    from freedm_tpu.core.debuglock import DebugLock, LockOrderRecorder
    from freedm_tpu.tools.gridlint import run_lint

    rec = LockOrderRecorder()
    gauge = M.SERVE_QUEUE_DEPTH
    old_lock = gauge._lock
    svc2 = Service(ServeConfig(max_batch=4, max_wait_ms=2.0, queue_depth=64,
                               buckets=(1, 2, 4)), start=False)
    cond_name = "freedm_tpu/serve/queue.py:AdmissionQueue._cond"
    metric_name = "freedm_tpu/core/metrics.py:_Metric._lock"
    svc2.queue._cond = threading.Condition(
        lock=DebugLock(cond_name, recorder=rec)
    )
    dbg_metric = DebugLock(metric_name, recursive=True, recorder=rec)
    try:
        gauge._lock = dbg_metric
        for child in gauge._children.values():
            child._lock = dbg_metric
        svc2.start()
        threads = [
            threading.Thread(
                target=lambda: svc2.request("pf", {"case": "case14"})
            )
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        svc2.stop()
        gauge._lock = old_lock
        for child in gauge._children.values():
            child._lock = old_lock

    observed = rec.snapshot_edges()
    assert rec.acquisitions > 0
    # put()/pop() update the depth gauge UNDER the queue condition:
    # that nesting must have been observed...
    assert (cond_name, metric_name) in observed
    # ...and never the reverse (metrics code calling back into serve).
    assert (metric_name, cond_name) not in observed

    root = pathlib.Path(__file__).resolve().parent.parent
    # The modules holding every lock these edges can touch (scanning
    # the subset keeps the static pass fast inside tier-1).
    static = run_lint(
        [str(root / "freedm_tpu" / d) for d in ("serve", "scenarios", "core")],
        root=str(root),
    )
    static_edges = {
        tuple(e) for e in static.artifacts["lock_graph"]["edges"]
    }
    union = observed | static_edges
    assert LockOrderRecorder.find_cycle(union) is None, (
        "observed lock order contradicts the GL006 static graph"
    )
