"""Topology sweep oracles: multi-flip SMW vs dense refactorization,
batched radiality vs host union-find, islanding exclusion, mesh/vmap
byte identity, and exact job resume after a mid-sweep kill."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid.cases import synthetic_mesh
from freedm_tpu.grid.matpower import load_builtin
from freedm_tpu.pf import topo as tp
from freedm_tpu.pf.fdlf import decoupled_parts
from freedm_tpu.pf.n1 import secure_outages


def _host_components(sys_, open_set):
    """Union-find component count over the closed branches (the host
    reference the batched min-label check is pinned against)."""
    parent = list(range(sys_.n_bus))

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    open_set = set(int(s) for s in open_set)
    for j in range(sys_.n_branch):
        if j not in open_set:
            ra, rb = find(int(sys_.from_bus[j])), find(int(sys_.to_bus[j]))
            if ra != rb:
                parent[ra] = rb
    return len({find(i) for i in range(sys_.n_bus)})


def _random_variants(sys_, rng, n, r_max=2):
    """Distinct random open-sets of rank 1..r_max as a slot matrix."""
    rows = []
    seen = set()
    while len(rows) < n:
        r = int(rng.integers(1, r_max + 1))
        combo = tuple(sorted(
            rng.choice(sys_.n_branch, size=r, replace=False).tolist()
        ))
        if combo in seen:
            continue
        seen.add(combo)
        row = np.full(r_max, -1, np.int32)
        row[:len(combo)] = combo
        rows.append(row)
    return np.stack(rows)


class TestEnumeration:
    def test_exhaustive_counts_and_order(self):
        v = tp.enumerate_variants(np.arange(5), 2)
        assert v.shape == (tp.count_exhaustive(5, 2), 2) == (15, 2)
        # Rank ascending, lexicographic within a rank; -1 pads.
        assert v[0].tolist() == [0, -1]
        assert v[4].tolist() == [4, -1]
        assert v[5].tolist() == [0, 1]
        assert v[-1].tolist() == [3, 4]
        # No duplicate open-sets.
        keys = {tuple(sorted(s for s in row if s >= 0)) for row in v}
        assert len(keys) == v.shape[0]

    def test_neighborhood_deterministic_and_distinct(self):
        a = tp.neighborhood_variants(np.arange(30), 3, 50, seed=7)
        b = tp.neighborhood_variants(np.arange(30), 3, 50, seed=7)
        assert np.array_equal(a, b)
        c = tp.neighborhood_variants(np.arange(30), 3, 50, seed=8)
        assert not np.array_equal(a, c)
        keys = {tuple(sorted(s for s in row if s >= 0)) for row in a}
        assert len(keys) == a.shape[0] == 50

    def test_neighborhood_caps_at_space_size(self):
        v = tp.neighborhood_variants(np.arange(4), 1, 100, seed=0)
        assert v.shape[0] == 4  # only 4 rank-1 open-sets exist

    def test_neighborhood_rank_caps_at_switch_count(self):
        # max_rank above the candidate count must cap the DRAW, not
        # crash rng.choice — and the slot width stays the requested
        # rank so the screen's static shape is unaffected.
        v = tp.neighborhood_variants(np.asarray([3]), 2, 5, seed=0)
        assert v.shape == (1, 2)
        assert v[0].tolist() == [3, -1]


class TestScreenOracle:
    """Multi-flip SMW lanes vs per-variant dense refactorization —
    the float64 correctness oracle of the whole screen."""

    def test_smw_matches_dense_refactorization(self, rng):
        sys_ = synthetic_mesh(40, seed=3, load_mw=5.0, chord_frac=1.0)
        m = sys_.n_branch
        ts = tp.make_topo_screen(sys_, r_max=2)
        variants = _random_variants(sys_, rng, 60)
        det = ts.detail(variants, flow_limit=1.0)
        parts = decoupled_parts(sys_, jnp.float64)
        th_free = np.asarray(parts.th_free)
        p0 = np.asarray(sys_.p_inj, np.float64)
        rhs = np.where(th_free > 0, p0, 0.0)
        w = 1.0 / np.asarray(sys_.x, np.float64)
        f = np.asarray(sys_.from_bus)
        t = np.asarray(sys_.to_bus)
        islanded = np.asarray(det.islanded)
        for i in range(variants.shape[0]):
            open_set = [int(s) for s in variants[i] if s >= 0]
            connected = _host_components(sys_, open_set) == 1
            # The SMW singularity flag IS the islanding verdict.
            assert bool(islanded[i]) == (not connected), open_set
            if not connected:
                continue
            status = np.ones(m)
            status[open_set] = 0.0
            b = np.asarray(parts.b_prime(jnp.asarray(status)))
            theta_ref = np.linalg.solve(b, rhs)
            np.testing.assert_allclose(
                np.asarray(det.theta[i]), theta_ref, atol=1e-9
            )
            flows_ref = (theta_ref[f] - theta_ref[t]) * w
            flows_ref[open_set] = 0.0
            np.testing.assert_allclose(
                np.asarray(det.flows[i]), flows_ref, atol=1e-9
            )
            # Objective columns recompute from the reference flows.
            r_series = np.asarray(sys_.r, np.float64)
            assert np.isclose(
                float(det.loss[i]), float(np.sum(r_series * flows_ref**2)),
                atol=1e-9,
            )
            assert np.isclose(
                float(det.worst_flow[i]), float(np.max(np.abs(flows_ref))),
                atol=1e-9,
            )

    def test_rank0_lane_is_base_case(self):
        sys_ = synthetic_mesh(24, seed=1, load_mw=5.0, chord_frac=1.0)
        ts = tp.make_topo_screen(sys_, r_max=2)
        base = ts.detail(np.full((1, 2), -1, np.int32), flow_limit=1.0)
        parts = decoupled_parts(sys_, jnp.float64)
        th_free = np.asarray(parts.th_free)
        rhs = np.where(th_free > 0, np.asarray(sys_.p_inj), 0.0)
        theta_ref = np.linalg.solve(
            np.asarray(parts.b_prime(None)), rhs
        )
        assert not bool(np.asarray(base.islanded)[0])
        np.testing.assert_allclose(
            np.asarray(base.theta[0]), theta_ref, atol=1e-10
        )

    def test_screen_ranking_matches_detail(self, rng):
        sys_ = synthetic_mesh(30, seed=2, load_mw=5.0, chord_frac=1.0)
        ts = tp.make_topo_screen(sys_, r_max=2)
        variants = _random_variants(sys_, rng, 40)
        s = ts.screen(variants, flow_limit=1.0)
        d = ts.detail(variants, flow_limit=1.0)
        for field in ("loss", "worst_flow", "violations", "islanded"):
            np.testing.assert_array_equal(
                np.asarray(getattr(s, field)),
                np.asarray(getattr(d, field)),
            )

    def test_shared_lu_matches_own_factorization(self):
        # The serving-cache seam: an adopted B' LU pair must produce
        # bit-identical screens to a self-factorized one.
        sys_ = synthetic_mesh(24, seed=5, load_mw=5.0, chord_frac=1.0)
        parts = decoupled_parts(sys_, jnp.float64)
        with jax.default_matmul_precision("highest"):
            lu = jax.scipy.linalg.lu_factor(parts.b_prime(None))
        own = tp.make_topo_screen(sys_, r_max=1)
        shared = tp.make_topo_screen(sys_, r_max=1, lu=lu)
        variants = tp.enumerate_variants(np.arange(sys_.n_branch), 1)
        a = own.screen(variants, flow_limit=1.0)
        b = shared.screen(variants, flow_limit=1.0)
        np.testing.assert_array_equal(np.asarray(a.loss),
                                      np.asarray(b.loss))
        np.testing.assert_array_equal(np.asarray(a.islanded),
                                      np.asarray(b.islanded))


class TestRadiality:
    def test_connectivity_matches_union_find(self, rng):
        sys_ = synthetic_mesh(30, seed=4, load_mw=5.0, chord_frac=0.3)
        check = tp.make_radiality_check(sys_, r_max=3)
        variants = _random_variants(sys_, rng, 80, r_max=3)
        rr = check(variants)
        conn = np.asarray(rr.connected)
        rad = np.asarray(rr.radial)
        n, m = sys_.n_bus, sys_.n_branch
        for i in range(variants.shape[0]):
            open_set = [int(s) for s in variants[i] if s >= 0]
            comps = _host_components(sys_, open_set)
            assert bool(conn[i]) == (comps == 1), open_set
            want_radial = comps == 1 and (m - len(open_set)) == n - 1
            assert bool(rad[i]) == want_radial, open_set

    def test_radial_detects_spanning_tree(self):
        # A ring of n buses has m == n: opening exactly one branch
        # leaves a spanning tree (radial); opening none leaves a mesh.
        sys_ = synthetic_mesh(12, seed=0, load_mw=5.0, chord_frac=0.0)
        assert sys_.n_branch == sys_.n_bus  # the ring
        check = tp.make_radiality_check(sys_, r_max=2)
        slots = np.full((2, 2), -1, np.int32)
        slots[1, 0] = 3  # open one ring branch
        rr = check(slots)
        conn = np.asarray(rr.connected)
        rad = np.asarray(rr.radial)
        assert conn.tolist() == [True, True]
        assert rad.tolist() == [False, True]

    def test_bridge_outage_flags_both_checks(self):
        sys_ = load_builtin("case14")
        bridges = sorted(
            set(range(sys_.n_branch)) - set(secure_outages(sys_))
        )
        assert bridges, "case14 should have at least one bridge"
        check = tp.make_radiality_check(sys_, r_max=2)
        ts = tp.make_topo_screen(sys_, r_max=2)
        slots = np.full((len(bridges), 2), -1, np.int32)
        slots[:, 0] = bridges
        rr = check(slots)
        res = ts.screen(slots, flow_limit=1.0)
        assert not np.asarray(rr.connected).any()
        # The SMW singular-capacitance backstop agrees lane for lane.
        assert np.asarray(res.islanded).all()
        assert np.isinf(np.asarray(
            tp.select_objective(res, "loss")
        )).all()


class TestMeshByteIdentity:
    def test_mesh_screen_equals_vmap_screen(self, devices8):
        from freedm_tpu.parallel.mesh import solver_mesh

        sys_ = synthetic_mesh(24, seed=6, load_mw=5.0, chord_frac=1.0)
        mesh = solver_mesh(4)
        plain = tp.make_topo_screen(sys_, r_max=2)
        sharded = tp.make_topo_screen(sys_, r_max=2, mesh=mesh)
        variants = tp.enumerate_variants(np.arange(sys_.n_branch), 2)
        a = plain.screen(variants, flow_limit=1.0)
        b = sharded.screen(variants, flow_limit=1.0)
        for field in ("loss", "worst_flow", "violations", "islanded"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, field)),
                np.asarray(getattr(b, field)),
                err_msg=field,
            )

    def test_mesh_handles_ragged_lane_counts(self, devices8):
        from freedm_tpu.parallel.mesh import solver_mesh

        sys_ = synthetic_mesh(24, seed=6, load_mw=5.0, chord_frac=1.0)
        sharded = tp.make_topo_screen(sys_, r_max=1,
                                      mesh=solver_mesh(4))
        variants = tp.enumerate_variants(np.arange(7), 1)  # 7 lanes
        r = sharded.screen(variants, flow_limit=1.0)
        assert np.asarray(r.loss).shape == (7,)


class TestTopkMerge:
    def test_merge_is_chunking_invariant(self):
        rng = np.random.default_rng(3)
        obj = rng.uniform(0, 1, 100)
        obj[rng.choice(100, 10, replace=False)] = np.inf
        slots = rng.integers(0, 20, (100, 2)).astype(np.int32)
        gid = np.arange(100, dtype=np.int32)
        merge = tp.make_topk_merge(2, 8)

        def run(chunk):
            best = merge.init()
            for v0 in range(0, 100, chunk):
                best = merge(*best, jnp.asarray(obj[v0:v0 + chunk]),
                             jnp.asarray(slots[v0:v0 + chunk]),
                             jnp.asarray(gid[v0:v0 + chunk]))
            return [np.asarray(b).tolist() for b in best]

        assert run(10) == run(25) == run(100)
        # And it is genuinely the global top-8 by objective.
        best = run(100)
        want = np.sort(obj)[:8].tolist()
        np.testing.assert_allclose(best[0], want)

    def test_merge_ties_keep_lowest_gid(self):
        merge = tp.make_topk_merge(1, 2)
        best = merge.init()
        obj = jnp.asarray([0.5, 0.5, 0.5])
        slots = jnp.asarray([[0], [1], [2]], jnp.int32)
        gid = jnp.asarray([10, 11, 12], jnp.int32)
        out = merge(*best, obj, slots, gid)
        assert np.asarray(out[2]).tolist() == [10, 11]


class TestSweep:
    def test_islanded_variants_never_reach_ac_verify(self):
        sys_ = load_builtin("case14")
        bridges = set(range(sys_.n_branch)) - set(secure_outages(sys_))
        s = tp.run_topo_sweep(tp.TopoSweepSpec(
            case="case14", max_rank=2, chunk_variants=128, top_k=6,
        ))
        assert s["completed"]
        # 'islanded' counts SMW-backstop-ONLY exclusions: the
        # structural check catches every case14 islanding variant
        # first, so the backstop has nothing left to fire on alone.
        assert s["disconnected"] > 0 and s["islanded"] == 0
        assert s["shortlist"], "no feasible variant survived?"
        for e in s["shortlist"]:
            assert not (set(e["open_branches"]) & bridges), e
            assert e["ac_converged"]
            assert e["ac_true_mismatch_pu"] < 1e-6
        # Ranking is ascending in the objective.
        objs = [e["objective"] for e in s["shortlist"]]
        assert objs == sorted(objs)

    def test_sweep_resume_exact_after_midsweep_kill(self, tmp_path):
        ck = str(tmp_path / "topo.json")
        spec = tp.TopoSweepSpec(case="case14", max_rank=2,
                                chunk_variants=48, top_k=4,
                                ac_verify=False)
        part = tp.run_topo_sweep(spec, checkpoint_path=ck,
                                 stop_after_chunks=2)
        assert part["completed"] is False and part["chunks_done"] == 2
        resumed = tp.run_topo_sweep(spec, checkpoint_path=ck)
        assert resumed["resumed_from_chunk"] == 2
        ref = tp.run_topo_sweep(spec)
        assert tp.strip_topo_timing(resumed) == tp.strip_topo_timing(ref)

    def test_sweep_chunking_invariant(self):
        a = tp.run_topo_sweep(tp.TopoSweepSpec(
            case="case14", max_rank=2, chunk_variants=32,
            ac_verify=False,
        ))
        b = tp.run_topo_sweep(tp.TopoSweepSpec(
            case="case14", max_rank=2, chunk_variants=128,
            ac_verify=False,
        ))
        assert (tp.strip_topo_timing({**a, "chunks_total": 0})
                == tp.strip_topo_timing({**b, "chunks_total": 0}))

    def test_checkpoint_spec_mismatch_restarts_clean(self, tmp_path):
        ck = str(tmp_path / "topo.json")
        tp.run_topo_sweep(tp.TopoSweepSpec(
            case="case14", max_rank=1, chunk_variants=64,
            ac_verify=False,
        ), checkpoint_path=ck)
        # A different spec must ignore the stale checkpoint.
        s = tp.run_topo_sweep(tp.TopoSweepSpec(
            case="case14", max_rank=2, chunk_variants=64,
            ac_verify=False,
        ), checkpoint_path=ck)
        assert s["resumed_from_chunk"] == 0 and s["completed"]

    def test_validate_sweep_spec_typed_errors(self):
        with pytest.raises(ValueError, match="objective"):
            tp.run_topo_sweep(tp.TopoSweepSpec(case="case14",
                                               objective="nope"))
        with pytest.raises(ValueError, match="flow_limit"):
            tp.run_topo_sweep(tp.TopoSweepSpec(
                case="case14", objective="violations", flow_limit=0.0,
            ))
        with pytest.raises(ValueError, match="switch indices"):
            tp.run_topo_sweep(tp.TopoSweepSpec(
                case="case14", switches=(0, 999),
            ))
        with pytest.raises(ValueError, match="samples"):
            tp.run_topo_sweep(tp.TopoSweepSpec(
                case="case14", search="neighborhood", samples=0,
            ))


class TestServeTopo:
    @pytest.fixture(scope="class")
    def service(self):
        from freedm_tpu.serve import ServeConfig, Service

        svc = Service(ServeConfig(max_batch=4, buckets=(1, 4),
                                  topo_top_k=4))
        yield svc
        svc.stop()

    def test_sync_roundtrip_and_accounting(self, service):
        resp = service.request("topo", {
            "case": "case14", "max_rank": 2, "top_k": 3,
            "timeout_s": 300,
        })
        assert resp.workload == "topo" and resp.n_variants == 210
        assert (resp.n_feasible + resp.n_disconnected
                + resp.n_nonradial + resp.n_islanded) == resp.n_variants
        assert resp.n_disconnected > 0 and resp.n_islanded == 0
        assert resp.shortlist and resp.all_verified
        assert len(resp.shortlist) == 3
        sys_ = load_builtin("case14")
        bridges = set(range(sys_.n_branch)) - set(secure_outages(sys_))
        for e in resp.shortlist:
            assert not (set(e["open_branches"]) & bridges)
            assert e["ac_converged"] and e["ac_residual_pu"] < 1e-6

    def test_small_variant_count_below_topk_cap(self, service):
        # 2 variants under a 4-deep shortlist cap: lax.top_k must run
        # at the lane count and the shortlist just comes back short.
        resp = service.request("topo", {
            "case": "case14", "switches": [0, 1], "max_rank": 1,
            "top_k": 4, "timeout_s": 300,
        })
        assert resp.n_variants == 2
        assert len(resp.shortlist) == resp.n_feasible == 2
        assert resp.all_verified

    def test_sync_matches_sweep_ranking(self, service):
        resp = service.request("topo", {
            "case": "case14", "max_rank": 2, "top_k": 3,
            "timeout_s": 300,
        })
        sweep = tp.run_topo_sweep(tp.TopoSweepSpec(
            case="case14", max_rank=2, top_k=3, chunk_variants=64,
            ac_verify=False,
        ))
        assert ([e["open_branches"] for e in resp.shortlist]
                == [e["open_branches"] for e in sweep["shortlist"]])

    def test_validation_typed_errors(self, service):
        from freedm_tpu.serve import InvalidRequest

        bad = [
            {"case": "case14", "objective": "nope"},
            {"case": "case14", "mode": "nope"},
            {"case": "case14", "max_rank": 99},
            {"case": "case14", "top_k": 99},
            {"case": "case14", "switches": [0, 0]},
            {"case": "case14", "switches": [999]},
            {"case": "case14", "search": "neighborhood", "samples": 0},
            {"case": "case14", "objective": "violations",
             "flow_limit": 0.0},
            {"case": "case14", "unknown_field": 1},
        ]
        for payload in bad:
            with pytest.raises(InvalidRequest):
                service.request("topo", payload)

    def test_radial_mode_counts_nonradial(self, service):
        resp = service.request("topo", {
            "case": "case14", "max_rank": 1, "mode": "radial",
            "timeout_s": 300,
        })
        # case14 is meshed: opening ONE branch cannot reach a spanning
        # tree, so every connected variant is excluded as non-radial.
        assert resp.n_feasible == 0
        assert (resp.n_nonradial + resp.n_disconnected
                + resp.n_islanded == resp.n_variants)
        assert resp.shortlist == []


class TestTopoJobs:
    def test_job_lifecycle_and_resume_metadata(self, tmp_path):
        import time as _time

        from freedm_tpu.scenarios.jobs import JobManager
        from freedm_tpu.serve.queue import InvalidRequest, NotFound

        jm = JobManager(workers=1,
                        checkpoint_dir=str(tmp_path)).start()
        try:
            out = jm.submit_topo({
                "case": "case14", "max_rank": 2, "chunk_variants": 64,
                "job_key": "t1", "ac_verify": False,
            })
            assert out["kind"] == "topo" and out["state"] == "queued"
            assert out["chunks_total"] == 4
            deadline = _time.monotonic() + 240
            while _time.monotonic() < deadline:
                j = jm.get(out["job_id"])
                if j["state"] in ("completed", "failed", "cancelled"):
                    break
                _time.sleep(0.1)
            assert j["state"] == "completed", j
            assert j["summary"]["variants_total"] == 210
            assert (tmp_path / "topo_t1.json").exists()
            with pytest.raises(NotFound):
                jm.get("nope")
            with pytest.raises(InvalidRequest):
                jm.submit_topo({"case": "case14", "objective": "nope"})
            with pytest.raises(InvalidRequest):
                jm.submit_topo({"case": "case14", "bogus": 1})
            with pytest.raises(InvalidRequest):
                jm.submit_topo({"case": "case14", "chunk_variants": 1})
        finally:
            jm.stop()
