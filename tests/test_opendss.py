"""OpenDSS text-protocol adapter tests (VERDICT r3 item 7).

A scripted fake OpenDSS TCP server serves the reference's text blobs
("Bus : 1,Node1 : 2,…", ``COpenDssAdapter.cpp``) and records the text
commands written back — including the VVC hook: a VVC round reading
Pload values from the adapter and scattering Q setpoints as text
(``vvc/VoltVarCtrl.cpp:334-336``).
"""

import socket
import threading
import time

import numpy as np
import pytest

from freedm_tpu.devices.adapters.opendss import (
    OpenDssAdapter,
    format_pairs,
    parse_pairs,
)
from freedm_tpu.devices.manager import DeviceManager


class FakeOpenDss:
    """Scripted server: sends a state blob per connection read cycle,
    records every received command line."""

    def __init__(self, state_text):
        self.state_text = state_text
        self.commands = []
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(1)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            sock.settimeout(0.2)
            buf = ""
            try:
                while not self._stop.is_set():
                    # Push the current state blob (newline-framed), then
                    # drain commands.
                    sock.sendall((self.state_text + "\n").encode())
                    try:
                        data = sock.recv(4096)
                        if not data:
                            break
                        buf += data.decode()
                        while "\n" in buf:
                            line, _, buf = buf.partition("\n")
                            if line.strip():
                                self.commands.append(line.strip())
                    except socket.timeout:
                        pass
            except OSError:
                pass
            finally:
                sock.close()

    def stop(self):
        self._stop.set()
        self._srv.close()


def wait_for(cond, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def test_parse_and_format_pairs():
    pairs = parse_pairs("Bus : 1,Node1 : 2,Basekv : 88.88,junk,bad : x")
    assert pairs == [("Bus", 1.0), ("Node1", 2.0), ("Basekv", 88.88)]
    assert format_pairs([("A.b", 1.5)]) == "A.b : 1.5"


def test_state_read_and_command_write_cycle():
    srv = FakeOpenDss("Mag1 : 7088.5,Angle1 : -2.0")
    manager = DeviceManager()
    a = OpenDssAdapter("127.0.0.1", srv.port, poll_s=0.01)
    manager.add_device("BUS1", "Sst", a)
    a.bind_state("BUS1", "gateway", 0)
    a.bind_command("BUS1", "gateway", 0)
    try:
        a.start()
        # Reveal is deferred until the first good exchange.
        assert wait_for(lambda: a.revealed)
        assert manager.get_state("BUS1", "gateway") == pytest.approx(7088.5)
        # A command goes out as a text pair.
        manager.set_command("BUS1", "gateway", 42.0)
        assert wait_for(lambda: any("BUS1.gateway : 42.0" == c for c in srv.commands))
        assert a.error is None
    finally:
        a.stop()
        srv.stop()


def test_unreachable_server_latches_error():
    a = OpenDssAdapter("127.0.0.1", 1, poll_s=0.01, socket_timeout_s=0.2)
    a.bind_state("X", "gateway", 0)
    a.start()
    assert wait_for(lambda: a.error is not None)
    assert not a.revealed
    a.stop()


def test_short_state_blob_is_skipped_not_fatal():
    srv = FakeOpenDss("OnlyOne : 5.0")
    a = OpenDssAdapter("127.0.0.1", srv.port, poll_s=0.01)
    a.bind_state("D", "gateway", 0)
    a.bind_state("D", "storage", 1)  # needs 2 values, server sends 1
    try:
        a.start()
        time.sleep(0.2)
        assert a.error is None  # tolerated, just skipped
        assert not a.revealed  # never initialized
    finally:
        a.stop()
        srv.stop()


def test_vvc_hook_reads_opendss_and_scatters_q():
    """The reference pokes OpenDSS from the VVC agent
    (VoltVarCtrl.cpp:334-336); here the hook is structural: Pload/Sst_x
    devices on an opendss adapter make the VVC phase consume the text
    data and actuate text commands."""
    from freedm_tpu.grid import cases
    from freedm_tpu.runtime import Fleet, NodeHandle, VvcModule, build_broker

    feeder = cases.vvc_9bus()
    # Serve Pload readings for row 3 (differ from the defaults so the
    # staleness sentinel passes them through), plus a Q device row.
    srv = FakeOpenDss("Pl3_a : 55.0,Pl3_b : 66.0,Pl3_c : 77.0")
    manager = DeviceManager()
    a = OpenDssAdapter("127.0.0.1", srv.port, poll_s=0.01)
    for i, ph in enumerate("abc"):
        manager.add_device(f"Pl3_{ph}", f"Pload_{ph}", a)
        a.bind_state(f"Pl3_{ph}", "pload", i)
        manager.add_device(f"Q4_{ph}", f"Sst_{ph}", a)
        a.bind_state(f"Q4_{ph}", "gateway", 3 + i)
        a.bind_command(f"Q4_{ph}", "gateway", i)
    srv.state_text = (
        "Pl3_a : 55.0,Pl3_b : 66.0,Pl3_c : 77.0,"
        "Q4_a : 0.0,Q4_b : 0.0,Q4_c : 0.0"
    )
    try:
        a.start()
        assert wait_for(lambda: a.revealed)
        fleet = Fleet([NodeHandle("n0:50860", manager)])
        vvc = VvcModule(fleet, feeder)
        broker = build_broker(fleet, extra_modules=[vvc])
        broker.run(n_rounds=3)
        # The live Pload readings were consumed (not flagged stale) and
        # the VVC actuated row 4's Q devices.
        assert vvc.rounds == 3
        q = np.asarray(vvc.q_kvar)
        assert np.abs(q[4]).sum() > 0.0
        # The Q setpoints crossed the wire as text commands.
        assert wait_for(lambda: any(c.startswith("Q4_") for c in srv.commands))
    finally:
        a.stop()
        srv.stop()


def test_segmented_stream_does_not_corrupt_state():
    """A blob split across TCP segments must not install truncated
    values ("Mag1 : 70" from "Mag1 : 7088.5") — only complete
    newline-framed lines are consumed."""

    class SegmentingServer(FakeOpenDss):
        def _serve(self):
            sock, _ = self._srv.accept()
            sock.settimeout(0.2)
            try:
                # One blob, deliberately split mid-float.
                sock.sendall(b"Mag1 : 70")
                time.sleep(0.15)
                sock.sendall(b"88.5,Angle1 : -2.0\n")
                while not self._stop.is_set():
                    time.sleep(0.05)
            except OSError:
                pass
            finally:
                sock.close()

    srv = SegmentingServer("")
    a = OpenDssAdapter("127.0.0.1", srv.port, poll_s=0.01)
    a.bind_state("BUS1", "gateway", 0)
    a.bind_state("BUS1", "storage", 1)
    try:
        a.start()
        assert wait_for(lambda: a.revealed)
        # The truncated "70" was never installed; the full value was.
        assert a.get_state("BUS1", "gateway") == pytest.approx(7088.5)
        assert a.get_state("BUS1", "storage") == pytest.approx(-2.0)
    finally:
        a.stop()
        srv.stop()
