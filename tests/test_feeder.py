"""Feeder data-model tests: Dl parsing, relabeling, subtree compilation."""

import numpy as np
import pytest

from freedm_tpu.grid import cases, from_branch_table, load_dl_mat


def test_9bus_structure():
    f = cases.vvc_9bus()
    assert f.n_branches == 8
    assert f.n_nodes == 9
    # Main: 0-1-2-3-4-5, lateral: 1-6-7-8 (load_system_data.cpp topology).
    assert f.from_node.tolist() == [0, 1, 2, 3, 4, 1, 6, 7]
    assert f.parent.tolist() == [-1, 0, 1, 2, 3, 0, 5, 6]
    assert f.levels == 5  # longest chain 0→1→2→3→4→5 has depth 4
    # Subtree of the transformer branch (0) contains every branch.
    assert f.subtree[0].sum() == 8
    # Subtree of branch feeding node 6 (index 5) = branches 5,6,7.
    assert f.subtree[5].tolist() == [0, 0, 0, 0, 0, 1, 1, 1]
    # Path to node 8 = branches 0,5,6,7 (column of subtree).
    assert f.subtree[:, 7].tolist() == [1, 0, 0, 0, 0, 1, 1, 1]
    assert f.phase_mask.min() == 1.0  # all phases present


def test_transformer_branch_decoupled():
    f = cases.vvc_9bus()
    z0 = f.z_pu[0]
    assert np.count_nonzero(z0 - np.diag(np.diag(z0))) == 0  # diagonal
    z1 = f.z_pu[1]
    assert abs(z1[0, 1]) > 0  # feeder lines have mutual coupling


def test_duplicate_rbus_rejected():
    dl = np.zeros((2, 13))
    dl[0] = [1, 0, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]
    dl[1] = [2, 0, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]
    with pytest.raises(ValueError, match="duplicate receiving bus"):
        from_branch_table(dl, cases.Z_CODES_9BUS)


def test_unknown_sbus_rejected():
    dl = np.zeros((1, 13))
    dl[0] = [1, 7, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]
    with pytest.raises(ValueError, match="source bus"):
        from_branch_table(dl, cases.Z_CODES_9BUS)


def test_dl_roundtrip():
    f = cases.vvc_9bus()
    dl = f.to_dl()
    f2 = from_branch_table(dl, np.stack([f.z_pu[i] * f.z_base_ohm for i in range(8)]))
    # Same topology after round-trip (z codes re-expanded per branch).
    assert f2.parent.tolist() == f.parent.tolist()
    np.testing.assert_allclose(f2.s_load, f.s_load)


def test_load_reference_dl_new():
    from refdata import resolve

    f = load_dl_mat(resolve("Dl_new.mat", "/root/reference/Broker/Dl_new.mat"))
    assert f.n_branches == 33
    assert f.levels > 5  # deep feeder with laterals
    # Non-contiguous laterals relabeled: every parent valid.
    assert (f.parent >= -1).all() and (f.parent < f.n_branches).all()


def test_out_of_order_rows():
    """A child row listed before its parent must still compile correctly
    (regression: depth/phase-mask propagation once assumed parent-first)."""
    z = cases.Z_CODES_9BUS
    dl = np.zeros((3, 13))
    dl[0] = [1, 5, 7, 1, 1, 1, 10, 0, 10, 0, 10, 0, 0]  # child of node 5
    dl[1] = [2, 0, 5, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]  # root branch
    dl[2] = [3, 7, 9, 1, 1, 1, 5, 0, 5, 0, 5, 0, 0]  # grandchild
    f = from_branch_table(dl, z)
    assert f.phase_mask.min() == 1.0  # every phase reachable
    assert f.depth.tolist() == [1, 0, 2]
    assert f.levels == 3


def test_cycle_rejected():
    z = cases.Z_CODES_9BUS
    dl = np.zeros((3, 13))
    dl[0] = [1, 0, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]
    dl[1] = [2, 3, 2, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]  # 3 -> 2
    dl[2] = [3, 2, 3, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]  # 2 -> 3 (cycle)
    with pytest.raises(ValueError, match="cycle or disconnected"):
        from_branch_table(dl, z)


def test_synthetic_radial_deterministic():
    f1 = cases.synthetic_radial(256, seed=7)
    f2 = cases.synthetic_radial(256, seed=7)
    assert f1.n_branches == 256
    np.testing.assert_array_equal(f1.parent, f2.parent)
    np.testing.assert_allclose(f1.s_load, f2.s_load)
    # Subtree of the first branch spans everything fed through it.
    assert f1.subtree.sum(axis=1).max() <= 256
