"""Causal distributed tracing tests (``freedm_tpu.core.tracing`` +
``tools/trace_report.py``).

Covers: span recorder semantics and the disabled-by-default no-op path;
wire propagation across the SR protocol (a dropped-then-retransmitted
frame yields exactly one recv/handler span, parented to the original
send span); broker round/phase spans with timer annotations and overrun
tags; solver spans tagging the jit-compile hit; the skew-corrected
timeline reconstructor; and a 3-node fleet traced end-to-end across OS
processes with deliberately skewed host clocks.
"""

import json
import subprocess
import sys
import textwrap
import time

import pytest

from freedm_tpu.core import tracing
from freedm_tpu.dcn.protocol import SrChannel
from freedm_tpu.runtime.broker import Broker
from freedm_tpu.runtime.messages import ModuleMessage
from freedm_tpu.runtime.module import DgiModule
from freedm_tpu.tools import trace_report


@pytest.fixture
def traced(tmp_path):
    """Enable the process tracer for one test; hard-reset afterwards so
    the rest of the suite runs on the disabled no-op path."""
    tracing.TRACER.configure(
        enabled=True, node="test:1", path=str(tmp_path / "trace.jsonl")
    )
    yield tracing.TRACER
    tracing.TRACER.reset()


# ---------------------------------------------------------------------------
# recorder semantics
# ---------------------------------------------------------------------------


def test_disabled_tracer_is_noop():
    assert not tracing.TRACER.enabled
    s = tracing.TRACER.start("anything", kind="x", tags={"a": 1})
    assert s is tracing.NOOP
    s.tag(b=2).annotate("ev")
    s.end()
    assert s.context() is None
    assert len(tracing.TRACER) == 0


def test_span_tree_ring_and_file_export(traced, tmp_path):
    with traced.start("outer", kind="round", tags={"round": 7}) as outer:
        inner = traced.start("inner", kind="phase")  # implicit parent: outer
        inner.annotate("tick", n=1)
        inner.end()
    recs = traced.tail()
    assert [r["name"] for r in recs] == ["inner", "outer"]  # ended in order
    by_name = {r["name"]: r for r in recs}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["inner"]["trace_id"] == by_name["outer"]["trace_id"]
    assert by_name["inner"]["events"][0]["name"] == "tick"
    assert by_name["outer"]["tags"] == {"round": 7}
    assert by_name["outer"]["node"] == "test:1"
    assert all(r["t1"] >= r["t0"] for r in recs)
    # The JSONL export carries the same records.
    on_disk = [
        json.loads(l)
        for l in (tmp_path / "trace.jsonl").read_text().splitlines()
    ]
    assert on_disk == recs
    # trace_id filter on the flight recorder.
    assert traced.tail(trace_id=by_name["outer"]["trace_id"]) == recs
    assert traced.tail(trace_id="nope") == []


def test_trace_file_rotates_once_past_max_bytes(tmp_path):
    t = tracing.Tracer(max_bytes=800)
    t.configure(enabled=True, node="n", path=str(tmp_path / "t.jsonl"))
    for i in range(40):
        t.start(f"span{i}", kind="x", tags={"pad": "y" * 10}).end()
    t.close()
    assert (tmp_path / "t.jsonl.1").exists(), "rotation never happened"
    recs = [
        json.loads(l) for l in (tmp_path / "t.jsonl").read_text().splitlines()
    ]
    assert recs and recs[-1]["name"] == "span39"


def test_clock_records_are_deduplicated(traced):
    traced.record_clock_offset(0.25)
    traced.record_clock_offset(0.25)  # unchanged: no second record
    traced.record_clock_offset(-0.1)
    clocks = [r for r in traced.tail() if r.get("rec") == "clock"]
    assert [c["offset_s"] for c in clocks] == [0.25, -0.1]
    assert all(c["node"] == "test:1" for c in clocks)


# ---------------------------------------------------------------------------
# wire propagation: dropped-then-retransmitted frame, sans-IO
# ---------------------------------------------------------------------------


def msg(i):
    return ModuleMessage("lb", "draft_request", {"i": i}, source="hostA:1")


def test_retransmitted_frame_yields_one_recv_span_linked_to_send(traced):
    a = SrChannel("hostB:2", resend_time_s=0.05, ttl_s=60.0, src_uuid="hostA:1")
    b = SrChannel("hostA:1", resend_time_s=0.05, ttl_s=60.0, src_uuid="hostB:2")
    a.send(msg(0), 0.0)
    a.poll(0.0)  # first transmission: eaten by the wire
    frames = a.poll(0.1)  # retransmission
    delivered = b.accept_frames(frames, 0.1)
    assert [m.payload["i"] for m in delivered] == [0]
    # The same frames arrive again (duplicate datagram): no new span.
    assert b.accept_frames([f for f in frames if f.msg is not None], 0.1) == []
    a.accept_frames(b.poll(0.1), 0.1)  # ACKs retire the window
    recs = traced.tail()
    sends = [r for r in recs if r["kind"] == "send"]
    recvs = [r for r in recs if r["kind"] == "recv"]
    assert len(sends) == 1 and len(recvs) == 1
    assert recvs[0]["parent_id"] == sends[0]["span_id"]
    assert recvs[0]["trace_id"] == sends[0]["trace_id"]
    # The send span saw its retransmission, and its ACK (with an RTT).
    assert any(e["name"] == "retransmit" for e in sends[0]["events"])
    assert sends[0]["tags"]["acked"] is True
    assert sends[0]["tags"]["rtt_s"] >= 0.0
    # The delivered message's context now points at the recv span, so a
    # downstream handler span chains send → recv → handler.
    assert delivered[0].trace["span_id"] == recvs[0]["span_id"]


def test_expired_send_span_is_tagged(traced):
    a = SrChannel("hostB:2", resend_time_s=0.05, ttl_s=0.2, src_uuid="hostA:1")
    b = SrChannel("hostA:1", resend_time_s=0.05, ttl_s=0.2, src_uuid="hostB:2")
    a.send(msg(0), 0.0)
    b.accept_frames(a.poll(0.0), 0.0)  # SYN + msg 0 delivered...
    a.accept_frames(b.poll(0.0), 0.0)  # ...and ACKed: channel synced
    a.send(msg(1), 0.1)
    a.poll(0.1)  # transmitted once, eaten by the wire
    a.poll(1.0)  # long past the TTL: the message dies at the sender
    sends = {r["tags"]["seq"]: r for r in traced.tail() if r["kind"] == "send"}
    expired = [s for s in sends.values() if s["tags"].get("expired")]
    assert len(expired) == 1
    assert "acked" not in expired[0]["tags"]
    assert expired[0]["tags"]["type"] == "draft_request"


def test_handler_span_parents_to_wire_context(traced):
    class Sink(DgiModule):
        name = "lb"

        def run_phase(self, ctx):
            pass

        def handle_message(self, m, ctx=None):
            pass

    broker = Broker()
    broker.register_module(Sink(), 10)
    ctx = {"trace_id": "feedfacefeedface", "span_id": "abadcafe00000000"}
    broker.deliver(
        ModuleMessage("lb", "ping", {"x": 1}, source="hostB:2", trace=ctx)
    )
    broker.run_round()
    handlers = [r for r in traced.tail() if r["kind"] == "handler"]
    assert len(handlers) == 1
    assert handlers[0]["trace_id"] == "feedfacefeedface"
    assert handlers[0]["parent_id"] == "abadcafe00000000"
    assert handlers[0]["tags"]["module"] == "lb"
    assert handlers[0]["name"] == "handle:ping"
    # Dispatch-to-execution wait of the phase-queued handler.
    assert handlers[0]["tags"]["queue_ms"] >= 0.0


# ---------------------------------------------------------------------------
# broker round/phase spans
# ---------------------------------------------------------------------------


def test_round_phase_spans_overrun_tags_and_timer_annotations(traced):
    class Slow(DgiModule):
        name = "slow"

        def run_phase(self, ctx):
            time.sleep(0.02)

    broker = Broker()
    broker.register_module(Slow(), 1)  # 1 ms budget: guaranteed overrun
    timer = broker.allocate_timer("slow")
    broker.schedule_timer(timer, 0.0, lambda: None)
    broker.run_round()
    recs = traced.tail()
    rounds = [r for r in recs if r["kind"] == "round"]
    phases = [r for r in recs if r["kind"] == "phase"]
    assert len(rounds) == 1 and len(phases) == 1
    ph = phases[0]
    assert ph["parent_id"] == rounds[0]["span_id"]
    assert ph["name"] == "phase:slow"
    assert ph["tags"]["overrun"] is True and ph["tags"]["overrun_ms"] > 0
    assert ph["tags"]["phase_ms"] >= 20.0
    fired = [e for e in ph.get("events", ()) if e["name"] == "timer_fired"]
    assert len(fired) == 1 and fired[0]["handle"] == timer


def test_crashing_phase_still_lands_in_flight_recorder(traced):
    class Boom(DgiModule):
        name = "boom"

        def run_phase(self, ctx):
            raise RuntimeError("kaput")

    broker = Broker()
    broker.register_module(Boom(), 10)
    with pytest.raises(RuntimeError, match="kaput"):
        broker.run_round()
    recs = traced.tail()
    phases = [r for r in recs if r["kind"] == "phase"]
    rounds = [r for r in recs if r["kind"] == "round"]
    assert len(phases) == 1 and "kaput" in phases[0]["tags"]["error"]
    assert len(rounds) == 1 and rounds[0]["tags"]["error"] is True
    assert phases[0]["parent_id"] == rounds[0]["span_id"]


def test_loopback_message_handler_parents_to_phase_span(traced):
    class Echo(DgiModule):
        name = "gm"

        def run_phase(self, ctx):
            pass

        def handle_message(self, m, ctx=None):
            pass

    broker = Broker()
    broker.register_module(Echo(), 10)
    broker.deliver(ModuleMessage("gm", "hello", {}, source="x"))  # no trace ctx
    broker.run_round()
    recs = traced.tail()
    phases = {r["span_id"]: r for r in recs if r["kind"] == "phase"}
    handlers = [r for r in recs if r["kind"] == "handler"]
    assert len(handlers) == 1
    # Queued before the round: it executes inside the gm phase span.
    assert handlers[0]["parent_id"] in phases


# ---------------------------------------------------------------------------
# solver spans
# ---------------------------------------------------------------------------


def test_solver_spans_tag_jit_compile_hit(traced):
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver

    sys_ = synthetic_mesh(10, seed=0, load_mw=1.0, chord_frac=1.0)
    solve, _ = make_newton_solver(sys_)
    solve()
    solve()
    solves = [r for r in traced.tail() if r["kind"] == "solve"]
    assert [s["tags"]["jit_compile"] for s in solves] == [True, False]
    assert all(s["name"] == "pf.solve:newton" for s in solves)
    # The compile-hit span dwarfs the steady-state dispatch span.
    d0 = solves[0]["t1"] - solves[0]["t0"]
    d1 = solves[1]["t1"] - solves[1]["t0"]
    assert d0 > d1


def test_late_enabled_tracer_does_not_mislabel_compile_hit(tmp_path):
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver

    sys_ = synthetic_mesh(10, seed=0, load_mw=1.0, chord_frac=1.0)
    solve, _ = make_newton_solver(sys_)
    solve()  # the real jit compile happens here, untraced
    tracing.TRACER.configure(enabled=True, node="late:1")
    try:
        solve()
        solves = [r for r in tracing.TRACER.tail() if r["kind"] == "solve"]
        assert len(solves) == 1
        assert solves[0]["tags"]["jit_compile"] is False  # warm dispatch
    finally:
        tracing.TRACER.reset()


def test_solver_under_vmap_records_no_bogus_spans(traced):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver

    sys_ = synthetic_mesh(10, seed=0, load_mw=1.0, chord_frac=1.0)
    _, solve_fixed = make_newton_solver(sys_, max_iter=4)
    scale = np.random.default_rng(0).uniform(0.9, 1.1, (3, 1))
    p = jnp.asarray(scale * np.asarray(sys_.p_inj)[None, :])
    q = jnp.asarray(scale * np.asarray(sys_.q_inj)[None, :])
    before = len([r for r in traced.tail() if r["kind"] == "solve"])
    jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi))(p, q)
    after = len([r for r in traced.tail() if r["kind"] == "solve"])
    assert after == before  # transformation traces record nothing


# ---------------------------------------------------------------------------
# trace_report: merge, clock correction, critical path, overruns
# ---------------------------------------------------------------------------


def _write_jsonl(path, records):
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")


def test_trace_report_corrects_cross_node_skew(tmp_path):
    # Node B's host clock runs 5 s ahead of node A's; the synchronizer
    # measured offsets that meet in the middle (virtual = raw + offset).
    _write_jsonl(tmp_path / "a.jsonl", [
        {"rec": "clock", "node": "A", "ts": 90.0, "offset_s": 2.5},
        {"trace_id": "t1", "span_id": "s1", "name": "dcn.send",
         "kind": "send", "node": "A", "t0": 100.0, "t1": 100.05,
         "tags": {"peer": "B", "acked": True, "rtt_s": 0.05}},
    ])
    _write_jsonl(tmp_path / "b.jsonl", [
        {"rec": "clock", "node": "B", "ts": 95.0, "offset_s": -2.5},
        {"trace_id": "t1", "span_id": "r1", "parent_id": "s1",
         "name": "dcn.recv", "kind": "recv", "node": "B",
         "t0": 105.01, "t1": 105.01},
        {"trace_id": "t1", "span_id": "h1", "parent_id": "r1",
         "name": "handle:ping", "kind": "handler", "node": "B",
         "t0": 105.012, "t1": 105.08},
    ])
    files = [str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")]

    raw = trace_report.report(files, correct=False)
    tr_raw = raw["traces"]["t1"]["tree"]
    send_raw = tr_raw["by_id"]["s1"]
    recv_raw = tr_raw["by_id"]["r1"]
    assert recv_raw["t0"] - send_raw["t0"] == pytest.approx(5.01)

    rep = trace_report.report(files)
    tr = rep["traces"]["t1"]
    tree = tr["tree"]
    send, recv, handler = tree["by_id"]["s1"], tree["by_id"]["r1"], tree["by_id"]["h1"]
    # Corrected onto the shared virtual clock: the recv happens 10 ms
    # after the send, not 5 s after.
    assert recv["t0"] - send["t0"] == pytest.approx(0.01)
    assert handler["t0"] >= send["t0"]
    assert rep["clock_offsets_s"] == {"A": 2.5, "B": -2.5}
    # One causal tree, one cross-node edge, critical path send→recv→handler.
    assert tr["cross_node_links"] == 1
    assert [s["name"] for s in tr["critical_path"]] == [
        "dcn.send", "dcn.recv", "handle:ping"
    ]
    assert tr["nodes"] == ["A", "B"]
    # The human rendering and JSON stripping both hold together.
    text = trace_report.render_text(rep)
    assert "dcn.send" in text and "handle:ping" in text
    json.dumps(trace_report._strip_internal(rep))


def test_trace_report_overrun_attribution_and_summaries(tmp_path):
    _write_jsonl(tmp_path / "a.jsonl", [
        {"trace_id": "t1", "span_id": "p1", "name": "phase:lb",
         "kind": "phase", "node": "A", "t0": 10.0, "t1": 10.3,
         "tags": {"round": 4, "budget_ms": 150, "overrun": True,
                  "overrun_ms": 150.0, "phase_ms": 300.0}},
        {"trace_id": "t2", "span_id": "p2", "name": "phase:lb",
         "kind": "phase", "node": "A", "t0": 11.0, "t1": 11.1,
         "tags": {"round": 5, "budget_ms": 150, "phase_ms": 100.0}},
    ])
    rep = trace_report.report([str(tmp_path / "a.jsonl")])
    assert rep["overruns"] == {
        "A/phase:lb": {"count": 1, "total_ms": 150.0, "max_ms": 150.0,
                       "rounds": [4]}
    }
    q = rep["summaries"]["phase_ms"]["phase:lb"]
    assert q["count"] == 2
    assert 100.0 <= q["p50_ms"] <= 300.0
    assert "OVERRUN" in trace_report.render_text(rep)


# ---------------------------------------------------------------------------
# 3-node fleet, end-to-end across OS processes with skewed host clocks
# ---------------------------------------------------------------------------

FLEET_CHILD = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, "__REPO__")
    from freedm_tpu.core import tracing
    from freedm_tpu.dcn.endpoint import UdpEndpoint
    from freedm_tpu.runtime.broker import Broker
    from freedm_tpu.runtime.clocksync import ClockSynchronizer
    from freedm_tpu.runtime.messages import ModuleMessage
    from freedm_tpu.runtime.module import DgiModule

    trace_path, port, skew = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
    peers = sys.argv[4:]
    uuid = "127.0.0.1:%d" % port
    clock = lambda: time.time() + skew  # this host's (skewed) wall clock
    tracing.TRACER.configure(enabled=True, node=uuid, path=trace_path,
                             clock=clock)

    class Pinger(DgiModule):
        name = "lb"
        sent_rounds = 0
        def __init__(self):
            self.pings_from = {p: 0 for p in peers}
        def run_phase(self, ctx):
            # Ping peers only once the clock sync demonstrably
            # converged: every peer's regression holds >= 8 sample
            # pairs (pings sent earlier would be corrected with a
            # half-formed offset table).
            if self.sent_rounds >= 6:
                return
            ready = all(
                len(clk._responses.get(p, ())) >= 16 for p in peers
            )
            if ready:
                self.sent_rounds += 1
                for p in peers:
                    ep.send(p, ModuleMessage("lb", "ping",
                                             {"r": ctx.round_index},
                                             source=uuid))
        def handle_message(self, m, ctx=None):
            if m.type == "ping" and m.source in self.pings_from:
                self.pings_from[m.source] += 1

    broker = Broker(clock=clock)
    pinger = Pinger()
    broker.register_module(pinger, 40)  # one 40 ms phase per round
    ep = UdpEndpoint(uuid, bind=("127.0.0.1", port), sink=broker.deliver,
                     resend_time_s=0.02)
    for p in peers:
        host, _, pp = p.rpartition(":")
        ep.connect(p, (host, int(pp)))
    clk = ClockSynchronizer(uuid, peers, ep.send, clock=clock,
                            query_interval_s=0.2)
    broker.attach_clock_sync(clk)
    ep.start()

    # Readiness-polled run (no fixed round count, no fixed drain
    # sleep): batches of realtime rounds until (a) this node sent its
    # ping window, (b) every peer's ping window ARRIVED here (the
    # peers got their useful work done too, so an early exit cannot
    # strand their un-ACKed sends), and (c) this node's own SR windows
    # drained (our send spans closed on their ACKs) — all bounded by a
    # hard wall-clock deadline so a wedged fleet exits instead of
    # hanging the parent.
    deadline = time.monotonic() + 90.0
    while time.monotonic() < deadline:
        broker.run(n_rounds=4, realtime=True)
        done = (
            pinger.sent_rounds >= 6
            and all(n >= 6 for n in pinger.pings_from.values())
            and all(len(ep.channel(p)._out_window) == 0 for p in peers)
        )
        if done:
            break
    ep.stop()
    tracing.TRACER.close()
""")


def _run_three_node_fleet(workdir):
    """Spawn the three skewed children and poll the fleet to completion
    (readiness polling, not fixed sleeps: each child runs until its
    pings went out, its peers' pings arrived, and its SR windows
    drained, all under its own deadline); return the trace file paths.

    Every failure mode — a child that exits nonzero AND a child that
    outlives the parent's budget — surfaces as ``AssertionError`` so
    the caller's bounded retry covers all of them.
    """
    import os

    from test_federation import free_udp_ports

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    workdir.mkdir(parents=True, exist_ok=True)
    ports = free_udp_ports(3)
    uuids = [f"127.0.0.1:{p}" for p in ports]
    skews = [-2.0, 0.0, 2.0]
    files = [workdir / f"trace_{p}.jsonl" for p in ports]
    procs = []
    for i, port in enumerate(ports):
        peers = [u for u in uuids if u != uuids[i]]
        procs.append(subprocess.Popen(
            [sys.executable, "-c", FLEET_CHILD.replace("__REPO__", repo),
             str(files[i]), str(port), str(skews[i]), *peers],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        ))
    # Poll for fleet completion (the children gate their own exit on
    # readiness, 90 s ceiling each); the parent budget only has to
    # cover the slowest child plus startup stagger.
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline and any(
        p.poll() is None for p in procs
    ):
        time.sleep(0.25)
    hung = [p for p in procs if p.poll() is None]
    for p in hung:
        p.kill()
    outs = [p.communicate(timeout=30) for p in procs]
    assert not hung, "fleet children outlived the polling budget"
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err.decode()
    return [str(f) for f in files], uuids, skews


#: Bounded retries for the fleet scenario: multi-process + wall-clock
#: regression is inherently load-sensitive, so a failed run is retried,
#: but never more than this many attempts total.
FLEET_ATTEMPTS = 2


def test_three_node_fleet_traced_end_to_end(tmp_path):
    """The acceptance scenario: three OS processes with host clocks
    skewed by up to 4 s, federated over real UDP with clock sync.  The
    merged report must show round spans from every node, cross-node
    message spans parent-linked through the wire trace context, and
    timestamps corrected by the journaled clocksync offsets.
    """
    last = None
    for attempt in range(FLEET_ATTEMPTS):
        try:
            _assert_three_node_fleet(tmp_path / f"attempt{attempt}")
            return
        except AssertionError as e:
            last = e
    raise last


def _assert_three_node_fleet(workdir):
    paths, uuids, skews = _run_three_node_fleet(workdir)
    spans, clocks = trace_report.load_records(paths)
    # Every node journaled rounds and clock offsets.
    assert {s["node"] for s in spans if s["kind"] == "round"} == set(uuids)
    assert set(clocks) == set(uuids)
    # The synchronizer measured (roughly) the injected skews: corrected
    # clocks meet near the fleet mean, so each offset ≈ -skew.  The
    # tolerance is loose — under CI load convergence is slower, and the
    # acceptance-critical property (corrected cross-node deltas) is
    # asserted separately below.
    final = {n: tbl[-1][1] for n, tbl in clocks.items()}
    for uuid, skew in zip(uuids, skews):
        assert final[uuid] == pytest.approx(-skew, abs=0.8), final

    rep = trace_report.report(paths)
    cross = {
        tid: tr for tid, tr in rep["traces"].items()
        if tr["cross_node_links"] > 0
    }
    assert cross, "no cross-node parent-linked spans survived"
    # Pick the traced pings (sent AFTER the synchronizer converged —
    # spans from the bootstrap clk exchanges predate any offset
    # measurement and are uncorrectable by construction): each send
    # (node A) and the peer's recv must be a parent-linked pair on
    # DIFFERENT nodes.  After correction, causality must hold (a recv
    # cannot precede its send beyond the correction noise) and the
    # typical pair must sit close together despite the 4 s raw clock
    # spread — individual pairs may carry genuine delivery latency
    # (retransmissions under load), so the upper bound is a median.
    deltas = []
    for tr in cross.values():
        tree = tr["tree"]
        for s in tree["spans"]:
            if s["kind"] != "recv":
                continue
            parent = tree["by_id"].get(s.get("parent_id"))
            if (parent is None or parent["kind"] != "send"
                    or parent["tags"].get("type") != "ping"):
                continue
            assert parent["node"] != s["node"]
            deltas.append(s["t0"] - parent["t0"])
    assert deltas
    assert all(d > -0.5 for d in deltas), deltas  # causality restored
    assert sorted(deltas)[len(deltas) // 2] < 0.5, deltas
    # A cross-node trace roots in the sending node's round span.
    assert any("round" in tr["roots"] for tr in cross.values())
    # And the raw (uncorrected) stamps really were seconds apart — the
    # correction did the work, not clock luck.
    raw = trace_report.report(paths, correct=False)
    raw_deltas = []
    for tr in raw["traces"].values():
        tree = tr["tree"]
        for s in tree["spans"]:
            parent = tree["by_id"].get(s.get("parent_id"))
            if (parent is not None and s["kind"] == "recv"
                    and parent["kind"] == "send"
                    and parent["node"] != s["node"]):
                raw_deltas.append(abs(s["t0"] - parent["t0"]))
    assert raw_deltas and max(raw_deltas) > 1.0
