"""Device layer tests: schema compiler, device tensor, manager pumps,
adapter factory XML path, and the JAX plant adapter.

Reference behaviors mirrored: device.xml parsing (CDeviceBuilder),
GetNetValue aggregation (CDeviceManager.cpp:296-312), adapter.xml entry
binding (CAdapterFactory/IBufferAdapter), NULL_COMMAND semantics
(IAdapter), hidden-until-revealed lifecycle.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.core.config import NULL_COMMAND
from freedm_tpu.devices import (
    AdapterFactory,
    DeviceManager,
    compile_layout,
    parse_adapter_xml,
    parse_device_xml,
)
from freedm_tpu.devices import tensor as dt
from freedm_tpu.devices.adapters.base import BufferAdapter
from freedm_tpu.devices.adapters.fake import FakeAdapter
from freedm_tpu.devices.adapters.plant import NOMINAL_OMEGA, PlantAdapter
from freedm_tpu.grid import cases

DEVICE_XML = """
<root>
  <deviceType><id>Sst</id><state>gateway</state><command>gateway</command></deviceType>
  <deviceType><id>Drer</id><state>generation</state></deviceType>
</root>
"""

ADAPTER_XML = """
<root>
  <adapter name="demo" type="fake">
    <info><host>localhost</host><port>5004</port></info>
    <state>
      <entry index="1"><type>Sst</type><device>SST1</device><signal>gateway</signal></entry>
      <entry index="2"><type>Drer</type><device>DRER1</device><signal>generation</signal></entry>
    </state>
    <command>
      <entry index="1"><type>Sst</type><device>SST1</device><signal>gateway</signal></entry>
    </command>
  </adapter>
</root>
"""


def test_schema_compile_and_xml():
    types = parse_device_xml(DEVICE_XML)
    assert [t.id for t in types] == ["Sst", "Drer"]
    lay = compile_layout(types)
    assert lay.signals == ("gateway", "generation")
    assert lay.state_mask.tolist() == [[1.0, 0.0], [0.0, 1.0]]
    assert lay.command_mask.tolist() == [[1.0, 0.0], [0.0, 0.0]]
    # Default layout covers the reference's sample classes.
    default = compile_layout()
    for t in ("Sst", "Desd", "Drer", "Load", "Fid", "Logger", "Omega"):
        assert t in default.type_ids


def test_tensor_aggregations():
    lay = compile_layout()
    sst = lay.type_ids["Sst"]
    drer = lay.type_ids["Drer"]
    gw = lay.signal_index("gateway")
    gen = lay.signal_index("generation")
    t = dt.empty(lay, capacity=8)
    t = t._replace(
        type_id=t.type_id.at[:4].set(jnp.asarray([sst, sst, drer, drer], jnp.int32)),
        alive=t.alive.at[:4].set(1.0).at[3].set(0.0),  # row 3 dead
        state=t.state.at[0, gw].set(2.0).at[1, gw].set(3.0).at[2, gen].set(7.0).at[3, gen].set(100.0),
    )
    assert float(dt.net_value(t, sst, gw)) == 5.0
    assert float(dt.net_value(t, drer, gen)) == 7.0  # dead row excluded
    assert int(dt.count_devices(t, sst)) == 2
    # set_commands only touches live rows of the type.
    t2 = dt.set_commands(t, sst, gw, 1.5)
    assert np.asarray(dt.commanded(t2))[:, gw].tolist() == [1.0, 1.0, 0.0, 0.0, 0, 0, 0, 0]
    t3 = dt.clear_commands(t2)
    assert float(jnp.sum(dt.commanded(t3))) == 0.0


def test_manager_lifecycle_and_pumps():
    mgr = DeviceManager(capacity=4)
    ad = FakeAdapter()
    mgr.add_device("SST1", "Sst", ad)
    mgr.add_device("LOAD1", "Load", ad)
    # Hidden until reveal.
    assert mgr.device_names() == ()
    ad.reveal_devices()
    assert mgr.device_names() == ("LOAD1", "SST1")
    ad.set_state("SST1", "gateway", 4.0)
    ad.set_state("LOAD1", "drain", 9.0)
    assert mgr.get_net_value("Sst", "gateway") == 4.0

    t = mgr.snapshot()
    lay = mgr.layout
    assert float(dt.net_value(t, lay.type_ids["Load"], lay.signal_index("drain"))) == 9.0
    # Command path: write via tensor, apply back to the adapter.
    t = dt.set_commands(t, lay.type_ids["Sst"], lay.signal_index("gateway"), -2.5)
    assert mgr.apply_commands(t) == 1  # only the Sst gateway was commanded
    assert ad.get_state("SST1", "gateway") == -2.5

    # Slot reuse on removal (PnP departure).
    row = mgr.row_of("LOAD1")
    mgr.remove_device("LOAD1")
    ad2 = FakeAdapter()
    assert mgr.add_device("PNP1", "Drer", ad2) == row


def test_capacity_and_duplicates():
    mgr = DeviceManager(capacity=1)
    ad = FakeAdapter()
    mgr.add_device("A", "Sst", ad)
    with pytest.raises(ValueError):
        mgr.add_device("A", "Sst", ad)
    with pytest.raises(RuntimeError):
        mgr.add_device("B", "Sst", ad)
    with pytest.raises(ValueError):
        mgr.add_device("C", "NotAType", ad)


def test_factory_from_xml():
    mgr = DeviceManager(capacity=8)
    fac = AdapterFactory(mgr)
    (spec,) = parse_adapter_xml(ADAPTER_XML)
    assert spec.info["port"] == "5004"
    assert spec.devices == (("SST1", "Sst"), ("DRER1", "Drer"))
    adapter = fac.create_adapter(spec)
    assert adapter.revealed
    assert mgr.device_names() == ("DRER1", "SST1")
    with pytest.raises(ValueError):
        fac.create_adapter(spec)  # duplicate name
    fac.stop()
    assert mgr.device_names() == ()


def test_factory_unknown_type():
    mgr = DeviceManager(capacity=2)
    fac = AdapterFactory(mgr)
    (spec,) = parse_adapter_xml(ADAPTER_XML.replace('type="fake"', 'type="nope"'))
    with pytest.raises(ValueError, match="unknown adapter type"):
        fac.create_adapter(spec)


def test_buffer_adapter_bindings():
    ba = BufferAdapter()
    ba.bind_state("SST1", "gateway", 0)
    ba.bind_state("DRER1", "generation", 1)
    ba.bind_command("SST1", "gateway", 0)
    ba.finalize_bindings()
    assert (ba.state_size, ba.command_size) == (2, 1)
    # Transport pushes a state buffer, collects the command buffer.
    cmds = ba.swap_state(np.array([1.5, 7.0], np.float32))
    assert cmds.tolist() == [NULL_COMMAND]
    assert ba.get_state("DRER1", "generation") == 7.0
    ba.set_command("SST1", "gateway", -3.0)
    assert ba.swap_state(np.array([0.0, 0.0], np.float32)).tolist() == [-3.0]
    # Non-dense indices rejected.
    bad = BufferAdapter()
    bad.bind_state("X", "s", 1)
    with pytest.raises(ValueError):
        bad.finalize_bindings()


def test_plant_adapter_physics():
    feeder = cases.vvc_9bus()
    placements = {
        "LOAD1": ("Load", 1),
        "DRER1": ("Drer", 2),
        "SST1": ("Sst", 3),
        "DESD1": ("Desd", 4),
        "OMEGA": ("Omega", 0),
        "FID1": ("Fid", 0),
    }
    plant = PlantAdapter(feeder, placements, dt_hours=1.0)
    plant.reveal_devices()
    plant.start()

    # Balanced-ish plant: frequency near nominal.
    w0 = plant.get_state("OMEGA", "frequency")
    assert w0 == pytest.approx(NOMINAL_OMEGA, rel=0.05)

    # Importing power through the SST raises frequency (droop sign).
    plant.set_command("SST1", "gateway", 100.0)
    plant.step()
    assert plant.get_state("OMEGA", "frequency") > w0
    assert plant.get_state("SST1", "gateway") == 100.0

    # Storage integrates its charge command.
    s0 = plant.get_state("DESD1", "storage")
    plant.set_command("DESD1", "storage", 2.0)
    plant.step()
    assert plant.get_state("DESD1", "storage") == pytest.approx(s0 + 2.0)

    # Fid command flips its state.
    plant.set_command("FID1", "state", 0.0)
    assert plant.get_state("FID1", "state") == 0.0

    # Power flow ran: voltages are sane.
    assert 0.9 < plant.voltage_pu(3) < 1.1
