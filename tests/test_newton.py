"""Newton-Raphson solver correctness.

Oracles, strongest first:

1. **Reverse construction** — pick a random voltage profile, compute the
   exact injections it implies (numpy complex, independent math), and
   require NR to recover the profile.  Catches any systematic modeling
   error in Ybus or the mismatch equations.
2. **Ladder cross-check** — on a phase-decoupled radial feeder, phase a
   of the (independently validated) ladder solver must agree with the
   single-phase NR solution in the same per-unit system.
3. Conservation and batching properties.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid import cases, matpower
from freedm_tpu.grid.bus import PQ, PV, SLACK, BusSystem, ybus_dense
from freedm_tpu.grid.feeder import from_branch_table
from freedm_tpu.pf import ladder
from freedm_tpu.pf.newton import branch_flows, make_newton_solver


def _np_ybus(sys, status=None):
    y = ybus_dense(sys, status=status)
    return np.asarray(y.re) + 1j * np.asarray(y.im)


def test_recovers_constructed_solution(rng):
    sys = cases.synthetic_mesh(30, seed=7)
    n = sys.n_bus
    # Construct a ground-truth operating point.
    # Stay in the normal operating region so the flat start converges to
    # this solution (AC power flow has multiple branches; a wild profile
    # would be a different, equally valid fixed point).
    v_true = 1.0 + rng.uniform(-0.03, 0.03, n)
    th_true = rng.uniform(-0.08, 0.08, n)
    th_true[sys.slack] = 0.0
    vc = v_true * np.exp(1j * th_true)
    s = vc * np.conj(_np_ybus(sys) @ vc)

    bus_type = sys.bus_type
    sys2 = BusSystem(
        **{
            **sys.__dict__,
            "p_inj": s.real,
            "q_inj": s.imag,
            "v_set": np.where(bus_type != PQ, v_true, 1.0),
        }
    )
    solve, _ = make_newton_solver(sys2, tol=1e-10)
    res = solve()
    assert bool(res.converged), float(res.mismatch)
    np.testing.assert_allclose(np.asarray(res.v), v_true, atol=1e-8)
    np.testing.assert_allclose(np.asarray(res.theta), th_true, atol=1e-8)
    # Realized injections at *all* buses match the constructed ones
    # (slack/PV included, since the profile is exactly feasible).
    np.testing.assert_allclose(np.asarray(res.p), s.real, atol=1e-8)
    np.testing.assert_allclose(np.asarray(res.q), s.imag, atol=1e-8)


def test_matches_ladder_on_decoupled_radial():
    # Balanced loads + diagonal impedances => phases decouple and phase a
    # of the 3-phase ladder equals a single-phase NR solve in the same
    # per-unit system (V_LN base, per-phase power base).
    edges = [(0, 1), (1, 2), (2, 3), (1, 4)]
    loads_kw = {1: 30.0, 2: 50.0, 3: -20.0, 4: 40.0}
    dl = np.zeros((len(edges), 13))
    for i, (f, t) in enumerate(edges):
        p = loads_kw[t]
        q = 0.3 * p
        dl[i] = [i + 1, f, t, 1, 1.0, 1, p, q, p, q, p, q, 0]
    z_code = np.eye(3) * (0.9 + 1.1j)
    feeder = from_branch_table(dl, z_code[None], base_kva=1000.0, base_kv=12.47, v_source_pu=1.02)
    solve_l, _ = ladder.make_ladder_solver(feeder, eps=1e-12, max_iter=60)
    res_l = solve_l(feeder.s_load)
    assert bool(res_l.converged)

    nb = feeder.n_branches
    n = nb + 1
    s_pu = feeder.s_load_pu()  # per-phase pu
    z_pu = feeder.z_pu[:, 0, 0]
    sys = BusSystem(
        bus_type=np.array([SLACK] + [PQ] * nb),
        p_inj=np.concatenate([[0.0], -s_pu[:, 0].real]),  # load = -injection
        q_inj=np.concatenate([[0.0], -s_pu[:, 0].imag]),
        v_set=np.full(n, 1.02),
        g_shunt=np.zeros(n),
        b_shunt=np.zeros(n),
        from_bus=feeder.from_node.astype(np.int64),
        to_bus=np.arange(1, n, dtype=np.int64),
        r=z_pu.real,
        x=z_pu.imag,
        b_chg=np.zeros(nb),
        tap=np.ones(nb),
        shift=np.zeros(nb),
    ).validate()
    solve_n, _ = make_newton_solver(sys, tol=1e-12)
    res_n = solve_n()
    assert bool(res_n.converged)

    v_l, ang_l = ladder.v_polar(res_l)
    np.testing.assert_allclose(np.asarray(res_n.v), np.asarray(v_l[:, 0]), atol=1e-8)
    np.testing.assert_allclose(
        np.asarray(res_n.theta), np.deg2rad(np.asarray(ang_l[:, 0])), atol=1e-8
    )


def test_slack_balances_and_flows_conserve():
    sys = cases.synthetic_mesh(50, seed=8)
    solve, _ = make_newton_solver(sys)
    res = solve()
    assert bool(res.converged)
    # PQ buses realize their schedule; PV buses their P and V.
    pq = sys.bus_type == PQ
    pv = sys.bus_type == PV
    np.testing.assert_allclose(np.asarray(res.p)[pq], sys.p_inj[pq], atol=1e-6)
    np.testing.assert_allclose(np.asarray(res.q)[pq], sys.q_inj[pq], atol=1e-6)
    np.testing.assert_allclose(np.asarray(res.p)[pv], sys.p_inj[pv], atol=1e-6)
    np.testing.assert_allclose(np.asarray(res.v)[pv], sys.v_set[pv], atol=1e-9)
    # Total injections = losses >= 0 (no shunts in this case).
    s_f, s_t = branch_flows(sys, res)
    loss = np.asarray((s_f + s_t).re).sum()
    assert loss >= 0
    assert np.asarray(res.p).sum() == pytest.approx(loss, abs=1e-6)
    # Bus injections equal the sum of incident branch flows.
    p_from_flows = np.zeros(sys.n_bus)
    np.add.at(p_from_flows, sys.from_bus, np.asarray(s_f.re))
    np.add.at(p_from_flows, sys.to_bus, np.asarray(s_t.re))
    np.testing.assert_allclose(p_from_flows, np.asarray(res.p), atol=1e-6)


def test_vmap_scenarios_and_contingencies():
    sys = cases.synthetic_mesh(40, seed=9)
    solve, _ = make_newton_solver(sys)

    scales = jnp.linspace(0.5, 1.1, 8)
    batch = jax.vmap(lambda s: solve(p_inj=sys.p_inj * s, q_inj=sys.q_inj * s))(scales)
    assert bool(jnp.all(batch.converged)), np.asarray(batch.mismatch)
    assert batch.v.shape == (8, sys.n_bus)

    # N-1 over the chords (ring stays intact => network stays connected).
    m = sys.n_branch
    n_ring = sys.n_bus
    outages = []
    for k in range(n_ring, m):
        st = np.ones(m)
        st[k] = 0.0
        outages.append(st)
    outages = jnp.asarray(np.stack(outages))
    nminus1 = jax.vmap(lambda st: solve(status=st))(outages)
    assert bool(jnp.all(nminus1.converged))
    # Outages actually change the solution.
    base = solve()
    dv = jnp.max(jnp.abs(nminus1.v - base.v[None, :]))
    assert float(dv) > 1e-9


def test_gradient_through_fixed_solver():
    sys = cases.synthetic_mesh(20, seed=10)
    _, solve_fixed = make_newton_solver(sys, max_iter=8)

    def loss_fn(q_inj):
        res = solve_fixed(q_inj=q_inj)
        s_f, s_t = branch_flows(sys, res)
        return jnp.sum((s_f + s_t).re)  # total network losses

    g = jax.grad(loss_fn)(jnp.asarray(sys.q_inj))
    assert g.shape == (sys.n_bus,)
    assert bool(jnp.all(jnp.isfinite(g)))
    # Finite-difference check on one coordinate.
    i = int(np.argmax(np.abs(np.asarray(g))))
    eps = 1e-6
    qp = np.asarray(sys.q_inj, dtype=np.float64).copy()
    qm = qp.copy()
    qp[i] += eps
    qm[i] -= eps
    fd = (float(loss_fn(jnp.asarray(qp))) - float(loss_fn(jnp.asarray(qm)))) / (2 * eps)
    assert fd == pytest.approx(float(g[i]), rel=1e-4, abs=1e-8)


def test_matpower_parser():
    case = """
function mpc = case4
mpc.version = '2';
mpc.baseMVA = 100;
mpc.bus = [
  1 3 0   0  0 0 1 1.00 0 230 1 1.1 0.9;
  2 2 0   0  0 0 1 1.00 0 230 1 1.1 0.9;
  3 2 90  30 0 0 1 1.00 0 230 1 1.1 0.9; % PV on paper, but its only unit is off
  4 1 50  10 0 5 1 1.00 0 230 1 1.1 0.9;
];
mpc.gen = [
  1 0  0 300 -300 1.02 100 1 250 10;
  2 80 0 300 -300 1.03 100 1 250 10;
  3 10 5 300 -300 1.00 100 0 250 10; % out of service
];
mpc.branch = [
  1 2 0.01 0.06 0.02 250 250 250 0    0  1 -360 360;
  1 3 0.02 0.08 0.01 250 250 250 0    0  1 -360 360;
  2 4 0.01 0.05 0.02 250 250 250 0.98 2  1 -360 360;
  3 4 0.03 0.09 0.00 250 250 250 0    0  0 -360 360; % out of service
];
"""
    sys = matpower.from_mpc(matpower.parse_case_text(case))
    assert sys.n_bus == 4
    assert sys.n_branch == 3  # out-of-service branch dropped
    assert sys.bus_type[0] == SLACK and sys.bus_type[1] == PV
    assert sys.bus_type[2] == PQ  # PV bus with no live unit degrades to PQ
    assert sys.p_inj[1] == pytest.approx(0.8)  # 80 MW gen
    assert sys.p_inj[2] == pytest.approx(-0.9)  # out-of-service gen ignored
    assert sys.v_set[0] == pytest.approx(1.02)  # VG overrides bus VM
    assert sys.v_set[1] == pytest.approx(1.03)
    assert sys.b_shunt[3] == pytest.approx(0.05)
    assert sys.tap[2] == pytest.approx(0.98)
    assert sys.shift[2] == pytest.approx(np.deg2rad(2))
    solve, _ = make_newton_solver(sys)
    res = solve()
    assert bool(res.converged)


def test_hand_jacobian_matches_jacfwd():
    """The hand-assembled polar Jacobian must equal jax.jacfwd of the
    masked residual exactly (same formulation, analytic derivative)."""
    import jax
    import jax.numpy as jnp

    from freedm_tpu.grid.bus import PQ, SLACK, ybus_dense

    sys = cases.synthetic_mesh(24, seed=12)
    n = sys.n_bus
    rdtype = jnp.float64
    y = ybus_dense(sys, dtype=rdtype)
    bus_type = jnp.asarray(sys.bus_type)
    th_free = (bus_type != SLACK).astype(rdtype)
    v_free = (bus_type == PQ).astype(rdtype)
    v_set = jnp.asarray(sys.v_set, rdtype)
    p_sched = jnp.asarray(sys.p_inj, rdtype)
    q_sched = jnp.asarray(sys.q_inj, rdtype)

    from freedm_tpu.utils import cplx

    def residual(x):
        theta, v = x[:n], x[n:]
        vc = cplx.polar(v, theta)
        i = cplx.C(
            y.re @ vc.re - y.im @ vc.im, y.re @ vc.im + y.im @ vc.re
        )
        s = vc * i.conj()
        f_p = jnp.where(th_free > 0, s.re - p_sched, theta)
        f_q = jnp.where(v_free > 0, s.im - q_sched, v - v_set)
        return jnp.concatenate([f_p, f_q])

    rng = np.random.default_rng(3)
    x = jnp.concatenate(
        [
            jnp.asarray(rng.uniform(-0.2, 0.2, n), rdtype),
            jnp.asarray(rng.uniform(0.95, 1.05, n), rdtype),
        ]
    )
    # Expected: one exact Newton step x1 = x0 − J(x0)⁻¹ f(x0) with the
    # Jacobian from jacfwd of the masked residual.
    f0 = residual(x)
    want_jac = jax.jacfwd(residual)(x)
    want_x1 = x + jnp.linalg.solve(want_jac, -f0)

    # Shipped path: ONE fixed Newton step from the same start point —
    # this drives newton.py's actual hand-assembled _newton_step, so a
    # sign flip in the production assembly fails here.
    _, solve_fixed1 = make_newton_solver(sys, max_iter=1, dtype=rdtype)
    got = solve_fixed1(v0=x[n:], theta0=x[:n])
    got_x1 = jnp.concatenate([got.theta, got.v])
    np.testing.assert_allclose(np.asarray(got_x1), np.asarray(want_x1), atol=1e-9)


def test_newton_2k_bus_mesh_converges():
    """The hand-assembled Jacobian path handles a 2000-bus mesh (the
    scale jacfwd could not reach) — VERDICT r3 item 4."""
    # Light loading + dense chords: a 2000-bus ring backbone at the
    # 40 MW default is physically infeasible (divergence is correct).
    sys = cases.synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    solve, _ = make_newton_solver(sys, max_iter=15)
    out = solve()
    assert bool(out.converged), float(out.mismatch)
    v = np.asarray(out.v)
    assert v.min() > 0.7 and v.max() < 1.2


# ---------------------------------------------------------------------------
# Fast-decoupled load flow (pf/fdlf.py)
# ---------------------------------------------------------------------------


def test_fdlf_matches_newton():
    """The decoupled iteration converges to the same operating point
    Newton finds (same masked formulation, different iteration)."""
    from freedm_tpu.pf.fdlf import make_fdlf_solver

    sys = cases.synthetic_mesh(50, seed=8)
    fsolve, _ = make_fdlf_solver(sys, tol=1e-10, max_iter=80)
    nsolve, _ = make_newton_solver(sys, tol=1e-10)
    fo = fsolve()
    no = nsolve()
    assert bool(fo.converged), float(fo.mismatch)
    np.testing.assert_allclose(np.asarray(fo.v), np.asarray(no.v), atol=1e-8)
    np.testing.assert_allclose(
        np.asarray(fo.theta), np.asarray(no.theta), atol=1e-8
    )


def test_fdlf_2k_mesh_and_n1_batch():
    from freedm_tpu.pf.fdlf import make_fdlf_solver
    import jax

    sys = cases.synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    solve, solve_fixed = make_fdlf_solver(sys, max_iter=30)
    out = solve()
    assert bool(out.converged), float(out.mismatch)
    # A small N-1 batch re-factorizes per lane on device.
    m = sys.n_branch
    k = 4
    status = np.ones((k, m), np.float32)
    status[np.arange(k), np.arange(k)] = 0.0
    b = jax.jit(jax.vmap(lambda s: solve_fixed(status=s)))(jnp.asarray(status))
    assert np.all(np.asarray(b.converged)), np.asarray(b.mismatch)


def test_fdlf_respects_pv_and_slack_pins():
    from freedm_tpu.pf.fdlf import make_fdlf_solver

    sys = cases.synthetic_mesh(40, seed=9)
    solve, _ = make_fdlf_solver(sys)
    out = solve()
    assert bool(out.converged)
    pinned = sys.bus_type != PQ  # PV + slack hold v_set
    np.testing.assert_allclose(
        np.asarray(out.v)[pinned], sys.v_set[pinned], atol=1e-9
    )
    slack = sys.bus_type == SLACK
    np.testing.assert_allclose(np.asarray(out.theta)[slack], 0.0, atol=1e-12)
