"""Matrix-free Newton-Krylov solver vs the dense Newton oracle.

VERDICT r4 item 1: the 10k-bus meshed path must agree with the dense
[2n, 2n] Newton solver at sizes where both run.  The dense solver is
itself pinned to published IEEE solutions (``tests/test_ieee_cases.py``),
so tolerance-level agreement here chains the Krylov path to the same
external oracle.
"""

import jax.numpy as jnp
import numpy as np

from freedm_tpu.grid.cases import synthetic_mesh
from freedm_tpu.grid.matpower import load_builtin
from freedm_tpu.pf.krylov import make_krylov_solver, _newton_schulz
from freedm_tpu.pf.newton import make_newton_solver


def _compare(sys_, atol, **kw):
    solve_d, _ = make_newton_solver(sys_, max_iter=12)
    solve_k, _ = make_krylov_solver(sys_, max_iter=15)
    rd = solve_d(**kw)
    rk = solve_k(**kw)
    assert bool(rd.converged) and bool(rk.converged)
    np.testing.assert_allclose(np.asarray(rk.v), np.asarray(rd.v), atol=atol)
    np.testing.assert_allclose(
        np.asarray(rk.theta), np.asarray(rd.theta), atol=atol
    )
    return rd, rk


def test_matches_dense_newton_small_mesh():
    sys_ = synthetic_mesh(300, seed=4, load_mw=2.0, chord_frac=1.0)
    _compare(sys_, atol=5e-9)


def test_matches_dense_newton_2000bus_mesh():
    # The VERDICT-level gate: agreement at the dense solver's size limit.
    sys_ = synthetic_mesh(2000, seed=4, load_mw=2.0, chord_frac=1.0)
    _compare(sys_, atol=1e-8)


def test_matches_dense_on_real_ieee_case():
    sys_ = load_builtin("case_ieee30")
    _compare(sys_, atol=1e-8)


def test_branch_outage_status_is_traced():
    sys_ = synthetic_mesh(300, seed=4, load_mw=2.0, chord_frac=1.0)
    status = np.ones(sys_.n_branch)
    status[sys_.n_bus + 3] = 0.0  # drop a chord (keeps the ring intact)
    _compare(sys_, atol=5e-9, status=jnp.asarray(status))


def test_injection_overrides_are_traced():
    sys_ = synthetic_mesh(300, seed=4, load_mw=2.0, chord_frac=1.0)
    _compare(
        sys_,
        atol=5e-9,
        p_inj=jnp.asarray(sys_.p_inj * 1.1),
        q_inj=jnp.asarray(sys_.q_inj * 0.9),
    )


def test_newton_schulz_inverse_quality():
    rng = np.random.default_rng(0)
    # SPD-ish diagonally dominant matrix, like B'.
    a = rng.normal(0, 1, (64, 64))
    a = a @ a.T + 64 * np.eye(64)
    x, resid = _newton_schulz(jnp.asarray(a))
    assert float(resid) <= 0.05
    err = np.max(np.abs(np.asarray(x) @ a - np.eye(64)))
    assert err < 0.1


def test_reports_nonconvergence():
    sys_ = synthetic_mesh(120, seed=4, load_mw=2.0, chord_frac=1.0)
    solve, _ = make_krylov_solver(sys_, max_iter=15)
    # An infeasible loading (far beyond any operating point) must not be
    # reported as converged.
    r = solve(p_inj=jnp.asarray(sys_.p_inj * 500.0))
    assert not bool(r.converged)


def test_gradient_through_fixed_solver():
    """The meshed VVC adjoint for free: d(losses)/d(q_inj) by reverse-
    mode AD through the fixed-iteration matrix-free solve, checked
    against central finite differences.  The reference hand-builds this
    adjoint for its 9-bus radial case only (form_Ftheta/Fv/J + inv);
    here it exists at transmission scale by construction."""
    import jax

    sys_ = synthetic_mesh(120, seed=4, load_mw=2.0, chord_frac=1.0)
    _, solve_fixed = make_krylov_solver(sys_, max_iter=6, inner_iters=16)
    q0 = jnp.asarray(sys_.q_inj)

    def slack_p(q):
        # Slack active injection = total losses + net load: a scalar
        # whose q-sensitivity is the classic loss-gradient signal.
        r = solve_fixed(q_inj=q)
        return r.p[sys_.slack]

    g = jax.grad(slack_p)(q0)
    h = 1e-5
    for idx in (3, 47, 101):
        e = jnp.zeros_like(q0).at[idx].set(h)
        fd = (slack_p(q0 + e) - slack_p(q0 - e)) / (2 * h)
        np.testing.assert_allclose(
            np.asarray(g[idx]), np.asarray(fd), rtol=1e-4, atol=1e-8
        )
