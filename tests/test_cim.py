"""Unbalanced 3-phase current-injection solver vs the ladder oracle.

VERDICT r4 item 6: a weakly-meshed unbalanced feeder (closed tie
switch) must solve, and the radial subcase (tie open) must match the
ladder sweep — the two solvers share no iteration code, so agreement is
a real cross-oracle, and the KCL residual check re-derives injections
from the Ybus independently of both.
"""

import numpy as np

from freedm_tpu.grid.cases import Z_CODES_9BUS, synthetic_radial, vvc_9bus
from freedm_tpu.pf.cim import kcl_residual_kva, make_cim_solver
from freedm_tpu.pf.ladder import make_ladder_solver

# The 9-bus feeder's tie candidate: nodes 5 (end of the main) and 8
# (end of the lateral), one unit-length feeder-code line.
TIE_5_8 = (5, 8, Z_CODES_9BUS[0] / (1000.0 * 12.47**2 / 1000.0))


def _ladder_solution(feeder, s_kva):
    solve, _ = make_ladder_solver(feeder, eps=1e-12, max_iter=200)
    r = solve(s_kva)
    assert bool(r.converged)
    return r


def test_radial_matches_ladder_9bus():
    feeder = vvc_9bus()
    rl = _ladder_solution(feeder, feeder.s_load)
    solve, _ = make_cim_solver(feeder, max_iter=200)
    rc = solve(feeder.s_load)
    assert bool(rc.converged)
    np.testing.assert_allclose(
        rc.v_node.to_numpy(), rl.v_node.to_numpy(), atol=1e-8
    )


def test_radial_matches_ladder_synthetic_200bus():
    feeder = synthetic_radial(200, seed=3, load_kw=30.0)
    rl = _ladder_solution(feeder, feeder.s_load)
    solve, _ = make_cim_solver(feeder, max_iter=400)
    rc = solve(feeder.s_load)
    assert bool(rc.converged)
    np.testing.assert_allclose(
        rc.v_node.to_numpy(), rl.v_node.to_numpy(), atol=1e-7
    )


def test_closed_tie_switch_solves_and_satisfies_kcl():
    feeder = vvc_9bus()
    ties = [TIE_5_8]
    solve, _ = make_cim_solver(feeder, ties=ties, max_iter=200)
    rc = solve(feeder.s_load)
    assert bool(rc.converged)
    # Independent oracle: node-wise complex power balance on the meshed
    # Ybus.  1e-6 kVA on a feeder whose loads are O(100) kW.
    resid = kcl_residual_kva(feeder, ties, rc)
    assert resid.max() < 1e-6


def test_tie_reduces_voltage_spread():
    # Electrical sanity: closing a tie between the two feeder ends ties
    # their voltages together — the spread across tie endpoints shrinks.
    feeder = vvc_9bus()
    s = feeder.s_load
    open_solve, _ = make_cim_solver(feeder, max_iter=200)
    closed_solve, _ = make_cim_solver(feeder, ties=[TIE_5_8], max_iter=200)
    vo = np.abs(open_solve(s).v_node.to_numpy())
    vc = np.abs(closed_solve(s).v_node.to_numpy())
    gap_open = np.abs(vo[5] - vo[8]).max()
    gap_closed = np.abs(vc[5] - vc[8]).max()
    assert gap_closed < gap_open


def test_open_tie_equals_no_tie():
    # Opening the tie (removing it) must reproduce the radial solution —
    # the meshed machinery collapses cleanly.
    feeder = vvc_9bus()
    radial_solve, _ = make_cim_solver(feeder, max_iter=200)
    rr = radial_solve(feeder.s_load)
    rl = _ladder_solution(feeder, feeder.s_load)
    np.testing.assert_allclose(
        rr.v_node.to_numpy(), rl.v_node.to_numpy(), atol=1e-8
    )


def test_unbalanced_loads_meshed():
    # Phase-unbalanced loading through the tie: still solves, still
    # passes the independent KCL check.
    feeder = vvc_9bus()
    s = feeder.s_load.copy()
    s[:, 0] *= 1.5  # overload phase a
    s[:, 2] *= 0.5
    ties = [TIE_5_8]
    solve, _ = make_cim_solver(feeder, ties=ties, max_iter=300)
    rc = solve(s)
    assert bool(rc.converged)
    resid = kcl_residual_kva(feeder, ties, rc, s_load_kva=s)
    assert resid.max() < 1e-6


def test_fixed_variant_matches_while_loop():
    feeder = vvc_9bus()
    solve, solve_fixed = make_cim_solver(feeder, ties=[TIE_5_8], max_iter=120)
    a = solve(feeder.s_load)
    b = solve_fixed(feeder.s_load)
    np.testing.assert_allclose(
        a.v_node.to_numpy(), b.v_node.to_numpy(), atol=1e-9
    )


def test_gradient_through_fixed_solver():
    """Unbalanced weakly-meshed VVC adjoint: the gradient of a voltage-
    profile objective w.r.t. per-phase reactive loads, by reverse-mode
    AD through the fixed-iteration current-injection solve, checked
    against finite differences — a capability the reference's
    hand-built 9-bus adjoint cannot reach (its solver is radial-only)."""
    import jax
    import jax.numpy as jnp

    from freedm_tpu.utils import cplx

    feeder = vvc_9bus()
    _, solve_fixed = make_cim_solver(feeder, ties=[TIE_5_8], max_iter=80)
    p0 = jnp.asarray(feeder.s_load.real)
    q00 = jnp.asarray(feeder.s_load.imag)

    def profile_loss(q):
        r = solve_fixed(cplx.C(p0, q))
        v2 = r.v_node.abs2()[1:]
        return jnp.sum((v2 - 1.0) ** 2)

    g = jax.grad(profile_loss)(q00)
    h = 1e-3
    for idx in ((1, 0), (4, 2), (7, 1)):
        e = jnp.zeros_like(q00).at[idx].set(h)
        fd = (profile_loss(q00 + e) - profile_loss(q00 - e)) / (2 * h)
        np.testing.assert_allclose(
            np.asarray(g[idx]), np.asarray(fd), rtol=1e-4, atol=1e-10
        )
