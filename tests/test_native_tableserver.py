"""Native C++ table-server tests.

``native/tableserver.cpp`` is the standalone C++ role of the reference's
pscad-interface (``pscad-interface-master/src``): reader/writer-locked
state/command tables served over the RTDS byte protocol (to DGI
processes) and the PSCAD header protocol (to a co-simulation) — for
co-sim hosts that must not carry a Python/JAX runtime.  These tests
build it with g++, then drive both protocols from Python, including
wire interop with the framework's own RtdsAdapter.
"""

import json
import os
import shutil
import socket
import subprocess
import time

import numpy as np
import pytest

from freedm_tpu.core.config import NULL_COMMAND
from freedm_tpu.devices.adapters.rtds import WIRE_DTYPE, read_exactly
from freedm_tpu.sim.plantserver import SIM_DTYPE, SIM_HEADER_SIZE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None and shutil.which("make") is None,
    reason="no C++ toolchain",
)


@pytest.fixture(scope="module")
def binary():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)
    return os.path.join(NATIVE, "tableserver")


@pytest.fixture
def server(binary, tmp_path):
    """Two ports over one table pair; index 0 rtds, index 1 pscad."""
    cfg = tmp_path / "tables.cfg"
    cfg.write_text(
        "# shared tables: one DGI rtds port, one PSCAD sim port\n"
        "seed SST1.gateway 5.5\n"
        "seed LOAD_A.drain 20.0\n"
        "rtds 0 states SST1.gateway LOAD_A.drain commands SST1.gateway\n"
        "pscad 0 states LOAD_A.drain commands SST1.gateway\n"
    )
    proc = subprocess.Popen(
        [binary, str(cfg)], stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    line = proc.stdout.readline()
    try:
        ports = [tuple(p) for p in json.loads(line)["tableserver"]]
    except Exception:
        proc.kill()
        raise RuntimeError(f"tableserver failed: {line!r} {proc.stderr.read()}")
    yield ports
    proc.kill()
    proc.wait(timeout=5)


def sim_header(kind):
    return kind.encode().ljust(SIM_HEADER_SIZE, b"\x00")


def test_rtds_exchange_serves_seeded_states(server):
    rtds_addr, _ = server
    with socket.create_connection(rtds_addr, timeout=5) as s:
        cmds = np.full(1, NULL_COMMAND, WIRE_DTYPE)
        s.sendall(cmds.tobytes())
        raw = read_exactly(s, 2 * 4)
    states = np.frombuffer(raw, WIRE_DTYPE)
    assert states[0] == pytest.approx(5.5)
    assert states[1] == pytest.approx(20.0)


def test_dgi_command_crosses_to_pscad_get(server):
    rtds_addr, sim_addr = server
    with socket.create_connection(rtds_addr, timeout=5) as s:
        s.sendall(np.asarray([42.5], WIRE_DTYPE).tobytes())
        read_exactly(s, 2 * 4)  # sync: command applied before reply
    with socket.create_connection(sim_addr, timeout=5) as s:
        s.sendall(sim_header("GET"))
        raw = read_exactly(s, SIM_DTYPE.itemsize)
    assert np.frombuffer(raw, SIM_DTYPE)[0] == pytest.approx(42.5)


def test_pscad_set_crosses_to_rtds_states(server):
    rtds_addr, sim_addr = server
    with socket.create_connection(sim_addr, timeout=5) as sim:
        sim.sendall(sim_header("SET") + np.asarray([33.0], SIM_DTYPE).tobytes())
        sim.sendall(sim_header("GET"))
        read_exactly(sim, SIM_DTYPE.itemsize)  # sync
    with socket.create_connection(rtds_addr, timeout=5) as s:
        s.sendall(np.full(1, NULL_COMMAND, WIRE_DTYPE).tobytes())
        raw = read_exactly(s, 2 * 4)
    assert np.frombuffer(raw, WIRE_DTYPE)[1] == pytest.approx(33.0)


def test_framework_rtds_adapter_interops(server):
    """The framework's own RtdsAdapter runs its lock-step exchange
    against the native server — full wire compatibility."""
    from freedm_tpu.devices.adapters.rtds import RtdsAdapter
    from freedm_tpu.devices.manager import DeviceManager

    rtds_addr, _ = server
    manager = DeviceManager()
    a = RtdsAdapter(rtds_addr[0], int(rtds_addr[1]), poll_s=0.01)
    manager.add_device("SST1", "Sst", a)
    manager.add_device("LOAD_A", "Load", a)
    a.bind_state("SST1", "gateway", 0)
    a.bind_state("LOAD_A", "drain", 1)
    a.bind_command("SST1", "gateway", 0)
    a.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not a.revealed:
            time.sleep(0.01)
        assert a.revealed, a.error
        assert manager.get_state("LOAD_A", "drain") == pytest.approx(20.0)
        # Commands land in the COMMAND table (the simulator's side of
        # the contract — static tables don't feed commands back into
        # states the way the live-physics plantserver does).
        manager.set_command("SST1", "gateway", 7.0)
        deadline = time.monotonic() + 5
        got = None
        while time.monotonic() < deadline:
            with socket.create_connection(server[1], timeout=5) as s:
                s.sendall(sim_header("GET"))
                got = np.frombuffer(
                    read_exactly(s, SIM_DTYPE.itemsize), SIM_DTYPE
                )[0]
            if got == pytest.approx(7.0):
                break
            time.sleep(0.02)
        assert got == pytest.approx(7.0)
        assert a.error is None
    finally:
        a.stop()
