"""DCN transport tests: the SR protocol property-tested under simulated
loss/reorder/duplication (sans-IO, virtual clock), then over real UDP
sockets within one process and across two OS processes.

Reference semantics under test: CProtocolSR's at-most-once, in-order,
expiring delivery (Broker/src/CProtocolSR.cpp:95-446) with kill-number
gap skipping and stale-connection resync, and the CUSTOMNETWORK loss
injection (IProtocol.cpp:94-101).
"""

import copy
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from freedm_tpu.dcn import endpoint as ep_mod
from freedm_tpu.dcn import wire
from freedm_tpu.dcn.protocol import MAX_DROPPED_MSGS, SrChannel
from freedm_tpu.runtime.messages import ModuleMessage


def msg(i, ttl=None):
    m = ModuleMessage("lb", "draft_request", {"i": i}, source="hostA:50000")
    return m


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_wire_roundtrip():
    frames = [
        wire.Frame(status=wire.MESSAGE, seq=5, hash="abc", kill=3, expire=12.5,
                   msg=wire.pack_message(msg(1))),
        wire.Frame(status=wire.ACCEPTED, seq=4, hash="def"),
    ]
    data = wire.encode_window("hostA:50000", frames, 99.0)
    src, sent, out = wire.decode_window(data)
    assert src == "hostA:50000" and sent == 99.0
    assert out[0].seq == 5 and out[0].kill == 3
    assert wire.unpack_message(out[0].msg).payload == {"i": 1}
    with pytest.raises(ValueError):
        wire.decode_window(b"not json")


def test_decode_window_tolerates_unknown_fields():
    # Forward compatibility: a newer peer's datagram may carry frame
    # keys (and top-level window keys) this build has never heard of —
    # they are dropped, not a decode crash (old nodes must tolerate
    # traced datagrams).
    import json

    gram = json.dumps({
        "src": "hostA:50000",
        "sent": 99.0,
        "future_window_key": {"x": 1},
        "frames": [{
            "status": wire.MESSAGE, "seq": 5, "hash": "abc",
            "msg": wire.pack_message(msg(1)),
            "trace": {"trace_id": "t", "span_id": "s"},
            "future_frame_key": [1, 2, 3],
        }],
    }).encode()
    src, sent, frames = wire.decode_window(gram)
    assert src == "hostA:50000" and sent == 99.0
    assert frames[0].seq == 5
    assert frames[0].trace == {"trace_id": "t", "span_id": "s"}
    assert not hasattr(frames[0], "future_frame_key")
    # Required fields still required; non-dict frames still malformed.
    with pytest.raises(ValueError):
        wire.decode_window(json.dumps(
            {"src": "a", "sent": 0.0, "frames": [{"hash": "h"}]}
        ).encode())
    with pytest.raises(ValueError):
        wire.decode_window(json.dumps(
            {"src": "a", "sent": 0.0, "frames": [[1, 2]]}
        ).encode())


def test_wire_omits_null_fields_on_the_wire():
    # None-valued frame fields put zero bytes on the wire (an untraced
    # frame looks exactly like a pre-tracing frame to an old peer).
    import json

    f = wire.Frame(status=wire.ACCEPTED, seq=4, hash="def")
    gram = wire.encode_window("u", [f], 0.0)
    keys = set(json.loads(gram.decode())["frames"][0])
    assert keys == {"status", "seq", "hash"}
    # And the roundtrip restores dataclass defaults for absent keys.
    _, _, out = wire.decode_window(gram)
    assert out[0].kill is None and out[0].trace is None


def test_wire_size_cap():
    big = ModuleMessage("lb", "x", {"blob": "y" * wire.MAX_PACKET_SIZE})
    with pytest.raises(ValueError, match="too long"):
        wire.encode_window("u", [wire.Frame(status=wire.MESSAGE, seq=0,
                                            msg=wire.pack_message(big))], 0.0)


# ---------------------------------------------------------------------------
# sans-IO harness: two channels over a fault-injecting virtual network
# ---------------------------------------------------------------------------


class VirtualLink:
    """Deterministic lossy/reordering/duplicating frame carrier."""

    def __init__(self, a: SrChannel, b: SrChannel, seed=0, loss=0.0,
                 dup=0.0, reorder=0.0, latency=0.005):
        self.ends = {"a": a, "b": b}
        self.rng = np.random.default_rng(seed)
        self.loss, self.dup, self.reorder, self.latency = loss, dup, reorder, latency
        self.in_flight = []  # (deliver_at, dst, frames)
        self.delivered = {"a": [], "b": []}
        self.outage = False

    def pump(self, src: str, now: float) -> None:
        frames = self.ends[src].poll(now)
        if not frames:
            return
        dst = "b" if src == "a" else "a"
        for _ in range(1 + (self.rng.random() < self.dup)):
            if self.outage or self.rng.random() < self.loss:
                continue
            delay = self.latency * (1 + 3 * (self.rng.random() < self.reorder))
            # Deep-copy: real datagrams are serialized, so receiver-side
            # state must not alias sender frames.
            self.in_flight.append((now + delay, dst, copy.deepcopy(frames)))

    def deliver(self, now: float) -> None:
        due = [x for x in self.in_flight if x[0] <= now]
        self.in_flight = [x for x in self.in_flight if x[0] > now]
        self.rng.shuffle(due)
        for _, dst, frames in due:
            self.delivered[dst].extend(self.ends[dst].accept_frames(frames, now))

    def run(self, until: float, step=0.01, start=0.0):
        t = start
        while t < until:
            self.pump("a", t)
            self.pump("b", t)
            self.deliver(t)
            t += step
        return self


def test_lossless_in_order_delivery():
    a, b = SrChannel("b"), SrChannel("a")
    link = VirtualLink(a, b)
    for i in range(20):
        a.send(msg(i), 0.0)
    link.run(1.0)
    got = [m.payload["i"] for m in link.delivered["b"]]
    assert got == list(range(20))
    assert a.outstanding == 0  # everything ACKed


@pytest.mark.parametrize("loss,dup,reorder,seed", [
    (0.3, 0.0, 0.0, 1),
    (0.0, 0.5, 0.3, 2),
    (0.4, 0.3, 0.3, 3),
])
def test_exactly_once_under_faults(loss, dup, reorder, seed):
    # Property: with TTLs longer than the run, every sent message is
    # delivered exactly once, in order, despite loss+dup+reorder.
    a, b = SrChannel("b", ttl_s=60.0), SrChannel("a", ttl_s=60.0)
    link = VirtualLink(a, b, seed=seed, loss=loss, dup=dup, reorder=reorder)
    t = 0.0
    for i in range(30):
        a.send(msg(i), t)
        link.run(t + 0.1, start=t)
        t += 0.1
    link.run(t + 5.0, start=t)
    got = [m.payload["i"] for m in link.delivered["b"]]
    assert got == list(range(30))


def test_expiry_kills_skip_gap():
    # An outage longer than the TTL must expire undelivered messages
    # (they are *meant* to die, CProtocolSR.cpp:113,154-169); later
    # messages arrive via the kill-number gap skip, exactly once.
    a, b = SrChannel("b", ttl_s=0.3), SrChannel("a", ttl_s=0.3)
    link = VirtualLink(a, b)
    a.send(msg(0), 0.0)
    link.run(0.1)  # delivered
    link.outage = True
    a.send(msg(1), 0.1)
    a.send(msg(2), 0.15)
    link.run(0.6, start=0.1)  # TTL 0.3 passes during outage
    link.outage = False
    a.send(msg(3), 0.6)
    link.run(2.0, start=0.6)
    got = [m.payload["i"] for m in link.delivered["b"]]
    assert got[0] == 0 and got[-1] == 3
    assert len(got) == len(set(got))  # exactly-once
    assert 1 not in got and 2 not in got  # expired in the outage
    assert a.expired >= 2


def test_stale_connection_reconnects():
    a, b = SrChannel("b", ttl_s=0.1), SrChannel("a", ttl_s=0.1)
    link = VirtualLink(a, b)
    link.outage = True
    t = 0.0
    for i in range(MAX_DROPPED_MSGS + 3):
        a.send(msg(i), t)
        link.run(t + 0.2, start=t)
        t += 0.2
    assert a.reconnects >= 1
    link.outage = False
    a.send(msg(99), t)
    link.run(t + 2.0, start=t)
    assert link.delivered["b"][-1].payload["i"] == 99  # recovered


def test_unsynced_receiver_triggers_bad_request_resync():
    a, b = SrChannel("b"), SrChannel("a")
    # Hand-craft a MESSAGE frame arriving before any SYN.
    f = wire.Frame(status=wire.MESSAGE, seq=7, hash="h",
                   msg=wire.pack_message(msg(0)))
    assert b.accept_frames([f], 0.0) == []
    reply = b.poll(0.0)
    assert any(fr.status == wire.BAD_REQUEST for fr in reply)
    # Sender reacts to BAD_REQUEST with a SYN at the window front.
    a.send(msg(1), 0.0)
    a.accept_frames([fr for fr in reply if fr.status == wire.BAD_REQUEST], 0.0)
    out = a.poll(0.0)
    assert out[0].status == wire.CREATED


# ---------------------------------------------------------------------------
# real UDP, one process
# ---------------------------------------------------------------------------


def test_udp_endpoints_exchange_modulemessages():
    got_a, got_b = [], []
    ea = ep_mod.UdpEndpoint("hostA:1", sink=got_a.append, resend_time_s=0.02).start()
    eb = ep_mod.UdpEndpoint("hostB:2", sink=got_b.append, resend_time_s=0.02).start()
    try:
        ea.connect("hostB:2", eb.address)
        eb.connect("hostA:1", ea.address)
        for i in range(10):
            ea.send("hostB:2", ModuleMessage("lb", "ping", {"i": i}, source="hostA:1"))
        eb.send("hostA:1", ModuleMessage("gm", "pong", {"ok": True}, source="hostB:2"))
        deadline = time.time() + 5.0
        while (len(got_b) < 10 or len(got_a) < 1) and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ea.stop(); eb.stop()
    assert [m.payload["i"] for m in got_b] == list(range(10))
    assert got_a[0].type == "pong"


def test_udp_lossy_channel_still_delivers():
    got = []
    ea = ep_mod.UdpEndpoint("hostA:1", resend_time_s=0.01, seed=7).start()
    eb = ep_mod.UdpEndpoint("hostB:2", sink=got.append, resend_time_s=0.01).start()
    try:
        ea.connect("hostB:2", eb.address, reliability=60)  # 40% outgoing drop
        eb.connect("hostA:1", ea.address)
        for i in range(10):
            ea.send("hostB:2", ModuleMessage("lb", "ping", {"i": i}, source="hostA:1"))
        deadline = time.time() + 10.0
        while len(got) < 10 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ea.stop(); eb.stop()
    assert [m.payload["i"] for m in got] == list(range(10))


def test_peerlist_plugs_in_udp_transport():
    from freedm_tpu.runtime.peers import PeerList

    got = []
    ea = ep_mod.UdpEndpoint("hostA:1", resend_time_s=0.02).start()
    eb = ep_mod.UdpEndpoint("hostB:2", sink=got.append, resend_time_s=0.02).start()
    try:
        ea.connect("hostB:2", eb.address)
        peers = PeerList("hostA:1", loopback=lambda m: None)
        peers.add("hostB:2", ea.transport_for("hostB:2"))
        peers.get("hostB:2").send(ModuleMessage("lb", "draft", {"x": 1}, source="hostA:1"))
        deadline = time.time() + 5.0
        while not got and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ea.stop(); eb.stop()
    assert got and got[0].type == "draft" and got[0].send_time is not None


def test_network_xml_reliability_config(tmp_path):
    ea = ep_mod.UdpEndpoint("hostA:1")
    ea.connect("peer-uuid", ("127.0.0.1", 1))
    xml = ("<network><incoming><reliability>90</reliability></incoming>"
           "<outgoing><channel uuid='peer-uuid'><reliability>75</reliability>"
           "</channel></outgoing></network>")
    ep_mod.load_network_config(ea, xml)
    assert ea.incoming_reliability == 90
    assert ea._peers["peer-uuid"].reliability == 75
    ea.stop()


# ---------------------------------------------------------------------------
# two OS processes
# ---------------------------------------------------------------------------

ECHO_CHILD = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, "__REPO__")
    from freedm_tpu.dcn.endpoint import UdpEndpoint
    from freedm_tpu.runtime.messages import ModuleMessage

    parent_addr = ("127.0.0.1", int(sys.argv[1]))
    ep = UdpEndpoint("child:1", resend_time_s=0.02)

    def echo(m):
        ep.send("parent:1", ModuleMessage("lb", "echo", m.payload, source="child:1"))

    ep.sink = echo
    ep.connect("parent:1", parent_addr)
    ep.start()
    # Announce readiness so the parent learns our port.
    ep.send("parent:1", ModuleMessage("lb", "hello", {}, source="child:1"))
    time.sleep(8.0)
    ep.stop()
""")


def test_two_process_exchange(tmp_path):
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    got = []
    ep = ep_mod.UdpEndpoint("parent:1", sink=got.append, resend_time_s=0.02).start()
    child = subprocess.Popen(
        [sys.executable, "-c", ECHO_CHILD.replace("__REPO__", repo), str(ep.address[1])],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        deadline = time.time() + 10.0
        while not any(m.type == "hello" for m in got) and time.time() < deadline:
            time.sleep(0.05)
        assert any(m.type == "hello" for m in got), "child never said hello"
        for i in range(5):
            ep.send("child:1", ModuleMessage("lb", "ping", {"i": i}, source="parent:1"))
        while sum(m.type == "echo" for m in got) < 5 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        child.terminate()
        child.wait(timeout=5)
        ep.stop()
    echoes = [m.payload["i"] for m in got if m.type == "echo"]
    assert echoes == list(range(5))


def test_dcn_accept_feeds_sc_intransit_count(three_node_fleet=None):
    # An LB "accept" arriving over the DCN boundary must be counted by
    # SC as in-transit channel state (PosixMain.cpp:361,367 subscription;
    # HandleAccept, StateCollection.cpp:539-558) and surfaced with the
    # next cut, then reset.
    from freedm_tpu.devices.adapters.fake import FakeAdapter
    from freedm_tpu.devices.manager import DeviceManager
    from freedm_tpu.runtime.fleet import Fleet, NodeHandle, build_broker

    managers = []
    for i in range(2):
        m = DeviceManager()
        fake = FakeAdapter()
        m.add_device(f"SST{i}", "Sst", fake)
        fake.reveal_devices()
        managers.append(m)
    fleet = Fleet([NodeHandle(f"h{i}:1", m) for i, m in enumerate(managers)])
    broker = build_broker(fleet)

    got = []
    ep_in = ep_mod.UdpEndpoint("hostA:1", sink=broker.deliver, resend_time_s=0.02).start()
    ep_far = ep_mod.UdpEndpoint("hil:9", resend_time_s=0.02).start()
    try:
        ep_far.connect("hostA:1", ep_in.address)
        ep_far.send("hostA:1", ModuleMessage("lb", "accept", {"amount": 1.0}, source="hil:9"))
        deadline = time.time() + 5.0
        while ep_far.channel("hostA:1").outstanding and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ep_far.stop(); ep_in.stop()
    broker.run(n_rounds=1)
    assert broker.shared["dcn_accepts"] == 1
    broker.run(n_rounds=1)
    assert broker.shared["dcn_accepts"] == 0  # reset with the cut


def test_lost_syn_ack_recovers_via_duplicate_reack():
    # A lost SYN-ACK must not wedge the sender's window head: the
    # receiver re-ACKs duplicate SYNs (and duplicate messages), so the
    # resent window clears on the next exchange.
    a, b = SrChannel("b"), SrChannel("a")
    a.send(msg(0), 0.0)
    b.accept_frames(a.poll(0.0), 0.0)
    b.poll(0.0)  # ACKs generated here are "lost"
    assert a.outstanding == 2  # SYN + message still queued
    redelivered = b.accept_frames(a.poll(0.1), 0.1)  # resent SYN + msg0
    assert redelivered == []  # duplicates are not re-delivered...
    a.accept_frames(b.poll(0.1), 0.1)  # ...but they are re-ACKed
    assert a.outstanding == 0
    # And the channel keeps working afterwards.
    a.send(msg(1), 0.2)
    delivered = b.accept_frames(a.poll(0.2), 0.2)
    assert [m.payload["i"] for m in delivered] == [1]


def test_window_chunking_splits_large_backlog():
    frames = [
        wire.Frame(status=wire.MESSAGE, seq=i, hash="h%d" % i,
                   msg=wire.pack_message(ModuleMessage("lb", "x", {"pad": "p" * 400, "i": i})))
        for i in range(200)
    ]
    grams = wire.encode_windows("u", frames, 0.0)
    assert len(grams) > 1
    seen = []
    for g in grams:
        assert len(g) <= wire.MAX_PACKET_SIZE
        _, _, fs = wire.decode_window(g)
        seen.extend(f.seq for f in fs)
    assert seen == list(range(200))


def test_oversize_message_raises_at_sender():
    ep = ep_mod.UdpEndpoint("a:1")
    ep.connect("b:1", ("127.0.0.1", 1))
    with pytest.raises(ValueError, match="too long"):
        ep.send("b:1", ModuleMessage("lb", "x", {"blob": "y" * wire.MAX_PACKET_SIZE}))
    ep.stop()


def test_oversize_send_burns_no_sequence_number():
    # A rejected oversize send must leave the channel untouched: the
    # next valid message keeps the expected seq and delivers normally.
    a = SrChannel("b", src_uuid="a")
    b = SrChannel("a", src_uuid="b")
    with pytest.raises(ValueError, match="too long"):
        a.send(ModuleMessage("lb", "big", {"blob": "y" * wire.MAX_PACKET_SIZE}), 0.0)
    a.send(ModuleMessage("lb", "ok", {}), 0.0)
    delivered = []
    for _ in range(4):
        for f in a.poll(0.01):
            delivered.extend(b.accept_frames([f], 0.01))
        for f in b.poll(0.01):
            a.accept_frames([f], 0.01)
    assert [m.type for m in delivered] == ["ok"]


def test_sender_size_check_uses_local_uuid():
    # Round-2 advisor finding: the send() size pre-check must be
    # computed with the *endpoint's* uuid (what goes on the wire as
    # src), not the peer's.  With a long local uuid and a short peer
    # uuid, a message sized to just fit under the cap with the short
    # uuid must be rejected at send(), not explode later in the pump.
    long_uuid = "sender-" + "x" * 200
    ep = ep_mod.UdpEndpoint(long_uuid)
    ep.connect("b", ("127.0.0.1", 1))
    pad = "y" * (wire.MAX_PACKET_SIZE - 400)  # fits with "b", not with long_uuid
    msg = ModuleMessage("lb", "x", {"blob": pad})
    # Sanity: the peer-uuid-sized window would have passed.
    frame = wire.Frame(status=wire.MESSAGE, seq=0, hash=msg.hash(),
                       msg=wire.pack_message(msg))
    assert len(wire.encode_window("b", [frame], 0.0)) <= wire.MAX_PACKET_SIZE
    with pytest.raises(ValueError, match="too long"):
        ep.send("b", msg)
    ep.stop()


def test_trace_propagation_survives_lossy_udp_channel():
    # Satellite (PR 2): across a 40%-loss UDP link, every message must
    # yield exactly ONE recv span (retransmissions and duplicates
    # collapse in the accept logic), each parent-linked to its
    # originating send span through the wire trace context.
    from freedm_tpu.core import tracing

    tracing.TRACER.configure(enabled=True, node="hostA:1")
    got = []
    ea = ep_mod.UdpEndpoint("hostA:1", resend_time_s=0.01, seed=7).start()
    eb = ep_mod.UdpEndpoint("hostB:2", sink=got.append, resend_time_s=0.01).start()
    try:
        ea.connect("hostB:2", eb.address, reliability=60)  # 40% outgoing drop
        eb.connect("hostA:1", ea.address)
        for i in range(10):
            ea.send("hostB:2", ModuleMessage("lb", "ping", {"i": i}, source="hostA:1"))
        deadline = time.time() + 10.0
        while len(got) < 10 and time.time() < deadline:
            time.sleep(0.02)
        # A send span ENDS on its ACK, which trails the delivery by a
        # beat (and the ACK itself can ride a retransmit under loss) —
        # wait for all ten ping send spans to land in the ring before
        # snapshotting, or a recv's parent is legitimately still open
        # and the parent-linkage assert flakes.
        recs = tracing.TRACER.tail()
        while time.time() < deadline:
            done = sum(
                1 for r in recs
                if r["kind"] == "send" and r["tags"].get("type") == "ping"
            )
            if done >= 10:
                break
            time.sleep(0.02)
            recs = tracing.TRACER.tail()
    finally:
        ea.stop(); eb.stop()
        tracing.TRACER.reset()
    assert [m.payload["i"] for m in got] == list(range(10))
    sends = {r["span_id"]: r for r in recs
             if r["kind"] == "send" and r["tags"]["type"] == "ping"}
    recvs = [r for r in recs if r["kind"] == "recv"]
    # Exactly one recv span per message, despite loss + retransmission.
    assert len(recvs) == 10
    parents = [r["parent_id"] for r in recvs]
    assert len(set(parents)) == 10 and all(p in sends for p in parents)
    # Delivered messages carry the recv span as their context, so the
    # sink (normally broker.deliver) parents handler spans causally.
    recv_ids = {r["span_id"] for r in recvs}
    assert all(m.trace["span_id"] in recv_ids for m in got)


def test_marker_frame_dropped_unacked_without_snapshot_handler():
    # Forward-compat pin (core.snapshot): to a channel with no
    # ``on_marker`` handler a MARKER is an unknown status — dropped
    # unACKed, byte-for-byte what a pre-marker build does.  The sender's
    # marker dies at its TTL and the snapshot initiator resolves the
    # channel as a typed incomplete; nothing wedges, and ordinary
    # traffic keeps flowing through the gap-skip afterwards.
    a = SrChannel("b", src_uuid="a", ttl_s=0.3)
    b = SrChannel("a", src_uuid="b")  # on_marker unset: pre-marker peer
    a.send(msg(0), 0.0)
    b.accept_frames(a.poll(0.0), 0.0)
    a.accept_frames(b.poll(0.0), 0.0)
    assert a.outstanding == 0  # pair SYNced, msg0 settled
    a.send_marker({"snapshot_id": "s1"}, 0.1)
    assert b.accept_frames(a.poll(0.1), 0.1) == []  # never delivered
    assert b.poll(0.1) == []                        # never ACKed
    assert not b.snap_done
    assert a.outstanding == 1                       # marker still queued
    # TTL expiry clears the sender's window — the marker is gone, and a
    # later message arrives via the kill-number gap skip, exactly once.
    delivered = []
    a.send(msg(1), 0.6)
    for t in (0.6, 0.7, 0.8):
        delivered += b.accept_frames(a.poll(t), t)
        a.accept_frames(b.poll(t), t)
    assert [m.payload["i"] for m in delivered] == [1]
    assert a.outstanding == 0
    # The SAME frame sequence with a handler attached delivers the
    # marker: the pin is about the handler's absence, not the frame.
    c = SrChannel("a", src_uuid="c")
    seen = []
    c.on_marker = lambda peer, payload: seen.append(payload)
    a2 = SrChannel("c", src_uuid="a", ttl_s=0.3)
    a2.send(msg(0), 0.0)
    c.accept_frames(a2.poll(0.0), 0.0)
    a2.accept_frames(c.poll(0.0), 0.0)
    a2.send_marker({"snapshot_id": "s1"}, 0.1)
    c.accept_frames(a2.poll(0.1), 0.1)
    assert c.snap_done and seen[0]["snapshot_id"] == "s1"


def test_large_backlog_does_not_kill_pump():
    # Unreachable peer + deep backlog: the pump thread must chunk and
    # keep running, and delivery must complete once the peer appears.
    got = []
    ea = ep_mod.UdpEndpoint("a:1", resend_time_s=0.02).start()
    try:
        ea.connect("b:1", None)  # no address yet: pure backlog
        for i in range(150):
            ea.send("b:1", ModuleMessage("lb", "x", {"pad": "p" * 300, "i": i}))
        time.sleep(0.1)  # pump survives with 150 queued frames
        eb = ep_mod.UdpEndpoint("b:1", sink=got.append, resend_time_s=0.02).start()
        try:
            ea.connect("b:1", eb.address)
            eb.connect("a:1", ea.address)
            deadline = time.time() + 10.0
            while len(got) < 150 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            eb.stop()
    finally:
        ea.stop()
    assert [m.payload["i"] for m in got] == list(range(150))
