"""gridprobe tests: one violating + one clean fixture per IR rule
(GP001-GP004), the registry-orphan finding (GP005), inventory
round-trip + deliberate-drift rejection (GP006), the repo-wide
self-audit-clean contract, and the GP003 burn-down pins (the dense
Newton identity, the FDLF/DC factor pairs, and the krylov/sparse
preconditioner pair all reach their programs as runtime arguments or
in-program values, never as large captured constants).

Fixture registries are small python files written into ``tmp_path`` and
loaded via ``--registry-file`` — the same seam the CI negative step
uses, so ``main()`` exit codes are proven end-to-end.
"""

import json
import pathlib
import textwrap

import numpy as np
import pytest

from freedm_tpu.tools.gridprobe import main, run_probe

REPO = pathlib.Path(__file__).resolve().parent.parent

HEADER = """
    import jax
    import jax.numpy as jnp
    from freedm_tpu.tools.ir_rules.base import ProgramSpec
    F64_SURFACES = []
"""


def _registry(tmp_path, body, name="reg.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(HEADER) + textwrap.dedent(body))
    return str(p)


def _run(path, *args):
    return main(["--registry-file", path, "--no-inventory", *args])


def _findings(path, **kw):
    return run_probe(registry_file=path, inventory_mode="skip",
                     **kw).findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# GP001 dtype flow
# ---------------------------------------------------------------------------

GP001_BAD = """
    def build():
        def demote(x):
            return (x.astype(jnp.float32) * 2).astype(jnp.float64)
        return demote, (jnp.ones(4, jnp.float64),)
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/demote", "freedm_tpu/pf/newton.py", build,
                    f64=True),
    ]
"""

GP001_CLEAN = """
    def build():
        return (lambda x: x * 2.0), (jnp.ones(4, jnp.float64),)
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/pure64", "freedm_tpu/pf/newton.py", build,
                    f64=True),
    ]
"""

GP001_BF16_BOUNDARY = """
    def build():
        def mixed(x, m):
            return (m @ x.astype(jnp.bfloat16)).astype(jnp.float64)
        return mixed, (jnp.ones(4, jnp.float64),
                       jnp.eye(4, dtype=jnp.bfloat16))
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/mixed", "freedm_tpu/pf/newton.py", build,
                    f64=True, allow_dtypes=frozenset({"bfloat16"}),
                    boundary_reason="declared bf16 stream (test)"),
    ]
"""


def test_gp001_flags_f64_demotion(tmp_path):
    findings = _findings(_registry(tmp_path, GP001_BAD))
    assert _rules_of(findings) == ["GP001"]
    assert "float64 -> float32" in findings[0].message


def test_gp001_clean_f64_flow(tmp_path):
    assert _findings(_registry(tmp_path, GP001_CLEAN)) == []


def test_gp001_bf16_needs_declared_boundary(tmp_path):
    # Same program, boundary declared -> clean; undeclared -> findings.
    clean = _findings(_registry(tmp_path, GP001_BF16_BOUNDARY))
    assert clean == []
    undeclared = GP001_BF16_BOUNDARY.replace(
        "allow_dtypes=frozenset({\"bfloat16\"}),\n", ""
    ).replace("boundary_reason=\"declared bf16 stream (test)\"", "")
    findings = _findings(_registry(tmp_path, undeclared, name="reg2.py"))
    assert "GP001" in _rules_of(findings)
    assert any("bfloat16" in f.message for f in findings)


def test_gp001_host_surface_demotion(tmp_path):
    reg = _registry(tmp_path, """
        import numpy as np
        from freedm_tpu.tools.ir_rules.base import F64Surface
        PROGRAM_REGISTRY = []
        def bad_oracle():
            return (lambda x: np.asarray(x, np.float32)), \\
                (np.ones(3, np.float64),)
        F64_SURFACES = [
            F64Surface("fix/oracle", "freedm_tpu/pf/krylov.py",
                       bad_oracle),
        ]
    """)
    findings = _findings(reg)
    assert _rules_of(findings) == ["GP001"]
    assert "float32" in findings[0].message


def test_dtype_blind_surface_is_a_finding(tmp_path):
    # A surface returning only builtin floats carries no dtype evidence
    # — an unfalsifiable check must fail loudly (GP005), not pass.
    reg = _registry(tmp_path, """
        import numpy as np
        from freedm_tpu.tools.ir_rules.base import F64Surface
        PROGRAM_REGISTRY = []
        def blind_oracle():
            return (lambda x: float(np.sum(x))), (np.ones(3, np.float32),)
        F64_SURFACES = [
            F64Surface("fix/blind", "freedm_tpu/pf/krylov.py",
                       blind_oracle),
        ]
    """)
    findings = _findings(reg)
    assert _rules_of(findings) == ["GP005"]
    assert "no numpy floating leaves" in findings[0].message


def test_gp001_flags_low_precision_args_and_consts(tmp_path):
    # bf16 entering as an ARGUMENT or CONSTANT whose only consumer
    # upcasts it is still low-precision data in the IR — the boundary
    # must be declared even when no bf16 outvar exists.
    reg = _registry(tmp_path, """
        def arg_build():
            return (lambda x: x.astype(jnp.float64) * 2.0), \\
                (jnp.ones(4, jnp.bfloat16),)
        def const_build():
            c = jnp.ones(4, jnp.bfloat16)
            return jax.jit(lambda x: x + c.astype(jnp.float64)), \\
                (jnp.ones(4, jnp.float64),)
        PROGRAM_REGISTRY = [
            ProgramSpec("fix/bf16arg", "freedm_tpu/pf/newton.py",
                        arg_build, f64=True),
            ProgramSpec("fix/bf16const", "freedm_tpu/pf/newton.py",
                        const_build, f64=True),
        ]
    """)
    findings = _findings(reg)
    assert _rules_of(findings) == ["GP001"]
    msgs = " ".join(f.message for f in findings)
    assert "argument 0" in msgs and "captured constant" in msgs


# ---------------------------------------------------------------------------
# GP002 host transfer
# ---------------------------------------------------------------------------

GP002_BAD = """
    import numpy as np
    def build():
        def f(x):
            out = jax.ShapeDtypeStruct(x.shape, x.dtype)
            return jax.pure_callback(lambda v: np.asarray(v), out, x)
        return f, (jnp.ones(3),)
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/cb", "freedm_tpu/pf/newton.py", build),
    ]
"""


def test_gp002_flags_callbacks_and_main_exits_1(tmp_path, capsys):
    reg = _registry(tmp_path, GP002_BAD)
    findings = _findings(reg)
    assert _rules_of(findings) == ["GP002"]
    assert "pure_callback" in findings[0].message
    assert _run(reg) == 1
    out = capsys.readouterr().out
    assert "GP002" in out


# ---------------------------------------------------------------------------
# GP003 constant capture
# ---------------------------------------------------------------------------

GP003_BAD = """
    def build():
        big = jnp.zeros(200_000)  # 1.6 MB f64 closure constant
        return jax.jit(lambda x: x + big.sum()), (jnp.ones(3),)
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/capture", "freedm_tpu/pf/newton.py", build),
    ]
"""

GP003_CLEAN = """
    def build():
        # Same bytes, threaded as a runtime ARGUMENT (the krylov
        # preconditioner discipline) -> not a program constant.
        return (jax.jit(lambda x, big: x + big.sum()),
                (jnp.ones(3), jnp.zeros(200_000)))
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/arg", "freedm_tpu/pf/newton.py", build),
    ]
"""


def test_gp003_flags_large_capture(tmp_path):
    findings = _findings(_registry(tmp_path, GP003_BAD))
    assert _rules_of(findings) == ["GP003"]
    assert "1.60 MB" in findings[0].message


def test_gp003_arg_threading_is_clean(tmp_path):
    assert _findings(_registry(tmp_path, GP003_CLEAN)) == []


# ---------------------------------------------------------------------------
# GP004 donation readiness
# ---------------------------------------------------------------------------

GP004_BAD = """
    def build():
        fn = jax.jit(lambda x: jnp.sum(x), donate_argnums=(0,))
        return fn, (jnp.ones(5),)
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/donate", "freedm_tpu/pf/newton.py", build,
                    donatable=(0,)),
    ]
"""

GP004_CLEAN = """
    def build():
        fn = jax.jit(lambda x: x * 2.0, donate_argnums=(0,))
        return fn, (jnp.ones(5),)
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/donate_ok", "freedm_tpu/pf/newton.py", build,
                    donatable=(0,)),
    ]
"""

GP004_NOT_DONATED = """
    def build():
        return (lambda x: x * 2.0), (jnp.ones(5),)
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/undonated", "freedm_tpu/pf/newton.py", build,
                    donatable=(0,)),
    ]
"""

GP004_UNDECLARED = """
    def build():
        fn = jax.jit(lambda x: x * 2.0, donate_argnums=(0,))
        return fn, (jnp.ones(5),)
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/undeclared", "freedm_tpu/pf/newton.py", build),
    ]
"""


def test_gp004_declared_donation_without_alias(tmp_path):
    findings = _findings(_registry(tmp_path, GP004_BAD))
    assert _rules_of(findings) == ["GP004"]
    assert "no result buffer can alias" in findings[0].message


def test_gp004_declared_but_not_donated(tmp_path):
    # The flip side shipped with the donation work: a declared
    # donatable pair the compiled program does NOT donate is a dropped
    # HBM win, not a pass.
    findings = _findings(_registry(tmp_path, GP004_NOT_DONATED))
    assert _rules_of(findings) == ["GP004"]
    assert "does not donate" in findings[0].message


def test_gp004_donated_but_not_declared(tmp_path):
    # Donation destroys the caller's buffer — an undeclared
    # donate_argnums is an invisible aliasing hazard.
    findings = _findings(_registry(tmp_path, GP004_UNDECLARED))
    assert _rules_of(findings) == ["GP004"]
    assert "not declared donatable" in findings[0].message


def test_gp004_checks_declared_index_not_greedy_pairing(tmp_path):
    # Two same-shaped arguments, one result: the inventory's greedy
    # pairing gives the candidate to arg 0, but declaring arg 1
    # donatable is still legitimate — the rule checks the declared
    # index directly against the results.
    reg = _registry(tmp_path, """
        def build():
            fn = jax.jit(lambda x, y: x + y, donate_argnums=(1,))
            return fn, (jnp.ones(5), jnp.ones(5))
        PROGRAM_REGISTRY = [
            ProgramSpec("fix/second_arg", "freedm_tpu/pf/newton.py",
                        build, donatable=(1,)),
        ]
    """)
    assert _findings(reg) == []


def test_rules_subset_scopes_engine_findings_too(tmp_path):
    # A broken builder is a GP005 finding on default runs, but a
    # --rules GP003 iteration loop must see only GP003.
    reg = _registry(tmp_path, GP005_ORPHAN)
    assert _rules_of(_findings(reg)) == ["GP005"]
    assert _findings(reg, rules=["GP003"]) == []
    assert _run(reg, "--rules", "GP003") == 0


def test_gp004_aliasable_declaration_is_clean_and_recorded(tmp_path):
    res = run_probe(registry_file=_registry(tmp_path, GP004_CLEAN),
                    inventory_mode="skip")
    assert res.findings == []
    prog = res.inventory["programs"]["fix/donate_ok"]
    cands = prog["donation_candidates"]
    assert cands and cands[0][:2] == [0, 0]
    # The inventory records what the compiled program actually donates,
    # so the could-vs-does gap stays measurable.
    assert prog["donated"] == [0]


# ---------------------------------------------------------------------------
# GP005 registry orphan
# ---------------------------------------------------------------------------

GP005_ORPHAN = """
    def build():
        from freedm_tpu.pf.newton import make_newton_solver_RENAMED
        return make_newton_solver_RENAMED, ()
    PROGRAM_REGISTRY = [
        ProgramSpec("fix/orphan", "freedm_tpu/pf/newton.py", build),
    ]
"""


def test_gp005_orphaned_registry_entry(tmp_path):
    findings = _findings(_registry(tmp_path, GP005_ORPHAN))
    assert _rules_of(findings) == ["GP005"]
    assert "failed to build/trace" in findings[0].message


def test_gp005_missing_where_path_and_undocumented_boundary(tmp_path):
    reg = _registry(tmp_path, """
        def build():
            return (lambda x: x), (jnp.ones(2),)
        PROGRAM_REGISTRY = [
            ProgramSpec("fix/nowhere", "freedm_tpu/pf/NO_SUCH.py", build),
            ProgramSpec("fix/noreason", "freedm_tpu/pf/newton.py", build,
                        allow_dtypes=frozenset({"bfloat16"})),
        ]
    """)
    findings = _findings(reg)
    assert _rules_of(findings) == ["GP005"]
    msgs = " ".join(f.message for f in findings)
    assert "does not exist" in msgs
    assert "boundary_reason" in msgs


# ---------------------------------------------------------------------------
# GP006 inventory round-trip + drift rejection
# ---------------------------------------------------------------------------

def test_gp006_inventory_roundtrip_and_drift(tmp_path, capsys):
    reg = _registry(tmp_path, GP001_CLEAN)
    inv = tmp_path / "inv.json"
    # Missing inventory is itself a finding (nothing to diff against).
    assert main(["--registry-file", reg, "--inventory", str(inv)]) == 1
    capsys.readouterr()
    # Write, then re-check: identical trace must round-trip clean.
    assert main(["--registry-file", reg, "--inventory", str(inv),
                 "--write-inventory"]) == 0
    capsys.readouterr()
    assert main(["--registry-file", reg, "--inventory", str(inv)]) == 0
    capsys.readouterr()
    # Deliberate dtype drift in a throwaway copy -> exit 1, GP006,
    # readable delta naming the program.
    d = json.loads(inv.read_text())
    d["programs"]["fix/pure64"]["args"][0] = \
        d["programs"]["fix/pure64"]["args"][0].replace("float64", "float32")
    drift = tmp_path / "drift.json"
    drift.write_text(json.dumps(d))
    rc = main(["--registry-file", reg, "--inventory", str(drift),
               "--format=json"])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    rules = {f["rule"] for f in out["findings"]}
    assert rules == {"GP006"}
    assert any("args drifted" in f["message"] for f in out["findings"])


def test_gp006_program_set_drift(tmp_path, capsys):
    reg = _registry(tmp_path, GP001_CLEAN)
    inv = tmp_path / "inv.json"
    assert main(["--registry-file", reg, "--inventory", str(inv),
                 "--write-inventory"]) == 0
    capsys.readouterr()
    # A program in the inventory that is no longer traced (and one
    # traced but unrecorded) both produce readable GP006 findings.
    d = json.loads(inv.read_text())
    d["programs"]["fix/ghost"] = d["programs"]["fix/pure64"]
    inv.write_text(json.dumps(d))
    rc = main(["--registry-file", reg, "--inventory", str(inv)])
    assert rc == 1
    assert "no longer traced" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Repo-wide self-audit + burn-down pins
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def self_audit():
    """One full probe of the real registry, shared by the assertions
    below (traces all ~14 registered programs once)."""
    return run_probe(inventory_mode="check")


def test_repo_self_audit_clean(self_audit):
    assert self_audit.findings == [], "\n".join(
        f"{f.rule} {f.path}: {f.message}" for f in self_audit.findings
    )


def test_checked_in_inventory_exists_and_matches_version(self_audit):
    path = REPO / "freedm_tpu" / "tools" / "ir_inventory.json"
    recorded = json.loads(path.read_text())
    assert recorded["version"] == self_audit.inventory["version"]
    assert recorded["x64"] is True
    assert set(recorded["programs"]) == set(
        self_audit.inventory["programs"])


def test_f64_surfaces_cover_residual_verify_sites(self_audit):
    # The acceptance contract: the krylov accuracy oracle and the serve
    # cache's delta-verify gate are BOTH registered f64 surfaces.
    names = set(self_audit.inventory["f64_surfaces"])
    assert {"pf/krylov/host_injections", "pf/krylov/true_mismatch",
            "serve/cache/verify"} <= names


def _program(self_audit, name):
    for tp in self_audit.programs:
        if tp.spec.name == name:
            return tp
    raise AssertionError(f"program {name} not traced")


def test_burn_down_newton_identity_not_captured(self_audit):
    # Pre-fix, pf/newton.py captured jnp.eye(2n) as a closure constant
    # (445 KB at the registry's 118-bus case; 3.2 GB at 10k buses).
    # The identity is now built in-program — no const above 100 KB.
    tp = _program(self_audit, "pf/newton/dense")
    biggest = max((getattr(c, "nbytes", 0) for c in tp.consts), default=0)
    assert biggest < 100_000, f"largest captured const {biggest} bytes"


def test_burn_down_fdlf_and_dc_factors_ride_as_arguments(self_audit):
    # Pre-fix, the FDLF B'/B'' LU pair and the DC screen's B' LU were
    # closure constants (320 KB each at the registry's 200-bus case,
    # 64/32 MB per topology at 2000 buses).  They now thread as runtime
    # arguments: multiple array args, small residual consts.
    for name in ("pf/fdlf", "pf/dc/solve", "pf/dc/screen"):
        tp = _program(self_audit, name)
        assert len(tp.in_avals) >= 2, name
        biggest = max((getattr(c, "nbytes", 0) for c in tp.consts),
                      default=0)
        assert biggest < 100_000, f"{name}: largest const {biggest} bytes"


def test_krylov_bf16_boundary_is_argument_threaded(self_audit):
    # The declared bf16 boundary is the preconditioner PAIR, and it
    # enters as arguments (not constants): the first two in_avals are
    # bfloat16 squares.
    tp = _program(self_audit, "pf/krylov")
    assert [a.dtype.name for a in tp.in_avals[:2]] == \
        ["bfloat16", "bfloat16"]


def test_fdlf_solver_still_correct_after_arg_threading():
    # The GP003 burn-down rewired fdlf's jit boundary; pin numerics:
    # solve/vmap-over-status behave exactly as before the refactor.
    import jax
    import jax.numpy as jnp

    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.fdlf import make_fdlf_solver
    from freedm_tpu.pf.krylov import true_mismatch
    from freedm_tpu.pf.newton import make_newton_solver

    sys_ = synthetic_mesh(30, seed=2)
    solve, _ = make_fdlf_solver(sys_)
    r = solve()
    assert bool(r.converged)
    assert true_mismatch(sys_, r) < 1e-7
    # status-traced path (outage) + vmap over a status batch.
    status = np.ones(sys_.n_branch)
    status[0] = 0.0
    r1 = solve(status=status)
    assert float(r1.mismatch) < 1e-6
    batch = jnp.asarray(np.stack([np.ones(sys_.n_branch), status]))
    rb = jax.vmap(lambda s: solve(status=s))(batch)
    assert np.allclose(np.asarray(rb.v)[1], np.asarray(r1.v), atol=1e-9)
    # Cross-check against dense Newton on the base case.
    nsolve, _ = make_newton_solver(sys_, backend="dense")
    rn = nsolve()
    assert np.allclose(np.asarray(r.v), np.asarray(rn.v), atol=1e-6)


def test_dc_solver_still_correct_after_arg_threading():
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.dc import make_dc_solver

    sys_ = synthetic_mesh(30, seed=2)
    dc = make_dc_solver(sys_)
    single = dc.solve()
    lanes = dc.solve(np.stack([np.asarray(sys_.p_inj)] * 3))
    assert np.allclose(np.asarray(lanes.theta)[0],
                       np.asarray(single.theta), atol=1e-12)
    scr = dc.screen_outages(np.arange(4))
    assert scr.theta.shape == (4, sys_.n_bus)
    assert np.all(np.isfinite(np.asarray(scr.severity))
                  | np.asarray(scr.islanded))


def test_probe_config_keys_reach_the_probe(tmp_path):
    # The probe-* GlobalConfig keys are live, not dead plumbing: a cfg
    # file raising probe-const-mb above the fixture's 1.6 MB capture
    # silences GP003 for the same registry.
    reg = _registry(tmp_path, GP003_BAD)
    assert _rules_of(_findings(reg)) == ["GP003"]
    cfg = tmp_path / "freedm.cfg"
    cfg.write_text("probe-const-mb = 2.0\n")
    res = run_probe(registry_file=reg, inventory_mode="skip",
                    config_path=str(cfg))
    assert res.findings == []


def test_gp006_zero_baseline_scalar_has_absolute_slack(tmp_path, capsys):
    # A program whose recorded consts_bytes is 0 must tolerate a
    # few-byte lowering change (jax-version noise), while a real blowup
    # past both the slack and the relative tolerance still fails.
    reg = _registry(tmp_path, GP001_CLEAN)
    inv = tmp_path / "inv.json"
    assert main(["--registry-file", reg, "--inventory", str(inv),
                 "--write-inventory"]) == 0
    capsys.readouterr()
    d = json.loads(inv.read_text())
    prog = d["programs"]["fix/pure64"]
    assert prog["consts_bytes"] == 0
    prog["consts_bytes"] = 8  # 8-byte noise vs a zero baseline: pass
    inv.write_text(json.dumps(d))
    assert main(["--registry-file", reg, "--inventory", str(inv)]) == 0
    capsys.readouterr()
    prog["consts_bytes"] = 10_000_000  # a real blowup: fail
    inv.write_text(json.dumps(d))
    assert main(["--registry-file", reg, "--inventory", str(inv)]) == 1
    assert "consts_bytes drifted" in capsys.readouterr().out


def test_rules_subset_filters_surface_findings(tmp_path):
    reg = _registry(tmp_path, """
        import numpy as np
        from freedm_tpu.tools.ir_rules.base import F64Surface
        PROGRAM_REGISTRY = []
        def bad_oracle():
            return (lambda x: np.asarray(x, np.float32)), \\
                (np.ones(3, np.float64),)
        F64_SURFACES = [
            F64Surface("fix/oracle", "freedm_tpu/pf/krylov.py",
                       bad_oracle),
        ]
    """)
    # GP001 selected -> the surface demotion reports ...
    assert _rules_of(_findings(reg, rules=["GP001"])) == ["GP001"]
    # ... excluded -> it must not leak through a GP002-only run.
    assert _findings(reg, rules=["GP002"]) == []


def test_list_programs_and_internal_error_exit(tmp_path, capsys):
    assert main(["--list-programs"]) == 0
    out = capsys.readouterr().out
    assert "pf/newton/dense" in out and "f64-surface" in out
    # A broken registry file is a 2 (internal error), never a clean 0.
    bad = tmp_path / "broken.py"
    bad.write_text("this is not python ][")
    assert main(["--registry-file", str(bad), "--no-inventory"]) == 2
