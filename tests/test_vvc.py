"""Gradient Volt-VAR controller tests.

Reference behavior being matched (``Broker/src/vvc/VoltVarCtrl.cpp``):
per-round loss descent via projected gradient steps on Q injections with
backtracking acceptance — validated here by finite-difference gradient
checks, monotone descent, limit projection, and convergence to the same
optimum an independent optimizer finds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid import cases
from freedm_tpu.modules import vvc
from freedm_tpu.pf import ladder
from freedm_tpu.utils import cplx


@pytest.fixture(scope="module")
def feeder():
    return cases.vvc_9bus()


@pytest.fixture(scope="module")
def s_reactive(feeder):
    # Lagging loads (Q = 0.6 P): the case Volt-VAR control exists for.
    return feeder.s_load.real * (1 + 0.6j)


def test_gradient_matches_finite_difference(feeder, s_reactive):
    step = vvc.make_vvc_controller(feeder)
    q0 = jnp.zeros((feeder.n_branches, 3))
    out = step(s_reactive, q0)
    g = np.asarray(out.grad_kw_per_kvar)

    mask = jnp.asarray(feeder.phase_mask)
    _, solve_fixed = ladder.make_ladder_solver(feeder)
    sc = cplx.as_c(s_reactive)

    def loss(q):
        return float(
            ladder.total_loss_kw(feeder, solve_fixed(cplx.C(sc.re, sc.im - q * mask)))
        )

    eps = 1e-4
    i, p = 3, 0  # a live node-phase
    dq = np.zeros((feeder.n_branches, 3))
    dq[i, p] = eps
    fd = (loss(jnp.asarray(dq)) - loss(jnp.asarray(-dq))) / (2 * eps)
    assert fd == pytest.approx(float(g[i, p]), rel=1e-4, abs=1e-10)


def test_single_step_descends(feeder, s_reactive):
    step = vvc.make_vvc_controller(feeder)
    q0 = jnp.zeros((feeder.n_branches, 3))
    out = step(s_reactive, q0)
    assert bool(out.improved)
    assert float(out.loss_after_kw) < float(out.loss_before_kw)
    assert float(out.alpha) > 0
    # Voltage deltas are reported and bounded (sub-percent for one step).
    assert out.v_delta_pu.shape == (feeder.n_nodes, 3)
    assert float(jnp.max(jnp.abs(out.v_delta_pu))) < 0.05


def test_rounds_converge_to_optimum(feeder, s_reactive):
    step = vvc.make_vvc_controller(feeder)
    q0 = jnp.zeros((feeder.n_branches, 3))
    qf, losses, alphas, improved = vvc.run_rounds(step, s_reactive, q0, 120)
    l0, lf = float(losses[0]), float(losses[-1])
    # Accepted-only updates => monotone non-increasing trajectory.
    assert np.all(np.diff(np.asarray(losses)) <= 1e-12)
    # ~9% loss reduction on this case; plateau reached (last rounds flat).
    base = step(s_reactive, q0)
    assert lf < 0.92 * float(base.loss_before_kw)
    assert abs(float(losses[-1]) - float(losses[-10])) < 1e-5
    # Independent check: optimum loss is stationary under the controller.
    out = step(s_reactive, qf)
    assert float(out.loss_after_kw) >= lf - 1e-6


def test_q_limits_projected(feeder, s_reactive):
    cfg = vvc.VVCConfig(q_min_kvar=-5.0, q_max_kvar=5.0)
    step = vvc.make_vvc_controller(feeder, config=cfg)
    q0 = jnp.zeros((feeder.n_branches, 3))
    qf, losses, _, _ = vvc.run_rounds(step, s_reactive, q0, 30)
    assert float(jnp.max(qf)) <= 5.0 + 1e-12
    assert float(jnp.min(qf)) >= -5.0 - 1e-12
    # Dead phases stay uncontrolled.
    assert float(jnp.max(jnp.abs(qf * (1 - feeder.phase_mask)))) == 0.0


def test_ctrl_mask_restricts_actuation(feeder, s_reactive):
    ctrl = np.zeros((feeder.n_branches, 3))
    ctrl[4] = 1.0  # only node 5 is an SST
    step = vvc.make_vvc_controller(feeder, ctrl_mask=ctrl)
    q0 = jnp.zeros((feeder.n_branches, 3))
    out = step(s_reactive, q0)
    off = np.ones((feeder.n_branches, 3)) - ctrl
    assert float(jnp.max(jnp.abs(out.q_ctrl_kvar * off))) == 0.0


def test_vmap_scenarios(feeder):
    step = vvc.make_vvc_controller(feeder)
    scales = jnp.linspace(0.5, 1.2, 6)
    s = cplx.as_c(feeder.s_load.real * (1 + 0.6j))
    q0 = jnp.zeros((feeder.n_branches, 3))
    batch = jax.vmap(lambda k: step(cplx.C(s.re * k, s.im * k), q0))(scales)
    assert batch.loss_after_kw.shape == (6,)
    assert bool(jnp.all(batch.loss_after_kw <= batch.loss_before_kw))
