"""Plug-and-play session protocol tests.

Reference behaviors under test (``docs/devices/pnp_adapter.rst``,
``Broker/src/device/CPnpAdapter.cpp``, ``CAdapterFactory.cpp:522-760``):
Hello → adapter creation → Start; DeviceStates answered by full
DeviceCommands; NULL sentinels ignored both ways; malformed packets
dropped with Error but the session lives; PoliteDisconnect frees slots
gracefully; heartbeat silence reaps the adapter and frees its slots;
duplicate live sessions rejected; unknown types BadRequest'd — and the
dynamic devices feed a live LB fleet mid-run through real sockets.
"""

import time

import numpy as np
import pytest

from freedm_tpu.core.config import NULL_COMMAND
from freedm_tpu.devices.adapters.pnp import PnpServer
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.sim.controller import PnpClient
from freedm_tpu.runtime import Fleet, NodeHandle, build_broker


@pytest.fixture
def server():
    manager = DeviceManager(capacity=16)
    events = []
    srv = PnpServer(
        manager,
        heartbeat_s=0.4,
        on_join=lambda ident, a: events.append(("join", ident)),
        on_leave=lambda ident, reason: events.append(("leave", ident, reason)),
    ).start()
    yield srv, manager, events
    srv.stop()


def wait_for(cond, timeout=5.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_hello_start_states_commands_roundtrip(server):
    srv, manager, events = server
    c = PnpClient("ctrl1", srv.address)
    c.enable("Sst", "sst", gateway=5.0)
    c.enable("Drer", "solar", generation=12.5)
    assert c.connect() == "Start"
    assert ("join", "ctrl1") in events
    # Devices registered under namespaced names, revealed, readable.
    assert manager.device_names("Sst") == ("ctrl1:sst",)
    assert c.exchange() == {}  # no commands staged yet
    assert manager.get_state("ctrl1:sst", "gateway") == pytest.approx(5.0)
    assert manager.get_state("ctrl1:solar", "generation") == pytest.approx(12.5)
    # DGI stages a command; the next exchange delivers it.
    manager.set_command("ctrl1:sst", "gateway", -3.0)
    cmds = c.exchange()
    assert cmds == {("sst", "gateway"): pytest.approx(-3.0)}
    # NULL state values are ignored (previous reading kept).
    c.change("sst", "gateway", NULL_COMMAND)
    c.exchange()
    assert manager.get_state("ctrl1:sst", "gateway") == pytest.approx(5.0)
    c.disconnect()
    wait_for(lambda: not manager.device_names(), what="slots freed")
    # The on_leave callback fires on the server thread and can land
    # just after the slots free: poll for it instead of asserting a
    # racy snapshot.
    wait_for(
        lambda: ("leave", "ctrl1", "polite disconnect") in events,
        what="leave event",
    )


def test_heartbeat_timeout_reaps_adapter_and_allows_rejoin(server):
    srv, manager, events = server
    c = PnpClient("ctrl2", srv.address)
    c.enable("Load", "fridge", drain=2.0)
    assert c.connect() == "Start"
    c.exchange()
    assert manager.device_names("Load") == ("ctrl2:fridge",)
    # Go silent (socket open, no messages): the countdown must kill the
    # adapter and free the slots without notice.
    wait_for(
        lambda: any(e[0] == "leave" and e[1] == "ctrl2" for e in events),
        timeout=3.0,
        what="heartbeat reap",
    )
    assert manager.device_names() == ()
    assert srv.sessions_reaped == 1
    c.close()
    # The controller may restart the protocol from Hello.
    c2 = PnpClient("ctrl2", srv.address)
    c2.enable("Load", "fridge", drain=3.0)
    assert c2.connect() == "Start"
    c2.exchange()
    assert manager.get_state("ctrl2:fridge", "drain") == pytest.approx(3.0)
    c2.disconnect()


def test_duplicate_session_rejected_and_bad_packets_survivable(server):
    srv, manager, events = server
    c = PnpClient("ctrl3", srv.address)
    c.enable("Sst", "sst", gateway=0.0)
    assert c.connect() == "Start"
    # Same identifier, live session: rejected (EDuplicateSession).
    dup = PnpClient("ctrl3", srv.address)
    dup.enable("Sst", "sst", gateway=0.0)
    assert dup.connect() == "Error"
    # Unknown device type: BadRequest.
    bad = PnpClient("ctrl4", srv.address)
    bad.enable("Toaster", "t", heat=1.0)
    assert bad.connect() == "BadRequest"
    # Malformed DeviceStates (missing a state): Error, session survives.
    c._send("DeviceStates", "sst gateway not-a-number")
    reply = c._recv()
    assert reply[0] == "Error"
    assert c.exchange() == {}  # still alive
    c.disconnect()


def test_pipelined_messages_in_one_segment(server):
    """TCP gives no framing guarantee: Hello and the first DeviceStates
    coalesced into one segment must both be processed, not kill the
    session."""
    import socket as socklib

    srv, manager, events = server
    s = socklib.create_connection(srv.address)
    s.settimeout(5.0)
    msg = (
        "Hello\r\nctrlP\r\nSst sst\r\n\r\n"
        "DeviceStates\r\nsst gateway 7.0\r\n\r\n"
    )
    s.sendall(msg.encode("ascii"))

    rbuf = bytearray()

    def recv_msg():
        while b"\r\n\r\n" not in rbuf:
            chunk = s.recv(4096)
            if not chunk:
                raise ConnectionError("server closed")
            rbuf.extend(chunk)
        text, _, rest = bytes(rbuf).partition(b"\r\n\r\n")
        rbuf[:] = rest
        return text.decode("ascii").split("\r\n")

    assert recv_msg() == ["Start"]
    reply = recv_msg()
    assert reply[0] == "DeviceCommands"  # the pipelined states were served
    assert manager.get_state("ctrlP:sst", "gateway") == pytest.approx(7.0)
    s.sendall(b"PoliteDisconnect\r\n\r\n")
    assert recv_msg()[0] == "PoliteDisconnect"
    s.close()


def test_cli_runtime_starts_session_server():
    # factory-port in the config starts the PnP server on the process's
    # own node (PosixMain's StartSessionProtocol path).
    from freedm_tpu.cli import build_runtime
    from freedm_tpu.core.config import GlobalConfig

    cfg = GlobalConfig(hostname="node0", port=50860, factory_port=0, address="127.0.0.1")
    rt = build_runtime(cfg)
    try:
        srv = rt.factories[cfg.uuid].session_server
        assert srv is not None
        c = PnpClient("cli-ctrl", srv.address)
        c.enable("Drer", "pv", generation=4.0)
        assert c.connect() == "Start"
        c.exchange()
        assert rt.fleet.nodes[0].manager.get_state("cli-ctrl:pv", "generation") == pytest.approx(4.0)
        c.disconnect()
    finally:
        rt.stop()


def test_pnp_device_joins_lb_fleet_mid_run(server):
    """A PnP controller Hello-joins mid-run, its devices flow into the
    LB round (demand served), then silence reaps it and the fleet's
    view of the node empties — all through sockets."""
    srv, pnp_manager, events = server

    # Node A: static supply (fake in-memory adapter). Node B: owns the
    # PnP manager — its devices arrive dynamically.
    from freedm_tpu.devices.adapters.fake import FakeAdapter

    fake = FakeAdapter()
    ma = DeviceManager(capacity=8)
    ma.add_device("SST_A", "Sst", fake)
    ma.add_device("GEN_A", "Drer", fake)
    fake.reveal_devices()
    fake.set_state("SST_A", "gateway", 0.0)
    fake.set_state("GEN_A", "generation", 20.0)

    fleet = Fleet(
        [NodeHandle("a:1", ma), NodeHandle("b:2", pnp_manager)],
        migration_step=1.0,
    )
    broker = build_broker(fleet)
    broker.run(n_rounds=2)
    out = broker.shared["lb_round"]
    # Before the join: node B is empty, nothing to balance.
    assert int(out.n_migrations) == 0

    c = PnpClient("ctrlB", srv.address)
    c.enable("Sst", "sst", gateway=0.0)
    c.enable("Load", "plant", drain=10.0)
    assert c.connect() == "Start"

    # Pump exchanges on a thread so the heartbeat stays fresh while the
    # broker compiles/runs (a real controller's periodic DeviceStates).
    import threading

    pumping = threading.Event()
    pumping.set()

    def pump():
        while pumping.is_set():
            try:
                c.exchange()
            except (ConnectionError, OSError):
                return
            time.sleep(0.05)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    broker.run(n_rounds=4)
    # Node B's demand is visible and an import was commanded to its SST.
    r = fleet.read_devices()
    assert float(r["drain"][1]) == pytest.approx(10.0)
    assert int(broker.shared["lb_round"].state[1]) == -1  # DEMAND
    wait_for(
        lambda: (c.last_commands.get(("sst", "gateway")) or 0.0) < 0.0,
        what="import command over the wire",
    )

    # Silence: reap frees node B's devices; the fleet sees them vanish.
    pumping.clear()
    t.join(timeout=2)
    wait_for(
        lambda: any(e[0] == "leave" and e[1] == "ctrlB" for e in events),
        timeout=3.0,
        what="mid-run reap",
    )
    broker.run(n_rounds=2)
    r = fleet.read_devices()
    assert float(r["drain"][1]) == 0.0
    c.close()
